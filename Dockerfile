# Container recipe for autocycler-tpu (CPU/host build; on TPU VMs install
# the matching jax[tpu] wheel instead of jax[cpu]).
#
# The external assemblers driven by `autocycler helper` are not bundled —
# add the ones you use (Flye, Canu, Raven, ...) or mount a conda env, the
# same model as the reference's pipeline containers.

FROM python:3.12-slim

RUN apt-get update \
    && apt-get install -y --no-install-recommends g++ make \
    && rm -rf /var/lib/apt/lists/*

WORKDIR /opt/autocycler-tpu
COPY pyproject.toml README.md ./
COPY autocycler_tpu ./autocycler_tpu
COPY native ./native
COPY pipelines ./pipelines

RUN pip install --no-cache-dir "jax[cpu]" numpy pyyaml pillow matplotlib \
    && pip install --no-cache-dir --no-build-isolation . \
    && make -C native

# the installed package doesn't carry native/; point the loader at the
# image's build of the kernel library
ENV AUTOCYCLER_NATIVE_LIB=/opt/autocycler-tpu/native/libseqkernel.so

ENTRYPOINT ["autocycler"]
CMD ["--help"]
