"""autocycler-tpu: a TPU-native consensus-assembly framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of rrwick/Autocycler
(long-read bacterial consensus assembly).  The hot computations (k-mer
grouping, all-vs-all contig distance, path-overlap DP, dotplot grid) run as
batched device kernels; the irregular graph surgery stays on the host.

Layering (bottom → top), mirroring the reference's layer map
(see SURVEY.md §1; reference: /root/reference/src/main.rs:18-42):

- ``utils``    — I/O, logging, small numerics (reference: misc.rs, log.rs)
- ``models``   — Sequence / Position / Unitig / UnitigGraph data model
                 (reference: sequence.rs, position.rs, unitig.rs,
                 unitig_graph.rs, graph_simplification.rs)
- ``ops``      — JAX/Pallas device kernels (greenfield; replaces the
                 reference's hash-map hot loops, kmer_graph.rs)
- ``parallel`` — mesh / sharding for batched multi-isolate runs (greenfield)
- ``commands`` — the 12 pipeline subcommands (reference: compress.rs,
                 cluster.rs, trim.rs, resolve.rs, combine.rs, clean.rs,
                 decompress.rs, dotplot.rs, gfa2fasta.rs, subsample.rs,
                 table.rs, helper.rs)
- ``cli``      — argparse front-end (reference: main.rs)
"""

__version__ = "0.1.0"
