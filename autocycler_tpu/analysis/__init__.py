"""Static analysis for the repo's own invariants (`autocycler lint`).

A self-contained AST-walking rule engine (stdlib ``ast`` only) that
enforces the conventions the codebase runs on but nothing else checks:
knob-registry discipline, lock discipline around module-level state,
JAX purity inside jitted call graphs, never-raise reader contracts, and
Prometheus metric/span naming.  See docs/static-analysis.md.
"""

from .engine import (Finding, LintContext, Module, load_baseline, run_lint,
                     split_baseline, write_baseline)
from .rules import ALL_RULES, rule_ids

__all__ = [
    "ALL_RULES",
    "Finding",
    "LintContext",
    "Module",
    "load_baseline",
    "rule_ids",
    "run_lint",
    "split_baseline",
    "write_baseline",
]
