"""The lint engine: file discovery, AST modules, suppressions, baselines.

Rules (see :mod:`.rules`) are callables over a parsed :class:`Module` (or,
for cross-file checks, over the whole batch) that yield :class:`Finding`
objects.  The engine layers two escape hatches on top:

- per-line suppressions: ``# lint: ignore[rule-id]`` on the offending line
  (bare ``# lint: ignore`` silences every rule on that line; a family
  prefix like ``knobs`` silences every ``knobs.*`` rule);
- a committed baseline file (``lint_baseline.json``) holding fingerprints
  of accepted pre-existing findings, so new code is held to the bar
  without blocking on archaeology.

Fingerprints hash (rule, path, message) — deliberately not the line
number, so unrelated edits above a baselined finding don't un-baseline it.
"""

from __future__ import annotations

import ast
import hashlib
import json
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*ignore(?:\[(?P<rules>[A-Za-z0-9_.,\s-]*)\])?")

SKIP_DIRS = {"__pycache__", ".git", ".cache", "node_modules", ".venv",
             "build", "dist"}


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # posix-style path relative to the lint root
    line: int
    message: str

    def fingerprint(self) -> str:
        key = f"{self.rule}|{self.path}|{self.message}"
        return hashlib.sha1(key.encode("utf-8")).hexdigest()[:12]

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "fingerprint": self.fingerprint()}

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def rule_matches(selector: str, rule_id: str) -> bool:
    """``knobs`` matches every ``knobs.*`` rule; exact ids match themselves."""
    return rule_id == selector or rule_id.startswith(selector + ".")


def _parse_suppressions(lines: Sequence[str]) -> Dict[int, Optional[Set[str]]]:
    """{1-based line: None (suppress all) or set of rule selectors}."""
    out: Dict[int, Optional[Set[str]]] = {}
    for i, text in enumerate(lines, start=1):
        if "lint:" not in text:
            continue
        m = SUPPRESS_RE.search(text)
        if not m:
            continue
        raw = m.group("rules")
        if raw is None or not raw.strip():
            out[i] = None
        else:
            out[i] = {part.strip() for part in raw.split(",") if part.strip()}
    return out


class Module:
    """One parsed source file, with parent links and suppression info."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        self.suppressions = _parse_suppressions(self.lines)
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                child._lint_parent = node  # type: ignore[attr-defined]

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return getattr(node, "_lint_parent", None)

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def suppressed(self, line: int, rule_id: str) -> bool:
        sel = self.suppressions.get(line, False)
        if sel is False:
            return False
        if sel is None:
            return True
        return any(rule_matches(s, rule_id) for s in sel)

    def module_str_constants(self) -> Dict[str, str]:
        """Module-level ``NAME = "literal"`` assignments (the metric-name
        constant idiom) for resolving Name references statically."""
        out: Dict[str, str] = {}
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                out[node.targets[0].id] = node.value.value
        return out


@dataclass
class LintContext:
    root: Path                      # paths in findings are relative to this
    docs_path: Optional[Path] = None  # docs/cli.md for the docs-drift rule


def iter_python_files(paths: Sequence[Path]) -> List[Path]:
    files: List[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_file() and p.suffix == ".py":
            files.append(p)
        elif p.is_dir():
            for sub in sorted(p.rglob("*.py")):
                if not any(part in SKIP_DIRS for part in sub.parts):
                    files.append(sub)
    seen: Set[Path] = set()
    out = []
    for f in files:
        r = f.resolve()
        if r not in seen:
            seen.add(r)
            out.append(f)
    return out


def _relpath(path: Path, root: Path) -> str:
    try:
        return path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        return path.as_posix()


def load_modules(paths: Sequence[Path], ctx: LintContext
                 ) -> Tuple[List[Module], List[Finding]]:
    """Parse every file; unparseable files become findings, not crashes."""
    modules: List[Module] = []
    errors: List[Finding] = []
    for f in iter_python_files(paths):
        rel = _relpath(f, ctx.root)
        try:
            source = f.read_text(encoding="utf-8", errors="replace")
            modules.append(Module(f, rel, source))
        except SyntaxError as e:
            errors.append(Finding("engine.parse", rel, e.lineno or 1,
                                  f"syntax error: {e.msg}"))
        except OSError as e:
            errors.append(Finding("engine.parse", rel, 1,
                                  f"unreadable: {e}"))
    return modules, errors


def run_lint(paths: Sequence[Path], ctx: LintContext,
             rules: Optional[Sequence] = None,
             selectors: Optional[Sequence[str]] = None
             ) -> Tuple[List[Finding], int]:
    """Run rules over paths. Returns (non-suppressed findings sorted by
    path/line, number of files checked). ``selectors`` filters rule ids
    (family prefixes allowed)."""
    from .rules import ALL_RULES
    active = list(rules if rules is not None else ALL_RULES)
    modules, findings = load_modules(paths, ctx)
    # one engine.parse finding per unparseable file: those files were
    # still checked, so they count
    n_files = len(modules) + len(findings)
    for rule in active:
        for mod in modules:
            for finding in rule.check_module(mod, ctx):
                if not mod.suppressed(finding.line, finding.rule):
                    findings.append(finding)
        project_check = getattr(rule, "check_project", None)
        if project_check is not None:
            findings.extend(project_check(modules, ctx))
    if selectors:
        findings = [f for f in findings
                    if any(rule_matches(s, f.rule) for s in selectors)]
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return findings, n_files


# ---- baseline ----

def load_baseline(path) -> Set[Tuple[str, str, str]]:
    """{(rule, path, fingerprint)} from a baseline file; empty when the
    file is missing or unreadable (a broken baseline must not hide new
    findings silently — it surfaces as every finding being 'new')."""
    try:
        data = json.loads(Path(path).read_text())
        return {(e["rule"], e["path"], e["fingerprint"])
                for e in data.get("findings", [])}
    except (OSError, ValueError, KeyError, TypeError):
        return set()


def split_baseline(findings: Sequence[Finding],
                   baseline: Set[Tuple[str, str, str]]
                   ) -> Tuple[List[Finding], List[Finding]]:
    """(new findings, baselined findings)."""
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        key = (f.rule, f.path, f.fingerprint())
        (old if key in baseline else new).append(f)
    return new, old


def write_baseline(findings: Sequence[Finding], path) -> None:
    entries = [{"rule": f.rule, "path": f.path, "message": f.message,
                "fingerprint": f.fingerprint()} for f in findings]
    entries.sort(key=lambda e: (e["path"], e["rule"], e["message"]))
    payload = {"version": 1, "findings": entries}
    Path(path).write_text(json.dumps(payload, indent=2, sort_keys=True)
                          + "\n")
