"""The lint rule families. Each rule object exposes:

- ``name``: the family prefix its rule ids live under;
- ``check_module(module, ctx)``: per-file findings generator;
- optionally ``check_project(modules, ctx)``: cross-file findings.

``ALL_RULES`` is the registry the engine and CLI run by default; adding a
family means appending an instance here (docs/static-analysis.md walks
through it).
"""

from .faults import FaultRules
from .knobs import KnobRules
from .locks import LockRules
from .metrics import MetricsRules
from .purity import PurityRules
from .readers import ReaderRules

ALL_RULES = (
    KnobRules(),
    LockRules(),
    PurityRules(),
    ReaderRules(),
    MetricsRules(),
    FaultRules(),
)


def rule_ids():
    """Every concrete rule id, for --rule validation and docs."""
    out = []
    for rule in ALL_RULES:
        out.extend(rule.ids)
    return tuple(out)


__all__ = ["ALL_RULES", "FaultRules", "KnobRules", "LockRules",
           "MetricsRules", "PurityRules", "ReaderRules", "rule_ids"]
