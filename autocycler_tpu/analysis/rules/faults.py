"""Fault-site documentation discipline.

- ``faults.documented``: the fault-site registry
  (``utils/resilience.FAULT_SITES``, which includes the registered
  ``CRASH_POINTS``) and the generated site table in
  docs/failure-modes.md disagree (either direction).  Only the region
  between the ``<!-- faults:begin -->`` / ``<!-- faults:end -->``
  markers is compared, and only each table row's first backticked cell
  counts as a documented site, so prose mentions elsewhere in the file
  don't mask a missing row.

Chaos recovery claims live in that table (crash point -> what survives,
how resume re-enters); this rule is what keeps the table honest when a
new fault hook or crash point lands in code.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Iterable, List

from ..engine import Finding, LintContext, Module

DOCS_BEGIN = "<!-- faults:begin -->"
DOCS_END = "<!-- faults:end -->"
DOCS_NAME = "failure-modes.md"
# a table row whose first cell is one backticked site token
ROW_RE = re.compile(r"^\s*\|\s*`([a-z][a-z0-9_-]*)`\s*\|")


def _registry():
    from ...utils.resilience import FAULT_SITES
    return FAULT_SITES


class FaultRules:
    name = "faults"
    ids = ("faults.documented",)

    def check_module(self, mod: Module, ctx: LintContext
                     ) -> Iterable[Finding]:
        return ()

    def check_project(self, modules: List[Module], ctx: LintContext
                      ) -> List[Finding]:
        # same gate as the knob docs rule: no docs tree (linting an
        # arbitrary target, not this repo) -> nothing to check. A missing
        # failure-modes.md counts as "no docs tree" too, exactly like a
        # missing cli.md does for the knob rule.
        if ctx.docs_path is None:
            return []
        docs = Path(ctx.docs_path).parent / DOCS_NAME
        if not docs.is_file():
            return []
        try:
            rel = docs.resolve().relative_to(ctx.root.resolve()).as_posix()
        except ValueError:
            rel = docs.as_posix()
        try:
            lines = docs.read_text().splitlines()
        except OSError as e:
            return [Finding("faults.documented", rel, 1,
                            f"fault-site docs unreadable: {e}")]
        begin = end = None
        for i, line in enumerate(lines, start=1):
            if DOCS_BEGIN in line and begin is None:
                begin = i
            elif DOCS_END in line and begin is not None:
                end = i
                break
        if begin is None or end is None:
            return [Finding(
                "faults.documented", rel, 1,
                f"missing {DOCS_BEGIN} / {DOCS_END} markers around the "
                "fault-site table (one row per utils/resilience.FAULT_SITES "
                "entry)")]
        documented = {}
        for i in range(begin, end):
            m = ROW_RE.match(lines[i - 1])
            if m:
                documented.setdefault(m.group(1), i)
        out: List[Finding] = []
        for site in _registry():
            if site not in documented:
                out.append(Finding(
                    "faults.documented", rel, begin,
                    f"fault site {site} (utils/resilience.FAULT_SITES) has "
                    "no row in the fault-site table"))
        for site, line in sorted(documented.items()):
            if site not in _registry():
                out.append(Finding(
                    "faults.documented", rel, line,
                    f"documented fault site {site} is not registered in "
                    "utils/resilience.FAULT_SITES"))
        return out
