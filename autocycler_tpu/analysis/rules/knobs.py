"""Knob-registry discipline.

- ``knobs.direct-read``: an ``AUTOCYCLER_*`` name read straight from
  ``os.environ`` (``.get``/``getenv``/subscript load) anywhere outside
  ``utils/knobs.py``.  Writes (``environ[...] = ``, ``setdefault``,
  ``pop``, ``del``) stay legal — bench and tests pin knobs that way.
- ``knobs.undeclared``: a ``knob_*`` accessor call naming a knob that is
  not declared in the registry.
- ``knobs.docs-drift``: the registry and the generated knob table in
  docs/cli.md disagree (either direction).  Only the region between the
  ``<!-- knobs:begin -->`` / ``<!-- knobs:end -->`` markers is compared,
  so CLI usage placeholders elsewhere in the file don't count.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterable, List

from ..engine import Finding, LintContext, Module

KNOB_RE = re.compile(r"^AUTOCYCLER_[A-Z0-9_]+$")
KNOB_TOKEN_RE = re.compile(r"AUTOCYCLER_[A-Z0-9_]+")
ACCESSORS = ("knob_int", "knob_float", "knob_bool", "knob_str",
             "knob_raw", "knob_set")
DOCS_BEGIN = "<!-- knobs:begin -->"
DOCS_END = "<!-- knobs:end -->"


def _const_str(node) -> str:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return ""


def _is_os_environ(node) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "environ"
            and isinstance(node.value, ast.Name) and node.value.id == "os")


def _registry():
    from ...utils.knobs import KNOBS
    return KNOBS


class KnobRules:
    name = "knobs"
    ids = ("knobs.direct-read", "knobs.undeclared", "knobs.docs-drift")

    def check_module(self, mod: Module, ctx: LintContext
                     ) -> Iterable[Finding]:
        if mod.rel.replace("\\", "/").endswith("utils/knobs.py"):
            return
        consts = mod.module_str_constants()
        declared = _registry()

        def resolve(node) -> str:
            value = _const_str(node)
            if not value and isinstance(node, ast.Name):
                value = consts.get(node.id, "")
            return value

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                fn = node.func
                env_get = (isinstance(fn, ast.Attribute) and fn.attr == "get"
                           and _is_os_environ(fn.value))
                getenv = ((isinstance(fn, ast.Attribute)
                           and fn.attr == "getenv"
                           and isinstance(fn.value, ast.Name)
                           and fn.value.id == "os")
                          or (isinstance(fn, ast.Name)
                              and fn.id == "getenv"))
                if (env_get or getenv) and node.args:
                    name = resolve(node.args[0])
                    if KNOB_RE.match(name):
                        yield Finding(
                            "knobs.direct-read", mod.rel, node.lineno,
                            f"direct environment read of {name}; go through "
                            "the typed accessors in utils/knobs.py")
                        continue
                meth = (fn.id if isinstance(fn, ast.Name)
                        else fn.attr if isinstance(fn, ast.Attribute)
                        else None)
                if meth in ACCESSORS and node.args:
                    name = resolve(node.args[0])
                    if KNOB_RE.match(name) and name not in declared:
                        yield Finding(
                            "knobs.undeclared", mod.rel, node.lineno,
                            f"{meth}() reads {name}, which is not declared "
                            "in the utils/knobs.py registry")
            elif isinstance(node, ast.Subscript) and _is_os_environ(node.value):
                if isinstance(node.ctx, ast.Load):
                    name = resolve(node.slice)
                    if KNOB_RE.match(name):
                        yield Finding(
                            "knobs.direct-read", mod.rel, node.lineno,
                            f"direct environment read of {name}; go through "
                            "the typed accessors in utils/knobs.py")

    def check_project(self, modules: List[Module], ctx: LintContext
                      ) -> List[Finding]:
        docs = ctx.docs_path
        if docs is None:
            return []
        docs = Path(docs)
        try:
            rel = docs.resolve().relative_to(ctx.root.resolve()).as_posix()
        except ValueError:
            rel = docs.as_posix()
        try:
            lines = docs.read_text().splitlines()
        except OSError as e:
            return [Finding("knobs.docs-drift", rel, 1,
                            f"knob docs unreadable: {e}")]
        begin = end = None
        for i, line in enumerate(lines, start=1):
            if DOCS_BEGIN in line and begin is None:
                begin = i
            elif DOCS_END in line and begin is not None:
                end = i
                break
        if begin is None or end is None:
            return [Finding(
                "knobs.docs-drift", rel, 1,
                f"missing {DOCS_BEGIN} / {DOCS_END} markers around the "
                "generated knob table (autocycler lint --knobs-md)")]
        documented = {}
        for i in range(begin, end):
            for token in KNOB_TOKEN_RE.findall(lines[i - 1]):
                documented.setdefault(token, i)
        out: List[Finding] = []
        for name in _registry():
            if name not in documented:
                out.append(Finding(
                    "knobs.docs-drift", rel, begin,
                    f"declared knob {name} is missing from the knob table "
                    "(regenerate with autocycler lint --knobs-md)"))
        for name, line in sorted(documented.items()):
            if name not in _registry():
                out.append(Finding(
                    "knobs.docs-drift", rel, line,
                    f"documented knob {name} is not declared in "
                    "utils/knobs.py"))
        return out
