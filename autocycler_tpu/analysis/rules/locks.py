"""Lock discipline for modules that own concurrency.

- ``locks.unguarded-global``: in a module that defines a module-level
  ``Lock``/``RLock``, a ``global X; X = ...`` rebind (or augmented
  assignment) executed outside any ``with <lock>:`` block.  The module
  declared its state shared by defining a lock; every writer must hold it.
  Functions named ``*_locked`` are exempt: the suffix is the repo's
  contract that the caller already holds the lock.
- ``locks.thread-daemon``: ``threading.Thread(...)`` constructed without
  ``daemon=True`` — the sampler/watcher/probe convention, so a wedged
  helper thread can never hold a process open.
- ``locks.guarded-field``: a class that declares its lock discipline with
  a ``_GUARDED_BY = {"_lock": ("_jobs", ...)}`` literal (the serve
  scheduler's contract, where N worker threads mutate one job table) gets
  every mutation of a guarded instance field checked: assignment,
  augmented assignment, subscript store and known mutator calls
  (``.pop``/``.update``/…) must sit inside ``with self.<lock>:``.
  ``__init__`` (single-threaded construction) and ``*_locked`` methods
  (caller holds the lock) are exempt.
"""

from __future__ import annotations

import ast
from typing import Iterable, Set

from ..engine import Finding, LintContext, Module

LOCK_FACTORIES = {"Lock", "RLock"}


def _callee_name(fn) -> str:
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def module_lock_names(mod: Module) -> Set[str]:
    names: Set[str] = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _callee_name(node.value.func) in LOCK_FACTORIES:
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        names.add(target.id)
    return names


def _own_scope_walk(func) -> Iterable[ast.AST]:
    """Walk a function body without descending into nested defs/lambdas,
    so ``global`` declarations and writes attach to the right scope."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _under_lock(mod: Module, node: ast.AST, locks: Set[str]) -> bool:
    """Whether the statement sits lexically inside a ``with <lock>:`` in
    its own function (an enclosing function's lock does not protect a
    nested function body that runs later)."""
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                ce = item.context_expr
                if isinstance(ce, ast.Name) and ce.id in locks:
                    return True
        elif isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
    return False


# method calls that mutate a container in place — the guarded-field rule
# treats these as writes
MUTATOR_CALLS = {"append", "add", "update", "pop", "popitem", "clear",
                 "remove", "discard", "extend", "setdefault", "insert"}


def _guard_map(cls: ast.ClassDef) -> dict:
    """The class's ``_GUARDED_BY`` literal as {lock_field: {field, ...}},
    or {} when absent/unparseable (the rule only binds where the class
    opted in)."""
    for stmt in cls.body:
        if not isinstance(stmt, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == "_GUARDED_BY"
                   for t in stmt.targets):
            continue
        try:
            value = ast.literal_eval(stmt.value)
        except (ValueError, SyntaxError, TypeError):
            return {}
        if not isinstance(value, dict):
            return {}
        return {str(lock): {str(f) for f in (fields or ())}
                for lock, fields in value.items() if isinstance(lock, str)}
    return {}


def _self_attr(node) -> str:
    """``self.<attr>`` -> the attr name, else ''."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return ""


def _under_self_lock(mod: Module, node: ast.AST, locks: Set[str]) -> bool:
    """Whether the statement sits lexically inside ``with self.<lock>:``
    in its own function."""
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.With):
            for item in anc.items:
                if _self_attr(item.context_expr) in locks:
                    return True
        elif isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
    return False


def _field_mutations(func) -> Iterable:
    """(node, field) pairs for every mutation of a ``self.<field>`` in the
    function's own scope: plain/aug/ann assignment, subscript store, and
    in-place mutator calls."""
    for node in _own_scope_walk(func):
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for target in targets:
                field = _self_attr(target)
                if field:
                    yield node, field
                elif isinstance(target, ast.Subscript):
                    field = _self_attr(target.value)
                    if field:
                        yield node, field
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in MUTATOR_CALLS:
            field = _self_attr(node.func.value)
            if field:
                yield node, field


class LockRules:
    name = "locks"
    ids = ("locks.unguarded-global", "locks.thread-daemon",
           "locks.guarded-field")

    def check_module(self, mod: Module, ctx: LintContext
                     ) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and _callee_name(node.func) == "Thread":
                daemon_true = any(
                    kw.arg == "daemon"
                    and isinstance(kw.value, ast.Constant)
                    and kw.value.value is True
                    for kw in node.keywords)
                if not daemon_true:
                    yield Finding(
                        "locks.thread-daemon", mod.rel, node.lineno,
                        "Thread(...) without daemon=True; helper threads "
                        "must not be able to hold the process open")

        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            guards = _guard_map(cls)
            if not guards:
                continue
            lock_names = set(guards)
            guarded = {f for fields in guards.values() for f in fields}
            for func in cls.body:
                if not isinstance(func, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if func.name == "__init__" or func.name.endswith("_locked"):
                    continue
                for node, field in _field_mutations(func):
                    if field not in guarded:
                        continue
                    if not _under_self_lock(mod, node, lock_names):
                        yield Finding(
                            "locks.guarded-field", mod.rel, node.lineno,
                            f"mutation of '{cls.name}.{field}' outside "
                            f"'with self.<lock>:' — _GUARDED_BY declares "
                            f"it lock-protected")

        locks = module_lock_names(mod)
        if not locks:
            return
        for func in ast.walk(mod.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if func.name.endswith("_locked"):
                continue    # contract: caller holds the lock
            declared_global: Set[str] = set()
            for stmt in _own_scope_walk(func):
                if isinstance(stmt, ast.Global):
                    declared_global.update(stmt.names)
            if not declared_global:
                continue
            for node in _own_scope_walk(func):
                if isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                    for target in targets:
                        if not (isinstance(target, ast.Name)
                                and target.id in declared_global):
                            continue
                        if not _under_lock(mod, node, locks):
                            yield Finding(
                                "locks.unguarded-global", mod.rel,
                                node.lineno,
                                f"write to module global '{target.id}' "
                                "outside a 'with <lock>:' block in a "
                                "module that defines a lock")
