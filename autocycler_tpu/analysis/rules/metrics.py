"""Metric and span naming hygiene, checked statically.

``tests/test_metrics_hygiene.py`` lints the registry snapshot at runtime,
but only for names an e2e run happens to touch.  These rules apply the
same Prometheus conventions to every ``counter_inc``/``gauge_set``/
``observe``/``info_set`` call site in the source, resolving first
arguments through the module-level string-constant idiom
(``DEVICE_SECONDS = "autocycler_device_seconds_total"``).  Dynamic names
that cannot be resolved statically are skipped — the runtime test still
owns those.

- ``metrics.name``: name regex, ``__``, ``_total`` reserved for counters
  and required on them, histograms need a unit suffix and must not end in
  ``_count``/``_sum``/``_bucket``;
- ``metrics.label``: label-name regex and the reserved Prometheus labels;
- ``metrics.span``: literal span names (or the literal head of an
  f-string) must be lowercase slug-like.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, Optional

from ..engine import Finding, LintContext, Module

NAME_RE = re.compile(r"^autocycler_[a-z][a-z0-9_]*[a-z0-9]$")
LABEL_RE = re.compile(r"^[a-z][a-z0-9_]*$")
SPAN_RE = re.compile(r"^[a-z0-9][a-z0-9_./: -]*$")
UNIT_SUFFIXES = ("_seconds", "_bytes", "_ratio")
FORBIDDEN_HIST_SUFFIXES = ("_count", "_sum", "_bucket")
RESERVED_LABELS = {"le", "quantile", "job", "instance"}
KIND_BY_METHOD = {"counter_inc": "counter", "gauge_set": "gauge",
                  "observe": "histogram", "info_set": "info"}
NON_LABEL_KWARGS = {"help", "buckets", "value"}


def _resolve_name(node, consts) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def _name_findings(name: str, kind: str):
    if not NAME_RE.match(name):
        yield (f"metric name {name!r} does not match "
               "^autocycler_[a-z][a-z0-9_]*[a-z0-9]$")
        return
    if "__" in name:
        yield f"metric name {name!r} contains a double underscore"
    if kind == "counter" and not name.endswith("_total"):
        yield f"counter {name!r} must end with _total"
    if kind != "counter" and name.endswith("_total"):
        yield (f"{kind} {name!r} must not end with _total "
               "(reserved for counters)")
    if kind == "histogram":
        if not name.endswith(UNIT_SUFFIXES):
            yield (f"histogram {name!r} needs a unit suffix "
                   f"({', '.join(UNIT_SUFFIXES)})")
        if name.endswith(FORBIDDEN_HIST_SUFFIXES):
            yield (f"histogram {name!r} must not end with "
                   "_count/_sum/_bucket (Prometheus series suffixes)")


class MetricsRules:
    name = "metrics"
    ids = ("metrics.name", "metrics.label", "metrics.span")

    def check_module(self, mod: Module, ctx: LintContext
                     ) -> Iterable[Finding]:
        if mod.rel.replace("\\", "/").endswith("obs/metrics_registry.py"):
            return     # the registry plumbs names through variables
        consts = mod.module_str_constants()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            meth = (fn.attr if isinstance(fn, ast.Attribute)
                    else fn.id if isinstance(fn, ast.Name) else None)
            if meth in KIND_BY_METHOD:
                kind = KIND_BY_METHOD[meth]
                name = (_resolve_name(node.args[0], consts)
                        if node.args else None)
                if name is not None:
                    for msg in _name_findings(name, kind):
                        yield Finding("metrics.name", mod.rel,
                                      node.lineno, msg)
                for kw in node.keywords:
                    if kw.arg is None or kw.arg in NON_LABEL_KWARGS:
                        continue
                    if kw.arg in RESERVED_LABELS:
                        yield Finding(
                            "metrics.label", mod.rel, node.lineno,
                            f"label {kw.arg!r} is reserved by Prometheus")
                    elif not LABEL_RE.match(kw.arg):
                        yield Finding(
                            "metrics.label", mod.rel, node.lineno,
                            f"label {kw.arg!r} does not match "
                            "^[a-z][a-z0-9_]*$")
            elif meth == "span" and node.args:
                head = None
                arg = node.args[0]
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str):
                    head = arg.value
                elif isinstance(arg, ast.JoinedStr) and arg.values \
                        and isinstance(arg.values[0], ast.Constant) \
                        and isinstance(arg.values[0].value, str):
                    head = arg.values[0].value
                if head and not SPAN_RE.match(head):
                    yield Finding(
                        "metrics.span", mod.rel, node.lineno,
                        f"span name {head!r} is not a lowercase slug "
                        "([a-z0-9_./: -], lowercase start)")
