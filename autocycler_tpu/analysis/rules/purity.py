"""JAX purity inside jitted call graphs.

``purity.impure-call`` flags host side effects and device-sync coercions
inside any function reachable from a ``jax.jit``/``jax.vmap``/
``pallas_call`` site in the same module: ``time.*``, stdlib ``random.*``
and ``np.random.*`` (``jax.random`` is pure and stays legal),
``os.environ``, ``print``, ``open``, ``.item()`` and ``float(...)``
coercions.  A stale closure or host callback inside a jitted function
silently poisons the persistent compile cache; this holds the line
statically.

Reachability is a module-local, name-based call graph: decoration sites
(``@jit`` / ``@partial(jax.jit, ...)``) plus first-argument function
references (``jax.jit(fn)``, ``vmap(fn)``, ``pl.pallas_call(kernel)``)
seed a BFS over plain-name calls.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, Set

from ..engine import Finding, LintContext, Module

JIT_WRAPPERS = {"jit", "vmap", "pallas_call"}


def _callee_name(fn) -> str:
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def jit_roots(mod: Module, funcs: Dict[str, ast.AST]) -> Set[str]:
    roots: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if _callee_name(target) in JIT_WRAPPERS:
                    roots.add(node.name)
                elif isinstance(dec, ast.Call) \
                        and _callee_name(dec.func) == "partial" \
                        and dec.args \
                        and _callee_name(dec.args[0]) in JIT_WRAPPERS:
                    roots.add(node.name)
        elif isinstance(node, ast.Call) \
                and _callee_name(node.func) in JIT_WRAPPERS \
                and node.args and isinstance(node.args[0], ast.Name):
            roots.add(node.args[0].id)
    return roots & set(funcs)


def _reachable(funcs: Dict[str, ast.AST], roots: Set[str]) -> Set[str]:
    reach: Set[str] = set()
    stack = list(roots)
    while stack:
        name = stack.pop()
        if name in reach:
            continue
        reach.add(name)
        for node in ast.walk(funcs[name]):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id in funcs \
                    and node.func.id not in reach:
                stack.append(node.func.id)
    return reach


def _impure_reason(node: ast.AST) -> str:
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name):
            if fn.id == "print":
                return "print() (host side effect)"
            if fn.id == "open":
                return "open() (host I/O)"
            if fn.id == "float" and node.args:
                return "float(...) coercion (forces device sync)"
        elif isinstance(fn, ast.Attribute):
            if isinstance(fn.value, ast.Name) and fn.value.id == "time":
                return f"time.{fn.attr}() (host clock)"
            if isinstance(fn.value, ast.Name) and fn.value.id == "random":
                return f"random.{fn.attr}() (host RNG; use jax.random)"
            if isinstance(fn.value, ast.Attribute) \
                    and fn.value.attr == "random" \
                    and isinstance(fn.value.value, ast.Name) \
                    and fn.value.value.id in ("np", "numpy"):
                return (f"np.random.{fn.attr}() (host RNG; "
                        "use jax.random)")
            if fn.attr == "item" and not node.args:
                return ".item() (forces device sync)"
    elif isinstance(node, ast.Attribute) and node.attr == "environ" \
            and isinstance(node.value, ast.Name) and node.value.id == "os":
        return "os.environ access (host state)"
    return ""


class PurityRules:
    name = "purity"
    ids = ("purity.impure-call",)

    def check_module(self, mod: Module, ctx: LintContext
                     ) -> Iterable[Finding]:
        funcs: Dict[str, ast.AST] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs.setdefault(node.name, node)
        roots = jit_roots(mod, funcs)
        if not roots:
            return
        for name in sorted(_reachable(funcs, roots)):
            func = funcs[name]
            for node in ast.walk(func):
                reason = _impure_reason(node)
                if reason:
                    yield Finding(
                        "purity.impure-call", mod.rel, node.lineno,
                        f"{reason} inside '{name}', which is reachable "
                        "from a jit/vmap/pallas_call site")
