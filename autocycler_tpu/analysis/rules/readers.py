"""Never-raise reader contract.

Observability readers (``read_*``, ``*_report`` builders, ``follow*``
followers — obs/watch.py, obs/timeseries.py, obs/report.py and friends)
run against files another process is writing, half-written JSON, and runs
that died mid-stage.  They must degrade to empty results, never take the
caller down:

- ``readers.raise``: a ``raise`` statement anywhere in a reader (bare
  re-raise included);
- ``readers.unguarded-io``: ``open()``, ``Path.read_text/read_bytes`` or
  ``json.load/loads`` outside any ``try`` block.

Writers and pure renderers (``write_*``, ``render_*``) are exempt.
"""

from __future__ import annotations

import ast
from typing import Iterable

from ..engine import Finding, LintContext, Module

IO_READ_ATTRS = {"read_text", "read_bytes"}
EXEMPT_PREFIXES = ("write_", "render_")


def is_reader_name(name: str) -> bool:
    if name.startswith(EXEMPT_PREFIXES):
        return False
    return (name.startswith("read_")
            or name.endswith("_report")
            or name == "follow" or name.startswith("follow_"))


def _is_io_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id == "open":
        return True
    if isinstance(fn, ast.Attribute):
        if fn.attr in IO_READ_ATTRS:
            return True
        if fn.attr in ("load", "loads") \
                and isinstance(fn.value, ast.Name) \
                and fn.value.id == "json":
            return True
    return False


def _inside_try(mod: Module, node: ast.AST, func: ast.AST) -> bool:
    for anc in mod.ancestors(node):
        if isinstance(anc, ast.Try) and anc.handlers:
            return True
        if anc is func:
            return False
    return False


class ReaderRules:
    name = "readers"
    ids = ("readers.raise", "readers.unguarded-io")

    def check_module(self, mod: Module, ctx: LintContext
                     ) -> Iterable[Finding]:
        for func in ast.walk(mod.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not is_reader_name(func.name):
                continue
            for node in ast.walk(func):
                if isinstance(node, ast.Raise):
                    yield Finding(
                        "readers.raise", mod.rel, node.lineno,
                        f"never-raise reader '{func.name}' contains a "
                        "raise statement")
                elif _is_io_call(node) and not _inside_try(mod, node, func):
                    yield Finding(
                        "readers.unguarded-io", mod.rel, node.lineno,
                        f"file/JSON read in reader '{func.name}' outside "
                        "any try/except")
