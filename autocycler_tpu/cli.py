"""Command-line interface: the twelve Autocycler subcommands plus the
TPU-native `batch` extension (mesh-batched multi-isolate processing).

Parity target: reference main.rs:44-370 — same subcommand names, flags,
defaults and validation ranges, dispatching to commands/*.
"""

from __future__ import annotations

import argparse
import os
import sys

from . import __version__
from .utils import AutocyclerError
from .utils.knobs import knob_str

BANNER = r"""                _                        _
     /\        | |                      | |
    /  \  _   _| |_ ___   ___ _   _  ___| | ___ _ __
   / /\ \| | | | __/ _ \ / __| | | |/ __| |/ _ \ '__|
  / ____ \ |_| | || (_) | (__| |_| | (__| |  __/ |
 /_/    \_\__,_|\__\___/ \___|\__, |\___|_|\___|_|
                               __/ |
                              |___/        (TPU-native)"""


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="autocycler",
        description="a tool for generating consensus bacterial genome assemblies "
                    "(TPU-native implementation)")
    parser.add_argument("--version", action="version",
                        version=f"Autocycler-TPU v{__version__}")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("batch",
                       help="compress + cluster distances for MANY isolates in one "
                            "mesh-batched device step (TPU-native extension)")
    p.add_argument("-i", "--assemblies_parent", required=True,
                   help="directory of isolate subdirectories, each a normal "
                        "--assemblies_dir")
    p.add_argument("-a", "--out_parent", required=True)
    p.add_argument("-k", "--kmer", type=int, default=51)
    p.add_argument("--max_contigs", type=int, default=25)
    p.add_argument("--resume", action="store_true",
                   help="replay a previous run from its batch_manifest.json, "
                        "retrying only failed/pending isolates")
    p.add_argument("--fleet", choices=["off", "on", "auto"], default=None,
                   help="route the run through the sharded fleet runner "
                        "(bucketed shards, mesh-sharded distances, prefetched "
                        "loads); default: the AUTOCYCLER_FLEET_MODE knob")
    p.add_argument("-t", "--threads", type=int, default=8)

    p = sub.add_parser("clean",
                       help="manual manipulation of the final consensus assembly graph "
                            "(and warm-start cache purging with --cache)")
    p.add_argument("-i", "--in_gfa")
    p.add_argument("-o", "--out_gfa")
    p.add_argument("-r", "--remove")
    p.add_argument("-d", "--duplicate")
    p.add_argument("-m", "--min_depth", type=float)
    p.add_argument("--cache", metavar="DIR",
                   help="purge the warm-start cache under DIR (an "
                        "autocycler dir or a .cache dir); may be used "
                        "alone, without -i/-o")

    p = sub.add_parser("cluster",
                       help="cluster contigs in the unitig graph based on similarity")
    p.add_argument("-a", "--autocycler_dir", required=True)
    p.add_argument("--cutoff", type=float, default=0.2)
    p.add_argument("--min_assemblies", type=int)
    p.add_argument("--max_contigs", type=int, default=25)
    p.add_argument("--manual")

    p = sub.add_parser("combine", help="combine Autocycler GFAs into one assembly")
    p.add_argument("-a", "--autocycler_dir", required=True)
    p.add_argument("-i", "--in_gfas", required=True, nargs="+")

    p = sub.add_parser("compress", help="compress input contigs into a unitig graph")
    p.add_argument("-i", "--assemblies_dir", required=True)
    p.add_argument("-a", "--autocycler_dir", required=True)
    p.add_argument("--kmer", type=int, default=51)
    p.add_argument("--max_contigs", type=int, default=25)
    p.add_argument("-t", "--threads", type=int, default=8)

    p = sub.add_parser("decompress", help="decompress contigs from a unitig graph")
    p.add_argument("-i", "--in_gfa", required=True)
    p.add_argument("-o", "--out_dir")
    p.add_argument("-f", "--out_file")

    p = sub.add_parser("doctor",
                       help="device forensics: probe history, negative-cache "
                            "state, environment snapshot and recommended "
                            "actions (reads state only — no device bring-up)")
    p.add_argument("-d", "--dir", default=".",
                   help="run directory holding probe_log.jsonl / "
                        "device_probe.json (default: cwd)")
    p.add_argument("--json", action="store_true",
                   help="emit the structured report as JSON")
    p.add_argument("--probe", action="store_true",
                   help="run one live subprocess probe (killable, captures "
                        "init stderr) before reporting")
    p.add_argument("--watch", action="store_true",
                   help="run the probe sentinel in the foreground, printing "
                        "one JSON outcome per cycle")
    p.add_argument("--interval", type=float,
                   help="--watch probe interval in seconds (default: "
                        "AUTOCYCLER_PROBE_WATCH or 30)")
    p.add_argument("--cycles", type=int,
                   help="--watch: stop after this many probe cycles")

    p = sub.add_parser("dotplot",
                       help="generate an all-vs-all dotplot from a unitig graph")
    p.add_argument("-i", "--input", required=True)
    p.add_argument("-o", "--out_png", required=True)
    p.add_argument("--res", type=int, default=2000)
    p.add_argument("--kmer", type=int, default=32)
    p.add_argument("--grid-mode", dest="grid_mode", default="auto",
                   choices=["auto", "host", "device"],
                   help="k-mer matching backend: host sort-join (near-linear, "
                        "the measured default) or the TPU Pallas match grid "
                        "with exact per-tile refinement")

    p = sub.add_parser("gfa2fasta",
                       help="convert an Autocycler GFA file to FASTA format")
    p.add_argument("-i", "--in_gfa", required=True)
    p.add_argument("-o", "--out_fasta", required=True)

    p = sub.add_parser("helper", help="helper commands for long-read assemblers")
    p.add_argument("task")
    p.add_argument("-r", "--reads", required=True)
    p.add_argument("-o", "--out_prefix")
    p.add_argument("-g", "--genome_size")
    p.add_argument("-t", "--threads", type=int, default=8)
    p.add_argument("-d", "--dir")
    p.add_argument("--read_type", default="ont_r10",
                   choices=["ont_r9", "ont_r10", "pacbio_clr", "pacbio_hifi"])
    p.add_argument("--min_depth_abs", type=float)
    p.add_argument("--min_depth_rel", type=float)
    p.add_argument("--timeout", type=float,
                   help="per-subprocess wall-clock limit in seconds (a hung "
                        "assembler is killed and counts as a failed attempt)")
    p.add_argument("--retries", type=int,
                   help="failed/hung subprocess retries with exponential "
                        "backoff (default 0)")
    p.add_argument("--args", dest="extra_args", nargs="+", default=[])

    p = sub.add_parser("lint",
                       help="statically check the repo's own invariants "
                            "(knob registry, lock discipline, JAX purity, "
                            "reader contracts, metric naming)")
    p.add_argument("paths", nargs="*",
                   help="files/directories to lint (default: the package, "
                        "bench.py and pipelines/)")
    p.add_argument("--json", action="store_true",
                   help="emit findings as JSON")
    p.add_argument("--rule", action="append", default=None, metavar="ID",
                   help="only run this rule id or family prefix "
                        "(repeatable, e.g. --rule knobs)")
    p.add_argument("--baseline", default=None, metavar="PATH",
                   help="baseline file of accepted findings "
                        "(default: lint_baseline.json at the repo root)")
    p.add_argument("--write-baseline", default=None, metavar="PATH",
                   help="accept the current findings: write them as the "
                        "new baseline and exit 0")
    p.add_argument("--report", default=None, metavar="PATH",
                   help="also write a lint_report.json artifact readable "
                        "by `autocycler report`")
    p.add_argument("--knobs-md", action="store_true",
                   help="print the generated AUTOCYCLER_* knob table "
                        "(markdown) and exit")

    p = sub.add_parser("report",
                       help="render a run's telemetry (trace spans, metrics, "
                            "batch manifest, bench artifacts) as one report")
    p.add_argument("run_dir",
                   help="directory holding trace.jsonl / metrics.json "
                        "(an AUTOCYCLER_TRACE_DIR run dir or an output dir)")
    p.add_argument("--json", action="store_true",
                   help="emit the merged report as JSON instead of text")
    p.add_argument("--html", nargs="?", const="", default=None,
                   metavar="PATH",
                   help="additionally write a self-contained run_report.html "
                        "(into the run dir, or to PATH when given)")
    p.add_argument("--correlate", default=None, metavar="TRACE_ID",
                   help="cross-run mode: merge every trace under run_dir "
                        "carrying this correlation id (client submissions, "
                        "replica jobs, fleet shards) into one Chrome trace "
                        "with one process lane per run")

    p = sub.add_parser("resolve", help="resolve repeats in the unitig graph")
    p.add_argument("-c", "--cluster_dir", required=True)
    p.add_argument("--verbose", action="store_true")

    p = sub.add_parser("serve",
                       help="assembly-as-a-service daemon: accept isolate "
                            "jobs over a local HTTP endpoint with warm "
                            "JIT/parse/repair caches, a bounded work queue "
                            "and live /metrics + /healthz")
    p.add_argument("-a", "--dir", dest="serve_dir", required=True,
                   help="daemon root: job run dirs, the shared warm-start "
                        "cache and serve_manifest.json live here")
    p.add_argument("--host", default="127.0.0.1",
                   help="TCP bind address (default 127.0.0.1)")
    p.add_argument("--port", type=int, default=8642,
                   help="TCP port (default 8642; 0 picks a free port)")
    p.add_argument("--socket", dest="socket_path",
                   help="serve on a Unix domain socket at this path "
                        "instead of TCP")
    p.add_argument("--queue-size", dest="queue_size", type=int, default=16,
                   help="bounded work queue capacity; submissions past it "
                        "get HTTP 503 (default 16)")
    p.add_argument("--workers", type=int, default=None,
                   help="worker threads executing jobs concurrently "
                        "(default AUTOCYCLER_SERVE_WORKERS or "
                        "min(4, cpu//2); 1 reproduces the single-worker "
                        "daemon bit for bit)")

    p = sub.add_parser("submit",
                       help="submit one isolate job to a running "
                            "`autocycler serve` daemon")
    p.add_argument("-i", "--assemblies_dir", required=True)
    p.add_argument("-a", "--out_dir",
                   help="assembly output directory (default: the job's "
                        "run dir under the daemon root)")
    p.add_argument("--server",
                   help="daemon endpoint URL (default: discovery via "
                        "--dir, AUTOCYCLER_SERVE, or localhost:8642)")
    p.add_argument("--socket", dest="socket_path",
                   help="daemon Unix socket path")
    p.add_argument("-d", "--dir", dest="serve_dir",
                   help="daemon root — reads its serve.json discovery file")
    p.add_argument("--fleet-dir", dest="fleet_dir",
                   help="fleet dir of replica serve roots: route the job "
                        "to the least-loaded healthy replica (probes each "
                        "replica's /healthz; overrides --server/--dir)")
    p.add_argument("--trace-id", dest="trace_id",
                   help="correlation id to propagate (default: minted per "
                        "submission; see `autocycler report --correlate`)")
    p.add_argument("--command", dest="job_command", default="compress",
                   choices=["compress", "pipeline"],
                   help="compress only, or the full per-isolate pipeline "
                        "(cluster + trim + resolve + combine)")
    p.add_argument("-k", "--kmer", type=int, default=51)
    p.add_argument("--max_contigs", type=int, default=25)
    p.add_argument("-t", "--threads", type=int, default=8)
    p.add_argument("--wait", action="store_true",
                   help="block until the job finishes; exit 1 on failure")
    p.add_argument("--follow", action="store_true",
                   help="follow the job's span stream live (implies "
                        "--wait; renders `autocycler watch` frames)")
    p.add_argument("--timeout", type=float,
                   help="--wait/--follow: give up after this many seconds")

    p = sub.add_parser("subsample", help="subsample a long-read set")
    p.add_argument("-r", "--reads", required=True)
    p.add_argument("-o", "--out_dir", required=True)
    p.add_argument("-g", "--genome_size", required=True)
    p.add_argument("-c", "--count", type=int, default=4)
    p.add_argument("-d", "--min_read_depth", type=float, default=25.0)
    p.add_argument("-s", "--seed", type=int, default=0)

    p = sub.add_parser("table", help="create TSV line from YAML files")
    p.add_argument("-a", "--autocycler_dir")
    p.add_argument("-n", "--name", default="")
    from .commands.table import DEFAULT_FIELDS
    p.add_argument("-f", "--fields", default=DEFAULT_FIELDS)
    p.add_argument("-s", "--sigfigs", type=int, default=3)

    p = sub.add_parser("watch",
                       help="follow another process's run live: tail a run "
                            "directory's trace.jsonl and render the stage/"
                            "isolate tree with QC highlights")
    p.add_argument("run_dir",
                   help="the run's AUTOCYCLER_TRACE_DIR directory "
                        "(holds trace.jsonl)")
    p.add_argument("--follow", action="store_true",
                   help="keep polling and re-rendering until the run "
                        "finishes (default: render once and exit)")
    p.add_argument("--once", action="store_true",
                   help="render the current state once and exit "
                        "(the default; overrides --follow)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="--follow poll interval in seconds (default 2)")
    p.add_argument("--cycles", type=int,
                   help="--follow: stop after this many polls even if the "
                        "run has not finished")

    p = sub.add_parser("top",
                       help="live fleet dashboard: queue depth and "
                            "throughput sparklines, latency quantiles with "
                            "the SLO verdict, cache hit-rate and memory — "
                            "aggregated from a serve root's (or run dir's) "
                            "timeseries.jsonl and manifests")
    p.add_argument("dir", nargs="?", default=".",
                   help="serve root or run directory (default: cwd)")
    p.add_argument("--follow", action="store_true",
                   help="keep re-rendering every --interval seconds "
                        "(default: render once and exit)")
    p.add_argument("--once", action="store_true",
                   help="render one frame and exit (the default; "
                        "overrides --follow)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="--follow refresh interval in seconds (default 2)")
    p.add_argument("--cycles", type=int,
                   help="--follow: stop after this many frames")
    p.add_argument("--fleet", action="store_true",
                   help="federated view: treat DIR as a fleet dir of "
                        "replica serve roots, poll every replica's /healthz "
                        "+ /metrics and render the merged snapshot with the "
                        "scale verdict (writes fleet_status.json)")

    p = sub.add_parser("trim", help="trim contigs in a cluster")
    p.add_argument("-c", "--cluster_dir", required=True)
    p.add_argument("--min_identity", type=float, default=0.75)
    p.add_argument("--max_unitigs", type=int, default=5000)
    p.add_argument("--mad", type=float, default=5.0)
    p.add_argument("-t", "--threads", type=int, default=8)

    return parser


def dispatch(args) -> int:
    """Run the selected subcommand; returns the process exit code (batch
    returns 2 on partial failure — some isolates quarantined, the rest
    completed — so orchestrators can distinguish it from total failure)."""
    if args.command == "batch":
        from .commands.batch import batch
        return batch(args.assemblies_parent, args.out_parent, args.kmer,
                     args.max_contigs, resume=args.resume,
                     threads=args.threads, fleet=args.fleet)
    elif args.command == "clean":
        from .commands.clean import clean
        clean(args.in_gfa, args.out_gfa, args.remove, args.duplicate,
              args.min_depth, cache=args.cache)
    elif args.command == "cluster":
        from .commands.cluster import cluster
        cluster(args.autocycler_dir, args.cutoff, args.min_assemblies,
                args.max_contigs, args.manual)
    elif args.command == "combine":
        from .commands.combine import combine
        combine(args.autocycler_dir, args.in_gfas)
    elif args.command == "compress":
        from .commands.compress import compress
        compress(args.assemblies_dir, args.autocycler_dir, args.kmer,
                 args.max_contigs, threads=args.threads)
    elif args.command == "decompress":
        from .commands.decompress import decompress
        decompress(args.in_gfa, args.out_dir, args.out_file)
    elif args.command == "doctor":
        from .commands.doctor import doctor
        return doctor(args.dir, as_json=args.json, watch=args.watch,
                      probe=args.probe, interval=args.interval,
                      cycles=args.cycles)
    elif args.command == "dotplot":
        from .commands.dotplot import dotplot
        dotplot(args.input, args.out_png, args.res, args.kmer, args.grid_mode)
    elif args.command == "gfa2fasta":
        from .commands.gfa2fasta import gfa2fasta
        gfa2fasta(args.in_gfa, args.out_fasta)
    elif args.command == "helper":
        from .commands.helper import helper
        helper(args.task, args.reads, args.out_prefix, args.genome_size, args.threads,
               args.dir, args.read_type, args.min_depth_abs, args.min_depth_rel,
               args.extra_args, timeout=args.timeout, retries=args.retries)
    elif args.command == "lint":
        from .commands.lint import lint
        return lint(paths=args.paths or None, baseline=args.baseline,
                    rules=args.rule, as_json=args.json,
                    write_baseline_path=args.write_baseline,
                    report_path=args.report, knobs_md=args.knobs_md)
    elif args.command == "report":
        from .obs.report import report
        return report(args.run_dir, as_json=args.json, html=args.html,
                      correlate=args.correlate)
    elif args.command == "resolve":
        from .commands.resolve import resolve
        resolve(args.cluster_dir, args.verbose)
    elif args.command == "serve":
        from .serve.server import serve
        return serve(args.serve_dir, host=args.host, port=args.port,
                     workers=args.workers,
                     socket_path=args.socket_path,
                     queue_size=args.queue_size)
    elif args.command == "submit":
        from .serve.client import submit
        return submit(args.assemblies_dir, server=args.server,
                      socket_path=args.socket_path, serve_dir=args.serve_dir,
                      fleet_dir=args.fleet_dir,
                      command=args.job_command, out_dir=args.out_dir,
                      kmer=args.kmer, max_contigs=args.max_contigs,
                      threads=args.threads, wait=args.wait,
                      follow=args.follow, timeout=args.timeout,
                      trace_id=args.trace_id)
    elif args.command == "subsample":
        from .commands.subsample import subsample
        subsample(args.reads, args.out_dir, args.genome_size, args.count,
                  args.min_read_depth, args.seed)
    elif args.command == "table":
        from .commands.table import table
        table(args.autocycler_dir, args.name, args.fields, args.sigfigs)
    elif args.command == "trim":
        from .commands.trim import trim
        trim(args.cluster_dir, args.min_identity, args.max_unitigs, args.mad,
             args.threads)
    elif args.command == "top":
        from .obs.top import top
        return top(args.dir, follow=args.follow and not args.once,
                   interval=args.interval, cycles=args.cycles,
                   fleet=args.fleet)
    elif args.command == "watch":
        from .obs.watch import watch
        return watch(args.run_dir, follow=args.follow and not args.once,
                     interval=args.interval, cycles=args.cycles)


# Subcommands that build the reference-cyclic unitig graph (next/prev
# adjacency lists): generational cycle collection repeatedly traverses
# millions of live graph objects mid-stage for nothing — measured at >20% of
# pipeline wall time on the headline config. Each is one bounded process;
# reference counting handles everything acyclic and the OS reclaims the rest
# at exit. `helper` (8-hour assembler subprocess loops) and the other
# non-graph subcommands keep the collector ON — they are long-lived or
# allocation-light, so the disable would be all risk and no win.
GC_DISABLED_COMMANDS = frozenset({
    "compress", "cluster", "trim", "resolve", "combine", "clean",
    "decompress", "dotplot", "gfa2fasta", "batch",
})


def main(argv=None) -> int:
    # Honour an explicit JAX_PLATFORMS pin through jax.config: an installed
    # PJRT plugin (the axon TPU tunnel) can override the environment
    # variable, which would send a user's pinned-CPU run to a remote device
    # anyway — or hang it when the tunnel is wedged. Only touches jax when
    # the user set the variable.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            import jax
            jax.config.update("jax_platforms", plat)
        except Exception:
            pass

    from .utils import log
    if not log._json_mode():   # the banner would corrupt the JSONL stream
        print(BANNER, file=sys.stderr)
    parser = build_parser()
    args = parser.parse_args(argv)
    if args.command in GC_DISABLED_COMMANDS:
        import gc
        gc.disable()
    from .obs import trace
    # `report`, `watch` and `top` read a previous/other run's telemetry —
    # tracing them would clutter (or clobber) the very artifacts they
    # render. `doctor` likewise only inspects state (and must stay
    # side-effect-free on a wedged host). `serve` owns one trace run PER
    # JOB (each job's run dir gets its own trace/QC/ledger), and `submit`
    # is a thin client.
    may_own_run = args.command not in ("report", "doctor", "watch", "top",
                                       "serve", "submit", "lint")
    # continuous telemetry rides the same run dir as the trace: one
    # background thread, one timeseries.jsonl next to trace.jsonl. The
    # sampler starts BEFORE the run clock and stops AFTER it closes, so
    # thread spawn/join never shows up as untraced wall time inside the
    # run (the stage-tree/wall agreement must hold on millisecond runs).
    sampler = None
    trace_target = (knob_str("AUTOCYCLER_TRACE_DIR") or "").strip()
    if may_own_run and trace_target:
        from .obs import timeseries
        if timeseries.timeseries_enabled():
            sampler = timeseries.TimeseriesSampler(trace_target).start()
    owns_run = may_own_run and trace.maybe_start_run(name=args.command)
    if not owns_run and sampler is not None:
        sampler.stop(final_sample=False)   # another run is already active
        sampler = None
    if owns_run:
        from .obs import ledger, qc
        qc.reset()
        ledger.reset()
    if args.command not in ("report", "doctor", "watch", "top", "submit",
                            "lint"):
        from .obs import sentinel
        sentinel.maybe_start_watcher()
        # Kick off the device probe on a background thread now, so its
        # (potentially slow) subprocess attach overlaps host-side load and
        # parse work. The first device-dispatch point blocks on the future
        # only for whatever time has not already elapsed. compress/batch
        # (and serve, at daemon start) start it themselves right after
        # set_probe_cache_dir(), so the runner can adopt a persisted
        # negative result from disk.
        if args.command not in ("compress", "batch", "serve"):
            from .ops.distance import start_background_probe
            start_background_probe()
    try:
        with trace.span(args.command, cat="command",
                        **({"argv": list(argv)} if argv else {})):
            rc = dispatch(args)
    except AutocyclerError as e:
        print(f"\nError: {e}", file=sys.stderr)
        return 1
    finally:
        if owns_run:
            run_dir = trace.finish_run()
            if run_dir:
                from .obs import ledger, qc
                qc.write_qc_report(run_dir)
                ledger.write_ledger(run_dir, command=args.command)
        if sampler is not None:
            sampler.stop()   # outside the run wall; takes the final tick
        metrics_path = knob_str("AUTOCYCLER_METRICS")
        if metrics_path:
            trace.write_metrics_file(metrics_path)
    return int(rc) if rc else 0


if __name__ == "__main__":
    sys.exit(main())
