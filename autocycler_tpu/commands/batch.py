"""`autocycler batch`: many isolates through compress + cluster distances in
one mesh-batched device step.

This subcommand is greenfield (the reference processes one isolate per
invocation; SURVEY.md §2.4 lists multi-chip batching as this port's design
axis): given a directory of isolate subdirectories (each a normal
``--assemblies_dir``), it compresses every isolate to its unitig graph,
computes ALL isolates' exact all-vs-all contig distance matrices in one
sharded device contraction (isolates on the mesh 'data' axis, the unitig
axis on 'seq' — parallel.batch.batched_membership_intersections), and runs
the FULL `cluster` stage per isolate from those matrices (UPGMA tree,
refinement, QC, per-cluster GFAs, TSV/YAML) — so each isolate's output
directory is ready for `trim`/`resolve`.

The distances are bit-identical to what `autocycler cluster` computes per
isolate (integer intersection matmul + the same float division), which is
asserted by tests/test_parallel.py on a 96-isolate CPU mesh.
"""

from __future__ import annotations

import gc
import os
from pathlib import Path
from typing import List

from ..models.simplify import simplify_structure
from ..ops.distance import intersections_to_distances, membership_matrix
from ..ops.graph_build import build_unitig_graph
from ..parallel.batch import batched_membership_intersections
from ..parallel.mesh import make_mesh
from ..utils import log, quit_with_error
from .cluster import cluster as run_cluster
from .combine import combine
from .compress import load_sequences
from .resolve import resolve
from .trim import trim


def find_isolate_dirs(parent) -> List[Path]:
    parent = Path(parent)
    if not parent.is_dir():
        quit_with_error(f"directory does not exist: {parent}")
    isolates = sorted(d for d in parent.iterdir() if d.is_dir())
    if not isolates:
        quit_with_error(f"no isolate subdirectories found in {parent}")
    return isolates


def batch(assemblies_parent, out_parent, k_size: int = 51,
          max_contigs: int = 25) -> None:
    """Compress every isolate and emit per-isolate clustering from one
    batched device distance step."""
    if k_size < 11 or k_size > 501 or k_size % 2 == 0:
        quit_with_error("--kmer must be an odd number between 11 and 501")
    log.section_header("Starting autocycler batch")
    log.explanation("Each isolate subdirectory is compressed into a unitig graph; the "
                    "exact all-vs-all contig distance matrices of ALL isolates are then "
                    "computed in a single sharded device step and clustered per isolate.")
    isolates = find_isolate_dirs(assemblies_parent)
    out_parent = Path(out_parent)
    os.makedirs(out_parent, exist_ok=True)

    seq_lists, Ms, ws = [], [], []
    for iso in isolates:
        log.message(f"Compressing isolate {iso.name}")
        from ..metrics import InputAssemblyMetrics
        sequences, _ = load_sequences(iso, k_size, InputAssemblyMetrics(),
                                      max_contigs)
        graph = build_unitig_graph(sequences, k_size)
        simplify_structure(graph, sequences)
        out_dir = out_parent / iso.name
        os.makedirs(out_dir, exist_ok=True)
        graph.save_gfa(out_dir / "input_assemblies.gfa", sequences)
        M, w, ids = membership_matrix(graph, sequences)
        seq_lists.append((sequences, ids))
        Ms.append(M)
        ws.append(w)
        del graph
        # the CLI disables the cycle collector; each isolate's graph is
        # reference-cyclic, so reclaim it explicitly or RSS grows by one
        # full graph per isolate
        gc.collect()
    log.message()

    log.section_header("Batched distance step")
    log.explanation("Isolates ride the mesh 'data' axis; the unitig axis is sharded over "
                    "'seq' and contracted with an integer matmul + psum, so every "
                    "isolate's matrix is exactly the single-isolate computation.")
    mesh = make_mesh()
    inters = batched_membership_intersections(mesh, Ms, ws)

    for iso, (sequences, ids), inter in zip(isolates, seq_lists, inters):
        distances = intersections_to_distances(inter, ids)
        run_cluster(out_parent / iso.name, max_contigs=max_contigs,
                    precomputed_distances=distances)
        log.message(f"{iso.name}: {len(sequences)} contigs clustered")

    log.section_header("Batched trim screen")
    log.explanation("Every isolate's trim overlap DPs (start-end + both hairpin "
                    "passes for every sequence of every QC-pass cluster) are screened "
                    "in ONE batched device DP — the vmapped right-edge recurrence; "
                    "screened-positive sequences then get their full alignment "
                    "decoded from the device DP's packed traceback bits, so the "
                    "host never re-runs the DP and the final graphs are bitwise "
                    "identical to sequential trim.")
    cluster_dirs = []
    for iso in isolates:
        qc_pass = out_parent / iso.name / "clustering" / "qc_pass"
        if qc_pass.is_dir():
            cluster_dirs.extend(sorted(d for d in qc_pass.iterdir()
                                       if d.is_dir()))
    screens, graphs = _batched_trim_screens(cluster_dirs, mesh=mesh)
    n_all = sum(len(s) for s in screens.values())
    n_dev = sum(isinstance(v, list) for s in screens.values()
                for v in s.values())
    n_host = sum(v is True for s in screens.values() for v in s.values())
    log.message(f"{n_all} trim DPs screened; {n_dev} alignments decoded from "
                f"the device traceback; {n_host} need the full host DP")
    log.message()

    for cdir in cluster_dirs:
        trimmed = trim(cdir, dp_screen=screens[cdir], preloaded=graphs.pop(cdir))
        resolve(cdir, preloaded=trimmed)
        del trimmed   # the graph is reference-cyclic; drop it before collecting
        gc.collect()
    for iso in isolates:
        qc_pass = out_parent / iso.name / "clustering" / "qc_pass"
        finals = sorted(qc_pass.glob("cluster_*/5_final.gfa")) \
            if qc_pass.is_dir() else []
        if finals:
            combine(out_parent / iso.name, finals)

    log.section_header("Finished!")
    log.message(f"Per-isolate outputs: {out_parent}/<isolate>/clustering/ "
                f"+ consensus_assembly.gfa/.fasta")
    log.message()


def _batched_trim_screens(cluster_dirs, max_unitigs: int = 5000, mesh=None,
                          min_identity: float = 0.75):
    """One batched screen call covering every (sequence, trim kind) of every
    cluster, then ONE device traceback pass for the screened-positive jobs;
    returns {cluster_dir: {(seq_id, kind): False | alignment pieces}}. With
    a mesh the screen shards over every device
    (parallel.batch.sharded_overlap_screen). Job construction mirrors
    trim_path_start_end / trim_path_hairpin_* (trim.rs:288-326): start_end
    aligns path vs itself off-diagonal, hairpin_start aligns path vs its
    signed reverse, hairpin_end the mirror. Screened-positive jobs get their
    full alignment decoded from the device DP's packed direction bits
    (ops.align.overlap_tracebacks_batch) — the host never re-runs the DP;
    jobs outside the int32 traceback domain stay True (host DP in trim)."""
    import numpy as np

    from ..models import UnitigGraph
    from ..ops.align import overlap_positive_batch, overlap_tracebacks_batch
    from ..parallel.batch import sharded_overlap_screen
    from ..utils import reverse_signed_path

    jobs, keys = [], []
    graphs = {}
    for cdir in cluster_dirs:
        graph, sequences = UnitigGraph.from_gfa_file(cdir / "1_untrimmed.gfa")
        graphs[cdir] = (graph, sequences)
        max_num = max((u.number for u in graph.unitigs), default=0)
        weights = np.zeros(max_num + 1, dtype=np.int64)
        for u in graph.unitigs:
            weights[u.number] = u.length()
        all_paths = graph.get_unitig_paths_for_sequences(
            [s.id for s in sequences])
        for seq in sequences:
            path = [n if st else -n for n, st in all_paths[seq.id]]
            rev = reverse_signed_path(path)
            jobs.append((path, path, weights, True))
            keys.append((cdir, seq.id, "start_end"))
            jobs.append((path, rev, weights, False))
            keys.append((cdir, seq.id, "hairpin_start"))
            jobs.append((rev, path, weights, False))
            keys.append((cdir, seq.id, "hairpin_end"))
    verdicts = sharded_overlap_screen(mesh, jobs, max_unitigs) \
        if mesh is not None else overlap_positive_batch(jobs, max_unitigs)
    pos_idx = [i for i, v in enumerate(verdicts) if v]
    decoded = overlap_tracebacks_batch([jobs[i] for i in pos_idx],
                                       max_unitigs, min_identity)
    screens = {cdir: {} for cdir in cluster_dirs}
    for (cdir, seq_id, kind), v in zip(keys, verdicts):
        screens[cdir][(seq_id, kind)] = bool(v)
    for i, pieces in zip(pos_idx, decoded):
        cdir, seq_id, kind = keys[i]
        if pieces is not None:
            screens[cdir][(seq_id, kind)] = pieces
    return screens, graphs
