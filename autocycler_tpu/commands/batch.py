"""`autocycler batch`: many isolates through compress + cluster distances in
one mesh-batched device step, with per-isolate fault isolation and resume.

This subcommand is greenfield (the reference processes one isolate per
invocation; SURVEY.md §2.4 lists multi-chip batching as this port's design
axis): given a directory of isolate subdirectories (each a normal
``--assemblies_dir``), it compresses every isolate to its unitig graph,
computes ALL isolates' exact all-vs-all contig distance matrices in one
sharded device contraction (isolates on the mesh 'data' axis, the unitig
axis on 'seq' — parallel.batch.batched_membership_intersections), and runs
the FULL `cluster` stage per isolate from those matrices (UPGMA tree,
refinement, QC, per-cluster GFAs, TSV/YAML) — so each isolate's output
directory is ready for `trim`/`resolve`.

The distances are bit-identical to what `autocycler cluster` computes per
isolate (integer intersection matmul + the same float division), which is
asserted by tests/test_parallel.py on a 96-isolate CPU mesh.

Fault isolation (utils.resilience): a malformed isolate — corrupt FASTA,
too many contigs, an unreadable cluster GFA — is quarantined, recorded in
``<out_parent>/batch_manifest.json`` (per-isolate status, error, attempt
count) and skipped; the batch completes the rest. The exit status reflects
partial failure (2), and ``--resume`` replays only failed/pending isolates
from the manifest. This mirrors the reference's per-assembler tolerance
(helper.rs:645-654) one level up: some of N isolates failing must not cost
the other N-1 their multi-hour run.

Fleet mode (``--fleet`` / ``AUTOCYCLER_FLEET_MODE``, parallel/fleet.py):
instead of one global compress -> distances -> cluster -> finalise sweep,
isolates are packed into size-bucketed shards sized to the device mesh.
Each shard's contraction is one device dispatch sharded over the isolate
axis (parallel.mesh.shard_leading_axis), padded up a power-of-two shape
ladder so XLA compiles once per bucket, and the host load/encode of
upcoming isolates runs ahead on the shared pool, overlapping the current
shard's device work. The serial path stays the oracle: per-isolate outputs
are byte-identical by construction (same helpers, same integer device
math), which `bench.py fleetsmoke` and tests/test_fleet.py enforce. The
``mid-fleet-shard`` crash point between a shard's durable compress
checkpoints and its cluster stage makes preemption mid-shard a resumable
event (chaos-harness covered).
"""

from __future__ import annotations

import gc
import os
from pathlib import Path
from typing import List, NamedTuple, Optional

from ..models.simplify import simplify_structure
from ..obs import ledger, trace
from ..obs import qc as obs_qc
from ..ops.distance import intersections_to_distances, membership_matrix
from ..ops.graph_build import build_unitig_graph
from ..parallel.batch import batched_membership_intersections
from ..parallel.mesh import make_mesh
from ..utils import AutocyclerError, log, quit_with_error
from ..utils.resilience import RunManifest, collect_errors
from ..utils.timing import stage_timer
from .cluster import cluster as run_cluster
from .combine import combine
from .compress import load_sequences
from .resolve import resolve
from .trim import trim

MANIFEST_NAME = "batch_manifest.json"


class IsolateJob(NamedTuple):
    """One isolate of a (fleet) batch: where its assemblies live and where
    its outputs go. The CLI derives these from isolate subdirectories;
    serve's fleet route derives them from batch job specs."""
    name: str
    asm_dir: Path
    out_dir: Path


def find_isolate_dirs(parent) -> List[Path]:
    parent = Path(parent)
    if not parent.is_dir():
        quit_with_error(f"directory does not exist: {parent}")
    isolates = sorted(d for d in parent.iterdir() if d.is_dir())
    if not isolates:
        quit_with_error(f"no isolate subdirectories found in {parent}")
    return isolates


def _cluster_outputs(out_dir: Path) -> List[Path]:
    clustering = out_dir / "clustering"
    return [clustering / "pairwise_distances.phylip",
            clustering / "clustering.newick",
            clustering / "clustering.tsv",
            clustering / "clustering.yaml"] \
        + sorted(clustering.glob("qc_*/cluster_*/1_untrimmed.gfa"))


def _load_isolate(asm_dir, out_dir: Path, k_size: int, max_contigs: int,
                  threads: int):
    """The host side of one isolate's compress: load + parse + encode +
    end-repair. Shared verbatim by the serial loop and the fleet prefetch
    lane, so both paths produce identical sequences by construction."""
    from ..metrics import InputAssemblyMetrics
    from ..utils.cache import open_cache

    # warm-start caches live under the isolate's out dir, so a --resume
    # (or repeat) run skips load+encode+repair for isolates whose inputs
    # have not changed
    sequences, _ = load_sequences(asm_dir, k_size, InputAssemblyMetrics(),
                                  max_contigs, threads,
                                  cache=open_cache(out_dir))
    return sequences


def _build_isolate(out_dir: Path, sequences, k_size: int, threads: int):
    """Build + simplify + persist one isolate's unitig graph (the device
    side of compress). Must run one isolate at a time: the stream spill
    root is process-global state."""
    # streamed k-mer spill lives under the isolate's out dir, so bins from
    # concurrent/killed batch runs never collide
    from ..stream import prepare_stream_root
    prepare_stream_root(out_dir)
    graph = build_unitig_graph(sequences, k_size, threads=threads)
    simplify_structure(graph, sequences)
    os.makedirs(out_dir, exist_ok=True)
    graph.save_gfa(out_dir / "input_assemblies.gfa", sequences)
    obs_qc.compress_qc(graph, sequences)
    ledger.record_stage(
        "compress", outputs=[out_dir / "input_assemblies.gfa"])
    return graph


def _screen_and_finalise(jobs: List[IsolateJob], mesh, errs, manifest,
                         completed: List[str]) -> None:
    """Trim screen + trim/resolve/combine for clustered isolates: ONE
    batched device DP screens every isolate's overlap jobs, then each
    isolate finalises under quarantine. Serial batch calls this once over
    the whole run; fleet mode calls it per shard — per-isolate outputs are
    identical either way because every DP job's verdict/traceback depends
    only on that job."""
    from ..models import UnitigGraph

    iso_cluster_dirs = {}
    graphs = {}
    with stage_timer("batch/trim_screen"):
        for job in jobs:
            qc_pass = job.out_dir / "clustering" / "qc_pass"
            dirs = sorted(d for d in qc_pass.iterdir() if d.is_dir()) \
                if qc_pass.is_dir() else []
            # per-isolate graph loading is quarantined too: one unreadable
            # cluster GFA must not sink the whole batched screen
            with errs.quarantine(job.name):
                for cdir in dirs:
                    graphs[cdir] = UnitigGraph.from_gfa_file(
                        cdir / "1_untrimmed.gfa")
            if errs.failed(job.name):
                manifest.fail(job.name, str(errs.errors[job.name].cause),
                              stage="trim")
                for cdir in dirs:
                    graphs.pop(cdir, None)
            else:
                iso_cluster_dirs[job.name] = dirs
        cluster_dirs = [d for dirs in iso_cluster_dirs.values()
                        for d in dirs]
        screens = _batched_trim_screens(cluster_dirs, graphs, mesh=mesh)
    n_all = sum(len(s) for s in screens.values())
    n_dev = sum(isinstance(v, list) for s in screens.values()
                for v in s.values())
    n_host = sum(v is True for s in screens.values() for v in s.values())
    log.message(f"{n_all} trim DPs screened; {n_dev} alignments decoded from "
                f"the device traceback; {n_host} need the full host DP")
    log.message()

    with stage_timer("batch/finalise"):
        for job in jobs:
            if job.name not in iso_cluster_dirs:
                continue
            with trace.span(f"isolate/{job.name}", cat="isolate",
                            stage="finalise"), obs_qc.scope(job.name), \
                    errs.quarantine(job.name):
                for cdir in iso_cluster_dirs[job.name]:
                    trimmed = trim(cdir, dp_screen=screens[cdir],
                                   preloaded=graphs.pop(cdir))
                    resolve(cdir, preloaded=trimmed)
                    del trimmed   # reference-cyclic; drop before collecting
                    gc.collect()
                qc_pass = job.out_dir / "clustering" / "qc_pass"
                finals = sorted(qc_pass.glob("cluster_*/5_final.gfa")) \
                    if qc_pass.is_dir() else []
                if finals:
                    combine(job.out_dir, finals)
            if errs.failed(job.name):
                manifest.fail(job.name, str(errs.errors[job.name].cause),
                              stage="finalise")
            else:
                manifest.stage_done(
                    job.name, "finalise",
                    outputs=[job.out_dir / "consensus_assembly.gfa",
                             job.out_dir / "consensus_assembly.fasta"])
                manifest.done(job.name)
                completed.append(job.name)


def _summarise(completed: List[str], errs, manifest_path: Path,
               out_parent: Path, n_todo: int) -> int:
    log.section_header("Finished!")
    n_failed = len(errs)
    log.message(f"{len(completed)} isolate(s) complete, {n_failed} failed "
                f"(statuses recorded in {manifest_path})")
    if n_failed:
        for name in sorted(errs.errors):
            log.message(f"  FAILED {name}: {errs.errors[name].cause}")
        log.message("Re-run with --resume to retry only the failed isolates.")
    log.message(f"Per-isolate outputs: {out_parent}/<isolate>/clustering/ "
                f"+ consensus_assembly.gfa/.fasta")
    log.message()
    if not completed:
        raise AutocyclerError(
            f"all {n_todo} isolate(s) failed; see {manifest_path}")
    return 2 if n_failed else 0


def batch(assemblies_parent, out_parent, k_size: int = 51,
          max_contigs: int = 25, resume: bool = False,
          threads: int = 1, fleet: Optional[str] = None) -> int:
    """Compress every isolate and emit per-isolate clustering from one
    batched device distance step. Per-isolate failures are quarantined into
    the run manifest; returns the process exit code (0 = all complete,
    2 = partial failure; all-failed raises). ``threads`` reaches end-repair
    and the k-mer grouping of every isolate's compress. ``fleet`` overrides
    the ``AUTOCYCLER_FLEET_MODE`` knob ('off'/'on'/'auto'); when engaged
    the run goes through the sharded fleet runner instead of the serial
    sweep, with byte-identical per-isolate outputs."""
    if k_size < 11 or k_size > 501 or k_size % 2 == 0:
        quit_with_error("--kmer must be an odd number between 11 and 501")
    from ..utils import check_threads
    check_threads(threads)
    from ..parallel.fleet import fleet_engaged, resolve_fleet_mode
    mode = resolve_fleet_mode(fleet)
    log.section_header("Starting autocycler batch")
    log.explanation("Each isolate subdirectory is compressed into a unitig graph; the "
                    "exact all-vs-all contig distance matrices of ALL isolates are then "
                    "computed in a single sharded device step and clustered per isolate. "
                    "A malformed isolate is quarantined and recorded in the run "
                    "manifest; the batch completes the rest.")
    isolates = find_isolate_dirs(assemblies_parent)
    out_parent = Path(out_parent)
    os.makedirs(out_parent, exist_ok=True)
    from ..ops.distance import set_probe_cache_dir, start_background_probe
    set_probe_cache_dir(out_parent / ".cache")
    # Overlap the device attach with isolate discovery + compress host work.
    start_background_probe()
    manifest_path = out_parent / MANIFEST_NAME
    manifest = RunManifest.load(manifest_path) if resume \
        else RunManifest(manifest_path)

    todo = []
    for iso in isolates:
        if resume and manifest.status(iso.name) == "done":
            log.message(f"{iso.name}: already complete — skipped (--resume)")
            continue
        manifest.pending(iso.name)
        todo.append(iso)
    if not todo:
        log.message("All isolates already complete; nothing to do")
        return 0
    errs = collect_errors()

    # stage-granular resume: an interrupted isolate re-enters at its last
    # verified checkpoint (every recorded output re-hashes clean) instead
    # of starting over. Cluster-verified isolates skip straight to the
    # trim screen; compress-verified ones reload the unitig graph from the
    # on-disk GFA (bit-identical membership by the round-trip identity
    # tests/test_parallel.py asserts) and redo distances + clustering.
    resume_cluster = set()
    resume_compress = set()
    if resume:
        for iso in todo:
            if manifest.stage_complete(iso.name, "cluster"):
                resume_cluster.add(iso.name)
            elif manifest.stage_complete(iso.name, "compress"):
                resume_compress.add(iso.name)

    if fleet_engaged(mode, len(todo)):
        jobs = [IsolateJob(iso.name, iso, out_parent / iso.name)
                for iso in todo]
        return _fleet_batch(jobs, out_parent, k_size, max_contigs, threads,
                            manifest, manifest_path, resume_cluster,
                            resume_compress, errs)
    if mode != "off":
        log.message(f"Fleet mode {mode!r} not engaged for {len(todo)} "
                    "isolate(s) — running the serial path")

    # ---- per-isolate compress (quarantined) ----
    from ..models import UnitigGraph
    compressed = []   # (iso, (sequences, ids), M, w)
    with stage_timer("batch/compress"):
        for iso in todo:
            manifest.start(iso.name)
            out_dir = out_parent / iso.name
            if iso.name in resume_cluster:
                log.message(f"{iso.name}: compress + cluster checkpoints "
                            "verified — resuming at trim (--resume)")
                ledger.record_stage(
                    "compress", outputs=[out_dir / "input_assemblies.gfa"],
                    skipped=True)
                ledger.record_stage(
                    "cluster", outputs=_cluster_outputs(out_dir),
                    skipped=True)
                continue
            with trace.span(f"isolate/{iso.name}", cat="isolate",
                            stage="compress"), obs_qc.scope(iso.name), \
                    errs.quarantine(iso.name):
                if iso.name in resume_compress:
                    log.message(f"{iso.name}: compress checkpoint verified "
                                "— reloading unitig graph (--resume)")
                    graph, sequences = UnitigGraph.from_gfa_file(
                        out_dir / "input_assemblies.gfa")
                    ledger.record_stage(
                        "compress",
                        outputs=[out_dir / "input_assemblies.gfa"],
                        skipped=True)
                else:
                    log.message(f"Compressing isolate {iso.name}")
                    sequences = _load_isolate(iso, out_dir, k_size,
                                              max_contigs, threads)
                    graph = _build_isolate(out_dir, sequences, k_size,
                                           threads)
                M, w, ids = membership_matrix(graph, sequences)
                compressed.append((iso, (sequences, ids), M, w))
                del graph
                # the CLI disables the cycle collector; each isolate's graph
                # is reference-cyclic, so reclaim it explicitly or RSS grows
                # by one full graph per isolate
                gc.collect()
            if errs.failed(iso.name):
                manifest.fail(iso.name, str(errs.errors[iso.name].cause),
                              stage="compress")
            else:
                manifest.stage_done(
                    iso.name, "compress",
                    outputs=[out_dir / "input_assemblies.gfa"])
    log.message()
    if not compressed and not resume_cluster:
        raise AutocyclerError(
            f"all {len(todo)} isolate(s) failed during compress; "
            f"see {manifest_path}")

    log.section_header("Batched distance step")
    log.explanation("Isolates ride the mesh 'data' axis; the unitig axis is sharded over "
                    "'seq' and contracted with an integer matmul + psum, so every "
                    "isolate's matrix is exactly the single-isolate computation.")
    with stage_timer("batch/distances"):
        mesh = make_mesh()
        inters = batched_membership_intersections(
            mesh, [c[2] for c in compressed], [c[3] for c in compressed]) \
            if compressed else []

    # ---- per-isolate clustering (quarantined) ----
    clustered = [iso for iso in todo if iso.name in resume_cluster]
    with stage_timer("batch/cluster"):
        for (iso, (sequences, ids), _, _), inter in zip(compressed, inters):
            with trace.span(f"isolate/{iso.name}", cat="isolate",
                            stage="cluster"), obs_qc.scope(iso.name), \
                    errs.quarantine(iso.name):
                distances = intersections_to_distances(inter, ids)
                run_cluster(out_parent / iso.name, max_contigs=max_contigs,
                            precomputed_distances=distances)
                log.message(f"{iso.name}: {len(sequences)} contigs clustered")
                clustered.append(iso)
            if errs.failed(iso.name):
                manifest.fail(iso.name, str(errs.errors[iso.name].cause),
                              stage="cluster")
            else:
                manifest.stage_done(iso.name, "cluster",
                                    outputs=_cluster_outputs(
                                        out_parent / iso.name))
    clustered.sort(key=lambda p: p.name)

    log.section_header("Batched trim screen")
    log.explanation("Every isolate's trim overlap DPs (start-end + both hairpin "
                    "passes for every sequence of every QC-pass cluster) are screened "
                    "in ONE batched device DP — the vmapped right-edge recurrence; "
                    "screened-positive sequences then get their full alignment "
                    "decoded from the device DP's packed traceback bits, so the "
                    "host never re-runs the DP and the final graphs are bitwise "
                    "identical to sequential trim.")
    completed: List[str] = []
    _screen_and_finalise(
        [IsolateJob(iso.name, iso, out_parent / iso.name)
         for iso in clustered],
        mesh, errs, manifest, completed)
    return _summarise(completed, errs, manifest_path, out_parent, len(todo))


# ---------------------------------------------------------------------------
# Fleet mode (parallel/fleet.py planning + bucketed device shapes)
# ---------------------------------------------------------------------------

def _fleet_batch(jobs: List[IsolateJob], out_parent: Path, k_size: int,
                 max_contigs: int, threads: int, manifest: RunManifest,
                 manifest_path: Path, resume_cluster: set,
                 resume_compress: set, errs) -> int:
    """The sharded fleet runner: size-bucketed shards, one mesh-sharded
    contraction per shard, prefetched host loads, stage-granular + fleet-
    granular resume. Byte-identical to the serial sweep per isolate."""
    from ..models import UnitigGraph
    from ..parallel import fleet as fleet_mod
    from ..utils.knobs import knob_int
    from ..utils.pool import prefetch_iter
    from ..utils.resilience import crash_point

    by_name = {j.name: j for j in jobs}
    resume_jobs = [j for j in jobs if j.name in resume_cluster]
    fleet_jobs = [j for j in jobs if j.name not in resume_cluster]
    n_dev = fleet_mod.fleet_devices()
    plan = fleet_mod.plan_fleet(
        {j.name: fleet_mod.isolate_cost(j.asm_dir) for j in fleet_jobs},
        shard_size=n_dev,
        n_buckets=knob_int("AUTOCYCLER_FLEET_BUCKETS"))
    log.section_header("Fleet plan")
    log.explanation("Isolates are packed into size-bucketed shards; each shard's exact "
                    "membership contraction is ONE device dispatch sharded over the "
                    "isolate axis (padded up a power-of-two shape ladder, so XLA "
                    "compiles once per bucket), and the host load/encode of upcoming "
                    "isolates runs ahead on the shared pool, overlapping the current "
                    "shard's device work.")
    log.message(f"{len(fleet_jobs)} isolate(s) in {len(plan.shards)} "
                f"shard(s) of up to {plan.shard_size} "
                f"({plan.n_buckets} size bucket(s), {n_dev} device(s))")
    log.message()

    prefetch = knob_int("AUTOCYCLER_FLEET_PREFETCH")
    depth = max(1, int(prefetch)) * plan.shard_size
    order = [by_name[name] for sh in plan.shards for name in sh.names]

    def _load_job(job: IsolateJob):
        """One isolate's host load, run ahead on the shared pool while the
        current shard owns the device. Failures are returned as values and
        re-raised under the consumer's quarantine, so one corrupt isolate
        cannot kill the prefetch lane for the isolates behind it."""
        try:
            with trace.span(f"isolate/{job.name}", cat="isolate",
                            stage="load"), obs_qc.scope(job.name):
                if job.name in resume_compress:
                    graph, sequences = UnitigGraph.from_gfa_file(
                        job.out_dir / "input_assemblies.gfa")
                    ledger.record_stage(
                        "compress",
                        outputs=[job.out_dir / "input_assemblies.gfa"],
                        skipped=True)
                    return ("graph", graph, sequences)
                sequences = _load_isolate(job.asm_dir, job.out_dir, k_size,
                                          max_contigs, threads)
                return ("seqs", sequences, None)
        except Exception as e:  # noqa: BLE001 — re-raised at consume time
            return ("err", e, None)

    # the lane is wider than the prefetch depth so a load task that fans
    # its own parse/encode subtasks across the shared executor always
    # leaves >= threads free workers — no nested-submission starvation
    loads = prefetch_iter(_load_job, order, workers=threads + depth,
                          depth=depth)
    mesh = make_mesh()
    completed: List[str] = []
    any_compressed = bool(resume_jobs)
    for shard in plan.shards:
        with trace.span(f"fleet/shard-{shard.index:03d}", cat="fleet",
                        bucket=shard.bucket, isolates=len(shard.names)):
            compressed = []   # (job, (sequences, ids), M, w)
            with stage_timer("batch/compress"):
                for name in shard.names:
                    job = by_name[name]
                    manifest.start(job.name)
                    loaded = next(loads)
                    with trace.span(f"isolate/{job.name}", cat="isolate",
                                    stage="compress"), \
                            obs_qc.scope(job.name), \
                            errs.quarantine(job.name):
                        if loaded[0] == "err":
                            raise loaded[1]
                        if loaded[0] == "graph":
                            log.message(
                                f"{job.name}: compress checkpoint verified "
                                "— reloading unitig graph (--resume)")
                            graph, sequences = loaded[1], loaded[2]
                        else:
                            log.message(f"Compressing isolate {job.name}")
                            sequences = loaded[1]
                            graph = _build_isolate(job.out_dir, sequences,
                                                   k_size, threads)
                        M, w, ids = membership_matrix(graph, sequences)
                        compressed.append((job, (sequences, ids), M, w))
                        del graph
                        gc.collect()
                    if errs.failed(job.name):
                        manifest.fail(job.name,
                                      str(errs.errors[job.name].cause),
                                      stage="compress")
                    else:
                        manifest.stage_done(
                            job.name, "compress",
                            outputs=[job.out_dir / "input_assemblies.gfa"])
            with stage_timer("batch/distances"):
                inters = fleet_mod.fleet_membership_intersections(
                    [c[2] for c in compressed],
                    [c[3] for c in compressed],
                    devices=n_dev) if compressed else []
            # the registered preemption boundary: every isolate of this
            # shard has a durable compress checkpoint, nothing after has
            # run — a kill here must resume into reload + re-cluster
            crash_point("mid-fleet-shard", f"shard-{shard.index:03d}")
            shard_clustered: List[IsolateJob] = []
            with stage_timer("batch/cluster"):
                for (job, (sequences, ids), _, _), inter \
                        in zip(compressed, inters):
                    with trace.span(f"isolate/{job.name}", cat="isolate",
                                    stage="cluster"), \
                            obs_qc.scope(job.name), \
                            errs.quarantine(job.name):
                        distances = intersections_to_distances(inter, ids)
                        run_cluster(job.out_dir, max_contigs=max_contigs,
                                    precomputed_distances=distances)
                        log.message(f"{job.name}: {len(sequences)} contigs "
                                    "clustered")
                        shard_clustered.append(job)
                    if errs.failed(job.name):
                        manifest.fail(job.name,
                                      str(errs.errors[job.name].cause),
                                      stage="cluster")
                    else:
                        manifest.stage_done(
                            job.name, "cluster",
                            outputs=_cluster_outputs(job.out_dir))
            if compressed:
                any_compressed = True
            _screen_and_finalise(shard_clustered, mesh, errs, manifest,
                                 completed)
            fleet_mod.record_shard_metrics(len(shard.names), shard.bucket)
    if resume_jobs:
        for job in resume_jobs:
            manifest.start(job.name)
            log.message(f"{job.name}: compress + cluster checkpoints "
                        "verified — resuming at trim (--resume)")
            with obs_qc.scope(job.name):
                ledger.record_stage(
                    "compress",
                    outputs=[job.out_dir / "input_assemblies.gfa"],
                    skipped=True)
                ledger.record_stage(
                    "cluster", outputs=_cluster_outputs(job.out_dir),
                    skipped=True)
        _screen_and_finalise(resume_jobs, mesh, errs, manifest, completed)
    if not any_compressed:
        raise AutocyclerError(
            f"all {len(jobs)} isolate(s) failed during compress; "
            f"see {manifest_path}")
    return _summarise(completed, errs, manifest_path, out_parent, len(jobs))


def run_fleet_jobs(jobs: List[IsolateJob], k_size: int = 51,
                   max_contigs: int = 25, threads: int = 1,
                   manifest_path=None, resume: bool = False) -> int:
    """Serve's entry into the fleet runner: one scheduler admission fans
    its batch items over the mesh in a single worker slot. ``jobs`` carry
    explicit per-item assembly/output dirs; the fleet manifest at
    ``manifest_path`` gives the admission crash-safe replay (a restarted
    daemon re-runs the job with ``resume=True`` and it re-enters at the
    per-isolate stage checkpoints). Returns the batch exit code (0 = all
    complete, 2 = partial failure; all-failed raises)."""
    jobs = [IsolateJob(j.name, Path(j.asm_dir), Path(j.out_dir))
            for j in jobs]
    manifest_path = Path(manifest_path)
    manifest = RunManifest.load(manifest_path) if resume \
        else RunManifest(manifest_path)
    todo = []
    for job in jobs:
        if resume and manifest.status(job.name) == "done":
            log.message(f"{job.name}: already complete — skipped (resume)")
            continue
        manifest.pending(job.name)
        todo.append(job)
    if not todo:
        log.message("All fleet isolates already complete; nothing to do")
        return 0
    resume_cluster = set()
    resume_compress = set()
    if resume:
        for job in todo:
            if manifest.stage_complete(job.name, "cluster"):
                resume_cluster.add(job.name)
            elif manifest.stage_complete(job.name, "compress"):
                resume_compress.add(job.name)
    errs = collect_errors()
    return _fleet_batch(todo, manifest_path.parent, k_size, max_contigs,
                        threads, manifest, manifest_path, resume_cluster,
                        resume_compress, errs)


def _batched_trim_screens(cluster_dirs, graphs, max_unitigs: int = 5000,
                          mesh=None, min_identity: float = 0.75):
    """One batched screen call covering every (sequence, trim kind) of every
    cluster, then ONE device traceback pass for the screened-positive jobs;
    returns {cluster_dir: {(seq_id, kind): False | alignment pieces}}.
    ``graphs`` maps each cluster dir to its preloaded (graph, sequences) —
    loading happens in `batch` under per-isolate quarantine, so an
    unreadable GFA skips one isolate, not the screen. With a mesh the
    screen shards over every device (parallel.batch.sharded_overlap_screen).
    Job construction mirrors trim_path_start_end / trim_path_hairpin_*
    (trim.rs:288-326): start_end aligns path vs itself off-diagonal,
    hairpin_start aligns path vs its signed reverse, hairpin_end the
    mirror. Screened-positive jobs get their full alignment decoded from
    the device DP's packed direction bits (ops.align.overlap_tracebacks_batch)
    — the host never re-runs the DP; jobs outside the int32 traceback
    domain stay True (host DP in trim)."""
    import numpy as np

    from ..ops.align import overlap_positive_batch, overlap_tracebacks_batch
    from ..parallel.batch import sharded_overlap_screen
    from ..utils import reverse_signed_path

    jobs, keys = [], []
    for cdir in cluster_dirs:
        graph, sequences = graphs[cdir]
        max_num = max((u.number for u in graph.unitigs), default=0)
        weights = np.zeros(max_num + 1, dtype=np.int64)
        for u in graph.unitigs:
            weights[u.number] = u.length()
        all_paths = graph.get_unitig_paths_for_sequences(
            [s.id for s in sequences])
        for seq in sequences:
            path = [n if st else -n for n, st in all_paths[seq.id]]
            rev = reverse_signed_path(path)
            jobs.append((path, path, weights, True))
            keys.append((cdir, seq.id, "start_end"))
            jobs.append((path, rev, weights, False))
            keys.append((cdir, seq.id, "hairpin_start"))
            jobs.append((rev, path, weights, False))
            keys.append((cdir, seq.id, "hairpin_end"))
    verdicts = sharded_overlap_screen(mesh, jobs, max_unitigs) \
        if mesh is not None else overlap_positive_batch(jobs, max_unitigs)
    pos_idx = [i for i, v in enumerate(verdicts) if v]
    decoded = overlap_tracebacks_batch([jobs[i] for i in pos_idx],
                                       max_unitigs, min_identity)
    screens = {cdir: {} for cdir in cluster_dirs}
    for (cdir, seq_id, kind), v in zip(keys, verdicts):
        screens[cdir][(seq_id, kind)] = bool(v)
    for i, pieces in zip(pos_idx, decoded):
        cdir, seq_id, kind = keys[i]
        if pieces is not None:
            screens[cdir][(seq_id, kind)] = pieces
    return screens
