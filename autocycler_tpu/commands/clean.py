"""`autocycler clean`: manual graph surgery on the final assembly graph.

Parity target: reference clean.rs — remove user-specified tigs, duplicate
tigs (requires exactly two non-self links), drop low-depth tigs when no dead
end results, then merge linear paths and renumber.
"""

from __future__ import annotations

import os
from typing import List, Optional

from ..models import UnitigGraph
from ..models.simplify import merge_linear_paths
from ..obs.timeseries import purge_timeseries
from ..utils import log, quit_with_error
from ..utils.cache import purge_cache


def parse_tig_numbers(tig_num_str: Optional[str]) -> List[int]:
    """'1, 2,3' -> [1, 2, 3], sorted (reference clean.rs:142-152)."""
    if not tig_num_str:
        return []
    out = []
    for token in tig_num_str.replace(" ", "").split(","):
        try:
            out.append(int(token))
        except ValueError:
            quit_with_error(f"failed to parse '{token}' as a tig number")
    return sorted(out)


def clean_cache(cache_dir) -> None:
    """`autocycler clean --cache <dir>`: purge the warm-start cache under
    an autocycler dir (or a cache dir itself), plus any rotated
    continuous-telemetry series (``timeseries.jsonl`` at the root and
    under serve job dirs) and any ``lint_report.json`` artifact (the
    committed ``lint_baseline.json`` is config, not cache, and is kept).
    A daemon's shared cache is LRU-capped automatically; this is the
    manual full reset."""
    if not os.path.isdir(cache_dir):
        quit_with_error(f"directory does not exist: {cache_dir}")
    removed, reclaimed = purge_cache(cache_dir)
    log.message(f"Purged warm-start cache under {cache_dir}: "
                f"{removed} entr{'y' if removed == 1 else 'ies'}, "
                f"{reclaimed} bytes reclaimed")
    ts_removed, ts_reclaimed = purge_timeseries(cache_dir)
    if ts_removed:
        log.message(f"Purged telemetry series under {cache_dir}: "
                    f"{ts_removed} file{'' if ts_removed == 1 else 's'}, "
                    f"{ts_reclaimed} bytes reclaimed")
    # lint_report.json is a derived artifact (`autocycler lint --report`
    # regenerates it); lint_baseline.json is configuration and survives
    report_path = os.path.join(cache_dir, "lint_report.json")
    if os.path.isfile(report_path):
        report_bytes = os.path.getsize(report_path)
        os.remove(report_path)
        log.message(f"Purged lint report {report_path}: "
                    f"{report_bytes} bytes reclaimed")
    # streamed k-mer spill bins are per-run scratch; anything still on disk
    # here was left behind by a killed or crashed run
    from ..stream import purge_stream_spills
    sp_removed, sp_reclaimed = purge_stream_spills(cache_dir)
    if sp_removed:
        log.message(f"Purged stream spill dirs under {cache_dir}: "
                    f"{sp_removed} run dir{'' if sp_removed == 1 else 's'}, "
                    f"{sp_reclaimed} bytes reclaimed")
    log.message()


def clean(in_gfa, out_gfa, remove: Optional[str] = None, duplicate: Optional[str] = None,
          min_depth: Optional[float] = None, cache: Optional[str] = None) -> None:
    if cache is not None:
        clean_cache(cache)
        if in_gfa is None and out_gfa is None:
            return
    if in_gfa is None or out_gfa is None:
        quit_with_error("clean requires -i and -o (or --cache DIR alone)")
    if not os.path.isfile(in_gfa):
        quit_with_error(f"file does not exist: {in_gfa}")
    log.section_header("Starting autocycler clean")
    log.explanation("This command removes user-specified tigs from a combined Autocycler "
                    "graph and then merges all linear paths to produce a clean output "
                    "graph.")
    remove_nums = parse_tig_numbers(remove)
    duplicate_nums = parse_tig_numbers(duplicate)
    graph, _ = UnitigGraph.from_gfa_file(in_gfa)
    graph.print_basic_graph_info()
    _check_tig_numbers_are_valid(in_gfa, graph, remove_nums)
    _check_tig_numbers_are_valid(in_gfa, graph, duplicate_nums)
    if remove_nums:
        graph.remove_unitigs_by_number(set(remove_nums))
        graph.print_basic_graph_info()
    for tig in duplicate_nums:
        graph.duplicate_unitig_by_number(tig)
    if min_depth is not None:
        graph.remove_low_depth_unitigs(min_depth)
    merge_linear_paths(graph, [])
    graph.renumber_unitigs()
    graph.print_basic_graph_info()
    graph.save_gfa(out_gfa, [], use_other_colour=True)
    log.section_header("Finished!")
    log.message(f"Cleaned graph: {out_gfa}")
    log.message()


def _check_tig_numbers_are_valid(in_gfa, graph: UnitigGraph, tig_numbers: List[int]) -> None:
    existing = {u.number for u in graph.unitigs}
    for tig in tig_numbers:
        if tig not in existing:
            quit_with_error(f"{in_gfa} does not contain tig {tig}")
