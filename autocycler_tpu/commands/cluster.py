"""`autocycler cluster`: group contigs into replicon clusters.

Parity target: reference cluster.rs. Pipeline: load input_assemblies.gfa,
compute the asymmetric pairwise distance matrix (one device matmul,
ops.distance), symmetrize by max, build a UPGMA tree, normalise the root to
0.5, cut at --cutoff with hill-climb refinement against the clustering score
(balance + tightness), QC the clusters (min_assemblies / containment /
trusted overrides), and write per-cluster 1_untrimmed.gfa checkpoints plus
PHYLIP, Newick, TSV and YAML outputs.
"""

from __future__ import annotations

import os
import shutil
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..metrics import ClusteringMetrics, UntrimmedClusterMetrics
from ..models import Sequence, UnitigGraph
from ..models.simplify import merge_linear_paths
from ..obs import ledger
from ..obs import qc as obs_qc
from ..ops.distance import pairwise_contig_distances
from ..ops.sketch import sketch_contig_distances, sketch_params
from ..utils import (format_float, load_file_lines, log, median, quit_with_error,
                     usize_division_rounded)
from ..utils.cache import open_cache
from ..utils.knobs import knob_int, knob_str
from ..utils.timing import stage_timer


# ---------------- tree ----------------

class TreeNode:
    """UPGMA tree node (reference cluster.rs:195-348). ``distance`` is the
    node-to-tip distance; tips carry sequence ids, internal nodes get fresh
    ids above the largest sequence id."""

    __slots__ = ("id", "left", "right", "distance")

    def __init__(self, id: int, left=None, right=None, distance: float = 0.0):
        self.id = id
        self.left = left
        self.right = right
        self.distance = distance

    def is_tip(self) -> bool:
        return self.left is None

    def max_pairwise_distance(self, node_num: int) -> float:
        if self.id == node_num:
            return self.distance * 2.0
        if self.is_tip():
            return -1.0
        return max(self.left.max_pairwise_distance(node_num),
                   self.right.max_pairwise_distance(node_num))

    def automatic_clustering(self, cutoff: float) -> List[int]:
        clusters: List[int] = []
        self._collect_clusters(cutoff / 2.0, [], clusters)
        return sorted(clusters)

    def manual_clustering(self, cutoff: float, manual_clusters: List[int]) -> List[int]:
        clusters: List[int] = []
        self._check_consistency(manual_clusters)
        self._collect_clusters(cutoff / 2.0, manual_clusters, clusters)
        return sorted(clusters)

    def _collect_clusters(self, cutoff: float, manual: List[int],
                          clusters: List[int]) -> None:
        if self.id in manual or (self.distance <= cutoff
                                 and not self._has_manual_child(manual)):
            clusters.append(self.id)
        elif not self.is_tip():
            self.left._collect_clusters(cutoff, manual, clusters)
            self.right._collect_clusters(cutoff, manual, clusters)

    def _has_manual_child(self, manual: List[int]) -> bool:
        if self.id in manual:
            return True
        if not self.is_tip():
            return (self.left._has_manual_child(manual)
                    or self.right._has_manual_child(manual))
        return False

    def _check_consistency(self, manual: List[int]) -> None:
        if not self.is_tip():
            if self.id in manual and (self.left._has_manual_child(manual)
                                      or self.right._has_manual_child(manual)):
                quit_with_error("manual clusters cannot be nested")
            self.left._check_consistency(manual)
            self.right._check_consistency(manual)

    def get_tips(self, node_num: int) -> List[int]:
        node = self.find_node(node_num)
        if node is None:
            return []
        tips: List[int] = []
        node._collect_tips(tips)
        return tips

    def _collect_tips(self, tips: List[int]) -> None:
        if self.is_tip():
            tips.append(self.id)
        else:
            self.left._collect_tips(tips)
            self.right._collect_tips(tips)

    def check_complete_coverage(self, clusters: List[int]) -> None:
        all_tips = set(self.get_tips(self.id))
        covered = set()
        for c in clusters:
            for tip in self.get_tips(c):
                if tip in covered:
                    raise AssertionError("overlap detected")
                covered.add(tip)
        if covered != all_tips:
            raise AssertionError("incomplete coverage")

    def split_clusters(self, clusters: List[int]) -> List[List[int]]:
        """All clusterings reachable by splitting one splittable cluster into
        its two children (reference cluster.rs:311-334)."""
        self.check_complete_coverage(clusters)
        result = []
        for cluster in clusters:
            node = self.find_node(cluster)
            if node is not None and not node.is_tip():
                alt = [c for c in clusters if c != cluster]
                alt.extend([node.left.id, node.right.id])
                result.append(sorted(alt))
        result.sort()
        return result

    def find_node(self, node_num: int) -> Optional["TreeNode"]:
        if self.id == node_num:
            return self
        if self.is_tip():
            return None
        found = self.left.find_node(node_num)
        if found is not None:
            return found
        return self.right.find_node(node_num)


def _distances_to_matrix(distances: Dict[Tuple[int, int], float],
                         pos: Dict[int, int], n: int) -> np.ndarray:
    """One vectorised pass over a {(id_a, id_b): d} dict into a dense
    [n, n] float64 matrix, +inf where absent; pairs whose ids are missing
    from ``pos`` are ignored. Shared by upgma() and containment_counts()
    so the scatter pattern can't drift between them."""
    D = np.full((n, n), np.inf)
    if distances:
        idx = np.array([(pos.get(a, -1), pos.get(b, -1))
                        for a, b in distances], np.int64).reshape(-1, 2)
        vals = np.fromiter(distances.values(), np.float64, len(distances))
        m = (idx[:, 0] >= 0) & (idx[:, 1] >= 0)
        D[idx[m, 0], idx[m, 1]] = vals[m]
    return D


def upgma(distances: Dict[Tuple[int, int], float], sequences: List[Sequence]) -> TreeNode:
    """UPGMA over the symmetric distance map; merged clusters keep the id
    min(a, b); internal node ids count up from the largest sequence id; ties
    broken by the first pair in sorted-id order (reference cluster.rs:395-458).

    The reference (and the previous implementation here) re-scans a dict of
    pair distances per merge — O(n³) with heavy constants. This wraps the
    O(n²) matrix implementation below; the closest-pair tie-break (smallest
    id pair in sorted order) is preserved. Inter-cluster averages are the
    same sums of ORIGINAL pair distances divided once, accumulated in merge
    order rather than flat order — mathematically identical, but float
    addition is not associative, so candidate-pair averages can differ from
    the reference's flat re-summation by ulps: EXACT ties and ulp-level
    near-ties between closest-pair candidates may resolve differently on
    pathological inputs (the previous dict implementation summed in
    unordered set-iteration order, so it made no stronger guarantee).

    A pair missing from ``distances`` in BOTH directions is an error: the
    matrix would otherwise treat it as distance 0 and merge it first, where
    the dict implementation failed loudly during averaging.
    """
    ids = sorted(s.id for s in sequences)
    n = len(ids)
    pos = {a: i for i, a in enumerate(ids)}
    D = _distances_to_matrix(distances, pos, n)
    diag = np.diag(D).copy()
    diag[np.isinf(diag)] = 0.0       # absent self-pairs are distance 0
    np.fill_diagonal(D, diag)
    D = np.minimum(D, D.T)           # fills any one-directional entries
    if n > 1 and not np.isfinite(D).all():   # diagonal is finite, so any
        #                                      inf is a missing off-diag pair
        a, b = np.argwhere(~np.isfinite(D))[0]
        raise ValueError(
            f"distance map is missing pair ({ids[a]}, {ids[b]}): UPGMA "
            "requires every sequence pair (an absent pair would otherwise "
            "merge first as distance 0)")
    return upgma_matrix(D, ids)


def upgma_matrix(D: np.ndarray, ids: List[int]) -> TreeNode:
    """O(n²) UPGMA over a dense symmetric distance matrix (row/col order =
    ascending cluster ids). Cluster-to-cluster distance is the mean of the
    ORIGINAL member-pair distances, maintained as exact pair-sums merged
    additively; the closest pair is the row-major-first minimum (identical
    tie-break to scanning pairs in sorted-id order). Per merge only the
    merged row/column and invalidated row-minima are recomputed."""
    n = len(ids)
    if n == 1:
        return TreeNode(ids[0])
    S = np.asarray(D, dtype=np.float64).copy()  # pair-distance sums
    size = np.ones(n, dtype=np.int64)
    active = np.ones(n, dtype=bool)
    nodes: Dict[int, TreeNode] = {i: TreeNode(ids[i]) for i in range(n)}
    internal_node_num = max(ids)
    INF = np.inf

    # rowmin[i] = min over active j>i of avg(i, j); rowarg[i] = smallest such j
    def avg_row(i: int) -> np.ndarray:
        return S[i] / (size[i] * size)

    rowmin = np.full(n, INF)
    rowarg = np.full(n, -1, dtype=np.int64)

    def recompute_row(i: int) -> None:
        vals = avg_row(i)
        vals = np.where(active, vals, INF)
        vals[:i + 1] = INF
        j = int(np.argmin(vals))
        rowmin[i], rowarg[i] = vals[j], j

    for i in range(n):
        recompute_row(i)

    for _ in range(n - 1):
        a = int(np.argmin(rowmin))       # first occurrence = smallest id pair
        b = int(rowarg[a])
        pair_distance = float(rowmin[a])  # plain float: numpy scalars would
        #                                   leak np.float64 reprs into YAML/TSV

        internal_node_num += 1
        nodes[a] = TreeNode(internal_node_num, nodes.pop(a), nodes.pop(b),
                            pair_distance / 2.0)

        # merge b into a: sums add exactly; sizes add
        S[a] += S[b]
        S[:, a] = S[a]
        size[a] += size[b]
        active[b] = False
        rowmin[b] = INF

        if len(nodes) == 1:
            break

        # rows i<a: column a changed, column b vanished
        lo = np.flatnonzero(active[:a])
        if len(lo):
            newvals = S[lo, a] / (size[lo] * size[a])
            improve = (newvals < rowmin[lo]) | \
                ((newvals == rowmin[lo]) & (a < rowarg[lo]))
            rowmin[lo[improve]] = newvals[improve]
            rowarg[lo[improve]] = a
            stale = lo[~improve]
            for i in stale[np.isin(rowarg[stale], (a, b))]:
                recompute_row(int(i))
        # rows a<i<b that pointed at b
        mid = np.flatnonzero(active[a + 1:b]) + a + 1
        for i in mid[rowarg[mid] == b]:
            recompute_row(int(i))
        recompute_row(a)

    return next(iter(nodes.values()))


def normalise_tree(root: TreeNode) -> None:
    """Scale so root-to-tip distance is at most 0.5 (reference cluster.rs:483-494)."""
    if root.distance > 0.5:
        _scale(root, 0.5 / root.distance)


def _scale(node: TreeNode, factor: float) -> None:
    node.distance *= factor
    if node.left is not None:
        _scale(node.left, factor)
        _scale(node.right, factor)


def _fmt(x: float) -> str:
    """Shortest round-trip float representation, integral values without a
    decimal point (Rust `{}` Display semantics, which the reference uses for
    Newick branch lengths)."""
    s = repr(float(x))
    return s[:-2] if s.endswith(".0") else s


def tree_to_newick(node: TreeNode, index: Dict[int, Sequence]) -> str:
    if node.left is not None and node.right is not None:
        left = tree_to_newick(node.left, index)
        right = tree_to_newick(node.right, index)
        return (f"({left}:{_fmt(node.distance - node.left.distance)},"
                f"{right}:{_fmt(node.distance - node.right.distance)}){node.id}")
    return index[node.id].string_for_newick()


def save_tree_to_newick(root: TreeNode, sequences: List[Sequence], file_path) -> None:
    """Newick with a root branch padding root-to-tip distances to 0.5
    (reference cluster.rs:363-380)."""
    index = {s.id: s for s in sequences}
    newick = tree_to_newick(root, index)
    with open(file_path, "w") as f:
        if root.distance < 0.5:
            f.write(f"({newick}:{_fmt(0.5 - root.distance)});\n")
        else:
            f.write(f"{newick};\n")


# ---------------- QC ----------------

class ClusterQC:
    __slots__ = ("failure_reasons", "cluster_dist")

    def __init__(self, cluster_dist: float = 0.0):
        self.failure_reasons: List[str] = []
        self.cluster_dist = cluster_dist

    def passed(self) -> bool:
        return not self.failure_reasons


def make_symmetrical_distances(asym: Dict[Tuple[int, int], float],
                               sequences: List[Sequence]) -> Dict[Tuple[int, int], float]:
    """max(A->B, B->A) per pair (reference cluster.rs:177-192)."""
    sym = {}
    for a in sequences:
        for b in sequences:
            sym[(a.id, b.id)] = max(asym[(a.id, b.id)], asym[(b.id, a.id)])
    return sym


def generate_clusters(tree: TreeNode, sequences: List[Sequence],
                      distances: Dict[Tuple[int, int], float], cutoff: float,
                      min_assemblies: int, manual_clusters: List[int]
                      ) -> Dict[int, ClusterQC]:
    try:
        if not manual_clusters:
            auto = tree.automatic_clustering(cutoff)
            clusters = refine_auto_clusters(tree, sequences, distances, auto,
                                            cutoff, min_assemblies)
        else:
            clusters = tree.manual_clustering(cutoff, manual_clusters)
        tree.check_complete_coverage(clusters)
        return qc_clusters(tree, sequences, distances, clusters, manual_clusters,
                           cutoff, min_assemblies)
    finally:
        # the containment memo exists to serve the hill-climb's many score
        # evaluations above; release the dense matrix + dict reference when
        # clustering is done so a long batch run doesn't carry the largest
        # isolate's S x S matrix to process exit (advisor r5)
        _contain_cache.clear()


def qc_clusters(tree: TreeNode, sequences: List[Sequence],
                distances: Dict[Tuple[int, int], float], cluster_nodes: List[int],
                manual_clusters: List[int], cutoff: float, min_assemblies: int
                ) -> Dict[int, ClusterQC]:
    """Assign cluster numbers and decide pass/fail: too-few-assemblies and
    containment failures, with trusted contigs exempting their cluster
    (reference cluster.rs:511-570)."""
    qc_results: Dict[int, ClusterQC] = {}
    current = 0
    for n in cluster_nodes:
        node = tree.find_node(n)
        if node is None:
            quit_with_error(f"clustering tree does not contain a node with id {n}")
        current += 1
        _assign_cluster_to_node(node, sequences, current)
        qc = ClusterQC(tree.max_pairwise_distance(n))
        if manual_clusters and n not in manual_clusters:
            qc.failure_reasons.append("not included in manual clusters")
        qc_results[current] = qc

    old_to_new = reorder_clusters(sequences)
    qc_results = {old_to_new[old]: qc for old, qc in qc_results.items()}

    if not manual_clusters:
        max_cluster = get_max_cluster(sequences)
        for c in range(1, max_cluster + 1):
            count = cluster_assembly_count(sequences, c)
            if count < min_assemblies and not cluster_is_trusted(sequences, c):
                qc_results[c].failure_reasons.append("present in too few assemblies")
        # the pair-count matrices are cluster-assignment-dependent but not
        # qc-status-dependent, so they are computed once; the sequential
        # loop below still sees earlier containment failures through
        # qc_results, exactly like the reference's per-cluster re-check
        counts = containment_counts(sequences, distances, cutoff)
        for c in range(1, max_cluster + 1):
            container = cluster_is_contained_in_another(c, sequences, distances, cutoff,
                                                        qc_results, counts=counts)
            if container > 0 and not cluster_is_trusted(sequences, c):
                qc_results[c].failure_reasons.append(
                    f"contained within cluster {container}")
    return qc_results


def _assign_cluster_to_node(node: TreeNode, sequences: List[Sequence],
                            cluster: int) -> None:
    for s in sequences:
        if s.id == node.id:
            s.cluster = cluster
    if node.left is not None:
        _assign_cluster_to_node(node.left, sequences, cluster)
        _assign_cluster_to_node(node.right, sequences, cluster)


def cluster_assembly_count(sequences: List[Sequence], c: int) -> int:
    """Assemblies represented in the cluster, scaled by the max cluster-weight
    directive per file (reference cluster.rs:572-585)."""
    weights: Dict[str, int] = {}
    for seq in sequences:
        if seq.cluster != c:
            continue
        w = seq.cluster_weight()
        if seq.filename not in weights or w > weights[seq.filename]:
            weights[seq.filename] = w
    return sum(weights.values())


def cluster_is_trusted(sequences: List[Sequence], c: int) -> bool:
    return any(s.cluster == c and s.is_trusted() for s in sequences)


# single-slot memo for the cluster-assignment-INDEPENDENT part of
# containment counting: the refinement hill-climb scores many candidate
# clusterings against the same distance dict, and rebuilding the dense
# [S, S] matrix per score evaluation would reintroduce the O(S²)-per-call
# Python constant this module just removed (advisor r5 finding). A hit
# requires the SAME dict object (`is` against the held strong reference —
# id() alone can alias two distinct dicts once the first is garbage
# collected and its id recycled) plus equal cutoff and id tuple;
# generate_clusters() clears the slot when clustering finishes.
_contain_cache: Dict[str, object] = {}


def _contain_ab_cached(distances: Dict[Tuple[int, int], float],
                       cutoff: float, ids: Tuple[int, ...]) -> np.ndarray:
    key = (cutoff, ids)
    if _contain_cache.get("distances_ref") is not distances \
            or _contain_cache.get("key") != key:
        pos = {a: i for i, a in enumerate(ids)}
        D = _distances_to_matrix(distances, pos, len(ids))
        _contain_cache.update(key=key, distances_ref=distances,
                              contain_ab=(D < D.T) & (D < cutoff))
    return _contain_cache["contain_ab"]  # type: ignore[return-value]


def containment_counts(sequences: List[Sequence],
                       distances: Dict[Tuple[int, int], float],
                       cutoff: float) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorised cross-cluster containment accounting (the reference counts
    these pairs with nested per-cluster-pair loops, cluster.rs:692-723 —
    O(S²) Python at the 32,767-sequence cap the position model supports).

    One pass over the distance dict rebuilds the [S, S] matrix (the same
    pattern as upgma()); the per-cluster-pair pair counts are then two
    integer matmuls with the cluster-membership matrix. Returns
    ``(contain, total)``, both [C+1, C+1] int64 where C is the max cluster
    number: ``contain[c, o]`` = number of (a in c, b in o) pairs with
    d(a,b) < d(b,a) and d(a,b) < cutoff; ``total[c, o]`` = |c| * |o|.
    Pairs absent from the dict never count as contained (their distance is
    +inf); the product flow always passes a complete matrix-derived dict."""
    clustered = [s for s in sequences if s.cluster >= 1]
    max_cluster = max((s.cluster for s in clustered), default=0)
    if not clustered:
        z = np.zeros((1, 1), np.int64)
        return z, z
    S = len(clustered)
    contain_ab = _contain_ab_cached(distances, cutoff,
                                    tuple(s.id for s in clustered))
    P = np.zeros((max_cluster + 1, S), np.int64)
    P[np.array([s.cluster for s in clustered]), np.arange(S)] = 1
    # uint8 cast: the matmul promotes with int64 P, so the result is the
    # same exact integer count at 1/8 the temporary size (S² at the 32k
    # sequence cap is the design point)
    contain = P @ contain_ab.astype(np.uint8) @ P.T
    sizes = P.sum(axis=1)
    total = sizes[:, None] * sizes[None, :]
    return contain, total


def cluster_is_contained_in_another(cluster_num: int, sequences: List[Sequence],
                                    distances: Dict[Tuple[int, int], float],
                                    cutoff: float, qc_results: Dict[int, ClusterQC],
                                    counts: Optional[Tuple[np.ndarray, np.ndarray]] = None
                                    ) -> int:
    """A cluster is contained in a passing cluster when the majority of
    cross-pair distances are asymmetric and below the cutoff
    (reference cluster.rs:692-723). The pair counting is vectorised in
    :func:`containment_counts`; callers checking many clusters (qc_clusters)
    compute the matrices once and pass them as ``counts``. The first passing
    cluster in qc_results iteration order wins, as in the reference."""
    contain, total = counts if counts is not None else \
        containment_counts(sequences, distances, cutoff)
    C = contain.shape[0]
    for other in (c for c, qc in qc_results.items() if qc.passed()):
        if other == cluster_num or other >= C or cluster_num >= C:
            continue
        t = total[cluster_num, other]
        if t and contain[cluster_num, other] / t > 0.5:
            return other
    return 0


def score_clustering(tree: TreeNode, sequences: List[Sequence],
                     distances: Dict[Tuple[int, int], float], clusters: List[int],
                     cutoff: float, min_assemblies: int) -> float:
    qc = qc_clusters(tree, sequences, distances, clusters, [], cutoff, min_assemblies)
    return clustering_metrics(sequences, qc).overall_clustering_score


def refine_auto_clusters(tree: TreeNode, sequences: List[Sequence],
                         distances: Dict[Tuple[int, int], float], clusters: List[int],
                         cutoff: float, min_assemblies: int) -> List[int]:
    """Hill-climb: split any cluster whose split improves the overall score
    (reference cluster.rs:607-630)."""
    best = list(clusters)
    best_score = score_clustering(tree, sequences, distances, best, cutoff,
                                  min_assemblies)
    improved = True
    while improved:
        improved = False
        for alt in tree.split_clusters(best):
            alt_score = score_clustering(tree, sequences, distances, alt, cutoff,
                                         min_assemblies)
            if alt_score > best_score + 1e-12:
                best, best_score = alt, alt_score
                improved = True
    return best


def reorder_clusters(sequences: List[Sequence]) -> Dict[int, int]:
    """Renumber clusters by median sequence length, descending; ties by old
    number (reference cluster.rs:882-903)."""
    cluster_lengths = {}
    for c in range(1, get_max_cluster(sequences) + 1):
        lengths = [s.length for s in sequences if s.cluster == c]
        cluster_lengths[c] = median(lengths)
    ordered = sorted(cluster_lengths.items(), key=lambda kv: (-kv[1], kv[0]))
    old_to_new = {old: i + 1 for i, (old, _) in enumerate(ordered)}
    for s in sequences:
        if s.cluster >= 1:
            s.cluster = old_to_new[s.cluster]
    return old_to_new


def get_assembly_count(sequences: List[Sequence]) -> int:
    return len({s.filename for s in sequences})


def get_max_cluster(sequences: List[Sequence]) -> int:
    return max(s.cluster for s in sequences)


def set_min_assemblies(min_assemblies_option: Optional[int],
                       sequences: List[Sequence]) -> int:
    """Auto --min_assemblies: assemblies/4 rounded, min 2 (1 when there is a
    single assembly) (reference cluster.rs:645-661)."""
    if min_assemblies_option is not None:
        return min_assemblies_option
    count = get_assembly_count(sequences)
    if count == 1:
        return 1
    return max(2, usize_division_rounded(count, 4))


def parse_manual_clusters(manual: Optional[str]) -> List[int]:
    if not manual:
        return []
    out = []
    for token in manual.replace(" ", "").split(","):
        try:
            out.append(int(token))
        except ValueError:
            quit_with_error(f"failed to parse '{token}' as a node number")
    return sorted(out)


def clustering_metrics(sequences: List[Sequence], qc_results: Dict[int, ClusterQC]
                       ) -> ClusteringMetrics:
    metrics = ClusteringMetrics()
    cluster_filenames: Dict[int, List[str]] = {}
    for seq in sequences:
        qc = qc_results[seq.cluster]
        cluster_filenames.setdefault(seq.cluster, []).append(seq.filename)
        if qc.passed():
            metrics.pass_contig_count += 1
        else:
            metrics.fail_contig_count += 1
    pass_cluster_stats = []
    for c in range(1, get_max_cluster(sequences) + 1):
        qc = qc_results[c]
        if qc.passed():
            metrics.pass_cluster_count += 1
            size = len(cluster_filenames.get(c, []))
            pass_cluster_stats.append((qc.cluster_dist, size))
        else:
            metrics.fail_cluster_count += 1
    metrics.calculate_fractions()
    metrics.calculate_scores(cluster_filenames, pass_cluster_stats)
    return metrics


# ---------------- outputs ----------------

def save_distance_matrix(distances: Dict[Tuple[int, int], float],
                         sequences: List[Sequence], file_path) -> None:
    """PHYLIP matrix with display names (reference cluster.rs:160-174)."""
    with open(file_path, "w") as f:
        f.write(f"{len(sequences)}\n")
        for a in sequences:
            f.write(str(a))
            for b in sequences:
                f.write(f"\t{distances[(a.id, b.id)]:.8f}")
            f.write("\n")


def save_cluster_gfa(sequences: List[Sequence], cluster_num: int,
                     graph: UnitigGraph, out_gfa
                     ) -> Tuple[UnitigGraph, List[Sequence]]:
    """Per-cluster graph: subset the in-memory graph to the cluster's
    sequences, recalc depths, drop zero-depth unitigs, merge linear paths
    (reference cluster.rs:794-822, which filters P-lines and re-loads the
    GFA text — the subset produces the identical graph without the text
    round trip). Returns (cluster_graph, cluster_seqs) so in-process
    callers can hand them to trim(preloaded=...)."""
    cluster_seqs = [_clone_seq(s) for s in sequences if s.cluster == cluster_num]
    cluster_graph = graph.subset_for_sequences([s.id for s in cluster_seqs])
    cluster_graph.recalculate_depths()
    cluster_graph.remove_zero_depth_unitigs()
    merge_linear_paths(cluster_graph, cluster_seqs)
    cluster_graph.save_gfa(out_gfa, cluster_seqs)
    return cluster_graph, cluster_seqs


def _clone_seq(s: Sequence) -> Sequence:
    return Sequence(s.id, s.forward_seq, s.reverse_seq, s.filename, s.contig_header,
                    s.length, s.cluster)


def save_clusters(sequences: List[Sequence], qc_results: Dict[int, ClusterQC],
                  clustering_dir, graph: UnitigGraph,
                  collect_handoff: bool = False
                  ) -> Dict[Path, Tuple[UnitigGraph, List[Sequence]]]:
    """Writes every cluster's 1_untrimmed.gfa/.yaml; with ``collect_handoff``
    returns {qc-pass cluster dir: (cluster_graph, cluster_seqs)} for
    in-process handoff to trim (kept off by default so CLI runs keep the
    one-cluster-at-a-time graph lifetime instead of holding every cluster's
    positions in memory at once)."""
    handoff = {}
    for c in range(1, get_max_cluster(sequences) + 1):
        qc = qc_results[c]
        sub = "qc_pass" if qc.passed() else "qc_fail"
        cluster_dir = Path(clustering_dir) / sub / f"cluster_{c:03d}"
        os.makedirs(cluster_dir, exist_ok=True)
        log.message(f"Cluster {c:03d}:")
        lengths = [s.length for s in sequences if s.cluster == c]
        for s in sequences:
            if s.cluster == c:
                log.message(f"  {s}")
        if len(lengths) > 1:
            log.message(f"  cluster distance: {format_float(qc.cluster_dist)}")
        if qc.passed():
            log.message("  passed QC")
        else:
            for reason in qc.failure_reasons:
                log.message(f"  failed QC: {reason}")
        pair = save_cluster_gfa(sequences, c, graph, cluster_dir / "1_untrimmed.gfa")
        if collect_handoff and qc.passed():
            handoff[cluster_dir] = pair
        del pair
        UntrimmedClusterMetrics.new(lengths, qc.cluster_dist).save_to_yaml(
            cluster_dir / "1_untrimmed.yaml")
        log.message()
    return handoff


def save_data_to_tsv(sequences: List[Sequence], qc_results: Dict[int, ClusterQC],
                     file_path) -> None:
    with open(file_path, "w") as f:
        f.write("node_name\tpassing_clusters\tall_clusters\tsequence_id\tfile_name\t"
                "contig_name\tlength\ttrusted\tcluster_weight\tconsensus_weight\n")
        for seq in sequences:
            assert seq.cluster != 0
            qc = qc_results[seq.cluster]
            pass_cluster = str(seq.cluster) if qc.passed() else "none"
            f.write(f"{seq.string_for_newick()}\t{pass_cluster}\t{seq.cluster}\t"
                    f"{seq.id}\t{seq.filename}\t{seq.contig_name()}\t{seq.length}\t"
                    f"{str(seq.is_trusted()).lower()}\t{seq.cluster_weight()}\t"
                    f"{seq.consensus_weight()}\n")


# ---------------- distance backend selection ----------------

def resolve_distance_mode(n_contigs: int) -> str:
    """'exact' | 'sketch' | 'verify' from AUTOCYCLER_SKETCH_DISTANCE.

    'auto' (the default) engages sketching at AUTOCYCLER_SKETCH_MIN_CONTIGS
    contigs and above — below that the exact path is both fast enough and
    the oracle. 'on'/'off' force a backend; 'verify' runs BOTH, clusters
    from the exact distances, and records the sketch-vs-exact max abs
    error in QC + the ledger (the production parity probe)."""
    raw = (knob_str("AUTOCYCLER_SKETCH_DISTANCE") or "auto").strip().lower()
    if raw in ("0", "off", "false", "no", "exact"):
        return "exact"
    if raw in ("1", "on", "true", "yes", "sketch"):
        return "sketch"
    if raw == "verify":
        return "verify"
    threshold = int(knob_int("AUTOCYCLER_SKETCH_MIN_CONTIGS"))
    return "sketch" if n_contigs >= threshold else "exact"


def compute_distances(graph, sequences, autocycler_dir=None, use_jax=None
                      ) -> Tuple[Dict[Tuple[int, int], float], dict]:
    """The cluster distance dict plus a provenance record
    ``{"mode", "sketch_s", "sketch_max_abs_error"?}``.

    Sketch mode replaces the contig×unitig membership contraction with
    bottom-s minimizer sketches and one batched containment grid
    (ops.sketch); the distances flow through the identical
    UPGMA/cutoff machinery either way. Sketches are content-addressed in
    the warm-start cache so serve's daemon reuses them across jobs."""
    mode = resolve_distance_mode(len(sequences))
    k, w, s = sketch_params()
    record = {"mode": mode, "sketch_s": s}
    sketch = exact = None
    if mode in ("sketch", "verify"):
        sketch = sketch_contig_distances(
            graph, sequences, cache=open_cache(autocycler_dir),
            use_jax=use_jax)
    if mode in ("exact", "verify"):
        exact = pairwise_contig_distances(graph, sequences, use_jax=use_jax)
    if mode == "verify":
        record["sketch_max_abs_error"] = max(
            (abs(sketch[p] - exact[p]) for p in exact), default=0.0)
    return (exact if exact is not None else sketch), record


# ---------------- entry point ----------------

def cluster(autocycler_dir, cutoff: float = 0.2, min_assemblies: Optional[int] = None,
            max_contigs: int = 25, manual: Optional[str] = None, use_jax=None,
            precomputed_distances=None, collect_handoff: bool = False
            ) -> Optional[Dict[Path, Tuple[UnitigGraph, List[Sequence]]]]:
    """precomputed_distances: optional {(id_a, id_b): float} replacing the
    in-process distance computation — the `batch` subcommand passes each
    isolate's matrix from the mesh-batched device contraction (bit-identical
    to what pairwise_contig_distances would compute here).
    collect_handoff: return {qc-pass cluster dir: (graph, sequences)} for
    in-process trim(preloaded=...) chaining (bench/batch); None otherwise."""
    autocycler_dir = Path(autocycler_dir)
    gfa = autocycler_dir / "input_assemblies.gfa"
    clustering_dir = autocycler_dir / "clustering"
    if not autocycler_dir.is_dir():
        quit_with_error(f"directory does not exist: {autocycler_dir}")
    if not gfa.is_file():
        quit_with_error(f"file does not exist: {gfa}")
    if cutoff <= 0.0 or cutoff >= 1.0:
        quit_with_error("--cutoff must be between 0 and 1 (exclusive)")
    if min_assemblies is not None and min_assemblies < 1:
        quit_with_error("--min_assemblies must be 1 or greater")
    if clustering_dir.is_dir():
        shutil.rmtree(clustering_dir)
    os.makedirs(clustering_dir)

    log.section_header("Starting autocycler cluster")
    log.explanation("This command takes a unitig graph (made by autocycler compress) and "
                    "clusters the sequences based on their similarity. Ideally, each "
                    "cluster will then contain sequences which can be combined into a "
                    "consensus.")
    with stage_timer("cluster/load"):
        gfa_lines = load_file_lines(gfa)
        graph, sequences = UnitigGraph.from_gfa_lines(gfa_lines)
    min_asm = set_min_assemblies(min_assemblies, sequences)
    manual_clusters = parse_manual_clusters(manual)

    if not sequences:
        quit_with_error("no sequences found in input_assemblies.gfa")
    mean = len(sequences) / get_assembly_count(sequences)
    if mean > max_contigs:
        quit_with_error(
            f"the mean number of contigs per input assembly ({mean:.1f}) exceeds the "
            f"allowed threshold ({max_contigs}). Are your input assemblies fragmented "
            "or contaminated?")

    log.section_header("Pairwise distances")
    log.explanation("Every pairwise distance between contigs is calculated based on the "
                    "similarity of their paths through the graph.")
    with stage_timer("cluster/distances"):
        if precomputed_distances is not None:
            asym = precomputed_distances
            distance_record = {"mode": "precomputed"}
        else:
            asym, distance_record = compute_distances(
                graph, sequences, autocycler_dir=autocycler_dir,
                use_jax=use_jax)
        obs_qc.record("cluster_distance", contigs=len(sequences),
                      **distance_record)
        save_distance_matrix(asym, sequences,
                             clustering_dir / "pairwise_distances.phylip")

    log.section_header("Clustering sequences")
    log.explanation("Contigs are organised into a tree using UPGMA. Then clusters are "
                    "defined from the tree using the distance cutoff.")
    with stage_timer("cluster/tree"):
        sym = make_symmetrical_distances(asym, sequences)
        tree = upgma(sym, sequences)
        normalise_tree(tree)
        save_tree_to_newick(tree, sequences, clustering_dir / "clustering.newick")

        qc_results = generate_clusters(tree, sequences, asym, cutoff, min_asm,
                                       manual_clusters)
    with stage_timer("cluster/outputs"):
        handoff = save_clusters(sequences, qc_results, clustering_dir, graph,
                                collect_handoff=collect_handoff)
        save_data_to_tsv(sequences, qc_results, clustering_dir / "clustering.tsv")
        clustering_metrics(sequences, qc_results).save_to_yaml(
            clustering_dir / "clustering.yaml")
    obs_qc.cluster_qc(sequences, qc_results)
    ledger.record_stage(
        "cluster", inputs=[gfa],
        outputs=[clustering_dir / "pairwise_distances.phylip",
                 clustering_dir / "clustering.newick",
                 clustering_dir / "clustering.tsv",
                 clustering_dir / "clustering.yaml"]
        + sorted(clustering_dir.glob("qc_*/cluster_*/1_untrimmed.gfa")),
        **{f"distance_{key}": value
           for key, value in distance_record.items()})

    log.section_header("Finished!")
    log.explanation("You can now run autocycler trim on each cluster.")
    log.message(f"Pairwise distances:         {clustering_dir / 'pairwise_distances.phylip'}")
    log.message(f"Clustering tree (Newick):   {clustering_dir / 'clustering.newick'}")
    log.message(f"Clustering tree (metadata): {clustering_dir / 'clustering.tsv'}")
    log.message()
    # {qc-pass cluster dir: (graph, sequences)} for in-process trim handoff
    return handoff if collect_handoff else None
