"""`autocycler combine`: concatenate per-cluster final graphs into one
consensus assembly.

Parity target: reference combine.rs:25-137 — unitig numbers are offset per
cluster, topology (circular=true/linear) is stamped into FASTA headers,
colour tags into the GFA, and consensus_assembly_fully_resolved records
whether every cluster collapsed to a single unitig.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import List

from ..metrics import CombineMetrics, ResolvedClusterDetails
from ..models import UnitigGraph
from ..obs import ledger, qc
from ..utils import log, quit_with_error


def unitig_topology_suffix(unitig) -> str:
    if unitig.is_isolated_and_circular():
        return " circular=true topology=circular"
    if unitig.is_isolated_and_linear():
        return " circular=false topology=linear"
    return ""


def combine(autocycler_dir, in_gfas: List) -> None:
    autocycler_dir = Path(autocycler_dir)
    combined_gfa = autocycler_dir / "consensus_assembly.gfa"
    combined_fasta = autocycler_dir / "consensus_assembly.fasta"
    combined_yaml = autocycler_dir / "consensus_assembly.yaml"
    for gfa in in_gfas:
        if not os.path.isfile(gfa):
            quit_with_error(f"file does not exist: {gfa}")
    os.makedirs(autocycler_dir, exist_ok=True)

    log.section_header("Starting autocycler combine")
    log.explanation("This command combines different clusters into a single assembly file.")
    metrics = CombineMetrics()
    combine_clusters(in_gfas, combined_gfa, combined_fasta, metrics)
    metrics.save_to_yaml(combined_yaml)
    qc.combine_qc(metrics)
    ledger.record_stage("combine", inputs=in_gfas,
                        outputs=[combined_gfa, combined_fasta, combined_yaml])

    log.section_header("Finished!")
    log.message(f"Combined graph: {combined_gfa}")
    log.message(f"Combined fasta: {combined_fasta}")
    log.message()
    if metrics.consensus_assembly_fully_resolved:
        log.message("Consensus assembly is fully resolved")
    else:
        log.message("One or more clusters failed to fully resolve")
    log.message()


def combine_clusters(in_gfas: List, combined_gfa, combined_fasta,
                     metrics: CombineMetrics) -> None:
    """Concatenate cluster graphs with unitig-number offsets
    (reference combine.rs:90-137)."""
    gfa_lines = ["H\tVN:Z:1.0"]
    fasta_lines = []
    metrics.consensus_assembly_fully_resolved = True
    offset = 0
    for gfa in in_gfas:
        log.message(str(gfa))
        graph, _ = UnitigGraph.from_gfa_file(gfa)
        graph.print_basic_graph_info(with_topology=True)
        for unitig in graph.unitigs:
            num = unitig.number + offset
            seq = unitig.seq_str()
            colour_tag = unitig.colour_tag(True) or "\tCL:Z:orangered"
            gfa_lines.append(f"S\t{num}\t{seq}\tDP:f:{unitig.depth:.2f}{colour_tag}")
            fasta_lines.append(f">{num} length={unitig.length()}"
                               f"{unitig_topology_suffix(unitig)}")
            fasta_lines.append(seq)
        for a, a_strand, b, b_strand in graph.links_for_gfa(offset):
            gfa_lines.append(f"L\t{a}\t{a_strand}\t{b}\t{b_strand}\t0M")
        offset += graph.max_unitig_number()
        metrics.consensus_assembly_bases += graph.total_length()
        metrics.consensus_assembly_unitigs += len(graph.unitigs)
        metrics.consensus_assembly_clusters.append(ResolvedClusterDetails(
            length=graph.total_length(), unitigs=len(graph.unitigs),
            topology=graph.topology()))
        if len(graph.unitigs) > 1:
            metrics.consensus_assembly_fully_resolved = False
    with open(combined_gfa, "w") as f:
        f.write("\n".join(gfa_lines) + "\n")
    with open(combined_fasta, "w") as f:
        f.write("\n".join(fasta_lines) + "\n" if fasta_lines else "")
