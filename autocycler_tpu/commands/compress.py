"""`autocycler compress`: input assemblies -> compacted unitig graph GFA.

Parity target: reference compress.rs:32-50. Pipeline: discover FASTAs, load
and pad contigs, repair dotted ends, build the k-mer index + unitig graph on
device (ops.kmers / ops.debruijn / ops.graph_build — replacing the reference's
hash-map hot loops), simplify repeats, and write input_assemblies.gfa plus
input_assemblies.yaml metrics.
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import List, Optional, Tuple

import numpy as np

from ..metrics import InputAssemblyDetails, InputAssemblyMetrics, InputContigDetails
from ..models import Sequence, UnitigGraph
from ..models.sequence import padded_strand
from ..models.simplify import simplify_structure
from ..obs import ledger, qc
from ..ops.end_repair import sequence_end_repair
from ..ops.graph_build import build_unitig_graph
from ..utils import (Spinner, check_threads, find_all_assemblies,
                     format_duration, load_fasta, log, quit_with_error,
                     record_degrade, reverse_complement_bytes)
from ..utils.cache import EncodeCache, content_hash, open_cache
from ..utils.pool import pool_map
from ..utils.timing import stage_timer, substage

MAX_INPUT_SEQUENCES = 32767  # position packing limit (reference compress.rs:112-114)


def check_settings(assemblies_dir, autocycler_dir, k_size: int) -> None:
    """Flag validation (reference compress.rs:53-62)."""
    if not os.path.isdir(assemblies_dir):
        quit_with_error(f"directory does not exist: {assemblies_dir}")
    if os.path.exists(autocycler_dir) and not os.path.isdir(autocycler_dir):
        quit_with_error(f"{autocycler_dir} exists but is not a directory")
    if k_size < 11:
        quit_with_error("--kmer cannot be less than 11")
    if k_size > 501:
        quit_with_error("--kmer cannot be greater than 501")
    if k_size % 2 == 0:
        quit_with_error("--kmer must be odd")


def compress(assemblies_dir, autocycler_dir, k_size: int = 51,
             max_contigs: int = 25, use_jax=None, threads: int = 1) -> None:
    start_time = time.perf_counter()
    check_settings(assemblies_dir, autocycler_dir, k_size)
    check_threads(threads)
    log.section_header("Starting autocycler compress")
    log.explanation("This command finds all assemblies in the given input directory and "
                    "compresses them into a compacted De Bruijn graph. This graph can then "
                    "be used to recover the assemblies (with autocycler decompress) or "
                    "generate a consensus assembly (with autocycler resolve).")
    os.makedirs(autocycler_dir, exist_ok=True)
    from ..ops.distance import set_probe_cache_dir, start_background_probe
    set_probe_cache_dir(Path(autocycler_dir) / ".cache")
    # streamed k-mer grouping spills under <autocycler_dir>/.stream; sweep
    # orphans a killed run left behind before this run starts spilling
    from ..stream import prepare_stream_root
    prepare_stream_root(autocycler_dir)
    # No-op when cli.main() already started it; covers library callers that
    # enter compress() directly. Started after the cache dir is set so the
    # runner can adopt a persisted negative result without spawning jax.
    start_background_probe()
    metrics = InputAssemblyMetrics()
    with stage_timer("compress/load_and_repair"):
        sequences, assembly_count = load_sequences(
            assemblies_dir, k_size, metrics, max_contigs, threads,
            cache=open_cache(autocycler_dir))
    log.section_header("Building compacted unitig graph")
    log.explanation("K-mers are grouped with a sort-based device kernel, unitig chains "
                    "are assembled, and all non-branching paths are collapsed to form a "
                    "compacted De Bruijn graph, a.k.a. a unitig graph.")
    with stage_timer("compress/build_graph"), \
            Spinner("adding k-mers to graph..."):
        graph = build_unitig_graph(sequences, k_size, use_jax=use_jax,
                                   threads=threads)
    graph.print_basic_graph_info()

    log.section_header("Simplifying unitig graph")
    log.explanation("The graph structure is now simplified by moving sequence into repeat "
                    "unitigs when possible.")
    with stage_timer("compress/simplify"), Spinner("simplifying graph..."):
        simplify_structure(graph, sequences)
    graph.print_basic_graph_info()

    out_gfa = Path(autocycler_dir) / "input_assemblies.gfa"
    out_yaml = Path(autocycler_dir) / "input_assemblies.yaml"
    graph.save_gfa(out_gfa, sequences)
    _save_metrics(metrics, assembly_count, sequences, graph, out_yaml)
    qc.compress_qc(graph, sequences)
    ledger.record_stage("compress", outputs=[out_gfa, out_yaml])
    # registered crash point: artifacts are flushed but no caller-side
    # manifest has recorded the stage yet — a crash here re-runs compress
    # on resume, idempotently and byte-identically
    from ..utils.resilience import crash_point
    crash_point("post-stage", "compress")

    log.section_header("Finished!")
    log.explanation("You can now run autocycler cluster to group contigs based on their "
                    "similarity.")
    log.message(f"Compressed unitig graph: {out_gfa}")
    log.message(f"Input assembly stats:    {out_yaml}")
    log.message(f"Time to run: {format_duration(time.perf_counter() - start_time)}")
    log.message()


def load_sequences(assemblies_dir, k_size: int, metrics: InputAssemblyMetrics,
                   max_contigs: int, threads: int = 1,
                   cache: Optional[EncodeCache] = None
                   ) -> Tuple[List[Sequence], int]:
    """Load all contigs from all assemblies, skipping sub-k contigs and
    ignored headers, then repair dotted ends (reference compress.rs:98-133).

    Files load/parse/encode concurrently (one task per FASTA on the shared
    pool) and merge in deterministic file order, so sequence ids, log lines
    and metrics are identical to the serial walk at every thread count. A
    parse/repair cache (utils.cache) makes repeat runs skip both phases."""
    log.section_header("Loading input assemblies")
    log.explanation("Input assemblies are now loaded and each contig is given a unique ID.")
    assemblies = find_all_assemblies(assemblies_dir)
    ledger.record_inputs(assemblies)
    with substage("load"):
        per_file, file_hashes = _load_assembly_files(assemblies, k_size,
                                                     threads, cache)
    seq_id = 0
    sequences: List[Sequence] = []
    for assembly, records in zip(assemblies, per_file):
        details = InputAssemblyDetails(filename=str(assembly))
        filename = Path(assembly).name
        for contig_header, forward, reverse, length in records:
            seq_id += 1
            if seq_id > MAX_INPUT_SEQUENCES:
                quit_with_error(
                    f"no more than {MAX_INPUT_SEQUENCES} input sequences are allowed")
            sequence = Sequence(seq_id, forward, reverse, filename,
                                contig_header, length)
            log.message(f" {seq_id:>3}: {sequence}")
            details.contigs.append(InputContigDetails(
                name=sequence.contig_name(), description=sequence.contig_description(),
                length=sequence.length))
            if not sequence.is_ignored():
                sequences.append(sequence)
        metrics.input_assembly_details.append(details)
    log.message()
    check_sequence_count(sequences, len(assemblies), max_contigs)
    with Spinner("repairing sequence ends..."), substage("repair"):
        _repair_with_cache(sequences, k_size, threads, cache, file_hashes)
    n = seq_id
    log.message(f"{n} sequence{'' if n == 1 else 's'} loaded from {len(assemblies)} "
                f"assembl{'y' if len(assemblies) == 1 else 'ies'}")
    log.message()
    return sequences, len(assemblies)


def _load_assembly_files(assemblies, k_size: int, threads: int,
                         cache: Optional[EncodeCache]):
    """One load/parse/pad/revcomp task per FASTA file on the shared pool,
    merged in file order. Returns (per-file record lists, per-file content
    hashes). Each record is (contig_header, forward, reverse, length) for a
    contig of at least k bases — sub-k contigs are dropped here exactly as
    the serial walk dropped them.

    A worker failure with threads > 1 degrades VISIBLY to one serial retry:
    a bounded fault (e.g. a transient read error) must not corrupt ordering
    or kill the run, while a persistent error still propagates from the
    serial pass with its original message."""
    half_k = k_size // 2

    def load_one(assembly):
        file_hash = None
        if cache is not None:
            try:
                file_hash = content_hash(Path(assembly).read_bytes())
            except OSError:
                file_hash = None
            if file_hash is not None:
                hit = cache.load_parsed(file_hash, k_size)
                if hit is not None:
                    return [(header, fwd, reverse_complement_bytes(fwd), ln)
                            for header, fwd, ln in hit], file_hash
        filename = Path(assembly).name
        parsed = []
        for _, header, seq in load_fasta(assembly):
            if len(seq) < k_size:
                continue
            contig_header = " ".join(header.split())
            parsed.append((contig_header, padded_strand(seq, filename, half_k),
                           len(seq)))
        if cache is not None and file_hash is not None:
            cache.store_parsed(file_hash, k_size, parsed)
        return [(header, fwd, reverse_complement_bytes(fwd), ln)
                for header, fwd, ln in parsed], file_hash

    workers = min(max(1, int(threads)), len(assemblies))
    if workers > 1:
        try:
            results = pool_map(load_one, assemblies, workers)
        except Exception as e:  # noqa: BLE001 — fault isolation: degrade to
            # the serial walk rather than corrupt ordering or die on a
            # transient per-file failure; a persistent failure re-raises
            # below with its original message
            import sys
            record_degrade("assembly-load", "parallel", "serial",
                           f"{type(e).__name__}: {e}")
            print(f"autocycler: parallel assembly load failed "
                  f"({type(e).__name__}: {e}); retrying serially",
                  file=sys.stderr)
            results = [load_one(a) for a in assemblies]
    else:
        results = [load_one(a) for a in assemblies]
    return [r[0] for r in results], [r[1] for r in results]


def _repair_with_cache(sequences: List[Sequence], k_size: int, threads: int,
                       cache: Optional[EncodeCache], file_hashes) -> None:
    """Sequence-end repair with a warm-start cache: repair candidates are
    searched across ALL inputs, so the cache key is the hash over every
    file's content hash plus k, and only the repaired 2*(k-1) end bytes per
    sequence are stored — a hit patches the strands in place and skips the
    whole occurrence scan."""
    overlap = k_size - 1
    combined = None
    if (cache is not None and sequences and overlap > 0
            and all(h is not None for h in file_hashes)):
        combined = content_hash("|".join(file_hashes).encode())
        ends = cache.load_repair_ends(combined, k_size, len(sequences))
        if ends is not None:
            for i, s in enumerate(sequences):
                repaired = s.forward_seq          # fresh per run: own array
                repaired[:overlap] = ends[i, 0]
                repaired[len(repaired) - overlap:] = ends[i, 1]
                s.forward_seq = repaired          # setter invalidates codes
                s.reverse_seq = reverse_complement_bytes(repaired)
            return
    sequence_end_repair(sequences, k_size, threads)
    if combined is not None:
        ends = np.stack([
            np.stack([s.forward_seq[:overlap],
                      s.forward_seq[len(s.forward_seq) - overlap:]])
            for s in sequences])
        cache.store_repair_ends(combined, k_size, ends)


def check_sequence_count(sequences: List[Sequence], assembly_count: int,
                         max_contigs: int) -> None:
    """Reject empty or overly-fragmented inputs (reference compress.rs:84-95)."""
    if not sequences:
        quit_with_error("no sequences found in input assemblies")
    mean = len(sequences) / assembly_count
    if mean > max_contigs:
        quit_with_error(
            f"the mean number of contigs per input assembly ({mean:.1f}) exceeds the "
            f"allowed threshold ({max_contigs}). Are your input assemblies fragmented "
            "or contaminated?")


def _save_metrics(metrics: InputAssemblyMetrics, assembly_count: int,
                  sequences: List[Sequence], graph: UnitigGraph, out_yaml) -> None:
    metrics.input_assemblies_count = assembly_count
    metrics.input_assemblies_total_contigs = len(sequences)
    metrics.input_assemblies_total_length = sum(s.length for s in sequences)
    metrics.compressed_unitig_count = len(graph.unitigs)
    metrics.compressed_unitig_total_length = graph.total_length()
    metrics.save_to_yaml(out_yaml)
