"""`autocycler decompress`: lossless inverse of compress.

Parity target: reference decompress.rs:27-138 — walk each P-line path through
the unitig graph and emit the original FASTA(s), either into a directory
(same filenames, gzip preserved by extension) or into one combined file.
"""

from __future__ import annotations

import gzip
import os
from pathlib import Path

from ..models import UnitigGraph
from ..utils import log, quit_with_error, up_to_first_space


def decompress(in_gfa, out_dir=None, out_file=None) -> None:
    if not os.path.isfile(in_gfa):
        quit_with_error(f"file does not exist: {in_gfa}")
    if out_dir is None and out_file is None:
        quit_with_error("either --out_dir or --out_file is required")
    if out_dir is not None and os.path.exists(out_dir) and not os.path.isdir(out_dir):
        quit_with_error(f"{out_dir} exists but is not a directory")

    log.section_header("Starting autocycler decompress")
    log.explanation("This command will take a unitig graph (made by autocycler compress), "
                    "reconstruct the assemblies used to build that graph and save them in "
                    "the specified directory and/or file.")
    graph, sequences = UnitigGraph.from_gfa_file(in_gfa)
    graph.print_basic_graph_info()

    if out_dir is not None:
        os.makedirs(out_dir, exist_ok=True)
        save_original_seqs_to_dir(out_dir, graph, sequences)
    if out_file is not None:
        save_original_seqs_to_file(out_file, graph, sequences)


def save_original_seqs_to_dir(out_dir, graph: UnitigGraph, sequences) -> None:
    """One output file per input filename, gzipped when the name ends .gz
    (reference decompress.rs:84-117)."""
    original = graph.reconstruct_original_sequences(sequences)
    for filename in sorted(original):
        path = Path(out_dir) / filename
        opener = gzip.open if str(path).endswith(".gz") else open
        log.message(f"{path}:")
        with opener(path, "wt") as f:
            for header, seq in original[filename]:
                log.message(f"  {up_to_first_space(header)} ({len(seq)} bp)")
                f.write(f">{header}\n{seq}\n")
        log.message()


def save_original_seqs_to_file(out_file, graph: UnitigGraph, sequences) -> None:
    """All contigs in one file, headers prefixed with their source filename
    (reference decompress.rs:120-138)."""
    original = graph.reconstruct_original_sequences(sequences)
    log.message(f"{out_file}:")
    with open(out_file, "w") as f:
        for filename in sorted(original):
            clean = filename.replace(" ", "_")
            for header, seq in original[filename]:
                log.message(f"  {filename}__{up_to_first_space(header)} ({len(seq)} bp)")
                f.write(f">{clean}__{header}\n{seq}\n")
    log.message()
