"""`autocycler doctor`: device forensics for humans and machines.

Renders what the framework knows about the device path — environment
snapshot, last in-process probe state, the persisted negative-probe cache,
and the probe history (``probe_log.jsonl``) — plus a rule-driven list of
recommended actions. The default invocation initiates NO device bring-up:
it only reads state, so it is safe on a host whose transport is wedged
(the exact situation it exists to diagnose). ``--probe`` runs one live
subprocess probe; ``--watch`` runs the sentinel in the foreground.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

from ..obs import sentinel
from ..utils.knobs import knob_bool, knob_float


def negative_cache_state(run_dir: str = ".") -> dict:
    """The persisted negative-probe cache (``device_probe.json``) as doctor
    evidence: looks in ``run_dir`` and ``run_dir/.cache`` (where compress/
    batch put it). Reports freshness against the active TTL so the reader
    knows whether the cache is still suppressing probes."""
    ttl = float(knob_float("AUTOCYCLER_PROBE_NEG_TTL_S"))
    for cand in (Path(run_dir) / "device_probe.json",
                 Path(run_dir) / ".cache" / "device_probe.json"):
        try:
            entry = json.loads(cand.read_text())
        except (OSError, ValueError):
            continue
        age = time.time() - float(entry.get("at", 0) or 0)
        return {"present": True, "path": str(cand),
                "kind": entry.get("kind"), "reason": entry.get("reason"),
                "age_s": round(age, 1), "ttl_s": ttl,
                "fresh": bool(ttl > 0 and age < ttl)}
    return {"present": False, "ttl_s": ttl, "fresh": False}


def recommended_actions(probe_state: dict, neg_cache: dict, env: dict,
                        history: list) -> list:
    """Rule engine mapping the gathered evidence to next steps. Pure — unit
    tested directly; keep side-effect free."""
    actions = []
    kind = probe_state.get("kind")
    fresh_neg = neg_cache.get("fresh")
    last_real = next((e for e in reversed(history)
                      if "attached" in e and "type" not in e), None)
    if kind is None and last_real is not None:
        kind = last_real.get("kind")

    if kind == "timeout" or (fresh_neg and neg_cache.get("kind") == "timeout"):
        actions.append(
            "wedged transport: the probe never answered. Inspect "
            "`stderr_tail` in probe_log.jsonl for the PJRT/libtpu init "
            "chatter, then restart the device tunnel/plugin. Device paths "
            "are disabled until a probe succeeds.")
        if fresh_neg:
            actions.append(
                f"a fresh negative cache ({neg_cache.get('path')}, age "
                f"{neg_cache.get('age_s')}s / ttl {neg_cache.get('ttl_s')}s) "
                "is suppressing re-probes; delete it or set "
                "AUTOCYCLER_PROBE_NEG_TTL_S=0 to force an immediate retry.")
        actions.append(
            "set AUTOCYCLER_PROBE_WATCH=<seconds> so the sentinel re-probes "
            "in the background and auto-captures device evidence the moment "
            "the transport recovers.")
    elif kind == "error" or (fresh_neg and neg_cache.get("kind") == "error"):
        actions.append(
            "device init failed outright (kind=error): check the probe "
            "reason and `plugin_versions` above for a jax <-> TPU plugin "
            "mismatch, and `accel_devices` for missing /dev/accel* nodes.")
    elif kind == "pinned":
        actions.append(
            f"JAX_PLATFORMS={env.get('jax_platforms')!r} pins a non-TPU "
            "backend, so device paths are intentionally off; unset it to "
            "let the probe try the device.")
    elif kind == "no-tpu":
        if env.get("accel_devices"):
            actions.append(
                "jax initialised without a TPU backend although accelerator "
                "device files exist — check that the TPU PJRT plugin "
                "(plugin_versions above) is installed into THIS interpreter.")
        else:
            actions.append(
                "host-only machine (no TPU backend, no /dev/accel*): "
                "nothing to fix; host fallbacks are the expected path here.")
    elif kind == "ok":
        actions.append("device probe healthy — no action needed.")
    else:
        actions.append(
            "no probe has run in this process and no probe history was "
            "found; run `autocycler doctor --probe` for a live diagnosis "
            "(subprocess probe, killable, captures init stderr).")

    if not any("AUTOCYCLER_PROBE_WATCH" in a for a in actions) \
            and kind not in ("ok", "pinned") \
            and not env.get("env", {}).get("AUTOCYCLER_PROBE_WATCH"):
        actions.append(
            "tip: AUTOCYCLER_PROBE_WATCH=<seconds> keeps a background "
            "sentinel watching for device recovery during long runs.")
    return actions


def lint_state(run_dir: str = ".") -> dict:
    """Static-analysis posture: the committed lint baseline (when this is
    a source tree) and any ``lint_report.json`` artifact in the run dir.
    Never raises — doctor must work anywhere."""
    out: dict = {"baseline": None, "baselined": 0, "report": None}
    try:
        from .lint import repo_root
        baseline = repo_root() / "lint_baseline.json"
        if baseline.is_file():
            data = json.loads(baseline.read_text())
            out["baseline"] = str(baseline)
            out["baselined"] = len(data.get("findings") or [])
        report = Path(run_dir) / "lint_report.json"
        if report.is_file():
            data = json.loads(report.read_text())
            out["report"] = {
                "findings": len(data.get("findings") or []),
                "files": data.get("files"),
                "wall_s": data.get("wall_s"),
            }
    except Exception:
        pass
    return out


def gather(run_dir: str = ".") -> dict:
    """Everything doctor knows, as one dict (the ``--json`` payload)."""
    from ..ops.distance import device_probe_report, probe_overlap_report
    env = sentinel.environment_snapshot()
    probe_state = device_probe_report()
    async_probe = probe_overlap_report()
    neg_cache = negative_cache_state(run_dir)
    log_path = Path(run_dir) / sentinel.PROBE_LOG
    if not log_path.exists():
        fallback = sentinel.probe_log_path()
        log_path = fallback if fallback is not None else log_path
    history = sentinel.read_probe_log(log_path, limit=50)
    return {
        "env": env,
        "probe_state": probe_state,
        "async_probe": async_probe,
        "negative_cache": neg_cache,
        "probe_log": {"path": str(log_path), "entries": history},
        "lint": lint_state(run_dir),
        "actions": recommended_actions(probe_state, neg_cache, env, history),
    }


def _render_text(report: dict) -> None:
    env = report["env"]
    print("autocycler doctor")
    print("=================")
    print(f"python {env['python']} on {env['platform']}  "
          f"(cpus: {env['cpu_count']})")
    print(f"JAX_PLATFORMS: {env['jax_platforms']!r}")
    if env["plugin_versions"]:
        print("plugins: " + ", ".join(f"{k}=={v}" for k, v
                                      in env["plugin_versions"].items()))
    else:
        print("plugins: none (no jax/tpu/pjrt packages found)")
    print("accel devices: "
          + (", ".join(env["accel_devices"]) or "none"))
    if env["env"]:
        print("knobs: " + ", ".join(f"{k}={v}" for k, v
                                    in sorted(env["env"].items())))
    lint = report.get("lint") or {}
    if lint.get("baseline"):
        line = (f"lint: baseline present "
                f"({lint.get('baselined', 0)} accepted finding(s))")
        rep = lint.get("report")
        if isinstance(rep, dict):
            line += (f"; last report: {rep.get('findings')} new across "
                     f"{rep.get('files')} files")
        print(line)

    ps = report["probe_state"]
    print("\nlast in-process probe")
    print("---------------------")
    if ps.get("attached") is None:
        print("no probe has run in this process (doctor does not initiate "
              "device bring-up; use --probe)")
    else:
        print(f"attached={ps['attached']} kind={ps.get('kind')} "
              f"seconds={ps.get('seconds')} probes={ps.get('probes')}")
        print(f"reason: {ps.get('reason')}")

    ap = report.get("async_probe") or {}
    print("\nbackground (async) probe")
    print("------------------------")
    state = ap.get("state", "unstarted")
    if state == "unstarted":
        print("not started in this process (commands start it at launch so "
              "the attach overlaps host work)")
    else:
        print(f"state={state} kind={ap.get('kind')} "
              f"attempts={ap.get('attempts')} "
              f"deadline_s={ap.get('deadline_s')}")
        if ap.get("resolve_s") is not None:
            print(f"resolved in {ap['resolve_s']:.2f}s; callers blocked "
                  f"{ap.get('wait_s', 0.0):.2f}s "
                  f"(overlap saved {ap.get('overlap_saved_s', 0.0):.2f}s, "
                  f"{ap.get('pending_consults', 0)} pending consult(s) "
                  "answered host-path)")

    nc = report["negative_cache"]
    print("\nnegative cache")
    print("--------------")
    if nc.get("present"):
        state = "FRESH (suppressing probes)" if nc["fresh"] else "stale"
        print(f"{nc['path']}: kind={nc['kind']} age={nc['age_s']}s "
              f"ttl={nc['ttl_s']}s [{state}]")
        print(f"reason: {nc.get('reason')}")
    else:
        print("none persisted")

    entries = report["probe_log"]["entries"]
    print(f"\nprobe history ({report['probe_log']['path']})")
    print("-------------")
    if not entries:
        print("no probe log found")
    for e in entries[-10:]:
        if e.get("type"):
            print(f"  [{e.get('ts')}] {e['type']}: "
                  f"{e.get('note') or ''}".rstrip())
        else:
            print(f"  [{e.get('ts')}] {e.get('source')}: "
                  f"attached={e.get('attached')} kind={e.get('kind')} "
                  f"seconds={e.get('seconds')} — {e.get('reason')}")

    print("\nrecommended actions")
    print("-------------------")
    for i, action in enumerate(report["actions"], 1):
        print(f"{i}. {action}")


def doctor(run_dir: str = ".", as_json: bool = False, watch: bool = False,
           probe: bool = False, interval: float = None,
           cycles: int = None) -> int:
    """Entry point for the subcommand. ``probe`` runs ONE live subprocess
    probe (recorded to the probe log) before reporting; ``watch`` runs the
    sentinel loop in the foreground (``cycles`` bounds it, else Ctrl-C),
    with the recovery auto-capture hook armed."""
    sentinel.set_probe_log_dir(run_dir, fallback=True)
    if watch:
        if knob_bool("AUTOCYCLER_RECOVERY_CAPTURE"):
            sentinel.on_recovery(sentinel.recovery_capture)
        iv = interval if interval is not None else (
            sentinel.watch_interval() or 30.0)
        watcher = sentinel.ProbeWatcher(iv, source="doctor-watch")
        print(f"watching: probing every {iv:g}s "
              f"(deadline {watcher.deadline:g}s); Ctrl-C to stop",
              file=sys.stderr)
        try:
            while not watcher.stop_event.is_set():
                entry = watcher.cycle()
                print(json.dumps(entry, default=str), flush=True)
                if cycles is not None and watcher.cycles >= cycles:
                    break
                if watcher.stop_event.wait(iv):
                    break
        except KeyboardInterrupt:
            pass
        return 0
    if probe:
        outcome = sentinel.subprocess_probe(sentinel.probe_deadline())
        sentinel.record_outcome(outcome, source="doctor")
    report = gather(run_dir)
    if as_json:
        print(json.dumps(report, indent=2, default=str))
    else:
        _render_text(report)
    return 0
