"""`autocycler dotplot`: all-vs-all k-mer dotplot PNG.

Parity target: reference dotplot.rs — input may be an Autocycler GFA
(sequences reconstructed from paths), a FASTA file or a directory of
assemblies; layout constants, label auto-scaling with vertical left-side
text, lightgrey self-vs-self panels, mediumblue forward / firebrick reverse
dots, drawn in reverse-then-forward order so forward wins overlaps.

The k-mer matching is the sort-based grouping kernel from ops.kmers
(group_windows) instead of per-pair hash maps: all windows of A (forward and
reverse-complement) and B are grouped in one shot and matches join on group
id (SURVEY.md's "vmapped k-mer match grid" north star; ops/dotplot_pallas.py
holds the brute-force Pallas grid kernel used for benchmarking).
"""

from __future__ import annotations

import os
import sys
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models import UnitigGraph
from ..ops.encode import encode_both_strands
from ..ops.kmers import group_windows
from ..utils import (Spinner, find_all_assemblies, load_fasta, log,
                     quit_with_error)

# layout constants (reference dotplot.rs:28-41)
INITIAL_TOP_LEFT_GAP = 0.1
BORDER_GAP = 0.015
BETWEEN_SEQ_GAP = 0.01
TOTAL_BETWEEN_SEQ_GAP = 0.1
TEXT_GAP = 0.0025
MAX_FONT_SIZE = 0.025
BACKGROUND = (255, 255, 255)
SELF_VS_SELF = (211, 211, 211)
SELF_VS_OTHER = (245, 245, 245)
TEXT_COLOUR = (0, 0, 0)
OUTLINE = (0, 0, 0)
FORWARD_DOT = (0, 0, 205)
REVERSE_DOT = (178, 34, 34)


def dotplot(input_path, out_png, res: int = 2000, kmer: int = 32,
            grid_mode: str = "auto") -> None:
    if res < 500:
        quit_with_error("--res cannot be less than 500")
    if res > 10000:
        quit_with_error("--res cannot be greater than 10000")
    if kmer < 10:
        quit_with_error("--kmer cannot be less than 10")
    if kmer > 100:
        quit_with_error("--kmer cannot be greater than 100")
    if grid_mode not in ("auto", "host", "device"):
        quit_with_error("--grid-mode must be auto, host or device")
    log.section_header("Starting autocycler dotplot")
    log.explanation("This command will take a unitig graph (either before or after "
                    "trimming) and generate a dotplot image containing all pairwise "
                    "comparisons of the sequences.")
    seqs = load_dotplot_sequences(input_path)
    with Spinner("creating dotplot..."):
        create_dotplot(seqs, out_png, res, kmer, grid_mode)
    log.section_header("Finished!")
    log.message(f"Pairwise dotplots: {out_png}")
    log.message()


def load_dotplot_sequences(input_path) -> List[Tuple[Tuple[str, str], np.ndarray]]:
    """((filename, seqname), bytes) records from GFA / FASTA / directory
    (reference dotplot.rs:107-175)."""
    input_path = Path(input_path)
    records: List[Tuple[Tuple[str, str], np.ndarray]] = []
    if input_path.is_dir():
        for assembly in find_all_assemblies(input_path):
            for name, _header, seq in load_fasta(assembly):
                records.append(((assembly.name, name),
                                np.frombuffer(seq.encode(), dtype=np.uint8)))
        if not records:
            quit_with_error("no sequences were loaded")
        return records
    if not input_path.is_file():
        quit_with_error("--input is neither a file nor a directory")
    with open(input_path, "rb") as f:
        first_char = f.read(1).decode(errors="replace")
    if first_char == ">":
        for name, _header, seq in load_fasta(input_path):
            records.append(((input_path.name, name),
                            np.frombuffer(seq.encode(), dtype=np.uint8)))
    elif first_char in ("H", "S"):
        graph, sequences = UnitigGraph.from_gfa_file(input_path)
        reconstructed = graph.reconstruct_original_sequences(sequences)
        flat = []
        for filename, pairs in reconstructed.items():
            for header, seq in pairs:
                flat.append(((filename, header.split()[0]),
                             np.frombuffer(seq.encode(), dtype=np.uint8)))
        flat.sort(key=lambda r: r[0])
        records = flat
    else:
        quit_with_error("--input is neither GFA or FASTA")
    if not records:
        quit_with_error("no sequences were loaded")
    return records


def _between_seq_gap(gap: float, max_total_gap: float, seq_count: int) -> float:
    if seq_count <= 1:
        return gap
    if (seq_count - 1) * gap > max_total_gap:
        return max_total_gap / (seq_count - 1)
    return gap


def get_positions(seqs, res: int, kmer: int, top_left_gap: int, bottom_right_gap: int,
                  between_seq_gap: int):
    """Image start/end coordinate per sequence plus bp-per-pixel scale
    (reference dotplot.rs:224-267)."""
    names = [name for name, _ in seqs]
    seq_lengths = {name: max(0, len(seq) - kmer + 1) for name, seq in seqs}
    all_gaps = top_left_gap + bottom_right_gap + between_seq_gap * (len(seqs) - 1)
    pixels_for_sequence = max(0, res - all_gaps)
    if all_gaps > pixels_for_sequence and len(seqs) > 1:
        between_seq_gap = (res // 2 - top_left_gap - bottom_right_gap) // (len(seqs) - 1)
        all_gaps = top_left_gap + bottom_right_gap + between_seq_gap * (len(seqs) - 1)
        pixels_for_sequence = max(0, res - all_gaps)
    total = sum(seq_lengths.values())
    bp_per_pixel = total / pixels_for_sequence
    start_positions: Dict = {}
    end_positions: Dict = {}
    pos = top_left_gap
    for name in names:
        start_positions[name] = pos
        pos += round(seq_lengths[name] / bp_per_pixel)
        end_positions[name] = pos
        pos += between_seq_gap
    return start_positions, end_positions, bp_per_pixel


# Device-grid dispatch threshold for --grid-mode auto: the Pallas match grid
# is O(nA*nB) while the host sort-join is near-linear, so on measurement the
# host path wins at every size through the current remote-execution tunnel
# (see docs/architecture.md "dotplot grid" table). auto therefore behaves
# like host; the device path stays available via --grid-mode device and is
# pixel-exact (coarse device tiles + exact per-tile refinement).
DEVICE_GRID_MIN_CELLS = None


# Above this many grid cells the count grid no longer fits device memory;
# pairs beyond it always use the host sort-join, which is near-linear
# anyway.
MAX_DEVICE_CELLS = 5e11


def _device_match_pair(a_words: np.ndarray, b_words: np.ndarray, tile: int = 2048
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Exact (i, j) match positions via the Pallas coarse count grid: run
    the device kernel for tile-level counts, then refine only NONZERO tiles
    — also on device (ops.dotplot_pallas.match_tile_bits returns packed
    equality bitmasks; matches are sparse diagonals, so few tiles refine).
    The host only unpacks set bits and drops tile-padding cells (an all-T
    word equals the A-pad sentinel, so edge-tile pad bits can be spurious —
    the count kernel masks them by global index; here the bound filter does
    the same)."""
    from ..ops.dotplot_pallas import match_grid, match_tile_bits, unpack_tile_bits
    from ..utils.timing import device_dispatch

    n_a = a_words.shape[1]
    n_b = b_words.shape[1]
    with device_dispatch("dotplot match grid"):
        tiles = np.asarray(match_grid(a_words, b_words, tile_a=tile, tile_b=tile))
    pairs = np.argwhere(tiles > 0)
    if not len(pairs):
        z = np.zeros(0, np.int64)
        return z, z
    with device_dispatch("dotplot tile refinement"):
        packed = match_tile_bits(a_words, b_words, [tuple(p) for p in pairs],
                                 tile_a=tile, tile_b=tile)
    iis: List[np.ndarray] = []
    jjs: List[np.ndarray] = []
    for (ti, tj), bits in zip(pairs, packed):
        ii, jj = np.nonzero(unpack_tile_bits(bits))
        ii = ii.astype(np.int64) + ti * tile
        jj = jj.astype(np.int64) + tj * tile
        keep = (ii < n_a) & (jj < n_b)
        iis.append(ii[keep])
        jjs.append(jj[keep])
    return np.concatenate(iis), np.concatenate(jjs)


def kmer_match_positions_device(seq_a: np.ndarray, seq_b: np.ndarray,
                                kmer: int, enc_a=None, enc_b=None
                                ) -> Optional[Tuple[np.ndarray, np.ndarray,
                                                    np.ndarray, np.ndarray]]:
    """Device-grid variant of :func:`kmer_match_positions` (same contract and
    identical results). Returns None when inputs contain non-ACGT bytes —
    the 2-bit device packing cannot represent them, so the caller falls back
    to the host sort-join. ``enc_a``/``enc_b`` are optional precomputed
    (forward codes, revcomp codes) pairs from encode_both_strands, so
    create_dotplot's N^2 pair loop encodes each sequence once, not per
    pair."""
    from ..ops.dotplot_pallas import pack_2bit_words

    n_a = len(seq_a) - kmer + 1
    n_b = len(seq_b) - kmer + 1
    if n_a <= 0 or n_b <= 0:
        z = np.zeros(0, np.int64)
        return z, z, z, z
    if float(n_a) * float(n_b) > MAX_DEVICE_CELLS:
        return None
    from ..ops.distance import jax_backend_safe, warn_backend_unsafe_once
    if not jax_backend_safe():
        # the installed TPU plugin overrides JAX_PLATFORMS, so when its
        # transport is wedged even an "interpret-mode" grid would hang in
        # backend init; the probe's deadline already ran — fall back to the
        # host sort-join loudly (once per process, with the probe's actual
        # reason) instead of blocking the CLI forever
        warn_backend_unsafe_once("device grid mode")
        return None
    codes_a, codes_rc = enc_a if enc_a is not None \
        else encode_both_strands(seq_a)
    codes_b = (enc_b if enc_b is not None
               else encode_both_strands(seq_b))[0]
    if (codes_a == 0).any() or (codes_b == 0).any():
        return None
    wa = pack_2bit_words(codes_a, kmer)
    wrc = pack_2bit_words(codes_rc, kmer)
    wb = pack_2bit_words(codes_b, kmer)
    fwd_i, fwd_j = _device_match_pair(wa, wb)
    rc_i, rev_j = _device_match_pair(wrc, wb)
    rev_i = n_a - 1 - rc_i  # reference's reverse mapping (dotplot.rs:433-450)
    return fwd_i, fwd_j, rev_i, rev_j


def kmer_match_positions(seq_a: np.ndarray, seq_b: np.ndarray, kmer: int,
                         enc_a=None, enc_b=None
                         ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """All (i, j) k-mer matches of A-forward vs B and A-reverse vs B, with
    A-reverse positions mapped like the reference (n_a - i - 1,
    dotplot.rs:433-450). Returns (fwd_i, fwd_j, rev_i, rev_j).
    ``enc_a``/``enc_b`` are optional precomputed encode_both_strands pairs;
    A's reverse strand comes from the arithmetic code-space reverse
    complement, not a reverse_complement_bytes + re-encode round trip."""
    n_a = len(seq_a) - kmer + 1
    n_b = len(seq_b) - kmer + 1
    if n_a <= 0 or n_b <= 0:
        z = np.zeros(0, np.int64)
        return z, z, z, z
    if enc_a is None:
        enc_a = encode_both_strands(seq_a)
    if enc_b is None:
        enc_b = encode_both_strands(seq_b)
    codes = np.concatenate([enc_a[0], enc_a[1], enc_b[0]])
    starts = np.concatenate([
        np.arange(n_a, dtype=np.int64),
        len(seq_a) + np.arange(n_a, dtype=np.int64),
        2 * len(seq_a) + np.arange(n_b, dtype=np.int64)])
    order, gid_sorted = group_windows(codes, starts, kmer)
    gid = np.empty(len(starts), np.int64)
    gid[order] = gid_sorted
    G = int(gid_sorted[-1]) + 1 if len(starts) else 0

    a_fwd_gid = gid[:n_a]
    a_rev_gid = gid[n_a:2 * n_a]
    b_gid = gid[2 * n_a:]

    def join(a_gid, a_pos):
        order_a = np.argsort(a_gid, kind="stable")
        sorted_gid = a_gid[order_a]
        starts_in_a = np.searchsorted(sorted_gid, b_gid, side="left")
        ends_in_a = np.searchsorted(sorted_gid, b_gid, side="right")
        counts = ends_in_a - starts_in_a
        j = np.repeat(np.arange(n_b, dtype=np.int64), counts)
        take = np.concatenate([np.arange(s, e) for s, e in zip(starts_in_a, ends_in_a)
                               if e > s]) if counts.sum() else np.zeros(0, np.int64)
        i = a_pos[order_a][take]
        return i, j

    fwd_i, fwd_j = join(a_fwd_gid, np.arange(n_a, dtype=np.int64))
    rev_i, rev_j = join(a_rev_gid, n_a - 1 - np.arange(n_a, dtype=np.int64))
    return fwd_i, fwd_j, rev_i, rev_j


def create_dotplot(seqs, png_filename, res: int, kmer: int,
                   grid_mode: str = "auto") -> None:
    from PIL import Image, ImageDraw

    log.section_header("Creating dotplot")
    log.explanation("K-mers common between sequences are now used to build the dotplot "
                    "image.")
    rf = float(res)
    top_left_gap = round(INITIAL_TOP_LEFT_GAP * rf)
    border_gap = max(2, round(BORDER_GAP * rf))
    between_gap = max(2, round(_between_seq_gap(BETWEEN_SEQ_GAP, TOTAL_BETWEEN_SEQ_GAP,
                                               len(seqs)) * rf))
    text_gap = max(1, round(TEXT_GAP * rf))
    max_font_size = max(1, round(MAX_FONT_SIZE * rf))

    font_path = _find_font()
    start_positions, end_positions, _ = get_positions(
        seqs, res, kmer, top_left_gap, border_gap, between_gap)
    text_height = _reduce_scale(seqs, start_positions, end_positions, font_path,
                                max_font_size)
    top_left_gap = int(2 * text_height) + border_gap
    start_positions, end_positions, bp_per_pixel = get_positions(
        seqs, res, kmer, top_left_gap, border_gap, between_gap)

    img = Image.new("RGB", (res, res), BACKGROUND)
    draw = ImageDraw.Draw(img)
    _draw_sequence_boxes(draw, seqs, start_positions, end_positions, fill=True)
    _draw_labels(img, seqs, start_positions, end_positions, text_gap, font_path,
                 text_height)

    arr = np.array(img)
    count = 0
    # one both-strand encoding per sequence, shared by every pair in the
    # N^2 loop (each sequence previously re-encoded — forward AND a byte
    # revcomp round trip — once per pair)
    encs = [encode_both_strands(seq) for _, seq in seqs]
    for (name_a, seq_a), enc_a in zip(seqs, encs):
        for (name_b, seq_b), enc_b in zip(seqs, encs):
            use_device = grid_mode == "device" or (
                grid_mode == "auto" and DEVICE_GRID_MIN_CELLS is not None and
                max(0, len(seq_a) - kmer + 1) * max(0, len(seq_b) - kmer + 1)
                >= DEVICE_GRID_MIN_CELLS)
            matches = kmer_match_positions_device(seq_a, seq_b, kmer,
                                                  enc_a, enc_b) \
                if use_device else None
            if matches is None:
                matches = kmer_match_positions(seq_a, seq_b, kmer,
                                               enc_a, enc_b)
            fwd_i, fwd_j, rev_i, rev_j = matches
            a0, b0 = start_positions[name_a], start_positions[name_b]
            # reverse dots first so forward dots win overlaps, like the
            # reference's draw order (dotplot.rs:394-423)
            for ii, jj, colour in ((rev_i, rev_j, REVERSE_DOT),
                                   (fwd_i, fwd_j, FORWARD_DOT)):
                if not len(ii):
                    continue
                px = np.round(ii / bp_per_pixel).astype(np.int64) + a0
                py = np.round(jj / bp_per_pixel).astype(np.int64) + b0
                ok = (px >= 0) & (px < res) & (py >= 0) & (py < res)
                arr[py[ok], px[ok]] = colour
            count += 1
    img = Image.fromarray(arr)
    draw = ImageDraw.Draw(img)
    _draw_sequence_boxes(draw, seqs, start_positions, end_positions, fill=False)
    img.save(png_filename)
    log.message(f"{count} pairwise dotplot{'' if count == 1 else 's'} drawn to image")
    log.message()


def _find_font():
    """Scalable label font, checked in order (reference dotplot.rs:26
    embeds DejaVuSans; this package bundles the same free font so labels
    always scale):
    1. AUTOCYCLER_DOTPLOT_FONT (any .ttf/.otf path),
    2. the bundled DejaVuSans (autocycler_tpu/assets/),
    3. matplotlib's bundled DejaVuSans,
    4. standard fontconfig directories (DejaVu/Liberation/Noto/FreeSans),
    5. `fc-match` if fontconfig's CLI is available.
    Falls back to PIL's bitmap font with a stderr note (labels then cannot
    scale)."""
    from ..utils.knobs import knob_str
    override = knob_str("AUTOCYCLER_DOTPLOT_FONT")
    if override:
        if Path(override).is_file():
            return override
        print(f"autocycler: AUTOCYCLER_DOTPLOT_FONT={override} not found; "
              "continuing with discovery", file=sys.stderr)
    bundled = Path(__file__).resolve().parent.parent / "assets" / "DejaVuSans.ttf"
    if bundled.is_file():
        return str(bundled)
    try:
        import matplotlib
        path = Path(matplotlib.get_data_path()) / "fonts" / "ttf" / "DejaVuSans.ttf"
        if path.is_file():
            return str(path)
    except Exception:
        pass
    for candidate in (
            "/usr/share/fonts/truetype/dejavu/DejaVuSans.ttf",
            "/usr/share/fonts/dejavu/DejaVuSans.ttf",
            "/usr/share/fonts/TTF/DejaVuSans.ttf",
            "/usr/share/fonts/truetype/liberation/LiberationSans-Regular.ttf",
            "/usr/share/fonts/truetype/noto/NotoSans-Regular.ttf",
            "/usr/share/fonts/truetype/freefont/FreeSans.ttf",
            "/System/Library/Fonts/Helvetica.ttc",
            "C:/Windows/Fonts/arial.ttf"):
        if Path(candidate).is_file():
            return candidate
    try:
        import subprocess
        out = subprocess.run(["fc-match", "-f", "%{file}", "sans"],
                             capture_output=True, text=True, timeout=10)
        path = out.stdout.strip()
        if out.returncode == 0 and path and Path(path).is_file():
            return path
    except Exception:
        pass
    print("autocycler: no scalable font found — dotplot labels will use "
          "PIL's fixed-size bitmap font (set AUTOCYCLER_DOTPLOT_FONT to fix)",
          file=sys.stderr)
    return None


def _text_width(text: str, font_path, size: float) -> float:
    from PIL import ImageFont
    if font_path is None or size < 1:
        return len(text) * size * 0.6
    font = ImageFont.truetype(font_path, max(1, int(size)))
    return font.getlength(text)


def _reduce_scale(seqs, start_positions, end_positions, font_path,
                  max_font_size: int) -> float:
    """Shrink the font until every label fits its panel width
    (reference dotplot.rs:308-328)."""
    text_height = float(max_font_size)
    for (filename, seqname), _ in seqs:
        name = (filename, seqname)
        available = float(end_positions[name] - start_positions[name])
        width = max(_text_width(filename, font_path, text_height),
                    _text_width(seqname, font_path, text_height))
        if width > available and width > 0:
            text_height *= available / width
    return text_height


def _draw_sequence_boxes(draw, seqs, start_positions, end_positions, fill: bool) -> None:
    for name_a, _ in seqs:
        sa, ea = start_positions[name_a] - 1, end_positions[name_a] + 1
        for name_b, _ in seqs:
            sb, eb = start_positions[name_b] - 1, end_positions[name_b] + 1
            if fill:
                colour = SELF_VS_SELF if name_a == name_b else SELF_VS_OTHER
                draw.rectangle([sa, sb, ea, eb], fill=colour, outline=OUTLINE)
            else:
                draw.rectangle([sa, sb, ea, eb], outline=OUTLINE)


def _draw_labels(img, seqs, start_positions, end_positions, text_gap: int, font_path,
                 text_height: float) -> None:
    from PIL import Image, ImageDraw, ImageFont
    if font_path is None or text_height < 1:
        return
    font = ImageFont.truetype(font_path, max(1, int(text_height)))
    draw = ImageDraw.Draw(img)
    min_pos = min(start_positions.values())
    h = int(text_height)
    for (filename, seqname), _ in seqs:
        name = (filename, seqname)
        start, end = start_positions[name], end_positions[name]
        pos_1 = min_pos - text_gap - h
        pos_2 = pos_1 - h
        draw.text((start, pos_1), seqname, fill=TEXT_COLOUR, font=font)
        draw.text((start, pos_2), filename, fill=TEXT_COLOUR, font=font)
        # vertical labels on the left side, rotated 90° counterclockwise
        for text, x in ((seqname, pos_1), (filename, pos_2)):
            w = int(_text_width(text, font_path, text_height)) + 1
            tmp = Image.new("RGB", (w, h + 2), BACKGROUND)
            ImageDraw.Draw(tmp).text((0, 0), text, fill=TEXT_COLOUR, font=font)
            rotated = tmp.rotate(90, expand=True)
            mask = Image.eval(rotated.convert("L"), lambda v: 255 if v < 250 else 0)
            img.paste(rotated, (x, end - rotated.height), mask)
