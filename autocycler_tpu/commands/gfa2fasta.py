"""`autocycler gfa2fasta`: GFA -> FASTA with topology annotations.

Parity target: reference gfa2fasta.rs — per-unitig headers carry
``length=`` plus ``circular=true topology=circular`` /
``circular=false topology=linear`` derived from the link structure.
"""

from __future__ import annotations

import os

from ..models import UnitigGraph
from ..utils import log, quit_with_error
from .combine import unitig_topology_suffix


def save_graph_to_fasta(graph: UnitigGraph, out_fasta) -> None:
    circ = linear = other = 0
    with open(out_fasta, "w") as f:
        for unitig in graph.unitigs:
            seq = unitig.seq_str()
            if not seq:
                continue
            topology = unitig_topology_suffix(unitig)
            if "circular=true" in topology:
                circ += 1
            elif "circular=false" in topology:
                linear += 1
            else:
                other += 1
            f.write(f">{unitig.number} length={unitig.length()}{topology}\n{seq}\n")
    log.message(f"{circ} circular sequence{'' if circ == 1 else 's'}")
    log.message(f"{linear} linear sequence{'' if linear == 1 else 's'}")
    log.message(f"{other} other sequence{'' if other == 1 else 's'}")
    log.message()


def gfa2fasta(in_gfa, out_fasta) -> None:
    if not os.path.isfile(in_gfa):
        quit_with_error(f"file does not exist: {in_gfa}")
    log.section_header("Starting autocycler gfa2fasta")
    log.explanation("This command loads an Autocycler graph and saves it as a FASTA file "
                    "with topological information in the sequence headers.")
    graph, _ = UnitigGraph.from_gfa_file(in_gfa)
    graph.print_basic_graph_info()
    save_graph_to_fasta(graph, out_fasta)
