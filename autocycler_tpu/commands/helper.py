"""`autocycler helper`: uniform wrappers around 14 long-read assembler
toolchains.

Parity target: reference helper.rs — tasks genome_size (via Raven) plus 13
assembler pipelines (Canu, Flye, Hifiasm, Ilesta+Minipolish, LJA, metaMDBG,
miniasm+minimap2+Minipolish, Myloasm, NECAT, NextDenovo+NextPolish,
Plassembler, Raven, Redbean/wtdbg2). Outputs are normalised to
``prefix.fasta`` (plus ``.gfa``/``.log`` where available) with depth and
circularity stamped into headers; a depth filter (--min_depth_abs /
--min_depth_rel) can drop low-coverage contigs; subprocess failures are
reported but not fatal — the consensus design tolerates individual assembler
failures (reference helper.rs:645-654).
"""

from __future__ import annotations

import os
import random
import re
import shutil
import signal
import sys
import tempfile
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple

from ..utils import is_fasta_empty, load_fasta, log, quit_with_error, total_fasta_length
from ..utils import resilience
from ..utils.resilience import SubprocessError
from .subsample import parse_genome_size

READ_TYPES = ("ont_r9", "ont_r10", "pacbio_clr", "pacbio_hifi")


# ---------------- subprocess plumbing ----------------

def check_requirements(programs: List[str]) -> None:
    for cmd in programs:
        if shutil.which(cmd) is None:
            quit_with_error(f"required program '{cmd}' not found in $PATH")


def run_command(cmd: List[str], stdout_file=None, cwd=None, timeout=None,
                retries=None) -> None:
    """Run a subprocess; failure is printed but NOT fatal
    (reference helper.rs:645-654).

    Execution goes through the hardened resilience runner
    (utils.resilience.run_command): per-command timeout and bounded
    retries with backoff (``--timeout``/``--retries`` flags or the
    AUTOCYCLER_SUBPROCESS_* env vars), stderr tails captured into the
    logged :class:`SubprocessError`, and partial stdout files removed on
    failure so `copy_output_file` never mistakes them for real output."""
    log.message()
    log.message(" ".join(f'"{c}"' if " " in str(c) else str(c) for c in cmd))
    log.message()
    try:
        resilience.run_command(cmd, stdout_file=stdout_file, cwd=cwd,
                               timeout=timeout, retries=retries)
    except SubprocessError as e:
        log.message(str(e))
    except FileNotFoundError as e:
        quit_with_error(f"failed to launch {cmd[0]}: {e}")


def add_extension(prefix, extension: str) -> Path:
    return Path(str(prefix) + "." + extension)


def copy_output_file(src, dest) -> None:
    src, dest = Path(src), Path(dest)
    if not src.exists() or src.stat().st_size == 0:
        if Path(dest).exists():
            os.remove(dest)
        return
    shutil.copy(src, dest)


def copy_fasta(src, dest) -> None:
    """Copy a (possibly gzipped, possibly wrapped) FASTA to an uncompressed
    one-line-per-sequence FASTA (reference helper.rs:622-631)."""
    src = Path(src)
    if not src.exists() or is_fasta_empty(src):
        if Path(dest).exists():
            os.remove(dest)
        return
    with open(dest, "w") as f:
        for _, header, seq in load_fasta(src):
            f.write(f">{header}\n{seq}\n")


# ---------------- output normalisation ----------------

def gfa_to_fasta(gfa, fasta) -> None:
    """GFA S-lines -> FASTA with circularity (name ending 'c') and depth
    (dp:f: / rd:i: tags) in headers (reference helper.rs:682-698)."""
    gfa = Path(gfa)
    if not gfa.exists() or gfa.stat().st_size == 0:
        return
    with open(gfa) as r, open(fasta, "w") as w:
        for line in r:
            if not line.startswith("S"):
                continue
            cols = line.rstrip("\n").split("\t")
            name = cols[1] if len(cols) > 1 else ""
            seq = cols[2] if len(cols) > 2 else ""
            depth = None
            for field in cols[3:]:
                if field.startswith("dp:f:"):
                    depth = field[5:]
                    break
            if depth is None:
                for field in cols[3:]:
                    if field.startswith("rd:i:"):
                        depth = field[5:]
                        break
            header = f">{name}"
            if name.endswith("c"):
                header += " circular=true"
            if depth is not None:
                header += f" depth={depth}"
            w.write(f"{header}\n{seq}\n")


def load_flye_assembly_info(assembly_info) -> Dict[str, Tuple[bool, str]]:
    info: Dict[str, Tuple[bool, str]] = {}
    with open(assembly_info) as f:
        for line in f:
            if line.startswith("#") or not line.strip():
                continue
            cols = line.rstrip("\n").split("\t")
            if len(cols) < 4:
                continue
            info[cols[0]] = (cols[3] == "Y", cols[2])
    return info


def copy_flye_fasta(src, assembly_info, dest) -> None:
    """Stamp Flye's circularity/depth info into the FASTA headers
    (reference helper.rs:701-715)."""
    src = Path(src)
    if not src.exists() or is_fasta_empty(src):
        return
    info = load_flye_assembly_info(assembly_info)
    with open(dest, "w") as f:
        for name, _, seq in load_fasta(src):
            header = name
            if name in info:
                circ, depth = info[name]
                if circ:
                    header += " circular=true"
                header += f" depth={depth}"
            f.write(f">{header}\n{seq}\n")


def load_canu_assembly_depth(assembly_info) -> Dict[str, str]:
    info: Dict[str, str] = {}
    with open(assembly_info) as f:
        for line in f:
            if line.startswith("#") or not line.strip():
                continue
            cols = line.rstrip("\n").split("\t")
            if len(cols) < 3:
                continue
            try:
                tig_id = int(cols[0])
            except ValueError:
                continue
            info[f"tig{tig_id:08d}"] = cols[2]
    return info


def trim_canu_contig(header: str, seq: str) -> Tuple[str, str]:
    """Trim the overlap off circular Canu contigs using the trim=start-end
    header hint (reference helper.rs:756-774)."""
    if "suggestCircular=yes" not in header:
        return header, seq
    m = re.search(r"trim=(\d+)-(\d+)", header)
    if m:
        start, end = int(m.group(1)), int(m.group(2))
        if start < end and end <= len(seq):
            seq = seq[start:end]
            header = re.sub(r"trim=\d+-\d+", f"trim=0-{len(seq)}", header)
            header = re.sub(r"len=\d+", f"len={len(seq)}", header)
    return header, seq


def copy_canu_fasta(src, assembly_info, dest) -> None:
    """Copy Canu output: drop repeat/bubble contigs, trim circular overlaps,
    stamp depth (reference helper.rs:733-753)."""
    src = Path(src)
    if not src.exists() or is_fasta_empty(src):
        return
    depth = load_canu_assembly_depth(assembly_info)
    with open(dest, "w") as f:
        for name, header, seq in load_fasta(src):
            if "suggestRepeat=yes" in header or "suggestBubble=yes" in header:
                continue
            header, seq = trim_canu_contig(header, seq)
            if name in depth:
                header += f" depth={depth[name]}"
            f.write(f">{header}\n{seq}\n")


def rotate_plassembler_contigs(src, dest, seed: int = 0) -> None:
    """Randomly (seeded) rotate circular plasmids so their start points vary
    between subsamples (reference helper.rs:904-917)."""
    src = Path(src)
    if not src.exists() or is_fasta_empty(src):
        return
    rng = random.Random(seed)
    with open(dest, "w") as f:
        for _, header, seq in load_fasta(src):
            if "circular=true" in header.lower() and len(seq) > 1:
                r = rng.randrange(1, len(seq))
                seq = seq[r:] + seq[:r]
            f.write(f">{header}\n{seq}\n")


def replace_underscores_with_spaces(filename) -> None:
    filename = Path(filename)
    if not filename.exists() or filename.stat().st_size == 0:
        return
    text = filename.read_text().replace("_", " ")
    filename.write_text(text)


def depth_from_header(header: str) -> Optional[float]:
    """Extract depth=/depth-/coverage= from a contig header
    (reference helper.rs:984-993)."""
    for marker in ("depth=", "depth-", "coverage="):
        i = header.find(marker)
        if i >= 0:
            token = re.split(r"[-_ ]", header[i + len(marker):])[0]
            try:
                return float(token)
            except ValueError:
                return None
    return None


def depth_filter(out_prefix, min_depth_abs: Optional[float],
                 min_depth_rel: Optional[float]) -> None:
    """Drop contigs below the depth threshold; a missing depth on any contig
    disables filtering (reference helper.rs:932-974)."""
    if min_depth_abs is None and min_depth_rel is None:
        return
    fasta = add_extension(out_prefix, "fasta")
    if not fasta.exists() or is_fasta_empty(fasta):
        return
    records = []
    longest_len, longest_depth = 0, 0.0
    for name, header, seq in load_fasta(fasta):
        depth = depth_from_header(header)
        if depth is None:
            return
        if len(seq) > longest_len:
            longest_len, longest_depth = len(seq), depth
        records.append((name, header, seq, depth))
    threshold = min_depth_abs or 0.0
    if min_depth_rel is not None:
        threshold = max(threshold, min_depth_rel * longest_depth)
    log.message(f"Autocycler helper depth filter: threshold = {threshold:.3f}")
    kept = []
    for name, header, seq, depth in records:
        passed = depth >= threshold
        log.message(f"{name}: depth={depth:.3f}, {'PASS' if passed else 'FAIL'}")
        if passed:
            kept.append((header, seq))
    if not kept:
        os.remove(fasta)
        return
    with open(fasta, "w") as f:
        for header, seq in kept:
            f.write(f">{header}\n{seq}\n")


def delete_fasta_if_empty(out_prefix) -> None:
    fasta = add_extension(out_prefix, "fasta")
    if fasta.exists() and is_fasta_empty(fasta):
        os.remove(fasta)


# ---------------- config-file generation ----------------

def make_necat_files(reads, directory, genome_size: int, threads: int) -> None:
    """NECAT read list + config (reference helper.rs:790-825)."""
    directory = Path(directory)
    (directory / "read_list.txt").write_text(f"{Path(reads).resolve()}\n")
    (directory / "config.txt").write_text("\n".join([
        "PROJECT=necat",
        "ONT_READ_LIST=read_list.txt",
        f"GENOME_SIZE={genome_size}",
        f"THREADS={threads}",
        "MIN_READ_LENGTH=3000",
        "PREP_OUTPUT_COVERAGE=40",
        "OVLP_FAST_OPTIONS=-n 500 -z 20 -b 2000 -e 0.5 -j 0 -u 1 -a 1000",
        "OVLP_SENSITIVE_OPTIONS=-n 500 -z 10 -e 0.5 -j 0 -u 1 -a 1000",
        "CNS_FAST_OPTIONS=-a 2000 -x 4 -y 12 -l 1000 -e 0.5 -p 0.8 -u 0",
        "CNS_SENSITIVE_OPTIONS=-a 2000 -x 4 -y 12 -l 1000 -e 0.5 -p 0.8 -u 0",
        "TRIM_OVLP_OPTIONS=-n 100 -z 10 -b 2000 -e 0.5 -j 1 -u 1 -a 400",
        "ASM_OVLP_OPTIONS=-n 100 -z 10 -b 2000 -e 0.5 -j 1 -u 0 -a 400",
        "NUM_ITER=2",
        "CNS_OUTPUT_COVERAGE=30",
        "CLEANUP=1",
        "USE_GRID=false",
        "GRID_NODE=0",
        "GRID_OPTIONS=",
        "SMALL_MEMORY=0",
        "FSA_OL_FILTER_OPTIONS=",
        "FSA_ASSEMBLE_OPTIONS=",
        "FSA_CTG_BRIDGE_OPTIONS=",
        "POLISH_CONTIGS=true",
    ]) + "\n")


def make_nextdenovo_files(directory, reads, genome_size: int, threads: int,
                          read_type: str) -> None:
    """NextDenovo + NextPolish configs (reference helper.rs:828-867)."""
    directory = Path(directory)
    lgs_or_hifi, nd_read_type, map_preset = {
        "ont_r9": ("lgs", "ont", "map-ont"),
        "ont_r10": ("lgs", "ont", "map-ont"),  # lr:hq breaks NextPolish
        "pacbio_clr": ("lgs", "clr", "map-pb"),
        "pacbio_hifi": ("hifi", "hifi", "map-hifi"),
    }[read_type]
    (directory / "input.fofn").write_text(f"{Path(reads).resolve()}\n")
    (directory / "nextdenovo_run.cfg").write_text(
        "[General]\n"
        "job_type = local\njob_prefix = nextDenovo\ntask = all\n"
        "rewrite = yes\ndeltmp = yes\nparallel_jobs = 1\ninput_type = raw\n"
        f"read_type = {nd_read_type}\n"
        "input_fofn = input.fofn\nworkdir = nextdenovo\n\n"
        "[correct_option]\n"
        "read_cutoff = 1k\n"
        f"genome_size = {genome_size}\n"
        f"sort_options = -m 20g -t {threads}\n"
        f"minimap2_options_raw = -t {threads}\n"
        "pa_correction = 1\n"
        f"correction_options = -p {threads}\n\n"
        "[assemble_option]\n"
        f"minimap2_options_cns = -t {threads}\n"
        "nextgraph_options = -a 1\n")
    (directory / "nextpolish_run.cfg").write_text(
        "[General]\n"
        "job_type = local\njob_prefix = nextPolish\ntask = best\n"
        "rewrite = yes\ndeltmp = yes\nrerun = 3\nparallel_jobs = 1\n"
        f"multithread_jobs = {threads}\n"
        "genome = nextdenovo/03.ctg_graph/nd.asm.fasta\n"
        "genome_size = auto\nworkdir = nextpolish\n"
        f"polish_options = -p {threads}\n\n"
        f"[{lgs_or_hifi}_option]\n"
        f"{lgs_or_hifi}_fofn = input.fofn\n"
        f"{lgs_or_hifi}_options = -min_read_len 1k -max_depth 100\n"
        f"{lgs_or_hifi}_minimap2_options = -x {map_preset} -t {threads}\n")


def find_plassembler_db() -> Path:
    db = os.environ.get("PLASSEMBLER_DB")
    if db and Path(db).is_dir():
        return Path(db)
    conda = os.environ.get("CONDA_PREFIX")
    if conda and (Path(conda) / "plassembler_db").is_dir():
        return Path(conda) / "plassembler_db"
    quit_with_error("No Plassembler database found. Set PLASSEMBLER_DB or ensure "
                    "$CONDA_PREFIX/plassembler_db exists.")


def find_log_file(directory, prefix: str) -> Path:
    for p in Path(directory).iterdir():
        if p.name.startswith(prefix) and p.name.endswith(".log"):
            return p
    quit_with_error(f"{prefix} log file not found")


# ---------------- assembler runners ----------------

def _decompress_if_gzipped(reads, directory) -> Path:
    from ..utils import is_file_gzipped
    reads = Path(reads)
    if not is_file_gzipped(reads):
        return reads
    import gzip
    name = reads.name[:-3] if reads.name.endswith(".gz") else reads.name
    out = Path(directory) / name
    with gzip.open(reads, "rb") as r, open(out, "wb") as w:
        shutil.copyfileobj(r, w)
    return out


def _run_genome_size(reads, out_prefix, genome_size, threads, directory, read_type,
                     extra_args):
    check_requirements(["raven"])
    assembly = Path(directory) / "assembly.fasta"
    run_command(["raven", "--threads", threads, "--disable-checkpoints", reads]
                + extra_args, stdout_file=assembly)
    if is_fasta_empty(assembly):
        quit_with_error("Raven assembly failed")
    print(total_fasta_length(assembly))


def _run_canu(reads, out_prefix, genome_size, threads, directory, read_type, extra_args):
    gs = _require_genome_size(genome_size, "Canu")
    check_requirements(["canu"])
    input_flag = {"ont_r9": "-nanopore", "ont_r10": "-nanopore",
                  "pacbio_clr": "-pacbio", "pacbio_hifi": "-pacbio-hifi"}[read_type]
    run_command(["canu", "-p", "canu", "-d", directory, "-fast", f"genomeSize={gs}",
                 "useGrid=false", f"maxThreads={threads}", input_flag, reads]
                + extra_args)
    d = Path(directory)
    copy_canu_fasta(d / "canu.contigs.fasta", d / "canu.contigs.layout.tigInfo",
                    add_extension(out_prefix, "fasta"))
    copy_output_file(d / "canu.report", add_extension(out_prefix, "log"))


def _run_flye(reads, out_prefix, genome_size, threads, directory, read_type, extra_args):
    check_requirements(["flye"])
    input_flag = {"ont_r9": "--nano-raw", "ont_r10": "--nano-hq",
                  "pacbio_clr": "--pacbio-raw", "pacbio_hifi": "--pacbio-hifi"}[read_type]
    run_command(["flye", input_flag, reads, "--threads", threads, "--out-dir",
                 directory] + extra_args)
    d = Path(directory)
    copy_flye_fasta(d / "assembly.fasta", d / "assembly_info.txt",
                    add_extension(out_prefix, "fasta"))
    copy_output_file(d / "assembly_graph.gfa", add_extension(out_prefix, "gfa"))
    copy_output_file(d / "flye.log", add_extension(out_prefix, "log"))


def _run_hifiasm(reads, out_prefix, genome_size, threads, directory, read_type,
                 extra_args):
    check_requirements(["hifiasm"])
    cmd = ["hifiasm", "-t", threads, "-o", Path(directory) / "hifiasm", "-l", "0",
           "-f", "0"]
    if read_type != "pacbio_hifi":
        cmd.append("--ont")
    cmd += extra_args + [reads]
    run_command(cmd)
    d = Path(directory)
    gfa_to_fasta(d / "hifiasm.bp.p_ctg.gfa", add_extension(out_prefix, "fasta"))
    copy_output_file(d / "hifiasm.bp.p_ctg.gfa", add_extension(out_prefix, "gfa"))


_MAP_PRESET = {"ont_r9": "map-ont", "ont_r10": "lr:hq", "pacbio_clr": "map-pb",
               "pacbio_hifi": "map-hifi"}


def _run_ilesta(reads, out_prefix, genome_size, threads, directory, read_type,
                extra_args):
    check_requirements(["Ilesta", "minipolish", "minimap2", "racon"])
    input_reads = _decompress_if_gzipped(reads, directory)
    run_command(["Ilesta", "assemble", "--output-dir", directory, "--reads-fq",
                 input_reads, "--threads", threads] + extra_args)
    run_command(["minipolish", "--threads", threads, "--minimap2-preset",
                 _MAP_PRESET[read_type], reads, Path(directory) / "unitigs.gfa"],
                stdout_file=add_extension(out_prefix, "gfa"))
    gfa_to_fasta(add_extension(out_prefix, "gfa"), add_extension(out_prefix, "fasta"))


def _run_lja(reads, out_prefix, genome_size, threads, directory, read_type, extra_args):
    check_requirements(["lja"])
    run_command(["lja", "--output-dir", directory, "--reads", reads, "--threads",
                 threads] + extra_args)
    d = Path(directory)
    copy_fasta(d / "assembly.fasta", add_extension(out_prefix, "fasta"))
    copy_output_file(d / "mdbg.gfa", add_extension(out_prefix, "gfa"))
    copy_output_file(d / "dbg.log", add_extension(out_prefix, "log"))


def _run_metamdbg(reads, out_prefix, genome_size, threads, directory, read_type,
                  extra_args):
    check_requirements(["metaMDBG"])
    input_flag = "--in-hifi" if read_type == "pacbio_hifi" else "--in-ont"
    run_command(["metaMDBG", "asm", "--out-dir", directory, input_flag, reads,
                 "--threads", threads] + extra_args)
    d = Path(directory)
    copy_fasta(d / "contigs.fasta.gz", add_extension(out_prefix, "fasta"))
    copy_output_file(d / "metaMDBG.log", add_extension(out_prefix, "log"))


def _run_miniasm(reads, out_prefix, genome_size, threads, directory, read_type,
                 extra_args):
    check_requirements(["miniasm", "minipolish", "minimap2", "racon"])
    ava = {"ont_r9": "ava-ont", "ont_r10": "-k19 -Xw7 -e0 -m100",
           "pacbio_clr": "ava-pb", "pacbio_hifi": "-k23 -Xw11 -e0 -m100"}[read_type]
    d = Path(directory)
    cmd = ["minimap2", "-t", threads]
    if ava.startswith("-"):
        cmd += ava.split()
    else:
        cmd += ["-x", ava]
    cmd += [reads, reads]
    run_command(cmd, stdout_file=d / "overlap.paf")
    run_command(["miniasm", "-f", reads, d / "overlap.paf"] + extra_args,
                stdout_file=d / "unpolished.gfa")
    run_command(["minipolish", "--threads", threads, "--minimap2-preset",
                 _MAP_PRESET[read_type], reads, d / "unpolished.gfa"],
                stdout_file=add_extension(out_prefix, "gfa"))
    gfa_to_fasta(add_extension(out_prefix, "gfa"), add_extension(out_prefix, "fasta"))


def _run_myloasm(reads, out_prefix, genome_size, threads, directory, read_type,
                 extra_args):
    check_requirements(["myloasm"])
    cmd = ["myloasm", "--output-dir", directory, reads, "--threads", threads]
    if read_type == "pacbio_hifi":
        cmd.append("--hifi")
    elif read_type == "ont_r10":
        cmd.append("--nano-r10")
    run_command(cmd + extra_args)
    d = Path(directory)
    copy_fasta(d / "assembly_primary.fa", add_extension(out_prefix, "fasta"))
    replace_underscores_with_spaces(add_extension(out_prefix, "fasta"))
    copy_output_file(d / "final_contig_graph.gfa", add_extension(out_prefix, "gfa"))
    copy_output_file(find_log_file(d, "myloasm"), add_extension(out_prefix, "log"))


def _find_necat() -> str:
    for cmd in ("necat", "necat.pl"):
        if shutil.which(cmd):
            return cmd
    quit_with_error("required program 'necat' (or 'necat.pl') not found in $PATH")


def _run_necat(reads, out_prefix, genome_size, threads, directory, read_type,
               extra_args):
    gs = _require_genome_size(genome_size, "NECAT")
    make_necat_files(reads, directory, gs, threads)
    run_command([_find_necat(), "bridge", "config.txt"] + extra_args, cwd=directory)
    copy_fasta(Path(directory) / "necat/6-bridge_contigs/polished_contigs.fasta",
               add_extension(out_prefix, "fasta"))


def _run_nextdenovo(reads, out_prefix, genome_size, threads, directory, read_type,
                    extra_args):
    gs = _require_genome_size(genome_size, "NextDenovo")
    check_requirements(["nextDenovo", "nextPolish"])
    make_nextdenovo_files(directory, reads, gs, threads, read_type)
    run_command(["nextDenovo", "nextdenovo_run.cfg"] + extra_args, cwd=directory)
    run_command(["nextPolish", "nextpolish_run.cfg"], cwd=directory)
    d = Path(directory)
    copy_fasta(d / "nextpolish/genome.nextpolish.fasta",
               add_extension(out_prefix, "fasta"))
    logs = sorted(d.glob("pid*.log.info"), key=lambda p: p.stat().st_mtime)
    if logs:
        with open(add_extension(out_prefix, "log"), "w") as out:
            for p in logs:
                out.write(p.read_text())


def _run_plassembler(reads, out_prefix, genome_size, threads, directory, read_type,
                     extra_args):
    check_requirements(["plassembler", "chopper", "dnaapler", "fastp", "mash",
                        "minimap2", "raven", "samtools", "unicycler"])
    db = find_plassembler_db()
    cmd = ["plassembler", "long", "-d", db, "-l", reads, "-o", directory, "-t",
           threads, "--force", "--skip_qc"]
    if read_type == "ont_r9":
        cmd.append("--raw_flag")
    if read_type == "pacbio_clr":
        cmd += ["--pacbio_model", "pacbio-raw"]
    if read_type == "pacbio_hifi":
        cmd += ["--pacbio_model", "pacbio-hifi"]
    run_command(cmd + extra_args)
    d = Path(directory)
    copy_output_file(d / "plassembler_plasmids.gfa", add_extension(out_prefix, "gfa"))
    rotate_plassembler_contigs(d / "plassembler_plasmids.fasta",
                               add_extension(out_prefix, "fasta"))
    copy_output_file(find_log_file(d, "plassembler"), add_extension(out_prefix, "log"))


def _run_raven(reads, out_prefix, genome_size, threads, directory, read_type,
               extra_args):
    check_requirements(["raven"])
    run_command(["raven", "--threads", threads, "--disable-checkpoints",
                 "--graphical-fragment-assembly", add_extension(out_prefix, "gfa"),
                 reads] + extra_args, stdout_file=add_extension(out_prefix, "fasta"))


def _run_redbean(reads, out_prefix, genome_size, threads, directory, read_type,
                 extra_args):
    gs = _require_genome_size(genome_size, "Redbean")
    check_requirements(["wtdbg2", "wtpoa-cns"])
    preset = {"ont_r9": "preset2", "ont_r10": "preset2", "pacbio_clr": "preset1",
              "pacbio_hifi": "preset4"}[read_type]
    d = Path(directory)
    run_command(["wtdbg2", "-x", preset, "-g", gs, "-i", reads, "-t", threads, "-f",
                 "-o", d / "dbg"] + extra_args)
    run_command(["wtpoa-cns", "-t", threads, "-i", d / "dbg.ctg.lay.gz", "-f", "-o",
                 d / "assembly.fasta"])
    copy_fasta(d / "assembly.fasta", add_extension(out_prefix, "fasta"))


def _require_genome_size(genome_size: Optional[str], assembler_name: str) -> int:
    if genome_size is None:
        quit_with_error(f"assembly with {assembler_name} requires --genome_size")
    return parse_genome_size(genome_size)


TASKS: Dict[str, Callable] = {
    "genome_size": _run_genome_size,
    "canu": _run_canu,
    "flye": _run_flye,
    "hifiasm": _run_hifiasm,
    "ilesta": _run_ilesta,
    "lja": _run_lja,
    "metamdbg": _run_metamdbg,
    "miniasm": _run_miniasm,
    "myloasm": _run_myloasm,
    "necat": _run_necat,
    "nextdenovo": _run_nextdenovo,
    "plassembler": _run_plassembler,
    "raven": _run_raven,
    "redbean": _run_redbean,
}


def helper(task: str, reads, out_prefix=None, genome_size: Optional[str] = None,
           threads: int = 8, directory=None, read_type: str = "ont_r10",
           min_depth_abs: Optional[float] = None,
           min_depth_rel: Optional[float] = None,
           extra_args: Optional[List[str]] = None,
           timeout: Optional[float] = None,
           retries: Optional[int] = None) -> None:
    if timeout is not None or retries is not None:
        # CLI flags become the process-wide subprocess policy so every
        # assembler invocation in this run inherits them
        resilience.set_subprocess_policy(timeout=timeout, retries=retries)
    if task not in TASKS:
        quit_with_error(f"unknown helper task: {task} "
                        f"(choose from {', '.join(sorted(TASKS))})")
    if read_type not in READ_TYPES:
        quit_with_error(f"unknown read type: {read_type}")
    if not os.path.isfile(reads):
        quit_with_error(f"file does not exist: {reads}")
    extra_args = list(extra_args or [])

    temp_guard = None
    if directory is None:
        temp_guard = tempfile.TemporaryDirectory(prefix="autocycler_helper_")
        directory = temp_guard.name
        # clean up on Ctrl-C like the reference (helper.rs:599-609)
        previous = signal.getsignal(signal.SIGINT)

        def _cleanup(signum, frame):
            temp_guard.cleanup()
            signal.signal(signal.SIGINT, previous)
            sys.exit(130)

        try:
            signal.signal(signal.SIGINT, _cleanup)
        except ValueError:
            pass  # not the main thread
    os.makedirs(directory, exist_ok=True)

    try:
        if task == "genome_size":
            TASKS[task](reads, None, genome_size, threads, directory, read_type,
                        extra_args)
            return
        if out_prefix is None:
            quit_with_error("assembly helper commands require --out_prefix")
        prefix_parent = Path(out_prefix).parent
        if prefix_parent and not prefix_parent.exists():
            os.makedirs(prefix_parent, exist_ok=True)
        TASKS[task](reads, out_prefix, genome_size, threads, directory, read_type,
                    extra_args)
        depth_filter(out_prefix, min_depth_abs, min_depth_rel)
        delete_fasta_if_empty(out_prefix)
    finally:
        if temp_guard is not None:
            temp_guard.cleanup()
