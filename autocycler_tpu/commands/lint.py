"""`autocycler lint` — run the static invariant checks over the repo.

Defaults to linting the installed package plus the repo-level bench.py
and pipelines/ when run from a source tree, against the committed
``lint_baseline.json``.  Exit code 0 means no non-baselined findings.

Also the home of ``--knobs-md`` (regenerates the knob table embedded in
docs/cli.md) and ``--write-baseline`` (accepts the current findings as
the new baseline).  ``--report`` writes a ``lint_report.json`` artifact
readable by ``autocycler report`` and ``bench.py lintsmoke``.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import List, Optional

from ..analysis import (LintContext, load_baseline, run_lint,
                        split_baseline, write_baseline)
from ..analysis.rules import rule_ids
from ..utils.knobs import knobs_markdown


def repo_root() -> Path:
    """The source tree root (the directory holding the package dir)."""
    return Path(__file__).resolve().parents[2]


def default_paths(root: Path) -> List[Path]:
    out = [root / "autocycler_tpu"]
    for extra in ("bench.py", "pipelines"):
        if (root / extra).exists():
            out.append(root / extra)
    return out


def run(paths: Optional[List[str]] = None,
        baseline: Optional[str] = None,
        rules: Optional[List[str]] = None,
        as_json: bool = False,
        write_baseline_path: Optional[str] = None,
        report_path: Optional[str] = None,
        docs: Optional[str] = None) -> dict:
    """The reusable core: returns the result dict the CLI renders (and
    bench.py lintsmoke consumes)."""
    root = repo_root()
    targets = [Path(p) for p in paths] if paths else default_paths(root)
    docs_path = Path(docs) if docs else (
        root / "docs" / "cli.md"
        if (root / "docs" / "cli.md").exists() else None)
    ctx = LintContext(root=root, docs_path=docs_path)
    start = time.perf_counter()
    findings, n_files = run_lint(targets, ctx, selectors=rules)
    wall_s = time.perf_counter() - start
    baseline_path = Path(baseline) if baseline else root / "lint_baseline.json"
    baseline_keys = load_baseline(baseline_path) \
        if baseline_path.exists() else set()
    new, old = split_baseline(findings, baseline_keys)
    if write_baseline_path:
        write_baseline(findings, write_baseline_path)
    result = {
        "files": n_files,
        "wall_s": round(wall_s, 4),
        "findings": [f.to_dict() for f in new],
        "baselined": len(old),
        "baseline": str(baseline_path) if baseline_path.exists() else None,
        "rules": list(rules) if rules else sorted(rule_ids()),
    }
    if report_path:
        payload = dict(result, generated_at=round(time.time(), 3))
        Path(report_path).write_text(
            json.dumps(payload, indent=2, sort_keys=True) + "\n")
    return result


def lint(paths: Optional[List[str]] = None,
         baseline: Optional[str] = None,
         rules: Optional[List[str]] = None,
         as_json: bool = False,
         write_baseline_path: Optional[str] = None,
         report_path: Optional[str] = None,
         knobs_md: bool = False) -> int:
    """CLI entry. Returns the process exit code."""
    if knobs_md:
        print(knobs_markdown(), end="")
        return 0
    known = rule_ids()
    for sel in rules or ():
        if not any(r == sel or r.startswith(sel + ".") for r in known):
            print(f"autocycler lint: unknown rule {sel!r} "
                  f"(known: {', '.join(known)})")
            return 2
    result = run(paths=paths, baseline=baseline, rules=rules,
                 write_baseline_path=write_baseline_path,
                 report_path=report_path)
    if as_json:
        print(json.dumps(result, indent=2, sort_keys=True))
    else:
        for f in result["findings"]:
            print(f"{f['path']}:{f['line']}: [{f['rule']}] {f['message']}")
        status = ("clean" if not result["findings"]
                  else f"{len(result['findings'])} finding(s)")
        print(f"lint: {status} across {result['files']} files "
              f"in {result['wall_s']:.2f}s"
              + (f" ({result['baselined']} baselined)"
                 if result["baselined"] else ""))
    if write_baseline_path:
        total = len(result["findings"]) + result["baselined"]
        print(f"lint: wrote baseline with {total} finding(s) "
              f"to {write_baseline_path}")
        return 0
    return 0 if not result["findings"] else 1
