"""`autocycler resolve`: bridge anchor unitigs into a consensus path.

Parity target: reference resolve.rs. Anchors are unitigs occurring exactly
once in every sequence; every sequence path is cut into anchor-to-anchor
segments (strand-canonicalised), segments sharing (start, end) form a
Bridge whose best path is the medoid under weighted global-alignment
distance (ops.align.global_alignment_distance, batched row-vectorised DP);
non-conflicting bridges are applied, then the lowest-depth conflicting
bridges are culled until none conflict and bridges are re-applied from a
fresh graph. Writes 3_bridged.gfa, 4_merged.gfa, 5_final.gfa.
"""

from __future__ import annotations

from pathlib import Path

import numpy as np
from typing import Dict, List, Optional, Set, Tuple

from ..models import Sequence, Unitig, UnitigGraph, UnitigType
from ..models.simplify import merge_linear_paths
from ..obs import ledger, qc
from ..ops.align import (global_alignment_distance,
                         global_alignment_distance_batch)
from ..utils import (load_file_lines, log, quit_with_error, reverse_signed_path,
                     sign_at_end, sign_at_end_vec)
from ..utils.timing import stage_timer


class Bridge:
    """An anchor-to-anchor connection with its supporting paths
    (reference resolve.rs:420-514)."""

    __slots__ = ("start", "end", "all_paths", "best_path", "conflicting")

    def __init__(self, start: int, end: int, all_paths: List[List[int]],
                 unitig_lengths: Dict[int, int],
                 pair_distances: Optional[Dict[tuple, int]] = None):
        trimmed = [path[1:-1] for path in all_paths]
        # The medoid objective Σ_j d(i, j) over occurrences equals
        # Σ_distinct_j mult_j · d(i, j) (self-distance is 0), so distances
        # are computed between DISTINCT paths only — groups are dominated by
        # duplicates since most assemblies agree on each bridge. When
        # pair_distances is supplied (create_bridges computes every group's
        # pairs in ONE batched DP), the per-pair Python calls vanish.
        mult: Dict[tuple, int] = {}
        for path in trimmed:
            mult[tuple(path)] = mult.get(tuple(path), 0) + 1
        distinct = sorted(mult)  # lexicographic: ties resolve to smaller path

        def dist(pi: tuple, pj: tuple) -> int:
            if pair_distances is not None:
                got = pair_distances.get((pi, pj))
                if got is None:
                    got = pair_distances.get((pj, pi))
                if got is not None:
                    return got
            return global_alignment_distance(pi, pj, unitig_lengths)

        best_path: List[int] = []
        best_total = None
        for path_i in distinct:
            total = 0
            for path_j, m in mult.items():
                if path_j != path_i:
                    total += m * dist(path_i, path_j)
            if best_total is None or total < best_total:
                best_total = total
                best_path = list(path_i)
        self.start = start
        self.end = end
        self.all_paths = trimmed
        self.best_path = best_path
        self.conflicting = False

    def rev_start(self) -> int:
        return -self.end

    def rev_end(self) -> int:
        return -self.start

    def depth(self) -> int:
        return len(self.all_paths)

    def sort_key(self):
        """(|start| asc, start desc, |end| asc, end desc, best_path asc) —
        reference resolve.rs Ord impl."""
        return (abs(self.start), -self.start, abs(self.end), -self.end, self.best_path)

    def __repr__(self):
        if not self.best_path:
            return f"{sign_at_end(self.start)} -> {sign_at_end(self.end)} ({self.depth()}x)"
        return (f"{sign_at_end(self.start)} -> {sign_at_end_vec(self.best_path)} -> "
                f"{sign_at_end(self.end)} ({self.depth()}x)")


def find_anchor_unitigs(graph: UnitigGraph, sequences: List[Sequence]) -> List[int]:
    """Anchors occur once and only once in every sequence
    (reference resolve.rs:134-163)."""
    all_seq_ids = np.sort(np.array([s.id for s in sequences], np.int32))
    anchor_ids = []
    for unitig in graph.unitigs:
        forward_seq_ids = np.sort(unitig.forward_positions.seq_id)
        if np.array_equal(forward_seq_ids, all_seq_ids):
            unitig.unitig_type = UnitigType.ANCHOR
            anchor_ids.append(unitig.number)
    n = len(anchor_ids)
    log.message(f"{n} anchor unitig{'' if n == 1 else 's'} found")
    log.message()
    return anchor_ids


def get_anchor_to_anchor_paths(sequence_paths: List[List[int]],
                               anchor_set: Set[int]) -> List[List[int]]:
    """Cut each path at anchors, canonicalising each segment to the
    lexicographically larger of itself and its reverse
    (reference resolve.rs:344-365)."""
    out = []
    for path in sequence_paths:
        last_anchor_i: Optional[int] = None
        for i, value in enumerate(path):
            if abs(value) in anchor_set:
                if last_anchor_i is not None:
                    forward = path[last_anchor_i:i + 1]
                    reverse = reverse_signed_path(forward)
                    out.append(forward if forward > reverse else reverse)
                last_anchor_i = i
    return out


def group_paths_by_start_end(paths: List[List[int]]
                             ) -> Dict[Tuple[int, int], List[List[int]]]:
    grouped: Dict[Tuple[int, int], List[List[int]]] = {}
    for path in paths:
        if path:
            grouped.setdefault((path[0], path[-1]), []).append(path)
    return grouped


def create_bridges(graph: UnitigGraph, sequences: List[Sequence], anchors: List[int],
                   verbose: bool = False) -> List[Bridge]:
    """One Bridge per (start, end) anchor pair; sequences contribute their
    path consensus_weight times (reference resolve.rs:166-190)."""
    anchor_set = set(anchors)
    all_paths = graph.get_unitig_paths_for_sequences([s.id for s in sequences])
    sequence_paths = []
    for s in sequences:
        weight = s.consensus_weight()
        if verbose:
            log.message(f"{s} consensus weight = {weight}")
        path = [n if st else -n for n, st in all_paths[s.id]]
        sequence_paths.extend([list(path) for _ in range(weight)])
    a_to_a = get_anchor_to_anchor_paths(sequence_paths, anchor_set)
    grouped = group_paths_by_start_end(a_to_a)
    unitig_lengths = {u.number: u.length() for u in graph.unitigs}
    pair_distances = _batched_medoid_distances(grouped, unitig_lengths)
    bridges = [Bridge(start, end, paths, unitig_lengths, pair_distances)
               for (start, end), paths in grouped.items()]
    bridges.sort(key=Bridge.sort_key)
    return bridges


def _batched_medoid_distances(grouped, unitig_lengths) -> Dict[tuple, int]:
    """Every bridge group's distinct-path pairs through ONE vectorised DP
    (ops.align.global_alignment_distance_batch) instead of O(paths^2) tiny
    Python calls per bridge (reference resolve.rs:387-418 scope)."""
    wanted = {}
    for paths in grouped.values():
        distinct = sorted({tuple(p[1:-1]) for p in paths})
        for i, pi in enumerate(distinct):
            for pj in distinct[i + 1:]:
                wanted.setdefault((pi, pj), None)
    # the batch pads every pair to the global max length, so a rare long
    # outlier pair would multiply the whole batch's cost — route those
    # through the scalar DP instead (sum(n*m) cost, no padding)
    batch_pairs, long_pairs = [], []
    for pair in wanted:
        (batch_pairs if max(len(pair[0]), len(pair[1])) <= 64
         else long_pairs).append(pair)
    dists = global_alignment_distance_batch(batch_pairs, unitig_lengths)
    out = {pair: int(d) for pair, d in zip(batch_pairs, dists)}
    for pi, pj in long_pairs:
        out[(pi, pj)] = global_alignment_distance(pi, pj, unitig_lengths)
    return out


def determine_ambiguity(bridges: List[Bridge]) -> int:
    """Mark bridges sharing a start or end (on either strand) as conflicting
    (reference resolve.rs:193-220)."""
    start_count: Dict[int, int] = {}
    end_count: Dict[int, int] = {}
    for b in bridges:
        start_count[b.start] = start_count.get(b.start, 0) + 1
        start_count[b.rev_start()] = start_count.get(b.rev_start(), 0) + 1
        end_count[b.end] = end_count.get(b.end, 0) + 1
        end_count[b.rev_end()] = end_count.get(b.rev_end(), 0) + 1
    ambi_starts = {n for n, c in start_count.items() if c > 1}
    ambi_ends = {n for n, c in end_count.items() if c > 1}
    count = 0
    for b in bridges:
        b.conflicting = (b.start in ambi_starts or b.rev_start() in ambi_starts
                         or b.end in ambi_ends or b.rev_end() in ambi_ends)
        count += b.conflicting
    return count


def apply_bridges(graph: UnitigGraph, bridges: List[Bridge], bridge_depth: float) -> None:
    """Apply non-conflicting bridges: replace the links out of each start and
    into each end with a bridge unitig (or a direct link for empty paths),
    reduce constituent depths, drop anchor-less components
    (reference resolve.rs:223-251)."""
    graph.clear_positions()
    next_num = graph.max_unitig_number()
    for bridge in bridges:
        if bridge.conflicting:
            continue
        graph.delete_outgoing_links(bridge.start)
        graph.delete_incoming_links(bridge.end)
        if not bridge.best_path:
            graph.create_link(bridge.start, bridge.end)
        else:
            bridge_seq = graph.get_sequence_from_path_signed(bridge.best_path)
            next_num += 1
            bridge_num = next_num
            unitig = Unitig.bridge(bridge_num, bridge_seq, bridge_depth)
            graph.unitigs.append(unitig)
            graph.index[bridge_num] = unitig
            _reduce_depths(graph, bridge)
            graph.create_link(bridge.start, bridge_num)
            graph.create_link(bridge_num, bridge.end)
    _delete_unitigs_not_connected_to_anchor(graph)
    graph.remove_zero_depth_unitigs()


def _reduce_depths(graph: UnitigGraph, bridge: Bridge) -> None:
    for path in bridge.all_paths:
        for signed_num in path:
            graph.index[abs(signed_num)].reduce_depth_by_one()


def _delete_unitigs_not_connected_to_anchor(graph: UnitigGraph) -> None:
    to_delete: Set[int] = set()
    for component in graph.connected_components():
        if all(graph.index[num].unitig_type is not UnitigType.ANCHOR
               for num in component):
            to_delete.update(component)
    graph.remove_unitigs_by_number(to_delete)


def merge_after_bridging(graph: UnitigGraph) -> None:
    merge_linear_paths(graph, [])
    graph.print_basic_graph_info()
    graph.renumber_unitigs()


def print_bridges(bridges: List[Bridge], verbose: bool) -> None:
    """Bridge summary, or every bridge when verbose (reference
    resolve.rs:316-341)."""
    unique = [b for b in bridges if not b.conflicting]
    conflicting = [b for b in bridges if b.conflicting]
    if verbose:
        if unique:
            log.message("Unique bridges:")
            for b in unique:
                log.message(f"  {b}")
        if conflicting:
            log.message("")
            log.message("Conflicting bridges:")
            for b in conflicting:
                log.message(f"  {b}")
    else:
        log.message(f"     Unique bridges: {len(unique)}")
        log.message(f"Conflicting bridges: {len(conflicting)}")
    log.message()


def cull_ambiguity(bridges: List[Bridge], verbose: bool = False) -> int:
    """Iteratively remove the lowest-depth conflicting bridge until no
    conflicts remain (reference resolve.rs:285-313)."""
    ambi = [b for b in bridges if b.conflicting]
    if not ambi:
        return 0
    log.section_header("Culling conflicting bridges")
    log.explanation("The least-supported conflicting bridges are now culled until no "
                    "bridges conflict.")
    cull_count = 0
    while ambi:
        ambi.sort(key=lambda b: (b.depth(),) + b.sort_key())
        to_cull = ambi[0]
        if verbose:
            log.message(f"  {to_cull}")
        idx = next(i for i, b in enumerate(bridges)
                   if b.start == to_cull.start and b.end == to_cull.end)
        bridges.pop(idx)
        cull_count += 1
        determine_ambiguity(bridges)
        ambi = [b for b in bridges if b.conflicting]
    log.message(f"{cull_count} conflicting bridge{'' if cull_count == 1 else 's'} culled")
    log.message()
    return cull_count


def resolve(cluster_dir, verbose: bool = False, preloaded=None) -> None:
    """preloaded: optional (graph, sequences) as returned by trim() — skips
    re-parsing 2_trimmed.gfa (the file remains the checkpoint of record and
    is still read back if ambiguity culling needs the pristine graph)."""
    cluster_dir = Path(cluster_dir)
    trimmed_gfa = cluster_dir / "2_trimmed.gfa"
    if not cluster_dir.is_dir():
        quit_with_error(f"directory does not exist: {cluster_dir}")
    if not trimmed_gfa.is_file():
        quit_with_error(f"file does not exist: {trimmed_gfa}")

    log.section_header("Starting autocycler resolve")
    log.explanation("This command resolves repeats in the unitig graph.")
    with stage_timer("resolve/load"):
        if preloaded is not None:
            graph, sequences = preloaded
            gfa_lines = None
            graph.check_links()   # the file path validates at parse; match it
        else:
            gfa_lines = load_file_lines(trimmed_gfa)
            graph, sequences = UnitigGraph.from_gfa_lines(gfa_lines)
    graph.print_basic_graph_info()

    log.section_header("Finding anchor unitigs")
    log.explanation("Anchor unitigs are those that occur once and only once in each "
                    "sequence. They will definitely be present in the final sequence and "
                    "will serve as the connection points for bridges.")
    with stage_timer("resolve/anchors"):
        anchors = find_anchor_unitigs(graph, sequences)

    log.section_header("Building bridges")
    log.explanation("Bridges connect one anchor unitig to the next.")
    with stage_timer("resolve/bridges"):
        bridges = create_bridges(graph, sequences, anchors, verbose)
        bridge_count = len(bridges)
        bridge_depth = float(len(sequences))
        conflicting = determine_ambiguity(bridges)
    print_bridges(bridges, verbose)

    log.section_header("Applying unique bridges")
    log.explanation("All unique bridges (those that do not conflict with other bridges) "
                    "are now applied to the graph, with linear paths merged to create "
                    "consentigs.")
    with stage_timer("resolve/apply"):
        apply_bridges(graph, bridges, bridge_depth)
        graph.save_gfa(cluster_dir / "3_bridged.gfa", [])
        merge_after_bridging(graph)
        graph.save_gfa(cluster_dir / "4_merged.gfa", [])

        cull_count = cull_ambiguity(bridges, verbose)
        if cull_count > 0:
            if gfa_lines is None:  # preloaded graph was mutated; re-read
                gfa_lines = load_file_lines(trimmed_gfa)
            graph, _ = UnitigGraph.from_gfa_lines(gfa_lines)
            for num in anchors:
                graph.index[num].unitig_type = UnitigType.ANCHOR
            log.section_header("Applying final bridges")
            log.explanation("Now that conflicting bridges have been removed, bridges are "
                            "applied one more time to create the final graph.")
            apply_bridges(graph, bridges, bridge_depth)
            merge_after_bridging(graph)
        elif bridge_count > 0:
            log.message("All bridges were unique, no culling necessary.")
            log.message()

        final_gfa = cluster_dir / "5_final.gfa"
        graph.save_gfa(final_gfa, [], use_other_colour=True)
    qc.resolve_qc(cluster_dir.name, len(anchors), bridges, conflicting,
                  cull_count)
    ledger.record_stage("resolve", inputs=[trimmed_gfa],
                        outputs=[cluster_dir / "3_bridged.gfa",
                                 cluster_dir / "4_merged.gfa", final_gfa],
                        cluster=cluster_dir.name)
    log.section_header("Finished!")
    log.message(f"Final consensus graph: {final_gfa}")
    log.message()
