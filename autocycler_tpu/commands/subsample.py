"""`autocycler subsample`: split a long-read set into maximally-independent
subsets.

Parity target: reference subsample.rs — FASTQ stats (count/bases/N50),
subset depth formula ``min_depth * log2(4 * total_depth / min_depth) / 2``,
seeded shuffle, and ``count`` overlapping windows over the shuffled order.
The shuffle is REPRODUCTION-EXACT against the reference for the same seed:
utils/rust_rand.py reimplements rand 0.9's StdRng (ChaCha12) seeding +
SliceRandom::shuffle bit-for-bit, gated by a runtime self-test of the
cipher core; if that gate ever fails, the seeded Python Fisher-Yates is
used instead and the divergence is stamped into subsample.yaml's
``shuffle`` field.
"""

from __future__ import annotations

import math
import os
import random
from pathlib import Path
from typing import List, Set

from ..metrics import ReadSetDetails, SubsampleMetrics
from ..utils import Spinner, fastq_reader, log, quit_with_error


def parse_genome_size(genome_size_str: str) -> int:
    """'4.5m' -> 4500000; bare numbers, k/m/g suffixes (reference
    subsample.rs:77-93). Rounds half-away-from-zero like Rust's f64::round."""
    s = genome_size_str.strip().lower()
    try:
        return int(math.floor(float(s) + 0.5))
    except ValueError:
        pass
    multiplier = {"k": 1e3, "m": 1e6, "g": 1e9}.get(s[-1] if s else "")
    if multiplier is None:
        quit_with_error("cannot interpret genome size")
    try:
        return int(math.floor(float(s[:-1]) * multiplier + 0.5))
    except ValueError:
        quit_with_error("cannot interpret genome size")


def calculate_subsets(read_count: int, read_bases: int, genome_size: int,
                      min_depth: float) -> int:
    """Reads per subset from the subset-depth formula (reference
    subsample.rs:113-135)."""
    total_depth = read_bases / genome_size
    if total_depth < min_depth:
        quit_with_error("input reads are too shallow to subset")
    subset_depth = min_depth * math.log2(4.0 * total_depth / min_depth) / 2.0
    subset_ratio = subset_depth / total_depth
    reads_per_subset = round(subset_ratio * read_count)
    log.message(f"Total read depth: {total_depth:.1f}x")
    log.message(f"  subset depth: {subset_depth:.1f}x")
    log.message(f"  reads per subset: {reads_per_subset}")
    log.message()
    return reads_per_subset


def subsample_indices(subset_count: int, reads_per_subset: int,
                      read_order: List[int], i: int) -> Set[int]:
    """Window i over the shuffled read order, wrapping around
    (reference subsample.rs:165-189)."""
    input_count = len(read_order)
    indices: Set[int] = set()
    start_1 = round(i * input_count / subset_count)
    end_1 = start_1 + reads_per_subset
    if end_1 > input_count:
        end_2 = end_1 - input_count
        end_1 = input_count
        for j in range(0, end_2):
            indices.add(read_order[j])
    for j in range(start_1, end_1):
        indices.add(read_order[j])
    assert len(indices) == reads_per_subset
    return indices


def subsample(fastq_file, out_dir, genome_size: str, count: int = 4,
              min_read_depth: float = 25.0, seed: int = 0) -> None:
    out_dir = Path(out_dir)
    genome_size_int = parse_genome_size(genome_size)
    if not os.path.isfile(fastq_file):
        quit_with_error(f"file does not exist: {fastq_file}")
    if os.path.exists(out_dir) and not os.path.isdir(out_dir):
        quit_with_error(f"{out_dir} exists but is not a directory")
    if genome_size_int < 1:
        quit_with_error("--genome_size must be at least 1")
    if count < 2:
        quit_with_error("--count must be at least 2")
    if min_read_depth <= 0.0:
        quit_with_error("--min_read_depth must be greater than 0")
    os.makedirs(out_dir, exist_ok=True)

    log.section_header("Starting autocycler subsample")
    log.explanation("This command subsamples a long-read set into subsets that are "
                    "maximally independent from each other.")
    metrics = SubsampleMetrics()
    read_lengths = sorted(len(seq) for _, seq, _ in fastq_reader(fastq_file))
    details = ReadSetDetails.from_sorted_lengths(read_lengths)
    metrics.input_read_count = details.count
    metrics.input_read_bases = details.bases
    metrics.input_read_n50 = details.n50
    log.message(f"Input FASTQ:")
    log.message(f"  Read count: {details.count}")
    log.message(f"  Read bases: {details.bases}")
    log.message(f"  Read N50 length: {details.n50} bp")
    log.message()

    reads_per_subset = calculate_subsets(details.count, details.bases, genome_size_int,
                                         min_read_depth)

    from ..utils.rust_rand import std_rng_shuffled_order
    read_order = std_rng_shuffled_order(details.count, seed)
    if read_order is not None:
        metrics.shuffle = "rust-stdrng-0.9"
    else:  # cipher self-test failed: legacy shuffle, recorded divergence
        metrics.shuffle = "python-fisher-yates"
        rng = random.Random(seed)
        read_order = list(range(details.count))
        rng.shuffle(read_order)
    subset_index_sets = [subsample_indices(count, reads_per_subset, read_order, i)
                         for i in range(count)]
    files = []
    for i in range(count):
        path = out_dir / f"sample_{i + 1:02d}.fastq"
        log.message(f"subset {i + 1}: {path}")
        files.append(open(path, "w"))
    sample_read_lengths: List[List[int]] = [[] for _ in range(count)]
    with Spinner("writing subsampled reads to files..."):
        for read_i, (header, seq, quals) in enumerate(fastq_reader(fastq_file)):
            record = f"@{header}\n{seq}\n+\n{quals}\n"
            for subset_i in range(count):
                if read_i in subset_index_sets[subset_i]:
                    files[subset_i].write(record)
                    sample_read_lengths[subset_i].append(len(seq))
        for f in files:
            f.close()
    for lengths in sample_read_lengths:
        metrics.output_reads.append(ReadSetDetails.from_sorted_lengths(sorted(lengths)))
    metrics.save_to_yaml(out_dir / "subsample.yaml")
    log.section_header("Finished!")
    log.explanation("You can now assemble each of the subsampled read sets to produce a "
                    "set of assemblies for input into Autocycler compress.")
