"""`autocycler table`: flatten per-stage metrics YAMLs into one TSV row.

Parity target: reference table.rs — discover stage YAMLs under a directory
(skipping qc_fail/ for the multi-copy cluster metrics), flatten to one row
per sample with significant-figure formatting; with no directory, print just
the header row.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Dict, List, Optional

import yaml

from ..metrics import (ClusteringMetrics, CombineMetrics, InputAssemblyMetrics,
                       SubsampleMetrics, TrimmedClusterMetrics, UntrimmedClusterMetrics)
from ..utils import format_float_sigfigs, log, quit_with_error

# default field list, reference main.rs:287-294
DEFAULT_FIELDS = ("input_read_count,input_read_bases,input_read_n50,"
                  "pass_cluster_count,fail_cluster_count,overall_clustering_score,"
                  "untrimmed_cluster_size,untrimmed_cluster_distance,"
                  "trimmed_cluster_size,trimmed_cluster_median,trimmed_cluster_mad,"
                  "consensus_assembly_bases,consensus_assembly_unitigs,"
                  "consensus_assembly_fully_resolved")


def parse_fields(comma_delimited: str) -> List[str]:
    fields = [f for f in comma_delimited.replace(" ", "").split(",") if f]
    valid = set()
    for cls in (SubsampleMetrics, InputAssemblyMetrics, ClusteringMetrics,
                CombineMetrics, UntrimmedClusterMetrics, TrimmedClusterMetrics):
        valid.update(cls.get_field_names())
    for field in fields:
        if field not in valid:
            quit_with_error(f"{field} is not a valid field name")
    return fields


def find_all_yaml_files(autocycler_dir) -> List[Path]:
    out = []
    for root, _dirs, files in os.walk(autocycler_dir):
        for f in files:
            if f.endswith(".yaml"):
                out.append(Path(root) / f)
    out.sort()
    return out


def get_one_copy_yaml(yaml_files: List[Path], filename: str) -> Optional[Path]:
    found = [p for p in yaml_files if p.name == filename]
    if not found:
        log.message(f"Warning: {filename} not found")
        return None
    if len(found) > 1:
        quit_with_error(f"Multiple {filename} files found")
    return found[0]


def get_multi_copy_yaml(yaml_files: List[Path], filename: str) -> List[Path]:
    found = [p for p in yaml_files
             if p.name == filename and "/qc_fail/" not in str(p)]
    if not found:
        log.message(f"Warning: {filename} not found")
    return found


def format_value(value, sigfigs: int) -> str:
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, float):
        return format_float_sigfigs(value, sigfigs)
    if isinstance(value, int):
        return str(value)
    if isinstance(value, str):
        return value
    if isinstance(value, list):
        return "[" + ",".join(format_value(v, sigfigs) for v in value) + "]"
    if isinstance(value, dict):
        return "{" + ",".join(f"{format_value(k, sigfigs)}:{format_value(v, sigfigs)}"
                              for k, v in value.items()) + "}"
    return ""


def table_row(autocycler_dir, name: str, fields: List[str], sigfigs: int) -> str:
    if "\t" in name:
        quit_with_error("--name cannot contain tab characters")
    yaml_files = find_all_yaml_files(autocycler_dir)
    merged: Dict[str, object] = {}
    for filename in ("subsample.yaml", "input_assemblies.yaml", "clustering.yaml",
                     "consensus_assembly.yaml"):
        path = get_one_copy_yaml(yaml_files, filename)
        if path is not None:
            with open(path) as f:
                merged.update(yaml.safe_load(f) or {})
    for filename in ("1_untrimmed.yaml", "2_trimmed.yaml"):
        paths = get_multi_copy_yaml(yaml_files, filename)
        combined: Dict[str, list] = {}
        for path in paths:
            with open(path) as f:
                for key, value in (yaml.safe_load(f) or {}).items():
                    combined.setdefault(key, []).append(value)
        merged.update(combined)
    cells = [name]
    for field in fields:
        value = merged.get(field)
        cells.append(format_value(value, sigfigs) if value is not None else "")
    return "\t".join(cells)


def table(autocycler_dir=None, name: str = "", fields: str = DEFAULT_FIELDS,
          sigfigs: int = 3) -> None:
    if sigfigs == 0:
        quit_with_error("--sigfigs must be 1 or greater")
    field_list = parse_fields(fields)
    if autocycler_dir is None:
        print("name\t" + "\t".join(field_list))
    else:
        if not os.path.isdir(autocycler_dir):
            quit_with_error(f"directory does not exist: {autocycler_dir}")
        print(table_row(autocycler_dir, name, field_list, sigfigs))
