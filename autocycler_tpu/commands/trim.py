"""`autocycler trim`: remove start-end (circular) and hairpin (linear)
overlaps from each contig's unitig path.

Parity target: reference trim.rs. The weighted path-overlap DP lives in
ops.align (row-vectorised, exact); this module owns the trimming policy:
start-end trim cuts at the alignment's weighted midpoint, hairpin trims use
reverse-path alignment, the more successful trim type wins, length outliers
beyond --mad MADs are excluded, and the graph is rebuilt.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..metrics import TrimmedClusterMetrics
from ..models import Sequence, UnitigGraph
from ..models.simplify import merge_linear_paths
from ..obs import ledger, qc
from ..ops.align import GAP, Weights, find_midpoint, overlap_alignment
from ..utils import (check_threads, log, mad as mad_fn, map_threaded, median,
                     quit_with_error, reverse_signed_path)
from ..utils.timing import stage_timer

TrimResult = Optional[Tuple[List[int], int]]


def screen_decision(dp_screen, seq_id: int, kind: str
                    ) -> Tuple[bool, Optional[list]]:
    """Decode one dp_screen entry into (skip, precomputed_alignment).
    Protocol: False or [] → the DP provably/already returned no alignment
    (skip); a non-empty list → alignment pieces decoded from the device DP's
    packed traceback (use directly); True or absent → run the host DP."""
    value = True if dp_screen is None else dp_screen.get((seq_id, kind), True)
    if value is False or value == []:
        return True, None
    return False, value if isinstance(value, list) else None


def trim(cluster_dir, min_identity: float = 0.75, max_unitigs: int = 5000,
         mad: float = 5.0, threads: int = 1, dp_screen=None,
         preloaded=None) -> Tuple[UnitigGraph, List[Sequence]]:
    """dp_screen: optional {(seq_id, kind): value} where kind is 'start_end',
    'hairpin_start' or 'hairpin_end'. value False means a batched exact
    screen (ops.align.overlap_positive_batch) proved that DP returns no
    alignment, so it is skipped; a list means the DEVICE already ran the DP
    and the decoded alignment pieces are used directly (an empty list = the
    device DP found no qualifying alignment); True/absent runs the host DP.
    `autocycler batch` screens every isolate's DPs in one device dispatch
    and decodes positives from the device's packed traceback bits; results
    are bitwise identical to an unscreened run.
    preloaded: optional (graph, sequences) already parsed from
    1_untrimmed.gfa (batch parses it for screen-job construction and hands
    it over instead of re-reading the file)."""
    cluster_dir = Path(cluster_dir)
    untrimmed_gfa = cluster_dir / "1_untrimmed.gfa"
    trimmed_gfa = cluster_dir / "2_trimmed.gfa"
    trimmed_yaml = cluster_dir / "2_trimmed.yaml"
    if not cluster_dir.is_dir():
        quit_with_error(f"directory does not exist: {cluster_dir}")
    if not untrimmed_gfa.is_file():
        quit_with_error(f"file does not exist: {untrimmed_gfa}")
    if not 0.0 <= min_identity <= 1.0:
        quit_with_error("--min_identity must be between 0.0 and 1 (inclusive)")
    if mad < 0.0:
        quit_with_error("--mad cannot be less than 0")
    check_threads(threads)

    log.section_header("Starting autocycler trim")
    log.explanation("This command takes a single-cluster unitig graph (made by autocycler "
                    "cluster) and trims any overlaps. It looks for both start-end overlaps "
                    "(can occur with circular sequences) and hairpin overlaps (can occur "
                    "with linear sequences).")
    with stage_timer("trim/load"):
        graph, sequences = preloaded if preloaded is not None else \
            UnitigGraph.from_gfa_file(untrimmed_gfa)
        graph.print_basic_graph_info()
        # dense number -> length array: scalar indexing works like the dict
        # and the alignment kernels can gather whole paths in one vector op
        max_num = max((u.number for u in graph.unitigs), default=0)
        weights = np.zeros(max_num + 1, dtype=np.int64)
        for u in graph.unitigs:
            weights[u.number] = u.length()

        # one path query serves both trimming passes (the graph is unchanged
        # until choose_trim_type applies the results)
        all_paths = graph.get_unitig_paths_for_sequences(
            [s.id for s in sequences]) if max_unitigs else {}
    orig_lengths = {s.id: s.length for s in sequences}
    with stage_timer("trim/overlaps"):
        start_end = trim_start_end_overlap(graph, sequences, weights,
                                           min_identity, max_unitigs,
                                           all_paths, threads, dp_screen)
        hairpin = trim_hairpin_overlap(graph, sequences, weights, min_identity,
                                       max_unitigs, all_paths, threads,
                                       dp_screen)
        # mirror choose_trim_type's winner selection (start_end wins ties)
        # so QC records exactly the trims that were applied
        se_count = sum(r is not None for r in start_end)
        hp_count = sum(r is not None for r in hairpin)
        winner = start_end if se_count >= hp_count else hairpin
        chosen = [(s.id, r if (se_count or hp_count) else None)
                  for s, r in zip(sequences, winner)]
        sequences = choose_trim_type(start_end, hairpin, graph, sequences)
    with stage_timer("trim/outputs"):
        pre_exclude_ids = {s.id for s in sequences}
        sequences = exclude_outliers_in_length(graph, sequences, mad)
        excluded_ids = pre_exclude_ids - {s.id for s in sequences}
        clean_up_graph(graph, sequences)
        graph.save_gfa(trimmed_gfa, sequences)
        TrimmedClusterMetrics.new(
            [s.length for s in sequences]).save_to_yaml(trimmed_yaml)
    qc.trim_qc(cluster_dir.name, orig_lengths, se_count, hp_count, chosen,
               sequences, excluded_ids)
    ledger.record_stage("trim", inputs=[untrimmed_gfa],
                        outputs=[trimmed_gfa, trimmed_yaml],
                        cluster=cluster_dir.name)
    log.section_header("Finished!")
    log.message(f"Unitig graph of trimmed sequences: {trimmed_gfa}")
    log.message()
    # in-process callers (bench, batch) can hand this straight to
    # resolve(preloaded=...) and skip re-parsing 2_trimmed.gfa; the file
    # just written stays the checkpoint of record (saved GFA round-trips
    # to the identical graph, asserted by tests)
    return graph, sequences


def trim_start_end_overlap(graph: UnitigGraph, sequences: List[Sequence],
                           weights: Weights, min_identity: float,
                           max_unitigs: int, all_paths=None,
                           threads: int = 1, dp_screen=None) -> List[TrimResult]:
    """Per-sequence circular start-end trimming (reference trim.rs:113-136).
    A max_unitigs of 0 disables trimming."""
    if max_unitigs == 0:
        return [None] * len(sequences)
    if all_paths is None:
        all_paths = graph.get_unitig_paths_for_sequences([s.id for s in sequences])

    def one(seq: Sequence) -> TrimResult:
        skip, pre = screen_decision(dp_screen, seq.id, "start_end")
        if skip:
            return None
        path = [n if s else -n for n, s in all_paths[seq.id]]
        trimmed = trim_path_start_end(path, weights, min_identity,
                                      max_unitigs, precomputed=pre)
        if trimmed is None:
            return None
        return trimmed, sum(weights[abs(u)] for u in trimmed)

    # the DP work runs (possibly pooled) first; logging stays sequential so
    # the output order matches the reference's
    results = map_threaded(one, sequences, threads)
    for seq, result in zip(sequences, results):
        if result is not None:
            log.message(f"{seq}: trimmed to {result[1]} bp")
        else:
            log.message(f"{seq}: not trimmed")
    log.message()
    return results


def trim_hairpin_overlap(graph: UnitigGraph, sequences: List[Sequence],
                         weights: Weights, min_identity: float,
                         max_unitigs: int, all_paths=None,
                         threads: int = 1, dp_screen=None) -> List[TrimResult]:
    """Per-sequence hairpin trimming at both path ends (reference trim.rs:139-186)."""
    if max_unitigs == 0:
        return [None] * len(sequences)
    if all_paths is None:
        all_paths = graph.get_unitig_paths_for_sequences([s.id for s in sequences])

    def one(seq: Sequence):
        path = [n if s else -n for n, s in all_paths[seq.id]]
        trimmed_start = trimmed_end = False
        skip_s, pre_s = screen_decision(dp_screen, seq.id, "hairpin_start")
        p2 = None if skip_s else \
            trim_path_hairpin_start(path, weights, min_identity, max_unitigs,
                                    precomputed=pre_s)
        if p2 is not None:
            trimmed_start = True
        else:
            p2 = list(path)
        # the hairpin_end screen/traceback was computed on the ORIGINAL
        # path; it only applies when hairpin_start left the path unchanged
        skip_e, pre_e = screen_decision(dp_screen, seq.id, "hairpin_end")
        if not trimmed_start and skip_e:
            p3 = None
        else:
            p3 = trim_path_hairpin_end(
                p2, weights, min_identity, max_unitigs,
                precomputed=pre_e if not trimmed_start else None)
        if p3 is not None:
            trimmed_end = True
        else:
            p3 = p2
        return p3, trimmed_start, trimmed_end

    results: List[TrimResult] = []
    for seq, (p3, trimmed_start, trimmed_end) in zip(
            sequences, map_threaded(one, sequences, threads)):
        if not trimmed_start and not trimmed_end:
            results.append(None)
            log.message(f"{seq}: not trimmed")
        else:
            length = sum(weights[abs(u)] for u in p3)
            results.append((p3, length))
            where = ("start and end" if trimmed_start and trimmed_end
                     else "start" if trimmed_start else "end")
            log.message(f"{seq}: trimmed from {where} to {length} bp")
    log.message()
    return results


def choose_trim_type(start_end_results: List[TrimResult],
                     hairpin_results: List[TrimResult], graph: UnitigGraph,
                     sequences: List[Sequence]) -> List[Sequence]:
    """Keep whichever trim type succeeded on more sequences, rebuild trimmed
    sequences' positions in the graph (reference trim.rs:189-226)."""
    start_end_count = sum(r is not None for r in start_end_results)
    hairpin_count = sum(r is not None for r in hairpin_results)
    if start_end_count == 0 and hairpin_count == 0:
        return list(sequences)
    results = start_end_results if start_end_count >= hairpin_count else hairpin_results
    # one batched removal + one batched stamping for ALL trimmed sequences
    graph.remove_sequences_from_graph(
        [seq.id for seq, r in zip(sequences, results) if r is not None])
    trimmed_sequences = []
    entries = []
    for seq, result in zip(sequences, results):
        if result is None:
            trimmed_sequences.append(seq)
        else:
            path, length = result
            arr = np.asarray(path, np.int64)
            entries.append((seq.id, length, np.abs(arr), arr > 0))
            trimmed_sequences.append(Sequence.without_seq(
                seq.id, seq.filename, seq.contig_header, length, seq.cluster))
    graph.stamp_paths_batch(entries)
    return trimmed_sequences


def exclude_outliers_in_length(graph: UnitigGraph, sequences: List[Sequence],
                               mad_threshold: float) -> List[Sequence]:
    """Exclude sequences outside median ± mad_threshold·MAD
    (reference trim.rs:229-257); 0 disables."""
    if mad_threshold == 0.0:
        return list(sequences)
    lengths = [s.length for s in sequences]
    med = median(lengths)
    deviation = mad_fn(lengths)
    min_length = round(med - deviation * mad_threshold)
    max_length = round(med + deviation * mad_threshold)
    log.message(f"Median sequence length:    {med} bp")
    log.message(f"Median absolute deviation: {deviation} bp")
    log.message(f"Allowed length range:      {min_length}-{max_length} bp")
    log.message()
    kept, excluded = [], []
    for seq in sequences:
        if min_length <= seq.length <= max_length:
            kept.append(seq)
            log.message(f"{seq}: kept")
        else:
            log.message(f"{seq}: excluded")
            excluded.append(seq.id)
    graph.remove_sequences_from_graph(excluded)
    log.message()
    return kept


def clean_up_graph(graph: UnitigGraph, sequences: List[Sequence]) -> None:
    """Recalculate depths, drop zero-depth unitigs, merge linear paths and
    renumber (reference trim.rs:260-269)."""
    graph.recalculate_depths()
    graph.remove_zero_depth_unitigs()
    merge_linear_paths(graph, sequences)
    graph.print_basic_graph_info()
    graph.renumber_unitigs()


# ---------------- path-level trimming ----------------

def trim_path_start_end(path: List[int], weights: Weights, min_identity: float,
                        max_unitigs: int,
                        precomputed: Optional[list] = None
                        ) -> Optional[List[int]]:
    """Detect a start-end overlap by aligning the path against itself (off-
    diagonal) and cut at the weighted midpoint (reference trim.rs:288-296).
    ``precomputed``: alignment pieces already decoded from the device DP's
    packed traceback (ops.align.overlap_tracebacks_batch)."""
    alignment = precomputed if precomputed is not None else \
        overlap_alignment(path, path, weights, min_identity, max_unitigs, True)
    if not alignment:
        return None
    midpoint = find_midpoint(alignment, weights)
    start = alignment[midpoint].a_index
    end = alignment[midpoint].b_index
    return list(path[start:end])


def trim_path_hairpin_end(path: List[int], weights: Weights,
                          min_identity: float, max_unitigs: int,
                          precomputed: Optional[list] = None
                          ) -> Optional[List[int]]:
    """Detect a hairpin overlap at the path end by aligning the reverse path
    against the path (reference trim.rs:299-317). ``precomputed``: device-
    decoded pieces for the (reverse path, path) alignment; the walk below
    pops pieces, so a copy is taken."""
    rev_path = reverse_signed_path(path)
    alignment = list(precomputed) if precomputed is not None else \
        overlap_alignment(rev_path, path, weights, min_identity, max_unitigs,
                          False)
    if not alignment:
        return None
    end = 0
    while alignment:
        while alignment and alignment[0].a_unitig == GAP:
            alignment.pop(0)
        while alignment and alignment[-1].b_unitig == GAP:
            alignment.pop()
        if not alignment:
            break
        back = alignment.pop()
        if alignment:
            assert back.b_unitig == -alignment[0].a_unitig
        if back.a_unitig != GAP:
            end = back.b_index
        if alignment:
            alignment.pop(0)
    return list(path[:end])


def trim_path_hairpin_start(path: List[int], weights: Weights,
                            min_identity: float, max_unitigs: int,
                            precomputed: Optional[list] = None
                            ) -> Optional[List[int]]:
    """Hairpin trim at the path start = hairpin-end trim of the reverse path
    (reference trim.rs:320-326). ``precomputed`` is the device-decoded
    (path, reverse path) alignment — exactly what the inner hairpin-end call
    computes for the reverse path."""
    rev_path = reverse_signed_path(path)
    trimmed = trim_path_hairpin_end(rev_path, weights, min_identity,
                                    max_unitigs, precomputed=precomputed)
    if trimmed is None:
        return None
    return reverse_signed_path(trimmed)
