"""Per-stage metrics, serialized to YAML and aggregated by `autocycler table`.

Parity target: reference metrics.rs:24-273 — one dataclass per pipeline stage,
a save_to_yaml helper and get_field_names reflection used by the table
command. YAML is emitted without external dependencies (the structures are
simple: scalars, lists, nested records).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, List, Tuple

from .utils import mad, median


def _yaml_scalar(v) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float):
        return repr(v)
    if isinstance(v, str):
        if v == "" or any(c in v for c in ":#{}[],&*!|>'\"%@`") or v.strip() != v:
            return "'" + v.replace("'", "''") + "'"
        return v
    return str(v)


def _to_yaml(obj, indent: int = 0) -> List[str]:
    pad = "  " * indent
    lines: List[str] = []
    if dataclasses.is_dataclass(obj):
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            if dataclasses.is_dataclass(v):
                lines.append(f"{pad}{f.name}:")
                lines.extend(_to_yaml(v, indent + 1))
            elif isinstance(v, list):
                if not v:
                    lines.append(f"{pad}{f.name}: []")
                else:
                    lines.append(f"{pad}{f.name}:")
                    for item in v:
                        if dataclasses.is_dataclass(item):
                            # "- " occupies one indent level, so the item's
                            # remaining keys keep the same column as its first
                            sub = _to_yaml(item, indent + 1)
                            lines.append(f"{pad}- {sub[0].strip()}")
                            lines.extend(sub[1:])
                        else:
                            lines.append(f"{pad}- {_yaml_scalar(item)}")
            else:
                lines.append(f"{pad}{f.name}: {_yaml_scalar(v)}")
    return lines


class MetricsBase:
    def save_to_yaml(self, filename) -> None:
        with open(filename, "w") as f:
            f.write("\n".join(_to_yaml(self)) + "\n")

    @classmethod
    def get_field_names(cls) -> List[str]:
        return sorted(f.name for f in dataclasses.fields(cls))


@dataclass
class ReadSetDetails(MetricsBase):
    count: int = 0
    bases: int = 0
    n50: int = 0

    @classmethod
    def from_sorted_lengths(cls, sorted_read_lengths: List[int]) -> "ReadSetDetails":
        """N50 over lengths sorted descending (reference metrics.rs:43-60)."""
        bases = sum(sorted_read_lengths)
        target = bases // 2
        running, n50 = 0, 0
        for length in sorted_read_lengths:
            running += length
            if running >= target:
                n50 = length
                break
        return cls(count=len(sorted_read_lengths), bases=bases, n50=n50)


@dataclass
class SubsampleMetrics(MetricsBase):
    input_read_count: int = 0
    input_read_bases: int = 0
    input_read_n50: int = 0
    # which shuffle produced the read partition: "rust-stdrng-0.9" =
    # reproduction-exact vs the reference for the same seed
    # (utils/rust_rand.py); "python-fisher-yates" = the documented-divergent
    # fallback. Stamped so users can detect partition compatibility.
    shuffle: str = ""
    output_reads: List[ReadSetDetails] = field(default_factory=list)


@dataclass
class InputContigDetails(MetricsBase):
    name: str = ""
    description: str = ""
    length: int = 0


@dataclass
class InputAssemblyDetails(MetricsBase):
    filename: str = ""
    contigs: List[InputContigDetails] = field(default_factory=list)


@dataclass
class InputAssemblyMetrics(MetricsBase):
    input_assemblies_count: int = 0
    input_assemblies_total_contigs: int = 0
    input_assemblies_total_length: int = 0
    compressed_unitig_count: int = 0
    compressed_unitig_total_length: int = 0
    input_assembly_details: List[InputAssemblyDetails] = field(default_factory=list)


@dataclass
class ClusteringMetrics(MetricsBase):
    pass_cluster_count: int = 0
    fail_cluster_count: int = 0
    pass_contig_count: int = 0
    fail_contig_count: int = 0
    pass_contig_fraction: float = 0.0
    fail_contig_fraction: float = 0.0
    cluster_balance_score: float = 0.0
    cluster_tightness_score: float = 0.0
    overall_clustering_score: float = 0.0

    def calculate_fractions(self) -> None:
        total = self.pass_contig_count + self.fail_contig_count
        if total > 0:
            self.pass_contig_fraction = self.pass_contig_count / total
            self.fail_contig_fraction = self.fail_contig_count / total

    def calculate_scores(self, cluster_filenames: Dict[int, List[str]],
                         pass_cluster_stats: List[Tuple[float, int]]) -> None:
        self.calculate_balance(cluster_filenames)
        self.calculate_tightness(pass_cluster_stats)
        self.overall_clustering_score = (self.cluster_balance_score
                                         + self.cluster_tightness_score) / 2.0

    def calculate_balance(self, cluster_filenames: Dict[int, List[str]]) -> None:
        """How evenly input files are distributed over clusters: per cluster,
        each known filename scores 1.0 iff it appears exactly once; cluster
        scores are size-weighted-averaged (reference metrics.rs:140-168)."""
        all_filenames = {f for cluster in cluster_filenames.values() for f in cluster}
        if not all_filenames:
            self.cluster_balance_score = 0.0
            return
        weighted_sum, total_weight = 0.0, 0.0
        for cluster in cluster_filenames.values():
            counts: Dict[str, int] = {}
            for f in cluster:
                counts[f] = counts.get(f, 0) + 1
            score = sum(1.0 if counts.get(f, 0) == 1 else 0.0
                        for f in all_filenames) / len(all_filenames)
            weighted_sum += score * len(cluster)
            total_weight += len(cluster)
        self.cluster_balance_score = weighted_sum / total_weight

    def calculate_tightness(self, pass_cluster_stats: List[Tuple[float, int]]) -> None:
        """Size-weighted mean of 1 - sqrt(cluster distance)
        (reference metrics.rs:170-187)."""
        if not pass_cluster_stats:
            self.cluster_tightness_score = 0.0
            return
        weighted_sum = sum((1.0 - distance ** 0.5) * size
                           for distance, size in pass_cluster_stats)
        total_weight = sum(size for _, size in pass_cluster_stats)
        self.cluster_tightness_score = weighted_sum / total_weight


@dataclass
class UntrimmedClusterMetrics(MetricsBase):
    untrimmed_cluster_size: int = 0
    untrimmed_cluster_lengths: List[int] = field(default_factory=list)
    untrimmed_cluster_median: int = 0
    untrimmed_cluster_mad: int = 0
    untrimmed_cluster_distance: float = 0.0

    @classmethod
    def new(cls, sequence_lengths: List[int], distance: float):
        return cls(untrimmed_cluster_size=len(sequence_lengths),
                   untrimmed_cluster_lengths=sequence_lengths,
                   untrimmed_cluster_median=median(sequence_lengths),
                   untrimmed_cluster_mad=mad(sequence_lengths),
                   untrimmed_cluster_distance=distance)


@dataclass
class TrimmedClusterMetrics(MetricsBase):
    trimmed_cluster_size: int = 0
    trimmed_cluster_lengths: List[int] = field(default_factory=list)
    trimmed_cluster_median: int = 0
    trimmed_cluster_mad: int = 0

    @classmethod
    def new(cls, sequence_lengths: List[int]):
        return cls(trimmed_cluster_size=len(sequence_lengths),
                   trimmed_cluster_lengths=sequence_lengths,
                   trimmed_cluster_median=median(sequence_lengths),
                   trimmed_cluster_mad=mad(sequence_lengths))


@dataclass
class ResolvedClusterDetails(MetricsBase):
    length: int = 0
    unitigs: int = 0
    topology: str = ""


@dataclass
class CombineMetrics(MetricsBase):
    consensus_assembly_bases: int = 0
    consensus_assembly_unitigs: int = 0
    consensus_assembly_fully_resolved: bool = False
    consensus_assembly_clusters: List[ResolvedClusterDetails] = field(default_factory=list)
