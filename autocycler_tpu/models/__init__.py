from .position import Position, PositionArray
from .sequence import Sequence
from .unitig import Unitig, UnitigStrand, UnitigType
from .unitig_graph import UnitigGraph
