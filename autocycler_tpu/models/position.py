"""Occurrences of a graph element within input contigs.

Parity target: reference position.rs:19-56, which bit-packs seq_id (15 bits)
and strand (1 bit) into a u16 plus a u32 position, stored in per-unitig Vecs.
Here the model is struct-of-arrays: every unitig strand carries ONE
:class:`PositionArray` (parallel seq_id/strand/pos numpy arrays), so whole-
graph sweeps (path reconstruction, depth recalculation, sequence removal) are
vector ops instead of per-occurrence object traversals. :class:`Position` is
the ergonomic single-occurrence view, kept for display and tests. The
32767-sequence cap from the reference's bit packing is enforced at load time
(reference compress.rs:112-114).
"""

from __future__ import annotations

from typing import Iterator, List

import numpy as np

MAX_SEQ_ID = 32767  # 15-bit packing limit, reference position.rs:21 + compress.rs:112-114


class Position:
    __slots__ = ("seq_id", "strand", "pos")

    def __init__(self, seq_id: int, strand: bool, pos: int):
        self.seq_id = seq_id
        self.strand = strand
        self.pos = pos

    def __repr__(self) -> str:
        return f"{self.seq_id}{'+' if self.strand else '-'}{self.pos}"

    def __eq__(self, other) -> bool:
        return (self.seq_id, self.strand, self.pos) == (other.seq_id, other.strand, other.pos)

    def __hash__(self) -> int:
        return hash((self.seq_id, self.strand, self.pos))

    def copy(self) -> "Position":
        return Position(self.seq_id, self.strand, self.pos)


_EMPTY_I32 = np.zeros(0, np.int32)
_EMPTY_BOOL = np.zeros(0, bool)
_EMPTY_I64 = np.zeros(0, np.int64)


class PositionArray:
    """SoA of occurrences: parallel ``seq_id`` (int32), ``strand`` (bool) and
    ``pos`` (int64) arrays. Replaces the reference's Vec<Position> per unitig
    strand (unitig.rs:38-39). Arrays may be views into a graph-level batch
    (built by UnitigGraph's vectorised path stamping); in-place edits only
    ever touch this unitig's own slice."""

    __slots__ = ("seq_id", "strand", "pos")

    def __init__(self, seq_id: np.ndarray = None, strand: np.ndarray = None,
                 pos: np.ndarray = None):
        self.seq_id = _EMPTY_I32 if seq_id is None else seq_id
        self.strand = _EMPTY_BOOL if strand is None else strand
        self.pos = _EMPTY_I64 if pos is None else pos

    @classmethod
    def from_list(cls, positions: List[Position]) -> "PositionArray":
        return cls(np.array([p.seq_id for p in positions], np.int32),
                   np.array([p.strand for p in positions], bool),
                   np.array([p.pos for p in positions], np.int64))

    def __len__(self) -> int:
        return len(self.seq_id)

    def __iter__(self) -> Iterator[Position]:
        for i in range(len(self.seq_id)):
            yield Position(int(self.seq_id[i]), bool(self.strand[i]),
                           int(self.pos[i]))

    def __getitem__(self, i: int) -> Position:
        return Position(int(self.seq_id[i]), bool(self.strand[i]),
                        int(self.pos[i]))

    def __repr__(self) -> str:
        return f"[{', '.join(repr(p) for p in self)}]"

    def copy(self) -> "PositionArray":
        return PositionArray(self.seq_id.copy(), self.strand.copy(),
                             self.pos.copy())

    def shift_pos(self, amount: int) -> None:
        """Add ``amount`` to every position (sequence-edit bookkeeping,
        reference unitig.rs:216-248). Writes in place (own slice only)."""
        if len(self.pos):
            self.pos += amount

    def without_seq_ids(self, seq_ids, lut: np.ndarray = None
                        ) -> "PositionArray":
        """Occurrences not belonging to any of the given sequence ids
        (reference unitig.rs:250-257). Pass an int32 ndarray when calling in
        a loop — it goes through without conversion — or a ``seq_id_lut``
        for the one-gather fast path."""
        if not len(self.seq_id):
            return self
        if lut is not None:
            keep = ~lut[self.seq_id]
        else:
            if not isinstance(seq_ids, np.ndarray):
                seq_ids = np.asarray(list(seq_ids), np.int32)
            keep = ~np.isin(self.seq_id, seq_ids)
        if keep.all():
            return self
        return PositionArray(self.seq_id[keep], self.strand[keep],
                             self.pos[keep])

    def only_seq_ids(self, seq_ids: np.ndarray, lut: np.ndarray = None
                     ) -> "PositionArray":
        """Copy holding only occurrences of the given (int32 ndarray) ids.
        Always copies, so the result mutates independently of this array.
        ``lut`` (bool array indexed by seq id) skips the per-call set
        machinery — callers filtering many position lists against the same
        id set (one LUT gather per list vs np.isin's sort per call) should
        build it once with :func:`seq_id_lut`."""
        if not len(self.seq_id):
            return PositionArray()
        m = lut[self.seq_id] if lut is not None else np.isin(self.seq_id, seq_ids)
        return PositionArray(self.seq_id[m], self.strand[m], self.pos[m])

    @staticmethod
    def seq_id_lut(seq_ids) -> np.ndarray:
        """Bool LUT (indexed by seq id) for :meth:`only_seq_ids` /
        :meth:`without_seq_ids` loops. Sized to the full sequence-id space
        (ids are capped at 32767, compress.rs:112-114) so indexing with ANY
        stored seq id is in range regardless of the filter set."""
        ids = np.asarray(list(seq_ids) if not isinstance(seq_ids, np.ndarray)
                         else seq_ids, np.int64)
        lut = np.zeros(MAX_SEQ_ID + 1, bool)
        lut[ids] = True
        return lut

    def concat(self, other: "PositionArray") -> "PositionArray":
        if not len(other):
            return self
        if not len(self):
            return other
        return PositionArray(np.concatenate([self.seq_id, other.seq_id]),
                             np.concatenate([self.strand, other.strand]),
                             np.concatenate([self.pos, other.pos]))
