"""Occurrence of a graph element within an input contig.

Parity target: reference position.rs:19-56, which bit-packs seq_id (15 bits)
and strand (1 bit) into a u16 plus a u32 position. On the device side we use a
struct-of-arrays int32 layout instead (ops.kmers); this host-side class is the
ergonomic single-occurrence view. The 32767-sequence cap from the bit packing
is enforced at load time (reference compress.rs:112-114).
"""

from __future__ import annotations

MAX_SEQ_ID = 32767  # 15-bit packing limit, reference position.rs:21 + compress.rs:112-114


class Position:
    __slots__ = ("seq_id", "strand", "pos")

    def __init__(self, seq_id: int, strand: bool, pos: int):
        self.seq_id = seq_id
        self.strand = strand
        self.pos = pos

    def __repr__(self) -> str:
        return f"{self.seq_id}{'+' if self.strand else '-'}{self.pos}"

    def __eq__(self, other) -> bool:
        return (self.seq_id, self.strand, self.pos) == (other.seq_id, other.strand, other.pos)

    def __hash__(self) -> int:
        return hash((self.seq_id, self.strand, self.pos))

    def copy(self) -> "Position":
        return Position(self.seq_id, self.strand, self.pos)
