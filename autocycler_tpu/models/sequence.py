"""Input-contig record with dot padding and header directives.

Parity target: reference sequence.rs:20-110.
- Sequences are padded with half-k dots on each end so terminal k-mers exist;
  dots act as wildcards during sequence-end repair (sequence.rs:31-59).
- FASTA header directives configure behaviour in-band (sequence.rs:89-109):
  Autocycler_trusted / Autocycler_ignore / Autocycler_cluster_weight= /
  Autocycler_consensus_weight= (all case-insensitive).
"""

from __future__ import annotations

import numpy as np

from ..utils import quit_with_error, reverse_complement_bytes, up_to_first_space, after_first_space

# byte-value lookup beats np.isin's sort-based path on Mbp arrays
_IS_ACGT = np.zeros(256, dtype=bool)
_IS_ACGT[np.frombuffer(b"ACGT", dtype=np.uint8)] = True

_ACGT = frozenset(b"ACGT")


def padded_strand(seq: str, filename: str, half_k: int) -> np.ndarray:
    """Validated, dot-padded forward strand bytes for one contig — the
    sequence-independent half of :meth:`Sequence.with_seq`, shared with the
    parallel loader (which builds strands in worker tasks before sequence
    ids exist) and the parse cache."""
    raw = np.frombuffer(seq.encode(), dtype=np.uint8)
    if not _IS_ACGT[raw].all():
        quit_with_error(f"{filename} contains non-ACGT characters")
    pad = np.full(half_k, ord("."), dtype=np.uint8)
    return np.concatenate([pad, raw, pad])


class Sequence:
    __slots__ = ("id", "_forward_seq", "_reverse_seq", "filename",
                 "contig_header", "length", "cluster", "_strand_codes")

    def __init__(self, id: int, forward_seq: np.ndarray, reverse_seq: np.ndarray,
                 filename: str, contig_header: str, length: int, cluster: int = 0):
        self.id = id
        self._strand_codes = None
        self.forward_seq = forward_seq      # uint8 array, dot-padded (may be empty)
        self.reverse_seq = reverse_seq
        self.filename = filename
        self.contig_header = contig_header
        self.length = length                # unpadded length
        self.cluster = cluster

    # the strand bytes are exposed through properties so reassignment (e.g.
    # sequence-end repair swapping in repaired strands) invalidates the
    # cached encoding — a length check would miss same-length rewrites
    @property
    def forward_seq(self) -> np.ndarray:
        return self._forward_seq

    @forward_seq.setter
    def forward_seq(self, value: np.ndarray) -> None:
        self._forward_seq = value
        self._strand_codes = None

    @property
    def reverse_seq(self) -> np.ndarray:
        return self._reverse_seq

    @reverse_seq.setter
    def reverse_seq(self, value: np.ndarray) -> None:
        self._reverse_seq = value
        self._strand_codes = None

    def encoded_strands(self):
        """(forward codes, reverse codes) of the padded strands, encoded at
        most once per sequence: the reverse strand is the arithmetic
        code-space reverse complement of the forward encoding (identical to
        encoding ``reverse_seq``, since reverse_seq is always the byte-space
        reverse complement of forward_seq)."""
        if self._strand_codes is None:
            from ..ops.encode import encode_both_strands
            self._strand_codes = encode_both_strands(self._forward_seq)
        return self._strand_codes

    @classmethod
    def with_seq(cls, id: int, seq: str, filename: str, contig_header: str,
                 half_k: int) -> "Sequence":
        """Construct with the actual sequence stored, dot-padded by half_k on
        both ends (reference sequence.rs:31-59)."""
        forward = padded_strand(seq, filename, half_k)
        return cls.from_padded_forward(id, forward, filename, contig_header,
                                       len(seq))

    @classmethod
    def from_padded_forward(cls, id: int, forward: np.ndarray, filename: str,
                            contig_header: str, length: int) -> "Sequence":
        """Construct from an already-validated padded forward strand (the
        parallel loader and the parse cache land here); the reverse strand
        is always re-derived, so cached bytes cannot desynchronise."""
        return cls(id, forward, reverse_complement_bytes(forward), filename,
                   contig_header, length)

    @classmethod
    def without_seq(cls, id: int, filename: str, contig_header: str, length: int,
                    cluster: int = 0) -> "Sequence":
        """Construct without sequence bytes — used once the sequence lives in
        the UnitigGraph (reference sequence.rs:61-75)."""
        empty = np.zeros(0, dtype=np.uint8)
        return cls(id, empty, empty, filename, contig_header, length, cluster)

    def contig_name(self) -> str:
        return up_to_first_space(self.contig_header)

    def contig_description(self) -> str:
        return after_first_space(self.contig_header)

    def string_for_newick(self) -> str:
        return f"{self.id}__{self.filename}__{self.contig_name()}__{self.length}_bp"

    def is_trusted(self) -> bool:
        return "autocycler_trusted" in self.contig_header.lower()

    def is_ignored(self) -> bool:
        return "autocycler_ignore" in self.contig_header.lower()

    def _weight_directive(self, prefix: str) -> int:
        for token in self.contig_header.lower().split():
            if token.startswith(prefix):
                value = token[len(prefix):]
                try:
                    n = int(value)
                except ValueError:
                    continue
                if n >= 0:
                    return n
        return 1

    def cluster_weight(self) -> int:
        return self._weight_directive("autocycler_cluster_weight=")

    def consensus_weight(self) -> int:
        return self._weight_directive("autocycler_consensus_weight=")

    def __str__(self) -> str:
        extras = []
        if self.is_trusted():
            extras.append("trusted")
        if self.is_ignored():
            extras.append("ignored")
        if self.cluster_weight() != 1:
            extras.append(f"cluster weight = {self.cluster_weight()}")
        if self.consensus_weight() != 1:
            extras.append(f"consensus weight = {self.consensus_weight()}")
        base = f"{self.filename} {self.contig_name()} ({self.length} bp)"
        return f"{base} [{', '.join(extras)}]" if extras else base

    __repr__ = __str__
