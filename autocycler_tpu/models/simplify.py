"""Graph-structure simplification: repeat expansion and linear-path merging.

Parity target: reference graph_simplification.rs.
- expand_repeats (:43-86) shifts common flanking sequence from branch unitigs
  into the shared repeat unitig until a fixpoint, e.g.

      ACTACTCAACT                    ACTACTC
                 \\                          \\
                  ATCGACTACGCTACG  ->         AACTATCGACTACGCTACGGCTA ...
                 /                          /
      GACTACGAACT                    GACTACG

  guarded so sequence paths keep unique start/end unitigs (:89-230).
- merge_linear_paths (:315-371) collapses 1-in/1-out chains, preserving path
  endpoints, circular loops and self-links.

These run on the host: the mutation pattern is irregular, but the sequences
being shuffled are numpy views so there is no byte copying beyond the edits.
"""

from __future__ import annotations

from typing import List, Set, Tuple

import numpy as np

from ..utils import FORWARD, REVERSE
from .sequence import Sequence
from .unitig import Unitig, UnitigStrand, UnitigType
from .unitig_graph import UnitigGraph


def simplify_structure(graph: UnitigGraph, seqs: List[Sequence]) -> None:
    """expand_repeats to fixpoint, then renumber
    (reference graph_simplification.rs:26-40).

    The fixed start/end sets are computed once: shifting sequence between
    unitigs never adds, removes or reorders path entries (and links are
    untouched), so the sets are invariant across iterations — the reference
    recomputes them each sweep with the same result."""
    fixed = get_fixed_unitig_starts_and_ends(graph, seqs)
    candidates = None  # first sweep visits everything
    while True:
        shifted, affected = _expand_repeats_pass(graph, seqs, fixed, candidates)
        if shifted == 0:
            break
        candidates = affected
    graph.renumber_unitigs()


def expand_repeats(graph: UnitigGraph, seqs: List[Sequence], fixed=None) -> int:
    """One full sweep of repeat expansion; returns total bases shifted
    (reference graph_simplification.rs:43-86)."""
    if fixed is None:
        fixed = get_fixed_unitig_starts_and_ends(graph, seqs)
    return _expand_repeats_pass(graph, seqs, fixed, None)[0]


def _expand_repeats_pass(graph: UnitigGraph, seqs: List[Sequence], fixed,
                         candidates) -> Tuple[int, Set[int]]:
    """One sweep in graph order; returns (bases shifted, the running
    ``affected`` set — every unitig a shift touched plus its immediate
    neighbours).

    ``candidates`` (None = visit all) restricts the sweep: a unitig is
    visited when it is in ``candidates`` OR already in ``affected`` (a shift
    EARLIER IN THIS SWEEP touched its neighbourhood). This reproduces the
    reference's re-sweep-everything fixpoint (graph_simplification.rs:33-39)
    exactly: a unitig's outcome depends only on its own seq/positions and
    its sources' (all within one link), so a skipped unitig — one no shift
    has touched since it last evaluated to 0 — would evaluate to 0 again,
    and every potentially non-zero unitig is visited at the same position
    in the same sweep as the reference's full sweep would visit it (units
    enabled mid-sweep by an earlier shift enter ``affected`` immediately;
    units before the enabling shift are re-visited next sweep, when the
    reference also re-visits them)."""
    fixed_starts, fixed_ends = fixed
    total_shifted = 0
    affected: Set[int] = set()

    def note_shift(centre: int, sources) -> None:
        touched = [centre] + [s.number for s in sources]
        affected.update(touched)
        for n in touched:
            u = graph.index[n]
            for links in (u.forward_next, u.forward_prev,
                          u.reverse_next, u.reverse_prev):
                affected.update(l.number for l in links)

    for unitig in graph.unitigs:
        number = unitig.number
        if (candidates is not None and number not in candidates
                and number not in affected):
            continue
        inputs = get_exclusive_inputs(unitig)
        if len(inputs) >= 2 and number not in fixed_starts:
            can_shift = all(
                not (inp.strand and inp.number in fixed_ends
                     or not inp.strand and inp.number in fixed_starts)
                for inp in inputs)
            if can_shift:
                amount = _shift_seq_into_start(inputs, unitig)
                if amount:
                    total_shifted += amount
                    note_shift(number, inputs)
        outputs = get_exclusive_outputs(unitig)
        if len(outputs) >= 2 and number not in fixed_ends:
            can_shift = all(
                not (out.strand and out.number in fixed_starts
                     or not out.strand and out.number in fixed_ends)
                for out in outputs)
            if can_shift:
                amount = _shift_seq_into_end(unitig, outputs)
                if amount:
                    total_shifted += amount
                    note_shift(number, outputs)
    return total_shifted, affected


def _shift_seq_into_start(sources: List[UnitigStrand], destination: Unitig) -> int:
    """Move common end-sequence of sources onto the destination's start
    (reference shift_sequence_1, graph_simplification.rs:89-119)."""
    common = _common_end_seq(sources)
    common = _avoid_zero_len_unitigs(common, sources, trim_from_start=True)
    common = _avoid_start_of_path(common, destination, trim_from_start=True)
    amount = len(common)
    if amount == 0:
        return 0
    for source in sources:
        if source.strand:
            source.unitig.remove_seq_from_end(amount)
        else:
            source.unitig.remove_seq_from_start(amount)
    destination.add_seq_to_start(common)
    return amount


def _shift_seq_into_end(destination: Unitig, sources: List[UnitigStrand]) -> int:
    """Move common start-sequence of sources onto the destination's end
    (reference shift_sequence_2, graph_simplification.rs:122-142)."""
    common = _common_start_seq(sources)
    common = _avoid_zero_len_unitigs(common, sources, trim_from_start=False)
    common = _avoid_start_of_path(common, destination, trim_from_start=False)
    amount = len(common)
    if amount == 0:
        return 0
    for source in sources:
        if source.strand:
            source.unitig.remove_seq_from_start(amount)
        else:
            source.unitig.remove_seq_from_end(amount)
    destination.add_seq_to_end(common)
    return amount


def _avoid_zero_len_unitigs(common: np.ndarray, sources: List[UnitigStrand],
                            trim_from_start: bool) -> np.ndarray:
    """Trim the common sequence so no source unitig reaches zero length;
    doubled requirement when a unitig appears in sources on both strands
    (reference graph_simplification.rs:145-161)."""
    if len(common) == 0:
        return common
    numbers = [s.number for s in sources]
    dup = 2 if len(set(numbers)) != len(numbers) else 1
    min_len = min(s.length() for s in sources)
    while len(common) and min_len <= len(common) * dup:
        common = common[1:] if trim_from_start else common[:-1]
    return common


def _avoid_start_of_path(common: np.ndarray, dest: Unitig,
                         trim_from_start: bool) -> np.ndarray:
    """Trim the common sequence so no destination position reaches the start
    of a path (reference graph_simplification.rs:164-181)."""
    if len(common) == 0:
        return common
    positions = dest.forward_positions if trim_from_start else dest.reverse_positions
    if len(positions):
        # the while-loop's fixpoint is min_pos > len(common); min is invariant
        min_pos = int(positions.pos.min())
        keep = min(len(common), max(0, min_pos - 1))
        common = common[len(common) - keep:] if trim_from_start else common[:keep]
    return common


def get_fixed_unitig_starts_and_ends(graph: UnitigGraph, sequences: List[Sequence]
                                     ) -> Tuple[Set[int], Set[int]]:
    """Unitigs whose start/end (forward-strand terms) must not change because
    a sequence path begins or ends there, plus their immediate neighbours
    (reference graph_simplification.rs:190-230)."""
    fixed_starts: Set[int] = set()
    fixed_ends: Set[int] = set()
    paths = graph.get_unitig_paths_for_sequences([s.id for s in sequences])
    for seq in sequences:
        path = paths[seq.id]
        if not path:
            continue
        first_unitig, first_strand = path[0]
        (fixed_starts if first_strand else fixed_ends).add(first_unitig)
        last_unitig, last_strand = path[-1]
        (fixed_ends if last_strand else fixed_starts).add(last_unitig)

    for u in list(fixed_starts):
        for upstream in graph.index[u].forward_prev:
            (fixed_ends if upstream.strand else fixed_starts).add(upstream.number)
    for u in list(fixed_ends):
        for downstream in graph.index[u].forward_next:
            (fixed_starts if downstream.strand else fixed_ends).add(downstream.number)
    return fixed_starts, fixed_ends


def get_exclusive_inputs(unitig: Unitig) -> List[UnitigStrand]:
    """Unitigs that feed ONLY into the given unitig; empty when any input is
    shared or is the unitig itself (reference graph_simplification.rs:233-255)."""
    inputs = []
    for prev in unitig.forward_prev:
        nxt = prev.unitig.forward_next if prev.strand else prev.unitig.reverse_next
        if not (len(nxt) == 1 and nxt[0].strand and nxt[0].number == unitig.number):
            return []
        inputs.append(UnitigStrand(prev.unitig, prev.strand))
    if any(inp.number == unitig.number for inp in inputs):
        return []
    return inputs


def get_exclusive_outputs(unitig: Unitig) -> List[UnitigStrand]:
    """Unitigs the given unitig feeds into exclusively
    (reference graph_simplification.rs:258-280)."""
    outputs = []
    for nxt in unitig.forward_next:
        prevs = nxt.unitig.forward_prev if nxt.strand else nxt.unitig.reverse_prev
        if not (len(prevs) == 1 and prevs[0].strand and prevs[0].number == unitig.number):
            return []
        outputs.append(UnitigStrand(nxt.unitig, nxt.strand))
    if any(out.number == unitig.number for out in outputs):
        return []
    return outputs


def _common_start_seq(unitigs: List[UnitigStrand]) -> np.ndarray:
    """Longest common prefix of the unitigs' strand-specific sequences
    (reference graph_simplification.rs:283-295). Probes only a
    min-length window of each strand (seq_prefix), never the full
    reverse-strand sequence."""
    if not unitigs:
        return np.zeros(0, np.uint8)
    prefix_len = min(u.length() for u in unitigs)
    first = unitigs[0].seq_prefix(prefix_len)
    for u in unitigs[1:]:
        if prefix_len == 0:
            break
        s = u.seq_prefix(prefix_len)
        neq = np.nonzero(first[:prefix_len] != s)[0]
        if len(neq):
            prefix_len = int(neq[0])
    return first[:prefix_len].copy()


def _common_end_seq(unitigs: List[UnitigStrand]) -> np.ndarray:
    """Longest common suffix (reference graph_simplification.rs:298-312),
    windowed like :func:`_common_start_seq`."""
    if not unitigs:
        return np.zeros(0, np.uint8)
    suffix_len = min(u.length() for u in unitigs)
    first = unitigs[0].seq_suffix(suffix_len)
    for u in unitigs[1:]:
        if suffix_len == 0:
            break
        s = u.seq_suffix(suffix_len)
        neq = np.nonzero(first[len(first) - suffix_len:] != s)[0]
        if len(neq):
            suffix_len = suffix_len - int(neq[-1]) - 1
    return (first[len(first) - suffix_len:].copy() if suffix_len
            else np.zeros(0, np.uint8))


# ---------------- linear-path merging ----------------

def merge_linear_paths(graph: UnitigGraph, seqs: List[Sequence]) -> None:
    """Collapse 1-in/1-out chains into single unitigs, respecting sequence
    path endpoints and circular-loop components
    (reference graph_simplification.rs:315-371)."""
    fixed_starts, fixed_ends = get_fixed_unitig_starts_and_ends(graph, seqs)
    _fix_circular_loops(graph, fixed_starts)
    already_used: Set[int] = set()
    merge_paths: List[List[UnitigStrand]] = []
    for unitig in graph.unitigs:
        number = unitig.number
        for strand in (FORWARD, REVERSE):
            if number in already_used:
                continue
            if (_has_single_exclusive_input(unitig, strand)
                    and not _cannot_merge_start(number, strand, fixed_starts, fixed_ends)):
                continue
            current = [UnitigStrand(unitig, strand)]
            already_used.add(number)
            while True:
                last = current[-1]
                if _cannot_merge_end(last.number, last.strand, fixed_starts, fixed_ends):
                    break
                outputs = (get_exclusive_outputs(last.unitig) if last.strand
                           else get_exclusive_inputs(last.unitig))
                if len(outputs) != 1:
                    break
                output = outputs[0]
                if not last.strand:
                    output = output.flipped()
                if output.number in already_used:
                    break
                if _cannot_merge_start(output.number, output.strand,
                                       fixed_starts, fixed_ends):
                    break
                current.append(output)
                already_used.add(output.number)
            if len(current) > 1:
                merge_paths.append(current)

    new_number = graph.max_unitig_number()
    for path in merge_paths:
        new_number += 1
        _merge_path(graph, path, new_number)
    graph.delete_dangling_links()
    graph.build_index()
    graph.check_links()


def _fix_circular_loops(graph: UnitigGraph, fixed_starts: Set[int]) -> None:
    """Mark the lowest-numbered unitig of each simple circular-loop component
    as a fixed start so the loop merges into one unitig
    (reference graph_simplification.rs:374-384)."""
    for component in graph.connected_components():
        if graph.component_is_circular_loop(component):
            fixed_starts.add(component[0])


def _cannot_merge_start(number: int, strand: bool, fixed_starts: Set[int],
                        fixed_ends: Set[int]) -> bool:
    return ((strand and number in fixed_starts)
            or (not strand and number in fixed_ends))


def _cannot_merge_end(number: int, strand: bool, fixed_starts: Set[int],
                      fixed_ends: Set[int]) -> bool:
    return ((strand and number in fixed_ends)
            or (not strand and number in fixed_starts))


def _has_single_exclusive_input(unitig: Unitig, strand: bool) -> bool:
    inputs = get_exclusive_inputs(unitig) if strand else get_exclusive_outputs(unitig)
    return len(inputs) == 1


def _merge_path(graph: UnitigGraph, path: List[UnitigStrand], new_number: int) -> None:
    """Replace a linear path with one merged unitig, rewiring neighbour and
    self links (reference graph_simplification.rs:410-485)."""
    merged_seq = np.concatenate([u.get_seq() for u in path])
    first, last = path[0], path[-1]
    forward_positions = (first.unitig.forward_positions if first.strand
                         else first.unitig.reverse_positions).copy()
    reverse_positions = (last.unitig.reverse_positions if last.strand
                         else last.unitig.forward_positions).copy()

    end_to_start = graph.link_exists(last.number, last.strand, first.number, first.strand)
    start_flip = graph.link_exists(first.number, not first.strand, first.number, first.strand)
    end_flip = graph.link_exists(last.number, last.strand, last.number, not last.strand)

    forward_prev = list(first.unitig.forward_prev if first.strand
                        else first.unitig.reverse_prev)
    reverse_next = list(first.unitig.reverse_next if first.strand
                        else first.unitig.forward_next)
    forward_next = list(last.unitig.forward_next if last.strand
                        else last.unitig.reverse_next)
    reverse_prev = list(last.unitig.reverse_prev if last.strand
                        else last.unitig.forward_prev)

    unitig = Unitig(new_number, merged_seq)
    unitig.depth = _merge_path_depth(path, forward_positions)
    unitig.forward_positions = forward_positions
    unitig.reverse_positions = reverse_positions
    unitig.forward_next = forward_next
    unitig.forward_prev = forward_prev
    unitig.reverse_next = reverse_next
    unitig.reverse_prev = reverse_prev
    if any(p.is_anchor() or p.is_consentig() for p in path):
        unitig.unitig_type = UnitigType.CONSENTIG
    graph.invalidate_paths_cache()
    graph.unitigs.append(unitig)

    for u in unitig.forward_next:
        (u.unitig.forward_prev if u.strand else u.unitig.reverse_prev).append(
            UnitigStrand(unitig, FORWARD))
    for u in unitig.forward_prev:
        (u.unitig.forward_next if u.strand else u.unitig.reverse_next).append(
            UnitigStrand(unitig, FORWARD))
    for u in unitig.reverse_next:
        (u.unitig.forward_prev if u.strand else u.unitig.reverse_prev).append(
            UnitigStrand(unitig, REVERSE))
    for u in unitig.reverse_prev:
        (u.unitig.forward_next if u.strand else u.unitig.reverse_next).append(
            UnitigStrand(unitig, REVERSE))

    if end_to_start:
        unitig.forward_next.append(UnitigStrand(unitig, FORWARD))
        unitig.forward_prev.append(UnitigStrand(unitig, FORWARD))
        unitig.reverse_next.append(UnitigStrand(unitig, REVERSE))
        unitig.reverse_prev.append(UnitigStrand(unitig, REVERSE))
    if start_flip:
        unitig.reverse_next.append(UnitigStrand(unitig, FORWARD))
        unitig.forward_prev.append(UnitigStrand(unitig, REVERSE))
    if end_flip:
        unitig.forward_next.append(UnitigStrand(unitig, REVERSE))
        unitig.reverse_prev.append(UnitigStrand(unitig, FORWARD))

    path_numbers = {u.number for u in path}
    graph.unitigs = [u for u in graph.unitigs if u.number not in path_numbers]


def _merge_path_depth(path: List[UnitigStrand], forward_positions) -> float:
    """Position count if available, else anchor depth, else length-weighted
    mean (reference graph_simplification.rs:501-526)."""
    if len(forward_positions):
        return float(len(forward_positions))
    for u in path:
        if u.is_anchor():
            return u.depth()
    total_length = sum(u.length() for u in path)
    return sum(u.depth() * u.length() for u in path) / total_length
