"""Unitig: a compacted non-branching path of the De Bruijn graph.

Parity target: reference unitig.rs.
- dual-strand sequence plus four adjacency lists (unitig.rs:31-45)
- GFA segment serialization with DP/CL tags (unitig.rs:62-100, 167-181)
- sequence edit ops used by repeat expansion (unitig.rs:216-248)
- topology helpers: hairpin/open ends, isolated circular/linear
  (unitig.rs:196-292)

Where the reference juggles Rc<RefCell<Unitig>> + Weak references, we just use
Python object references (the GC handles the cycles) and keep sequences as
numpy uint8 arrays so device kernels can view them zero-copy.
"""

from __future__ import annotations

import enum
from typing import List, Optional

import numpy as np

from ..utils import FORWARD, REVERSE, quit_with_error, reverse_complement_bytes
from .position import PositionArray

ANCHOR_COLOUR = "forestgreen"
BRIDGE_COLOUR = "pink"
CONSENTIG_COLOUR = "steelblue"
OTHER_COLOUR = "orangered"


class UnitigType(enum.Enum):
    ANCHOR = "anchor"
    BRIDGE = "bridge"
    CONSENTIG = "consentig"
    OTHER = "other"


_COLOUR_FOR_TYPE = {
    UnitigType.ANCHOR: ANCHOR_COLOUR,
    UnitigType.BRIDGE: BRIDGE_COLOUR,
    UnitigType.CONSENTIG: CONSENTIG_COLOUR,
    UnitigType.OTHER: OTHER_COLOUR,
}


class Unitig:
    __slots__ = ("number", "forward_seq", "_reverse_seq", "depth", "unitig_type",
                 "forward_positions", "reverse_positions",
                 "forward_next", "forward_prev", "reverse_next", "reverse_prev")

    def __init__(self, number: int = 0,
                 forward_seq: Optional[np.ndarray] = None,
                 reverse_seq: Optional[np.ndarray] = None,
                 depth: float = 0.0,
                 unitig_type: UnitigType = UnitigType.OTHER):
        self.number = number
        self.forward_seq = forward_seq if forward_seq is not None else np.zeros(0, np.uint8)
        # reverse strand is derived lazily: most unitigs of a loaded graph
        # never have their reverse sequence read
        self._reverse_seq = reverse_seq
        self.depth = depth
        self.unitig_type = unitig_type
        self.forward_positions = PositionArray()
        self.reverse_positions = PositionArray()
        self.forward_next: List[UnitigStrand] = []
        self.forward_prev: List[UnitigStrand] = []
        self.reverse_next: List[UnitigStrand] = []
        self.reverse_prev: List[UnitigStrand] = []

    @property
    def reverse_seq(self) -> np.ndarray:
        if self._reverse_seq is None:
            self._reverse_seq = reverse_complement_bytes(self.forward_seq)
        return self._reverse_seq

    @reverse_seq.setter
    def reverse_seq(self, value: Optional[np.ndarray]) -> None:
        self._reverse_seq = value

    # ---------------- construction ----------------

    @classmethod
    def from_segment_line(cls, segment_line: str) -> "Unitig":
        """Parse a GFA S-line (reference unitig.rs:62-91). Requires a DP:f:
        depth tag; unitig type is recovered from the CL:Z: colour tag."""
        parts = segment_line.rstrip("\r\n").split("\t")
        if len(parts) < 3:
            quit_with_error("Segment line does not have enough parts.")
        try:
            number = int(parts[1])
        except ValueError:
            quit_with_error("Unable to parse unitig number.")
        forward_seq = np.frombuffer(parts[2].encode(), dtype=np.uint8).copy()
        depth = None
        for p in parts:
            if p.startswith("DP:f:"):
                try:
                    depth = float(p[5:])
                except ValueError:
                    pass
                break
        if depth is None:
            quit_with_error("Could not find a depth tag (e.g. DP:f:10.00) in the GFA "
                            "segment line.\nAre you sure this is an Autocycler-generated "
                            "GFA file?")
        unitig_type = UnitigType.OTHER
        if f"CL:Z:{CONSENTIG_COLOUR}" in parts:
            unitig_type = UnitigType.CONSENTIG
        elif f"CL:Z:{ANCHOR_COLOUR}" in parts:
            unitig_type = UnitigType.ANCHOR
        elif f"CL:Z:{BRIDGE_COLOUR}" in parts:
            unitig_type = UnitigType.BRIDGE
        return cls(number, forward_seq, depth=depth, unitig_type=unitig_type)

    @classmethod
    def bridge(cls, number: int, forward_seq: np.ndarray, depth: float) -> "Unitig":
        """Manually-built bridge unitig (reference unitig.rs:93-100)."""
        return cls(number, forward_seq, depth=depth, unitig_type=UnitigType.BRIDGE)

    # ---------------- basic accessors ----------------

    def length(self) -> int:
        return len(self.forward_seq)

    def get_seq(self, strand: bool) -> np.ndarray:
        return self.forward_seq if strand else self.reverse_seq

    def seq_str(self, strand: bool = FORWARD) -> str:
        return self.get_seq(strand).tobytes().decode()

    # ---------------- GFA ----------------

    def colour_tag(self, use_other_colour: bool) -> str:
        if self.unitig_type is UnitigType.OTHER and not use_other_colour:
            return ""
        return f"\tCL:Z:{_COLOUR_FOR_TYPE[self.unitig_type]}"

    def gfa_segment_line(self, use_other_colour: bool) -> str:
        return (f"S\t{self.number}\t{self.seq_str()}\tDP:f:{self.depth:.2f}"
                f"{self.colour_tag(use_other_colour)}")

    # ---------------- topology ----------------

    def open_start(self) -> bool:
        return not self.reverse_next

    def open_end(self) -> bool:
        return not self.forward_next

    def hairpin_start(self) -> bool:
        return (len(self.reverse_next) == 1 and self.reverse_next[0].strand == FORWARD
                and self.reverse_next[0].unitig is self)

    def hairpin_end(self) -> bool:
        return (len(self.forward_next) == 1 and self.forward_next[0].strand == REVERSE
                and self.forward_next[0].unitig is self)

    def is_isolated_and_circular(self) -> bool:
        """One circularising self-link and nothing else (unitig.rs:275-281)."""
        if len(self.forward_next) != 1 or len(self.forward_prev) != 1:
            return False
        nxt, prv = self.forward_next[0], self.forward_prev[0]
        return (nxt.unitig is self and nxt.strand and prv.unitig is self and prv.strand)

    def is_isolated_and_linear(self) -> bool:
        """No links except optional hairpin-end self-links (unitig.rs:283-292)."""
        if len(self.forward_next) > 1 or len(self.forward_prev) > 1:
            return False
        if self.is_isolated_and_circular():
            return False
        return (all(u.unitig is self and not u.strand for u in self.forward_next)
                and all(u.unitig is self and not u.strand for u in self.forward_prev)
                and all(u.unitig is self and u.strand for u in self.reverse_next)
                and all(u.unitig is self and u.strand for u in self.reverse_prev))

    # ---------------- sequence edits (repeat expansion) ----------------

    def remove_seq_from_start(self, amount: int) -> None:
        assert amount <= len(self.forward_seq)
        self.forward_positions.shift_pos(amount)
        self.forward_seq = self.forward_seq[amount:]
        if self._reverse_seq is not None:
            # rc reverses order: trimming the forward START trims the
            # reverse END, so a live cache survives as a slice
            self._reverse_seq = self._reverse_seq[:len(self._reverse_seq) - amount]

    def remove_seq_from_end(self, amount: int) -> None:
        assert amount <= len(self.forward_seq)
        self.reverse_positions.shift_pos(amount)
        self.forward_seq = self.forward_seq[:len(self.forward_seq) - amount]
        if self._reverse_seq is not None:
            self._reverse_seq = self._reverse_seq[amount:]

    def add_seq_to_start(self, seq: np.ndarray) -> None:
        self.forward_positions.shift_pos(-len(seq))
        self.forward_seq = np.concatenate([seq, self.forward_seq])
        self._reverse_seq = None

    def add_seq_to_end(self, seq: np.ndarray) -> None:
        self.reverse_positions.shift_pos(-len(seq))
        self.forward_seq = np.concatenate([self.forward_seq, seq])
        self._reverse_seq = None

    # ---------------- positions / depth ----------------

    def remove_sequence(self, seq_id: int) -> None:
        """Drop all positions with the given sequence ID and recalculate depth
        (unitig.rs:250-257)."""
        self.remove_sequences((seq_id,))

    def remove_sequences(self, seq_ids, lut=None) -> None:
        """Batch form of :meth:`remove_sequence` — one mask per strand for
        the whole id set."""
        self.forward_positions = self.forward_positions.without_seq_ids(seq_ids, lut)
        self.reverse_positions = self.reverse_positions.without_seq_ids(seq_ids, lut)
        assert len(self.forward_positions) == len(self.reverse_positions)
        self.recalculate_depth()

    def recalculate_depth(self) -> None:
        self.depth = float(len(self.forward_positions))

    def clear_positions(self) -> None:
        self.forward_positions = PositionArray()
        self.reverse_positions = PositionArray()

    def reduce_depth_by_one(self) -> None:
        self.depth = max(0.0, self.depth - 1.0)

    def clear_all_links(self) -> None:
        self.forward_next = []
        self.forward_prev = []
        self.reverse_next = []
        self.reverse_prev = []

    def __str__(self) -> str:
        seq = self.seq_str()
        display = seq if len(seq) < 15 else f"{seq[:6]}...{seq[-6:]}"
        return f"unitig {self.number}: {display}, {len(seq)} bp, {self.depth:.2f}x"

    __repr__ = __str__


class UnitigStrand:
    """A unitig viewed on one strand (reference unitig.rs:322-372)."""

    __slots__ = ("unitig", "strand")

    def __init__(self, unitig: Unitig, strand: bool):
        self.unitig = unitig
        self.strand = strand

    @property
    def number(self) -> int:
        return self.unitig.number

    def signed_number(self) -> int:
        return self.unitig.number if self.strand else -self.unitig.number

    def length(self) -> int:
        return self.unitig.length()

    def depth(self) -> float:
        return self.unitig.depth

    def get_seq(self) -> np.ndarray:
        return self.unitig.get_seq(self.strand)

    def seq_prefix(self, n: int) -> np.ndarray:
        """First n symbols of the strand sequence. On the reverse strand
        this reverse-complements only an n-symbol window of the forward
        sequence instead of materialising the full reverse strand (repeat
        expansion probes prefixes of multi-Mbp unitigs after every edit).

        Contract: n <= length(). The windowed reverse-strand slice would
        silently wrap on a larger n, so it is asserted rather than clamped.
        """
        u = self.unitig
        assert n <= u.length(), (n, u.length())
        if self.strand:
            return u.forward_seq[:n]
        if u._reverse_seq is not None:
            return u._reverse_seq[:n]
        f = u.forward_seq
        return reverse_complement_bytes(f[len(f) - n:]) if n else f[:0]

    def seq_suffix(self, n: int) -> np.ndarray:
        """Last n symbols of the strand sequence (windowed like
        :meth:`seq_prefix`; same n <= length() contract)."""
        u = self.unitig
        assert n <= u.length(), (n, u.length())
        f = u.forward_seq
        if self.strand:
            return f[len(f) - n:] if n else f[:0]
        if u._reverse_seq is not None:
            r = u._reverse_seq
            return r[len(r) - n:] if n else r[:0]
        return reverse_complement_bytes(f[:n])

    def is_anchor(self) -> bool:
        return self.unitig.unitig_type is UnitigType.ANCHOR

    def is_consentig(self) -> bool:
        return self.unitig.unitig_type is UnitigType.CONSENTIG

    def flipped(self) -> "UnitigStrand":
        return UnitigStrand(self.unitig, not self.strand)

    def __repr__(self) -> str:
        return f"{self.unitig.number}{'+' if self.strand else '-'}"
