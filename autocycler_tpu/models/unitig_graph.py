"""UnitigGraph: the central host-side graph structure.

Parity target: reference unitig_graph.rs (1501 LoC). The graph is the
serialization format of the whole data model: every pipeline stage writes a
self-contained GFA (S segments with DP/CL tags, 0M L links, P path lines with
LN/FN/HD/CL provenance tags) that the next stage re-loads — see reference
unitig_graph.rs:50-174 (load) and :317-360 (save).

Construction from k-mers happens in ops/ + commands/compress.py (the device
path); this module owns parsing, serialization, link surgery, invariants and
topology queries. Irregular pointer-chasing graph mutation stays on the host
by design (SURVEY.md §2.1, §7).
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Tuple

import numpy as np

from ..utils import FORWARD, REVERSE, load_file_lines, quit_with_error
from .position import MAX_SEQ_ID, Position, PositionArray
from .sequence import Sequence
from .unitig import Unitig, UnitigStrand


def parse_unitig_path(path_str: str) -> List[Tuple[int, bool]]:
    """'1+,2-,3+' -> [(1, True), (2, False), (3, True)]
    (reference unitig_graph.rs:971-979)."""
    path = []
    for token in path_str.split(","):
        if token.endswith("+"):
            strand = FORWARD
        elif token.endswith("-"):
            strand = REVERSE
        else:
            quit_with_error(f"Invalid path strand: {token}")
        try:
            number = int(token[:-1])
        except ValueError:
            quit_with_error(f"unable to parse path unitig number: {token!r}")
        if number < 1:
            # dense-LUT consumers index by number; a negative here would
            # wrap via Python negative indexing onto the wrong unitig
            quit_with_error(f"path unitig numbers must be positive: {token!r}")
        path.append((number, strand))
    return path


def parse_unitig_path_arrays(path_str: str) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorised :func:`parse_unitig_path`: '1+,2-' -> (numbers int64[],
    strands bool[]). The whole P-line path is parsed with array ops (digit
    place-value accumulation per token) instead of per-token string slicing;
    malformed input falls back to the scalar parser for its error message."""
    b = np.frombuffer(path_str.encode(), np.uint8)
    if len(b) == 0:
        quit_with_error("Invalid path strand: ")
    is_comma = b == 44
    sign_idx = np.flatnonzero((b == 43) | (b == 45))
    comma_idx = np.flatnonzero(is_comma)
    T = len(comma_idx) + 1
    starts = np.concatenate([[0], comma_idx + 1])
    ends = np.concatenate([comma_idx, [len(b)]])
    digit_mask = (b >= 48) & (b <= 57)
    ok = (len(sign_idx) == T
          and np.array_equal(sign_idx, ends - 1)       # sign char ends token
          and (sign_idx - starts >= 1).all()           # >=1 digit per token
          # >15-digit ids would lose precision in the f64 place-value sum
          and (sign_idx - starts <= 15).all()
          and (digit_mask | is_comma | (b == 43) | (b == 45)).all())
    if not ok:
        path = parse_unitig_path(path_str)              # scalar error parity
        return (np.array([n for n, _ in path], np.int64),
                np.array([s for _, s in path], bool))
    # place-value accumulation: digit at i in token t weighs 10^(end_t-2-i)
    di = np.flatnonzero(digit_mask)
    tok = np.searchsorted(starts, di, side="right") - 1
    exp = (sign_idx[tok] - 1 - di).astype(np.float64)
    vals = np.bincount(tok, weights=(b[di] - 48) * 10.0 ** exp, minlength=T)
    if (vals < 1).any():
        parse_unitig_path(path_str)   # scalar parser rejects '0...' tokens
    return vals.astype(np.int64), b[sign_idx] == 43


def reverse_path(path: List[Tuple[int, bool]]) -> List[Tuple[int, bool]]:
    return [(num, not strand) for num, strand in reversed(path)]


class UnitigGraph:
    def __init__(self, k_size: int = 0):
        self.unitigs: List[Unitig] = []
        self.k_size = k_size
        self.index: Dict[int, Unitig] = {}
        # paths parsed from the GFA P-lines, valid until any mutation that
        # could change path composition (see invalidate_paths_cache callers);
        # position-COORDINATE edits (repeat expansion) keep it valid because
        # the (number, strand) sequence of every path is unchanged
        self._paths_cache = None
        # same P-line paths in array form (numbers int64[], strands bool[]),
        # kept so bulk consumers (get_sequences_for_ids) never touch
        # per-piece python tuples; invalidated together with _paths_cache
        self._paths_arrays_cache = None

    # ---------------- loading ----------------

    @classmethod
    def from_gfa_file(cls, gfa_filename) -> Tuple["UnitigGraph", List[Sequence]]:
        from ..utils.resilience import InputError, fault_fire
        if fault_fire("gfa", str(gfa_filename)) is not None:
            raise InputError(f"fault injection: corrupt GFA read: {gfa_filename}")
        return cls.from_gfa_lines(load_file_lines(gfa_filename))

    @classmethod
    def from_gfa_lines(cls, gfa_lines,
                       check: bool = True) -> Tuple["UnitigGraph", List[Sequence]]:
        """check=False skips the link-invariant pass — only for re-loading
        lines this process just generated itself (e.g. per-cluster subsetting
        of an in-memory graph); external files are always checked."""
        graph = cls()
        link_lines, path_lines = [], []
        for line in gfa_lines:
            parts = line.rstrip("\r\n").split("\t")
            if not parts:
                continue
            if parts[0] == "H":
                graph._read_header_line(parts)
            elif parts[0] == "S":
                graph.unitigs.append(Unitig.from_segment_line(line))
            elif parts[0] == "L":
                link_lines.append(parts)
            elif parts[0] == "P":
                path_lines.append(parts)
        seen = set()
        for u in graph.unitigs:
            if u.number < 1:
                # dense LUTs index by number; zero/negative would wrap via
                # Python negative indexing onto the wrong unitig
                quit_with_error(f"segment numbers must be positive: {u.number}")
            if u.number in seen:
                quit_with_error(f"duplicate segment number in GFA: {u.number}")
            seen.add(u.number)
        graph.build_index()
        graph._build_links_from_gfa(link_lines)
        sequences = graph._build_paths_from_gfa(path_lines)
        if check:
            graph.check_links()
        return graph, sequences

    def _read_header_line(self, parts: List[str]) -> None:
        for p in parts:
            if p.startswith("KM:i:"):
                try:
                    self.k_size = int(p[5:])
                    return
                except ValueError:
                    pass

    def build_index(self) -> None:
        self.index = {u.number: u for u in self.unitigs}

    def _dense_luts(self) -> Tuple[int, np.ndarray, np.ndarray]:
        """(max_num, row_of, lengths): dense number-indexed tables; -1 in
        row_of marks absent numbers (lengths valid only where row_of >= 0).
        Valid only until the unitig list next changes."""
        max_num = self.max_unitig_number()
        row_of = np.full(max_num + 1, -1, np.int64)
        lengths = np.zeros(max_num + 1, np.int64)
        for r, u in enumerate(self.unitigs):
            row_of[u.number] = r
            lengths[u.number] = len(u.forward_seq)
        return max_num, row_of, lengths

    def _build_links_from_gfa(self, link_lines: List[List[str]]) -> None:
        for parts in link_lines:
            if len(parts) < 6 or parts[5] != "0M":
                quit_with_error("non-zero overlap found on the GFA link line.\n"
                                "Are you sure this is an Autocycler-generated GFA file?")
            try:
                seg_1, seg_2 = int(parts[1]), int(parts[3])
            except ValueError:
                quit_with_error(f"unable to parse link segment numbers: "
                                f"{parts[1]!r}, {parts[3]!r}")
            if parts[2] not in ("+", "-") or parts[4] not in ("+", "-"):
                quit_with_error(f"invalid strand on GFA link line: "
                                f"{parts[2]!r}, {parts[4]!r}")
            strand_1, strand_2 = parts[2] == "+", parts[4] == "+"
            u1 = self.index.get(seg_1)
            u2 = self.index.get(seg_2)
            if u1 is None:
                quit_with_error(f"link refers to nonexistent unitig: {seg_1}")
            if u2 is None:
                quit_with_error(f"link refers to nonexistent unitig: {seg_2}")
            (u1.forward_next if strand_1 else u1.reverse_next).append(UnitigStrand(u2, strand_2))
            (u2.forward_prev if strand_2 else u2.reverse_prev).append(UnitigStrand(u1, strand_1))

    def _build_paths_from_gfa(self, path_lines: List[List[str]]) -> List[Sequence]:
        sequences = []
        entries = []
        paths_cache = {}
        # dense LUTs for the vectorised per-path LN check, shared with
        # stamp_paths_batch (skipped entirely when there are no P-lines)
        luts = self._dense_luts() if path_lines else None
        for parts in path_lines:
            if len(parts) < 3:
                quit_with_error("GFA path line does not have enough parts.")
            try:
                seq_id = int(parts[1])
            except ValueError:
                quit_with_error(f"unable to parse P-line sequence id: {parts[1]!r}")
            if not 0 <= seq_id <= MAX_SEQ_ID:
                quit_with_error(f"P-line sequence id {seq_id} outside the "
                                f"supported range 0..{MAX_SEQ_ID} (15-bit "
                                "id space, reference position.rs:21)")
            if seq_id in paths_cache:
                quit_with_error(f"duplicate P-line sequence id in GFA: {seq_id}")
            length = filename = header = None
            cluster = 0
            try:
                for p in parts[2:]:
                    if p.startswith("LN:i:"):
                        length = int(p[5:])
                    elif p.startswith("FN:Z:"):
                        filename = p[5:]
                    elif p.startswith("HD:Z:"):
                        header = p[5:]
                    elif p.startswith("CL:i:"):
                        cluster = int(p[5:])
            except ValueError:
                quit_with_error(f"unable to parse integer tag on GFA path "
                                f"line for sequence {seq_id}")
            if length is None or filename is None or header is None:
                quit_with_error("missing required tag in GFA path line.")
            numbers, strands = parse_unitig_path_arrays(parts[2])
            # missing path unitigs get their own error in stamp_paths_batch;
            # only a complete path can be length-validated here
            max_num, row_of, lengths = luts
            if len(numbers) and numbers.max() <= max_num \
                    and (row_of[numbers] >= 0).all():
                path_bp = int(lengths[numbers].sum())
                if path_bp != length:
                    quit_with_error(
                        f"P-line for sequence {seq_id} declares LN:i:{length} "
                        f"but its path totals {path_bp} bp — the GFA paths "
                        "do not match its segments")
            entries.append((seq_id, length, numbers, strands))
            sequences.append(Sequence.without_seq(seq_id, filename, header,
                                                  length, cluster))
            paths_cache[seq_id] = list(zip(numbers.tolist(), strands.tolist()))
        self.stamp_paths_batch(entries, luts=luts)
        self._paths_cache = paths_cache
        self._paths_arrays_cache = {e[0]: (e[2], e[3]) for e in entries}
        return sequences

    def stamp_paths_batch(self, entries, luts=None) -> None:
        """Stamp many sequence paths in one vectorised pass. ``entries`` is a
        list of (seq_id, length, numbers int64[], strands bool[]).
        ``luts`` optionally passes a prebuilt :meth:`_dense_luts` result so
        a caller that already built the tables doesn't rebuild them.

        One pass covers both strands: the reverse-path position of the step
        at forward position p is length - p - len(unitig)
        (reference unitig_graph.rs:151-174). All stamps of the batch are
        grouped per (unitig, strand) with one sort, then assigned as array
        slices — positions become views into two batch-level SoA blocks.
        Position ORDER within a unitig is not part of the model's contract
        (every consumer sorts or filters)."""
        self.invalidate_paths_cache()
        entries = [e for e in entries if len(e[2])]
        if not entries:
            return
        numbers_all = np.concatenate([e[2] for e in entries])
        strands_all = np.concatenate([e[3] for e in entries])
        sid_all = np.concatenate([np.full(len(e[2]), e[0], np.int32)
                                  for e in entries])
        L_all = np.concatenate([np.full(len(e[2]), e[1], np.int64)
                                for e in entries])
        path_off = np.zeros(len(entries) + 1, np.int64)
        np.cumsum([len(e[2]) for e in entries], out=path_off[1:])

        # dense number -> (row, length) lookup
        max_num, row_of, lengths = luts if luts is not None \
            else self._dense_luts()
        if numbers_all.min(initial=1) < 1 or \
                numbers_all.max(initial=0) > max_num or \
                (row_of[numbers_all] < 0).any():
            # min check first: a negative number would silently wrap through
            # the dense LUTs via Python negative indexing
            bad = numbers_all[(numbers_all < 1) | (numbers_all > max_num) |
                              (row_of[np.clip(numbers_all, 0, max_num)] < 0)][0]
            quit_with_error(f"unitig {int(bad)} not found in unitig index")
        ln = lengths[numbers_all]
        rows = row_of[numbers_all]

        # per-path exclusive cumsum of step lengths = forward positions
        cum = np.cumsum(ln)
        base = np.zeros(len(ln), np.int64)
        base[path_off[1:-1]] = cum[path_off[1:-1] - 1]
        pos = cum - ln - np.maximum.accumulate(base)
        # every path must sum to its declared length
        ends = cum[path_off[1:] - 1] - np.concatenate(
            [[0], cum[path_off[1:-1] - 1]])
        declared = np.array([e[1] for e in entries])
        # internal invariant (reference unitig_graph.rs:386) — malformed GFA
        # input is caught with a user-facing error in _build_paths_from_gfa
        # before entries reach this helper
        assert np.array_equal(ends, declared), \
            f"path length mismatch for sequence " \
            f"{entries[int(np.nonzero(ends != declared)[0][0])][0]}"

        mirror = L_all - pos - ln
        # first half: FORWARD stamps at pos; second half: REVERSE at mirror.
        # A + step stamps FORWARD onto the forward list (side True); a - step
        # stamps FORWARD onto the reverse list.
        side = np.concatenate([strands_all, ~strands_all])
        st = np.concatenate([np.ones(len(pos), bool), np.zeros(len(pos), bool)])
        sp = np.concatenate([pos, mirror])
        ssid = np.concatenate([sid_all, sid_all])
        srow = np.concatenate([rows, rows])

        key = srow * 2 + side
        order = np.argsort(key, kind="stable")
        ssid = ssid[order]
        st = st[order]
        sp = sp[order]
        touched = np.unique(key[order])
        bounds = np.searchsorted(key[order], np.concatenate([touched,
                                                             [key.max() + 1]]))
        for t in range(len(touched)):
            r, is_fwd = divmod(int(touched[t]), 2)
            u = self.unitigs[r]
            arr = PositionArray(ssid[bounds[t]:bounds[t + 1]],
                                st[bounds[t]:bounds[t + 1]],
                                sp[bounds[t]:bounds[t + 1]])
            if is_fwd:
                u.forward_positions = u.forward_positions.concat(arr)
            else:
                u.reverse_positions = u.reverse_positions.concat(arr)

    # ---------------- saving ----------------

    def save_gfa(self, gfa_filename, sequences: List[Sequence],
                 use_other_colour: bool = False) -> None:
        """Streams the same bytes gfa_text produces, but writes each unitig's
        sequence array directly instead of decoding Mbp of segments into
        Python strings first."""
        with open(gfa_filename, "wb") as f:
            f.write(f"H\tVN:Z:1.0\tKM:i:{self.k_size}\n".encode())
            for unitig in self.unitigs:
                f.write(f"S\t{unitig.number}\t".encode())
                f.write(unitig.forward_seq.tobytes())
                f.write(f"\tDP:f:{unitig.depth:.2f}"
                        f"{unitig.colour_tag(use_other_colour)}\n".encode())
            for a, a_strand, b, b_strand in self.links_for_gfa():
                f.write(f"L\t{a}\t{a_strand}\t{b}\t{b_strand}\t0M\n".encode())
            paths = self.get_unitig_paths_for_sequences([s.id for s in sequences])
            for seq in sequences:
                f.write(self.gfa_path_line(seq, paths[seq.id]).encode())
                f.write(b"\n")

    def gfa_text(self, sequences: List[Sequence], use_other_colour: bool = False) -> str:
        lines = [f"H\tVN:Z:1.0\tKM:i:{self.k_size}"]
        for unitig in self.unitigs:
            lines.append(unitig.gfa_segment_line(use_other_colour))
        for a, a_strand, b, b_strand in self.links_for_gfa():
            lines.append(f"L\t{a}\t{a_strand}\t{b}\t{b_strand}\t0M")
        paths = self.get_unitig_paths_for_sequences([s.id for s in sequences])
        for seq in sequences:
            lines.append(self.gfa_path_line(seq, paths[seq.id]))
        return "\n".join(lines) + "\n"

    def links_for_gfa(self, offset: int = 0):
        links = []
        for a in self.unitigs:
            for b in a.forward_next:
                links.append((a.number + offset, "+", b.number + offset,
                              "+" if b.strand else "-"))
            for b in a.reverse_next:
                links.append((a.number + offset, "-", b.number + offset,
                              "+" if b.strand else "-"))
        return links

    def gfa_path_line(self, seq: Sequence, path=None) -> str:
        if path is None:
            path = self.get_unitig_path_for_sequence(seq)
        path_str = ",".join(f"{num}{'+' if strand else '-'}" for num, strand in path)
        cluster_tag = f"\tCL:i:{seq.cluster}" if seq.cluster > 0 else ""
        return (f"P\t{seq.id}\t{path_str}\t*\tLN:i:{seq.length}\tFN:Z:{seq.filename}"
                f"\tHD:Z:{seq.contig_header}{cluster_tag}")

    # ---------------- sequence reconstruction ----------------

    def get_sequence_from_path(self, path: List[Tuple[int, bool]]) -> np.ndarray:
        pieces = [self.index[num].get_seq(strand) for num, strand in path]
        if not pieces:
            return np.zeros(0, dtype=np.uint8)
        return np.concatenate(pieces)

    def get_sequence_from_path_signed(self, path: List[int]) -> np.ndarray:
        return self.get_sequence_from_path([(abs(n), n >= 0) for n in path])

    def _path_arrays_for_sequences(self, seq_ids
                                   ) -> Dict[int, Tuple[np.ndarray, np.ndarray]]:
        """(numbers int64[], strands bool[]) per path. The GFA loader's
        array cache is returned directly; a mutated graph falls back to
        the tuple sweep and converts once."""
        cache = self._paths_arrays_cache
        if cache is not None and all(sid in cache for sid in seq_ids):
            return {sid: cache[sid] for sid in seq_ids}
        out: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}
        for sid, path in self.get_unitig_paths_for_sequences(seq_ids).items():
            nums = np.fromiter((p[0] for p in path), np.int64, len(path))
            strs = np.fromiter((p[1] for p in path), bool, len(path))
            out[sid] = (nums, strs)
        return out

    def get_sequences_for_ids(self, seq_ids) -> Dict[int, np.ndarray]:
        """Reconstruct many sequences at once: every unitig strand that
        any path touches is laid out once in a flat byte pool, pool
        offsets live in dense LUTs indexed by unitig number, and each
        path becomes a single fancy-index gather (one cumsum of per-piece
        position jumps). Bit-identical to get_sequence_from_path per id
        (asserted in tests), but O(total bp) array work with no per-piece
        python — the difference dominates on SNP-shredded graphs where
        pieces average tens of bases."""
        seq_ids = list(seq_ids)
        out: Dict[int, np.ndarray] = {}
        if not seq_ids:
            return out
        if not self.unitigs:
            return {sid: np.zeros(0, np.uint8) for sid in seq_ids}
        arrs = self._path_arrays_for_sequences(seq_ids)
        max_num = max(u.number for u in self.unitigs)
        # reverse strands are computed lazily per unitig; only pool the
        # ones some path actually walks backwards
        rev_used = np.zeros(max_num + 1, bool)
        for sid in seq_ids:
            nums, strs = arrs[sid]
            if nums.size:
                rev_used[nums[~strs]] = True
        len_lut = np.zeros(max_num + 1, np.int64)
        start_lut = np.zeros(2 * (max_num + 1), np.int64)
        parts: List[np.ndarray] = []
        cursor = 0
        for u in self.unitigs:
            n = len(u.forward_seq)
            len_lut[u.number] = n
            start_lut[2 * u.number + 1] = cursor
            parts.append(u.forward_seq)
            cursor += n
            if rev_used[u.number]:
                start_lut[2 * u.number] = cursor
                parts.append(u.reverse_seq)
                cursor += n
        pool = np.concatenate(parts) if parts else np.zeros(0, np.uint8)
        for sid in seq_ids:
            nums, strs = arrs[sid]
            ln = len_lut[nums]
            nz = ln > 0
            if not nz.all():
                nums, strs, ln = nums[nz], strs[nz], ln[nz]
            if not nums.size:
                out[sid] = np.zeros(0, np.uint8)
                continue
            st = start_lut[2 * nums + strs]
            total = int(ln.sum())
            # positions walk each piece start..start+len-1 consecutively:
            # ones everywhere, piece-boundary jumps patched in, one cumsum
            step = np.ones(total, np.int64)
            step[0] = st[0]
            ends = np.cumsum(ln)
            step[ends[:-1]] = st[1:] - st[:-1] - ln[:-1] + 1
            out[sid] = pool[np.cumsum(step)]
        return out

    def invalidate_paths_cache(self) -> None:
        self._paths_cache = None
        self._paths_arrays_cache = None

    def get_unitig_paths_for_sequences(self, seq_ids) -> Dict[int, List[Tuple[int, bool]]]:
        """Paths for many sequences in one sweep: every unitig's forward-
        strand positions are collected and sorted by coordinate, which
        reconstructs each path without the reference's step-by-step
        neighbour walk (unitig_graph.rs:407-465) — same result, O(total
        positions) instead of O(path · degree · positions).

        When the graph is unmutated since a GFA load, the parsed P-line
        paths are returned directly (identical by construction — asserted
        in tests/test_models_more.py).

        The sweep is pure array work on the per-unitig position SoAs: one
        concatenate per field, one mask, one lexsort."""
        cache = self._paths_cache
        if cache is not None and all(sid in cache for sid in seq_ids):
            return {sid: list(cache[sid]) for sid in seq_ids}
        wanted = set(seq_ids)
        out: Dict[int, List[Tuple[int, bool]]] = {sid: [] for sid in wanted}
        if not self.unitigs:
            return out
        sid = np.concatenate([a for u in self.unitigs
                              for a in (u.forward_positions.seq_id,
                                        u.reverse_positions.seq_id)])
        occ_strand = np.concatenate([a for u in self.unitigs
                                     for a in (u.forward_positions.strand,
                                               u.reverse_positions.strand)])
        pos = np.concatenate([a for u in self.unitigs
                              for a in (u.forward_positions.pos,
                                        u.reverse_positions.pos)])
        counts = np.fromiter((c for u in self.unitigs
                              for c in (len(u.forward_positions),
                                        len(u.reverse_positions))),
                             np.int64, count=2 * len(self.unitigs))
        codes = np.fromiter((c for u in self.unitigs
                             for c in ((u.number << 1) | 1, u.number << 1)),
                            np.int64, count=2 * len(self.unitigs))
        code = np.repeat(codes, counts)
        lens = np.repeat(
            np.fromiter((len(u.forward_seq) for u in self.unitigs),
                        np.int64, count=len(self.unitigs)).repeat(2), counts)

        m = occ_strand  # forward-strand occurrences define the path
        sid, pos, code, lens = sid[m], pos[m], code[m], lens[m]
        order = np.lexsort((pos, sid))
        sid, pos, code, lens = sid[order], pos[order], code[order], lens[order]
        starts = np.searchsorted(sid, np.unique(sid))
        bounds = np.concatenate([starts, [len(sid)]])
        uniq = sid[starts] if len(starts) else np.zeros(0, np.int32)
        for i, s in enumerate(uniq.tolist()):
            if s not in wanted:
                continue
            lo, hi = bounds[i], bounds[i + 1]
            p = pos[lo:hi]
            expected = np.zeros(hi - lo, np.int64)
            np.cumsum(lens[lo:hi - 1], out=expected[1:])
            assert np.array_equal(p, expected), "sequence path is not contiguous"
            c = code[lo:hi]
            out[s] = list(zip((c >> 1).tolist(), (c & 1).astype(bool).tolist()))
        return out

    def get_unitig_path_for_sequence(self, seq: Sequence) -> List[Tuple[int, bool]]:
        return self.get_unitig_paths_for_sequences([seq.id])[seq.id]

    def get_unitig_path_for_sequence_i32(self, seq: Sequence) -> List[int]:
        return [num if strand else -num
                for num, strand in self.get_unitig_path_for_sequence(seq)]

    def reconstruct_original_sequences(self, seqs: List[Sequence]
                                       ) -> Dict[str, List[Tuple[str, str]]]:
        """filename -> [(header, sequence string)], in input order
        (reference unitig_graph.rs:362-370)."""
        out: Dict[str, List[Tuple[str, str]]] = {}
        paths = self.get_unitig_paths_for_sequences([s.id for s in seqs])
        for seq in seqs:
            sequence = self.get_sequence_from_path(paths[seq.id])
            assert len(sequence) == seq.length, \
                "reconstructed sequence does not have expected length"
            out.setdefault(seq.filename, []).append(
                (seq.contig_header, sequence.tobytes().decode()))
        return out

    # ---------------- stats / topology ----------------

    def total_length(self) -> int:
        return sum(u.length() for u in self.unitigs)

    def link_count(self) -> Tuple[int, int]:
        """(all links incl. reverse-duplicates, single-direction links)
        (reference unitig_graph.rs:478-507). One canonical set instead of
        two: the closure size is 2·|undirected| − |self-symmetric| (a link
        equals its own reverse iff dst == −src)."""
        one_way = set()
        for a in self.unitigs:
            for signed_a, nexts in ((a.number, a.forward_next), (-a.number, a.reverse_next)):
                for b in nexts:
                    link = (signed_a, b.signed_number())
                    rev_link = (-link[1], -link[0])
                    one_way.add(link if link >= rev_link else rev_link)
        self_sym = sum(1 for (x, y) in one_way if x == -y)
        return 2 * len(one_way) - self_sym, len(one_way)

    def topology(self) -> str:
        """circular / linear-open-open / linear-hairpin-hairpin /
        linear-open-hairpin / fragmented / empty / other
        (reference unitig_graph.rs:527-545)."""
        if not self.unitigs:
            return "empty"
        if len(self.unitigs) > 1:
            return "fragmented"
        u = self.unitigs[0]
        if self.link_count()[0] == 0:
            return "linear-open-open"
        if u.is_isolated_and_circular():
            return "circular"
        if u.hairpin_start() and u.hairpin_end():
            return "linear-hairpin-hairpin"
        if u.hairpin_start() and u.open_end():
            return "linear-open-hairpin"
        if u.open_start() and u.hairpin_end():
            return "linear-open-hairpin"
        return "other"

    def max_unitig_number(self) -> int:
        return max((u.number for u in self.unitigs), default=0)

    def print_basic_graph_info(self, with_topology: bool = False) -> None:
        from ..utils import log
        n, links = len(self.unitigs), self.link_count()[1]
        topo = f" ({self.topology()})" if with_topology else ""
        log.message(f"{n} unitig{'' if n == 1 else 's'}, "
                    f"{links} link{'' if links == 1 else 's'}{topo}")
        log.message(f"total length: {self.total_length()} bp")
        log.message()

    # ---------------- renumbering ----------------

    def renumber_unitigs(self) -> None:
        """Deterministic renumbering by (length desc, sequence lex asc,
        depth desc) — the reproducibility anchor of the whole pipeline
        (reference unitig_graph.rs:295-315)."""
        self.invalidate_paths_cache()
        self.unitigs.sort(key=lambda u: (-u.length(), u.forward_seq.tobytes(), -u.depth))
        for i, unitig in enumerate(self.unitigs):
            unitig.number = i + 1
        self.build_index()

    # ---------------- link surgery ----------------

    def _unitig_for_signed(self, signed_num: int) -> Tuple[Unitig, bool]:
        unitig = self.index.get(abs(signed_num))
        if unitig is None:
            quit_with_error(f"unitig {abs(signed_num)} not found in unitig index")
        return unitig, signed_num > 0

    def create_link(self, start_num: int, end_num: int) -> None:
        """Create a signed link (and its reverse-strand twin unless it is its
        own twin, i.e. a hairpin) (reference unitig_graph.rs:867-893)."""
        self._create_link_one_way(start_num, end_num)
        if start_num != -end_num:
            self._create_link_one_way(-end_num, -start_num)

    def _create_link_one_way(self, start_num: int, end_num: int) -> None:
        start, start_strand = self._unitig_for_signed(start_num)
        end, end_strand = self._unitig_for_signed(end_num)
        (start.forward_next if start_strand else start.reverse_next).append(
            UnitigStrand(end, end_strand))
        (end.forward_prev if end_strand else end.reverse_prev).append(
            UnitigStrand(start, start_strand))

    def delete_link(self, start_num: int, end_num: int) -> None:
        self._delete_link_one_way(start_num, end_num)
        self._delete_link_one_way(-end_num, -start_num)

    def _delete_link_one_way(self, start_num: int, end_num: int) -> None:
        start, start_strand = self._unitig_for_signed(start_num)
        end, end_strand = self._unitig_for_signed(end_num)
        nexts = start.forward_next if start_strand else start.reverse_next
        keep = [c for c in nexts
                if not (c.number == abs(end_num) and c.strand == (end_num > 0))]
        if start_strand:
            start.forward_next = keep
        else:
            start.reverse_next = keep
        prevs = end.forward_prev if end_strand else end.reverse_prev
        keep = [c for c in prevs
                if not (c.number == abs(start_num) and c.strand == (start_num > 0))]
        if end_strand:
            end.forward_prev = keep
        else:
            end.reverse_prev = keep

    def delete_outgoing_links(self, signed_num: int) -> None:
        unitig, strand = self._unitig_for_signed(signed_num)
        nexts = unitig.forward_next if strand else unitig.reverse_next
        for next_num in [u.signed_number() for u in nexts]:
            self.delete_link(signed_num, next_num)

    def delete_incoming_links(self, signed_num: int) -> None:
        unitig, strand = self._unitig_for_signed(signed_num)
        prevs = unitig.forward_prev if strand else unitig.reverse_prev
        for prev_num in [u.signed_number() for u in prevs]:
            self.delete_link(prev_num, signed_num)

    def link_exists(self, a_num: int, a_strand: bool, b_num: int, b_strand: bool) -> bool:
        unitig = self.index.get(a_num)
        if unitig is None:
            return False
        nexts = unitig.forward_next if a_strand else unitig.reverse_next
        return any(n.number == b_num and n.strand == b_strand for n in nexts)

    def link_exists_prev(self, a_num: int, a_strand: bool, b_num: int, b_strand: bool) -> bool:
        unitig = self.index.get(b_num)
        if unitig is None:
            return False
        prevs = unitig.forward_prev if b_strand else unitig.reverse_prev
        return any(p.number == a_num and p.strand == a_strand for p in prevs)

    def check_links(self) -> None:
        """Invariant checker: every link has its strand twin, its prev/next
        mirror, and resolves through the index (reference
        unitig_graph.rs:752-793). Raises AssertionError on violation.

        Set-based: all next- and prev-edges are collected once, then every
        edge (either direction) must appear in both sets along with its
        strand twin — O(E) instead of per-link adjacency-list scans."""
        nexts, prevs = set(), set()
        for a in self.unitigs:
            for b in a.forward_next:
                nexts.add((a.number, FORWARD, b.number, b.strand))
            for b in a.reverse_next:
                nexts.add((a.number, REVERSE, b.number, b.strand))
            for b in a.forward_prev:
                prevs.add((b.number, b.strand, a.number, FORWARD))
            for b in a.reverse_prev:
                prevs.add((b.number, b.strand, a.number, REVERSE))
        # the per-edge form (each edge and its twin in both sets) reduces to
        # three whole-set relations, all C-speed; the assert messages (only
        # evaluated on failure) name the offending links
        assert nexts == prevs, \
            f"missing next/prev link: {sorted(nexts ^ prevs)[:5]}"
        twins = {(b_num, not b_strand, a_num, not a_strand)
                 for (a_num, a_strand, b_num, b_strand) in nexts}
        assert twins <= nexts, \
            f"missing strand-twin link: {sorted(twins - nexts)[:5]}"
        nums = {n for (a_num, _, b_num, _) in nexts for n in (a_num, b_num)}
        assert nums <= self.index.keys(), \
            f"unitig missing from index: {sorted(nums - self.index.keys())[:5]}"

    def delete_dangling_links(self) -> None:
        """Drop links that point at unitigs no longer in the graph
        (reference unitig_graph.rs:547-564)."""
        numbers = {u.number for u in self.unitigs}
        for u in self.unitigs:
            u.forward_next = [c for c in u.forward_next if c.number in numbers]
            u.forward_prev = [c for c in u.forward_prev if c.number in numbers]
            u.reverse_next = [c for c in u.reverse_next if c.number in numbers]
            u.reverse_prev = [c for c in u.reverse_prev if c.number in numbers]

    # ---------------- unitig-level surgery ----------------

    def remove_sequence_from_graph(self, seq_id: int) -> None:
        self.remove_sequences_from_graph((seq_id,))

    def remove_sequences_from_graph(self, seq_ids) -> None:
        """Batched removal: one position mask per unitig strand for the whole
        id set instead of a sweep per sequence."""
        self.invalidate_paths_cache()
        seq_ids = np.asarray(list(seq_ids), np.int32)
        if not len(seq_ids):
            return
        lut = PositionArray.seq_id_lut(seq_ids)
        for u in self.unitigs:
            u.remove_sequences(seq_ids, lut)

    def recalculate_depths(self) -> None:
        for u in self.unitigs:
            u.recalculate_depth()

    def clear_positions(self) -> None:
        self.invalidate_paths_cache()
        for u in self.unitigs:
            u.clear_positions()

    def remove_zero_depth_unitigs(self) -> None:
        self.invalidate_paths_cache()
        self.unitigs = [u for u in self.unitigs if u.depth > 0.0]
        self.delete_dangling_links()
        self.build_index()

    def remove_unitigs_by_number(self, to_remove) -> None:
        self.invalidate_paths_cache()
        to_remove = set(to_remove)
        self.unitigs = [u for u in self.unitigs if u.number not in to_remove]
        self.delete_dangling_links()
        self.build_index()

    def duplicate_unitig_by_number(self, unitig_num: int) -> None:
        """Split a unitig with exactly two non-self links into two half-depth
        copies, one link each; self-links are copied to both
        (reference unitig_graph.rs:594-653)."""
        self.invalidate_paths_cache()
        target = self.index.get(unitig_num)
        if target is None:
            quit_with_error(f"unitig {unitig_num} not found in unitig index")
        non_self = [(target.number, link.signed_number())
                    for link in target.forward_next if link.number != unitig_num]
        non_self += [(-target.number, link.signed_number())
                     for link in target.reverse_next if link.number != unitig_num]
        if len(non_self) != 2:
            quit_with_error(f"unitig {unitig_num} does not contain exactly two "
                            "non-self links")
        self_links_fwd = [link.strand for link in target.forward_next
                          if link.number == unitig_num]
        self_links_rev = [link.strand for link in target.reverse_next
                          if link.number == unitig_num]

        a_num = self.max_unitig_number() + 1
        b_num = a_num + 1
        copies = []
        for new_num in (a_num, b_num):
            copy = Unitig(new_num, target.forward_seq.copy(), target.reverse_seq.copy(),
                          depth=target.depth / 2.0, unitig_type=target.unitig_type)
            copy.forward_positions = target.forward_positions.copy()
            copy.reverse_positions = target.reverse_positions.copy()
            copies.append(copy)
        self.unitigs.extend(copies)
        self.remove_unitigs_by_number({unitig_num})

        for strand in self_links_fwd:
            self.create_link(a_num, a_num if strand else -a_num)
            self.create_link(b_num, b_num if strand else -b_num)
        for strand in self_links_rev:
            self.create_link(-a_num, a_num if strand else -a_num)
            self.create_link(-b_num, b_num if strand else -b_num)

        def substitute(pair, new_num):
            start, end = pair
            start = new_num if start == unitig_num else (-new_num if start == -unitig_num else start)
            end = new_num if end == unitig_num else (-new_num if end == -unitig_num else end)
            return start, end

        self.create_link(*substitute(non_self[0], a_num))
        self.create_link(*substitute(non_self[1], b_num))
        self.check_links()

    def remove_low_depth_unitigs(self, min_depth: float) -> None:
        """Remove unitigs at/below the depth threshold, but only when removal
        creates no dead ends (reference unitig_graph.rs:670-721). Iterates in
        reverse unitig order so longer unitigs are kept."""
        self.invalidate_paths_cache()
        for u in list(reversed(self.unitigs)):
            if u.number not in self.index:
                continue
            if u.depth > min_depth:
                continue
            ok = True
            for next_us in u.forward_next:
                if next_us.number == u.number:
                    continue
                prevs = (next_us.unitig.forward_prev if next_us.strand
                         else next_us.unitig.reverse_prev)
                if not any(lk.number != u.number for lk in prevs):
                    ok = False
                    break
            if ok:
                for prev_us in u.forward_prev:
                    if prev_us.number == u.number:
                        continue
                    nexts = (prev_us.unitig.forward_next if prev_us.strand
                             else prev_us.unitig.reverse_next)
                    if not any(lk.number != u.number for lk in nexts):
                        ok = False
                        break
            if not ok:
                continue
            self.unitigs = [x for x in self.unitigs if x.number != u.number]
            self.delete_dangling_links()
            self.build_index()

    def subset_for_sequences(self, keep_ids) -> "UnitigGraph":
        """Independent copy of the graph restricted to the given sequence
        ids: unitigs keep (copied) positions of only those sequences, links
        are rewired onto the new Unitig objects, sequence byte arrays are
        shared (all mutation paths rebind rather than write in place).
        Replaces the reference's filter-P-lines-and-reload flow
        (cluster.rs:794-822) without the GFA round trip; the caller then
        recalculates depths / drops zero-depth unitigs exactly as after a
        reload."""
        keep = np.asarray(sorted(set(keep_ids)), np.int32)
        lut = PositionArray.seq_id_lut(keep)
        g = UnitigGraph(self.k_size)
        mapping: Dict[int, Unitig] = {}
        for u in self.unitigs:
            nu = Unitig(u.number, u.forward_seq, u._reverse_seq,
                        depth=u.depth, unitig_type=u.unitig_type)
            nu.forward_positions = u.forward_positions.only_seq_ids(keep, lut)
            nu.reverse_positions = u.reverse_positions.only_seq_ids(keep, lut)
            mapping[u.number] = nu
            g.unitigs.append(nu)
        for u in self.unitigs:
            nu = mapping[u.number]
            nu.forward_next = [UnitigStrand(mapping[l.number], l.strand)
                               for l in u.forward_next]
            nu.forward_prev = [UnitigStrand(mapping[l.number], l.strand)
                               for l in u.forward_prev]
            nu.reverse_next = [UnitigStrand(mapping[l.number], l.strand)
                               for l in u.reverse_next]
            nu.reverse_prev = [UnitigStrand(mapping[l.number], l.strand)
                               for l in u.reverse_prev]
        g.build_index()
        return g

    # ---------------- components ----------------

    def connected_components(self) -> List[List[int]]:
        """Connected components as sorted lists of unitig numbers, sorted
        (reference unitig_graph.rs:905-933). NOTE: a scipy.sparse.csgraph
        variant was measured 6x SLOWER here (1.7 s vs 0.29 s on the 43k-
        unitig headline graph) — the per-link Python edge extraction costs
        more than the BFS's set churn — so the plain BFS stays."""
        visited = set()
        components = []
        for unitig in self.unitigs:
            if unitig.number in visited:
                continue
            component = []
            stack = [unitig.number]
            while stack:
                current = stack.pop()
                if current in visited:
                    continue
                visited.add(current)
                component.append(current)
                u = self.index[current]
                for links in (u.forward_next, u.forward_prev, u.reverse_next, u.reverse_prev):
                    for c in links:
                        if c.number not in visited:
                            stack.append(c.number)
            component.sort()
            components.append(component)
        components.sort()
        return components

    def component_is_circular_loop(self, component: List[int]) -> bool:
        """Whether a component forms one simple circular loop
        (reference unitig_graph.rs:949-967)."""
        if not component:
            return False
        first = component[0]
        num, strand = first, FORWARD
        visited = set()
        while num != first or not visited:
            if num in visited:
                return False
            visited.add(num)
            unitig = self.index[num]
            if (len(unitig.forward_next) != 1 or len(unitig.forward_prev) != 1 or
                    len(unitig.reverse_next) != 1 or len(unitig.reverse_prev) != 1):
                return False
            nxt = unitig.forward_next[0] if strand else unitig.reverse_next[0]
            num, strand = nxt.number, nxt.strand
        return len(visited) == len(component)
