"""ctypes bridge to the native host kernels (native/seqkernel.cpp).

The shared library is built on demand with the system compiler (the image
has no pybind11; the ABI is plain C). When no compiler is available the
callers fall back to the numpy implementations transparently.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = Path(__file__).resolve().parent.parent / "native"

# must match sk_abi_version() in seqkernel.cpp
ABI_VERSION = 3


def _lib_path() -> Path:
    """AUTOCYCLER_NATIVE_LIB overrides the source-tree location — installed
    packages (pip/containers) don't carry native/, so deployments point this
    at wherever they built libseqkernel.so."""
    from .utils.knobs import knob_str
    override = knob_str("AUTOCYCLER_NATIVE_LIB")
    if override:
        return Path(override)
    return _NATIVE_DIR / "libseqkernel.so"


_lib: Optional[ctypes.CDLL] = None
_tried = False
# get_lib can be hit concurrently on first use (trim/compress --threads
# pools); the lock keeps one thread building+loading while others wait
import threading
_lib_lock = threading.Lock()


def _build(lib_path: Path) -> bool:
    from .utils import resilience
    if resilience.fault_fire("native_build", str(lib_path)) is not None:
        return False
    src = _NATIVE_DIR / "seqkernel.cpp"
    if not src.is_file():
        return False
    try:
        subprocess.run(
            ["g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
             str(src), "-o", str(lib_path)],
            check=True, capture_output=True, timeout=120)
        return lib_path.is_file()
    except Exception:
        return False


def _stale(lib_path: Path) -> bool:
    src = _NATIVE_DIR / "seqkernel.cpp"
    try:
        return src.is_file() and src.stat().st_mtime > lib_path.stat().st_mtime
    except OSError:
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded library, (re)building it first if missing or older than the
    source; None if unavailable."""
    with _lib_lock:
        return _get_lib_locked()


def _reset_for_tests() -> None:
    """Forget the loaded/attempted library so fault-injection tests can walk
    the load paths again; production code never calls this."""
    global _lib, _tried
    with _lib_lock:
        _lib = None
        _tried = False


def _get_lib_locked() -> Optional[ctypes.CDLL]:
    from .utils import resilience

    global _lib, _tried
    if _lib is not None:
        return _lib
    if _tried:
        return None
    _tried = True
    lib_path = _lib_path()
    if resilience.fault_fire("native_load", str(lib_path)) is not None:
        resilience.record_degrade(
            "native", "ctypes", "numpy",
            "fault-injected library load failure")
        return None
    if (not lib_path.is_file() or _stale(lib_path)) and not _build(lib_path):
        if not lib_path.is_file():
            resilience.record_degrade(
                "native", "ctypes", "numpy",
                f"{lib_path.name} missing and build failed (no compiler?)")
            return None
        if _stale(lib_path):
            # the ABI gate below only catches signature changes; semantic
            # fixes that keep the ABI would otherwise run old code silently
            import sys
            print(f"autocycler: rebuild of {lib_path} failed; loading the "
                  f"STALE binary (older than seqkernel.cpp)", file=sys.stderr)
    try:
        lib = ctypes.CDLL(str(lib_path))
        # versioned feature set: a prebuilt library with a different ABI
        # (e.g. pinned via AUTOCYCLER_NATIVE_LIB) must not be called through
        # the newer signatures — fall back to numpy for those paths instead
        try:
            lib.sk_abi_version.restype = ctypes.c_int32
            lib.sk_abi_version.argtypes = []
            got_abi = lib.sk_abi_version()
            abi_ok = got_abi == ABI_VERSION
        except AttributeError:
            got_abi = None
            abi_ok = False
        if abi_ok and \
                resilience.fault_fire("native_abi", str(lib_path)) is not None:
            got_abi = "fault-injected mismatch"
            abi_ok = False
        if not abi_ok:
            resilience.record_degrade(
                "native-abi", f"abi-v{ABI_VERSION}", "numpy (ABI-gated kernels)",
                f"{lib_path.name} reports ABI {got_abi!r}, expected "
                f"{ABI_VERSION}; versioned kernels fall back to numpy")
        lib._abi_ok = abi_ok
        lib.sk_group_windows.restype = ctypes.c_int64
        lib.sk_group_windows.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        lib.sk_pack_words.restype = None
        lib.sk_pack_words.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int32, ctypes.POINTER(ctypes.c_int32)]
        lib.sk_group_kmers.restype = ctypes.c_int64
        lib.sk_group_kmers.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
        lib.sk_overlap_dp.restype = None
        lib.sk_overlap_dp.argtypes = [
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double),
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_double)]
        lib.sk_scan_gram_matches.restype = ctypes.c_int64
        lib.sk_scan_gram_matches.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64)]
        try:
            lib.sk_occ_index_build.restype = ctypes.c_int64
            lib.sk_occ_index_build.argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int32)]
            lib.sk_occ_index_finish.restype = ctypes.c_int32
            lib.sk_occ_index_finish.argtypes = [
                ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32)]
        except AttributeError:
            lib._has_occ_index = False
        else:
            lib._has_occ_index = abi_ok
        try:
            lib.sk_scan_gram_begin.restype = ctypes.c_int64
            lib.sk_scan_gram_begin.argtypes = [
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
            lib.sk_scan_gram_fetch.restype = ctypes.c_int32
            lib.sk_scan_gram_fetch.argtypes = [
                ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int64)]
        except AttributeError:
            lib._has_gram_begin = False
        else:
            lib._has_gram_begin = abi_ok
        try:
            lib.sk_overlap_dp_tb.restype = None
            lib.sk_overlap_dp_tb.argtypes = [
                ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double),
                ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_double),
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
                ctypes.POINTER(ctypes.c_double), ctypes.POINTER(ctypes.c_uint64)]
        except AttributeError:
            lib._has_dp_tb = False
        else:
            lib._has_dp_tb = abi_ok
        try:
            lib.sk_collect_marked_begin.restype = ctypes.c_int64
            lib.sk_collect_marked_begin.argtypes = [
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_uint8)]
            lib.sk_collect_marked_fetch.restype = ctypes.c_int32
            lib.sk_collect_marked_fetch.argtypes = [
                ctypes.POINTER(ctypes.c_int64)]
        except AttributeError:
            lib._has_collect = False
        else:
            lib._has_collect = abi_ok
        try:
            lib.sk_chain_walk.restype = ctypes.c_int64
            lib.sk_chain_walk.argtypes = [
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_uint8)]
        except AttributeError:
            lib._has_chain_walk = False
        else:
            lib._has_chain_walk = abi_ok
        _lib = lib
        return lib
    except OSError as e:
        resilience.record_degrade(
            "native", "ctypes", "numpy",
            f"loading {lib_path.name} failed: {e}")
        return None
    except AttributeError as e:
        # a pinned AUTOCYCLER_NATIVE_LIB predating even the stable symbol set
        # (sk_group_windows, sk_overlap_dp, ...) — treat as unavailable
        resilience.record_degrade(
            "native", "ctypes", "numpy",
            f"{lib_path.name} predates the stable symbol set ({e})")
        return None


def available() -> bool:
    return get_lib() is not None


def pack_words_native(codes: np.ndarray, starts: np.ndarray,
                      k: int) -> Optional[np.ndarray]:
    """codes uint8 (values 0..4) + window starts -> [W, n] int32 packed words
    (same layout as ops.kmers), or None when the library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    codes = np.ascontiguousarray(codes, dtype=np.uint8)
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    n = len(starts)
    W = (k + 9) // 10
    out = np.empty((W, n), dtype=np.int32)
    lib.sk_pack_words(
        codes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(n), ctypes.c_int32(k),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    return out


def group_kmers_full(codes: np.ndarray, starts: np.ndarray,
                     k: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Fused pack + group: codes uint8 (0..4) and window starts ->
    (gid, order) where gid[i] is window i's lexicographic-rank group id and
    order is the stable grouped permutation. None when unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    codes = np.ascontiguousarray(codes, dtype=np.uint8)
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    n = len(starts)
    gid = np.empty(n, dtype=np.int64)
    order = np.empty(n, dtype=np.int64)
    u = lib.sk_group_kmers(
        codes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(n), ctypes.c_int32(k),
        gid.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        order.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    if u < 0:
        return None
    return gid, order


def group_kmers_native(codes: np.ndarray, starts: np.ndarray,
                       k: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(order, gid_sorted) view of group_kmers_full — the group_windows
    contract."""
    result = group_kmers_full(codes, starts, k)
    if result is None:
        return None
    gid, order = result
    return order, gid[order]


def build_occ_index(seq_bytes: np.ndarray, fwd_off: np.ndarray, rev_off: np.ndarray,
                    seq_len: np.ndarray, k: int) -> Optional[dict]:
    """Fused occurrence-index build (k <= 55): one native call produces every
    per-occurrence and per-k-mer array ops.kmers.build_kmer_index needs.
    seq_bytes is the RAW padded ASCII buffer — the kernel translates symbols
    inline. Returns a dict of arrays, or None when unavailable."""
    lib = get_lib()
    if lib is None or not getattr(lib, "_has_occ_index", False) or k > 55:
        return None
    seq_bytes = np.ascontiguousarray(seq_bytes, dtype=np.uint8)
    fwd_off = np.ascontiguousarray(fwd_off, dtype=np.int64)
    rev_off = np.ascontiguousarray(rev_off, dtype=np.int64)
    seq_len = np.ascontiguousarray(seq_len, dtype=np.int64)
    S = len(seq_len)
    n_f = int(seq_len.sum())
    out_G = ctypes.c_int64(0)
    fwd_gid = np.empty(n_f, dtype=np.int32)  # written in place by the build
    U = lib.sk_occ_index_build(
        seq_bytes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_int64(len(seq_bytes)),
        fwd_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        rev_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        seq_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(S), ctypes.c_int32(k), ctypes.byref(out_G),
        fwd_gid.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if U < 0:
        return None
    depth = np.empty(U, dtype=np.int64)
    rep_byte = np.empty(U, dtype=np.int64)
    rev_kid = np.empty(U, dtype=np.int32)
    prefix_gid = np.empty(U, dtype=np.int32)
    suffix_gid = np.empty(U, dtype=np.int32)
    rc = lib.sk_occ_index_finish(
        depth.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        rep_byte.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        rev_kid.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        prefix_gid.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        suffix_gid.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
    if rc != 0:
        return None
    return dict(U=int(U), G=int(out_G.value), fwd_gid=fwd_gid, depth=depth,
                rep_byte=rep_byte, rev_kid=rev_kid,
                prefix_gid=prefix_gid, suffix_gid=suffix_gid)


def collect_marked(gid: np.ndarray, mark: np.ndarray):
    """Indices i with mark[gid[i]] set, as one native pass; None if
    unavailable (caller falls back to the numpy gather)."""
    lib = get_lib()
    if lib is None or not getattr(lib, "_has_collect", False):
        return None
    gid = np.ascontiguousarray(gid, dtype=np.int32)
    mark = np.ascontiguousarray(mark, dtype=np.uint8)
    count = lib.sk_collect_marked_begin(
        gid.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int64(len(gid)),
        mark.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    if count < 0:
        return None
    out = np.empty(count, dtype=np.int64)
    if lib.sk_collect_marked_fetch(
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))) != 0:
        return None
    return out


def chain_walk(next_int: np.ndarray):
    """Walk the internal-successor forest into unitig chains (exact same
    chain order/content as the pointer-doubling fallback in ops.debruijn).
    Returns (members, chain_off, is_cycle) or None when unavailable."""
    lib = get_lib()
    if lib is None or not getattr(lib, "_has_chain_walk", False):
        return None
    next_int = np.ascontiguousarray(next_int, dtype=np.int64)
    U = len(next_int)
    members = np.empty(U, dtype=np.int64)
    chain_off = np.empty(U + 1, dtype=np.int64)
    is_cycle = np.empty(U, dtype=np.uint8)
    C = lib.sk_chain_walk(
        next_int.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(U),
        members.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        chain_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        is_cycle.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    if C < 0:
        return None
    return members, chain_off[:C + 1], is_cycle[:C].astype(bool)


def overlap_dp_native(a_vals: np.ndarray, wa: np.ndarray, b_vals: np.ndarray,
                      wb: np.ndarray, n: int, kk: int,
                      skip_diagonal: bool) -> Optional[np.ndarray]:
    """Fill the (kk+1)^2 overlap-DP scoring matrix (bit-identical to the
    numpy row scans in ops.align); None when the library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    a_vals = np.ascontiguousarray(a_vals, dtype=np.int64)
    wa = np.ascontiguousarray(wa, dtype=np.float64)
    b_vals = np.ascontiguousarray(b_vals, dtype=np.int64)
    wb = np.ascontiguousarray(wb, dtype=np.float64)
    matrix = np.empty((kk + 1, kk + 1), dtype=np.float64)
    lib.sk_overlap_dp(
        a_vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        wa.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        b_vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        wb.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.c_int64(n), ctypes.c_int64(kk),
        ctypes.c_int32(1 if skip_diagonal else 0),
        matrix.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
    return matrix


def overlap_dp_tb_native(a_vals: np.ndarray, wa: np.ndarray, b_vals: np.ndarray,
                         wb: np.ndarray, n: int, kk: int, skip_diagonal: bool):
    """Rolling-row overlap DP: returns (right_edge_scores[kk+1],
    traceback_bits[(kk+1)*words], words) with scores/decisions bit-identical
    to the full-matrix kernel, using O(kk) score memory. None if unavailable."""
    lib = get_lib()
    if lib is None or not getattr(lib, "_has_dp_tb", False):
        return None
    a_vals = np.ascontiguousarray(a_vals, dtype=np.int64)
    wa = np.ascontiguousarray(wa, dtype=np.float64)
    b_vals = np.ascontiguousarray(b_vals, dtype=np.int64)
    wb = np.ascontiguousarray(wb, dtype=np.float64)
    words = (kk + 1 + 63) // 64
    right = np.empty(kk + 1, dtype=np.float64)
    bits = np.zeros((kk + 1) * words, dtype=np.uint64)
    lib.sk_overlap_dp_tb(
        a_vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        wa.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        b_vals.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        wb.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        ctypes.c_int64(n), ctypes.c_int64(kk),
        ctypes.c_int32(1 if skip_diagonal else 0),
        right.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        bits.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)))
    return right, bits, words


def scan_gram_matches_native(codes: np.ndarray, text_off: np.ndarray,
                             text_len: np.ndarray, h: int, q_starts: np.ndarray
                             ) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Find every occurrence of the Q query h-grams (given as byte offsets
    into codes) across the text segments. Returns (query_idx, text_idx,
    local_pos) ordered by (text, pos), or None when unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    codes = np.ascontiguousarray(codes, dtype=np.uint8)
    text_off = np.ascontiguousarray(text_off, dtype=np.int64)
    text_len = np.ascontiguousarray(text_len, dtype=np.int64)
    q_starts = np.ascontiguousarray(q_starts, dtype=np.int64)

    if getattr(lib, "_has_gram_begin", False):
        # single-pass: scan once with retained results, then fetch
        count = lib.sk_scan_gram_begin(
            codes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            text_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            text_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(len(text_off)), ctypes.c_int32(h),
            q_starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(len(q_starts)))
        if count < 0:
            return None
        out_q = np.empty(count, dtype=np.int32)
        out_t = np.empty(count, dtype=np.int32)
        out_p = np.empty(count, dtype=np.int64)
        if lib.sk_scan_gram_fetch(
                out_q.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                out_t.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                out_p.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))) != 0:
            return None
        return out_q, out_t, out_p

    def call(out_q, out_t, out_p):
        return lib.sk_scan_gram_matches(
            codes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            text_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            text_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(len(text_off)), ctypes.c_int32(h),
            q_starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(len(q_starts)), out_q, out_t, out_p)

    null_i32 = ctypes.POINTER(ctypes.c_int32)()
    null_i64 = ctypes.POINTER(ctypes.c_int64)()
    count = call(null_i32, null_i32, null_i64)
    if count < 0:
        return None
    out_q = np.empty(count, dtype=np.int32)
    out_t = np.empty(count, dtype=np.int32)
    out_p = np.empty(count, dtype=np.int64)
    call(out_q.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
         out_t.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
         out_p.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    return out_q, out_t, out_p


def group_windows_native(words: np.ndarray
                         ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """words: [W, n] int32, most significant word first.

    Returns (order, gid_sorted) with the exact same contract as the numpy
    lexsort grouping (group ids are lexicographic ranks; order is the stable
    grouped permutation), or None when the native library is unavailable.
    """
    lib = get_lib()
    if lib is None:
        return None
    words = np.ascontiguousarray(words, dtype=np.int32)
    W, n = words.shape
    gid = np.empty(n, dtype=np.int64)
    order = np.empty(n, dtype=np.int64)
    u = lib.sk_group_windows(
        words.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_int64(n), ctypes.c_int32(W),
        gid.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        order.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    if u < 0:
        return None
    return order, gid[order]
