"""Run telemetry: span tracing, the metrics registry, memory sampling and
the `autocycler report` renderer.

The pipeline's observability fragments (utils.timing accumulators,
utils.cache hit counters, utils.resilience degrade events, bench
artifacts) all write through this package, so one run directory — driven
by ``AUTOCYCLER_TRACE_DIR`` — answers "what did this run spend its time
and memory on, and what degraded?". See docs/observability.md.
"""

from . import metrics_registry, sentinel, trace
from .memory import memory_sample
from .metrics_registry import (MetricsRegistry, counter_inc, gauge_set,
                               info_set, observe, registry, snapshot,
                               to_prometheus)
from .trace import (current_span, finish_run, maybe_start_run, span,
                    start_run, tracing_active)
