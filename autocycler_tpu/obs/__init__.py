"""Run telemetry: span tracing, the metrics registry, memory sampling and
the `autocycler report` renderer.

The pipeline's observability fragments (utils.timing accumulators,
utils.cache hit counters, utils.resilience degrade events, bench
artifacts) all write through this package, so one run directory — driven
by ``AUTOCYCLER_TRACE_DIR`` — answers "what did this run spend its time
and memory on, and what degraded?". The data-plane layer adds "what did
the *assembly* look like, and where did every artifact come from?":
``qc`` journals per-stage scientific QC into ``qc_report.json``,
``ledger`` hashes input→output artifact lineage into ``ledger.json``, and
``watch`` follows another process's run live. See docs/observability.md.
"""

from . import ledger, metrics_registry, qc, sentinel, timeseries, trace, watch
from .memory import memory_sample
from .metrics_registry import (MetricsRegistry, counter_inc, gauge_set,
                               info_set, observe, quantile, registry,
                               snapshot, to_prometheus)
from .timeseries import (TimeseriesSampler, read_timeseries,
                         summarize_timeseries)
from .trace import (current_span, finish_run, maybe_start_run, span,
                    start_run, tracing_active)
