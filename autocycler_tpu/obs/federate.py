"""Fleet federation: many serve replicas, one merged observability view.

Every surface below this module is single-process (`/metrics`,
`/healthz`, `top`, `timeseries.jsonl` all describe ONE daemon); this is
the layer that sees the fleet. Three pieces:

- a **replica registry**: an explicit endpoint list, or a *fleet dir*
  scanned for ``serve.json`` discovery files (the fleet dir itself plus
  each immediate subdirectory — the natural layout is one serve root per
  replica under a shared parent);
- a **never-raise scraper** (:class:`FleetScraper`): polls each replica's
  ``/healthz`` and ``/metrics?format=json`` with a per-replica timeout
  (``AUTOCYCLER_FED_TIMEOUT_S``). A replica that fails a scrape keeps its
  last-known health for ``AUTOCYCLER_FED_STALE_S`` seconds, marked
  ``stale`` — an operator sees "old data" rather than a hole — and is
  never picked by the router;
- a **merged snapshot** written atomically to ``fleet_status.json``:
  counters summed across replicas, gauges kept per-replica plus a
  rollup, and latency histograms merged bucket-wise (counts added
  edge-for-edge, min-of-mins / max-of-maxes) so the merged entry keeps
  the registry snapshot shape and fleet p50/p95 fall out of the same
  :func:`obs.timeseries.snapshot_quantile` every other reader uses.

On top of the snapshot rides the **scale-verdict engine**: scale_out /
steady / scale_in from the fleet burn rate, worker utilization and queue
depth, gated by hysteresis (``AUTOCYCLER_SCALE_HYSTERESIS`` consecutive
agreeing polls) and a flip cooldown (``AUTOCYCLER_SCALE_COOLDOWN_S``).
Engine state persists inside ``fleet_status.json``, so one-shot
``autocycler top --fleet`` invocations accumulate hysteresis across
processes exactly like a long-lived poller.
"""

from __future__ import annotations

import contextlib
import json
import os
import tempfile
import time
from pathlib import Path
from typing import Dict, List, Optional

from .. import __version__
from ..serve.protocol import SERVE_INFO_JSON
from ..utils import AutocyclerError
from ..utils.knobs import knob_float, knob_int
from . import metrics_registry
from .timeseries import _flat_key, snapshot_quantile

FLEET_STATUS_JSON = "fleet_status.json"

# federation self-telemetry (the scraper is itself a replica-grade
# process, so its own registry carries these; label is `replica`, never
# the Prometheus-reserved `job`)
SCRAPES_TOTAL = "autocycler_fed_scrapes_total"
SCRAPE_SECONDS = "autocycler_fed_scrape_seconds"
REPLICAS_GAUGE = "autocycler_fed_replicas"
VERDICT_GAUGE = "autocycler_fed_scale_verdict"

# replica version skew detection: every /metrics export carries this info
# metric (package version in the value label, runtime versions as labels)
BUILD_INFO = "autocycler_build_info"

VERDICTS = ("scale_in", "steady", "scale_out")
_VERDICT_VALUE = {"scale_in": -1, "steady": 0, "scale_out": 1}


# ---- knobs (re-read per call, operator-tunable against a live poller) ----

def fed_timeout_s() -> float:
    return max(0.05, float(knob_float("AUTOCYCLER_FED_TIMEOUT_S")))


def fed_stale_s() -> float:
    return max(0.0, float(knob_float("AUTOCYCLER_FED_STALE_S")))


def scale_knobs() -> dict:
    return {
        "out_burn": float(knob_float("AUTOCYCLER_SCALE_OUT_BURN")),
        "out_util": float(knob_float("AUTOCYCLER_SCALE_OUT_UTIL")),
        "out_queue": float(knob_float("AUTOCYCLER_SCALE_OUT_QUEUE")),
        "in_util": float(knob_float("AUTOCYCLER_SCALE_IN_UTIL")),
        "cooldown_s": max(0.0,
                          float(knob_float("AUTOCYCLER_SCALE_COOLDOWN_S"))),
        "hysteresis": max(1, int(knob_int("AUTOCYCLER_SCALE_HYSTERESIS"))),
    }


# ---- build info ----

def build_info() -> Dict[str, str]:
    """Package + runtime versions of THIS process — what a federated
    scrape compares across replicas to detect version skew. Best-effort
    on every import (a replica without numpy still exports)."""
    info = {"autocycler_tpu": __version__}
    for mod in ("jax", "jaxlib", "numpy"):
        try:
            info[mod] = str(__import__(mod).__version__)
        except Exception:  # noqa: BLE001 — version probing must never fail
            info[mod] = "unavailable"
    return info


def record_build_info(registry=None) -> Dict[str, str]:
    """Export :func:`build_info` as the ``autocycler_build_info`` info
    metric (package version in the sample value, runtime versions as
    labels) — called once at daemon startup so every /metrics scrape
    carries it."""
    reg = registry or metrics_registry.registry()
    info = build_info()
    labels = {k: v for k, v in info.items() if k != "autocycler_tpu"}
    reg.info_set(BUILD_INFO, info["autocycler_tpu"],
                 help="package and runtime versions of this replica",
                 **labels)
    return info


# ---- replica registry ----

def read_serve_info(path) -> dict:
    """Never-raise ``serve.json`` reader: a missing, torn or non-object
    discovery file is an empty dict, mirroring ``read_manifest``."""
    try:
        data = json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return {}
    return data if isinstance(data, dict) else {}


def discover_replicas(fleet_dir=None,
                      endpoints: Optional[List[str]] = None) -> List[dict]:
    """The replica registry: explicit endpoints first (named
    ``replica-N``), then every ``serve.json`` under ``fleet_dir`` (the dir
    itself and each immediate subdirectory, named by the directory).
    Duplicate endpoints collapse to the first mention. Never raises."""
    replicas: List[dict] = []
    seen = set()
    for i, raw in enumerate(endpoints or []):
        endpoint = str(raw).strip()
        if not endpoint or endpoint in seen:
            continue
        seen.add(endpoint)
        replicas.append({"name": f"replica-{i}", "endpoint": endpoint,
                         "root": None, "info": {}})
    if fleet_dir is not None:
        fleet_dir = Path(fleet_dir)
        candidates = [fleet_dir / SERVE_INFO_JSON]
        with contextlib.suppress(OSError):
            candidates.extend(sorted(
                p / SERVE_INFO_JSON for p in fleet_dir.iterdir()
                if p.is_dir()))
        for path in candidates:
            info = read_serve_info(path)
            endpoint = info.get("endpoint")
            if not isinstance(endpoint, str) or not endpoint \
                    or endpoint in seen:
                continue
            seen.add(endpoint)
            replicas.append({"name": path.parent.name or str(path.parent),
                             "endpoint": endpoint,
                             "root": str(path.parent), "info": info})
    return replicas


# ---- scraping ----

def scrape_replica(endpoint: str, timeout: Optional[float] = None) -> dict:
    """One replica's /healthz + /metrics?format=json, or ``{"error":
    ...}``. Never raises — a dead or slow replica costs one timeout, not
    the poll."""
    timeout = fed_timeout_s() if timeout is None else timeout
    from ..serve.client import request_json
    try:
        status, health = request_json(endpoint, "GET", "/healthz",
                                      timeout=timeout)
        if status != 200 or not isinstance(health, dict):
            return {"error": f"healthz returned HTTP {status}"}
        out = {"health": health, "metrics": {}}
        status, snap = request_json(endpoint, "GET", "/metrics?format=json",
                                    timeout=timeout)
        if status == 200 and isinstance(snap, dict):
            out["metrics"] = {name: metric for name, metric in snap.items()
                              if isinstance(metric, dict)
                              and isinstance(metric.get("values"), list)}
        return out
    except (AutocyclerError, OSError, ValueError) as e:
        return {"error": str(e)}


# ---- merging ----

def merge_hist_entries(entries: List[dict]) -> Optional[dict]:
    """Merge per-replica snapshot histogram entries bucket-wise into one
    entry KEEPING the snapshot shape, so :func:`snapshot_quantile` works
    on the result unchanged. Only entries sharing the same bucket edges
    merge (mismatched ladders cannot be added meaningfully); when edges
    disagree across replicas, the group with the most observations wins
    and the rest are counted in ``skipped``."""
    groups: Dict[tuple, List[dict]] = {}
    for entry in entries:
        buckets = entry.get("buckets")
        if not isinstance(buckets, dict) or not entry.get("count"):
            continue
        groups.setdefault(tuple(buckets.keys()), []).append(entry)
    if not groups:
        return None
    sig, group = max(groups.items(),
                     key=lambda kv: sum(e.get("count", 0) for e in kv[1]))
    merged: dict = {"labels": dict(group[0].get("labels") or {}),
                    "sum": 0.0, "count": 0, "min": None, "max": None,
                    "buckets": {edge: 0 for edge in sig},
                    "replicas": len(group),
                    "skipped": sum(len(g) for g in groups.values())
                    - len(group)}
    for entry in group:
        merged["count"] += int(entry.get("count") or 0)
        merged["sum"] = round(merged["sum"]
                              + float(entry.get("sum") or 0.0), 6)
        for bound in ("min", "max"):
            val = entry.get(bound)
            if isinstance(val, (int, float)):
                best = merged[bound]
                pick = min if bound == "min" else max
                merged[bound] = val if best is None else pick(best, val)
        for edge in sig:
            count = entry["buckets"].get(edge)
            if isinstance(count, int):
                merged["buckets"][edge] += count
    return merged


def merge_metrics(snapshots: Dict[str, dict]) -> dict:
    """Merge per-replica registry snapshots into the fleet view:
    ``counters`` summed per flat key, ``gauges`` kept per-replica with a
    sum/min/max rollup, ``hists`` merged bucket-wise with fleet p50/p95
    attached. Info metrics are kept per-replica (skew shows up as
    differing values)."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, dict] = {}
    hists: Dict[str, List[dict]] = {}
    infos: Dict[str, Dict[str, str]] = {}
    for rname in sorted(snapshots):
        snap = snapshots.get(rname) or {}
        for name, metric in snap.items():
            kind = metric.get("type")
            for entry in metric.get("values") or []:
                if not isinstance(entry, dict):
                    continue
                key = _flat_key(name, entry.get("labels") or {})
                value = entry.get("value")
                if kind == "counter" and isinstance(value, (int, float)):
                    counters[key] = round(counters.get(key, 0.0) + value, 6)
                elif kind == "gauge" and isinstance(value, (int, float)):
                    gauges.setdefault(key, {"replicas": {}})
                    gauges[key]["replicas"][rname] = value
                elif kind == "info":
                    infos.setdefault(key, {})[rname] = str(value)
                elif kind == "histogram":
                    hists.setdefault(key, []).append(entry)
    for rollup in gauges.values():
        vals = [v for v in rollup["replicas"].values()
                if isinstance(v, (int, float))]
        if vals:
            rollup.update(sum=round(sum(vals), 6), min=min(vals),
                          max=max(vals))
    merged_hists: Dict[str, dict] = {}
    for key, entries in hists.items():
        merged = merge_hist_entries(entries)
        if merged is not None:
            merged["p50"] = snapshot_quantile(merged, 0.50)
            merged["p95"] = snapshot_quantile(merged, 0.95)
            merged_hists[key] = merged
    return {"counters": counters, "gauges": gauges, "hists": merged_hists,
            "info": infos}


def build_summary(blocks: Dict[str, dict]) -> dict:
    """The fleet rollup the verdict engine (and `top --fleet`) consumes:
    queue/busy/worker sums, utilization, the worst per-replica burn rate
    and a version-skew flag, over every replica with usable (fresh or
    stale-carried) health."""
    healthy = [b for b in blocks.values() if b.get("healthy")]
    stale = [b for b in blocks.values()
             if not b.get("healthy") and isinstance(b.get("health"), dict)]
    usable = healthy + stale
    queue = busy = workers = 0
    burn: Optional[float] = None
    versions = set()
    jobs: Dict[str, int] = {}
    for block in usable:
        health = block["health"]
        queue += int(health.get("queue_depth") or 0)
        busy += int(health.get("busy_workers") or 0)
        workers += int(health.get("workers") or 0)
        if isinstance(health.get("version"), str):
            versions.add(health["version"])
        for state, n in (health.get("jobs") or {}).items():
            if isinstance(n, int):
                jobs[state] = jobs.get(state, 0) + n
        rate = (health.get("slo") or {}).get("burn_rate")
        if isinstance(rate, (int, float)):
            burn = rate if burn is None else max(burn, rate)
    return {
        "replicas": len(blocks),
        "healthy": len(healthy),
        "stale": len(stale),
        "down": len(blocks) - len(healthy) - len(stale),
        "queue_depth": queue,
        "busy_workers": busy,
        "workers": workers,
        "utilization": round(busy / workers, 4) if workers else None,
        "queue_per_replica": round(queue / max(1, len(healthy)), 4),
        "burn_rate": burn,
        "jobs": jobs,
        "versions": sorted(versions),
        "version_skew": len(versions) > 1,
    }


# ---- scale verdicts ----

class ScaleVerdictEngine:
    """Hysteresis-gated scale verdicts over the fleet summary.

    The *desired* verdict is recomputed every poll from the knobs
    (scale_out on burn, utilization or queue pressure; scale_in only on
    an idle multi-replica fleet with ``AUTOCYCLER_SCALE_IN_UTIL`` raised
    above its scale_in-disabling default of 0.0). The *published* verdict
    only flips after ``AUTOCYCLER_SCALE_HYSTERESIS`` consecutive polls
    agree AND the last flip is older than ``AUTOCYCLER_SCALE_COOLDOWN_S``
    — a single noisy window sample can never flap an autoscaler.

    State round-trips through the ``verdict`` block of
    ``fleet_status.json`` so one-shot pollers keep hysteresis."""

    def __init__(self, state: Optional[dict] = None):
        state = state if isinstance(state, dict) else {}
        self.verdict = state.get("verdict") \
            if state.get("verdict") in VERDICTS else "steady"
        self.streak_verdict = state.get("streak_verdict") \
            if state.get("streak_verdict") in VERDICTS else self.verdict
        self.streak = state.get("streak") \
            if isinstance(state.get("streak"), int) else 0
        self.since_epoch = state.get("since_epoch") \
            if isinstance(state.get("since_epoch"), (int, float)) else None
        self.last_flip_epoch = state.get("last_flip_epoch") \
            if isinstance(state.get("last_flip_epoch"), (int, float)) \
            else None

    def desired(self, summary: dict) -> tuple:
        """(desired verdict, reasons) from one fleet summary — ungated."""
        knobs = scale_knobs()
        burn = summary.get("burn_rate")
        util = summary.get("utilization")
        queue_pr = summary.get("queue_per_replica") or 0.0
        reasons: List[str] = []
        if isinstance(burn, (int, float)) and burn > knobs["out_burn"]:
            reasons.append(f"burn {burn:g} > {knobs['out_burn']:g}")
        if isinstance(util, (int, float)) and util > knobs["out_util"]:
            reasons.append(
                f"utilization {util:g} > {knobs['out_util']:g}")
        if queue_pr > knobs["out_queue"]:
            reasons.append(
                f"queue/replica {queue_pr:g} > {knobs['out_queue']:g}")
        if reasons:
            return "scale_out", reasons
        if summary.get("healthy", 0) > 1 \
                and isinstance(util, (int, float)) \
                and util < knobs["in_util"] \
                and not summary.get("queue_depth", 0) \
                and (burn is None or burn <= knobs["out_burn"] / 2.0):
            return "scale_in", [f"utilization {util:g} < "
                                f"{knobs['in_util']:g} with empty queue"]
        return "steady", reasons

    def evaluate(self, summary: dict, now: Optional[float] = None) -> dict:
        now = time.time() if now is None else now
        knobs = scale_knobs()
        desired, reasons = self.desired(summary)
        if desired == self.verdict:
            self.streak_verdict, self.streak = desired, 0
        else:
            if desired == self.streak_verdict:
                self.streak += 1
            else:
                self.streak_verdict, self.streak = desired, 1
            cooled = self.last_flip_epoch is None \
                or now - self.last_flip_epoch >= knobs["cooldown_s"]
            if self.streak >= knobs["hysteresis"] and cooled:
                self.verdict = desired
                self.since_epoch = now
                self.last_flip_epoch = now
                self.streak = 0
        if self.since_epoch is None:
            self.since_epoch = now
        remaining = 0.0
        if self.last_flip_epoch is not None:
            remaining = max(0.0, knobs["cooldown_s"]
                            - (now - self.last_flip_epoch))
        return {
            "verdict": self.verdict,
            "desired": desired,
            "reasons": reasons,
            "streak": self.streak,
            "streak_verdict": self.streak_verdict,
            "needed": knobs["hysteresis"],
            "since_epoch": round(self.since_epoch, 3),
            "last_flip_epoch": round(self.last_flip_epoch, 3)
            if self.last_flip_epoch is not None else None,
            "cooldown_s": knobs["cooldown_s"],
            "cooldown_remaining_s": round(remaining, 3),
        }


# ---- the poller ----

def read_fleet_status(path) -> dict:
    """Never-raise ``fleet_status.json`` reader (missing/torn -> {})."""
    if path is None:
        return {}
    try:
        data = json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return {}
    return data if isinstance(data, dict) else {}


def write_fleet_status(path, snap: dict) -> Optional[Path]:
    """Atomic write (tempfile + rename) — a crashed poller or a
    concurrent reader never sees a torn snapshot. Never raises."""
    path = Path(path)
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=str(path.parent),
                                   prefix=path.name + ".tmp")
        with os.fdopen(fd, "w") as f:
            json.dump(snap, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return path
    except OSError:
        return None


class FleetScraper:
    """Polls every registered replica and maintains ``fleet_status.json``.

    One :meth:`poll` = one scrape of each replica + merge + verdict +
    atomic snapshot write. Construction reloads prior snapshot state, so
    staleness carry-forward and verdict hysteresis survive process
    boundaries (each `top --fleet` frame is its own process)."""

    def __init__(self, fleet_dir=None,
                 endpoints: Optional[List[str]] = None,
                 out_path=None, registry=None):
        self.fleet_dir = Path(fleet_dir) if fleet_dir is not None else None
        self.endpoints = list(endpoints) if endpoints else None
        if out_path is None and self.fleet_dir is not None:
            out_path = self.fleet_dir / FLEET_STATUS_JSON
        self.out_path = Path(out_path) if out_path is not None else None
        self._registry = registry or metrics_registry.registry()
        prior = read_fleet_status(self.out_path)
        self.engine = ScaleVerdictEngine(state=prior.get("verdict"))
        prev = prior.get("replicas")
        self._prev_replicas: Dict[str, dict] = \
            prev if isinstance(prev, dict) else {}

    def poll(self) -> dict:
        """One fleet poll; returns (and persists) the merged snapshot.
        Never raises — every replica failure is data, not an exception."""
        now = time.time()
        timeout = fed_timeout_s()
        stale_s = fed_stale_s()
        replicas = discover_replicas(self.fleet_dir, self.endpoints)
        blocks: Dict[str, dict] = {}
        snapshots: Dict[str, dict] = {}
        for rep in replicas:
            t0 = time.perf_counter()
            result = scrape_replica(rep["endpoint"], timeout=timeout)
            elapsed = time.perf_counter() - t0
            block: dict = {"endpoint": rep["endpoint"],
                           "root": rep.get("root"),
                           "scrape_s": round(elapsed, 6)}
            health = result.get("health")
            if isinstance(health, dict):
                block.update(healthy=True, stale=False,
                             scraped_epoch=round(now, 3), health=health)
                snapshots[rep["name"]] = result.get("metrics") or {}
                outcome = "ok"
            else:
                prev = self._prev_replicas.get(rep["name"]) or {}
                prev_epoch = prev.get("scraped_epoch")
                carried = isinstance(prev_epoch, (int, float)) \
                    and now - prev_epoch <= stale_s \
                    and isinstance(prev.get("health"), dict)
                block.update(
                    healthy=False, stale=True,
                    error=result.get("error") or "unreachable",
                    scraped_epoch=prev_epoch if carried else None,
                    health=prev.get("health") if carried else None)
                outcome = "error"
            self._registry.counter_inc(
                SCRAPES_TOTAL, 1, help="federated replica scrapes",
                replica=rep["name"], outcome=outcome)
            self._registry.observe(
                SCRAPE_SECONDS, elapsed,
                help="per-replica scrape round-trip seconds",
                replica=rep["name"])
            blocks[rep["name"]] = block
        summary = build_summary(blocks)
        verdict = self.engine.evaluate(summary, now=now)
        for state, n in (("healthy", summary["healthy"]),
                         ("stale", summary["stale"]),
                         ("down", summary["down"])):
            self._registry.gauge_set(
                REPLICAS_GAUGE, n, help="fleet replicas by scrape state",
                state=state)
        self._registry.gauge_set(
            VERDICT_GAUGE, _VERDICT_VALUE[verdict["verdict"]],
            help="fleet scale verdict (-1 scale_in, 0 steady, 1 scale_out)")
        snap = {
            "schema": 1,
            "polled_epoch": round(now, 3),
            "source": str(self.fleet_dir) if self.fleet_dir is not None
            else "endpoints",
            "replicas": blocks,
            "summary": summary,
            "metrics": merge_metrics(snapshots),
            "verdict": verdict,
        }
        self._prev_replicas = blocks
        if self.out_path is not None:
            write_fleet_status(self.out_path, snap)
        return snap
