"""Per-run provenance ledger: what went in, what came out, what was reused.

``ledger.json`` makes a run auditable after the fact: the sha256 of every
input file, the package/jax/libtpu versions and effective ``AUTOCYCLER_*``
knobs it ran under, the warm-start cache lineage (parse, end-repair,
compile, probe — did this run recompute or reuse?), and a per-stage record
of input → output artifact hashes. Two runs whose ledgers match inputs,
versions and knobs should match artifact hashes; when they don't, the
ledger says which stage diverged.

Collection is gated on an active trace run (:func:`autocycler_tpu.obs.trace
.tracing_active`): hashing artifacts costs real I/O, and the ledger is only
written into a run directory anyway. The CLI resets the ledger when it
starts a run and writes ``ledger.json`` atomically at run end, next to
``trace.jsonl`` and ``qc_report.json``.

``autocycler batch`` runs inside :func:`obs.qc.scope`, so per-isolate
stage entries carry their isolate name — a 100-isolate fleet run gets 100
auditable lineages in one ledger.
"""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

from . import metrics_registry, trace
from .qc import current_scope

LEDGER_JSON = "ledger.json"
SCHEMA = 1

_lock = threading.Lock()
# input table keyed (isolate scope, path): concurrent serve jobs hashing
# the same input files never clobber each other's lineage rows
_inputs: Dict[tuple, dict] = {}
_stages: List[dict] = []


def reset() -> None:
    with _lock:
        _inputs.clear()
        _stages.clear()


def _in_scope(iso, scope_name: str) -> bool:
    return iso == scope_name or (isinstance(iso, str)
                                 and iso.startswith(scope_name + "/"))


def drain_scope(scope_name: str) -> int:
    """Drop every input/stage entry tagged with ``scope_name`` (the serve
    daemon drains each job after its ledger is written, keeping the
    process-wide tables bounded). Returns the count removed."""
    with _lock:
        doomed = [k for k in _inputs if _in_scope(k[0], scope_name)]
        for k in doomed:
            del _inputs[k]
        keep = [s for s in _stages if not _in_scope(s.get("isolate"),
                                                    scope_name)]
        removed = len(doomed) + (len(_stages) - len(keep))
        _stages[:] = keep
    return removed


def artifact_hash(path) -> Optional[dict]:
    """{"sha256", "bytes"} of a file, streamed; None when unreadable.
    Shared with resilience's stage checkpoints so manifest stage records
    and ledger entries agree on artifact identity."""
    h = hashlib.sha256()
    size = 0
    try:
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
                size += len(chunk)
    except OSError:
        return None
    return {"sha256": h.hexdigest(), "bytes": size}


_hash_file = artifact_hash


def record_inputs(paths) -> None:
    """Hash run input files (assembly FASTAs) into the ledger's top-level
    input table. No-op without an active trace run; never raises."""
    if not trace.tracing_active():
        return
    for path in paths:
        try:
            key = str(path)
            digest = _hash_file(path)
        except Exception:  # noqa: BLE001 — provenance must not fail the run
            continue
        if digest is None:
            continue
        iso = current_scope()
        if iso:
            digest = dict(digest, isolate=iso)
        with _lock:
            _inputs[(iso, key)] = digest


def record_stage(stage: str, inputs=(), outputs=(),
                 cluster: Optional[str] = None, **extra) -> Optional[dict]:
    """One stage's input → output artifact hashes. Missing/unreadable files
    are skipped silently (a stage may legitimately not write an optional
    artifact). No-op without an active trace run."""
    if not trace.tracing_active():
        return None

    def table(paths) -> Dict[str, dict]:
        out = {}
        for path in paths:
            try:
                digest = _hash_file(path)
            except Exception:  # noqa: BLE001
                digest = None
            if digest is not None:
                out[str(path)] = digest
        return out

    entry = {"stage": stage, "ts_epoch": round(time.time(), 3),
             "inputs": table(inputs), "outputs": table(outputs)}
    iso = current_scope()
    if iso:
        entry["isolate"] = iso
    if cluster:
        entry["cluster"] = cluster
    if extra:
        entry["extra"] = extra
    with _lock:
        _stages.append(entry)
    return entry


def _env_knobs() -> dict:
    """The effective environment this run saw: the platform pin plus every
    AUTOCYCLER knob (same filter as the sentinel's environment snapshot).
    Secret-bearing knobs (``*TOKEN*``, ``*SECRET*``) are redacted — a
    ledger is an artifact clients download, never a credential store."""
    out = {}
    for k in sorted(os.environ):
        if not (k == "JAX_PLATFORMS" or k.startswith("AUTOCYCLER_")
                or k in ("XLA_FLAGS", "LIBTPU_INIT_ARGS", "TPU_NAME",
                         "PJRT_DEVICE", "TPU_LIBRARY_PATH")):
            continue
        out[k] = "<redacted>" if ("TOKEN" in k or "SECRET" in k) \
            else os.environ[k]
    return out


def _versions() -> dict:
    """Package versions without importing jax (a ledger write must be safe
    on a wedged host): autocycler itself, python, and every jax/TPU-adjacent
    distribution from importlib metadata."""
    import platform

    from .. import __version__

    packages = {}
    try:
        from importlib import metadata
        for dist in metadata.distributions():
            name = (dist.metadata.get("Name") or "").lower()
            if any(tag in name for tag in ("jax", "tpu", "pjrt", "axon")):
                packages[name] = dist.version
    except Exception:  # noqa: BLE001
        pass
    return {"autocycler_tpu": __version__,
            "python": platform.python_version(),
            "packages": dict(sorted(packages.items()))}


def _cache_lineage() -> dict:
    """Hit/miss lineage for every warm-start layer: the parse and
    end-repair caches (metrics registry), the persistent XLA compile cache
    (knob + directory), and the device-probe cache (last logged outcome +
    persisted negative-probe state + recovery count)."""
    reg = metrics_registry.registry()
    lineage: dict = {}
    for which in ("parse", "repair"):
        lineage[which] = {
            "hits": int(reg.value("autocycler_cache_events_total",
                                  cache=which, event="hit")),
            "misses": int(reg.value("autocycler_cache_events_total",
                                    cache=which, event="miss")),
        }
    from ..utils.knobs import knob_str
    compile_dir = (knob_str("AUTOCYCLER_COMPILE_CACHE") or "").strip()
    lineage["compile"] = {"enabled": bool(compile_dir),
                          "dir": compile_dir or None}
    probe: dict = {
        "recoveries": int(reg.value("autocycler_probe_recoveries_total")),
    }
    try:
        from . import sentinel
        tail = sentinel.read_probe_log(limit=1)
        if tail:
            probe["last"] = tail[-1]
        log_path = sentinel.probe_log_path()
        if log_path is not None:
            neg = log_path.parent / "device_probe.json"
            probe["negative_cache"] = neg.is_file()
    except Exception:  # noqa: BLE001
        pass
    lineage["probe"] = probe
    return lineage


def build_ledger(command: Optional[str] = None,
                 scope: Optional[str] = None,
                 trace_id: Optional[str] = None) -> dict:
    """The full ledger payload. With ``scope``, only inputs and stage
    entries tagged with that isolate scope are included — each concurrent
    serve job's ledger carries exactly its own lineage. ``trace_id`` (the
    submission's correlation id) is recorded as an additive key so a
    ledger links back to the client-side submission."""
    with _lock:
        inputs = {key[1]: dict(digest) for key, digest in _inputs.items()
                  if scope is None or _in_scope(key[0], scope)}
        stages = [dict(s) for s in _stages
                  if scope is None or _in_scope(s.get("isolate"), scope)]
    ledger = {
        "schema": SCHEMA,
        "created_epoch": round(time.time(), 3),
        "inputs": inputs,
        "stages": stages,
        "env": _env_knobs(),
        "versions": _versions(),
        "caches": _cache_lineage(),
    }
    if command:
        ledger["command"] = command
    if trace_id:
        ledger["trace_id"] = trace_id
    return ledger


def write_ledger(run_dir, command: Optional[str] = None,
                 scope: Optional[str] = None,
                 trace_id: Optional[str] = None) -> Optional[Path]:
    """Write ``ledger.json`` atomically (tempfile + rename — a reader or a
    crash never sees a torn ledger). Returns the path, or None when there
    is nothing to record or the write failed. ``scope`` filters to one
    isolate scope's entries (see :func:`build_ledger`)."""
    payload = build_ledger(command, scope=scope, trace_id=trace_id)
    if not payload["inputs"] and not payload["stages"]:
        return None
    path = Path(run_dir) / LEDGER_JSON
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=str(path.parent),
                                   prefix=path.name + ".tmp")
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
        from ..utils.resilience import crash_point  # lazy: avoids cycle
        crash_point("pre-artifact-rename", str(path))
        os.replace(tmp, path)
        return path
    except OSError:
        return None
