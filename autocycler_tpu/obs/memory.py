"""Lightweight memory sampling for top-level trace spans.

Three sources, each best-effort (a missing source is simply absent from the
sample — telemetry never fails the pipeline):

- peak RSS from ``resource.getrusage`` (ru_maxrss is KiB on Linux);
- current RSS from ``/proc/self/statm`` (page count x page size);
- JAX device/live-buffer bytes — only when jax is ALREADY imported
  (``sys.modules`` check: sampling must never be the thing that pays jax
  startup), preferring per-device ``memory_stats()`` (real TPU allocator
  numbers) and falling back to summing ``jax.live_arrays()``.
"""

from __future__ import annotations

import os
import sys


def memory_sample() -> dict:
    out: dict = {}
    try:
        import resource
        ru = resource.getrusage(resource.RUSAGE_SELF)
        scale = 1024 if sys.platform != "darwin" else 1
        out["peak_rss_bytes"] = int(ru.ru_maxrss) * scale
    except Exception:  # noqa: BLE001 — absent source == absent field
        pass
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        out["rss_bytes"] = pages * os.sysconf("SC_PAGE_SIZE")
    except Exception:  # noqa: BLE001
        pass
    out.update(jax_memory_sample())
    return out


def jax_memory_sample() -> dict:
    """Device-side memory evidence, only when jax is already live."""
    jax = sys.modules.get("jax")
    if jax is None:
        return {}
    try:
        # jax.local_devices() would INITIALIZE the backend on first call —
        # a sampling probe must never pay (or hang on) device bring-up, so
        # only read stats when a backend already exists.
        from jax._src import xla_bridge
        if not xla_bridge._backends:
            return {}
    except Exception:  # noqa: BLE001 — private API moved: skip device stats
        return {}
    out: dict = {}
    try:
        stats = {}
        for dev in jax.local_devices():
            s = getattr(dev, "memory_stats", lambda: None)()
            if s and "bytes_in_use" in s:
                stats[str(dev.id)] = int(s["bytes_in_use"])
        if stats:
            out["device_bytes_in_use"] = sum(stats.values())
    except Exception:  # noqa: BLE001 — backends without allocator stats
        pass
    if "device_bytes_in_use" not in out:
        try:
            live = jax.live_arrays()
            out["jax_live_buffer_bytes"] = int(sum(
                getattr(a, "nbytes", 0) for a in live))
            out["jax_live_buffers"] = len(live)
        except Exception:  # noqa: BLE001
            pass
    return out
