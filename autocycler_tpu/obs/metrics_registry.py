"""Process-wide metrics registry: named counters, gauges and histograms.

This is the single home for the run-level accounting that used to live in
scattered module-level dicts (utils.timing device/stage/substage seconds,
utils.cache hit counters, utils.resilience degrade events, utils.pool task
counts). Those modules now write here, and their legacy accessor functions
(`device_seconds()`, `cache_stats()`, ...) are views over this registry —
one snapshot answers "what did this run count?" for bench artifacts, the
`autocycler report` command and external scrapers alike.

Exports: :meth:`MetricsRegistry.snapshot` (JSON-able dict) and
:meth:`MetricsRegistry.to_prometheus` (Prometheus text exposition format,
version 0.0.4 — counters get a ``_total``-style sample per label set,
histograms get ``_bucket``/``_sum``/``_count`` samples with cumulative
``le`` buckets).

Thread-safe: one re-entrant lock guards the metric table; increments from
pool workers, device dispatch sites and the main thread interleave freely.
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional, Tuple

# default histogram buckets: wall-clock seconds from sub-millisecond device
# dispatches up to multi-minute stages
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   30.0, 60.0, 300.0)

# seconds-to-minutes preset for job/stage latency histograms: the default
# buckets are device-dispatch-oriented (sub-millisecond resolution wasted on
# a 20 s cold job), so serve job walls, queue waits and stage latencies use
# this coarser ladder — resolution where SLO objectives actually live
SECONDS_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 3.0, 5.0, 7.5, 10.0,
                   15.0, 20.0, 30.0, 45.0, 60.0, 120.0, 300.0, 600.0,
                   1200.0)

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> _LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _prom_sample(name: str, labels: _LabelKey, value) -> str:
    if labels:
        body = ",".join(f'{k}="{_escape_label(v)}"' for k, v in labels)
        return f"{name}{{{body}}} {value}"
    return f"{name} {value}"


class _Metric:
    __slots__ = ("name", "kind", "help", "series")

    def __init__(self, name: str, kind: str, help: str = ""):
        self.name = name
        self.kind = kind          # "counter" | "gauge" | "histogram" | "info"
        self.help = help
        self.series: Dict[_LabelKey, object] = {}


class MetricsRegistry:
    """A named collection of counters/gauges/histograms with label support.

    One process-wide instance (:func:`registry`) backs the pipeline; tests
    construct private instances freely."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}

    # ---- write API ----

    def _metric(self, name: str, kind: str, help: str) -> _Metric:
        m = self._metrics.get(name)
        if m is None:
            m = _Metric(name, kind, help)
            self._metrics[name] = m
        elif m.kind != kind:
            raise ValueError(
                f"metric {name!r} already registered as {m.kind}, not {kind}")
        if help and not m.help:
            m.help = help
        return m

    def counter_inc(self, name: str, value: float = 1.0, help: str = "",
                    **labels) -> float:
        """Add ``value`` (>= 0) to a counter; returns the new total."""
        if value < 0:
            raise ValueError(f"counter {name} cannot decrease (got {value})")
        key = _label_key(labels)
        with self._lock:
            m = self._metric(name, "counter", help)
            total = m.series.get(key, 0.0) + value
            m.series[key] = total
            return total

    def gauge_set(self, name: str, value: float, help: str = "",
                  **labels) -> None:
        with self._lock:
            m = self._metric(name, "gauge", help)
            m.series[_label_key(labels)] = float(value)

    def info_set(self, name: str, text: str, help: str = "",
                 **labels) -> None:
        """A string-valued sample (e.g. 'last device failure'). Exported to
        JSON verbatim and to Prometheus as a ``value="..."``-labelled 1."""
        with self._lock:
            m = self._metric(name, "info", help)
            m.series[_label_key(labels)] = str(text)

    def observe(self, name: str, value: float, help: str = "",
                buckets: Optional[Tuple[float, ...]] = None,
                **labels) -> None:
        """Record one observation into a histogram."""
        key = _label_key(labels)
        with self._lock:
            m = self._metric(name, "histogram", help)
            state = m.series.get(key)
            if state is None:
                bts = tuple(buckets) if buckets else DEFAULT_BUCKETS
                state = {"buckets": bts, "counts": [0] * (len(bts) + 1),
                         "sum": 0.0, "count": 0,
                         "min": float("inf"), "max": float("-inf")}
                m.series[key] = state
            state["sum"] += value
            state["count"] += 1
            state["min"] = min(state["min"], value)
            state["max"] = max(state["max"], value)
            for i, le in enumerate(state["buckets"]):
                if value <= le:
                    state["counts"][i] += 1
                    break
            else:
                state["counts"][-1] += 1

    # ---- read API ----

    def value(self, name: str, default: float = 0.0, **labels) -> float:
        """Current value of one counter/gauge series (0 when absent)."""
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                return default
            got = m.series.get(_label_key(labels))
            return default if got is None or isinstance(got, dict) else got

    def labeled(self, name: str, label: str) -> Dict[str, float]:
        """{label value: metric value} for every series of ``name`` carrying
        ``label`` (e.g. per-stage seconds keyed by the 'stage' label)."""
        out: Dict[str, float] = {}
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                return out
            for key, val in m.series.items():
                for k, v in key:
                    if k == label and not isinstance(val, dict):
                        out[v] = val
        return out

    def quantile(self, name: str, q: float, **labels) -> Optional[float]:
        """Streaming quantile estimate from one histogram series: walk the
        cumulative bucket counts to the bucket containing the ``q``-th
        observation and interpolate linearly inside it. No raw samples are
        stored, so the estimate's error is bounded by the bucket width; the
        result is clamped to the recorded [min, max] so it always brackets
        what was actually observed (the overflow bucket in particular has
        no finite upper edge without the clamp). Returns None when the
        series does not exist or is empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            m = self._metrics.get(name)
            if m is None or m.kind != "histogram":
                return None
            state = m.series.get(_label_key(labels))
            if not isinstance(state, dict) or not state["count"]:
                return None
            edges = list(state["buckets"])
            counts = list(state["counts"])
            lo, hi = state["min"], state["max"]
            count = state["count"]
        target = q * count
        cum = 0.0
        prev_edge = 0.0
        for edge, c in zip(edges + [hi], counts):
            if c and cum + c >= target:
                frac = (target - cum) / c
                est = prev_edge + frac * (max(edge, prev_edge) - prev_edge)
                return min(max(est, lo), hi)
            cum += c
            prev_edge = edge
        return hi

    def snapshot(self) -> dict:
        """JSON-able {metric name: {"type", "help", "values": [...]}} where
        each value entry carries its labels dict and value (histograms: the
        full bucket state)."""
        out: dict = {}
        with self._lock:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                values: List[dict] = []
                for key in sorted(m.series):
                    val = m.series[key]
                    entry: dict = {"labels": dict(key)}
                    if isinstance(val, dict):   # histogram state
                        entry.update(
                            sum=round(val["sum"], 6), count=val["count"],
                            min=(None if val["count"] == 0 else val["min"]),
                            max=(None if val["count"] == 0 else val["max"]),
                            buckets={str(le): c for le, c in
                                     zip(list(val["buckets"]) + ["+Inf"],
                                         val["counts"])})
                    else:
                        entry["value"] = round(val, 6) \
                            if isinstance(val, float) else val
                    values.append(entry)
                out[name] = {"type": m.kind, "help": m.help, "values": values}
        return out

    def to_json(self) -> str:
        return json.dumps(self.snapshot(), indent=2, sort_keys=True)

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (0.0.4). Counters/gauges export
        one sample per label set; histograms export cumulative ``_bucket``
        samples plus ``_sum``/``_count``; info metrics export a gauge 1 with
        the text riding in a ``value`` label."""
        lines: List[str] = []
        with self._lock:
            for name in sorted(self._metrics):
                m = self._metrics[name]
                if m.help:
                    lines.append(f"# HELP {name} {m.help}")
                kind = {"info": "gauge"}.get(m.kind, m.kind)
                lines.append(f"# TYPE {name} {kind}")
                for key in sorted(m.series):
                    val = m.series[key]
                    if m.kind == "histogram":
                        cum = 0
                        for le, c in zip(list(val["buckets"]) + ["+Inf"],
                                         val["counts"]):
                            cum += c
                            lines.append(_prom_sample(
                                f"{name}_bucket", key + (("le", str(le)),),
                                cum))
                        lines.append(_prom_sample(f"{name}_sum", key,
                                                  round(val["sum"], 6)))
                        lines.append(_prom_sample(f"{name}_count", key,
                                                  val["count"]))
                    elif m.kind == "info":
                        lines.append(_prom_sample(
                            name, key + (("value", str(val)),), 1))
                    else:
                        v = round(val, 6) if isinstance(val, float) else val
                        lines.append(_prom_sample(name, key, v))
        return "\n".join(lines) + ("\n" if lines else "")

    def _reset_for_tests(self) -> None:
        with self._lock:
            self._metrics.clear()


_registry = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide registry every pipeline accumulator writes to."""
    return _registry


# module-level conveniences over the process-wide registry
def counter_inc(name: str, value: float = 1.0, help: str = "",
                **labels) -> float:
    return _registry.counter_inc(name, value, help=help, **labels)


def gauge_set(name: str, value: float, help: str = "", **labels) -> None:
    _registry.gauge_set(name, value, help=help, **labels)


def info_set(name: str, text: str, help: str = "", **labels) -> None:
    _registry.info_set(name, text, help=help, **labels)


def observe(name: str, value: float, help: str = "",
            buckets: Optional[Tuple[float, ...]] = None, **labels) -> None:
    _registry.observe(name, value, help=help, buckets=buckets, **labels)


def quantile(name: str, q: float, **labels) -> Optional[float]:
    return _registry.quantile(name, q, **labels)


def snapshot() -> dict:
    return _registry.snapshot()


def to_prometheus() -> str:
    return _registry.to_prometheus()
