"""Scientific QC metrics: what the *science* of a run looked like.

The span tracer observes the process and the sentinel observes the device;
this module observes the assembly itself — the numbers a reviewer asks for
when judging a consensus: how compact the unitig graph came out, which
clusters passed QC and why the rest failed, how much sequence trimming
removed, how well-supported the consensus bridges were.

Each pipeline stage calls :func:`record` with its stage-specific metrics.
Every record is

- kept in an in-process journal, written to ``qc_report.json`` in the run
  directory at run end (the CLI drives this alongside ``ledger.json``);
- attached to the innermost open trace span as a ``qc`` attribute, so
  ``autocycler watch`` can highlight QC live as stages close;
- registered in the metrics registry as ``autocycler_qc_<stage>_<key>``
  gauges (numeric scalars only), so Prometheus scrapes and bench artifacts
  carry the same numbers.

``autocycler batch`` wraps each isolate's work in :func:`scope`, so a
fleet run's journal separates per-isolate QC. Collection is always on —
the cost is a few dict updates per *stage*, not per item — which lets
``bench.py`` embed a QC summary even in untraced runs.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

from . import metrics_registry, trace

QC_REPORT_JSON = "qc_report.json"

# unitig depth histogram edges (×: bp of unitig sequence at that depth);
# depth ~= how many input assemblies cover the unitig, so the low buckets
# are assembler disagreement and the high ones are repeats
DEPTH_EDGES = (1.5, 2.5, 3.5, 5.0, 10.0, 100.0)
DEPTH_LABELS = ("<=1", "2", "3", "4-5", "5-10", "10-100", ">100")

_lock = threading.Lock()
_entries: List[dict] = []
_scope = threading.local()


def current_scope() -> Optional[str]:
    """The active isolate scope (``autocycler batch``), or None."""
    return getattr(_scope, "name", None)


class scope:
    """Context manager tagging every :func:`record` (and ledger entry)
    inside it with an isolate name — `batch` wraps each isolate's phases."""

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        self._prev = getattr(_scope, "name", None)
        _scope.name = self.name
        return self

    def __exit__(self, *exc):
        _scope.name = self._prev
        return False


def reset() -> None:
    """Drop all journal entries (run start / test isolation)."""
    with _lock:
        _entries.clear()


def _in_scope(entry: dict, scope_name: str) -> bool:
    iso = entry.get("isolate")
    return iso == scope_name or (isinstance(iso, str)
                                 and iso.startswith(scope_name + "/"))


def drain_scope(scope_name: str) -> int:
    """Drop every journal entry tagged with ``scope_name`` (a serve daemon
    drains each job's entries after writing its report, so the process-wide
    journal stays bounded over thousands of jobs). Returns the count."""
    with _lock:
        keep = [e for e in _entries if not _in_scope(e, scope_name)]
        dropped = len(_entries) - len(keep)
        _entries[:] = keep
    return dropped


def record(stage: str, cluster: Optional[str] = None, **metrics) -> dict:
    """Journal one stage's QC metrics; returns the journal entry.

    Numeric scalars additionally become ``autocycler_qc_<stage>_<key>``
    gauges (labelled by isolate scope and cluster when present) and ride
    the innermost open span as a ``qc`` attribute. Never raises — QC
    observation must not fail the stage it observes."""
    entry = {"stage": stage, "ts_epoch": round(time.time(), 3),
             "metrics": metrics}
    iso = current_scope()
    if iso:
        entry["isolate"] = iso
    if cluster:
        entry["cluster"] = cluster
    with _lock:
        _entries.append(entry)
    scalars = {}
    for key, value in metrics.items():
        if isinstance(value, bool):
            scalars[key] = int(value)
        elif isinstance(value, (int, float)):
            scalars[key] = value
    try:
        labels = {}
        if iso:
            labels["isolate"] = iso
        if cluster:
            labels["cluster"] = cluster
        for key, value in scalars.items():
            metrics_registry.gauge_set(
                f"autocycler_qc_{stage}_{key}", value,
                help=f"assembly QC: {stage} {key.replace('_', ' ')}",
                **labels)
    except Exception:  # noqa: BLE001 — a bad metric name must not kill QC
        pass
    try:
        sp = trace.current_span()
        if sp is not None and hasattr(sp, "set_attr"):
            key = f"{stage}/{cluster}" if cluster else stage
            existing = (sp.attrs or {}).get("qc")
            merged = dict(existing) if isinstance(existing, dict) else {}
            merged[key] = scalars
            sp.set_attr(qc=merged)
    except Exception:  # noqa: BLE001
        pass
    return entry


def entries() -> List[dict]:
    with _lock:
        return [dict(e) for e in _entries]


def summary(journal: Optional[List[dict]] = None) -> dict:
    """Aggregate the journal per stage: numeric metrics sum across entries
    (one compress entry stays itself; per-cluster trim entries add up),
    booleans AND together, and an ``entries`` count records how many calls
    contributed. Isolate-scoped entries aggregate under ``isolates``.
    Pass ``journal`` to aggregate a pre-filtered entry list (the scoped
    serve reports) instead of the live journal."""
    out: dict = {}
    iso_out: Dict[str, dict] = {}
    if journal is None:
        with _lock:
            journal = list(_entries)
    for entry in journal:
        target = out
        if entry.get("isolate"):
            target = iso_out.setdefault(entry["isolate"], {})
        agg = target.setdefault(entry["stage"], {"entries": 0})
        agg["entries"] += 1
        for key, value in entry["metrics"].items():
            if isinstance(value, bool):
                agg[key] = bool(agg.get(key, True)) and value
            elif isinstance(value, (int, float)):
                agg[key] = round(agg.get(key, 0) + value, 6)
    if iso_out:
        out["isolates"] = iso_out
    return out


def write_qc_report(run_dir, scope: Optional[str] = None,
                    trace_id: Optional[str] = None) -> Optional[Path]:
    """Write ``qc_report.json`` (journal + summary) atomically into the run
    directory; returns the path (None on failure or empty journal —
    telemetry never fails the pipeline). With ``scope``, only entries
    tagged with that isolate scope are written — how concurrent serve jobs
    each get a report of exactly their own entries from the shared
    journal. ``trace_id`` (the submission's correlation id) rides along as
    an additive payload key."""
    with _lock:
        selected = [dict(e) for e in _entries
                    if scope is None or _in_scope(e, scope)]
    if not selected:
        return None
    payload = {"schema": 1, "created_epoch": round(time.time(), 3),
               "entries": selected}
    if trace_id:
        payload["trace_id"] = trace_id
    payload["summary"] = summary(selected)
    path = Path(run_dir) / QC_REPORT_JSON
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=str(path.parent),
                                   prefix=path.name + ".tmp")
        with os.fdopen(fd, "w") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return path
    except OSError:
        return None


# ---- per-stage metric builders (called by commands/*) ----

def n50(lengths) -> int:
    """Standard N50: the length at which half the total is in contigs at
    least that long."""
    ordered = sorted((int(n) for n in lengths), reverse=True)
    total = sum(ordered)
    running = 0
    for length in ordered:
        running += length
        if 2 * running >= total:
            return length
    return 0


def depth_histogram(graph) -> Dict[str, int]:
    """bp of unitig sequence per depth bucket (k-mer depth ~= assemblies
    covering the unitig)."""
    hist = {label: 0 for label in DEPTH_LABELS}
    for unitig in graph.unitigs:
        depth = float(unitig.depth)
        for edge, label in zip(DEPTH_EDGES, DEPTH_LABELS):
            if depth <= edge:
                hist[label] += unitig.length()
                break
        else:
            hist[DEPTH_LABELS[-1]] += unitig.length()
    return {label: bp for label, bp in hist.items() if bp}


def compress_qc(graph, sequences) -> dict:
    """Unitig count / N50 / total bp + the depth histogram of the
    compacted graph (called after simplify)."""
    lengths = [u.length() for u in graph.unitigs]
    return record(
        "compress",
        unitigs=len(graph.unitigs),
        total_bp=int(graph.total_length()),
        n50_bp=n50(lengths),
        input_contigs=len(sequences),
        input_bp=int(sum(s.length for s in sequences)),
        depth_hist_bp=depth_histogram(graph),
    )


def cluster_qc(sequences, qc_results) -> dict:
    """Pass/fail counts, size balance across passing clusters, and the
    per-cluster verdicts with distances and failure reasons."""
    clusters = []
    pass_sizes = []
    for c in sorted(qc_results):
        qc = qc_results[c]
        members = [s for s in sequences if s.cluster == c]
        passed = qc.passed()
        clusters.append({
            "cluster": c, "passed": passed,
            "contigs": len(members),
            "total_bp": int(sum(s.length for s in members)),
            "distance": round(float(qc.cluster_dist), 6),
            "failure_reasons": list(qc.failure_reasons),
        })
        if passed:
            pass_sizes.append(len(members))
    balance = round(min(pass_sizes) / max(pass_sizes), 4) \
        if pass_sizes and max(pass_sizes) else 0.0
    return record(
        "cluster",
        clusters_pass=sum(c["passed"] for c in clusters),
        clusters_fail=sum(not c["passed"] for c in clusters),
        size_balance_ratio=balance,
        clusters=clusters,
    )


def trim_qc(cluster_name: str, orig_lengths: Dict[int, int],
            start_end_count: int, hairpin_count: int, chosen,
            kept_sequences, excluded_ids) -> dict:
    """bp trimmed per contig plus the start-end vs hairpin decision.
    ``chosen`` is the winning TrimResult list aligned with the original
    sequence order (ids index ``orig_lengths``)."""
    per_contig = []
    trimmed_bp = 0
    for seq_id, result in chosen:
        if result is None:
            continue
        from_bp = int(orig_lengths.get(seq_id, 0))
        to_bp = int(result[1])
        per_contig.append({"id": seq_id, "from_bp": from_bp, "to_bp": to_bp,
                           "trimmed_bp": from_bp - to_bp})
        trimmed_bp += from_bp - to_bp
    trim_type = "none"
    if start_end_count or hairpin_count:
        trim_type = "start_end" if start_end_count >= hairpin_count \
            else "hairpin"
    return record(
        "trim", cluster=cluster_name,
        contigs=len(orig_lengths),
        trimmed_contigs=len(per_contig),
        trimmed_bp=trimmed_bp,
        start_end_trims=start_end_count,
        hairpin_trims=hairpin_count,
        excluded_contigs=len(excluded_ids),
        kept_contigs=len(kept_sequences),
        trim_type=trim_type,
        per_contig=per_contig,
    )


def resolve_qc(cluster_name: str, anchors: int, bridges,
               conflicting: int, culled: int) -> dict:
    """Anchor count, unique-vs-conflicting bridge split and consensus path
    support (the per-bridge count of input paths agreeing with the medoid)."""
    depths = [b.depth() for b in bridges]
    return record(
        "resolve", cluster=cluster_name,
        anchors=anchors,
        bridges=len(bridges),
        unique_bridges=len(bridges) - conflicting,
        conflicting_bridges=conflicting,
        culled_bridges=culled,
        min_bridge_support=min(depths) if depths else 0,
        mean_bridge_support=round(sum(depths) / len(depths), 3)
        if depths else 0.0,
    )


def combine_qc(metrics) -> dict:
    """Final consensus shape from the CombineMetrics the stage just saved."""
    return record(
        "combine",
        clusters=len(metrics.consensus_assembly_clusters),
        consensus_bp=int(metrics.consensus_assembly_bases),
        consensus_unitigs=int(metrics.consensus_assembly_unitigs),
        fully_resolved=bool(metrics.consensus_assembly_fully_resolved),
    )
