"""`autocycler report <dir>`: merge one run's telemetry into a readable
report.

Inputs, all optional except that at least one must exist in the directory:

- ``trace.jsonl`` (obs.trace) — the span stream: rendered as a nested stage
  tree with durations, call counts, share-of-parent percentages and the
  memory samples attached to top-level spans;
- ``metrics.json`` (metrics-registry snapshot) — rendered as the
  device-vs-host split, cache hit/miss summary, degradation/fault/retry
  summary and pool counters;
- ``batch_manifest.json`` (utils.resilience.RunManifest) — per-isolate
  status lines;
- ``qc_report.json`` (obs.qc) — per-stage scientific QC: unitig shape,
  cluster pass/fail verdicts, trim decisions, bridge support;
- ``ledger.json`` (obs.ledger) — input hashes, versions, env knobs, cache
  lineage and per-stage artifact hashes;
- ``BENCH*.json`` bench artifacts — one summary line each;
- ``lint_report.json`` (commands.lint ``--report``) — the static-analysis
  verdict, file count and findings.

``--json`` emits the merged structure as one JSON document instead, and
``--html`` additionally writes a self-contained ``run_report.html``.
"""

from __future__ import annotations

import html as _html
import json
import sys
import time
from pathlib import Path
from typing import Dict, List, Optional

from .ledger import LEDGER_JSON
from .qc import QC_REPORT_JSON
from .timeseries import (TIMESERIES_JSONL, read_timeseries,
                         summarize_timeseries)
from .trace import METRICS_JSON, TRACE_JSONL

RUN_REPORT_HTML = "run_report.html"
LINT_REPORT_JSON = "lint_report.json"

# report total vs recorded wall-clock agreement gate (the acceptance bar:
# a stage tree that disagrees with the wall by more than this is reported
# loudly — it means spans are missing or double-counted)
WALL_AGREEMENT = 0.05


def _fmt_s(seconds: float) -> str:
    if seconds >= 60:
        m, s = divmod(seconds, 60.0)
        return f"{int(m)}m{s:04.1f}s"
    if seconds >= 0.9995:
        return f"{seconds:.2f}s"
    return f"{seconds * 1000:.1f}ms"


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} TiB"


def load_trace(path) -> Optional[dict]:
    """Parse a trace.jsonl into {"run": header, "spans": [...], "finish":
    footer-or-None}. Unparseable lines are skipped (a killed run can leave
    a torn final line)."""
    path = Path(path)
    if not path.is_file():
        return None
    run = finish = None
    spans: List[dict] = []
    for line in path.read_text().splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        kind = rec.get("type")
        if kind == "run":
            run = rec
        elif kind == "finish":
            finish = rec
        elif kind == "span":
            spans.append(rec)
    return {"run": run or {}, "spans": spans, "finish": finish}


def span_tree(spans: List[dict]) -> List[dict]:
    """Aggregate the flat span stream into a nested tree: siblings with the
    same (name, cat) merge into one node carrying summed duration, call
    count, earliest start and the last memory sample seen. Children order
    is earliest-start first (pipeline order)."""
    by_parent: Dict[Optional[int], List[dict]] = {}
    for s in spans:
        by_parent.setdefault(s.get("parent"), []).append(s)

    def build(parent_ids: List[Optional[int]]) -> List[dict]:
        kids = [s for pid in parent_ids for s in by_parent.get(pid, [])]
        groups: Dict[tuple, dict] = {}
        for s in kids:
            g = groups.setdefault((s["name"], s.get("cat", "")), {
                "name": s["name"], "cat": s.get("cat", ""),
                "seconds": 0.0, "count": 0, "first_ts": s.get("ts", 0.0),
                "mem": None, "ids": []})
            g["seconds"] += s.get("dur", 0.0)
            g["count"] += 1
            g["first_ts"] = min(g["first_ts"], s.get("ts", 0.0))
            g["ids"].append(s.get("id"))
            if "mem" in s:
                g["mem"] = s["mem"]
        nodes = sorted(groups.values(), key=lambda g: g["first_ts"])
        for node in nodes:
            node["children"] = build(node.pop("ids"))
            node["seconds"] = round(node["seconds"], 6)
            del node["first_ts"]
        return nodes

    return build([None])


def _render_tree(nodes: List[dict], lines: List[str], depth: int = 0,
                 parent_seconds: Optional[float] = None) -> None:
    for node in nodes:
        pct = ""
        if parent_seconds and parent_seconds > 0:
            pct = f"  {100.0 * node['seconds'] / parent_seconds:5.1f}%"
        count = f"  x{node['count']}" if node["count"] > 1 else ""
        label = f"{'  ' * depth}{node['name']}"
        lines.append(f"  {label:<44} {_fmt_s(node['seconds']):>9}"
                     f"{pct}{count}")
        if node.get("mem"):
            mem = node["mem"]
            bits = []
            if "peak_rss_bytes" in mem:
                bits.append(f"peak RSS {_fmt_bytes(mem['peak_rss_bytes'])}")
            if "device_bytes_in_use" in mem:
                bits.append(
                    f"device {_fmt_bytes(mem['device_bytes_in_use'])}")
            elif "jax_live_buffer_bytes" in mem:
                bits.append(f"jax live "
                            f"{_fmt_bytes(mem['jax_live_buffer_bytes'])}")
            if bits:
                lines.append(f"  {'  ' * depth}  [{'; '.join(bits)}]")
        _render_tree(node["children"], lines, depth + 1, node["seconds"])


def _metric_values(snapshot: dict, name: str) -> List[dict]:
    return snapshot.get(name, {}).get("values", [])


def _metric_total(snapshot: dict, name: str) -> float:
    return sum(v.get("value", 0) for v in _metric_values(snapshot, name)
               if isinstance(v.get("value"), (int, float)))


def _metric_by_label(snapshot: dict, name: str, label: str) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for v in _metric_values(snapshot, name):
        key = v.get("labels", {}).get(label)
        if key is not None and isinstance(v.get("value"), (int, float)):
            out[key] = out.get(key, 0) + v["value"]
    return out


def build_report(run_dir) -> Optional[dict]:
    """The merged report structure, or None when the directory holds no
    telemetry at all."""
    run_dir = Path(run_dir)
    trace = load_trace(run_dir / TRACE_JSONL)
    metrics = None
    metrics_path = run_dir / METRICS_JSON
    if metrics_path.is_file():
        try:
            metrics = json.loads(metrics_path.read_text())
        except (OSError, json.JSONDecodeError):
            metrics = None
    manifest = None
    manifest_path = run_dir / "batch_manifest.json"
    if manifest_path.is_file():
        try:
            manifest = json.loads(manifest_path.read_text())
        except (OSError, json.JSONDecodeError):
            manifest = None
    qc = ledger = None
    for name, slot in ((QC_REPORT_JSON, "qc"), (LEDGER_JSON, "ledger")):
        path = run_dir / name
        if path.is_file():
            try:
                data = json.loads(path.read_text())
            except (OSError, json.JSONDecodeError):
                data = None
            if slot == "qc":
                qc = data
            else:
                ledger = data
    bench: List[dict] = []
    for path in sorted(run_dir.glob("BENCH*.json")) + \
            sorted(run_dir.glob("bench*.json")):
        try:
            data = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(data, dict):
            bench.append({"file": path.name, **data})
    lint = None
    lint_path = run_dir / LINT_REPORT_JSON
    if lint_path.is_file():
        try:
            data = json.loads(lint_path.read_text())
        except (OSError, json.JSONDecodeError):
            data = None
        if isinstance(data, dict):
            lint = data
    timeseries = None
    ts_entries = read_timeseries(run_dir / TIMESERIES_JSONL)
    if ts_entries:
        timeseries = summarize_timeseries(ts_entries)
        # the SLO verdict rides the last sampled tick that carried one
        # (the serve sampler attaches its rolling-window report per tick)
        slo = next((e["slo"] for e in reversed(ts_entries)
                    if isinstance(e.get("slo"), dict)), None)
        if slo is not None:
            timeseries["slo"] = slo
    if trace is None and metrics is None and manifest is None \
            and qc is None and ledger is None and not bench \
            and timeseries is None and lint is None:
        return None
    report: dict = {"dir": str(run_dir)}
    if trace is not None:
        tree = span_tree(trace["spans"])
        total = round(sum(n["seconds"] for n in tree), 6)
        report["trace"] = {
            "run": trace["run"], "finish": trace["finish"],
            "span_count": len(trace["spans"]),
            "tree": tree, "tree_total_s": total,
        }
        wall = (trace["finish"] or {}).get("wall")
        if isinstance(wall, (int, float)) and wall > 0:
            report["trace"]["wall_s"] = wall
            report["trace"]["wall_agreement"] = round(total / wall, 4)
    if metrics is not None:
        report["metrics"] = metrics
    if manifest is not None:
        report["manifest"] = manifest
    if qc is not None:
        report["qc"] = qc
    if ledger is not None:
        report["ledger"] = ledger
    if bench:
        report["bench"] = bench
    if timeseries is not None:
        report["timeseries"] = timeseries
    if lint is not None:
        report["lint"] = lint
    return report


def _telemetry_lines(ts: dict, lines: List[str]) -> None:
    """The continuous-telemetry section: series shape, host envelope,
    latency quantiles and the SLO verdict. Every field optional — a
    foreign or truncated series renders partially, never raises."""
    if not isinstance(ts, dict):
        return
    head = f"  {ts.get('ticks', '?')} sampler ticks"
    span = ts.get("span_s")
    if isinstance(span, (int, float)) and span > 0:
        head += f" over {_fmt_s(span)}"
    lines.append(head)
    host = ts.get("host") or {}
    rss = host.get("rss_bytes")
    if isinstance(rss, dict):
        lines.append(f"  RSS: min {_fmt_bytes(rss.get('min', 0))} · "
                     f"median {_fmt_bytes(rss.get('median', 0))} · "
                     f"max {_fmt_bytes(rss.get('max', 0))}")
    busy = host.get("cpu_busy_frac")
    if isinstance(busy, dict):
        lines.append(f"  host CPU busy: min {busy.get('min', 0) * 100:.0f}%"
                     f" · median {busy.get('median', 0) * 100:.0f}%"
                     f" · max {busy.get('max', 0) * 100:.0f}%")
    for key, stats in sorted((ts.get("gauges") or {}).items()):
        if key.startswith("autocycler_serve_queue_depth") \
                and isinstance(stats, dict):
            lines.append(f"  queue depth: median "
                         f"{stats.get('median', 0):g} · max "
                         f"{stats.get('max', 0):g}")
    for key, h in sorted((ts.get("hists") or {}).items()):
        if key.startswith("autocycler_serve_job_seconds") \
                and isinstance(h, dict) and h.get("p50") is not None:
            line = f"  job latency ({key}): p50 {_fmt_s(h['p50'])}"
            if h.get("p95") is not None:
                line += f" · p95 {_fmt_s(h['p95'])}"
            lines.append(line)
    slo = ts.get("slo")
    if isinstance(slo, dict):
        obj = slo.get("objectives") or {}
        if any(v is not None for v in obj.values()):
            verdict = "VIOLATED" if slo.get("violated") else "met"
            bits = [f"{k.replace('_s', '')} <= {v:g}s"
                    for k, v in sorted(obj.items()) if v is not None]
            line = f"  SLO ({', '.join(bits)}): {verdict}"
            burn = slo.get("burn_rate")
            if isinstance(burn, (int, float)):
                line += f", burn rate {burn:g}"
            lines.append(line)
        elif slo.get("p50_s") is not None:
            lines.append(f"  SLO: no objective set (window p50 "
                         f"{_fmt_s(slo['p50_s'])}, p95 "
                         f"{_fmt_s(slo.get('p95_s', slo['p50_s']))})")


def render_report(report: dict) -> str:
    lines: List[str] = []
    run_dir = report.get("dir", "")
    lines.append(f"Autocycler run report — {run_dir}")
    trace = report.get("trace")
    if trace:
        header = trace.get("run") or {}
        name = header.get("name", "?")
        argv = header.get("argv")
        lines.append(f"Command: {name}" +
                     (f"  ({' '.join(argv)})" if argv else ""))
        wall = trace.get("wall_s")
        total = trace.get("tree_total_s", 0.0)
        summary = (f"Spans: {trace.get('span_count', 0)}"
                   f"  stage-tree total {_fmt_s(total)}")
        if wall:
            summary += f"  wall {_fmt_s(wall)}"
            agreement = trace.get("wall_agreement", 0.0)
            if abs(agreement - 1.0) > WALL_AGREEMENT:
                summary += (f"  [WARNING: tree covers {agreement * 100:.1f}%"
                            " of wall — spans missing or double-counted]")
        lines.append(summary)
        lines.append("")
        lines.append("Stage tree:")
        _render_tree(trace.get("tree", []), lines,
                     parent_seconds=wall or total)
        finish = trace.get("finish") or {}
        mem = finish.get("mem") or {}
        if mem.get("peak_rss_bytes"):
            lines.append(f"  peak RSS at finish: "
                         f"{_fmt_bytes(mem['peak_rss_bytes'])}")
        lines.append("")
    metrics = report.get("metrics")
    if metrics:
        dev_s = _metric_total(metrics, "autocycler_device_seconds_total")
        dispatches = _metric_total(metrics,
                                   "autocycler_device_dispatches_total")
        failures = _metric_total(metrics, "autocycler_device_failures_total")
        wall = (trace or {}).get("wall_s")
        split = (f"Device vs host: {_fmt_s(dev_s)} on device across "
                 f"{int(dispatches)} dispatch"
                 f"{'es' if dispatches != 1 else ''}")
        if wall:
            split += f" ({100.0 * dev_s / wall:.1f}% of wall)"
        split += f"; {int(failures)} failure{'s' if failures != 1 else ''}"
        lines.append(split)
        for v in _metric_values(metrics, "autocycler_device_failure_last"):
            if v.get("value"):
                lines.append(f"  last device failure: {v['value']}")
        cache = _metric_by_label(metrics, "autocycler_cache_events_total",
                                 "cache")
        if cache:
            bits = []
            for which in sorted(cache):
                hits = misses = 0
                for v in _metric_values(metrics,
                                        "autocycler_cache_events_total"):
                    labels = v.get("labels", {})
                    if labels.get("cache") == which:
                        if labels.get("event") == "hit":
                            hits = int(v.get("value", 0))
                        elif labels.get("event") == "miss":
                            misses = int(v.get("value", 0))
                bits.append(f"{which} {hits} hit{'s' if hits != 1 else ''}"
                            f" / {misses} miss"
                            f"{'es' if misses != 1 else ''}")
            lines.append("Caches: " + " · ".join(bits))
        degrades = _metric_by_label(metrics, "autocycler_degrades_total",
                                    "chain")
        if degrades:
            lines.append("Degradations: " + ", ".join(
                f"{chain} x{int(n)}" for chain, n in sorted(degrades.items())))
        else:
            lines.append("Degradations: none recorded")
        faults = _metric_by_label(metrics, "autocycler_fault_injections_total",
                                  "site")
        if faults:
            lines.append("Fault injections: " + ", ".join(
                f"{site} x{int(n)}" for site, n in sorted(faults.items())))
        retries = _metric_by_label(
            metrics, "autocycler_subprocess_retries_total", "command")
        if retries:
            lines.append("Subprocess retries: " + ", ".join(
                f"{cmd} x{int(n)}" for cmd, n in sorted(retries.items())))
        pool = _metric_total(metrics, "autocycler_pool_tasks_total")
        if pool:
            lines.append(f"Pool tasks: {int(pool)}")
        lines.append("")
    manifest = report.get("manifest")
    if manifest:
        items = manifest.get("items", {})
        counts: Dict[str, int] = {}
        for entry in items.values():
            counts[entry.get("status", "?")] = \
                counts.get(entry.get("status", "?"), 0) + 1
        summary = ", ".join(f"{n} {status}"
                            for status, n in sorted(counts.items()))
        lines.append(f"Isolates ({len(items)}): {summary}")
        for name in sorted(items):
            entry = items[name]
            if entry.get("status") == "failed":
                stage = entry.get("stage") or "?"
                lines.append(f"  FAILED {name} (stage {stage}): "
                             f"{entry.get('error')}")
        lines.append("")
    timeseries = report.get("timeseries")
    if timeseries:
        lines.append("Continuous telemetry:")
        _telemetry_lines(timeseries, lines)
        lines.append("")
    qc = report.get("qc")
    if qc:
        lines.append("Assembly QC:")
        _render_qc_lines(qc, lines)
        lines.append("")
    ledger = report.get("ledger")
    if ledger:
        lines.append("Provenance:")
        _render_ledger_lines(ledger, lines)
        lines.append("")
    lint = report.get("lint")
    if lint:
        lines.append("Static analysis:")
        _render_lint_lines(lint, lines)
        lines.append("")
    for artifact in report.get("bench", []):
        if "metric" in artifact:
            line = (f"Bench {artifact['file']}: {artifact['metric']} = "
                    f"{artifact.get('value')} {artifact.get('unit', '')}")
            if artifact.get("vs_baseline"):
                line += f" (vs_baseline {artifact['vs_baseline']})"
            lines.append(line.rstrip())
        elif "bench" in artifact:
            lines.append(f"Bench {artifact['file']}: {artifact['bench']} "
                         f"passed={artifact.get('passed')}")
    return "\n".join(lines).rstrip() + "\n"


def _render_qc_lines(qc: dict, lines: List[str]) -> None:
    """Readable per-stage QC from a qc_report.json payload; tolerates
    partial/foreign payloads (every field is optional)."""
    for entry in qc.get("entries", []) if isinstance(qc, dict) else []:
        if not isinstance(entry, dict):
            continue
        stage = entry.get("stage", "?")
        metrics = entry.get("metrics") or {}
        prefix = "  "
        if entry.get("isolate"):
            prefix += f"[{entry['isolate']}] "
        if stage == "compress":
            lines.append(
                f"{prefix}compress: {metrics.get('unitigs', '?')} unitigs, "
                f"{metrics.get('total_bp', '?')} bp, "
                f"N50 {metrics.get('n50_bp', '?')} "
                f"(from {metrics.get('input_contigs', '?')} contigs / "
                f"{metrics.get('input_bp', '?')} bp)")
            hist = metrics.get("depth_hist_bp")
            if isinstance(hist, dict) and hist:
                lines.append(f"{prefix}  depth histogram (bp): " + ", ".join(
                    f"{k}: {v}" for k, v in hist.items()))
        elif stage == "cluster":
            lines.append(
                f"{prefix}cluster: {metrics.get('clusters_pass', '?')} pass /"
                f" {metrics.get('clusters_fail', '?')} fail "
                f"(size balance {metrics.get('size_balance_ratio', '?')})")
            for c in metrics.get("clusters") or []:
                if not isinstance(c, dict):
                    continue
                verdict = "PASS" if c.get("passed") else "FAIL"
                line = (f"{prefix}  cluster {c.get('cluster', '?'):>3}: "
                        f"{verdict}  {c.get('contigs', '?')} contigs "
                        f"{c.get('total_bp', '?')} bp "
                        f"dist {c.get('distance', '?')}")
                reasons = c.get("failure_reasons") or []
                if reasons:
                    line += f"  [{', '.join(str(r) for r in reasons)}]"
                lines.append(line)
        elif stage == "trim":
            lines.append(
                f"{prefix}trim {entry.get('cluster', '?')}: "
                f"{metrics.get('trimmed_contigs', '?')}/"
                f"{metrics.get('contigs', '?')} contigs trimmed, "
                f"{metrics.get('trimmed_bp', '?')} bp removed "
                f"({metrics.get('trim_type', '?')}; "
                f"{metrics.get('excluded_contigs', '?')} excluded)")
        elif stage == "resolve":
            lines.append(
                f"{prefix}resolve {entry.get('cluster', '?')}: "
                f"{metrics.get('anchors', '?')} anchors, "
                f"{metrics.get('bridges', '?')} bridges "
                f"({metrics.get('unique_bridges', '?')} unique / "
                f"{metrics.get('conflicting_bridges', '?')} conflicting, "
                f"{metrics.get('culled_bridges', '?')} culled), "
                f"min support {metrics.get('min_bridge_support', '?')}")
        elif stage == "combine":
            resolved = metrics.get("fully_resolved")
            lines.append(
                f"{prefix}combine: {metrics.get('clusters', '?')} clusters "
                f"-> {metrics.get('consensus_bp', '?')} bp consensus in "
                f"{metrics.get('consensus_unitigs', '?')} unitigs"
                + (", fully resolved" if resolved else
                   ", NOT fully resolved" if resolved is not None else ""))
        else:
            scalars = {k: v for k, v in metrics.items()
                       if isinstance(v, (int, float, bool, str))}
            lines.append(f"{prefix}{stage}: " + ", ".join(
                f"{k}={v}" for k, v in sorted(scalars.items())) if scalars
                else f"{prefix}{stage}")


def _render_lint_lines(lint: dict, lines: List[str]) -> None:
    """The static-analysis section from a lint_report.json artifact
    (written by `autocycler lint --report`); every field optional."""
    if not isinstance(lint, dict):
        return
    findings = lint.get("findings")
    findings = findings if isinstance(findings, list) else []
    verdict = "clean" if not findings else f"{len(findings)} finding(s)"
    bits = [verdict]
    files = lint.get("files")
    if files is not None:
        bits.append(f"{files} files")
    wall = lint.get("wall_s")
    if isinstance(wall, (int, float)):
        bits.append(f"{wall:.2f}s")
    baselined = lint.get("baselined")
    if baselined:
        bits.append(f"{baselined} baselined")
    lines.append("  lint: " + ", ".join(bits))
    for f in findings[:20]:
        if isinstance(f, dict):
            lines.append(f"    {f.get('path')}:{f.get('line')} "
                         f"[{f.get('rule')}] {f.get('message')}")
    if len(findings) > 20:
        lines.append(f"    ... and {len(findings) - 20} more")


def _render_ledger_lines(ledger: dict, lines: List[str]) -> None:
    if not isinstance(ledger, dict):
        return
    inputs = ledger.get("inputs") or {}
    if inputs:
        total = sum(v.get("bytes", 0) for v in inputs.values()
                    if isinstance(v, dict))
        lines.append(f"  inputs: {len(inputs)} file"
                     f"{'s' if len(inputs) != 1 else ''} hashed "
                     f"({_fmt_bytes(total)})")
    versions = ledger.get("versions") or {}
    if versions:
        bits = [f"autocycler_tpu {versions.get('autocycler_tpu', '?')}",
                f"python {versions.get('python', '?')}"]
        for pkg, ver in sorted((versions.get("packages") or {}).items()):
            bits.append(f"{pkg} {ver}")
        lines.append("  versions: " + " · ".join(bits))
    caches = ledger.get("caches") or {}
    if caches:
        bits = []
        for which in ("parse", "repair"):
            c = caches.get(which)
            if isinstance(c, dict):
                bits.append(f"{which} {c.get('hits', 0)} hit/"
                            f"{c.get('misses', 0)} miss")
        compile_c = caches.get("compile") or {}
        bits.append("compile " +
                    ("on" if compile_c.get("enabled") else "off"))
        probe = caches.get("probe") or {}
        bits.append(f"probe recoveries {probe.get('recoveries', 0)}")
        lines.append("  caches: " + " · ".join(bits))
    stages = ledger.get("stages") or []
    if stages:
        bits = []
        for s in stages:
            if not isinstance(s, dict):
                continue
            label = s.get("stage", "?")
            if s.get("cluster"):
                label += f"/{s['cluster']}"
            if s.get("isolate"):
                label = f"{s['isolate']}:{label}"
            bits.append(f"{label} ({len(s.get('inputs') or {})} in -> "
                        f"{len(s.get('outputs') or {})} out)")
        lines.append("  stages: " + ", ".join(bits))


def _esc(value) -> str:
    return _html.escape(str(value))


def _html_kv_table(rows, headers) -> List[str]:
    out = ["<table>", "<tr>" + "".join(f"<th>{_esc(h)}</th>"
                                       for h in headers) + "</tr>"]
    for row in rows:
        out.append("<tr>" + "".join(f"<td>{_esc(c)}</td>" for c in row)
                   + "</tr>")
    out.append("</table>")
    return out


def render_html(report: dict) -> str:
    """One self-contained HTML document (inline CSS, no external assets)
    from the merged report structure — openable from a laptop that only
    scp'd the run directory home."""
    title = f"Autocycler run report — {report.get('dir', '')}"
    parts: List[str] = [
        "<!DOCTYPE html>",
        "<html lang=\"en\"><head><meta charset=\"utf-8\">",
        f"<title>{_esc(title)}</title>",
        "<style>",
        "body{font-family:system-ui,sans-serif;margin:2em auto;"
        "max-width:70em;padding:0 1em;color:#1a1a2e;}",
        "h1{font-size:1.4em;border-bottom:2px solid #4a4e69;}",
        "h2{font-size:1.1em;margin-top:1.6em;color:#4a4e69;}",
        "pre{background:#f4f4f8;padding:0.8em;overflow-x:auto;"
        "border-radius:4px;font-size:0.85em;}",
        "table{border-collapse:collapse;margin:0.5em 0;font-size:0.9em;}",
        "th,td{border:1px solid #c9c9d4;padding:0.25em 0.6em;"
        "text-align:left;}",
        "th{background:#e9e9f0;}",
        ".pass{color:#1b7a3d;font-weight:600;}",
        ".fail{color:#b3261e;font-weight:600;}",
        ".warn{color:#8a5a00;font-weight:600;}",
        "</style></head><body>",
        f"<h1>{_esc(title)}</h1>",
    ]
    trace = report.get("trace")
    if trace:
        header = trace.get("run") or {}
        wall = trace.get("wall_s")
        bits = [f"command <b>{_esc(header.get('name', '?'))}</b>",
                f"{trace.get('span_count', 0)} spans",
                f"stage-tree total {_esc(_fmt_s(trace.get('tree_total_s', 0)))}"]
        if wall:
            bits.append(f"wall {_esc(_fmt_s(wall))}")
            agreement = trace.get("wall_agreement", 0.0)
            if abs(agreement - 1.0) > WALL_AGREEMENT:
                bits.append(f"<span class=\"warn\">tree covers "
                            f"{agreement * 100:.1f}% of wall</span>")
        parts.append("<p>" + " · ".join(bits) + "</p>")
        tree_lines: List[str] = []
        _render_tree(trace.get("tree", []), tree_lines,
                     parent_seconds=wall or trace.get("tree_total_s"))
        parts.append("<h2>Stage tree</h2>")
        parts.append("<pre>" + _esc("\n".join(tree_lines)) + "</pre>")
    qc = report.get("qc")
    if qc:
        parts.append("<h2>Assembly QC</h2>")
        qc_lines: List[str] = []
        _render_qc_lines(qc, qc_lines)
        parts.append("<pre>" + _esc("\n".join(qc_lines)) + "</pre>")
        clusters = []
        for entry in qc.get("entries", []):
            if isinstance(entry, dict) and entry.get("stage") == "cluster":
                clusters = (entry.get("metrics") or {}).get("clusters") or []
        if clusters:
            parts.append("<table><tr><th>cluster</th><th>verdict</th>"
                         "<th>contigs</th><th>bp</th><th>distance</th>"
                         "<th>failure reasons</th></tr>")
            for c in clusters:
                if not isinstance(c, dict):
                    continue
                verdict = ("<span class=\"pass\">PASS</span>"
                           if c.get("passed")
                           else "<span class=\"fail\">FAIL</span>")
                reasons = ", ".join(str(r) for r in
                                    (c.get("failure_reasons") or []))
                parts.append(
                    f"<tr><td>{_esc(c.get('cluster', '?'))}</td>"
                    f"<td>{verdict}</td>"
                    f"<td>{_esc(c.get('contigs', '?'))}</td>"
                    f"<td>{_esc(c.get('total_bp', '?'))}</td>"
                    f"<td>{_esc(c.get('distance', '?'))}</td>"
                    f"<td>{_esc(reasons)}</td></tr>")
            parts.append("</table>")
    ledger = report.get("ledger")
    if ledger:
        parts.append("<h2>Provenance</h2>")
        led_lines: List[str] = []
        _render_ledger_lines(ledger, led_lines)
        parts.append("<pre>" + _esc("\n".join(led_lines)) + "</pre>")
        inputs = ledger.get("inputs") or {}
        if inputs:
            rows = [(path, digest.get("bytes", "?"),
                     digest.get("sha256", "?")[:16] + "…")
                    for path, digest in sorted(inputs.items())
                    if isinstance(digest, dict)]
            parts.append("<h2>Input files</h2>")
            parts.extend(_html_kv_table(rows, ("path", "bytes", "sha256")))
        stage_rows = []
        for s in ledger.get("stages") or []:
            if not isinstance(s, dict):
                continue
            for path, digest in sorted((s.get("outputs") or {}).items()):
                if isinstance(digest, dict):
                    label = s.get("stage", "?")
                    if s.get("cluster"):
                        label += f"/{s['cluster']}"
                    if s.get("isolate"):
                        label = f"{s['isolate']}:{label}"
                    stage_rows.append((label, path, digest.get("bytes", "?"),
                                       digest.get("sha256", "?")[:16] + "…"))
        if stage_rows:
            parts.append("<h2>Stage outputs</h2>")
            parts.extend(_html_kv_table(
                stage_rows, ("stage", "artifact", "bytes", "sha256")))
    timeseries = report.get("timeseries")
    if timeseries:
        parts.append("<h2>Continuous telemetry</h2>")
        ts_lines: List[str] = []
        _telemetry_lines(timeseries, ts_lines)
        parts.append("<pre>" + _esc("\n".join(ts_lines)) + "</pre>")
        slo = timeseries.get("slo")
        if isinstance(slo, dict):
            obj = slo.get("objectives") or {}
            if any(v is not None for v in obj.values()):
                verdict = ("<span class=\"fail\">SLO VIOLATED</span>"
                           if slo.get("violated")
                           else "<span class=\"pass\">SLO met</span>")
                parts.append(f"<p>{verdict}</p>")
    lint = report.get("lint")
    if lint:
        parts.append("<h2>Static analysis</h2>")
        findings = lint.get("findings")
        findings = findings if isinstance(findings, list) else []
        verdict = ("<span class=\"pass\">clean</span>" if not findings
                   else f"<span class=\"fail\">{len(findings)} "
                        "finding(s)</span>")
        lint_lines: List[str] = []
        _render_lint_lines(lint, lint_lines)
        parts.append(f"<p>lint: {verdict}</p>")
        parts.append("<pre>" + _esc("\n".join(lint_lines)) + "</pre>")
        if findings:
            rows = [(f.get("rule", "?"), f.get("path", "?"),
                     f.get("line", "?"), f.get("message", "?"))
                    for f in findings if isinstance(f, dict)]
            parts.extend(_html_kv_table(
                rows, ("rule", "path", "line", "message")))
    metrics = report.get("metrics")
    if metrics:
        dev_s = _metric_total(metrics, "autocycler_device_seconds_total")
        dispatches = _metric_total(metrics,
                                   "autocycler_device_dispatches_total")
        failures = _metric_total(metrics, "autocycler_device_failures_total")
        parts.append("<h2>Device</h2>")
        parts.append(f"<p>{_esc(_fmt_s(dev_s))} on device across "
                     f"{int(dispatches)} dispatches; {int(failures)} "
                     f"failures</p>")
    manifest = report.get("manifest")
    if manifest:
        items = manifest.get("items", {})
        rows = [(name, entry.get("status", "?"), entry.get("stage") or "",
                 entry.get("error") or "")
                for name, entry in sorted(items.items())]
        parts.append(f"<h2>Isolates ({len(items)})</h2>")
        parts.extend(_html_kv_table(
            rows, ("isolate", "status", "stage", "error")))
    parts.append("</body></html>")
    return "\n".join(parts) + "\n"


def find_correlated_traces(root, trace_id: str) -> List[dict]:
    """Every trace under ``root`` carrying ``trace_id``: the run header's
    ``trace_id`` (daemon-side jobs) or any span whose ``trace`` attr
    matches (fleet shards, legacy runs). Searches the root itself, its
    ``jobs/*`` run dirs, and one replica level down (``<replica>/jobs/*``)
    — the fleet-dir layout. Returns [{"path", "rel", "trace"}] sorted by
    run start time."""
    root = Path(root)
    candidates: List[Path] = []
    for pattern in (TRACE_JSONL, f"jobs/*/{TRACE_JSONL}",
                    f"*/{TRACE_JSONL}", f"*/jobs/*/{TRACE_JSONL}"):
        candidates.extend(root.glob(pattern))
    matched: List[dict] = []
    seen = set()
    for path in candidates:
        resolved = path.resolve()
        if resolved in seen:
            continue
        seen.add(resolved)
        loaded = load_trace(path)
        if loaded is None:
            continue
        run = loaded.get("run") or {}
        hit = run.get("trace_id") == trace_id or any(
            (s.get("attrs") or {}).get("trace") == trace_id
            for s in loaded["spans"])
        if not hit:
            continue
        try:
            rel = str(path.parent.relative_to(root)) or "."
        except ValueError:
            rel = str(path.parent)
        matched.append({"path": path, "rel": rel, "trace": loaded})
    matched.sort(key=lambda m: (m["trace"]["run"].get("t0_epoch") or 0.0,
                                m["rel"]))
    return matched


def write_correlated_trace(root, trace_id: str,
                           out_path=None) -> Optional[Path]:
    """Merge every trace under ``root`` matching ``trace_id`` into ONE
    Chrome trace: one process lane per matched run (labelled by its run
    dir relative to ``root``), events aligned on a shared wall clock via
    each run header's ``t0_epoch`` — so the client's submit, each
    replica's job and its fleet shards render on one timeline. Returns the
    output path, or None when nothing matched."""
    root = Path(root)
    matched = find_correlated_traces(root, trace_id)
    if not matched:
        return None
    t0 = min(m["trace"]["run"].get("t0_epoch") or 0.0 for m in matched)
    events: List[dict] = []
    for pid, m in enumerate(matched, start=1):
        run = m["trace"]["run"]
        label = m["rel"] if m["rel"] != "." else (run.get("name") or "run")
        offset_s = (run.get("t0_epoch") or t0) - t0
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": label}})
        for s in m["trace"]["spans"]:
            events.append({
                "name": s.get("name", "?"), "cat": s.get("cat", "span"),
                "ph": "X",
                "ts": round((offset_s + (s.get("ts") or 0.0)) * 1e6, 3),
                "dur": round((s.get("dur") or 0.0) * 1e6, 3),
                "pid": pid, "tid": s.get("tid", 0),
                "args": dict(s.get("attrs", {}),
                             **({"mem": s["mem"]} if "mem" in s else {})),
            })
    out = Path(out_path) if out_path \
        else root / f"trace_correlated_{trace_id}.chrome.json"
    out.write_text(json.dumps({"traceEvents": events,
                               "displayTimeUnit": "ms"}))
    return out


def correlate_report(root, trace_id: str) -> int:
    """CLI body of `autocycler report --correlate <id>`."""
    matched = find_correlated_traces(root, trace_id)
    if not matched:
        print(f"Error: no trace under {root} carries correlation id "
              f"{trace_id!r} (looked in {TRACE_JSONL}, jobs/*/, */jobs/*/)",
              file=sys.stderr)
        return 1
    try:
        out = write_correlated_trace(root, trace_id)
    except OSError as e:
        print(f"Error: could not write merged trace: {e}", file=sys.stderr)
        return 1
    print(f"correlation {trace_id}: {len(matched)} trace(s)")
    for m in matched:
        run = m["trace"]["run"]
        spans = m["trace"]["spans"]
        t0_epoch = run.get("t0_epoch")
        started = time.strftime("%H:%M:%S", time.localtime(t0_epoch)) \
            if isinstance(t0_epoch, (int, float)) else "?"
        print(f"  {m['rel']:40s} {run.get('name', '?'):20s} "
              f"{len(spans):5d} spans  started {started}")
    print(f"merged Chrome trace: {out}")
    return 0


def report(run_dir, as_json: bool = False,
           html: Optional[str] = None,
           correlate: Optional[str] = None) -> int:
    """CLI entry point for `autocycler report`. ``html`` of "" writes
    ``run_report.html`` into the run dir; a non-empty value is the output
    path; None skips HTML. ``correlate`` switches to cross-run mode:
    merge every trace under ``run_dir`` carrying that correlation id into
    one Chrome trace with one process lane per replica/shard."""
    if correlate:
        return correlate_report(run_dir, correlate)
    built = build_report(run_dir)
    if built is None:
        print(f"Error: no telemetry found in {run_dir} (expected "
              f"{TRACE_JSONL}, {METRICS_JSON}, {QC_REPORT_JSON}, "
              f"{LEDGER_JSON}, {TIMESERIES_JSONL}, {LINT_REPORT_JSON}, "
              "batch_manifest.json or BENCH*.json)", file=sys.stderr)
        return 1
    if html is not None:
        out = Path(html) if html else Path(run_dir) / RUN_REPORT_HTML
        try:
            out.write_text(render_html(built))
            print(f"wrote {out}", file=sys.stderr)
        except OSError as e:
            print(f"Error: could not write {out}: {e}", file=sys.stderr)
            return 1
    if as_json:
        print(json.dumps(built, indent=2, sort_keys=True))
    else:
        print(render_report(built))
    return 0
