"""Probe sentinel: device forensics for the TPU probe.

Three consecutive bench rounds reported ``device_fraction: 0.0`` with a
one-line guess ("probe did not respond within 60s (wedged transport?)").
This module turns that guess into a diagnosis and keeps watching for the
transport to come back:

- :func:`subprocess_probe` runs the device probe in a *subprocess* so a
  wedged PJRT/libtpu init can be killed (a wedged in-process thread can
  only be abandoned, and keeps the jax init lock held), and so the init
  stderr — the PJRT plugin chatter that explains *why* bring-up stalled —
  is captured into the outcome instead of lost on the terminal.
- :func:`environment_snapshot` records what the probe ran against:
  ``JAX_PLATFORMS``, the ``AUTOCYCLER_*`` knobs, installed jax/TPU plugin
  versions, and ``/dev/accel*`` device files.
- every real outcome is appended to ``probe_log.jsonl`` (one JSON object
  per line) so `autocycler doctor` can render the probe history of a run
  directory, not just the last answer.
- :class:`ProbeWatcher` re-probes on an interval (``AUTOCYCLER_PROBE_WATCH``
  seconds) in a daemon thread; on the first ``false -> true`` transition it
  clears the negative probe caches (ops.distance) and fires the registered
  recovery hooks exactly once — by default :func:`recovery_capture`, a
  bounded micro-bench (grouping shootout + dotplot rates) so a transient
  tunnel recovery produces device evidence even if nobody was watching.

The sentinel never raises into the pipeline: telemetry must not fail the
run it is diagnosing.
"""

from __future__ import annotations

import glob
import json
import os
import platform
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path
from typing import Callable, List, Optional

from ..utils.knobs import (knob_bool, knob_float, knob_int, knob_raw,
                           knob_str)

PROBE_LOG = "probe_log.jsonl"
RECOVERY_CAPTURE_FILE = "recovery_capture.json"
_MARKER = "AUTOCYCLER_PROBE:"
_STDERR_TAIL = 4000

_lock = threading.Lock()
_log_dir: Optional[str] = None          # explicit (set_probe_log_dir)
_fallback_dir: Optional[str] = None     # from distance.set_probe_cache_dir
_hooks: List[Callable] = []
_last_attached: Optional[bool] = None
_recovery_fired = False
_watcher_thread: Optional[threading.Thread] = None


# ---- environment forensics ----

def environment_snapshot() -> dict:
    """What a probe on this host runs against: the platform pin, every
    AUTOCYCLER knob, installed jax/TPU-adjacent package versions and the
    accelerator device files. Pure inspection — never imports jax, never
    initialises a backend (``autocycler doctor`` must be safe to run on a
    wedged host)."""
    env_vars = {k: ("<redacted>" if ("TOKEN" in k or "SECRET" in k)
                    else os.environ[k])
                for k in sorted(os.environ)
                if k == "JAX_PLATFORMS" or k.startswith("AUTOCYCLER_")
                or k in ("XLA_FLAGS", "LIBTPU_INIT_ARGS", "TPU_NAME",
                         "PJRT_DEVICE", "TPU_LIBRARY_PATH")}
    versions = {}
    try:
        from importlib import metadata
        for dist in metadata.distributions():
            name = (dist.metadata.get("Name") or "").lower()
            if any(tag in name for tag in ("jax", "tpu", "pjrt", "axon")):
                versions[name] = dist.version
    except Exception:  # noqa: BLE001 — forensics must not fail the caller
        pass
    accel = sorted(glob.glob("/dev/accel*")) + sorted(glob.glob("/dev/vfio/*"))
    return {
        "jax_platforms": os.environ.get("JAX_PLATFORMS"),
        "env": env_vars,
        "plugin_versions": dict(sorted(versions.items())),
        "accel_devices": accel,
        "python": platform.python_version(),
        "platform": platform.platform(),
        "cpu_count": os.cpu_count(),
        "pid": os.getpid(),
    }


# ---- the subprocess probe ----

# The child replicates the CLI's platform pinning (the installed PJRT
# plugin overrides JAX_PLATFORMS from the environment, so the pin must go
# through jax.config), then initialises a backend and round-trips one tiny
# op — backend init alone can succeed while execution stalls. The outcome
# rides a marker line on stdout; everything the PJRT/libtpu init prints
# lands on stderr, which the parent captures as the diagnosis.
_PROBE_SNIPPET = """\
import json, os, time
t0 = time.perf_counter()
out = {}
try:
    import jax
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)
    backend = jax.default_backend()
    out["backend"] = backend
    out["device_count"] = jax.device_count()
    if backend != "tpu":
        out.update(attached=False, kind="no-tpu",
                   reason="jax default backend is %r" % backend)
    else:
        import jax.numpy as jnp
        float(jnp.asarray(1.0) + 1.0)
        out.update(attached=True, kind="ok",
                   reason="tpu backend verified (tiny op round-tripped "
                          "in probe subprocess)")
except Exception as e:
    out.update(attached=False, kind="error",
               reason="device init failed: %s: %s" % (type(e).__name__, e))
out["seconds"] = round(time.perf_counter() - t0, 3)
print("AUTOCYCLER_PROBE:" + json.dumps(out), flush=True)
"""


def _probe_argv() -> List[str]:
    """The probe child's argv — a seam so tests can substitute a stub that
    answers canned outcomes (or wedges) without importing jax."""
    return [sys.executable, "-c", _PROBE_SNIPPET]


def subprocess_probe(deadline: float) -> dict:
    """One device probe in a killable subprocess. Returns the outcome dict:
    ``{kind, attached, reason, seconds, stderr_tail, backend?,
    device_count?}`` where ``kind`` follows the ops.distance taxonomy
    ("ok" / "no-tpu" / "error" / "timeout"). A child that exceeds
    ``deadline`` is killed (whole session, so a wedged libtpu helper dies
    with it) and reported as a diagnosed timeout — with whatever init
    stderr it produced before wedging."""
    t0 = time.perf_counter()
    outcome: dict = {"mode": "subprocess"}
    err = ""
    try:
        proc = subprocess.Popen(_probe_argv(), stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True,
                                start_new_session=True)
    except OSError as e:
        return {"mode": "subprocess", "attached": False, "kind": "error",
                "reason": f"probe subprocess failed to start: {e}",
                "seconds": round(time.perf_counter() - t0, 3)}
    try:
        out, err = proc.communicate(timeout=deadline)
        parsed = None
        for line in (out or "").splitlines():
            if line.startswith(_MARKER):
                try:
                    parsed = json.loads(line[len(_MARKER):])
                except ValueError:
                    parsed = None
        if parsed is not None:
            outcome.update(parsed)
        elif proc.returncode != 0:
            outcome.update(attached=False, kind="error",
                           reason=f"probe subprocess exited "
                                  f"{proc.returncode} without an outcome")
        else:
            outcome.update(attached=False, kind="error",
                           reason="probe subprocess produced no outcome")
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, 9)
        except (OSError, ProcessLookupError):
            proc.kill()
        try:
            _, err = proc.communicate(timeout=2)
        except Exception:  # noqa: BLE001 — the tail is best-effort
            err = ""
        outcome.update(
            attached=False, kind="timeout",
            reason=(f"probe subprocess did not respond within "
                    f"{deadline:.0f}s (wedged transport) — killed; init "
                    "stderr captured"))
    outcome["seconds"] = round(time.perf_counter() - t0, 3)
    if err:
        outcome["stderr_tail"] = err[-_STDERR_TAIL:]
    return outcome


# ---- probe_log.jsonl ----

def set_probe_log_dir(path, fallback: bool = False) -> None:
    """Point ``probe_log.jsonl`` at ``path`` (None clears). With
    ``fallback=True`` the directory only applies when nothing explicit and
    no ``AUTOCYCLER_TRACE_DIR`` is set — ops.distance routes the probe
    cache dir here so batch/compress runs log next to device_probe.json."""
    global _log_dir, _fallback_dir
    with _lock:
        if fallback:
            _fallback_dir = None if path is None else str(path)
        else:
            _log_dir = None if path is None else str(path)


def probe_log_path() -> Optional[Path]:
    with _lock:
        explicit, fallback = _log_dir, _fallback_dir
    if explicit:
        return Path(explicit) / PROBE_LOG
    env = (knob_str("AUTOCYCLER_TRACE_DIR") or "").strip()
    if env:
        return Path(env) / PROBE_LOG
    if fallback:
        return Path(fallback) / PROBE_LOG
    return None


def probe_log_max() -> int:
    """Probe-log rotation cap: keep only the newest N entries
    (AUTOCYCLER_PROBE_LOG_MAX, default 500; 0 disables rotation)."""
    return max(0, int(knob_int("AUTOCYCLER_PROBE_LOG_MAX")))


def append_probe_log(entry: dict) -> None:
    """Append one JSON line to the configured probe log (no-op without a
    configured directory; never raises). The log is rotated to the newest
    ``probe_log_max()`` entries on append, so a long-lived
    AUTOCYCLER_PROBE_WATCH sentinel cannot grow it unboundedly."""
    path = probe_log_path()
    if path is None:
        return
    try:
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(entry, default=str) + "\n")
        _rotate_probe_log(path)
    except OSError:
        pass


def _rotate_probe_log(path: Path) -> None:
    """Truncate the probe log to its newest ``probe_log_max()`` lines via
    tempfile + atomic replace; a reader never sees a torn log. Cheap check
    first (line count ~ newline count) so the steady state is one stat."""
    cap = probe_log_max()
    if cap <= 0:
        return
    try:
        data = path.read_bytes()
    except OSError:
        return
    if data.count(b"\n") <= cap:
        return
    lines = data.splitlines(keepends=True)[-cap:]
    try:
        fd, tmp = tempfile.mkstemp(dir=str(path.parent),
                                   prefix=path.name + ".tmp")
        with os.fdopen(fd, "wb") as f:
            f.writelines(lines)
        os.replace(tmp, path)
    except OSError:
        pass


def read_probe_log(path=None, limit: Optional[int] = None) -> List[dict]:
    """Entries of a probe log (most recent last); ``limit`` keeps the tail.
    Malformed lines are skipped, a missing file is an empty history."""
    path = Path(path) if path is not None else probe_log_path()
    if path is None or not path.exists():
        return []
    entries = []
    try:
        for line in path.read_text().splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                entries.append(json.loads(line))
            except ValueError:
                continue
    except OSError:
        return []
    return entries[-limit:] if limit else entries


# ---- recovery hooks & transition tracking ----

def on_recovery(hook: Callable[[dict], None]) -> None:
    """Register a callable fired (once, with the recovering outcome) on the
    first ``false -> true`` probe transition this process observes."""
    with _lock:
        _hooks.append(hook)


def clear_recovery_hooks() -> None:
    with _lock:
        _hooks.clear()


def record_outcome(outcome: dict, source: str = "watcher") -> dict:
    """Log one probe outcome and run the transition bookkeeping: appends to
    ``probe_log.jsonl``, and on the first ``false -> true`` transition
    clears the negative probe caches (so the pipeline's gate re-probes
    immediately) and fires the recovery hooks exactly once."""
    global _last_attached, _recovery_fired
    entry = {"ts": round(time.time(), 3), "source": source}
    entry.update(outcome)
    tail = entry.get("stderr_tail")
    if isinstance(tail, str) and len(tail) > 2000:
        entry["stderr_tail"] = tail[-2000:]
    append_probe_log(entry)
    attached = bool(outcome.get("attached"))
    with _lock:
        prev = _last_attached
        _last_attached = attached
        fire = (prev is False and attached and not _recovery_fired)
        if fire:
            _recovery_fired = True
        hooks = list(_hooks)
    if attached:
        _clear_negative_caches()
    if fire:
        append_probe_log({"ts": round(time.time(), 3), "source": source,
                          "type": "recovery",
                          "note": "probe recovered (false -> true); firing "
                                  f"{len(hooks)} recovery hook(s)"})
        from . import metrics_registry
        metrics_registry.counter_inc(
            "autocycler_probe_recoveries_total", 1,
            help="false->true probe transitions observed by the sentinel")
        for hook in hooks:
            try:
                hook(entry)
            except Exception as e:  # noqa: BLE001 — a hook must not kill the watcher
                print(f"autocycler: probe recovery hook failed: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
    return entry


def _clear_negative_caches() -> None:
    """A healthy probe invalidates every cached negative: the in-memory
    failure state and the persisted device_probe.json (ops.distance owns
    both)."""
    try:
        from ..ops import distance
        distance.notify_probe_recovered()
    except Exception:  # noqa: BLE001 — cache clearing is best-effort
        pass


def _reset_for_tests() -> None:
    global _last_attached, _recovery_fired, _log_dir, _fallback_dir
    with _lock:
        _last_attached = None
        _recovery_fired = False
        _log_dir = None
        _fallback_dir = None
        _hooks.clear()


# ---- the recovery micro-bench capture ----

def recovery_capture(outcome: Optional[dict] = None,
                     out_dir=None) -> dict:
    """Bounded device-evidence capture run the moment the transport
    recovers: dotplot match-grid rate (VPU kernel, MFU-anchored) plus a
    small grouping shootout (native hash vs the LSD device sort, exactness
    checked). Results are written to ``recovery_capture.json`` next to the
    probe log and returned. Sizes are small (64k² grid, ~2 Mbp of windows)
    so the capture finishes in seconds — its job is evidence that the chip
    worked at recovery time, not a headline number."""
    result: dict = {"ts": round(time.time(), 3)}
    if outcome is not None:
        result["trigger"] = {k: outcome.get(k)
                             for k in ("ts", "kind", "reason", "source")}
    t0 = time.perf_counter()
    try:
        import jax
        backend = jax.default_backend()
        result["backend"] = backend
        if backend == "tpu":
            from ..ops.dotplot_pallas import benchmark_gcells
            from ..ops.mfu import vpu_grid_mfu
            n = int(knob_int("AUTOCYCLER_RECOVERY_DOTPLOT_N"))
            k = 32
            _, rate = benchmark_gcells(n_a=n, n_b=n, k=k, repeats=1,
                                       kernel="vpu")
            result["dotplot"] = {"kernel": "vpu", "grid": f"{n}x{n}", "k": k,
                                 "gcells_per_s": round(rate, 2),
                                 **vpu_grid_mfu(rate, k)}
        else:
            result["dotplot"] = {"skipped":
                                 f"backend {backend!r} is not a TPU"}
    except Exception as e:  # noqa: BLE001 — partial evidence beats none
        result["dotplot"] = {"error": f"{type(e).__name__}: {e}"}
    try:
        import numpy as np

        from ..ops.kmers import group_windows_full
        n = int(knob_float("AUTOCYCLER_RECOVERY_GROUPING_MBP") * 1e6)
        k = 51
        rng = np.random.default_rng(7)
        codes = rng.integers(1, 5, size=max(n, k + 2)).astype(np.uint8)
        starts = np.arange(0, len(codes) - k, dtype=np.int64)
        t = time.perf_counter()
        gid_n, order_n = group_windows_full(codes, starts, k, use_jax=False)
        native_s = time.perf_counter() - t
        t = time.perf_counter()
        gid, order = group_windows_full(codes, starts, k, use_jax="lsd")
        lsd_s = time.perf_counter() - t
        result["grouping"] = {
            "windows": len(starts), "k": k,
            "native_s": round(native_s, 3), "lsd_s": round(lsd_s, 3),
            "lsd_exact": bool((gid == gid_n).all()
                              and (order == order_n).all()),
        }
    except Exception as e:  # noqa: BLE001
        result["grouping"] = {"error": f"{type(e).__name__}: {e}"}
    result["seconds"] = round(time.perf_counter() - t0, 3)
    target = Path(out_dir) if out_dir is not None else (
        probe_log_path().parent if probe_log_path() else None)
    if target is not None:
        try:
            target.mkdir(parents=True, exist_ok=True)
            (target / RECOVERY_CAPTURE_FILE).write_text(
                json.dumps(result, indent=2, default=str) + "\n")
        except OSError:
            pass
    append_probe_log({"ts": round(time.time(), 3), "type": "capture",
                      "source": "recovery", "capture": result})
    return result


#: Default deadline for the BACKGROUND (overlapped) probe. Deliberately
#: lower than the legacy synchronous 60 s default: the background probe
#: overlaps host load/parse work, so its deadline bounds attach *lateness*
#: at the first device-dispatch point, not serial wall time — and a wedged
#: transport should stop stalling explicit `wait=True` consults after 20 s,
#: not 60.
BACKGROUND_PROBE_DEADLINE_S = 20.0


def probe_deadline(background: bool = False) -> float:
    """The probe deadline the sentinel shares with ops.distance:
    AUTOCYCLER_PROBE_DEADLINE_S wins, AUTOCYCLER_DEVICE_PROBE_TIMEOUT is
    the original spelling. The default depends on how the probe runs:
    60 s for a synchronous foreground probe (doctor --probe, the watcher,
    the legacy gate), :data:`BACKGROUND_PROBE_DEADLINE_S` when
    ``background`` (the overlapped probe started at CLI launch)."""
    default = BACKGROUND_PROBE_DEADLINE_S if background else 60.0
    if knob_raw("AUTOCYCLER_PROBE_DEADLINE_S") is not None:
        return float(knob_float("AUTOCYCLER_PROBE_DEADLINE_S",
                                default=default))
    if knob_raw("AUTOCYCLER_DEVICE_PROBE_TIMEOUT") is not None:
        return float(knob_float("AUTOCYCLER_DEVICE_PROBE_TIMEOUT",
                                default=default))
    return default


# ---- the watcher ----

class ProbeWatcher:
    """Interval re-probing with transition bookkeeping. ``cycle()`` is the
    unit of work (probe once, record, return the logged entry) so tests and
    ``doctor --watch`` drive it synchronously; :func:`maybe_start_watcher`
    wraps it in a daemon thread for pipeline runs."""

    def __init__(self, interval: float, deadline: Optional[float] = None,
                 probe_fn: Optional[Callable[[float], dict]] = None,
                 source: str = "watcher"):
        self.interval = max(float(interval), 0.01)
        self.deadline = probe_deadline() if deadline is None else deadline
        self.probe_fn = probe_fn or subprocess_probe
        self.source = source
        self.stop_event = threading.Event()
        self.cycles = 0

    def cycle(self) -> dict:
        outcome = self.probe_fn(self.deadline)
        self.cycles += 1
        return record_outcome(outcome, source=self.source)

    def run(self) -> None:
        while not self.stop_event.is_set():
            try:
                self.cycle()
            except Exception as e:  # noqa: BLE001 — the watcher must survive
                print(f"autocycler: probe watcher cycle failed: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)
            if self.stop_event.wait(self.interval):
                break

    def stop(self) -> None:
        self.stop_event.set()


def watch_interval() -> Optional[float]:
    """AUTOCYCLER_PROBE_WATCH as seconds; unset/<= 0/malformed disables."""
    interval = knob_float("AUTOCYCLER_PROBE_WATCH")
    if interval is None:
        return None
    return interval if interval > 0 else None


def maybe_start_watcher() -> Optional[threading.Thread]:
    """Start the background watcher thread when AUTOCYCLER_PROBE_WATCH is
    set (idempotent; returns the thread or None). The default recovery
    hook — the micro-bench capture — is registered unless
    AUTOCYCLER_RECOVERY_CAPTURE=0."""
    global _watcher_thread
    interval = watch_interval()
    if interval is None:
        return None
    with _lock:
        if _watcher_thread is not None and _watcher_thread.is_alive():
            return _watcher_thread
    if knob_bool("AUTOCYCLER_RECOVERY_CAPTURE"):
        with _lock:
            if recovery_capture not in _hooks:
                _hooks.append(recovery_capture)
    watcher = ProbeWatcher(interval)
    t = threading.Thread(target=watcher.run, daemon=True,
                         name="autocycler-probe-sentinel")
    t.watcher = watcher  # type: ignore[attr-defined] — reachable for stop()
    t.start()
    with _lock:
        _watcher_thread = t
    return t
