"""Continuous telemetry: a background sampler that turns the point-in-time
metrics registry into a time series.

Every observability surface before this one (spans, the registry snapshot,
`report`, `watch`, serve ``/metrics``) answers "what is true NOW / what was
true at the end?". The sampler answers "how did it evolve": a daemon thread
periodically snapshots the metrics registry, the host load (loadavg,
/proc/stat busy fraction, RSS) and the device-probe state into one JSON
line per tick in ``timeseries.jsonl`` under the run or serve root.

Design constraints, in order:

- **Never block the pipeline.** The sampler shares no lock with the serve
  scheduler's run lock (or any pipeline code); it only takes the metrics
  registry's own re-entrant lock for the microseconds a snapshot takes,
  and every filesystem touch is wrapped so an unwritable disk degrades to
  silence, not a crashed worker.
- **Bounded size.** Counters are delta-encoded per tick (each line is
  self-contained — rotation never breaks decodability) and the file is
  rotated to the newest ``AUTOCYCLER_TIMESERIES_MAX`` lines with the same
  tempfile + atomic-replace pattern as ``probe_log.jsonl``, so a
  weeks-long daemon cannot grow it unboundedly.
- **Torn-line safe readers.** A sampler killed mid-write leaves a partial
  final line; :func:`read_timeseries` only consumes up to the last
  newline and skips anything unparseable, mirroring the
  ``TraceFollower`` contract.

Knobs: ``AUTOCYCLER_TIMESERIES=0`` disables sampling,
``AUTOCYCLER_TIMESERIES_INTERVAL_S`` sets the tick period (default 5 s)
and ``AUTOCYCLER_TIMESERIES_MAX`` the rotation cap (default 2000 lines,
0 disables rotation).
"""

from __future__ import annotations

import contextlib
import json
import os
import tempfile
import threading
import time
from pathlib import Path
from typing import Callable, Dict, List, Optional

from . import metrics_registry
from ..utils.knobs import knob_bool, knob_float, knob_int

TIMESERIES_JSONL = "timeseries.jsonl"

# sampler self-telemetry: the liveness signal /healthz uses to detect a
# stale (wedged or dead) sampler, and the tick counter for rate math
TICKS_TOTAL = "autocycler_timeseries_ticks_total"
LAST_TICK_EPOCH = "autocycler_timeseries_last_tick_epoch"

def timeseries_enabled() -> bool:
    """Sampling is on by default; AUTOCYCLER_TIMESERIES=0/false/no/off
    turns it off."""
    return knob_bool("AUTOCYCLER_TIMESERIES")


def sample_interval() -> float:
    return max(0.05, float(knob_float("AUTOCYCLER_TIMESERIES_INTERVAL_S")))


def timeseries_max() -> int:
    """Rotation cap: keep only the newest N lines (0 disables rotation)."""
    return max(0, int(knob_int("AUTOCYCLER_TIMESERIES_MAX")))


# ---- host load ----

def host_sample() -> dict:
    """One host-load sample: loadavg, cumulative /proc/stat CPU jiffies
    (total + idle, so two samples give the busy fraction BETWEEN them),
    RSS and the interpreter's native thread count. Best-effort on every
    field — hosts without /proc still sample. ``bench.py
    host_load_snapshot`` is a view over this function, so bench artifacts
    and the time series can never disagree about the machine."""
    snap: dict = {"ts": round(time.time(), 3),
                  "threads": threading.active_count()}
    try:
        snap["loadavg"] = [round(v, 2) for v in os.getloadavg()]
    except (OSError, AttributeError):
        snap["loadavg"] = None
    try:
        with open("/proc/stat") as f:
            fields = f.readline().split()
        vals = [int(v) for v in fields[1:]]
        snap["cpu_jiffies_total"] = sum(vals)
        # idle + iowait: neither is work stolen from this process
        snap["cpu_jiffies_idle"] = vals[3] + (vals[4] if len(vals) > 4 else 0)
    except (OSError, ValueError, IndexError):
        pass
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        snap["rss_bytes"] = pages * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        pass
    return snap


def host_busy_frac(before: dict, after: dict) -> Optional[float]:
    """Whole-machine CPU busy fraction between two host samples (includes
    other processes — that contamination is the point), or None when
    either sample lacks /proc/stat."""
    t0, t1 = before.get("cpu_jiffies_total"), after.get("cpu_jiffies_total")
    i0, i1 = before.get("cpu_jiffies_idle"), after.get("cpu_jiffies_idle")
    if None in (t0, t1, i0, i1) or t1 <= t0:
        return None
    return round(1.0 - (i1 - i0) / (t1 - t0), 4)


# ---- registry flattening ----

def _flat_key(name: str, labels: Dict[str, str]) -> str:
    if not labels:
        return name
    body = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{body}}}"


def snapshot_quantile(entry: dict, q: float) -> Optional[float]:
    """Quantile estimate from one SNAPSHOT histogram entry (the
    ``{"buckets": {le: count}, "count", "min", "max"}`` shape
    :meth:`MetricsRegistry.snapshot` emits) — the cross-process twin of
    :meth:`MetricsRegistry.quantile` for readers that only have the
    serialized state (top, report)."""
    count = entry.get("count") or 0
    buckets = entry.get("buckets")
    lo, hi = entry.get("min"), entry.get("max")
    if not count or not isinstance(buckets, dict) \
            or not isinstance(lo, (int, float)) \
            or not isinstance(hi, (int, float)):
        return None
    target = q * count
    cum = 0.0
    prev_edge = 0.0
    for raw_edge, c in buckets.items():
        edge = hi if raw_edge == "+Inf" else float(raw_edge)
        if c and cum + c >= target:
            frac = (target - cum) / c
            est = prev_edge + frac * (max(edge, prev_edge) - prev_edge)
            return min(max(est, lo), hi)
        cum += c
        prev_edge = edge
    return hi


def _flatten(snap: dict) -> Dict[str, dict]:
    """Registry snapshot -> {"gauges": {key: value}, "counters": {key:
    cumulative}, "hists": {key: {"count", "sum", "p50", "p95"}}}. Info
    metrics are skipped (strings do not plot)."""
    gauges: Dict[str, float] = {}
    counters: Dict[str, float] = {}
    hists: Dict[str, dict] = {}
    for name, metric in snap.items():
        kind = metric.get("type")
        for entry in metric.get("values", []):
            key = _flat_key(name, entry.get("labels") or {})
            if kind == "counter":
                counters[key] = entry.get("value", 0)
            elif kind == "gauge":
                gauges[key] = entry.get("value", 0)
            elif kind == "histogram" and entry.get("count"):
                hists[key] = {
                    "count": entry["count"],
                    "sum": entry.get("sum", 0.0),
                    "p50": snapshot_quantile(entry, 0.50),
                    "p95": snapshot_quantile(entry, 0.95),
                }
    return {"gauges": gauges, "counters": counters, "hists": hists}


# ---- the sampler ----

class TimeseriesSampler:
    """Background thread appending one telemetry tick per interval to
    ``<out_dir>/timeseries.jsonl``.

    Each line is self-contained: gauges carry current values, counters and
    histogram count/sum carry the DELTA since the previous tick (so a
    rotated-away prefix loses history, never decodability), and host load
    carries the busy fraction measured across the tick. ``extra`` is an
    optional callable merged into every tick (the serve daemon passes its
    SLO/queue state through it); it must be cheap and lock-light — the
    sampler never touches pipeline locks by construction."""

    def __init__(self, out_dir, interval: Optional[float] = None,
                 registry: Optional[metrics_registry.MetricsRegistry] = None,
                 extra: Optional[Callable[[], dict]] = None):
        self.path = Path(out_dir) / TIMESERIES_JSONL
        self.interval = sample_interval() if interval is None \
            else max(0.05, float(interval))
        self._registry = registry or metrics_registry.registry()
        self._extra = extra
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._tick = 0
        self._prev_counters: Dict[str, float] = {}
        self._prev_hists: Dict[str, dict] = {}
        self._prev_host: Optional[dict] = None
        self.last_tick_epoch: Optional[float] = None

    # -- one tick --

    def sample(self) -> dict:
        """Take one tick now (the thread loop calls this; tests drive it
        synchronously). Returns the entry; never raises."""
        self._tick += 1
        now = time.time()
        entry: dict = {"ts": round(now, 3), "tick": self._tick,
                       "interval_s": self.interval}
        host = host_sample()
        if self._prev_host is not None:
            busy = host_busy_frac(self._prev_host, host)
            if busy is not None:
                host["cpu_busy_frac"] = busy
        self._prev_host = host
        entry["host"] = {k: v for k, v in host.items()
                         if k not in ("cpu_jiffies_total",
                                      "cpu_jiffies_idle")}
        try:
            flat = _flatten(self._registry.snapshot())
        except Exception:  # noqa: BLE001 — telemetry must never take
            flat = {"gauges": {}, "counters": {}, "hists": {}}  # down a run
        entry["gauges"] = flat["gauges"]
        entry["counters"] = {
            k: round(v - self._prev_counters.get(k, 0.0), 6)
            for k, v in flat["counters"].items()
            if v != self._prev_counters.get(k, 0.0)}
        self._prev_counters = flat["counters"]
        hists = {}
        for key, cur in flat["hists"].items():
            prev = self._prev_hists.get(key, {})
            hists[key] = {
                "count": cur["count"] - prev.get("count", 0),
                "sum": round(cur["sum"] - prev.get("sum", 0.0), 6),
                "p50": cur["p50"], "p95": cur["p95"]}
        self._prev_hists = flat["hists"]
        entry["hists"] = hists
        with contextlib.suppress(Exception):
            from ..ops.distance import probe_overlap_report
            entry["probe"] = probe_overlap_report()
        if self._extra is not None:
            with contextlib.suppress(Exception):
                entry.update(self._extra() or {})
        self.last_tick_epoch = now
        # self-telemetry AFTER the snapshot: the tick that records these
        # values is always the next one, keeping each line causal
        with contextlib.suppress(Exception):
            self._registry.counter_inc(
                TICKS_TOTAL, 1, help="telemetry sampler ticks taken")
            self._registry.gauge_set(
                LAST_TICK_EPOCH, now,
                help="epoch of the most recent telemetry sampler tick")
        self._append(entry)
        return entry

    def _append(self, entry: dict) -> None:
        try:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            with open(self.path, "a") as f:
                f.write(json.dumps(entry, default=str) + "\n")
            _rotate_timeseries(self.path)
        except OSError:
            pass

    # -- lifecycle --

    def start(self) -> "TimeseriesSampler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="autocycler-timeseries", daemon=True)
        self._thread.start()
        return self

    def _loop(self) -> None:
        # no immediate tick: a run shorter than one interval records
        # nothing and pays only thread start/join — sampling overhead must
        # stay invisible next to a tiny traced run's wall clock
        while not self._stop.wait(self.interval):
            self.sample()

    def stop(self, final_sample: bool = True) -> None:
        """Stop the thread; by default takes one last tick — but only when
        the series already has ticks, so a sub-interval lifetime stays a
        zero-cost no-op."""
        thread, self._thread = self._thread, None
        if thread is None:
            return
        self._stop.set()
        thread.join(timeout=max(5.0, self.interval * 2))
        if final_sample and self._tick > 0:
            self.sample()

    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()


def _rotate_timeseries(path: Path) -> None:
    """Truncate to the newest ``timeseries_max()`` lines via tempfile +
    atomic replace (the ``probe_log.jsonl`` pattern): a reader never sees
    a torn file, and the cheap newline count keeps the steady state at one
    read."""
    cap = timeseries_max()
    if cap <= 0:
        return
    try:
        data = path.read_bytes()
    except OSError:
        return
    if data.count(b"\n") <= cap:
        return
    lines = data.splitlines(keepends=True)[-cap:]
    try:
        fd, tmp = tempfile.mkstemp(dir=str(path.parent),
                                   prefix=path.name + ".tmp")
        with os.fdopen(fd, "wb") as f:
            f.writelines(lines)
        os.replace(tmp, path)
    except OSError:
        pass


# ---- readers ----

def read_timeseries(path, limit: Optional[int] = None) -> List[dict]:
    """Parse a timeseries.jsonl (most recent last); ``limit`` keeps the
    tail. Torn final lines (no trailing newline yet) and malformed lines
    are skipped; a missing file is an empty series. Never raises."""
    path = Path(path)
    try:
        data = path.read_bytes()
    except OSError:
        return []
    cut = data.rfind(b"\n")
    if cut < 0:
        return []
    entries: List[dict] = []
    for line in data[:cut].split(b"\n"):
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except (ValueError, UnicodeDecodeError):
            continue
        if isinstance(rec, dict):
            entries.append(rec)
    return entries[-limit:] if limit else entries


def _series(entries: List[dict], *path_keys: str) -> List[float]:
    """Numeric series for one nested key across entries (absent ticks are
    skipped, so schema growth never breaks old readers)."""
    out: List[float] = []
    for e in entries:
        node = e
        for k in path_keys:
            node = node.get(k) if isinstance(node, dict) else None
        if isinstance(node, (int, float)) and not isinstance(node, bool):
            out.append(float(node))
    return out


def _median(values: List[float]) -> float:
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    return ordered[mid] if n % 2 else (ordered[mid - 1] + ordered[mid]) / 2.0


def summarize_timeseries(entries: List[dict]) -> Optional[dict]:
    """min/median/max/last per sampled metric across the series — the
    ``report`` telemetry section. None for an empty series."""
    if not entries:
        return None

    def _dict(e: dict, key: str) -> dict:
        got = e.get(key)
        return got if isinstance(got, dict) else {}

    out: dict = {"ticks": len(entries)}
    ts = _series(entries, "ts")
    if len(ts) >= 2:
        out["span_s"] = round(ts[-1] - ts[0], 3)
    host: Dict[str, dict] = {}
    for field in ("cpu_busy_frac", "rss_bytes", "threads"):
        vals = _series(entries, "host", field)
        if vals:
            host[field] = {"min": min(vals), "median": _median(vals),
                           "max": max(vals), "last": vals[-1]}
    la = [_dict(e, "host").get("loadavg") for e in entries]
    la1 = [v[0] for v in la if isinstance(v, list) and v]
    if la1:
        host["loadavg1"] = {"min": min(la1), "median": _median(la1),
                            "max": max(la1), "last": la1[-1]}
    if host:
        out["host"] = host
    gauges: Dict[str, dict] = {}
    keys = {k for e in entries for k in _dict(e, "gauges")}
    for key in sorted(keys):
        vals = _series(entries, "gauges", key)
        if vals:
            gauges[key] = {"min": min(vals), "median": _median(vals),
                           "max": max(vals), "last": vals[-1]}
    if gauges:
        out["gauges"] = gauges
    counters: Dict[str, float] = {}
    for e in entries:
        for key, delta in _dict(e, "counters").items():
            if isinstance(delta, (int, float)):
                counters[key] = round(counters.get(key, 0.0) + delta, 6)
    if counters:
        out["counters"] = counters
    hists: Dict[str, dict] = {}
    for e in reversed(entries):
        for key, h in _dict(e, "hists").items():
            if key not in hists and isinstance(h, dict):
                hists[key] = {"p50": h.get("p50"), "p95": h.get("p95")}
    if hists:
        out["hists"] = hists
    return out


def purge_timeseries(root) -> tuple:
    """Delete the time-series artifacts under ``root``: the root's own
    ``timeseries.jsonl`` (+ leftover rotation temp files) and each serve
    job's. Returns (files removed, bytes reclaimed); missing dirs purge
    nothing — the `clean --cache` contract."""
    root = Path(root)
    removed = reclaimed = 0
    patterns = (TIMESERIES_JSONL, TIMESERIES_JSONL + ".tmp*",
                "jobs/*/" + TIMESERIES_JSONL,
                "jobs/*/" + TIMESERIES_JSONL + ".tmp*")
    for pattern in patterns:
        for path in root.glob(pattern):
            try:
                size = path.stat().st_size
                path.unlink()
            except OSError:
                continue
            removed += 1
            reclaimed += size
    return removed, reclaimed
