"""`autocycler top <dir>`: a live fleet dashboard over a serve root (or
any run directory with a time series).

`watch` follows one run's span stream; `top` is the fleet view — it
aggregates the daemon's discovery file (``serve.json``), the job manifest
(``serve_manifest.json``) and the continuous telemetry
(``timeseries.jsonl``, written by :mod:`obs.timeseries`) into one frame:
queue depth and throughput sparklines, latency quantiles with the SLO
verdict, cache hit-rate, the device/host split and memory. Everything is
read from artifacts, so it works cross-process against a live daemon, a
finished run, or a directory scp'd home — no HTTP endpoint required.

Modes mirror `watch`: ``--once`` (default) renders one frame and exits;
``--follow`` re-renders every ``--interval`` seconds (bounded by
``--cycles`` when given).
"""

from __future__ import annotations

import contextlib
import json
import sys
import time
from pathlib import Path
from typing import Dict, List, Optional

from . import report as obs_report
from .timeseries import (TIMESERIES_JSONL, read_timeseries,
                         summarize_timeseries)

SPARK_BLOCKS = "▁▂▃▄▅▆▇█"
SPARK_WIDTH = 32


def sparkline(values: List[float], width: int = SPARK_WIDTH) -> str:
    """Unicode block sparkline of the series tail (newest right). A flat
    series renders as a flat low line, not noise."""
    vals = [v for v in values if isinstance(v, (int, float))][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return SPARK_BLOCKS[0] * len(vals)
    span = hi - lo
    return "".join(
        SPARK_BLOCKS[min(len(SPARK_BLOCKS) - 1,
                         int((v - lo) / span * len(SPARK_BLOCKS)))]
        for v in vals)


def _load_json(path: Path) -> Optional[dict]:
    try:
        data = json.loads(path.read_text())
    except (OSError, ValueError):
        return None
    return data if isinstance(data, dict) else None


def _gauge_series(entries: List[dict], name: str) -> List[float]:
    """Per-tick values of one gauge, summed across label sets."""
    out = []
    for e in entries:
        total = None
        for key, v in (e.get("gauges") or {}).items():
            if key == name or key.startswith(name + "{"):
                if isinstance(v, (int, float)):
                    total = (total or 0.0) + v
        if total is not None:
            out.append(total)
    return out


def _counter_delta_series(entries: List[dict], name: str) -> List[float]:
    """Per-tick deltas of one counter, summed across label sets (absent
    in a tick means no change — rendered 0 so the sparkline stays dense)."""
    out = []
    for e in entries:
        total = 0.0
        for key, v in (e.get("counters") or {}).items():
            if key == name or key.startswith(name + "{"):
                if isinstance(v, (int, float)):
                    total += v
        out.append(total)
    return out


def _cache_rates(entries: List[dict]) -> Dict[str, dict]:
    """Cumulative hit/miss per cache from the delta-encoded counter
    stream."""
    out: Dict[str, dict] = {}
    for e in entries:
        for key, v in (e.get("counters") or {}).items():
            if not key.startswith("autocycler_cache_events_total{") \
                    or not isinstance(v, (int, float)):
                continue
            body = key[key.index("{") + 1:-1]
            labels = dict(part.split("=", 1) for part in body.split(",")
                          if "=" in part)
            which = labels.get("cache")
            event = labels.get("event")
            if which and event in ("hit", "miss"):
                out.setdefault(which, {"hit": 0.0, "miss": 0.0})
                out[which][event] += v
    return out


def _latest(entries: List[dict], key: str) -> Optional[dict]:
    for e in reversed(entries):
        node = e.get(key)
        if isinstance(node, dict):
            return node
    return None


def _load_entries(root: Path) -> List[dict]:
    """The root's own series, or — for a root whose jobs carry their own
    samplers — the per-job series merged in time order."""
    entries = read_timeseries(root / TIMESERIES_JSONL)
    if entries:
        return entries
    merged: List[dict] = []
    for path in sorted(root.glob("jobs/*/" + TIMESERIES_JSONL)):
        merged.extend(read_timeseries(path))
    merged.sort(key=lambda e: e.get("ts") or 0.0)
    return merged


def render_top_frame(root) -> Optional[str]:
    """One dashboard frame from the artifacts under ``root``; None when
    the directory holds neither a time series nor serve artifacts."""
    root = Path(root)
    info = _load_json(root / "serve.json")
    manifest = _load_json(root / "serve_manifest.json")
    if manifest is None:
        # a torn manifest (daemon crashed mid-save) still renders: the
        # resilience reader falls back to the last good .bak state
        from ..utils.resilience import read_manifest
        data = read_manifest(root / "serve_manifest.json")
        manifest = data if data.get("items") else None
    entries = _load_entries(root)
    if not entries and info is None and manifest is None:
        return None
    lines: List[str] = []

    head = f"Autocycler top — {root}"
    if info:
        up = ""
        started = info.get("started_epoch")
        if isinstance(started, (int, float)):
            up = f" up {obs_report._fmt_s(max(0.0, time.time() - started))}"
        head += (f"  [daemon pid {info.get('pid', '?')}{up} @ "
                 f"{info.get('endpoint', '?')}]")
    else:
        head += "  [no live daemon — rendering artifacts]"
    lines.append(head)

    if manifest:
        items = manifest.get("items") or {}
        counts: Dict[str, int] = {}
        for entry in items.values():
            if isinstance(entry, dict):
                status = entry.get("status", "?")
                counts[status] = counts.get(status, 0) + 1
        summary = " · ".join(f"{n} {status}"
                             for status, n in sorted(counts.items()))
        lines.append(f"Jobs:        {len(items)} total  ({summary})"
                     if items else "Jobs:        none yet")

    if entries:
        serve_last = _latest(entries, "serve")
        depth = _gauge_series(entries,
                              "autocycler_serve_queue_depth")
        if serve_last is not None or depth:
            now_depth = (serve_last or {}).get("queue_depth")
            if now_depth is None and depth:
                now_depth = int(depth[-1])
            spark = sparkline(depth)
            line = f"Queue depth  {spark or '-'}"
            if now_depth is not None:
                line += f"  now {int(now_depth)}"
            if depth:
                line += f" (max {int(max(depth))})"
            lines.append(line)

        # worker-pool width and live busy count: the serve block of the
        # latest tick when present, serve.json as the cross-version
        # fallback (older daemons record neither — line is omitted)
        workers = (serve_last or {}).get("workers") \
            or (info or {}).get("workers")
        if workers:
            busy = (serve_last or {}).get("busy_workers")
            wline = f"Workers      {workers}"
            if isinstance(busy, int):
                wline += f"  ({busy} busy)"
            lines.append(wline)

        jobs_deltas = _counter_delta_series(
            entries, "autocycler_serve_jobs_total")
        if any(jobs_deltas):
            total_jobs = sum(jobs_deltas)
            rate = ""
            interval = entries[-1].get("interval_s")
            if isinstance(interval, (int, float)) and interval > 0:
                rate = (f"  {jobs_deltas[-1] * 60.0 / interval:.1f} "
                        "jobs/min (last tick)")
            lines.append(f"Throughput   {sparkline(jobs_deltas)}"
                         f"{rate}  {int(total_jobs)} finished in view")

        slo = _latest(entries, "slo")
        lat = _latency_line(slo, entries)
        if lat:
            lines.append(lat)

        caches = _cache_rates(entries)
        if caches:
            bits = []
            for which in sorted(caches):
                hit, miss = caches[which]["hit"], caches[which]["miss"]
                total = hit + miss
                pct = f" ({100.0 * hit / total:.0f}% hit)" if total else ""
                bits.append(f"{which} {int(hit)}/{int(total)}{pct}")
            lines.append("Caches       " + " · ".join(bits))

        dev_deltas = _counter_delta_series(
            entries, "autocycler_device_seconds_total")
        busy = [v for v in
                (e.get("host", {}).get("cpu_busy_frac") for e in entries)
                if isinstance(v, (int, float))]
        if any(dev_deltas) or busy:
            bits = []
            if any(dev_deltas):
                bits.append(f"device {sparkline(dev_deltas)} "
                            f"{sum(dev_deltas):.2f}s in view")
            if busy:
                bits.append(f"host cpu {sparkline(busy)} "
                            f"now {busy[-1] * 100:.0f}%")
            lines.append("Device/host  " + " · ".join(bits))

        rss = [v for v in
               (e.get("host", {}).get("rss_bytes") for e in entries)
               if isinstance(v, (int, float))]
        if rss:
            lines.append(f"Memory       RSS {sparkline(rss)} now "
                         f"{obs_report._fmt_bytes(rss[-1])} "
                         f"(peak {obs_report._fmt_bytes(max(rss))})")

        spill = _gauge_series(entries, "autocycler_stream_spill_bytes")
        bin_deltas = _counter_delta_series(
            entries, "autocycler_stream_bins_total")
        rle = _gauge_series(entries, "autocycler_stream_rle_ratio")
        if any(spill) or any(bin_deltas):
            bits = [f"disk {sparkline(spill)} now "
                    f"{obs_report._fmt_bytes(spill[-1] if spill else 0)} "
                    f"(peak {obs_report._fmt_bytes(max(spill) if spill else 0)})"]
            if any(bin_deltas):
                bits.append(f"bins +{int(sum(bin_deltas))} in view")
            if any(rle):
                bits.append(f"rle {max(rle):.1f}x")
            lines.append("Spill        " + " · ".join(bits))

        summary = summarize_timeseries(entries) or {}
        span = summary.get("span_s")
        tick_bits = f"{summary.get('ticks', len(entries))} ticks"
        if isinstance(span, (int, float)) and span > 0:
            tick_bits += f" over {obs_report._fmt_s(span)}"
        interval = entries[-1].get("interval_s")
        if isinstance(interval, (int, float)):
            tick_bits += f" (interval {interval:g}s)"
        age = time.time() - (entries[-1].get("ts") or 0.0)
        if isinstance(interval, (int, float)) and age > 3 * interval:
            tick_bits += f"  [STALE: last tick {obs_report._fmt_s(age)} ago]"
        lines.append(f"Sampler      {tick_bits}")
    else:
        lines.append(f"No {TIMESERIES_JSONL} yet — queue/latency trends "
                     "appear once the sampler ticks")

    return "\n".join(lines).rstrip() + "\n"


def _latency_line(slo: Optional[dict], entries: List[dict]) -> Optional[str]:
    """The latency quantiles + SLO verdict line, preferring the daemon's
    windowed SLO block and falling back to histogram estimates from the
    latest tick."""
    p50 = p95 = None
    extra = ""
    if slo:
        p50, p95 = slo.get("p50_s"), slo.get("p95_s")
        qw, ex = slo.get("queue_wait_p50_s"), slo.get("exec_p50_s")
        if qw is not None or ex is not None:
            parts = []
            if qw is not None:
                parts.append(f"queue p50 {obs_report._fmt_s(qw)}")
            if ex is not None:
                parts.append(f"exec p50 {obs_report._fmt_s(ex)}")
            extra = "  (" + " · ".join(parts) + ")"
    if p50 is None:
        for e in reversed(entries):
            for key, h in (e.get("hists") or {}).items():
                if key.startswith("autocycler_serve_job_seconds") \
                        and isinstance(h, dict) and h.get("p50") is not None:
                    p50, p95 = h.get("p50"), h.get("p95")
                    break
            if p50 is not None:
                break
    if p50 is None:
        return None
    line = f"Latency      p50 {obs_report._fmt_s(p50)}"
    if p95 is not None:
        line += f"  p95 {obs_report._fmt_s(p95)}"
    line += extra
    if slo:
        obj = slo.get("objectives") or {}
        if any(v is not None for v in obj.values()):
            verdict = "VIOLATED" if slo.get("violated") else "ok"
            line += f"  SLO {verdict}"
            burn = slo.get("burn_rate")
            if isinstance(burn, (int, float)):
                line += f" (burn {burn:g})"
            if slo.get("shedding"):
                line += "  SHEDDING"
        else:
            line += "  SLO: no objective set"
    return line


def _fleet_snapshot(root: Path) -> Optional[dict]:
    """A fresh fleet snapshot: poll the replicas discovered under ``root``
    (this also refreshes ``fleet_status.json`` and advances the verdict
    engine's persisted hysteresis state); when nothing is discoverable,
    fall back to a previously written ``fleet_status.json``."""
    from .federate import FLEET_STATUS_JSON, FleetScraper, read_fleet_status
    scraper = FleetScraper(fleet_dir=root)
    snap = scraper.poll()
    if snap.get("replicas"):
        return snap
    stale = read_fleet_status(root / FLEET_STATUS_JSON)
    return stale if stale.get("replicas") else None


def render_fleet_frame(root) -> Optional[str]:
    """One `top --fleet` frame: per-replica health lines, the fleet
    rollup, merged latency quantiles and the hysteresis-gated scale
    verdict. None when no replica (live or recorded) is visible."""
    root = Path(root)
    snap = _fleet_snapshot(root)
    if snap is None:
        return None
    summary = snap.get("summary") or {}
    verdict = snap.get("verdict") or {}
    lines: List[str] = []
    lines.append(f"Autocycler fleet — {root}  "
                 f"[{summary.get('replicas', 0)} replica(s): "
                 f"{summary.get('healthy', 0)} healthy, "
                 f"{summary.get('stale', 0)} stale, "
                 f"{summary.get('down', 0)} down]")
    for name in sorted(snap.get("replicas") or {}):
        block = snap["replicas"][name] or {}
        health = block.get("health") or {}
        if block.get("healthy"):
            state = health.get("status", "ok")
        elif health:
            state = "stale"
        else:
            state = "down"
        line = (f"  {name:16s} {state:8s} "
                f"{block.get('endpoint', '?')}")
        if health:
            workers = health.get("workers") or 0
            busy = health.get("busy_workers") or 0
            line += (f"  queue {health.get('queue_depth', 0)}"
                     f"  busy {busy}/{workers}")
            slo = health.get("slo") or {}
            burn = slo.get("burn_rate")
            if isinstance(burn, (int, float)):
                line += f"  burn {burn:g}"
            if health.get("version"):
                line += f"  v{health['version']}"
        elif block.get("error"):
            line += f"  ({block['error']})"
        lines.append(line)
    util = summary.get("utilization")
    rollup = (f"Fleet        queue {summary.get('queue_depth', 0)}"
              f"  busy {summary.get('busy_workers', 0)}"
              f"/{summary.get('workers', 0)}")
    if isinstance(util, (int, float)):
        rollup += f"  util {util * 100:.0f}%"
    burn = summary.get("burn_rate")
    if isinstance(burn, (int, float)):
        rollup += f"  burn {burn:g}"
    jobs = summary.get("jobs") or {}
    if jobs:
        rollup += "  jobs " + " · ".join(
            f"{n} {state}" for state, n in sorted(jobs.items()))
    lines.append(rollup)
    # fleet latency: the merged (bucket-wise summed) job-seconds histogram
    # with the most observations across label sets
    hists = (snap.get("metrics") or {}).get("hists") or {}
    best = None
    for key, h in hists.items():
        if key.startswith("autocycler_serve_job_seconds") \
                and isinstance(h, dict) and h.get("count"):
            if best is None or h["count"] > best["count"]:
                best = h
    if best is not None and best.get("p50") is not None:
        line = (f"Latency      fleet p50 {obs_report._fmt_s(best['p50'])}")
        if best.get("p95") is not None:
            line += f"  p95 {obs_report._fmt_s(best['p95'])}"
        line += (f"  ({best['count']} job(s) across "
                 f"{best.get('replicas', '?')} replica(s))")
        lines.append(line)
    if summary.get("version_skew"):
        lines.append("Versions     SKEW: "
                     + ", ".join(summary.get("versions") or []))
    vline = f"Verdict      {verdict.get('verdict', 'steady').upper()}"
    reasons = verdict.get("reasons") or []
    if reasons:
        vline += "  (" + "; ".join(reasons) + ")"
    desired = verdict.get("desired")
    if desired and desired != verdict.get("verdict"):
        vline += (f"  [pending {desired}: streak "
                  f"{verdict.get('streak', 0)}/{verdict.get('needed', 1)}]")
    cooldown = verdict.get("cooldown_remaining_s")
    if isinstance(cooldown, (int, float)) and cooldown > 0:
        vline += f"  [cooldown {obs_report._fmt_s(cooldown)}]"
    lines.append(vline)
    return "\n".join(lines).rstrip() + "\n"


def top(root, follow: bool = False, interval: float = 2.0,
        cycles: Optional[int] = None, fleet: bool = False) -> int:
    """CLI entry for `autocycler top`. ``--once`` renders the current
    fleet state and exits (1 when the directory holds no artifacts at
    all); ``--follow`` re-renders until interrupted (or ``cycles``
    frames). ``--fleet`` switches to the federated view: ``root`` is a
    fleet dir of replica serve roots, each frame polls every replica and
    renders the merged snapshot + scale verdict."""
    root = Path(root)
    render = render_fleet_frame if fleet else render_top_frame
    if not follow:
        frame = render(root)
        if frame is None:
            if fleet:
                print(f"Error: no replica serve.json (or fleet_status.json)"
                      f" under {root} — nothing to federate",
                      file=sys.stderr)
            else:
                print(f"Error: no {TIMESERIES_JSONL}, serve.json or "
                      f"serve_manifest.json in {root} — nothing to show",
                      file=sys.stderr)
            return 1
        print(frame, end="")
        return 0
    polled = 0
    announced_wait = False
    with contextlib.suppress(KeyboardInterrupt):
        while True:
            frame = render(root)
            if frame is None:
                if not announced_wait:
                    print(f"Waiting for artifacts in {root} "
                          "(no daemon or sampler output yet)...", flush=True)
                    announced_wait = True
            else:
                stamp = time.strftime("%H:%M:%S")
                print(f"--- {stamp} ---")
                print(frame, end="", flush=True)
            polled += 1
            if cycles is not None and polled >= cycles:
                return 0
            time.sleep(max(0.1, interval))
    return 0
