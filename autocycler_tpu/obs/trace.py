"""Span tracer: nested, attributed timing spans for a whole run.

One *run* (``start_run``/``finish_run``, normally driven by the CLI from
``AUTOCYCLER_TRACE_DIR``) records every :func:`span` the pipeline opens —
command, stage, substage and device-dispatch granularity — to

- ``trace.jsonl``: one JSON record per completed span (id, parent, thread,
  start offset, duration, attributes, and a memory sample on top-level
  spans), streamed as spans close so a killed run keeps its partial trace;
- ``trace.chrome.json``: the same spans as Chrome ``trace_event`` complete
  ("ph": "X") events, loadable in Perfetto / ``chrome://tracing``;
- ``metrics.json`` / ``metrics.prom``: the metrics-registry snapshot at
  run end (JSON and Prometheus text format).

Parent/child nesting is tracked per thread (a span opened inside a pool
worker roots its own lane, exactly how the Chrome viewer renders it).

The disabled path is deliberately free: with no active run, :func:`span`
returns a shared no-op context manager — no I/O, no per-call state, O(1)
allocation — so tracing can stay compiled into every hot path
(tests/test_obs.py pins this).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from pathlib import Path
from typing import List, Optional

from . import memory as obs_memory
from . import metrics_registry

TRACE_JSONL = "trace.jsonl"
TRACE_CHROME = "trace.chrome.json"
METRICS_JSON = "metrics.json"
METRICS_PROM = "metrics.prom"

# spans kept in memory for the Chrome export; a run that somehow exceeds
# this (a pathological per-item span in a hot loop) keeps streaming JSONL
# but stops growing the in-memory list, and records how many were dropped
MAX_SPANS_IN_MEMORY = 200_000

_lock = threading.Lock()
_local = threading.local()


class _Run:
    __slots__ = ("dir", "file", "t0_perf", "t0_epoch", "name", "spans",
                 "next_id", "dropped", "tids")

    def __init__(self, trace_dir: Path, name: str):
        self.dir = trace_dir
        self.file = open(trace_dir / TRACE_JSONL, "w")
        self.t0_perf = time.perf_counter()
        self.t0_epoch = time.time()
        self.name = name
        self.spans: List[dict] = []
        self.next_id = 1
        self.dropped = 0
        self.tids = {}          # thread ident -> small stable lane number


_run: Optional[_Run] = None


def tracing_active() -> bool:
    return _run is not None


def trace_dir() -> Optional[Path]:
    return _run.dir if _run is not None else None


class _NoopSpan:
    """The shared disabled-path span: entering/exiting does nothing and
    allocates nothing (one module-level instance serves every call)."""
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


def _stack() -> list:
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    return stack


class _Span:
    __slots__ = ("name", "cat", "attrs", "id", "parent", "t0_perf", "ts")

    def __init__(self, name: str, cat: str, attrs: dict):
        self.name = name
        self.cat = cat
        self.attrs = attrs

    def __enter__(self):
        run = _run
        if run is None:          # run finished between span() and __enter__
            self.id = None
            return self
        stack = _stack()
        self.parent = stack[-1].id if stack else None
        with _lock:
            self.id = run.next_id
            run.next_id += 1
        self.t0_perf = time.perf_counter()
        self.ts = self.t0_perf - run.t0_perf
        stack.append(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self.id is None:
            return False
        dur = time.perf_counter() - self.t0_perf
        stack = _stack()
        if stack and stack[-1] is self:
            stack.pop()
        run = _run
        if run is None:
            return False
        record = {"type": "span", "name": self.name, "cat": self.cat,
                  "id": self.id, "parent": self.parent,
                  "ts": round(self.ts, 6), "dur": round(dur, 6)}
        if self.attrs:
            record["attrs"] = self.attrs
        if exc_type is not None:
            record["error"] = exc_type.__name__
        # top-level spans carry a memory sample: cheap (rusage + /proc +
        # already-live jax buffers) and exactly the granularity the report
        # renders ("what did each stage leave resident?")
        if self.parent is None and self.cat in ("command", "stage", "run"):
            mem = obs_memory.memory_sample()
            if mem:
                record["mem"] = mem
        ident = threading.get_ident()
        with _lock:
            if _run is not run:
                return False
            record["tid"] = run.tids.setdefault(ident, len(run.tids))
            if len(run.spans) < MAX_SPANS_IN_MEMORY:
                run.spans.append(record)
            else:
                run.dropped += 1
            try:
                run.file.write(json.dumps(record, default=str) + "\n")
            except (OSError, ValueError):
                pass
        return False

    def set_attr(self, **attrs) -> None:
        """Attach/overwrite attributes after the span opened."""
        if self.attrs:
            self.attrs.update(attrs)
        else:
            self.attrs = dict(attrs)


def span(name: str, cat: str = "stage", **attrs):
    """A context manager timing one nested unit of work.

    With no active run this is the shared :data:`NOOP_SPAN` (no I/O, O(1)
    allocation). With a run active it records start offset, duration,
    parent span (per-thread nesting), category and ``attrs`` into the run's
    span stream."""
    if _run is None:
        return NOOP_SPAN
    return _Span(name, cat, attrs)


def current_span() -> Optional[_Span]:
    stack = getattr(_local, "stack", None)
    return stack[-1] if stack else None


def start_run(trace_dir, name: str = "run") -> Path:
    """Begin recording a run into ``trace_dir`` (created if needed).
    Returns the directory. A second start while a run is active is an
    error — finish the first (the CLI owns the run lifecycle)."""
    global _run
    trace_dir = Path(trace_dir)
    trace_dir.mkdir(parents=True, exist_ok=True)
    with _lock:
        if _run is not None:
            raise RuntimeError(
                f"a trace run is already active in {_run.dir}")
        run = _Run(trace_dir, name)
        header = {"type": "run", "name": name, "t0_epoch": run.t0_epoch,
                  "pid": os.getpid(), "argv": list(sys.argv)}
        run.file.write(json.dumps(header) + "\n")
        run.file.flush()
        _run = run
    return trace_dir


def maybe_start_run(name: str = "run") -> bool:
    """Start a run from ``AUTOCYCLER_TRACE_DIR`` when the variable is set
    and no run is active; returns True when this call started one (and so
    owns the matching :func:`finish_run`)."""
    from ..utils.knobs import knob_str
    target = (knob_str("AUTOCYCLER_TRACE_DIR") or "").strip()
    if not target or _run is not None:
        return False
    try:
        start_run(target, name=name)
        return True
    except OSError as e:
        print(f"autocycler: cannot start trace run in {target!r}: {e}",
              file=sys.stderr)
        return False


def finish_run() -> Optional[Path]:
    """Close the active run: write the finish record, the Chrome trace and
    the metrics snapshot (JSON + Prometheus). Returns the run directory
    (None when no run was active). Never raises on I/O problems — telemetry
    must not fail the pipeline."""
    global _run
    with _lock:
        run = _run
        _run = None
    if run is None:
        return None
    wall = time.perf_counter() - run.t0_perf
    footer = {"type": "finish", "wall": round(wall, 6),
              "spans": len(run.spans) + run.dropped, "dropped": run.dropped,
              "mem": obs_memory.memory_sample()}
    try:
        run.file.write(json.dumps(footer, default=str) + "\n")
        run.file.close()
    except (OSError, ValueError):
        pass
    try:
        _write_chrome_trace(run.dir / TRACE_CHROME, run.spans, run.name)
    except OSError:
        pass
    try:
        reg = metrics_registry.registry()
        (run.dir / METRICS_JSON).write_text(reg.to_json() + "\n")
        (run.dir / METRICS_PROM).write_text(reg.to_prometheus())
    except OSError:
        pass
    return run.dir


def write_metrics_file(path) -> None:
    """Write the Prometheus text snapshot to ``path`` (the
    ``AUTOCYCLER_METRICS`` hook for scrape-file collectors)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(metrics_registry.to_prometheus())


def _write_chrome_trace(path: Path, spans: List[dict], name: str) -> None:
    """Chrome trace_event JSON: one complete ("ph": "X") event per span,
    timestamps/durations in microseconds, thread lanes from the per-run
    small thread ids."""
    pid = os.getpid()
    events = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
               "args": {"name": f"autocycler {name}"}}]
    for s in spans:
        events.append({
            "name": s["name"], "cat": s["cat"], "ph": "X",
            "ts": round(s["ts"] * 1e6, 3), "dur": round(s["dur"] * 1e6, 3),
            "pid": pid, "tid": s.get("tid", 0),
            "args": dict(s.get("attrs", {}),
                         **({"mem": s["mem"]} if "mem" in s else {})),
        })
    path.write_text(json.dumps({"traceEvents": events,
                                "displayTimeUnit": "ms"}))


def _abort_run_for_tests() -> None:
    """Drop any active run without writing artifacts (test isolation)."""
    global _run
    with _lock:
        run = _run
        _run = None
    if run is not None:
        try:
            run.file.close()
        except OSError:
            pass
