"""Span tracer: nested, attributed timing spans for a whole run.

One *run* (``start_run``/``finish_run``, normally driven by the CLI from
``AUTOCYCLER_TRACE_DIR``) records every :func:`span` the pipeline opens —
command, stage, substage and device-dispatch granularity — to

- ``trace.jsonl``: one JSON record per completed span (id, parent, thread,
  start offset, duration, attributes, and a memory sample on top-level
  spans), streamed as spans close so a killed run keeps its partial trace;
- ``trace.chrome.json``: the same spans as Chrome ``trace_event`` complete
  ("ph": "X") events, loadable in Perfetto / ``chrome://tracing``;
- ``metrics.json`` / ``metrics.prom``: the metrics-registry snapshot at
  run end (JSON and Prometheus text format).

Parent/child nesting is tracked per thread (a span opened inside a pool
worker roots its own lane, exactly how the Chrome viewer renders it).

Two kinds of run can be active at once:

- the process-wide run (``start_run``/``finish_run``) — the CLI path;
  at most one exists, and a second ``start_run`` is an error;
- *scoped* runs (``open_run``/``bind_run``/``close_run``) — the serve
  scheduler's path: each daemon job opens its own run and binds it to the
  thread(s) executing that job, so N concurrent jobs stream N disjoint
  ``trace.jsonl`` files from one process. A thread with no bound run falls
  back to the process-wide run, then — when exactly one scoped run is open
  — to that run, so single-worker daemons keep attributing pool-thread
  spans exactly as the global-run implementation did.

The disabled path is deliberately free: with no active run, :func:`span`
returns a shared no-op context manager — no I/O, no per-call state, O(1)
allocation — so tracing can stay compiled into every hot path
(tests/test_obs.py pins this).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from pathlib import Path
from typing import List, Optional

from . import memory as obs_memory
from . import metrics_registry

TRACE_JSONL = "trace.jsonl"
TRACE_CHROME = "trace.chrome.json"
METRICS_JSON = "metrics.json"
METRICS_PROM = "metrics.prom"

# spans kept in memory for the Chrome export; a run that somehow exceeds
# this (a pathological per-item span in a hot loop) keeps streaming JSONL
# but stops growing the in-memory list, and records how many were dropped
MAX_SPANS_IN_MEMORY = 200_000

_lock = threading.Lock()
_local = threading.local()


class _Run:
    __slots__ = ("dir", "file", "t0_perf", "t0_epoch", "name", "spans",
                 "next_id", "dropped", "tids", "lock", "closed")

    def __init__(self, trace_dir: Path, name: str):
        self.dir = trace_dir
        self.file = open(trace_dir / TRACE_JSONL, "w")
        self.t0_perf = time.perf_counter()
        self.t0_epoch = time.time()
        self.name = name
        self.spans: List[dict] = []
        self.next_id = 1
        self.dropped = 0
        self.tids = {}          # thread ident -> small stable lane number
        self.lock = threading.Lock()   # per-run: id allocation + file writes
        self.closed = False


_run: Optional[_Run] = None
_scoped_runs: List[_Run] = []


def _active_run() -> Optional[_Run]:
    """The run the current thread should record into: its bound scoped run,
    else the process-wide run, else — when exactly one scoped run is open —
    that run (so unbound pool threads under a single-worker daemon attribute
    spans exactly as they did with the global run)."""
    run = getattr(_local, "run", None)
    if run is not None and not run.closed:
        return run
    run = _run
    if run is not None:
        return run
    scoped = _scoped_runs
    if len(scoped) == 1:
        return scoped[0]
    return None


class bind_run:
    """Bind a scoped run to the current thread for the duration of the
    ``with`` block: every :func:`span`, :func:`tracing_active` check and
    ledger/QC hook on this thread resolves to ``run``. Nestable; restores
    the previous binding on exit."""

    def __init__(self, run: _Run):
        self.run = run

    def __enter__(self):
        self._prev = getattr(_local, "run", None)
        _local.run = self.run
        return self.run

    def __exit__(self, *exc):
        _local.run = self._prev
        return False


def current_run() -> Optional[_Run]:
    """The run the calling thread would record into (see
    :func:`_active_run`) — what pool helpers capture to propagate trace
    context into worker threads."""
    return _active_run()


def tracing_active() -> bool:
    return _active_run() is not None


def trace_dir() -> Optional[Path]:
    run = _active_run()
    return run.dir if run is not None else None


class _NoopSpan:
    """The shared disabled-path span: entering/exiting does nothing and
    allocates nothing (one module-level instance serves every call)."""
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


NOOP_SPAN = _NoopSpan()


def _stack(run: _Run) -> list:
    """The per-thread span stack of ``run``: nesting is tracked per
    (thread, run) so concurrent scoped runs never parent across runs."""
    stacks = getattr(_local, "stacks", None)
    if stacks is None:
        stacks = _local.stacks = {}
    stack = stacks.get(id(run))
    if stack is None:
        stack = stacks[id(run)] = []
    return stack


class _Span:
    __slots__ = ("name", "cat", "attrs", "id", "parent", "t0_perf", "ts",
                 "run")

    def __init__(self, name: str, cat: str, attrs: dict):
        self.name = name
        self.cat = cat
        self.attrs = attrs

    def __enter__(self):
        run = _active_run()
        if run is None or run.closed:   # run finished before __enter__
            self.id = None
            self.run = None
            return self
        self.run = run
        stack = _stack(run)
        self.parent = stack[-1].id if stack else None
        with run.lock:
            self.id = run.next_id
            run.next_id += 1
        self.t0_perf = time.perf_counter()
        self.ts = self.t0_perf - run.t0_perf
        stack.append(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self.id is None:
            return False
        dur = time.perf_counter() - self.t0_perf
        run = self.run
        stacks = getattr(_local, "stacks", None)
        stack = stacks.get(id(run)) if stacks else None
        if stack and stack[-1] is self:
            stack.pop()
            if not stack:
                del stacks[id(run)]
        record = {"type": "span", "name": self.name, "cat": self.cat,
                  "id": self.id, "parent": self.parent,
                  "ts": round(self.ts, 6), "dur": round(dur, 6)}
        if self.attrs:
            record["attrs"] = self.attrs
        if exc_type is not None:
            record["error"] = exc_type.__name__
        # top-level spans carry a memory sample: cheap (rusage + /proc +
        # already-live jax buffers) and exactly the granularity the report
        # renders ("what did each stage leave resident?")
        if self.parent is None and self.cat in ("command", "stage", "run"):
            mem = obs_memory.memory_sample()
            if mem:
                record["mem"] = mem
        ident = threading.get_ident()
        with run.lock:
            if run.closed:
                return False
            record["tid"] = run.tids.setdefault(ident, len(run.tids))
            if len(run.spans) < MAX_SPANS_IN_MEMORY:
                run.spans.append(record)
            else:
                run.dropped += 1
            try:
                run.file.write(json.dumps(record, default=str) + "\n")
            except (OSError, ValueError):
                pass
        return False

    def set_attr(self, **attrs) -> None:
        """Attach/overwrite attributes after the span opened."""
        if self.attrs:
            self.attrs.update(attrs)
        else:
            self.attrs = dict(attrs)


def span(name: str, cat: str = "stage", **attrs):
    """A context manager timing one nested unit of work.

    With no active run this is the shared :data:`NOOP_SPAN` (no I/O, O(1)
    allocation). With a run active it records start offset, duration,
    parent span (per-thread nesting), category and ``attrs`` into the run's
    span stream."""
    if _active_run() is None:
        return NOOP_SPAN
    return _Span(name, cat, attrs)


def current_span() -> Optional[_Span]:
    run = _active_run()
    if run is None:
        return None
    stacks = getattr(_local, "stacks", None)
    stack = stacks.get(id(run)) if stacks else None
    return stack[-1] if stack else None


def _create_run(trace_dir, name: str, trace_id: Optional[str] = None) -> _Run:
    trace_dir = Path(trace_dir)
    trace_dir.mkdir(parents=True, exist_ok=True)
    run = _Run(trace_dir, name)
    header = {"type": "run", "name": name, "t0_epoch": run.t0_epoch,
              "pid": os.getpid(), "argv": list(sys.argv)}
    if trace_id:
        # cross-process correlation id (X-Autocycler-Trace):
        # `autocycler report --correlate <id>` matches runs on this key
        header["trace_id"] = trace_id
    run.file.write(json.dumps(header) + "\n")
    run.file.flush()
    return run


def start_run(trace_dir, name: str = "run") -> Path:
    """Begin recording the process-wide run into ``trace_dir`` (created if
    needed). Returns the directory. A second start while a run is active is
    an error — finish the first (the CLI owns the run lifecycle)."""
    global _run
    with _lock:
        if _run is not None:
            raise RuntimeError(
                f"a trace run is already active in {_run.dir}")
        _run = _create_run(trace_dir, name)
        return _run.dir


def open_run(trace_dir, name: str = "run",
             trace_id: Optional[str] = None) -> _Run:
    """Open a *scoped* run: records like the process-wide run but does not
    claim the process-wide slot, so any number can be open concurrently
    (one per serve job). Threads record into it via :class:`bind_run`;
    finish it with :func:`close_run`. ``trace_id`` (a client correlation
    id) lands in the run header for `report --correlate`."""
    run = _create_run(trace_dir, name, trace_id=trace_id)
    with _lock:
        _scoped_runs.append(run)
    return run


def maybe_start_run(name: str = "run") -> bool:
    """Start a run from ``AUTOCYCLER_TRACE_DIR`` when the variable is set
    and no run is active; returns True when this call started one (and so
    owns the matching :func:`finish_run`)."""
    from ..utils.knobs import knob_str
    target = (knob_str("AUTOCYCLER_TRACE_DIR") or "").strip()
    if not target or _run is not None:
        return False
    try:
        start_run(target, name=name)
        return True
    except OSError as e:
        print(f"autocycler: cannot start trace run in {target!r}: {e}",
              file=sys.stderr)
        return False


def _finalize(run: _Run) -> Path:
    """Write the finish record, the Chrome trace and the metrics snapshot
    (JSON + Prometheus) for a run already removed from the active slots.
    Never raises on I/O problems — telemetry must not fail the pipeline."""
    wall = time.perf_counter() - run.t0_perf
    footer = {"type": "finish", "wall": round(wall, 6),
              "spans": len(run.spans) + run.dropped, "dropped": run.dropped,
              "mem": obs_memory.memory_sample()}
    try:
        run.file.write(json.dumps(footer, default=str) + "\n")
        run.file.close()
    except (OSError, ValueError):
        pass
    try:
        _write_chrome_trace(run.dir / TRACE_CHROME, run.spans, run.name)
    except OSError:
        pass
    try:
        reg = metrics_registry.registry()
        (run.dir / METRICS_JSON).write_text(reg.to_json() + "\n")
        (run.dir / METRICS_PROM).write_text(reg.to_prometheus())
    except OSError:
        pass
    return run.dir


def finish_run() -> Optional[Path]:
    """Close the process-wide run. Returns the run directory (None when no
    run was active)."""
    global _run
    with _lock:
        run = _run
        _run = None
    if run is None:
        return None
    with run.lock:
        run.closed = True
    return _finalize(run)


def close_run(run: _Run) -> Optional[Path]:
    """Close a scoped run opened with :func:`open_run`. Returns its
    directory (None when already closed). In-flight spans of other threads
    observe ``closed`` under the run lock and drop their records."""
    with _lock:
        if run in _scoped_runs:
            _scoped_runs.remove(run)
    with run.lock:
        if run.closed:
            return None
        run.closed = True
    return _finalize(run)


def write_metrics_file(path) -> None:
    """Write the Prometheus text snapshot to ``path`` (the
    ``AUTOCYCLER_METRICS`` hook for scrape-file collectors)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(metrics_registry.to_prometheus())


def _write_chrome_trace(path: Path, spans: List[dict], name: str) -> None:
    """Chrome trace_event JSON: one complete ("ph": "X") event per span,
    timestamps/durations in microseconds, thread lanes from the per-run
    small thread ids."""
    pid = os.getpid()
    events = [{"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
               "args": {"name": f"autocycler {name}"}}]
    for s in spans:
        events.append({
            "name": s["name"], "cat": s["cat"], "ph": "X",
            "ts": round(s["ts"] * 1e6, 3), "dur": round(s["dur"] * 1e6, 3),
            "pid": pid, "tid": s.get("tid", 0),
            "args": dict(s.get("attrs", {}),
                         **({"mem": s["mem"]} if "mem" in s else {})),
        })
    path.write_text(json.dumps({"traceEvents": events,
                                "displayTimeUnit": "ms"}))


def _abort_run_for_tests() -> None:
    """Drop any active run (global and scoped) without writing artifacts
    (test isolation)."""
    global _run
    with _lock:
        runs = ([_run] if _run is not None else []) + list(_scoped_runs)
        _run = None
        _scoped_runs.clear()
    for run in runs:
        run.closed = True
        try:
            run.file.close()
        except OSError:
            pass
