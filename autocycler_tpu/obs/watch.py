"""`autocycler watch <dir>`: follow a run's trace cross-process.

The span tracer streams ``trace.jsonl`` one record per *closed* span, so a
separate process can tail the file and render the run as it happens — the
observability substrate a long `batch` run (or the roadmap's `serve`
daemon) needs: "which isolate is it on, what has passed QC so far, how
much landed on device?" without attaching to the worker process.

Two modes:

- ``--once`` (the default): parse whatever the trace holds right now,
  render one frame, exit;
- ``--follow``: poll the file (default every 2 s), re-render whenever new
  spans land, and exit when the run's ``finish`` footer arrives. The run
  dir (or its ``trace.jsonl``) not existing *yet* is not an error in this
  mode: `autocycler submit --follow` starts watching a job's run dir
  before the daemon has admitted the job, so the follower announces it is
  waiting and keeps polling until the tracer creates the file.

The follower is torn-line safe (it only consumes up to the last newline,
exactly the boundary the tracer writes atomically under its lock) and
restarts cleanly when the file is replaced by a new run (the tracer opens
``trace.jsonl`` with ``"w"``, so a shrink or a fresh run header means
"start over").
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path
from typing import Dict, List, Optional

from . import report as obs_report
from .qc import QC_REPORT_JSON
from .trace import TRACE_JSONL


class TraceFollower:
    """Incremental reader of one ``trace.jsonl``: each :meth:`poll` returns
    the records appended since the last poll, never a torn line."""

    def __init__(self, path):
        self.path = Path(path)
        self._pos = 0
        self._carry = b""

    def poll(self) -> List[dict]:
        try:
            size = self.path.stat().st_size
        except OSError:
            return []
        if size < self._pos:        # file replaced by a new run — restart
            self._pos = 0
            self._carry = b""
        if size == self._pos:
            return []
        try:
            with open(self.path, "rb") as f:
                f.seek(self._pos)
                chunk = f.read()
        except OSError:
            return []
        self._pos += len(chunk)
        data = self._carry + chunk
        cut = data.rfind(b"\n")
        if cut < 0:                 # only a partial line so far — keep it
            self._carry = data
            return []
        self._carry = data[cut + 1:]
        records = []
        for line in data[:cut].split(b"\n"):
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue
        return records


def _load_qc(run_dir: Path) -> Optional[dict]:
    path = run_dir / QC_REPORT_JSON
    if not path.is_file():
        return None
    try:
        return json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return None


def _qc_from_spans(spans: List[dict]) -> Dict[str, dict]:
    """QC highlights straight from span attributes — available live, while
    ``qc_report.json`` only lands at run end."""
    out: Dict[str, dict] = {}
    for s in spans:
        qc = (s.get("attrs") or {}).get("qc")
        if isinstance(qc, dict):
            for key, metrics in qc.items():
                if isinstance(metrics, dict):
                    out[key] = metrics
    return out


def _fmt_metrics(metrics: dict) -> str:
    bits = []
    for key in sorted(metrics):
        value = metrics[key]
        if isinstance(value, float):
            bits.append(f"{key}={value:g}")
        else:
            bits.append(f"{key}={value}")
    return " ".join(bits)


def _async_probe_line(run_dir: Path) -> Optional[str]:
    """The worker's async-probe state, reconstructed cross-process from
    ``probe_log.jsonl`` (the watcher cannot see the worker's in-memory
    future). Returns None when no probe outcome has been logged yet —
    rendered as pending by the caller."""
    from . import sentinel
    entries = sentinel.read_probe_log(Path(run_dir) / sentinel.PROBE_LOG,
                                      limit=50)
    last = next((e for e in reversed(entries)
                 if "attached" in e and "type" not in e), None)
    if last is None:
        return None
    state = "attached" if last.get("attached") else "failed"
    line = (f"Async probe: {state} kind={last.get('kind')} "
            f"after {last.get('seconds')}s [{last.get('source')}]")
    retries = sum(1 for e in entries
                  if e.get("source") == "background-retry")
    if retries:
        line += f" ({retries} retr{'ies' if retries != 1 else 'y'} "
        line += "before the final outcome)"
    return line


def render_frame(run_dir, records: List[dict]) -> str:
    """One full text frame from the records parsed so far: run state, the
    stage/isolate tree, the device/host split and QC highlights."""
    run_dir = Path(run_dir)
    run = next((r for r in records if r.get("type") == "run"), None)
    finish = next((r for r in records if r.get("type") == "finish"), None)
    spans = [r for r in records if r.get("type") == "span"]
    lines: List[str] = []

    name = (run or {}).get("name", "?")
    if finish:
        state = f"finished (wall {obs_report._fmt_s(finish.get('wall', 0))})"
    elif run:
        elapsed = max(0.0, time.time() - run.get("t0_epoch", time.time()))
        state = f"running {obs_report._fmt_s(elapsed)}"
    else:
        state = "waiting for run header"
    lines.append(f"Watching {run_dir} — {name} [{state}]  "
                 f"{len(spans)} span{'s' if len(spans) != 1 else ''}")

    if spans:
        lines.append("")
        lines.append("Stage tree (closed spans so far):")
        tree = obs_report.span_tree(spans)
        total = sum(n["seconds"] for n in tree)
        obs_report._render_tree(tree, lines, parent_seconds=total or None)

        device_s = sum(s.get("dur", 0.0) for s in spans
                       if s.get("cat") == "device")
        device_n = sum(s.get("cat") == "device" for s in spans)
        wall = finish.get("wall") if finish else None
        split = (f"Device vs host: {obs_report._fmt_s(device_s)} across "
                 f"{device_n} dispatch{'es' if device_n != 1 else ''}")
        if isinstance(wall, (int, float)) and wall > 0:
            split += f" ({100.0 * device_s / wall:.1f}% of wall)"
        lines.append("")
        lines.append(split)
        wait_s = sum(s.get("dur", 0.0) for s in spans
                     if s.get("cat") == "device_wait")
        if wait_s:
            lines.append(f"Blocked on probe future: "
                         f"{obs_report._fmt_s(wait_s)} "
                         "(device wait, excluded from device time)")

    probe_line = _async_probe_line(run_dir)
    if probe_line:
        lines.append("")
        lines.append(probe_line)
    elif not finish and run:
        lines.append("")
        lines.append("Async probe: pending (no outcome logged yet)")

    if spans:
        isolates: Dict[str, dict] = {}
        for s in spans:
            if s.get("cat") != "isolate":
                continue
            iso = isolates.setdefault(s["name"], {"seconds": 0.0,
                                                  "stages": []})
            iso["seconds"] += s.get("dur", 0.0)
            stage = (s.get("attrs") or {}).get("stage")
            if stage and stage not in iso["stages"]:
                iso["stages"].append(stage)
        if isolates:
            lines.append("")
            lines.append(f"Isolates ({len(isolates)}):")
            for name in sorted(isolates):
                iso = isolates[name]
                stages = " -> ".join(iso["stages"]) or "?"
                lines.append(f"  {name:<30} {stages}  "
                             f"({obs_report._fmt_s(iso['seconds'])})")

    qc_report = _load_qc(run_dir)
    highlights = _qc_from_spans(spans)
    if qc_report:
        for entry in qc_report.get("entries", []):
            key = entry.get("stage", "?")
            if entry.get("cluster"):
                key = f"{key}/{entry['cluster']}"
            if entry.get("isolate"):
                key = f"{entry['isolate']}:{key}"
            scalars = {k: v for k, v in (entry.get("metrics") or {}).items()
                       if isinstance(v, (int, float, bool))}
            if scalars:
                highlights[key] = scalars
    if highlights:
        lines.append("")
        lines.append("QC:")
        for key in sorted(highlights):
            lines.append(f"  {key:<24} {_fmt_metrics(highlights[key])}")
    return "\n".join(lines).rstrip() + "\n"


def watch(run_dir, follow: bool = False, interval: float = 2.0,
          cycles: Optional[int] = None) -> int:
    """CLI entry for `autocycler watch`. ``--once`` renders the current
    state and exits (1 when there is no trace at all); ``--follow`` keeps
    polling (bounded by ``cycles`` when given) until the run finishes."""
    run_dir = Path(run_dir)
    trace_path = run_dir / TRACE_JSONL
    if not follow:
        if not trace_path.is_file():
            print(f"Error: no {TRACE_JSONL} in {run_dir} — nothing to watch",
                  file=sys.stderr)
            return 1
        follower = TraceFollower(trace_path)
        print(render_frame(run_dir, follower.poll()), end="")
        return 0

    follower = TraceFollower(trace_path)
    records: List[dict] = []
    polled = 0
    announced_wait = False
    try:
        while True:
            if not records and not trace_path.is_file():
                # run dir not created yet (e.g. the job is still queued in a
                # serve daemon) — wait for the tracer, don't error out
                if not announced_wait:
                    print(f"Waiting for {trace_path} to appear "
                          "(run not started yet)...", flush=True)
                    announced_wait = True
                polled += 1
                if cycles is not None and polled >= cycles:
                    return 0
                time.sleep(max(0.1, interval))
                continue
            new = follower.poll()
            if new:
                # a fresh run header means the file was rewritten — drop
                # the previous run's records
                for i, rec in enumerate(new):
                    if rec.get("type") == "run" and records:
                        records = []
                        new = new[i:]
                        break
                records.extend(new)
                stamp = time.strftime("%H:%M:%S")
                print(f"--- {stamp} ---")
                print(render_frame(run_dir, records), end="", flush=True)
                if any(r.get("type") == "finish" for r in new):
                    return 0
            polled += 1
            if cycles is not None and polled >= cycles:
                return 0
            time.sleep(max(0.1, interval))
    except KeyboardInterrupt:
        return 0
