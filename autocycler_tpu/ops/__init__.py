from .encode import (ALPHABET, encode_bytes, decode_codes, revcomp_codes,
                     CODE_DOT, CODE_A, CODE_C, CODE_G, CODE_T)
from .kmers import KmerIndex, build_kmer_index
