"""Weighted path-overlap alignment (the trim DP) and global alignment
distance (the resolve DP), as vectorised kernels.

Parity target: reference trim.rs:366-507 and resolve.rs:387-418. Both DPs
run over unitig-ID paths (ints), weighted by unitig length.

Vectorisation note: weights are integers, so every DP score is a multiple of
0.5 and f64 arithmetic on them is exact (no rounding). That lets the
row-sequential insert recurrence

    S[i][j] = max(base[i][j], S[i][j-1] - w_j)

be rewritten with column-weight prefix sums W as

    S[i][j] + W[j] = running_max(base[i][j] + W[j])

i.e. one cumulative-max per row — identical results to the reference's
cell-by-cell loops, but each row is a single vector op (numpy here; the same
formulation maps to a lax.scan over rows on TPU).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

# weights: unitig number -> length, as a dict or a dense number-indexed array
# (scalar indexing is identical; the array form lets the kernels gather whole
# paths in one vector op)
Weights = Union[Dict[int, int], np.ndarray]

GAP = 0
NONE = -1  # the reference uses usize::MAX; -1 is the Python stand-in


class AlignmentPiece:
    """One column of the overlap alignment (reference trim.rs:329-349)."""

    __slots__ = ("a_unitig", "a_index", "b_unitig", "b_index")

    def __init__(self, a_unitig: int, a_index: int, b_unitig: int, b_index: int):
        self.a_unitig = a_unitig
        self.a_index = a_index
        self.b_unitig = b_unitig
        self.b_index = b_index

    def __eq__(self, other):
        return (self.a_unitig, self.a_index, self.b_unitig, self.b_index) == \
            (other.a_unitig, other.a_index, other.b_unitig, other.b_index)

    def __repr__(self):
        a_u = "GAP" if self.a_unitig == GAP else str(self.a_unitig)
        b_u = "GAP" if self.b_unitig == GAP else str(self.b_unitig)
        a_i = "NONE" if self.a_index == NONE else str(self.a_index)
        b_i = "NONE" if self.b_index == NONE else str(self.b_index)
        return f"{a_u},{a_i},{b_u},{b_i}"


def overlap_alignment(path_a: Sequence[int], path_b: Sequence[int],
                      weights: Weights, min_identity: float,
                      max_unitigs: int, skip_diagonal: bool) -> List[AlignmentPiece]:
    """Find an overlap alignment from the right edge to the top edge of the
    (first k of a) × (last k of b) scoring matrix (reference trim.rs:366-479).

    Matches score +w, mismatches -(w_a+w_b)/2, indels -w; the matrix is
    capped at max_unitigs² and, for path-vs-itself alignment, the main
    diagonal is skipped to avoid the trivial whole-vs-whole alignment.
    Returns [] when no alignment reaches the top edge with positive score
    and sufficient identity.
    """
    assert len(path_a) == len(path_b)
    n = len(path_a)
    k = min(max_unitigs, n)
    if k == 0:
        return []

    pa = np.asarray(path_a, dtype=np.int64)
    pb = np.asarray(path_b, dtype=np.int64)
    if isinstance(weights, np.ndarray):
        wa = weights[np.abs(pa)].astype(np.float64)
        wb = weights[np.abs(pb)].astype(np.float64)
    else:
        wa = np.array([weights[abs(int(u))] for u in pa], dtype=np.float64)
        wb = np.array([weights[abs(int(u))] for u in pb], dtype=np.float64)

    b_glob = n - k + np.arange(1, k + 1) - 1       # global b index per column j=1..k
    wcol = wb[b_glob]
    Wcum = np.concatenate([[0.0], np.cumsum(wcol)])  # indexed by j=0..k
    a_vals = pa
    b_vals = pb[b_glob]

    # Exact no-overlap short-circuit: matches are the only positive score
    # contribution, so if no off-diagonal (a_window, b_window) pair is equal,
    # every right-edge score is <= 0 and the DP provably returns [] — an
    # O(k log k) test replacing the O(k^2) matrix for the common
    # nothing-to-trim case (most sequences in both trim passes).
    a_win = pa[:k]
    if len(np.intersect1d(a_win, b_vals)) == 0:
        return []
    if skip_diagonal:
        # total equal pairs vs equal pairs that sit exactly on the (skipped)
        # diagonal j == gi - (n-k) + 1, i.e. b_glob == gi
        common = np.intersect1d(a_win, b_vals)
        a_sort = np.sort(a_win)
        b_sort = np.sort(b_vals)
        a_counts = np.searchsorted(a_sort, common, side="right") - \
            np.searchsorted(a_sort, common, side="left")
        b_counts = np.searchsorted(b_sort, common, side="right") - \
            np.searchsorted(b_sort, common, side="left")
        total_pairs = int((a_counts.astype(np.int64) * b_counts).sum())
        # column j = gi-(n-k)+1 has global b index n-k+j-1 = gi, and is in
        # range 1..k only for gi in [max(0, n-k), k)
        gi_range = np.arange(max(0, n - k), k)
        diag_pairs = int((pa[gi_range] == pb[gi_range]).sum()) \
            if len(gi_range) else 0
        if total_pairs == diag_pairs:
            return []

    from .. import native
    matrix = None
    tb = native.overlap_dp_tb_native(pa, wa, b_vals, wcol, n, k, skip_diagonal) \
        if native.available() else None
    if tb is None and native.available():
        matrix = native.overlap_dp_native(pa, wa, b_vals, wcol, n, k, skip_diagonal)
    if tb is None and matrix is None:
        matrix = np.full((k + 1, k + 1), -np.inf)
        matrix[0, :] = 0.0
        matrix[:, 0] = 0.0
        for i in range(1, k + 1):
            gi = i - 1
            wi = wa[gi]
            prev = matrix[i - 1]
            match_add = np.where(a_vals[gi] == b_vals, wi, -(wi + wcol) / 2.0)
            base = np.maximum(prev[:k] + match_add, prev[1:] - wi)
            # diagonal skip leaves the cell at -inf and restarts the insert chain
            jd = gi - (n - k) + 1 if skip_diagonal else 0
            T = base + Wcum[1:]
            if 1 <= jd <= k:
                run = np.empty(k)
                run[:jd - 1] = np.maximum.accumulate(
                    np.concatenate([[0.0], T[:jd - 1]]))[1:]
                if jd < k:
                    run[jd:] = np.maximum.accumulate(T[jd:])
                row = run - Wcum[1:]
                row[jd - 1] = -np.inf
            else:
                row = np.maximum.accumulate(np.concatenate([[0.0], T]))[1:] - Wcum[1:]
            matrix[i, 1:] = row

    # best score on the right edge (smallest row wins ties, like the
    # reference's strict > scan)
    if tb is not None:
        right_edge, bits, words = tb

        def up_ge(i: int, j: int) -> bool:
            # packed (S[i-1][j] >= S[i][j-1]) bit from the rolling-row kernel
            return bool((int(bits[i * words + (j >> 6)]) >> (j & 63)) & 1)
    else:
        right_edge = matrix[:, k]

        def up_ge(i: int, j: int) -> bool:
            return matrix[i - 1, j] >= matrix[i, j - 1]

    max_i = int(np.argmax(right_edge[1:])) + 1
    max_score = right_edge[max_i]
    if not max_score > 0.0:
        return []
    return _traceback_and_identity(pa, pb, n, k, max_i, up_ge, weights,
                                   min_identity)


def _traceback_and_identity(pa, pb, n: int, k: int, max_i: int, up_ge,
                            weights: Weights, min_identity: float
                            ) -> List[AlignmentPiece]:
    """Traceback from (max_i, k) to the top edge plus the identity gate
    (reference trim.rs:426-475) — shared by the host DPs and the device
    packed-bits decode."""
    pieces: List[AlignmentPiece] = []
    i, j = max_i, k
    while i > 0 and j > 0:
        gi, gj = i - 1, n - k + j - 1
        if pa[gi] == pb[gj]:
            pieces.append(AlignmentPiece(int(pa[gi]), gi, int(pb[gj]), gj))
            i -= 1
            j -= 1
        elif up_ge(i, j):
            pieces.append(AlignmentPiece(int(pa[gi]), gi, GAP, NONE))
            i -= 1
        else:
            pieces.append(AlignmentPiece(GAP, NONE, int(pb[gj]), gj))
            j -= 1
    if i > 0:
        return []  # traceback must reach the top edge, not the left edge
    pieces.reverse()

    a_len = sum(weights[abs(p.a_unitig)] for p in pieces if p.a_unitig != GAP)
    b_len = sum(weights[abs(p.b_unitig)] for p in pieces if p.b_unitig != GAP)
    mean_length = (a_len + b_len) / 2.0
    matches = sum(weights[abs(p.a_unitig)] for p in pieces
                  if p.a_unitig == p.b_unitig)
    if mean_length == 0 or matches / mean_length < min_identity:
        return []
    return pieces


def _overlap_windows(path_a, path_b, weights, max_unitigs: int):
    """Shared window extraction for the overlap DP (trim.rs:366-386)."""
    n = len(path_a)
    k = min(max_unitigs, n)
    pa = np.asarray(path_a, dtype=np.int64)
    pb = np.asarray(path_b, dtype=np.int64)
    wa = weights[np.abs(pa[:k])].astype(np.int64)
    b_glob = n - k + np.arange(k)
    b_vals = pb[b_glob]
    wcol = weights[np.abs(b_vals)].astype(np.int64)
    return n, k, pa[:k], b_vals, wa, wcol


_NEG_BIG = -(1 << 28)  # worse than any true score (>= -2*total_length)


def pack_overlap_jobs(jobs, max_unitigs: int, pad_to: int = 1):
    """Pack (path_a, path_b, weights, skip_diagonal) jobs into the padded
    int32 arrays :func:`overlap_screen_scores` consumes. P is padded up to a
    multiple of ``pad_to`` (for sharding); padded rows have k=0 and always
    screen negative. A/B padding is 0, which only ever "matches" at
    columns/rows the kernel masks out (path values are nonzero signed ints).
    Returns (arrays dict, P_real) or None when there is nothing to run."""
    P = len(jobs)
    prepared = [_overlap_windows(pa, pb, w, max_unitigs)
                for pa, pb, w, _ in jobs]
    K = max((k for _, k, *_ in prepared), default=0)
    if P == 0 or K == 0:
        return None
    Pp = -(-P // pad_to) * pad_to
    A = np.zeros((Pp, K), np.int64)
    B = np.zeros((Pp, K), np.int64)
    WA = np.zeros((Pp, K), np.int64)
    WC = np.zeros((Pp, K), np.int64)
    k_arr = np.zeros(Pp, np.int64)
    n_arr = np.zeros(Pp, np.int64)
    skip_arr = np.zeros(Pp, bool)
    for p, ((_, _, _, skip), (n, k, a, b, wa, wcol)) in enumerate(
            zip(jobs, prepared)):
        A[p, :k] = a
        B[p, :k] = b
        WA[p, :k] = wa
        WC[p, :k] = wcol
        k_arr[p] = k
        n_arr[p] = n
        skip_arr[p] = skip
    if np.abs(A).max(initial=0) >= 2**31 or np.abs(B).max(initial=0) >= 2**31:
        raise ValueError("path values exceed int32 range")
    Wcum2 = np.zeros((Pp, K + 1), np.int64)
    np.cumsum(2 * WC, axis=1, out=Wcum2[:, 1:])
    valid_col = np.arange(1, K + 1)[None, :] <= k_arr[:, None]
    return {
        "A": A.astype(np.int32), "B": B.astype(np.int32),
        "WA": WA.astype(np.int32), "WC": WC.astype(np.int32),
        "Wc2": Wcum2.astype(np.int32), "k": k_arr.astype(np.int32),
        "jd_off": (n_arr - k_arr).astype(np.int32), "skip": skip_arr,
        "vcol": valid_col,
    }, P


def _overlap_screen_scan(arrs, emit_traceback: bool):
    """Shared lax.scan body for the batched overlap DP. With
    ``emit_traceback`` False returns the doubled best right-edge score per
    job ([P] int32); with True additionally stacks, per DP row i=1..K, the
    right-edge score ([K, P] int32) and the packed up_ge direction bits
    ([K, P, W] uint32, bit j-1 of row i = S[i-1][j] >= S[i][j-1]) — enough
    for the host to run the traceback without re-running the DP
    (reference trim.rs:426-461)."""
    import jax
    import jax.numpy as jnp

    A32, Bd = arrs["A"], arrs["B"]
    WAd, WCd, Wc2 = arrs["WA"], arrs["WC"], arrs["Wc2"]
    k_j, jd_off, skip_j, vcol = arrs["k"], arrs["jd_off"], arrs["skip"], arrs["vcol"]
    P, K = A32.shape
    W = (K + 31) // 32          # packed words per row (bits j = 1..K)

    def seg_cummax(X, boundary):
        """Segmented running max along axis 1: positions where boundary is
        True start a new segment."""
        def op(l, r):
            lb, lv = l
            rb, rv = r
            return lb | rb, jnp.where(rb, rv, jnp.maximum(lv, rv))
        _, out = jax.lax.associative_scan(op, (boundary, X), axis=1)
        return out

    idx = jnp.arange(K + 1)[None, :]             # X index = column number
    shift = jnp.arange(32, dtype=jnp.uint32)[None, None, :]

    def step(carry, i):
        prev, best = carry
        gi = jnp.minimum(i - 1, K - 1)
        active = i <= k_j
        wi = WAd[:, gi][:, None]
        a_col = A32[:, gi][:, None]
        match2 = jnp.where((a_col == Bd) & vcol, 2 * wi, -(wi + WCd))
        base = jnp.maximum(prev[:, :-1] + match2, prev[:, 1:] - 2 * wi)
        base = jnp.where(vcol, base, _NEG_BIG)
        X = jnp.concatenate(
            [jnp.zeros((P, 1), jnp.int32), base + Wc2[:, 1:]], axis=1)
        jd = jnp.where(skip_j, i - jd_off, 0)[:, None]       # 0 = no reset
        in_range = (jd >= 1) & (jd <= k_j[:, None])          # [P, 1]
        boundary = in_range & ((idx == jd) | (idx == jd + 1))
        X = jnp.where(boundary & (idx == jd), _NEG_BIG, X)
        run = seg_cummax(X, boundary)
        row = run - Wc2
        row = jnp.where(in_range & (idx == jd), _NEG_BIG, row)
        row = row.at[:, 0].set(0)
        row = jnp.maximum(row, _NEG_BIG)
        row = jnp.where(active[:, None], row, prev)
        edge = jnp.take_along_axis(row, k_j[:, None].astype(jnp.int32),
                                   axis=1)[:, 0]
        best = jnp.maximum(best, jnp.where(active, edge, _NEG_BIG))
        if not emit_traceback:
            return (row, best), None
        # up_ge bit for column j (1..K): prev row's cell j vs this row's
        # cell j-1; clamping keeps "effectively -inf" cells equal on both
        # sides, so the comparison matches the f64 DP whenever true scores
        # stay above the sentinel (guarded by traceback_in_domain)
        ge = prev[:, 1:] >= row[:, :-1]                       # [P, K]
        ge = jnp.pad(ge, ((0, 0), (0, W * 32 - K)))
        packed = (ge.reshape(P, W, 32).astype(jnp.uint32) << shift).sum(
            axis=-1, dtype=jnp.uint32)
        return (row, best), (edge, packed)

    # initial carry derived from the inputs (k_j * 0) so that under
    # shard_map it carries the same varying-manual-axes type as the body's
    # outputs (a plain zeros() is unvarying and scan rejects the mismatch)
    zero_row = (k_j * 0)[:, None]
    prev0 = jnp.zeros((P, K + 1), jnp.int32) + zero_row   # row 0: all zeros
    best0 = jnp.full(P, _NEG_BIG, jnp.int32) + zero_row[:, 0]
    (_, best), ys = jax.lax.scan(step, (prev0, best0),
                                 jnp.arange(1, K + 1, dtype=jnp.int32))
    if not emit_traceback:
        return best
    edges, bits = ys
    return best, edges, bits


def overlap_screen_scores(arrs):
    """Pure-jnp kernel: packed job arrays -> doubled best right-edge score
    per job ([P] int32). The vmapped form of the single overlap DP — the
    same recurrence, one lax.scan over rows, scores doubled so everything is
    integer and exact in int32; values clamp at a sentinel far below any
    reachable score, which cannot change any comparison against 0. Jittable
    and shard_map-able along axis 0 (jobs are independent)."""
    return _overlap_screen_scan(arrs, emit_traceback=False)


def overlap_screen_traceback(arrs):
    """(best [P], edges [K, P], bits [K, P, W]) — the screen plus packed
    traceback direction bits (see _overlap_screen_scan)."""
    return _overlap_screen_scan(arrs, emit_traceback=True)


def overlap_positive_batch(jobs, max_unitigs: int) -> np.ndarray:
    """Batched exact screen for :func:`overlap_alignment`: for each job
    (path_a, path_b, weights, skip_diagonal), does the overlap DP reach a
    POSITIVE right-edge score?

    Used by `autocycler batch` to screen MANY isolates' trim DPs in one
    device dispatch; jobs screened False provably return [] from
    overlap_alignment, jobs screened True run the full host DP + traceback.
    """
    import jax

    packed = pack_overlap_jobs(jobs, max_unitigs)
    if packed is None:
        return np.zeros(len(jobs), bool)
    arrs, P = packed
    from ..utils.timing import device_dispatch
    with device_dispatch("trim overlap screen"):
        best = np.asarray(jax.jit(overlap_screen_scores)(arrs))
    return best[:P] > 0


def traceback_in_domain(job, max_unitigs: int) -> bool:
    """Whether the int32 device DP's sentinel clamp is provably inert for
    this job's TRACEBACK (not just the sign of the best score): every true
    doubled score is bounded below by -2·(weight(A window) + weight(B
    window)), so as long as that bound stays above the sentinel, clamped
    cells are exactly the -inf cells of the f64 DP and every up_ge
    comparison matches. Jobs beyond the bound (≈ 67 Mbp of combined window
    weight) fall back to the host DP."""
    path_a, path_b, weights, _ = job
    n, k, _, _, wa, wcol = _overlap_windows(path_a, path_b, weights, max_unitigs)
    return 2 * int(wa.sum() + wcol.sum()) < -_NEG_BIG


def decode_overlap_alignment(path_a, path_b, weights: Weights,
                             min_identity: float, max_unitigs: int,
                             edges_col: np.ndarray, bits_col: np.ndarray
                             ) -> List[AlignmentPiece]:
    """Host-side decode of the device DP's packed traceback for ONE job:
    pick the best right-edge row (smallest row wins ties, like
    overlap_alignment), walk the packed up_ge bits, apply the top-edge and
    identity gates. Returns the same pieces overlap_alignment would.

    edges_col: [>=k] doubled right-edge scores for rows 1..k;
    bits_col: [>=k, W] packed up_ge words for rows 1..k (bit j-1 = up_ge at
    column j)."""
    n = len(path_a)
    k = min(max_unitigs, n)
    if k == 0:
        return []
    pa = np.asarray(path_a, dtype=np.int64)
    pb = np.asarray(path_b, dtype=np.int64)
    max_i = int(np.argmax(edges_col[:k])) + 1
    if not int(edges_col[max_i - 1]) > 0:
        return []

    def up_ge(i: int, j: int) -> bool:
        return bool((int(bits_col[i - 1, (j - 1) >> 5]) >> ((j - 1) & 31)) & 1)

    return _traceback_and_identity(pa, pb, n, k, max_i, up_ge, weights,
                                   min_identity)


# cap on one traceback dispatch's packed-bits footprint (K * P * ceil(K/32)
# uint32 words ≈ P·K²/8 bytes): K=5000 jobs carry ~3.1 MB of bits each, so
# dispatches are chunked — and grouped by similar K so short jobs never pay
# a long job's padded K
_TRACEBACK_BITS_BUDGET = 256 << 20


def overlap_tracebacks_batch(jobs, max_unitigs: int, min_identity: float):
    """Device DP + packed traceback for many jobs: returns a list whose
    entry per job is the decoded alignment pieces (possibly []), or None
    when the job is outside the int32 traceback domain (caller runs the
    host DP). Used by `autocycler batch` so screened-positive trim DPs
    never re-run on the host (VERDICT r3 item 3; reference trim.rs:366-479
    scope). Jobs are grouped by size class and chunked so one dispatch's
    bits tensor stays under ~256 MB."""
    import jax

    if not jobs:
        return []
    in_domain = [traceback_in_domain(job, max_unitigs) for job in jobs]
    results: List[Optional[List[AlignmentPiece]]] = [None] * len(jobs)
    run_idx = [i for i, ok in enumerate(in_domain) if ok]
    if not run_idx:
        return results
    # group by power-of-two K class (padded K within a chunk ≤ 2× any
    # member's k), then split each class by the bits budget
    k_of = {i: min(max_unitigs, len(jobs[i][0])) for i in run_idx}
    run_idx.sort(key=lambda i: k_of[i])
    chunks: List[List[int]] = []
    cur: List[int] = []
    cur_class = -1
    for i in run_idx:
        k = max(k_of[i], 1)
        cls = (k - 1).bit_length()
        kmax = 1 << cls
        per_job = kmax * ((kmax + 31) // 32) * 4
        if not cur or cls != cur_class or \
                (len(cur) + 1) * per_job > _TRACEBACK_BITS_BUDGET:
            cur = [i]
            chunks.append(cur)
            cur_class = cls
        else:
            cur.append(i)

    from ..utils.timing import device_dispatch
    for chunk in chunks:
        packed = pack_overlap_jobs([jobs[i] for i in chunk], max_unitigs)
        if packed is None:
            for i in chunk:
                results[i] = []
            continue
        arrs, _ = packed
        with device_dispatch("trim traceback DP"):
            _, edges, bits = jax.jit(overlap_screen_traceback)(arrs)
            edges = np.asarray(edges)
            bits = np.asarray(bits)
        for p, i in enumerate(chunk):
            path_a, path_b, weights, _ = jobs[i]
            results[i] = decode_overlap_alignment(
                path_a, path_b, weights, min_identity, max_unitigs,
                edges[:, p], bits[:, p, :])
    return results


def find_midpoint(alignment: List[AlignmentPiece], weights: Weights) -> int:
    """Index of the match column whose cumulative weight is closest to the
    alignment's weighted midpoint (reference trim.rs:482-507)."""
    total = 0
    for p in alignment:
        if p.a_unitig != GAP:
            total += weights[abs(p.a_unitig)]
        if p.b_unitig != GAP:
            total += weights[abs(p.b_unitig)]
    cumulative = 0
    best_index, best_closeness = 0, 1.0
    for i, p in enumerate(alignment):
        if p.a_unitig != GAP:
            cumulative += weights[abs(p.a_unitig)]
        if p.b_unitig != GAP:
            cumulative += weights[abs(p.b_unitig)]
        closeness = abs(0.5 - cumulative / total)
        if p.a_unitig == p.b_unitig and closeness < best_closeness:
            best_index, best_closeness = i, closeness
    return best_index


def global_alignment_distance_batch(pairs, weights: Weights,
                                    use_jax: bool = False) -> np.ndarray:
    """Many global-alignment distances in one padded, vectorised DP — the
    batched form of :func:`global_alignment_distance` (identical integers
    per pair). Used by resolve's medoid selection, which otherwise issues
    O(paths^2) tiny Python-level DP calls per bridge (resolve.rs:387-418).

    use_jax=True runs the identical recurrence as a lax.scan on the default
    device (measured slower than the host at bridge scale through the
    current TPU tunnel — docs/architecture.md "resolve medoid DP" table —
    so the host path is the default)."""
    P = len(pairs)
    if P == 0:
        return np.zeros(0, np.int64)
    n_len = np.array([len(a) for a, _ in pairs], dtype=np.int64)
    m_len = np.array([len(b) for _, b in pairs], dtype=np.int64)
    n_max = max(int(n_len.max()), 1)
    m_max = max(int(m_len.max()), 1)
    A = np.zeros((P, n_max), np.int64)
    B = np.zeros((P, m_max), np.int64)
    WA = np.zeros((P, n_max), np.int64)
    WB = np.zeros((P, m_max), np.int64)
    for p, (a, b) in enumerate(pairs):
        A[p, :len(a)] = a
        B[p, :len(b)] = b
        WA[p, :len(a)] = [weights[abs(int(u))] for u in a]
        WB[p, :len(b)] = [weights[abs(int(u))] for u in b]

    Wb = np.zeros((P, m_max + 1), np.int64)
    np.cumsum(WB, axis=1, out=Wb[:, 1:])

    if use_jax:
        import jax
        import jax.numpy as jnp

        def row_step(prev, xs):
            a_col, wi, active = xs
            mismatch = jnp.where(a_col[:, None] == Bd, 0,
                                 jnp.maximum(wi[:, None], WBd))
            base = jnp.minimum(prev[:, :-1] + mismatch,
                               prev[:, 1:] + wi[:, None])
            left = prev[:, 0] + wi
            run = jax.lax.associative_scan(
                jnp.minimum,
                jnp.concatenate([left[:, None], base - Wbd[:, 1:]], axis=1),
                axis=1)
            row = jnp.concatenate([left[:, None], run[:, 1:] + Wbd[:, 1:]],
                                  axis=1)
            return jnp.where(active[:, None], row, prev), None

        Bd, WBd, Wbd = jnp.asarray(B), jnp.asarray(WB), jnp.asarray(Wb)
        active_rows = (np.arange(n_max)[:, None] < n_len[None, :])
        final, _ = jax.lax.scan(
            row_step, jnp.asarray(Wb),
            (jnp.asarray(A.T), jnp.asarray(WA.T), jnp.asarray(active_rows)))
        prev = np.asarray(final)
        return prev[np.arange(P), m_len]

    prev = Wb.copy()
    for i in range(n_max):
        active = i < n_len
        wi = WA[:, i]
        mismatch = np.where(A[:, i:i + 1] == B, 0,
                            np.maximum(wi[:, None], WB))
        base = np.minimum(prev[:, :-1] + mismatch, prev[:, 1:] + wi[:, None])
        left = prev[:, 0] + wi
        run = np.minimum.accumulate(
            np.concatenate([left[:, None], base - Wb[:, 1:]], axis=1), axis=1)
        row = np.concatenate([left[:, None], run[:, 1:] + Wb[:, 1:]], axis=1)
        prev = np.where(active[:, None], row, prev)
    return prev[np.arange(P), m_len]


def global_alignment_distance(path_a: Sequence[int], path_b: Sequence[int],
                              weights: Weights) -> int:
    """Weighted global alignment (Needleman-Wunsch) distance between two
    paths (reference resolve.rs:387-418): match 0, mismatch max(w_a, w_b)
    (the longer tig), indel w; returns the minimum total distance. Row-
    vectorised with the min-plus prefix-scan form of the insert recurrence
    (integer arithmetic, exact)."""
    a = np.asarray(path_a, dtype=np.int64)
    b = np.asarray(path_b, dtype=np.int64)
    n, m = len(a), len(b)
    if isinstance(weights, np.ndarray):
        wa = weights[np.abs(a)]
        wb = weights[np.abs(b)]
    else:
        wa = np.array([weights[abs(int(u))] for u in a], dtype=np.int64) if n else np.zeros(0, np.int64)
        wb = np.array([weights[abs(int(u))] for u in b], dtype=np.int64) if m else np.zeros(0, np.int64)
    Wb = np.concatenate([[0], np.cumsum(wb)])      # top edge: gaps in A
    prev = Wb.copy()                               # row 0
    for i in range(n):
        wi = wa[i]
        mismatch = np.where(a[i] == b, 0, np.maximum(wi, wb))
        base = np.minimum(prev[:m] + mismatch, prev[1:] + wi)
        left_edge = prev[0] + wi
        # S[j] = min(base[j], S[j-1] + wb[j])  ->  min-plus prefix scan
        run = np.minimum.accumulate(np.concatenate([[left_edge], base - Wb[1:]]))
        row = np.empty(m + 1, dtype=np.int64)
        row[0] = left_edge
        row[1:] = run[1:] + Wb[1:]
        prev = row
    return int(prev[m])
