"""Unitig chain construction over the k-mer index.

Replaces the reference's sequential greedy walk (unitig_graph.rs:176-226:
per-k-mer graph walk with hash probes and a `seen` set) with a vectorised,
order-independent formulation:

An edge A->B is *unitig-internal* iff
    out_count(A) == 1  and  not first_pos(rev(A))      (A may extend right)
    and in_count(B) == 1  and  not first_pos(B)        (B may be entered)
which is exactly the conjunction of break conditions in the reference's
extension loops (unitig_graph.rs:192-205 forward, :210-223 backward) and is
strand-symmetric: internal(A->B) <=> internal(rev B->rev A). Chains under
this relation are therefore well-defined without any walk order, and are
computed by pointer-doubling (O(U log U) gathers, device- or numpy-side).

The reference's remaining walk behaviours are reproduced exactly:
- chains come in reverse-complement pairs; the one containing the globally
  smallest k-mer (= smallest id, ids are lexicographic ranks) is emitted,
  matching the sorted iteration order of the walk (kmer_graph.rs:168-173);
- cycles are rotated to start at their smallest k-mer (the walk starts
  there and goes around until it meets the start's `seen` mark);
- self-mirror chains (a chain that is its own reverse complement) split at
  the centre, keeping the half containing the smallest k-mer — the effect
  of the walk's `seen` check hitting the mirror half;
- self-mirror cycles fall back to a literal simulation of the walk.
"""

from __future__ import annotations

import functools as _functools

from dataclasses import dataclass
from typing import List

import numpy as np

from .kmers import KmerIndex


@dataclass
class Chains:
    """Emitted unitig chains: ordered k-mer ids, concatenated."""
    members: np.ndarray    # (T,) kmer ids in chain order, all chains concatenated
    chain_off: np.ndarray  # (C+1,) boundaries into members
    is_cycle: np.ndarray   # (C,) bool

    @property
    def count(self) -> int:
        return len(self.chain_off) - 1

    def chain(self, c: int) -> np.ndarray:
        return self.members[self.chain_off[c]:self.chain_off[c + 1]]


def internal_edges(index: KmerIndex, workers: int = 1) -> np.ndarray:
    """next_int[g] = unitig-internal successor of k-mer g, or -1. The
    U-sized gather chunks over the shared pool above one worker
    (bit-identical: chunks write disjoint ranges)."""
    from ..utils.pool import parallel_gather
    U = index.num_kmers
    succ = index.succ
    ok = (index.out_count == 1) & (succ >= 0)
    ok &= ~parallel_gather(index.first_pos, index.rev_kid, workers)
    src = np.flatnonzero(ok)
    tgt = succ[src]
    keep = (index.in_count[tgt] == 1) & ~index.first_pos[tgt]
    result = np.full(U, -1, np.int64)
    result[src[keep]] = tgt[keep]
    return result


def _pointer_double_heads(prev_int: np.ndarray):
    """For a forest of in-trees that are simple paths, find each node's head
    (the node with no predecessor) and its distance from it."""
    U = len(prev_int)
    node = np.arange(U, dtype=np.int64)
    P = np.where(prev_int < 0, node, prev_int)
    R = (prev_int >= 0).astype(np.int64)
    steps = max(1, int(np.ceil(np.log2(max(U, 2)))) + 1)
    for _ in range(steps):
        R = R + R[P]
        P = P[P]
    return P, R


def _chains_numpy(next_int: np.ndarray):
    """Pointer-doubling fallback: (members, chain_off, chain_is_cycle)."""
    U = len(next_int)
    prev_int = np.full(U, -1, np.int64)
    has_next = next_int >= 0
    prev_int[next_int[has_next]] = np.flatnonzero(has_next)

    # ---- one pointer-doubling pass finds heads AND detects cycles ----
    # Path nodes converge to their head (prev < 0); a node still pointing at
    # a predecessor-bearing node after full doubling lies on a cycle.
    head, rank = _pointer_double_heads(prev_int)
    in_cycle = prev_int[head] >= 0

    cycle_nodes = np.flatnonzero(in_cycle)
    prev_broken = prev_int
    if len(cycle_nodes):
        # representatives (= smallest member id, where the reference's
        # lexicographic walk starts): min-propagate over the cycle subset
        # only, with indices remapped to a compact array
        compact = np.full(U, -1, np.int64)
        compact[cycle_nodes] = np.arange(len(cycle_nodes))
        cprev = compact[prev_int[cycle_nodes]]
        cmin = cycle_nodes.copy()
        steps = max(1, int(np.ceil(np.log2(max(len(cycle_nodes), 2)))) + 1)
        P = cprev
        for _ in range(steps):
            new = np.minimum(cmin, cmin[P])
            if np.array_equal(new, cmin):
                break
            cmin = new
            P = P[P]
        # cmin now holds, for each cycle node, the min over enough
        # predecessors to cover the whole cycle
        cycle_reps = np.unique(cmin)
        prev_broken = prev_int.copy()
        tails = prev_int[cycle_reps]          # cycle predecessor of each rep
        prev_broken[cycle_reps] = -1
        next_int = next_int.copy()
        next_int[tails] = -1
        head, rank = _pointer_double_heads(prev_broken)

    # ---- order members by (chain, rank) with O(U) scatters ----
    is_head = prev_broken < 0
    cid_of_head = np.cumsum(is_head) - 1      # dense chain id per head node
    C = int(is_head.sum())
    chain_id = cid_of_head[head]              # chain index of every node
    sizes = np.bincount(chain_id, minlength=C)
    chain_off = np.zeros(C + 1, np.int64)
    chain_off[1:] = np.cumsum(sizes)
    members = np.empty(U, np.int64)
    members[chain_off[chain_id] + rank] = np.arange(U)
    chain_is_cycle = in_cycle[members[chain_off[:-1]]]
    return members, chain_off, chain_is_cycle


@_functools.lru_cache(maxsize=None)
def _chains_fn(bucket: int):
    """One compiled (node-bucket) executable for chain-following: the
    predecessor scatter, head/rank pointer doubling, masked cycle
    min-propagation, cycle breaking at representatives and the re-doubling
    all fuse into ONE jitted dispatch (static doubling depth
    ceil(log2(bucket)) + 1, so the executable compiles once per bucket
    class). Valid because ``next_int`` is functional AND injective (every
    internal edge has in_count == 1), so the graph is exactly disjoint
    simple paths and cycles. Pad nodes carry next = -1 and resolve to
    singleton non-cycle paths, sliced off by the host."""
    import jax
    import jax.numpy as jnp

    steps = max(1, int(np.ceil(np.log2(max(bucket, 2)))) + 1)

    def run(next_padded, n_real):
        node = jnp.arange(bucket, dtype=jnp.int32)
        real = node < n_real
        nxt = jnp.where(real, next_padded, jnp.int32(-1))
        has_next = nxt >= 0
        # prev scatter (injective: no duplicate real targets); invalid
        # targets clamp into the extra slot `bucket`
        tgt = jnp.where(has_next, nxt, bucket)
        prev = jnp.full(bucket + 1, -1, jnp.int32) \
            .at[tgt].max(jnp.where(has_next, node, jnp.int32(-1)))[:bucket]

        def double_heads(p):
            P = jnp.where(p < 0, node, p)
            R = (p >= 0).astype(jnp.int32)
            # fori_loop keeps the HLO graph O(1) in the doubling depth —
            # unrolling `steps` gather stages made XLA CPU compiles crawl
            P, R = jax.lax.fori_loop(
                0, steps, lambda _, s: (s[0][s[0]], s[1] + s[1][s[0]]),
                (P, R))
            return P, R

        head, rank = double_heads(prev)
        in_cycle = prev[head] >= 0

        # cycle representatives (= smallest member id): masked full-array
        # min-propagation — non-cycle nodes carry an out-of-band sentinel
        # and self-loop pointers, so they never contaminate a cycle's min
        cmin = jnp.where(in_cycle, node, jnp.int32(bucket))
        P = jnp.where(in_cycle, prev, node)
        cmin, P = jax.lax.fori_loop(
            0, steps,
            lambda _, s: (jnp.minimum(s[0], s[0][s[1]]), s[1][s[1]]),
            (cmin, P))
        rep = in_cycle & (cmin == node)
        # break each cycle at its representative: dropping the rep's
        # predecessor is sufficient — the re-doubling only consults prev
        # (exactly as _chains_numpy's head/rank pass does)
        prev2 = jnp.where(rep, jnp.int32(-1), prev)
        head2, rank2 = double_heads(prev2)
        return head2, rank2, in_cycle

    return jax.jit(run)


def _chains_device(next_int: np.ndarray):
    """Device chain-following: one upload of ``next_int``, one fused
    dispatch (:func:`_chains_fn`), one download of (head, rank, in_cycle);
    the O(U) ordering scatters finish on host exactly as
    :func:`_chains_numpy` orders its members — bit-identical by
    construction (chain ids are assigned in head-node order either way)."""
    import jax.numpy as jnp

    from ..utils.timing import device_dispatch
    from .kmers import _RADIX_DEVICE_ROW_FLOOR, _bucket_size

    U = len(next_int)
    b = _bucket_size(max(U, 1), floor=_RADIX_DEVICE_ROW_FLOOR)
    pad_next = np.full(b, -1, np.int32)
    pad_next[:U] = next_int
    with device_dispatch("chain pointer doubling",
                         bytes_moved=float(4 * b * (2 * np.ceil(np.log2(max(b, 2))) + 4))):
        head_d, rank_d, cyc_d = _chains_fn(b)(jnp.asarray(pad_next),
                                              jnp.int32(U))
        head = np.asarray(head_d)[:U].astype(np.int64)
        rank = np.asarray(rank_d)[:U].astype(np.int64)
        in_cycle = np.asarray(cyc_d)[:U]

    is_head = head == np.arange(U)
    cid_of_head = np.cumsum(is_head) - 1
    C = int(is_head.sum())
    chain_id = cid_of_head[head]
    sizes = np.bincount(chain_id, minlength=C)
    chain_off = np.zeros(C + 1, np.int64)
    chain_off[1:] = np.cumsum(sizes)
    members = np.empty(U, np.int64)
    members[chain_off[chain_id] + rank] = np.arange(U)
    chain_is_cycle = in_cycle[members[chain_off[:-1]]] if C \
        else np.zeros(0, bool)
    return members, chain_off, chain_is_cycle


def build_chains(index: KmerIndex, threads=None,
                 use_jax=None) -> Chains:
    U = index.num_kmers
    if U == 0:
        return Chains(np.zeros(0, np.int64), np.zeros(1, np.int64), np.zeros(0, bool))

    from .kmers import (_effective_workers, _resolve_threads,
                        _resolve_use_jax)
    workers = _effective_workers(_resolve_threads(threads))
    use_jax_r = _resolve_use_jax(use_jax)
    from ..utils.timing import substage
    with substage("chains"):
        next_int = internal_edges(index, workers)
        members = None
        if use_jax_r:
            # an explicitly requested device mode takes precedence over the
            # native walk so the compress hot path stays device-resident
            try:
                members, chain_off, chain_is_cycle = \
                    _chains_device(next_int)
            except Exception as e:  # noqa: BLE001 — host fallback guarantee
                import sys

                from ..utils.timing import record_device_failure
                what = (f"device chain following failed "
                        f"({type(e).__name__}: {e})")
                record_device_failure(what, exc=e)
                print(f"autocycler: {what}; falling back to host chain "
                      "walk", file=sys.stderr)
                members = None
        if members is None:
            from .. import native
            walked = native.chain_walk(next_int) if native.available() \
                else None
            if walked is not None:
                members, chain_off, chain_is_cycle = walked
            else:
                members, chain_off, chain_is_cycle = _chains_numpy(next_int)

    C = len(chain_off) - 1
    sizes = np.diff(chain_off)
    # chain index of every node (members lists each node exactly once)
    node_chain = np.empty(U, np.int64)
    node_chain[members] = np.repeat(np.arange(C, dtype=np.int64), sizes)
    chain_head = members[chain_off[:-1]]
    chain_tail = members[chain_off[1:] - 1]

    # per-chain minima, own and mirror
    min_own = np.minimum.reduceat(members, chain_off[:-1]) if C else \
        np.zeros(0, np.int64)
    min_mirror = np.minimum.reduceat(index.rev_kid[members], chain_off[:-1]) \
        if C else np.zeros(0, np.int64)
    mirror_chain = node_chain[index.rev_kid[chain_head]]
    self_mirror = mirror_chain == np.arange(C)

    # Emit chains vectorised: of each mirror pair keep the chain holding the
    # smaller minimum (ties == self-mirror, handled separately below).
    normal_keep = ~self_mirror & (min_own <= min_mirror)
    keep_node = np.repeat(normal_keep, sizes)
    flat = members[keep_node]
    kept_sizes = sizes[normal_keep]
    off = np.concatenate([[0], np.cumsum(kept_sizes)]).astype(np.int64)
    out_is_cycle = list(chain_is_cycle[normal_keep])

    # self-mirror chains are rare; the literal per-chain handling only runs
    # for them (appended after the vectorised bulk — chain order is
    # irrelevant, renumbering happens downstream)
    extra_members: List[np.ndarray] = []
    for c in np.flatnonzero(self_mirror):
        mem = members[chain_off[c]:chain_off[c + 1]]
        if chain_is_cycle[c]:
            extra_members.append(_simulate_walk_cycle(index, next_int, mem,
                                                      int(min_own[c])))
        else:
            half = len(mem) // 2
            pos_of_min = int(np.argmin(mem))
            extra_members.append(mem[:half] if pos_of_min < half else mem[half:])
        out_is_cycle.append(False)  # walk results are never full cycles
    if extra_members:
        flat = np.concatenate([flat] + extra_members)
        off = np.concatenate([off, off[-1] + np.cumsum([len(m) for m in extra_members])])
    return Chains(flat, off.astype(np.int64), np.array(out_is_cycle, dtype=bool))


def _simulate_walk_cycle(index: KmerIndex, next_int: np.ndarray,
                         cycle_members: np.ndarray, start: int) -> np.ndarray:
    """Literal reproduction of the reference walk for a self-mirror cycle
    (unitig_graph.rs:188-223): extend right then left, stopping when the
    next k-mer (or its reverse complement) was already taken."""
    seen = {start, int(index.rev_kid[start])}
    chain = [start]
    cur = start
    while True:
        nxt = int(next_int[cur])
        if nxt < 0 or nxt in seen:
            break
        chain.append(nxt)
        seen.add(nxt)
        seen.add(int(index.rev_kid[nxt]))
        cur = nxt
    prev_map = {int(next_int[m]): int(m) for m in cycle_members if next_int[m] >= 0}
    cur = start
    while True:
        prv = prev_map.get(cur, -1)
        if prv < 0 or prv in seen:
            break
        chain.insert(0, prv)
        seen.add(prv)
        seen.add(int(index.rev_kid[prv]))
        cur = prv
    return np.array(chain, dtype=np.int64)
