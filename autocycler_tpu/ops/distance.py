"""All-vs-all contig distance as one device matmul.

Parity target: reference cluster.rs:132-157 — the asymmetric distance
``1 - |A∩B|_len / |A|_len`` over the sets of unitig ids in each contig's
graph path, weighted by unitig length.

TPU formulation: build the binary membership matrix M (contigs × unitigs)
and the unitig length vector w. Then

    inter = (M * w) @ M.T          (one MXU matmul)
    dist[a, b] = 1 - inter[a, b] / inter[a, a]

replacing the reference's N² hash-set intersections. Arithmetic stays in
integers (int32 accumulation is exact for bacterial-genome scales) so the
result is bit-identical to the set-based computation.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

_JAX_THRESHOLD = 512 * 4096  # M elements; above this the matmul wins on any backend
_TPU_THRESHOLD = 1 << 16     # with a real TPU attached, use it from 64k elements:
#                              dispatch+transfer ≈ 0.2-0.5 s through the tunnel,
#                              so every realistic cluster run puts the
#                              intersection contraction on the MXU while
#                              tiny/test inputs skip the round trip


import threading as _threading
import time as _time

_PROBE_LOCK = _threading.Lock()
# last REAL probe outcome (short-circuit answers are never cached):
# {"attached", "seconds", "reason", "at" (monotonic), "probes"}
_probe_state: dict = {"probes": 0}

# background (asynchronous) probe bookkeeping — the probe future that lets
# attach cost overlap the host load/parse/encode stage instead of
# serializing in front of the first device dispatch:
# {"started", "resolved", "event", "started_at" (monotonic), "deadline",
#  "resolve_s", "wait_s", "pending_consults", "attempts"}
_bg_state: dict = {}

# the BACKGROUND probe's default deadline is deliberately lower than the
# legacy synchronous 60 s: it runs concurrently with host work, so a
# shorter deadline only bounds how late a slow-attaching device can still
# join the run — it never adds wall time. AUTOCYCLER_PROBE_DEADLINE_S /
# AUTOCYCLER_DEVICE_PROBE_TIMEOUT still win when set.
BACKGROUND_PROBE_DEADLINE_S = 20.0

# on-disk negative-probe cache: one wedged-transport probe costs a full
# deadline; persisting the failure (short TTL) under the run's autocycler
# dir stops every SUBSEQUENT process (batch isolates, CLI stage-per-process
# runs, bench reruns) from re-paying that stall. Only negative kinds that
# imply a wedged/broken transport ("timeout"/"error") persist — success is
# always re-verified per process (it is cheap when healthy).
_probe_cache_dir = None
_PROBE_CACHE_FILE = "device_probe.json"


def set_probe_cache_dir(path) -> None:
    """Enable the on-disk negative probe cache under ``path`` (compress and
    batch point it at ``<autocycler_dir>/.cache``; None disables). The
    probe sentinel's ``probe_log.jsonl`` follows the same directory as a
    fallback, so a run's probe history lands next to its negative cache."""
    global _probe_cache_dir
    with _PROBE_LOCK:
        _probe_cache_dir = None if path is None else str(path)
    try:
        from ..obs import sentinel
        sentinel.set_probe_log_dir(path, fallback=True)
    except Exception:  # noqa: BLE001 — forensics must not break the gate
        pass


def notify_probe_recovered() -> None:
    """Sentinel hand-back on a ``false -> true`` probe transition: drop the
    in-memory failed-probe cache and the persisted negative, so the next
    :func:`_tpu_attached` call re-probes immediately instead of waiting out
    a TTL/backoff window that no longer reflects reality."""
    with _PROBE_LOCK:
        cache_dir = _probe_cache_dir
        if not _probe_state.get("attached"):
            _probe_state["cached"] = False
            _probe_state["fails"] = 0
    if cache_dir:
        import os
        try:
            os.unlink(os.path.join(cache_dir, _PROBE_CACHE_FILE))
        except OSError:
            pass


def _probe_neg_ttl() -> float:
    from ..utils.knobs import knob_float
    return float(knob_float("AUTOCYCLER_PROBE_NEG_TTL_S"))


def _disk_probe_load():
    """A still-fresh persisted negative probe ({kind, reason, at}), or
    None."""
    with _PROBE_LOCK:
        cache_dir = _probe_cache_dir
    if not cache_dir:
        return None
    import json
    import os
    ttl = _probe_neg_ttl()
    if ttl <= 0:
        return None
    try:
        with open(os.path.join(cache_dir, _PROBE_CACHE_FILE)) as f:
            entry = json.load(f)
        if entry.get("kind") not in ("timeout", "error"):
            return None
        if _time.time() - float(entry.get("at", 0)) >= ttl:
            return None
        return entry
    except Exception:  # noqa: BLE001 — missing/corrupt cache == no cache
        return None


def _disk_probe_store(attached: bool, reason: str, kind: str) -> None:
    with _PROBE_LOCK:
        cache_dir = _probe_cache_dir
    if not cache_dir:
        return
    import json
    import os
    path = os.path.join(cache_dir, _PROBE_CACHE_FILE)
    try:
        if attached or kind not in ("timeout", "error"):
            # a healthy (or merely absent) device clears any stale negative
            if os.path.exists(path):
                os.unlink(path)
            return
        os.makedirs(cache_dir, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"kind": kind, "reason": reason, "at": _time.time()}, f)
        os.replace(tmp, path)
    except OSError:
        pass


def _record_probe(attached: bool, seconds: float, reason: str,
                  cache: bool, kind: str, detail: dict = None) -> None:
    with _PROBE_LOCK:
        fails = _probe_state.get("fails", 0)
        if cache:
            fails = 0 if attached else fails + 1
        _probe_state.update(attached=attached, seconds=round(seconds, 3),
                            reason=reason, cached=cache, fails=fails,
                            kind=kind, at=_time.monotonic(),
                            detail=detail or {},
                            probes=_probe_state.get("probes", 0) + (1 if cache else 0))


def device_probe_report() -> dict:
    """The last probe outcome, for artifacts: {"attached", "seconds",
    "reason", "kind", "probes"} — ``attached`` is None if nothing has
    resolved yet. ``kind`` is structured for callers that must distinguish
    WHY a probe answered False: "pinned" (JAX_PLATFORMS names a non-TPU
    backend — jax untouched but safe to initialise), "no-tpu" (backend
    initialised fine, just not a TPU), "ok", "timeout" (wedged transport —
    ANY jax backend init may hang), "error", "disabled". A framework whose
    device defaults hinge on this probe must surface the outcome, not bury
    it in stderr (VERDICT r4 item 1a)."""
    with _PROBE_LOCK:
        return {"attached": _probe_state.get("attached"),
                "seconds": _probe_state.get("seconds"),
                "reason": _probe_state.get("reason"),
                "kind": _probe_state.get("kind"),
                "probes": _probe_state.get("probes", 0),
                "detail": dict(_probe_state.get("detail") or {})}


_WARNED_UNSAFE: set = set()


def warn_backend_unsafe_once(context: str) -> None:
    """One stderr warning per (process, context) when a device feature
    degrades to a host path because jax backend init is not known-safe —
    shared by every call site so the flag, message shape and probe reason
    can't drift between them. The event also lands in the unified backend
    registry (utils.resilience.degrade_events) so a run's degradations are
    inspectable in one place."""
    with _PROBE_LOCK:
        if context in _WARNED_UNSAFE:
            return
        _WARNED_UNSAFE.add(context)
    from ..utils.resilience import record_degrade
    record_degrade(context, "device", "host",
                   "jax backend init is not known-safe "
                   f"({device_probe_report()['reason']})")
    import sys
    print(f"autocycler: {context} requested but jax backend init is not "
          f"known-safe ({device_probe_report()['reason']}); using the host "
          "path", file=sys.stderr)


def jax_backend_safe() -> bool:
    """Whether touching jax (ANY backend init, even interpret-mode Pallas)
    is known not to hang: True when the probe short-circuited on a pinned
    non-TPU platform or a backend actually initialised. A timed-out probe
    means the plugin transport is wedged — on this platform the plugin
    overrides JAX_PLATFORMS, so even 'cpu-only' jax use can block in
    backend init."""
    _tpu_attached()
    with _PROBE_LOCK:
        return _probe_state.get("kind") in ("pinned", "no-tpu", "ok")


def _probe_reset() -> None:
    global _probe_cache_dir
    with _PROBE_LOCK:
        _probe_state.clear()
        _probe_state["probes"] = 0
        _bg_state.clear()
        _probe_cache_dir = None


def _tpu_attached() -> bool:
    """TPU probe gating the device-by-default paths. When JAX_PLATFORMS
    pins a non-TPU backend this answers without importing jax; otherwise
    the probe initialises a backend AND runs one tiny device op (a TPU
    host then reuses the backend for the matmul, a CPU-only host pays the
    init once per process).

    The probe runs in a daemon thread with a deadline
    (AUTOCYCLER_DEVICE_PROBE_TIMEOUT, default 60 s): a remote/tunnelled
    device can wedge in a way that blocks the first device call forever,
    and the product path must degrade to the bit-identical host matmul
    instead of hanging the pipeline. The tiny op is what catches a wedged
    transport — backend init alone can succeed while execution stalls.

    Caching (VERDICT r4 item 1b): success is cached for the process
    lifetime (a healthy initialised backend needs no re-checking — every
    dispatch site has its own fallback), but FAILURE expires after
    AUTOCYCLER_DEVICE_PROBE_TTL seconds (default 120; <= 0 makes failure
    permanent), so one transient tunnel wedge at startup no longer pins a
    long `batch` run to host forever. Consecutive failures back off
    exponentially (TTL, 2*TTL, 4*TTL, ...) so a dead tunnel costs a
    bounded, shrinking share of a long run rather than one probe-deadline
    stall per TTL window. Every outcome is recorded and retrievable via
    :func:`device_probe_report`."""
    import os
    import sys
    platforms = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    if platforms and "tpu" not in platforms and "axon" not in platforms:
        # pinned to a non-TPU backend (tests pin cpu): answer without
        # importing jax. "axon" is the tunnelled-TPU plugin platform and
        # must fall through to the probe.
        _record_probe(False, 0.0,
                      f"JAX_PLATFORMS={platforms!r} pins a non-TPU backend",
                      cache=False, kind="pinned")
        return False
    # AUTOCYCLER_PROBE_DEADLINE_S is the operator-facing deadline knob and
    # takes precedence; AUTOCYCLER_DEVICE_PROBE_TIMEOUT remains as the
    # original spelling. Same semantics either way (<= 0 disables the
    # device path outright).
    from ..utils.knobs import knob_float, knob_raw
    if knob_raw("AUTOCYCLER_PROBE_DEADLINE_S") is not None:
        timeout = float(knob_float("AUTOCYCLER_PROBE_DEADLINE_S",
                                   default=60.0))
    else:
        timeout = float(knob_float("AUTOCYCLER_DEVICE_PROBE_TIMEOUT"))
    if timeout <= 0:       # explicit kill switch: host backends, no probe
        _record_probe(False, 0.0,
                      "AUTOCYCLER_DEVICE_PROBE_TIMEOUT <= 0 disables the "
                      "device path", cache=False, kind="disabled")
        return False

    with _PROBE_LOCK:
        st = dict(_probe_state)
        if st.get("cached"):
            if st["attached"]:
                return True
            ttl = float(knob_float("AUTOCYCLER_DEVICE_PROBE_TTL"))
            # exponential backoff: consecutive failures double the wait
            # before the next re-probe (a dead tunnel would otherwise cost
            # a probe-deadline stall every TTL for the whole run)
            backoff = ttl * (2 ** max(st.get("fails", 1) - 1, 0))
            if ttl <= 0 or _time.monotonic() - st["at"] < backoff:
                return False
            # failure older than the TTL: fall through and probe again (the
            # tunnel may have recovered). A timed-out earlier probe thread
            # may still be blocked inside backend init; the new probe then
            # blocks on the same init lock and times out too — correct
            # behaviour, just delayed by one more deadline.
        if _probe_state.get("probing"):
            # another thread is mid-probe: don't stack a second
            # deadline-long stall (or another daemon thread) on top —
            # answer from the last known state
            return bool(st.get("attached", False))
        _probe_state["probing"] = True

    persisted = _disk_probe_load()
    if persisted is not None:
        # a recent process already paid the deadline against this wedged
        # transport: adopt its negative outcome instead of stalling again
        _record_probe(False, 0.0,
                      f"persisted negative probe: {persisted['reason']}",
                      cache=True, kind=persisted["kind"])
        with _PROBE_LOCK:
            _probe_state["probing"] = False
        return False

    try:
        attached, reason, kind, detail, elapsed = _probe_attempt(timeout)
        _record_probe(attached, elapsed, reason, cache=True, kind=kind,
                      detail=detail)
        _disk_probe_store(attached, reason, kind)
        try:
            from ..obs import sentinel
            sentinel.record_outcome(
                dict(detail or {}, attached=attached, kind=kind,
                     reason=reason, seconds=round(elapsed, 3)),
                source="gate")
        except Exception:  # noqa: BLE001 — forensics must not break the gate
            pass
    finally:
        with _PROBE_LOCK:
            _probe_state["probing"] = False
    return attached


def _probe_mode() -> str:
    """"subprocess" (default): the probe runs in a killable child that
    captures PJRT/libtpu init stderr into the diagnosis (obs.sentinel) —
    a wedged transport becomes kind="timeout" WITH the init chatter that
    explains it. "inline" keeps the in-process thread probe (tests pin
    it; also the mode for hosts where fork/exec is unwelcome)."""
    from ..utils.knobs import knob_str
    return knob_str("AUTOCYCLER_PROBE_MODE").strip().lower()


def _probe_attempt(timeout: float, mode: str = None
                   ) -> Tuple[bool, str, str, dict, float]:
    """One REAL probe attempt with the given deadline, shared by the
    synchronous gate (:func:`_tpu_attached`) and the background runner.
    Returns ``(attached, reason, kind, detail, elapsed)``. Exactly one
    ``_threading.Thread`` is constructed per attempt (tests count these
    constructions to pin probe/cache semantics)."""
    import sys
    if mode is None:
        mode = _probe_mode()
    result: List[Tuple[bool, str, str, dict]] = []

    def probe() -> None:
        if mode != "inline":
            try:
                from ..obs import sentinel
                outcome = sentinel.subprocess_probe(timeout)
            except Exception as e:  # noqa: BLE001 — fall back like any failure
                result.append((False, "probe subprocess machinery failed: "
                               f"{type(e).__name__}: {e}", "error", {}))
                return
            result.append((bool(outcome.get("attached")),
                           str(outcome.get("reason", "no reason recorded")),
                           str(outcome.get("kind", "error")), outcome))
            return
        try:
            import jax
            import jax.numpy as jnp
            backend = jax.default_backend()
            if backend != "tpu":
                result.append((False, f"jax default backend is {backend!r}",
                               "no-tpu", {}))
                return
            float(jnp.asarray(1.0) + 1.0)  # end-to-end transport check
            result.append((True, "tpu backend verified (tiny op round-tripped)",
                           "ok", {}))
        except Exception as e:  # noqa: BLE001 — no jax / no device: host matmul
            result.append((False, f"device init failed: {type(e).__name__}: {e}",
                           "error", {}))

    t0 = _time.perf_counter()
    t = _threading.Thread(target=probe, daemon=True, name="tpu-probe")
    t.start()
    # the subprocess probe enforces the deadline itself (kill + stderr
    # capture), so its thread gets a small grace on top; the inline
    # probe can truly wedge and gets exactly the deadline
    grace = 0.0 if mode == "inline" else min(5.0, 0.5 + 0.1 * timeout)
    t.join(timeout + grace)
    if result:
        attached, reason, kind, detail = result[0]
    else:
        attached = False
        kind = "timeout"
        detail = {}
        reason = (f"probe did not respond within {timeout:.0f}s "
                  "(wedged transport?)")
        print(f"autocycler: device {reason}; falling back to host "
              "backends", file=sys.stderr)
    return attached, reason, kind, detail, _time.perf_counter() - t0


# test hook: keeps the pre-round-5 `_tpu_attached.cache_clear()` call sites
# (tests/test_device_probe.py) working against the stateful probe
_tpu_attached.cache_clear = _probe_reset  # type: ignore[attr-defined]


# ---- asynchronous probe (the probe future) ----
# `start_background_probe()` runs the device probe concurrently with the
# host load/parse/encode stage; `device_attached()` is the consult at the
# first device-dispatch point. A wedged probe therefore costs ZERO added
# wall time on the host fallback path: the default consult is a
# non-blocking peek that answers False while the probe is still pending.


def _background_deadline() -> float:
    """The background probe's deadline: the operator knobs win when set,
    otherwise :data:`BACKGROUND_PROBE_DEADLINE_S` (lower than the legacy
    synchronous 60 s default — the probe overlaps host work, so the
    deadline bounds attach lateness, not wall time). Delegates to
    obs.sentinel.probe_deadline(background=True) so the knob precedence
    lives in exactly one place."""
    try:
        from ..obs import sentinel
        return sentinel.probe_deadline(background=True)
    except Exception:  # noqa: BLE001 — sentinel must never break dispatch
        return BACKGROUND_PROBE_DEADLINE_S


def _probe_retries() -> Tuple[int, float]:
    """(bounded retry count, initial backoff seconds) for the background
    probe — retries happen BEFORE the persisted negative cache is written,
    so one transient wedge doesn't poison warm runs for the full TTL."""
    from ..utils.knobs import knob_float, knob_int
    retries = max(0, int(knob_int("AUTOCYCLER_PROBE_RETRIES")))
    backoff = float(knob_float("AUTOCYCLER_PROBE_RETRY_BACKOFF_S"))
    return retries, max(0.0, backoff)


def _background_runner(deadline: float, mode: str) -> None:
    """The background probe thread: bounded retry-with-backoff around
    :func:`_probe_attempt`; only the FINAL outcome reaches the in-memory
    cache, the persisted negative cache and the sentinel log (intermediate
    failed attempts log as source="background-retry")."""
    attached, reason, kind, detail = False, "probe never ran", "error", {}
    t0 = _time.perf_counter()
    attempts = 0
    try:
        persisted = _disk_probe_load()
        if persisted is not None:
            # a recent process already paid the deadline against this
            # wedged transport: adopt its negative outcome
            _record_probe(False, 0.0,
                          f"persisted negative probe: {persisted['reason']}",
                          cache=True, kind=persisted["kind"])
            return
        retries, backoff = _probe_retries()
        for i in range(retries + 1):
            attempts += 1
            with _PROBE_LOCK:
                _bg_state["attempts"] = attempts
            attached, reason, kind, detail, elapsed = \
                _probe_attempt(deadline, mode)
            if attached or kind not in ("timeout", "error"):
                break
            if i < retries:
                try:
                    from ..obs import sentinel
                    sentinel.record_outcome(
                        dict(detail or {}, attached=False, kind=kind,
                             reason=reason, seconds=round(elapsed, 3),
                             retry=i + 1),
                        source="background-retry")
                except Exception:  # noqa: BLE001 — forensics only
                    pass
                _time.sleep(backoff * (2 ** i))
        total = _time.perf_counter() - t0
        _record_probe(attached, total, reason, cache=True, kind=kind,
                      detail=detail)
        _disk_probe_store(attached, reason, kind)
        try:
            from ..obs import sentinel
            sentinel.record_outcome(
                dict(detail or {}, attached=attached, kind=kind,
                     reason=reason, seconds=round(total, 3),
                     attempts=attempts),
                source="background")
        except Exception:  # noqa: BLE001 — forensics must not break the gate
            pass
    finally:
        with _PROBE_LOCK:
            _probe_state["probing"] = False
            _bg_state["resolved"] = True
            _bg_state["resolve_s"] = round(_time.perf_counter() - t0, 3)
            event = _bg_state.get("event")
        if event is not None:
            event.set()


def start_background_probe() -> bool:
    """Kick off the device probe in a daemon thread so its cost overlaps
    the host load/parse/encode stage. Idempotent: the first call per
    process starts (or short-circuits) the probe, later calls are no-ops.
    Returns True when a background thread was actually started.

    Short-circuit cases resolve synchronously WITHOUT a thread or a jax
    import: a pinned non-TPU platform, a disabled deadline (<= 0), or an
    already-cached probe outcome."""
    import os
    with _PROBE_LOCK:
        if _bg_state.get("started"):
            return False
        _bg_state.update(started=True, resolved=False, wait_s=0.0,
                         pending_consults=0, attempts=0,
                         started_at=_time.monotonic(),
                         event=_threading.Event())
        already = _probe_state.get("cached")
        probing = _probe_state.get("probing")
    deadline = _background_deadline()
    platforms = os.environ.get("JAX_PLATFORMS", "").strip().lower()
    pinned = platforms and "tpu" not in platforms and "axon" not in platforms
    if already or probing or pinned or deadline <= 0:
        # resolve immediately: either the answer is already known/cheap
        # (pinned/cached — _tpu_attached answers without a probe) or the
        # device path is switched off; a concurrent synchronous probe
        # (probing) keeps its own thread and resolves the shared state
        if not probing:
            _tpu_attached()
        with _PROBE_LOCK:
            _bg_state["resolved"] = True
            _bg_state["resolve_s"] = 0.0
            event = _bg_state.get("event")
        event.set()
        return False
    with _PROBE_LOCK:
        _probe_state["probing"] = True
        _bg_state["deadline"] = deadline
    t = _threading.Thread(target=_background_runner,
                          args=(deadline, _probe_mode()),
                          daemon=True, name="tpu-probe-background")
    t.start()
    return True


def device_attached(wait: bool = False) -> bool:
    """The probe-future consult used at device-dispatch points.

    With the background probe pending: ``wait=False`` (the default, for
    auto-mode dispatch heuristics) answers False immediately — the caller
    takes the bit-identical host path and the pending consult is counted
    for :func:`probe_overlap_report`. ``wait=True`` (for explicit operator
    device requests) blocks until the probe resolves, bounded by the
    probe's remaining deadline budget; the wait is accounted under the
    DEVICE_WAIT metric (utils.timing.device_wait), NOT device_seconds.

    With no background probe in flight this is exactly the legacy
    synchronous gate (:func:`_tpu_attached`)."""
    with _PROBE_LOCK:
        pending = _bg_state.get("started") and not _bg_state.get("resolved")
        if pending and not wait:
            _bg_state["pending_consults"] = \
                _bg_state.get("pending_consults", 0) + 1
            return bool(_probe_state.get("attached", False))
        event = _bg_state.get("event")
        started_at = _bg_state.get("started_at", 0.0)
        deadline = _bg_state.get("deadline", 0.0)
    if pending and event is not None:
        # remaining budget: the full retry schedule (attempts + backoffs)
        # plus thread grace; a wedged background probe never blocks the
        # caller past this bound
        retries, backoff = _probe_retries()
        budget = (retries + 1) * (deadline + 5.0) \
            + sum(backoff * (2 ** i) for i in range(retries))
        remaining = max(0.5, budget - (_time.monotonic() - started_at))
        from ..utils.timing import device_wait
        t0 = _time.perf_counter()
        with device_wait("probe future"):
            event.wait(remaining)
        with _PROBE_LOCK:
            _bg_state["wait_s"] = round(
                _bg_state.get("wait_s", 0.0)
                + (_time.perf_counter() - t0), 3)
    return _tpu_attached()


def probe_overlap_report() -> dict:
    """The async-probe ledger for artifacts/doctor/watch: ``state``
    (unstarted | pending | attached | failed), ``kind`` (probe taxonomy),
    ``resolve_s`` (probe wall from start to resolution), ``wait_s``
    (host seconds callers actually blocked on the future),
    ``overlap_saved_s`` (resolve_s - wait_s: attach latency hidden behind
    host work), ``pending_consults`` (device-dispatch points that answered
    host-path while pending) and ``attempts``."""
    with _PROBE_LOCK:
        started = _bg_state.get("started", False)
        resolved = _bg_state.get("resolved", False)
        resolve_s = _bg_state.get("resolve_s")
        wait_s = _bg_state.get("wait_s", 0.0)
        attached = _probe_state.get("attached")
        kind = _probe_state.get("kind")
        pending_consults = _bg_state.get("pending_consults", 0)
        attempts = _bg_state.get("attempts", 0)
        deadline = _bg_state.get("deadline")
    if not started:
        state = "unstarted"
    elif not resolved:
        state = "pending"
    else:
        state = "attached" if attached else "failed"
    overlap = None
    if resolve_s is not None:
        overlap = round(max(0.0, resolve_s - wait_s), 3)
    return {"state": state, "kind": kind, "resolve_s": resolve_s,
            "wait_s": round(wait_s, 3), "overlap_saved_s": overlap,
            "pending_consults": pending_consults, "attempts": attempts,
            "deadline_s": deadline}


def exceeds_int32_accumulation(weighted: np.ndarray) -> bool:
    """Whether a device int32 contraction of ``weighted`` (a 0/1 membership
    matrix times per-column weights, rows = contigs) could wrap: the largest
    possible intersection cell is a full weighted row sum. Shared by the
    single-isolate matmul here and parallel/batch.py's mesh contraction so
    the exactness guard can't drift between them."""
    if not weighted.size:
        return False
    return int(weighted.sum(axis=-1).max()) > np.iinfo(np.int32).max


def membership_matrix(graph, sequences) -> Tuple[np.ndarray, np.ndarray, List[int]]:
    """(M: contigs × unitigs uint8, w: unitig lengths int64, seq ids)."""
    numbers = [u.number for u in graph.unitigs]
    col = {n: i for i, n in enumerate(numbers)}
    w = np.array([u.length() for u in graph.unitigs], dtype=np.int64)
    M = np.zeros((len(sequences), len(numbers)), dtype=np.uint8)
    ids = []
    paths = graph.get_unitig_paths_for_sequences([s.id for s in sequences])
    for i, seq in enumerate(sequences):
        ids.append(seq.id)
        for number, _ in paths[seq.id]:
            M[i, col[number]] = 1
    return M, w, ids


def _intersections_to_matrix(inter: np.ndarray) -> np.ndarray:
    """Integer intersection matrix -> asymmetric distance matrix. The single
    float expression shared by every backend (host matmul, device matmul,
    mesh-batched contraction) so their results stay bit-identical."""
    a_len = np.diag(inter).astype(np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        return 1.0 - inter / a_len[:, None]


def intersections_to_distances(inter: np.ndarray, ids: List[int]
                               ) -> Dict[Tuple[int, int], float]:
    """Reference-shaped {(id_a, id_b): distance} from an integer
    intersection matrix (used by `cluster` and the batched `batch` path)."""
    D = _intersections_to_matrix(inter)
    return {(ids[a], ids[b]): float(D[a, b])
            for a in range(len(ids)) for b in range(len(ids))}


def _blocked_intersections(M: np.ndarray, w: np.ndarray,
                           block: int) -> np.ndarray:
    """Host intersection contraction in row blocks: the weighted int64 copy
    of M exists only ``block`` rows at a time, so peak transient memory is
    one int64 transpose of M plus a block instead of two full weighted
    copies. Pure integer arithmetic in the same order per cell, so the
    result is bit-identical to the whole-matrix contraction."""
    S = M.shape[0]
    inter = np.empty((S, S), np.int64)
    Mt = np.ascontiguousarray(M.T, dtype=np.int64)
    for lo in range(0, S, block):
        hi = min(lo + block, S)
        inter[lo:hi] = (M[lo:hi].astype(np.int64) * w[None, :]) @ Mt
    return inter


def _distance_block() -> int:
    from ..utils.knobs import knob_int
    return int(knob_int("AUTOCYCLER_DISTANCE_BLOCK"))


def pairwise_distance_matrix(M: np.ndarray, w: np.ndarray,
                             use_jax=None) -> np.ndarray:
    """Asymmetric distance matrix D[a, b] = 1 - |A∩B|_len / |A|_len."""
    if use_jax is None:
        if M.size >= _JAX_THRESHOLD:
            use_jax = True          # wins on any backend; no probe needed
        elif M.size < _TPU_THRESHOLD:
            use_jax = False         # too small everywhere; keep jax unloaded
        else:
            # auto mode consults the probe future non-blockingly: while the
            # background probe is pending this answers False (host matmul,
            # bit-identical) rather than stalling the stage on attach
            use_jax = device_attached()
    if not use_jax:
        # AUTOCYCLER_DISTANCE_BLOCK bounds the exact host path's peak
        # memory on thousands-of-contigs inputs (default off: whole matrix)
        block = _distance_block()
        if 0 < block < M.shape[0]:
            return _intersections_to_matrix(_blocked_intersections(M, w, block))
    Mw = M.astype(np.int64) * w[None, :]
    if use_jax and exceeds_int32_accumulation(Mw):
        use_jax = False
    if use_jax:
        try:
            from ..utils.jaxcache import configure_compile_cache
            configure_compile_cache()
            import jax.numpy as jnp
            # pad to fixed shape buckets (rows to 64, cols to 8192) so the
            # compiled matmul is reused across datasets via the persistent
            # cache — every real run has a different (S, U) and would
            # otherwise pay a fresh ~2.5 s XLA compile. Zero rows/columns
            # contribute nothing to the intersection; the pad is sliced off.
            S, U = Mw.shape
            Sp = -(-S // 64) * 64
            Up = -(-U // 8192) * 8192
            Mw_p = np.zeros((Sp, Up), np.int32)
            Mw_p[:S, :U] = Mw
            Mt_p = np.zeros((Up, Sp), np.int32)
            Mt_p[:U, :S] = M.T
            from ..utils.timing import device_dispatch
            with device_dispatch("cluster distance matmul",
                                 flops=2.0 * Sp * Up * Sp):
                inter = np.asarray(
                    jnp.matmul(jnp.asarray(Mw_p), jnp.asarray(Mt_p)),
                )[:S, :S].astype(np.int64)
        except Exception as e:  # noqa: BLE001 — keep the host fallback
            # guarantee for ANY device failure, but surface it
            import sys

            from ..utils.timing import record_device_failure
            what = (f"device distance matmul failed "
                    f"({type(e).__name__}: {e})")
            record_device_failure(what, exc=e)
            print(f"autocycler: {what}; falling back to host matmul",
                  file=sys.stderr)
            inter = Mw @ M.astype(np.int64).T
    else:
        inter = Mw @ M.astype(np.int64).T
    return _intersections_to_matrix(inter)


def pairwise_contig_distances(graph, sequences, use_jax=None
                              ) -> Dict[Tuple[int, int], float]:
    """Distances keyed by (seq_a.id, seq_b.id), reference-shaped."""
    M, w, ids = membership_matrix(graph, sequences)
    D = pairwise_distance_matrix(M, w, use_jax=use_jax)
    return {(ids[a], ids[b]): float(D[a, b])
            for a in range(len(ids)) for b in range(len(ids))}
