"""Brute-force k-mer match grid as a Pallas TPU kernel.

This is the "vmapped Pallas k-mer match grid" north star (BASELINE.json /
SURVEY.md §2.2 dotplot row): compare every k-mer of sequence A against every
k-mer of sequence B — an nA × nB cell grid — and reduce match counts into
block-resolution tiles. The exact pixel-level dotplot uses the sort-join in
commands/dotplot.py; this kernel provides (a) a downsampled match-density
grid and (b) the Gcells/s throughput benchmark.

Formulation: ACGT k-mers are packed 16 bases per int32 word (2 bits/base),
so a k-mer equality test is W = ceil(k/16) integer compares. Each Pallas
program loads a [W, TA] tile of A words and a [W, TB] tile of B words into
VMEM, forms the [TA, TB] equality matrix on the VPU and writes one match
count — TA*TB cells per program.
"""

from __future__ import annotations

import functools
from typing import Tuple

import numpy as np

TILE_A = 512
TILE_B = 512


def pack_2bit_words(codes: np.ndarray, k: int) -> np.ndarray:
    """ACGT codes (1..4 from ops.encode) -> [W, n] int32 k-mer words,
    16 bases per word, zero-padded tail. n = len(codes) - k + 1."""
    n = len(codes) - k + 1
    if n <= 0:
        return np.zeros(((k + 15) // 16, 0), dtype=np.int32)
    W = (k + 15) // 16
    base2 = (codes.astype(np.int32) - 1).clip(0, 3)
    words = np.zeros((W, n), dtype=np.int32)
    for w in range(W):
        acc = np.zeros(n, dtype=np.int32)
        for t in range(16):
            idx = w * 16 + t
            acc <<= 2
            if idx < k:
                acc |= base2[idx:idx + n]
        words[w] = acc
    return words


def _pad_to(words: np.ndarray, tile: int, fill: int) -> np.ndarray:
    W, n = words.shape
    padded = -((-n) // tile) * tile
    if padded == n:
        return words
    out = np.full((W, max(padded, tile)), fill, dtype=np.int32)
    out[:, :n] = words
    return out


def _grid_kernel(n_a, n_b, tile_a, tile_b, ga, gb, ia, ib, a_ref, b_ref,
                 out_ref):
    """VPU word-compare grid with sub-grid output accumulation: grid step
    (I, J, a, b) computes the scalar count of tile (I*ia + a, J*ib + b) and
    deposits it into element (a, b) of the (8, 128) output block owned by
    (I, J). The block stays VMEM-resident across the inner steps (the out
    index_map ignores a, b) and is written to HBM ONCE — round 4's version
    broadcast each scalar over its own (8, 128) tile, a 1024x
    output-bandwidth waste flagged by the round-4 verdict. The inner
    sub-grid (ia, ib) = (min(8, ga), min(128, gb)) shrinks with the tile
    grid so small inputs don't pay 1024 inner steps for a handful of tiles
    (cells the inner grid never reaches stay at the first step's
    zero-init)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    ti = pl.program_id(0) * ia + pl.program_id(2)    # global tile row
    tj = pl.program_id(1) * ib + pl.program_id(3)    # global tile col
    a = pl.program_id(2)
    b = pl.program_id(3)

    def count(masked):
        eq = a_ref[0, :].reshape(-1, 1) == b_ref[0, :].reshape(1, -1)
        for w in range(1, a_ref.shape[0]):
            eq &= a_ref[w, :].reshape(-1, 1) == b_ref[w, :].reshape(1, -1)
        if masked:
            # mask tile padding by global index: 2-bit packing has no
            # out-of-band fill value (an all-T k-mer word is -1, colliding
            # with any constant)
            row = (jax.lax.broadcasted_iota(jnp.int32, (tile_a, 1), 0)
                   + ti * tile_a)
            col = (jax.lax.broadcasted_iota(jnp.int32, (1, tile_b), 1)
                   + tj * tile_b)
            eq &= (row < n_a) & (col < n_b)
        return eq.sum(dtype=jnp.int32)

    # deposit into the resident block via one-hot (scalar dynamic stores
    # are not a Mosaic strength; a (8, 128) VMEM select is free next to the
    # tile_a x tile_b compare)
    rows = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 1)
    onehot = (rows == a) & (cols == b)

    # Only the last tile row/column can contain padding; interior programs
    # skip the two iota compares + and per cell (measured 315 -> 459
    # Gcells/s at 512k^2 on v5e with 2048x4096 tiles). Tiles past the grid
    # edge (the sub-grid rounds ga/gb up to 8/128) skip the compare
    # entirely and deposit 0.
    in_grid = (ti < ga) & (tj < gb)
    interior = in_grid & ((ti + 1) * tile_a <= n_a) & ((tj + 1) * tile_b <= n_b)
    edge = in_grid & ~interior

    first = (a == 0) & (b == 0)

    @pl.when(first)
    def _():
        out_ref[:, :] = jnp.zeros_like(out_ref)

    @pl.when(interior)
    def _():
        out_ref[:, :] = out_ref[:, :] + jnp.where(onehot, count(False), 0)

    @pl.when(edge)
    def _():
        out_ref[:, :] = out_ref[:, :] + jnp.where(onehot, count(True), 0)


def _grid_call(a_pad, b_pad, n_a: int, n_b: int, tile_a: int, tile_b: int,
               interpret: bool):
    """The traced VPU-grid dispatch (tile-padded device arrays in, tile
    counts out) — the exact code the chip runs, shared by :func:`match_grid`
    and the AOT TPU-lowering tests (tests/test_tpu_lowering.py export THIS
    with interpret=False, so the production dispatch can't drift from what
    CI lowers)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    W = a_pad.shape[0]
    ga = a_pad.shape[1] // tile_a
    gb = b_pad.shape[1] // tile_b
    if ga == 0 or gb == 0:
        # zero k-mers on a side: a 1x-floor zero grid, matching the host
        # oracle's shape convention (match_grid_reference uses max(n, 1))
        return jnp.zeros((max(ga, 1), max(gb, 1)), jnp.int32)
    ia = min(8, ga)         # inner sub-grid: up to 8 x 128 tiles share one
    ib = min(128, gb)       # (8, 128) output block
    GA = -(-ga // ia)
    GB = -(-gb // ib)

    def a_map(I, J, a, b):  # noqa: E741 — grid index names
        # clamp: sub-grid tiles past the edge load a valid (ignored) block
        return (0, jnp.minimum(I * ia + a, ga - 1))

    def b_map(I, J, a, b):
        return (0, jnp.minimum(J * ib + b, gb - 1))

    tiles = pl.pallas_call(
        functools.partial(_grid_kernel, n_a, n_b, tile_a, tile_b, ga, gb,
                          ia, ib),
        grid=(GA, GB, ia, ib),
        in_specs=[
            pl.BlockSpec((W, tile_a), a_map),
            pl.BlockSpec((W, tile_b), b_map),
        ],
        out_specs=pl.BlockSpec((8, 128), lambda I, J, a, b: (I, J)),
        out_shape=jax.ShapeDtypeStruct((GA * 8, GB * 128), jnp.int32),
        interpret=interpret,
    )(a_pad, b_pad)
    return tiles[:ga, :gb]


def match_grid(a_words: np.ndarray, b_words: np.ndarray,
               tile_a: int = TILE_A, tile_b: int = TILE_B):
    """[W, nA] × [W, nB] k-mer words -> [ceil(nA/tile), ceil(nB/tile)] match
    counts. Runs the Pallas kernel on TPU, falling back to interpret mode on
    CPU backends."""
    import jax
    import jax.numpy as jnp

    _, n_a = a_words.shape
    _, n_b = b_words.shape
    a_pad = _pad_to(a_words, tile_a, -1)
    b_pad = _pad_to(b_words, tile_b, -2)
    return _grid_call(jnp.asarray(a_pad), jnp.asarray(b_pad), n_a, n_b,
                      tile_a, tile_b,
                      interpret=_interpret_fallback())


TILE_MXU = 1024


def expand_pm1_words(words, k: int, n_valid: int = None, dtype="bfloat16"):
    """Device-side bit-antipodal expansion: [W, n] packed int32 words ->
    [n, 2k] where column 2*p + b is +1 if bit b of base p is set, else -1.
    Two length-2k ±1 vectors dot to 2k - 2*hamming(bits), so two k-mers are
    equal iff their rows dot to exactly 2k — the equality test becomes a
    D=2k matmul, half the contraction depth of a 4-symbol one-hot (D=4k)
    for the same exact result.

    Rows at index >= n_valid are zeroed: a zero row dots to 0 != 2k against
    anything (k >= 1), so tile padding can NEVER register a match (2-bit
    packing has no out-of-band sentinel — every int32 is a real all-base
    word)."""
    import jax.numpy as jnp

    W, n = words.shape
    wd = jnp.asarray(words)
    cols = []
    for p in range(k):
        w, t = divmod(p, 16)
        base = (wd[w] >> (2 * (15 - t))) & 3        # base t at bits 2*(15-t)
        cols.append((base >> 1) * 2 - 1)            # high bit -> ±1
        cols.append((base & 1) * 2 - 1)             # low bit  -> ±1
    pm = jnp.stack(cols, axis=1).astype(jnp.dtype(dtype))   # [n, 2k]
    if n_valid is not None and n_valid < n:
        pm = pm * (jnp.arange(n)[:, None] < n_valid).astype(pm.dtype)
    return pm


def _mxu_kernel(two_k, acc_dtype, ga, gb, ia, ib, a_ref, b_ref, out_ref):
    """±1-matmul grid with the same sub-grid output accumulation as
    _grid_kernel: inner step (a, b) deposits its scalar into element (a, b)
    of the (8, 128) block resident for (I, J)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    ti = pl.program_id(0) * ia + pl.program_id(2)
    tj = pl.program_id(1) * ib + pl.program_id(3)
    a = pl.program_id(2)
    b = pl.program_id(3)
    rows = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 1)
    onehot = (rows == a) & (cols == b)
    in_grid = (ti < ga) & (tj < gb)

    @pl.when((a == 0) & (b == 0))
    def _():
        out_ref[:, :] = jnp.zeros_like(out_ref)

    @pl.when(in_grid)
    def _():
        # ±1 inputs: row dots are integers in [-2k, 2k] — exact in int32
        # trivially, and exact in f32 for any k (|dot| <= 512 << 2^24).
        # Mosaic REQUIRES a 32-bit matmul accumulator ('Expected matmul acc
        # to be 32-bit' — a bf16 preferred_element_type compiles under
        # interpret mode but fails verification on the chip). Tile padding
        # rows are zeroed by expand_pm1_words and dot to 0 != 2k.
        m = jax.lax.dot_general(a_ref[:, :], b_ref[:, :],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=acc_dtype)
        count = jnp.sum((m == two_k).astype(jnp.int32))
        out_ref[:, :] = out_ref[:, :] + jnp.where(onehot, count, 0)


def match_grid_mxu(a_words: np.ndarray, b_words: np.ndarray, k: int,
                   tile: int = TILE_MXU, tile_a: int = None,
                   tile_b: int = None, in_dtype: str = "bfloat16"):
    """MXU formulation of :func:`match_grid`: ±1 bit rows are expanded on
    device and each program contracts a [tile_a, 2k] x [tile_b, 2k] pair on
    the MXU. A cell matches iff its dot equals 2k (all 2k bits equal).
    Output matches match_grid's tile counts exactly.

    in_dtype picks the MXU input precision: "bfloat16" (f32 accumulation —
    exact, ±1 inputs and |dot| <= 2k <= 512) or "int8" (int32 accumulation,
    2x the bf16 MXU rate on v5e when Mosaic lowers it natively). Both are
    exact; the k <= 256 guard keeps 2k within trivial exact range, and
    k <= 55 in practice per the main.rs flag range."""
    import jax.numpy as jnp

    if k > 256:
        raise ValueError("match_grid_mxu requires k <= 256")
    tile_a = tile if tile_a is None else tile_a
    tile_b = tile if tile_b is None else tile_b
    W, n_a = a_words.shape
    _, n_b = b_words.shape
    a_pad = _pad_to(a_words, tile_a, -1)
    b_pad = _pad_to(b_words, tile_b, -2)
    return _mxu_run(jnp.asarray(a_pad), jnp.asarray(b_pad),
                    k, n_a, n_b, tile_a, tile_b, in_dtype)


@functools.lru_cache(maxsize=None)
def _mxu_jit():
    import jax

    return jax.jit(_mxu_run_impl,
                   static_argnames=("k", "n_a", "n_b", "tile_a", "tile_b",
                                    "in_dtype", "interpret"))


def _interpret_fallback() -> bool:
    """Whether the Pallas kernels must run under the interpret-mode
    simulator (no TPU answers); the Pallas→jnp degrade is recorded once per
    process through the unified backend registry."""
    import jax

    backend = jax.default_backend()
    if backend == "tpu":
        return False
    from ..utils.resilience import record_degrade
    record_degrade("pallas-match-grid", "pallas-tpu", "jnp-interpret",
                   f"jax default backend is {backend!r}, not 'tpu'")
    return True


def _mxu_run(a_pad, b_pad, k, n_a, n_b, tile_a, tile_b, in_dtype):
    return _mxu_jit()(a_pad, b_pad, k=k, n_a=n_a, n_b=n_b,
                      tile_a=tile_a, tile_b=tile_b, in_dtype=in_dtype,
                      interpret=_interpret_fallback())


def _mxu_run_impl(a_pad, b_pad, *, k, n_a, n_b, tile_a, tile_b, in_dtype,
                  interpret):
    """The traced MXU-grid dispatch — exported verbatim by the AOT
    TPU-lowering tests with interpret=False (tests/test_tpu_lowering.py)."""
    import functools as ft

    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    ga = a_pad.shape[1] // tile_a
    gb = b_pad.shape[1] // tile_b
    if ga == 0 or gb == 0:
        # zero k-mers on a side: a 1x-floor zero grid, matching the host
        # oracle's shape convention (match_grid_reference uses max(n, 1))
        return jnp.zeros((max(ga, 1), max(gb, 1)), jnp.int32)
    ia = min(8, ga)
    ib = min(128, gb)
    GA = -(-ga // ia)
    GB = -(-gb // ib)
    D = 2 * k
    acc = jnp.int32 if in_dtype == "int8" else jnp.float32
    a_pm = expand_pm1_words(a_pad, k, n_valid=n_a, dtype=in_dtype)
    b_pm = expand_pm1_words(b_pad, k, n_valid=n_b, dtype=in_dtype)
    tiles = pl.pallas_call(
        ft.partial(_mxu_kernel, 2 * k, acc, ga, gb, ia, ib),
        grid=(GA, GB, ia, ib),
        in_specs=[
            pl.BlockSpec((tile_a, D),
                         lambda I, J, a, b: (jnp.minimum(I * ia + a, ga - 1), 0)),
            pl.BlockSpec((tile_b, D),
                         lambda I, J, a, b: (jnp.minimum(J * ib + b, gb - 1), 0)),
        ],
        out_specs=pl.BlockSpec((8, 128), lambda I, J, a, b: (I, J)),
        out_shape=jax.ShapeDtypeStruct((GA * 8, GB * 128), jnp.int32),
        interpret=interpret,
    )(a_pm, b_pm)
    return tiles[:ga, :gb]


@functools.lru_cache(maxsize=None)
def _tile_bits_fn(W: int, tile_a: int, tile_b: int):
    """Compiled device refinement for one (W, tile_a, tile_b) shape class:
    (a_pad [W, nA], b_pad [W, nB], tis [T], tjs [T]) -> [T, tile_a,
    tile_b/32] uint32 packed equality bitmasks. lax.map keeps one [tile_a,
    tile_b] equality matrix live at a time."""
    import jax
    import jax.numpy as jnp

    assert tile_b % 32 == 0
    Wb = tile_b // 32
    shift = jnp.arange(32, dtype=jnp.uint32)[None, None, :]

    def run(a_pad, b_pad, tis, tjs):
        def one(ti_tj):
            ti, tj = ti_tj
            a = jax.lax.dynamic_slice(a_pad, (0, ti * tile_a), (W, tile_a))
            b = jax.lax.dynamic_slice(b_pad, (0, tj * tile_b), (W, tile_b))
            eq = a[0][:, None] == b[0][None, :]
            for w in range(1, W):
                eq &= a[w][:, None] == b[w][None, :]
            packed = (eq.reshape(tile_a, Wb, 32).astype(jnp.uint32)
                      << shift).sum(axis=-1, dtype=jnp.uint32)
            return packed
        return jax.lax.map(one, (tis, tjs))

    return jax.jit(run)


# device+host bytes ceiling for one packed-bits dispatch (the same budget
# discipline as the trim traceback's _TRACEBACK_BITS_BUDGET): repeat-rich
# sequences can light up thousands of nonzero tiles, and an unchunked
# dispatch would materialise [T, tile_a, tile_b/32] for ALL of them at once
_TILE_BITS_BUDGET = 256 * 1024 * 1024


def match_tile_bits(a_words: np.ndarray, b_words: np.ndarray, tile_pairs,
                    tile_a: int = TILE_A, tile_b: int = TILE_B) -> np.ndarray:
    """Device-side refinement of selected tiles (VERDICT r3 item 4): for
    each (ti, tj) in ``tile_pairs``, the exact [tile_a, tile_b] k-mer
    equality matrix is computed ON DEVICE and returned as packed uint32
    bitmasks ([T, tile_a, tile_b//32], bit j of word j//32 = cell (i, j)
    matches). The host only unpacks set bits (commands.dotplot), instead of
    re-running the W-word compare per nonzero tile. Tile padding cells
    compare against sentinel-filled pads (-1/-2), which never match.

    Dispatches are chunked under _TILE_BITS_BUDGET bytes and each chunk's
    pair count is padded to the next power of two (repeating the last
    pair), so memory stays bounded and the jitted refinement compiles for
    O(log T) shape classes instead of every distinct tile count (advisor
    r4 finding)."""
    import jax.numpy as jnp

    W = a_words.shape[0]
    a_pad = _pad_to(a_words, tile_a, -1)
    b_pad = _pad_to(b_words, tile_b, -2)
    tis = np.asarray([p[0] for p in tile_pairs], np.int32)
    tjs = np.asarray([p[1] for p in tile_pairs], np.int32)
    T = len(tis)
    if T == 0:
        return np.zeros((0, tile_a, tile_b // 32), np.uint32)
    per_tile = tile_a * (tile_b // 32) * 4
    max_chunk = max(_TILE_BITS_BUDGET // per_tile, 1)
    # floor to a power of two: chunks are padded UP to the next power of
    # two below, so a non-power-of-two cap would let full chunks dispatch
    # up to ~2x the budget
    while max_chunk & (max_chunk - 1):
        max_chunk &= max_chunk - 1
    fn = _tile_bits_fn(W, tile_a, tile_b)
    a_d, b_d = jnp.asarray(a_pad), jnp.asarray(b_pad)
    chunks = []
    for lo in range(0, T, max_chunk):
        ci, cj = tis[lo:lo + max_chunk], tjs[lo:lo + max_chunk]
        n = len(ci)
        padded = 1
        while padded < n:
            padded <<= 1
        if padded != n:   # repeat the last pair; sliced off below
            ci = np.concatenate([ci, np.full(padded - n, ci[-1], np.int32)])
            cj = np.concatenate([cj, np.full(padded - n, cj[-1], np.int32)])
        out = fn(a_d, b_d, jnp.asarray(ci), jnp.asarray(cj))
        chunks.append(np.asarray(out)[:n])
    return np.concatenate(chunks, axis=0)


def unpack_tile_bits(packed: np.ndarray) -> np.ndarray:
    """[tile_a, tile_b/32] uint32 packed bits -> [tile_a, tile_b] bool
    (little-endian bit order, matching match_tile_bits)."""
    return np.unpackbits(packed.view(np.uint8), axis=-1,
                         bitorder="little").astype(bool)


def match_grid_reference(a_words: np.ndarray, b_words: np.ndarray,
                         tile_a: int = TILE_A, tile_b: int = TILE_B) -> np.ndarray:
    """Plain-numpy oracle for the kernel (used by tests)."""
    W, n_a = a_words.shape
    _, n_b = b_words.shape
    ga = -(-max(n_a, 1) // tile_a)
    gb = -(-max(n_b, 1) // tile_b)
    out = np.zeros((ga, gb), dtype=np.int32)
    for i in range(ga):
        for j in range(gb):
            a = a_words[:, i * tile_a:(i + 1) * tile_a]
            b = b_words[:, j * tile_b:(j + 1) * tile_b]
            eq = np.ones((a.shape[1], b.shape[1]), dtype=bool)
            for w in range(W):
                eq &= a[w][:, None] == b[w][None, :]
            out[i, j] = eq.sum()
    return out


def benchmark_gcells(n_a: int = 524288, n_b: int = 524288, k: int = 32,
                     repeats: int = 3, tile: int = 2048, tile_b: int = None,
                     seed: int = 0, kernel: str = "vpu") -> Tuple[float, float]:
    """Time the match grid; returns (best seconds, Gcells/s).
    kernel="vpu" is the word-compare kernel, "mxu" the ±1 matmul with bf16
    inputs, "mxu8" the same with int8 inputs / int32 accumulation.
    The VPU kernel's B tile defaults to 2*tile (2048x4096 measured best on
    v5e — the asymmetry amortises the A-tile load); pass tile_b explicitly
    to measure other shapes. The MXU kernel uses square `tile` tiles.

    Honest-measurement rules for remote-execution backends: every trial uses
    freshly generated inputs (identical requests can be deduplicated
    upstream) and the result is reduced to a scalar materialized on the
    host (block_until_ready alone can return before execution finishes
    through the tunnel)."""
    import time

    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)

    def fresh_words(n):
        return pack_2bit_words(rng.integers(1, 5, size=n + k - 1).astype(np.uint8), k)

    if tile_b is None:
        tb = 2 * tile if kernel == "vpu" else tile
    else:
        tb = tile_b

    def run(a_w, b_w):
        if kernel == "mxu":
            grid = match_grid_mxu(a_w, b_w, k, tile_a=tile, tile_b=tb)
        elif kernel == "mxu8":
            grid = match_grid_mxu(a_w, b_w, k, tile_a=tile, tile_b=tb,
                                  in_dtype="int8")
        else:
            grid = match_grid(a_w, b_w, tile_a=tile, tile_b=tb)
        return np.asarray(jnp.sum(grid))

    run(fresh_words(n_a), fresh_words(n_b))  # compile + warm up
    best = float("inf")
    for _ in range(repeats):
        a_w, b_w = fresh_words(n_a), fresh_words(n_b)
        t0 = time.perf_counter()
        run(a_w, b_w)
        best = min(best, time.perf_counter() - t0)
    cells = float(n_a) * float(n_b)
    return best, cells / best / 1e9
