"""5-symbol sequence encoding for device kernels.

The alphabet is ``. A C G T`` (reference kmer_graph.rs:23) with codes 0..4
chosen in ASCII order so that integer comparisons reproduce byte-lexicographic
comparisons of the original sequences ('.' = 0x2E sorts before 'A' < 'C' <
'G' < 'T'). Reverse complement is the arithmetic map ``c -> (5 - c) % 5``:
dots stay dots, A<->T, C<->G.
"""

from __future__ import annotations

import numpy as np

ALPHABET = b".ACGT"
CODE_DOT, CODE_A, CODE_C, CODE_G, CODE_T = range(5)

_ENCODE = np.zeros(256, dtype=np.uint8)
for _i, _b in enumerate(ALPHABET):
    _ENCODE[_b] = _i

_DECODE = np.frombuffer(ALPHABET, dtype=np.uint8)


def encode_bytes(seq: np.ndarray) -> np.ndarray:
    """ASCII uint8 -> codes 0..4 (unknown bytes map to 0)."""
    return _ENCODE[seq]


def decode_codes(codes: np.ndarray) -> np.ndarray:
    """codes 0..4 -> ASCII uint8."""
    return _DECODE[codes]


# complement in code space: dot->dot, A<->T, C<->G — the table gather beats
# the arithmetic (5 - c) % 5 form (one lookup, no modulo)
_COMPLEMENT = np.array([0, 4, 3, 2, 1], dtype=np.uint8)


def revcomp_codes(codes: np.ndarray) -> np.ndarray:
    """Reverse complement in code space."""
    return _COMPLEMENT[codes[::-1]].astype(codes.dtype, copy=False)


def encode_both_strands(seq: np.ndarray):
    """(forward codes, reverse-complement codes) of one ASCII strand with a
    single encode pass: the reverse strand is derived arithmetically in code
    space instead of round-tripping through reverse_complement_bytes +
    re-encode. Identical to encoding the byte-space reverse complement —
    unknown bytes encode to 0 on both routes."""
    fwd = _ENCODE[seq]
    return fwd, _COMPLEMENT[fwd[::-1]]
