"""Sequence-end repair: replace dot padding with matching real sequence.

Parity target: reference compress.rs:202-270. Each padded sequence starts and
ends with half_k dots followed/preceded by half_k real bases; the reference
regex-matches that (k-1)-char dotted pattern against every sequence (both
strands) and substitutes the best match, defined as (1) fewest dots,
(2) highest occurrence count, (3) lexicographically first
(find_best_match, compress.rs:239-270). Regex ``find_iter`` yields
non-overlapping matches left-to-right, which we reproduce exactly.

A pattern of h dots + h real bases matches text at offset j iff
text[j+off : j+off+h] equals the h real bases — every match is an occurrence
of a query h-gram. Two providers find those occurrences:

- the native rolling-hash multi-pattern scan over the RAW byte buffer (one
  sequential pass over all texts for all 2S queries, native/seqkernel.cpp —
  hits are memcmp-verified, so no symbol encoding is needed), or
- sort-based grouping of ALL h-grams of the 5-symbol-encoded buffer
  (ops.kmers.group_windows) as the numpy fallback.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..models import Sequence
from ..utils import reverse_complement_bytes
from .encode import encode_bytes
from .kmers import group_windows


def _find_best_match(candidates: List[bytes]) -> bytes:
    """(fewest dots, most frequent, lexicographically first)
    (reference compress.rs:239-270). The key depends only on the candidate
    value, so the min runs over DISTINCT candidates. Kept as the scalar
    oracle for :func:`_best_match_rows` (tests/test_edge_cases.py)."""
    counts: Dict[bytes, int] = {}
    for c in candidates:
        counts[c] = counts.get(c, 0) + 1
    return min(counts, key=lambda c: (c.count(b"."), -counts[c], c))


def _best_match_rows(rows: np.ndarray) -> bytes:
    """Vectorised `_find_best_match` over a [N, overlap] byte matrix: dedupe
    with counts, then pick (fewest dots, most frequent, lexicographically
    first) without materialising per-occurrence byte objects."""
    distinct, counts = np.unique(rows, axis=0, return_counts=True)  # sorted
    dots = (distinct == ord(".")).sum(axis=1)
    order = np.lexsort((np.arange(len(distinct)), -counts, dots))
    return distinct[order[0]].tobytes()


def _matches_by_query_native(buf, text_off, text_len, h, q_starts):
    """buf is the RAW byte buffer — the rolling-hash scan verifies hits with
    memcmp, so any injective byte alphabet works (inputs are validated
    ACGT+dots); only the grouping fallback needs the 5-symbol encoding."""
    from .. import native
    if not native.available():
        return None
    result = native.scan_gram_matches_native(buf, text_off, text_len, h, q_starts)
    if result is None:
        return None
    q_idx, t_idx, pos = result
    # output is (text, pos)-ordered; stable grouping by query keeps that
    order = np.argsort(q_idx, kind="stable")
    by_query: List[Tuple[np.ndarray, np.ndarray]] = []
    boundaries = np.searchsorted(q_idx[order], np.arange(len(q_starts) + 1))
    for q in range(len(q_starts)):
        sel = order[boundaries[q]:boundaries[q + 1]]
        by_query.append((t_idx[sel], pos[sel]))
    return by_query


def _matches_by_query_grouped(codes, text_off, text_len, h, q_starts,
                              use_jax=None, threads=None):
    """Group every h-window of every text, then look up each query's group.
    The grouping dispatches through ops.kmers.group_windows, so with device
    grouping enabled (AUTOCYCLER_DEVICE_GROUPING / use_jax) the h-gram
    occurrence scan runs on the device — the VERDICT r3 item-6 path
    (reference compress.rs:202-270); with it disabled this is the exact
    numpy fallback (radix-parallel above one thread on large inputs)."""
    win_count = text_len - h + 1
    woff = np.zeros(len(text_len), np.int64)
    woff[1:] = np.cumsum(win_count)[:-1]
    W = int(win_count.sum())
    wocc = np.arange(W, dtype=np.int64)
    wtext = np.searchsorted(woff, wocc, side="right") - 1
    wpos = wocc - woff[wtext]
    wstarts = text_off[wtext] + wpos

    all_starts = np.concatenate([wstarts, q_starts])
    order, gid_sorted = group_windows(codes, all_starts, h, use_jax=use_jax,
                                      threads=threads)
    gid = np.empty(len(all_starts), np.int64)
    gid[order] = gid_sorted
    win_gid = gid[:W]
    query_gid = gid[W:]

    win_order = np.argsort(win_gid, kind="stable")  # groups keep (text,pos) order
    sorted_gid = win_gid[win_order]
    by_query = []
    for q in range(len(q_starts)):
        lo = np.searchsorted(sorted_gid, query_gid[q], side="left")
        hi = np.searchsorted(sorted_gid, query_gid[q], side="right")
        sel = win_order[lo:hi]
        by_query.append((wtext[sel], wpos[sel]))
    return by_query


def sequence_end_repair(sequences: List[Sequence], k_size: int,
                        threads: int = 1) -> None:
    """In-place repair of every sequence's dotted ends (compress.rs:202-236).

    Matches are searched in the ORIGINAL (pre-repair) sequences, like the
    reference's cloned all_seqs snapshot (compress.rs:209). The reference
    rayon-parallelises the per-sequence repair (compress.rs:210); here the
    occurrence scan is one batched native pass and only the per-sequence
    candidate selection distributes over ``threads``.
    """
    if not sequences:
        return
    h = k_size // 2
    if h == 0:
        return  # k=1: no padding, nothing to repair
    overlap = k_size - 1  # == 2h

    # text layout: per sequence, forward then reverse padded strands
    bufs = []
    text_off_list = []
    total = 0
    for s in sequences:
        for strand_seq in (s.forward_seq, s.reverse_seq):
            text_off_list.append(total)
            bufs.append(strand_seq)
            total += len(strand_seq)
    # the rolling-hash scan only needs an injective byte alphabet, and the
    # validated inputs are {., A, C, G, T} — raw bytes qualify, so the
    # 5-symbol encode pass is only materialised for the grouping fallback
    buf = np.concatenate(bufs)
    text_len = np.array([len(b) for b in bufs], dtype=np.int64)
    text_off = np.array(text_off_list, dtype=np.int64)

    # queries: per sequence, the start core (real bases at [h, 2h) of the
    # forward text) and the end core (real bases at [P-2h, P-h))
    q_starts = []
    for i, s in enumerate(sequences):
        fwd = text_off[2 * i]
        P = len(s.forward_seq)
        q_starts.append(fwd + h)          # start-pattern core (offset h in pattern)
        q_starts.append(fwd + P - 2 * h)  # end-pattern core (offset 0 in pattern)
    q_starts = np.array(q_starts, dtype=np.int64)

    # backend order: device grouping when opted in (the same
    # AUTOCYCLER_DEVICE_GROUPING switch as the k-mer index), then the native
    # rolling-hash scan, then the exact numpy grouping
    def strand_codes() -> np.ndarray:
        # the buf layout is per sequence (forward, reverse) — exactly what
        # Sequence.encoded_strands caches, so the grouping fallback reuses
        # the per-sequence encodings instead of re-encoding the whole buffer
        return np.concatenate(
            [c for s in sequences for c in s.encoded_strands()]) \
            if hasattr(sequences[0], "encoded_strands") else encode_bytes(buf)

    from .kmers import _resolve_use_jax
    use_jax = _resolve_use_jax(None)
    by_query = None
    if use_jax:
        try:
            by_query = _matches_by_query_grouped(
                strand_codes(), text_off, text_len, h, q_starts,
                use_jax=use_jax, threads=threads)
        except Exception as e:  # noqa: BLE001 — visible fallback, same
            # contract as the k-mer grouping dispatch
            import sys

            from ..utils.timing import record_device_failure
            what = (f"device end-repair grouping failed "
                    f"({type(e).__name__}: {e})")
            record_device_failure(what, exc=e)
            print(f"autocycler: {what}; falling back to host backend",
                  file=sys.stderr)
    if by_query is None:
        by_query = _matches_by_query_native(buf, text_off, text_len, h,
                                            q_starts)
    if by_query is None:
        by_query = _matches_by_query_grouped(strand_codes(), text_off,
                                             text_len, h, q_starts,
                                             use_jax=False, threads=threads)

    def best_candidate(q: int, core_offset: int) -> bytes:
        """Best non-overlapping (k-1)-byte candidate window for query q,
        whose core h-gram sits at ``core_offset`` within the pattern."""
        t_arr, p_arr = by_query[q]
        j_arr = p_arr - core_offset  # pattern start within the text
        valid = (j_arr >= 0) & (j_arr + overlap <= text_len[t_arr])
        t_v = t_arr[valid]
        j_v = j_arr[valid]
        keep = np.empty(len(t_v), dtype=bool)
        prev_text, prev_end = -1, -1
        for idx, (ti, ji) in enumerate(zip(t_v.tolist(), j_v.tolist())):
            if ti == prev_text and ji < prev_end:
                keep[idx] = False  # regex find_iter skips overlapping matches
                continue
            keep[idx] = True
            prev_text, prev_end = ti, ji + overlap
        starts = text_off[t_v[keep]] + j_v[keep]
        rows = buf[starts[:, None] + np.arange(overlap)]
        return _best_match_rows(rows)

    def repair_one(i: int) -> None:
        s = sequences[i]
        P = len(s.forward_seq)
        best_start = best_candidate(2 * i, h)
        best_end = best_candidate(2 * i + 1, 0)
        repaired = s.forward_seq.copy()
        repaired[:overlap] = np.frombuffer(best_start, dtype=np.uint8)
        repaired[P - overlap:] = np.frombuffer(best_end, dtype=np.uint8)
        s.forward_seq = repaired
        s.reverse_seq = reverse_complement_bytes(repaired)

    from ..utils import map_threaded
    map_threaded(repair_one, range(len(sequences)), threads)
