"""Sequence-end repair: replace dot padding with matching real sequence.

Parity target: reference compress.rs:202-270. Each padded sequence starts and
ends with half_k dots followed/preceded by half_k real bases; the reference
regex-matches that (k-1)-char dotted pattern against every sequence (both
strands) and substitutes the best match, defined as (1) fewest dots,
(2) highest occurrence count, (3) lexicographically first
(find_best_match, compress.rs:239-270). Regex ``find_iter`` yields
non-overlapping matches left-to-right, which we reproduce exactly.

TPU formulation: a pattern of h dots + h real bases matches text at offset j
iff text[j+h : j+2h] equals the h real bases — i.e. every match is an
occurrence of an h-gram. So one sort-based grouping of ALL h-grams of all
padded sequences (ops.kmers.group_windows) answers every pattern query at
once; candidate windows are then gathered from the byte buffer.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..models import Sequence
from ..utils import reverse_complement_bytes
from .encode import encode_bytes
from .kmers import group_windows


def _find_best_match(candidates: List[bytes]) -> bytes:
    """(fewest dots, most frequent, lexicographically first)
    (reference compress.rs:239-270)."""
    counts: Dict[bytes, int] = {}
    for c in candidates:
        counts[c] = counts.get(c, 0) + 1
    return min(candidates, key=lambda c: (c.count(b"."), -counts[c], c))


def sequence_end_repair(sequences: List[Sequence], k_size: int) -> None:
    """In-place repair of every sequence's dotted ends (compress.rs:202-236).

    Matches are searched in the ORIGINAL (pre-repair) sequences, like the
    reference's cloned all_seqs snapshot (compress.rs:209).
    """
    if not sequences:
        return
    h = k_size // 2
    if h == 0:
        return  # k=1: no padding, nothing to repair
    overlap = k_size - 1  # == 2h

    # text layout: per sequence, forward then reverse padded strands
    bufs = []
    text_off = []
    total = 0
    for s in sequences:
        for strand_seq in (s.forward_seq, s.reverse_seq):
            text_off.append(total)
            bufs.append(strand_seq)
            total += len(strand_seq)
    buf = np.concatenate(bufs)
    codes = encode_bytes(buf)
    text_len = np.array([len(b) for b in bufs], dtype=np.int64)
    text_off = np.array(text_off, dtype=np.int64)

    # all h-gram windows of every text
    win_count = text_len - h + 1
    woff = np.zeros(len(bufs), np.int64)
    woff[1:] = np.cumsum(win_count)[:-1]
    W = int(win_count.sum())
    wocc = np.arange(W, dtype=np.int64)
    wtext = np.searchsorted(woff, wocc, side="right") - 1
    wpos = wocc - woff[wtext]
    wstarts = text_off[wtext] + wpos

    order, gid_sorted = group_windows(codes, wstarts, h)
    win_gid = np.zeros(W, np.int64)
    win_gid[order] = gid_sorted
    G = int(gid_sorted[-1]) + 1 if W else 0
    gstart = np.zeros(G + 1, np.int64)
    np.add.at(gstart, gid_sorted + 1, 1)
    gstart = np.cumsum(gstart)

    def candidates_for(core_window: int, core_offset: int) -> List[bytes]:
        """All non-overlapping (k-1)-byte candidate windows containing the
        given core h-gram at ``core_offset`` within the pattern (h for the
        start pattern's trailing real bases, 0 for the end pattern's leading
        real bases)."""
        gid = win_gid[core_window]
        occ = order[gstart[gid]:gstart[gid + 1]]  # ascending => text asc, pos asc
        t = wtext[occ]
        p = wpos[occ]
        j = p - core_offset  # pattern start within the text
        valid = (j >= 0) & (j + overlap <= text_len[t])
        t, j = t[valid], j[valid]
        out: List[bytes] = []
        prev_text, prev_end = -1, -1
        for ti, ji in zip(t, j):
            if ti == prev_text and ji < prev_end:
                continue  # regex find_iter skips overlapping matches
            prev_text, prev_end = ti, ji + overlap
            start = text_off[ti] + ji
            out.append(buf[start:start + overlap].tobytes())
        return out

    for i, s in enumerate(sequences):
        fwd_text = 2 * i
        P = len(s.forward_seq)
        # start pattern: dots at [0,h), real core at [h,2h)
        start_core = woff[fwd_text] + h
        best_start = _find_best_match(candidates_for(int(start_core), h))
        # end pattern: real core at [P-2h, P-h), dots at [P-h, P)
        end_core = woff[fwd_text] + (P - 2 * h)
        best_end = _find_best_match(candidates_for(int(end_core), 0))

        repaired = s.forward_seq.copy()
        repaired[:overlap] = np.frombuffer(best_start, dtype=np.uint8)
        repaired[P - overlap:] = np.frombuffer(best_end, dtype=np.uint8)
        s.forward_seq = repaired
        s.reverse_seq = reverse_complement_bytes(repaired)
