"""Assemble a host UnitigGraph from the device k-mer index + chains.

This is the TPU-side replacement for UnitigGraph::from_kmer_graph
(reference unitig_graph.rs:36-48): chains come from ops.debruijn, unitig
sequences are gathered straight out of the padded input byte buffer (the
moral equivalent of the reference's raw-pointer k-mers, kmer_graph.rs:26-33,
without the unsafe), links are found by (k-1)-gram id equality instead of
hash-map joins (unitig_graph.rs:234-287), and overlap trimming
(unitig_graph.rs:289-293) happens implicitly by slicing half_k off both ends.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..models import Position, Sequence, Unitig, UnitigGraph, UnitigStrand
from ..utils import FORWARD, REVERSE, reverse_complement_bytes
from .debruijn import Chains, build_chains
from .kmers import KmerIndex, build_kmer_index


def unitig_graph_from_chains(index: KmerIndex, chains: Chains) -> UnitigGraph:
    graph = UnitigGraph(k_size=index.k)
    k, h = index.k, index.half_k

    # last byte of each unique k-mer's window (for chain sequence assembly);
    # any occurrence's bytes are the k-mer itself, so the representative works
    last_byte = index.buf[index.rep_byte + k - 1]

    C = chains.count
    fwd_start_gram = np.zeros(C, np.int64)
    fwd_end_gram = np.zeros(C, np.int64)
    rev_start_gram = np.zeros(C, np.int64)

    # batched position query for every chain head and reverse-complement tail
    query_kids = np.empty(2 * C, np.int64)
    for c in range(C):
        members = chains.chain(c)
        query_kids[2 * c] = members[0]
        query_kids[2 * c + 1] = index.rev_kid[members[-1]]
    positions = index.positions_for_kmers(query_kids) if C else {}

    def _mk_positions(kid: int) -> List[Position]:
        seq_idx, strand, pos = positions[int(kid)]
        ids = index.seq_ids[seq_idx]
        return [Position(int(i), bool(s), int(p))
                for i, s, p in zip(ids, strand, pos)]

    for c in range(C):
        members = chains.chain(c)
        head, tail = int(members[0]), int(members[-1])
        n = len(members)

        # untrimmed chain sequence: head k-mer bytes + last byte of each
        # following k-mer; trimming removes half_k from both ends
        head_bytes = index.buf[index.rep_byte[head]:index.rep_byte[head] + k]
        untrimmed = np.concatenate([head_bytes, last_byte[members[1:]]])
        trimmed = untrimmed[h:h + n].copy()

        unitig = Unitig(number=c + 1, forward_seq=trimmed)
        unitig.depth = float(index.depth[members].mean())
        unitig.forward_positions = _mk_positions(head)
        unitig.reverse_positions = _mk_positions(index.rev_kid[tail])
        graph.unitigs.append(unitig)

        fwd_start_gram[c] = index.prefix_gid[head]
        fwd_end_gram[c] = index.suffix_gid[tail]
        rev_start_gram[c] = index.prefix_gid[index.rev_kid[tail]]

    # rev_end_gram is the strand mirror of fwd_start_gram's matching rule;
    # matching uses the same three joins as the reference (unitig_graph.rs:253-285)
    by_fwd_start: dict = {}
    by_rev_start: dict = {}
    for c in range(C):
        by_fwd_start.setdefault(int(fwd_start_gram[c]), []).append(c)
        by_rev_start.setdefault(int(rev_start_gram[c]), []).append(c)
    rev_end_gram = [int(index.suffix_gid[index.rev_kid[int(chains.chain(c)[0])]])
                    for c in range(C)]

    for c in range(C):
        a = graph.unitigs[c]
        # a+ -> b+ (and strand twin b- -> a-)
        for j in by_fwd_start.get(int(fwd_end_gram[c]), []):
            b = graph.unitigs[j]
            a.forward_next.append(UnitigStrand(b, FORWARD))
            b.forward_prev.append(UnitigStrand(a, FORWARD))
            b.reverse_next.append(UnitigStrand(a, REVERSE))
            a.reverse_prev.append(UnitigStrand(b, REVERSE))
        # a+ -> b-
        for j in by_rev_start.get(int(fwd_end_gram[c]), []):
            b = graph.unitigs[j]
            a.forward_next.append(UnitigStrand(b, REVERSE))
            b.reverse_prev.append(UnitigStrand(a, FORWARD))
        # a- -> b+
        for j in by_fwd_start.get(rev_end_gram[c], []):
            b = graph.unitigs[j]
            a.reverse_next.append(UnitigStrand(b, FORWARD))
            b.forward_prev.append(UnitigStrand(a, REVERSE))

    graph.build_index()
    graph.renumber_unitigs()
    graph.check_links()
    return graph


def build_unitig_graph(sequences: List[Sequence], k: int,
                       use_jax=None) -> UnitigGraph:
    """Sequences (padded, end-repaired) -> compacted unitig graph."""
    from ..utils import log
    index = build_kmer_index(sequences, k, use_jax=use_jax)
    log.message(f"Graph contains {index.num_kmers} k-mers")
    log.message()
    chains = build_chains(index)
    return unitig_graph_from_chains(index, chains)
