"""Assemble a host UnitigGraph from the device k-mer index + chains.

This is the TPU-side replacement for UnitigGraph::from_kmer_graph
(reference unitig_graph.rs:36-48): chains come from ops.debruijn, unitig
sequences are gathered straight out of the padded input byte buffer (the
moral equivalent of the reference's raw-pointer k-mers, kmer_graph.rs:26-33,
without the unsafe), links are found by (k-1)-gram id equality instead of
hash-map joins (unitig_graph.rs:234-287), and overlap trimming
(unitig_graph.rs:289-293) happens implicitly by slicing half_k off both ends.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..models import PositionArray, Sequence, Unitig, UnitigGraph, UnitigStrand
from ..utils import FORWARD, REVERSE, reverse_complement_bytes
from .debruijn import Chains, build_chains
from .kmers import KmerIndex, build_kmer_index


def _link_pairs_dict(fwd_start_gram, rev_start_gram, fwd_end_gram,
                     rev_end_gram):
    """Python dict-of-lists link join — the original per-chain formulation,
    kept as the order oracle for :func:`_link_pairs` (the regression test
    asserts triple-for-triple equality, which pins GFA L-line order).
    Returns (src c, tgt j, join kind) triples: kind 0 = a+ -> b+,
    1 = a+ -> b-, 2 = a- -> b+."""
    C = len(fwd_start_gram)
    by_fwd_start: dict = {}
    by_rev_start: dict = {}
    for c in range(C):
        by_fwd_start.setdefault(int(fwd_start_gram[c]), []).append(c)
        by_rev_start.setdefault(int(rev_start_gram[c]), []).append(c)
    out = []
    for c in range(C):
        for j in by_fwd_start.get(int(fwd_end_gram[c]), []):
            out.append((c, j, 0))
        for j in by_rev_start.get(int(fwd_end_gram[c]), []):
            out.append((c, j, 1))
        for j in by_fwd_start.get(int(rev_end_gram[c]), []):
            out.append((c, j, 2))
    return out


def _link_pairs(fwd_start_gram, rev_start_gram, fwd_end_gram, rev_end_gram):
    """Vectorised argsort/searchsorted join over gram ids, replacing the
    per-chain dict loops. Emission order is identical to the dict join by
    construction: a stable argsort of the start grams lists, per gram,
    chain indices ascending (the dict built them ascending); the final
    stable sort on src restores per-chain order with the three join kinds'
    blocks in their original sequence. Returns (src, tgt, kind) arrays."""
    C = len(fwd_start_gram)
    if C == 0:
        z = np.zeros(0, np.int64)
        return z, z, z
    ord_f = np.argsort(fwd_start_gram, kind="stable")
    sorted_f = fwd_start_gram[ord_f]
    ord_r = np.argsort(rev_start_gram, kind="stable")
    sorted_r = rev_start_gram[ord_r]

    def join(sorted_keys, ord_, queries):
        lo = np.searchsorted(sorted_keys, queries, side="left")
        hi = np.searchsorted(sorted_keys, queries, side="right")
        cnt = hi - lo
        total = int(cnt.sum())
        off = np.zeros(C + 1, np.int64)
        np.cumsum(cnt, out=off[1:])
        pos = np.repeat(lo, cnt) + (np.arange(total) - np.repeat(off[:-1], cnt))
        return np.repeat(np.arange(C, dtype=np.int64), cnt), ord_[pos]

    src1, tgt1 = join(sorted_f, ord_f, fwd_end_gram)   # a+ -> b+
    src2, tgt2 = join(sorted_r, ord_r, fwd_end_gram)   # a+ -> b-
    src3, tgt3 = join(sorted_f, ord_f, rev_end_gram)   # a- -> b+
    src = np.concatenate([src1, src2, src3])
    tgt = np.concatenate([tgt1, tgt2, tgt3])
    kind = np.concatenate([np.zeros(len(src1), np.int64),
                           np.full(len(src2), 1, np.int64),
                           np.full(len(src3), 2, np.int64)])
    order = np.argsort(src, kind="stable")
    return src[order], tgt[order], kind[order]


def unitig_graph_from_chains(index: KmerIndex, chains: Chains) -> UnitigGraph:
    graph = UnitigGraph(k_size=index.k)
    k, h = index.k, index.half_k

    # last byte of each unique k-mer's window (for chain sequence assembly);
    # any occurrence's bytes are the k-mer itself, so the representative works
    last_byte = index.buf[index.rep_byte + k - 1]

    C = chains.count
    members_all = chains.members
    chain_off = chains.chain_off
    sizes = np.diff(chain_off)
    heads = members_all[chain_off[:-1]] if C else np.zeros(0, np.int64)
    tails = members_all[chain_off[1:] - 1] if C else np.zeros(0, np.int64)
    rev_tails = index.rev_kid[tails].astype(np.int64) if C else heads

    # ---- chain sequences, assembled in one pass over all chains ----
    # untrimmed chain sequence = head k-mer bytes + last byte of each
    # following k-mer; trimming removes half_k from both ends, so trimmed
    # byte i of a chain is the head window byte h+i while h+i < k and the
    # last byte of member i-h after that
    slot = np.arange(len(members_all), dtype=np.int64)
    # per-slot chain attributes come from np.repeat (sequential writes) —
    # measurably cheaper than materialising chain_of_slot and gathering
    # C-sized arrays through it
    pos_ic = slot - np.repeat(chain_off[:-1], sizes)
    from_head = pos_ic <= h
    head_byte_idx = (np.repeat(index.rep_byte[heads] + h, sizes)
                     + np.minimum(pos_ic, h))
    tail_byte = last_byte[members_all[np.maximum(slot - h, 0)]]
    seq_bytes = np.where(from_head, index.buf[head_byte_idx], tail_byte)

    depths = (np.add.reduceat(index.depth[members_all].astype(np.float64),
                              chain_off[:-1]) / sizes) if C else np.zeros(0)

    # batched position query for every chain head and reverse-complement
    # tail, in flat SoA form: per-chain PositionArrays are views into the
    # query result, and sequences are views into the chain byte block — the
    # construction loop allocates only the Unitig shells
    from ..utils.timing import substage
    if C:
        with substage("unitigs"):
            uniq, offs, seq_idx_f, strand_f, pos_f = index.positions_for_kmers_flat(
                np.concatenate([heads, rev_tails]))
            seqid_f = index.seq_ids[seq_idx_f].astype(np.int32, copy=False)
            strand_f = np.asarray(strand_f, bool)
            pos_f = np.asarray(pos_f, np.int64)
            h_at = np.searchsorted(uniq, heads)
            r_at = np.searchsorted(uniq, rev_tails)
            # batch shell construction: every per-chain slice bound becomes a
            # plain Python int up front (scalar-indexing numpy arrays inside
            # the loop costs ~3x the whole loop body)
            h_lo = offs[h_at].tolist()
            h_hi = offs[h_at + 1].tolist()
            r_lo = offs[r_at].tolist()
            r_hi = offs[r_at + 1].tolist()
            off_list = chain_off.tolist()
            depths_list = depths.tolist()
            unitigs = graph.unitigs
            for c in range(C):
                unitig = Unitig(number=c + 1,
                                forward_seq=seq_bytes[off_list[c]:off_list[c + 1]])
                unitig.depth = depths_list[c]
                unitig.forward_positions = PositionArray(
                    seqid_f[h_lo[c]:h_hi[c]], strand_f[h_lo[c]:h_hi[c]],
                    pos_f[h_lo[c]:h_hi[c]])
                unitig.reverse_positions = PositionArray(
                    seqid_f[r_lo[c]:r_hi[c]], strand_f[r_lo[c]:r_hi[c]],
                    pos_f[r_lo[c]:r_hi[c]])
                unitigs.append(unitig)

    fwd_start_gram = index.prefix_gid[heads].astype(np.int64)
    fwd_end_gram = index.suffix_gid[tails].astype(np.int64)
    rev_start_gram = index.prefix_gid[rev_tails].astype(np.int64)
    rev_end_gram = index.suffix_gid[index.rev_kid[heads]].astype(np.int64) \
        if C else fwd_start_gram

    # rev_end_gram is the strand mirror of fwd_start_gram's matching rule;
    # matching uses the same three joins as the reference
    # (unitig_graph.rs:253-285), vectorised — emission order identical to
    # the dict join (_link_pairs_dict, the tested oracle)
    with substage("links"):
        src, tgt, kind = _link_pairs(fwd_start_gram, rev_start_gram,
                                     fwd_end_gram, rev_end_gram)
        unitigs = graph.unitigs
        for c, j, g in zip(src.tolist(), tgt.tolist(), kind.tolist()):
            a = unitigs[c]
            b = unitigs[j]
            if g == 0:      # a+ -> b+ (and strand twin b- -> a-)
                a.forward_next.append(UnitigStrand(b, FORWARD))
                b.forward_prev.append(UnitigStrand(a, FORWARD))
                b.reverse_next.append(UnitigStrand(a, REVERSE))
                a.reverse_prev.append(UnitigStrand(b, REVERSE))
            elif g == 1:    # a+ -> b-
                a.forward_next.append(UnitigStrand(b, REVERSE))
                b.reverse_prev.append(UnitigStrand(a, FORWARD))
            else:           # a- -> b+
                a.reverse_next.append(UnitigStrand(b, FORWARD))
                b.forward_prev.append(UnitigStrand(a, REVERSE))

    graph.build_index()
    graph.renumber_unitigs()
    graph.check_links()
    return graph


def build_unitig_graph(sequences: List[Sequence], k: int,
                       use_jax=None, threads=None) -> UnitigGraph:
    """Sequences (padded, end-repaired) -> compacted unitig graph.
    ``threads`` flows into the k-mer grouping (the radix-partitioned
    parallel path engages above one worker on large inputs); ``use_jax``
    flows into grouping, adjacency AND chain-following, so a device run
    keeps the whole compress hot path on the accelerator. Results are
    bit-identical at every thread count and on every backend."""
    from ..utils import log
    index = build_kmer_index(sequences, k, use_jax=use_jax, threads=threads)
    log.message(f"Graph contains {index.num_kmers} k-mers")
    log.message()
    chains = build_chains(index, threads=threads, use_jax=use_jax)
    return unitig_graph_from_chains(index, chains)
