"""Exact k-mer grouping as a sort-based device kernel.

This replaces the reference's FxHashMap De Bruijn graph — the #1 hot loop
(reference kmer_graph.rs:86-134: two hash upserts per base, both strands) —
with a TPU-friendly formulation:

1. encode all padded sequences (both strands) as 5-symbol codes,
2. pack every k-window into ceil(k/10) int32 words (3 bits/symbol, most
   significant first, zero-filled tail) so word-tuple comparison equals
   byte-lexicographic k-mer comparison,
3. one stable lexsort groups identical k-mers; group ids ARE the
   lexicographic ranks (so the reference's sorted iteration,
   kmer_graph.rs:168-173, falls out for free),
4. (k-1)-gram ids, computed the same way, give De Bruijn adjacency by
   integer equality instead of hash probes (kmer_graph.rs:136-166).

Everything is exact — no fingerprint collisions — and deterministic. The
packing/sort runs through jax.numpy on the configured default device (TPU
when present); small inputs fall back to numpy to skip dispatch overhead.
"""

from __future__ import annotations

import functools
import os

from dataclasses import dataclass
from typing import List, Optional, Tuple, Union

import numpy as np

from ..utils.knobs import knob_int, knob_str

from .encode import encode_bytes

SYMS_PER_WORD = 10  # 3 bits per symbol in an int32 (numpy fallback packing)
# device packing is base-5: 13 symbols/word (5^13 < 2^31), so k=51 needs 4
# words instead of 6 — fewer sort operands, same lexicographic order
SYMS_PER_WORD_DEV = 13

# use_jax accepts True (direct device sort), "bucketed" (fixed-shape,
# persistently-cacheable device sort), "lsd" (multi-pass 2-operand stable
# sorts), "radix" (radix-partitioned buckets sharded across the mesh,
# fixed-shape per-shard sorts), False, or None (resolve via env)
UseJax = Union[bool, str, None]


def _resolve_use_jax(use_jax: UseJax) -> UseJax:
    """None resolves through AUTOCYCLER_DEVICE_GROUPING: a generic enable
    value ('1'/'true'/'yes'/'on') opts into the Pallas bitonic sort-network
    kernel (ops/sortnet.py) when a TPU answers the probe, else the bucketed
    XLA sort WHEN jax backend init is known-safe (the Pallas path on a host
    backend would run the network through the interpret-mode simulator,
    which at product scale is an effective hang; and with the probe timed
    out — or disabled without a platform pin — even "host" jax use can
    block in the plugin's backend init, so the native default is kept with
    a stderr note). 'pallas' / 'bucketed' / 'lsd' / 'radix' / 'direct'
    select a variant explicitly (benchmarks and tests); explicit disable
    spellings
    and '' keep the native/host default. Unrecognised values keep the
    default too, with a stderr note — guessing an operator's intent the
    expensive way ('off' enabling a ~170 s/sort tunnel path) is worse than
    ignoring a typo."""
    if use_jax is not None:
        return use_jax
    value = (knob_str("AUTOCYCLER_DEVICE_GROUPING") or "").strip().lower()
    if value in ("1", "true", "yes", "on"):
        from .distance import (device_attached, jax_backend_safe,
                               warn_backend_unsafe_once)
        # an explicit operator enable is worth a bounded wait on the probe
        # future (the background probe may still be attaching); the wait is
        # accounted under DEVICE_WAIT, never device_seconds
        if device_attached(wait=True):
            return "pallas"
        if jax_backend_safe():
            return "bucketed"
        # probe timed out / errored / disabled without a platform pin: the
        # plugin overrides JAX_PLATFORMS, so ANY jax-touching mode (even
        # the "host" bucketed sort) could hang in backend init — keep the
        # native/host default, loudly but once per process
        warn_backend_unsafe_once("device grouping")
        return False
    if value == "pallas":
        return "pallas"
    if value == "bucketed":
        return "bucketed"
    if value == "lsd":
        return "lsd"
    if value == "radix":
        return "radix"
    if value == "direct":
        return True
    if value not in ("", "0", "false", "no", "off", "disabled"):
        import sys
        print(f"autocycler: unrecognised AUTOCYCLER_DEVICE_GROUPING="
              f"{value!r}; keeping the host grouping default", file=sys.stderr)
    return False


def _num_words(k: int) -> int:
    return (k + SYMS_PER_WORD - 1) // SYMS_PER_WORD


def _pack_words_numpy(codes: np.ndarray, starts: np.ndarray, k: int):
    words = []
    for j in range(_num_words(k)):
        w = np.zeros(len(starts), dtype=np.int32)
        for t in range(SYMS_PER_WORD):
            idx = j * SYMS_PER_WORD + t
            w <<= 3
            if idx < k:
                w |= codes[starts + idx].astype(np.int32)
        words.append(w)
    return words


def _pack_and_rank_numpy(codes: np.ndarray, starts: np.ndarray, k: int):
    words = _pack_words_numpy(codes, starts, k)
    order = np.lexsort(tuple(reversed(words)))  # last key is primary in lexsort
    sorted_words = [w[order] for w in words]
    new_group = np.zeros(len(starts), dtype=bool)
    if len(starts):
        new_group[0] = True
        for w in sorted_words:
            new_group[1:] |= w[1:] != w[:-1]
    gid_sorted = np.cumsum(new_group, dtype=np.int64) - 1
    return order, gid_sorted


# ---------------------------------------------------------------------------
# Radix-partitioned parallel grouping (the KMC 2 / Gerbil shape: partition
# k-mers into disjoint leading-prefix buckets, then group each bucket
# independently). The leading base-5 radix of a window is a strict prefix of
# its first packed word, so ascending radix ranges are ascending k-mer
# ranges: per-bucket lexicographic ranks stitch into global ranks by adding
# bucket offsets, preserving the exact rank semantics ops.debruijn and
# ops.graph_build depend on. Buckets group concurrently — the per-bucket
# work is the native hash kernel (ctypes releases the GIL) or numpy's
# lexsort (also GIL-free) — and even single-threaded the partition wins:
# each bucket's hash table stays cache-resident instead of thrashing one
# giant table (measured ~2x on 12M windows before any thread scaling).
# ---------------------------------------------------------------------------

RADIX_SYMS = 6          # leading base-5 radix: 5**6 = 15625 keys fit uint16


def _resolve_threads(threads) -> int:
    return 1 if threads is None else max(1, int(threads))


def _effective_workers(threads: int) -> int:
    """Worker count actually worth spawning: more threads than cores only
    adds contention to the GIL-free numpy/native chunk work. An explicit
    AUTOCYCLER_GROUPING_EXECUTOR choice disables the core clamp — the
    operator (or the parity suite, on single-core CI) asked for that
    executor and gets the requested width."""
    if (knob_str("AUTOCYCLER_GROUPING_EXECUTOR") or "").strip():
        return max(1, threads)
    return max(1, min(threads, os.cpu_count() or 1))


def _radix_min_windows() -> int:
    """Below this window count the radix path's partition overhead outweighs
    the bucket wins; the single native/numpy call is used instead. Tests
    (and tiny-machine operators) override via AUTOCYCLER_RADIX_MIN_WINDOWS."""
    return int(knob_int("AUTOCYCLER_RADIX_MIN_WINDOWS"))


def _host_radix_enabled(n: int, k: int, workers: int, partitions) -> bool:
    """Host dispatch policy: explicit ``partitions`` or
    AUTOCYCLER_HOST_GROUPING=radix force the radix path; =native/=numpy
    force the serial backends; otherwise radix engages when more than one
    worker is usable and the input is large enough to amortise the
    partition pass."""
    if k < 1 or n == 0:
        return False
    if partitions is not None:
        return True
    mode = (knob_str("AUTOCYCLER_HOST_GROUPING") or "").strip().lower()
    if mode == "radix":
        return True
    if mode in ("native", "numpy"):
        return False
    return workers > 1 and n >= _radix_min_windows()


def _radix_slab(codes: np.ndarray, starts: np.ndarray, k: int,
                lo: int, hi: int):
    """Stable key-sort of one contiguous window slab: returns (slab order as
    GLOBAL window indices, per-key counts). The key is the first
    min(RADIX_SYMS, k) symbols packed base-5 into uint16 — numpy's stable
    argsort on uint16 is an O(n) LSD radix sort."""
    r = min(RADIX_SYMS, k)
    sl = starts[lo:hi]
    key = codes[sl].astype(np.uint16)
    for i in range(1, r):
        key *= np.uint16(5)
        key += codes[sl + i]
    order = np.argsort(key, kind="stable")
    counts = np.bincount(key, minlength=5 ** r)
    return order + lo, counts


def _radix_partition(codes: np.ndarray, starts: np.ndarray, k: int,
                     workers: int, n_parts: int):
    """Stable O(N) partition of windows into at most ``n_parts`` contiguous
    radix-key ranges with roughly equal window counts.

    Returns (part, offs): ``part`` is a window permutation ordering windows
    by ascending radix key (original order preserved inside equal keys);
    chunk c owns ``part[offs[c]:offs[c+1]]``. Chunk boundaries always align
    with key boundaries, so equal k-mers never straddle chunks — per-chunk
    group ids stitch to global lexicographic ranks by offset addition.

    Slabs of the window range are key-sorted concurrently; per-chunk output
    concatenates each slab's key-range segment in slab order, which keeps
    the global permutation stable (slab s precedes slab s+1 originally).
    """
    n = len(starts)
    r = min(RADIX_SYMS, k)
    n_keys = 5 ** r
    n_slabs = max(1, min(workers, n // (1 << 16) or 1))
    bounds = np.linspace(0, n, n_slabs + 1).astype(np.int64)
    jobs = [(int(lo), int(hi)) for lo, hi in zip(bounds[:-1], bounds[1:])
            if hi > lo]
    if len(jobs) > 1 and workers > 1:
        from ..utils.pool import get_executor
        slabs = list(get_executor(workers).map(
            lambda j: _radix_slab(codes, starts, k, *j), jobs))
    else:
        slabs = [_radix_slab(codes, starts, k, *j) for j in jobs]

    counts = np.stack([c for _, c in slabs])          # [S, n_keys]
    cum_slab = np.cumsum(counts, axis=1)
    cum_total = np.cumsum(counts.sum(axis=0))
    n_parts = max(1, min(int(n_parts), n_keys))
    targets = (np.arange(1, n_parts) * n) // n_parts
    cut = np.searchsorted(cum_total, targets, side="left")
    cut = np.unique(np.append(cut, n_keys - 1))       # key index ending each chunk

    part = np.empty(n, np.int64)
    offs = [0]
    pos = 0
    cursor = np.zeros(len(slabs), np.int64)
    for key_end in cut:
        for s, (order_s, _) in enumerate(slabs):
            b = int(cum_slab[s][key_end])
            seg = order_s[cursor[s]:b]
            part[pos:pos + len(seg)] = seg
            pos += len(seg)
            cursor[s] = b
        if pos > offs[-1]:                            # drop empty chunks
            offs.append(pos)
    return part, np.array(offs, np.int64)


def _radix_chunk_job(codes: np.ndarray, chunk_starts: np.ndarray, k: int):
    """Group one radix bucket: (local grouped order, local gid_sorted,
    per-group depth, group-start positions in the sorted view). Runs the
    fused native hash kernel when available (its table stays cache-resident
    at bucket size), else the numpy lexsort."""
    from .. import native
    res = native.group_kmers_full(codes, chunk_starts, k) \
        if native.available() else None
    if res is not None:
        gid_l, o = res
        gid_sorted = gid_l[o]
    else:
        o, gid_sorted = _pack_and_rank_numpy(codes, chunk_starts, k)
    m = len(chunk_starts)
    change = np.empty(m, bool)
    change[0] = True
    np.not_equal(gid_sorted[1:], gid_sorted[:-1], out=change[1:])
    gstart = np.flatnonzero(change)
    depth = np.diff(np.append(gstart, m))
    return o, gid_sorted, depth, gstart


# shared operand for forked process-pool workers (set by _chunk_pool_map
# immediately before the fork; children inherit it copy-on-write, so the
# codes buffer is never pickled per chunk)
_PROC_CODES: Optional[np.ndarray] = None


def _radix_chunk_job_proc(args):
    chunk_starts, k = args
    return _radix_chunk_job(_PROC_CODES, chunk_starts, k)


def _chunk_pool_map(codes: np.ndarray, chunk_starts_list, k: int,
                    workers: int):
    """Map _radix_chunk_job over buckets. Default executor is a thread pool
    (the chunk work — native ctypes calls and numpy sorts — releases the
    GIL); AUTOCYCLER_GROUPING_EXECUTOR=process switches to a forked process
    pool for workloads where the GIL still binds."""
    if workers <= 1 or len(chunk_starts_list) <= 1:
        return [_radix_chunk_job(codes, cs, k) for cs in chunk_starts_list]
    mode = (knob_str("AUTOCYCLER_GROUPING_EXECUTOR") or "").strip().lower()
    if mode == "process":
        import multiprocessing as mp
        from concurrent.futures import ProcessPoolExecutor
        global _PROC_CODES
        try:
            ctx = mp.get_context("fork")
        except ValueError:
            ctx = None            # no fork on this platform: thread pool below
        if ctx is not None:
            _PROC_CODES = codes
            try:
                with ProcessPoolExecutor(max_workers=workers,
                                         mp_context=ctx) as pool:
                    return list(pool.map(
                        _radix_chunk_job_proc,
                        [(cs, k) for cs in chunk_starts_list]))
            finally:
                _PROC_CODES = None
    from ..utils.pool import get_executor
    return list(get_executor(workers).map(
        lambda cs: _radix_chunk_job(codes, cs, k), chunk_starts_list))


def _radix_rank_stats(codes: np.ndarray, starts: np.ndarray, k: int,
                      workers: int, partitions=None):
    """Radix-partitioned grouping with per-group statistics:
    (gid, order, depth, first_occ) — gid/order exactly as
    :func:`group_windows_full`, plus per-group occurrence counts and the
    smallest occurrence index per group, computed bucket-locally (no global
    O(N) bincount pass)."""
    from ..utils.timing import substage

    n = len(starts)
    if partitions is None:
        partitions = min(256, max(16, workers * 16))
    with substage("partition"):
        part, offs = _radix_partition(codes, starts, k, workers,
                                      max(1, int(partitions)))
    chunks = [part[offs[c]:offs[c + 1]] for c in range(len(offs) - 1)]
    with substage("sort"):
        chunk_starts = [starts[idx] for idx in chunks]
        results = _chunk_pool_map(codes, chunk_starts, k, workers)
    with substage("stitch"):
        order = np.empty(n, np.int64)
        gid_sorted = np.empty(n, np.int64)
        depth_parts, first_parts = [], []
        g_off = 0
        for c, (idx, (o, g_l, d_l, gs_l)) in enumerate(zip(chunks, results)):
            lo, hi = offs[c], offs[c + 1]
            sorted_idx = idx[o]
            order[lo:hi] = sorted_idx
            np.add(g_l, g_off, out=gid_sorted[lo:hi])
            depth_parts.append(d_l)
            first_parts.append(sorted_idx[gs_l])
            g_off += len(d_l)
        depth = np.concatenate(depth_parts) if depth_parts \
            else np.zeros(0, np.int64)
        first_occ = np.concatenate(first_parts) if first_parts \
            else np.zeros(0, np.int64)
        gid = np.empty(n, np.int64)
        gid[order] = gid_sorted
    return gid, order, depth, first_occ


def _derive_stats(gid: np.ndarray, order: np.ndarray):
    """(depth, first_occ) from a (gid, order) pair, for backends that do not
    produce them bucket-locally."""
    n = len(order)
    if n == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    gid_sorted = gid[order]
    change = np.empty(n, bool)
    change[0] = True
    np.not_equal(gid_sorted[1:], gid_sorted[:-1], out=change[1:])
    gstart = np.flatnonzero(change)
    return np.diff(np.append(gstart, n)), order[gstart]


def _pack_words_traced(codes_d, starts_d, k: int, real=None):
    """Traced base-5 window packing: 13 symbols per int32 word (5^13 < 2^31),
    most significant first, zero-filled tail — word-tuple comparison equals
    byte-lexicographic k-mer comparison, with ceil(k/13) words (k=51 → 4
    words vs 6 for the 3-bit packing). ``real`` (optional bool mask) forces
    pad windows' words to int32 max so they sort after every real window
    (base-5 words stay below 5^13 - 1 < 2^31 - 1, so the value is out of
    band)."""
    import jax.numpy as jnp

    words = []
    n_words = (k + SYMS_PER_WORD_DEV - 1) // SYMS_PER_WORD_DEV
    for j in range(n_words):
        w = jnp.zeros(starts_d.shape[0], dtype=jnp.int32)
        for t in range(SYMS_PER_WORD_DEV):
            idx = j * SYMS_PER_WORD_DEV + t
            w = w * 5
            if idx < k:
                w = w + codes_d[starts_d + idx].astype(jnp.int32)
        if real is not None:
            w = jnp.where(real, w, jnp.int32(2**31 - 1))
        words.append(w)
    return words


def _gids_from_sorted_words(sorted_words):
    """Adjacent-difference group ids over lexicographically sorted word
    tuples."""
    import jax.numpy as jnp

    n = sorted_words[0].shape[0]
    new_group = jnp.zeros(n, dtype=bool).at[0].set(True)
    for w in sorted_words:
        new_group = new_group.at[1:].set(new_group[1:] | (w[1:] != w[:-1]))
    return jnp.cumsum(new_group) - 1


def _rank_windows_traced(codes_d, starts_d, k: int, real=None):
    """Traced pack + lexsort + group-id body shared by the direct and
    bucketed jax paths (one variadic sort over all words + the index)."""
    import jax.numpy as jnp

    words = _pack_words_traced(codes_d, starts_d, k, real=real)
    order = jnp.lexsort(tuple(reversed(words)))
    gid_sorted = _gids_from_sorted_words([w[order] for w in words])
    return order, gid_sorted


def _rank_windows_traced_lsd(codes_d, starts_d, k: int):
    """LSD multi-pass ranking: one stable 2-operand sort_key_val per word,
    least-significant word first — after the last (most-significant) pass
    the carried index permutation is the stable lexicographic order. Avoids
    the variadic sort entirely: each pass sorts ONE int32 key with the
    permutation as its value, the cheapest sort XLA can run, at the price of
    one gather per pass to re-key the permuted windows."""
    import jax.numpy as jnp
    from jax import lax

    words = _pack_words_traced(codes_d, starts_d, k)
    n = starts_d.shape[0]
    order = jnp.arange(n, dtype=jnp.int32)
    for w in reversed(words):
        _, order = lax.sort((w[order], order), num_keys=1, is_stable=True)
    gid_sorted = _gids_from_sorted_words([w[order] for w in words])
    return order, gid_sorted


def _pack_and_rank_jax(codes: np.ndarray, starts: np.ndarray, k: int):
    import jax.numpy as jnp

    from ..utils.timing import device_dispatch
    with device_dispatch("k-mer grouping sort"):
        order, gid_sorted = _rank_windows_traced(
            jnp.asarray(codes), jnp.asarray(starts.astype(np.int32)), k)
        return np.asarray(order), np.asarray(gid_sorted)


@functools.lru_cache(maxsize=None)
def _lsd_rank_fn(kk: int):
    import jax

    return jax.jit(functools.partial(_rank_windows_traced_lsd, k=kk))


# network block size for the Pallas grouping path; tests shrink it so the
# interpret-mode network stays small
_PALLAS_BLOCK_ROWS = 1024


@functools.lru_cache(maxsize=None)
def _pallas_rank_fn(N: int, codes_bucket: int, kk: int, interpret: bool,
                    block_rows: int):
    """One compiled (padded-window-count, codes-bucket, k) executable for
    the Pallas sort-network grouping: base-5 packing, the bitonic network
    (ops/sortnet.py) and the group-id extraction fuse into ONE dispatch.
    N is a power of two; pad windows pack to INT32_MAX words (the
    ``real`` mask in _pack_words_traced) so they sort strictly last, and
    the original index rides the network as the least-significant word —
    stability and a total order by construction."""
    import jax
    import jax.numpy as jnp

    from .sortnet import run_network

    def run(codes_d, starts_d, n_real):
        real = jnp.arange(N) < n_real
        words = _pack_words_traced(codes_d, starts_d, kk, real=real)
        idx = jnp.arange(N, dtype=jnp.int32)
        out = run_network([w.astype(jnp.int32) for w in words] + [idx],
                          block_rows=block_rows, interpret=interpret)
        sorted_words, order = out[:-1], out[-1]
        gid_sorted = _gids_from_sorted_words(sorted_words)
        return order, gid_sorted

    return jax.jit(run)


def _pack_and_rank_jax_pallas(codes: np.ndarray, starts: np.ndarray, k: int):
    """Fixed-shape Pallas sort-network grouping (the round-5 device
    grouping kernel): windows pad to the next power of two, codes to the
    shared bucket sizes, so each (N, codes-bucket, k) compiles once into
    the persistent cache. Pad entries sort last and are sliced off."""
    import jax
    import jax.numpy as jnp

    from .sortnet import _ceil_pow2

    n = len(starts)
    block_rows = _PALLAS_BLOCK_ROWS
    N = max(_ceil_pow2(n), block_rows * 128)   # >= one network block
    interpret_guard = 1 << 18
    if jax.default_backend() != "tpu" and N > interpret_guard:
        # the interpret-mode simulator at product scale is an effective
        # hang; raising here reaches group_windows_full's visible host
        # fallback instead
        raise RuntimeError(
            f"pallas sort network of {N} elements requested on the "
            f"'{jax.default_backend()}' backend: interpret mode is only "
            "viable for small inputs")
    cb = _bucket_size(len(codes))
    pad_starts = np.zeros(N, np.int64)
    pad_starts[:n] = starts
    pad_codes = np.zeros(cb, codes.dtype)
    pad_codes[:len(codes)] = codes
    interpret = jax.default_backend() != "tpu"
    from ..utils.timing import device_dispatch
    with device_dispatch("k-mer grouping sort (pallas network)"):
        order, gid_sorted = _pallas_rank_fn(N, cb, k, interpret,
                                            block_rows)(
            jnp.asarray(pad_codes), jnp.asarray(pad_starts.astype(np.int32)),
            jnp.int32(n))
        return np.asarray(order)[:n], np.asarray(gid_sorted)[:n]


def _pack_and_rank_jax_lsd(codes: np.ndarray, starts: np.ndarray, k: int):
    import jax.numpy as jnp

    from ..utils.timing import device_dispatch
    with device_dispatch("k-mer grouping sort (lsd)"):
        order, gid_sorted = _lsd_rank_fn(k)(
            jnp.asarray(codes), jnp.asarray(starts.astype(np.int32)))
        return np.asarray(order), np.asarray(gid_sorted)


def _bucket_size(n: int, floor: int = 1 << 16) -> int:
    """Fixed padded sizes so the expensive device sort compiles once per
    bucket into the persistent cache (XLA's variadic sort costs minutes to
    compile per shape on the current platform): powers of 4 from 64k."""
    b = floor
    while b < n:
        b <<= 2
    return b


@functools.lru_cache(maxsize=None)
def _bucketed_rank_fn(bucket: int, codes_bucket: int, kk: int):
    """One compiled (window-bucket, codes-bucket, k) sort executable. The
    real window count is a traced argument, so every input size within the
    bucket reuses the same executable (and the persistent compilation cache
    serves it across processes)."""
    import jax
    import jax.numpy as jnp

    def run(codes_d, starts_d, n_real):
        real = jnp.arange(bucket) < n_real
        return _rank_windows_traced(codes_d, starts_d, kk, real=real)

    return jax.jit(run)


def _pack_and_rank_jax_bucketed(codes: np.ndarray, starts: np.ndarray, k: int):
    """Fixed-shape variant of :func:`_pack_and_rank_jax`: windows AND codes
    are padded to bucket sizes so device sorts compile once per bucket; pad
    windows sort to the end, leaving the real windows' (order, gid) results
    unchanged (pad entries are sliced away before returning)."""
    import jax.numpy as jnp

    n = len(starts)
    b = _bucket_size(n)
    cb = _bucket_size(len(codes))
    pad_starts = np.zeros(b, np.int64)
    pad_starts[:n] = starts
    pad_codes = np.zeros(cb, codes.dtype)
    pad_codes[:len(codes)] = codes
    from ..utils.timing import device_dispatch
    with device_dispatch("k-mer grouping sort (bucketed)"):
        order, gid_sorted = _bucketed_rank_fn(b, cb, k)(
            jnp.asarray(pad_codes), jnp.asarray(pad_starts.astype(np.int32)),
            jnp.int32(n))
        return np.asarray(order)[:n], np.asarray(gid_sorted)[:n]


# floor for the per-row padded bucket of the radix-sharded device path —
# much smaller than the global _bucket_size floor because each row holds
# only ~1/P of the windows
_RADIX_DEVICE_ROW_FLOOR = 1 << 12


@functools.lru_cache(maxsize=None)
def _radix_sharded_rank_fn(rows: int, bucket: int, codes_bucket: int,
                           kk: int):
    """One compiled (rows, row-bucket, codes-bucket, k) executable for the
    radix-sharded device grouping. Each row is one radix bucket, vmapped
    over the leading axis; when the inputs arrive sharded across the mesh,
    GSPMD partitions the vmap so every device sorts only its rows. Fixed
    shapes all around, so the expensive variadic sort compiles once per
    bucket class into the persistent cache — and each sort operand is
    ``bucket`` elements instead of the whole window set."""
    import jax
    import jax.numpy as jnp

    def run(codes_d, starts_mat, n_real):
        def one(starts_row, m):
            real = jnp.arange(bucket) < m
            return _rank_windows_traced(codes_d, starts_row, kk, real=real)

        return jax.vmap(one)(starts_mat, n_real)

    return jax.jit(run)


@functools.lru_cache(maxsize=None)
def _radix_sharded_stats_fn(rows: int, bucket: int, codes_bucket: int,
                            kk: int):
    """The fused pack+rank+group-stats executable: one jitted per-bucket
    kernel that, in the SAME dispatch as the sort, scatters per-group depth
    (segment count) and first-occurrence (segment min of the stable order)
    on device — so the caller's statistics need no host _derive_stats pass
    and the bucket data makes exactly one host->device round trip.

    Scatter indices clamp pad rows into an extra slot (index ``bucket``):
    pad windows pack to INT32_MAX and sort last, so their gid would land
    exactly at n_groups — inside the real range only when a row is full,
    but the extra slot makes the no-corruption argument unconditional."""
    import jax
    import jax.numpy as jnp

    int32_max = jnp.int32(2**31 - 1)

    def run(codes_d, starts_mat, n_real):
        def one(starts_row, m):
            pos = jnp.arange(bucket)
            real = pos < m
            order, gid_sorted = _rank_windows_traced(codes_d, starts_row, kk,
                                                     real=real)
            # `real` indexes the SORTED view here: pads sort strictly last,
            # so sorted positions >= m are exactly the pad entries
            gid_c = jnp.where(real, gid_sorted, bucket)
            depth = jnp.zeros(bucket + 1, jnp.int32).at[gid_c].add(
                jnp.where(real, 1, 0))
            # stable sort => within a group the carried original indices
            # ascend, so the segment-min of `order` is the group's first
            # occurrence (row-local index)
            first_local = jnp.full(bucket + 1, int32_max, jnp.int32) \
                .at[gid_c].min(jnp.where(real, order.astype(jnp.int32),
                                         int32_max))
            n_groups = jnp.where(m > 0,
                                 gid_sorted[jnp.maximum(m - 1, 0)] + 1, 0)
            return (order, gid_sorted, depth[:bucket], first_local[:bucket],
                    n_groups.astype(jnp.int32))

        return jax.vmap(one)(starts_mat, n_real)

    return jax.jit(run)


def _radix_device_layout(codes: np.ndarray, starts: np.ndarray, k: int,
                         threads=None):
    """Host-side partition + fixed-shape padding shared by the radix-sharded
    device paths: the same base-5 partition as the parallel host path splits
    windows into equal-count key-aligned buckets, which pad to one shared
    fixed shape and stack to [rows, bucket] (rows padded to a device
    multiple so the leading axis shards across the mesh). Returns
    ``(part, offs, rows, b, cb, starts_mat, n_real, pad_codes)``."""
    import jax

    from ..utils.timing import substage

    workers = _effective_workers(_resolve_threads(threads))
    n_dev = max(1, len(jax.devices()))
    with substage("partition"):
        part, offs = _radix_partition(codes, starts, k, workers,
                                      max(n_dev, 8))
    C = len(offs) - 1
    rows = -(-C // n_dev) * n_dev          # pad row count to a device multiple
    sizes = np.diff(offs)
    b = _bucket_size(int(sizes.max()) if C else 1,
                     floor=_RADIX_DEVICE_ROW_FLOOR)
    cb = _bucket_size(len(codes))
    starts_mat = np.zeros((rows, b), np.int32)
    n_real = np.zeros(rows, np.int32)
    for c in range(C):
        lo, hi = int(offs[c]), int(offs[c + 1])
        starts_mat[c, :hi - lo] = starts[part[lo:hi]]
        n_real[c] = hi - lo
    pad_codes = np.zeros(cb, codes.dtype)
    pad_codes[:len(codes)] = codes
    return part, offs, rows, b, cb, starts_mat, n_real, pad_codes


def _pack_and_rank_jax_radix(codes: np.ndarray, starts: np.ndarray, k: int,
                             threads=None):
    """Radix-partitioned device grouping: the same host-side base-5
    partition as the parallel host path splits windows into equal-count
    key-aligned buckets; buckets pad to one shared fixed shape, stack to
    [rows, bucket] and sort per row on device, with the leading axis laid
    across the mesh (parallel/mesh.shard_leading_axis) when more than one
    device is attached. Per-bucket (order, gid) results stitch to global
    lexicographic ranks on the host exactly as in the host radix path."""
    import jax.numpy as jnp

    from ..parallel.mesh import shard_leading_axis
    from ..utils.timing import device_dispatch, substage

    n = len(starts)
    part, offs, rows, b, cb, starts_mat, n_real, pad_codes = \
        _radix_device_layout(codes, starts, k, threads)
    C = len(offs) - 1

    with device_dispatch("k-mer grouping sort (radix-sharded)"), \
            substage("sort"):
        codes_d, mat_d, nr_d = shard_leading_axis(
            jnp.asarray(pad_codes), starts_mat, n_real)
        orders, gids = _radix_sharded_rank_fn(rows, b, cb, k)(
            codes_d, mat_d, nr_d)
        orders = np.asarray(orders)
        gids = np.asarray(gids)

    with substage("stitch"):
        order = np.empty(n, np.int64)
        gid_sorted = np.empty(n, np.int64)
        g_off = 0
        for c in range(C):
            lo, hi = int(offs[c]), int(offs[c + 1])
            m = hi - lo
            idx = part[lo:hi]
            # real windows sort before pad entries, so the first m sorted
            # positions are exactly the bucket's windows (row-local indices)
            o_row = orders[c, :m].astype(np.int64)
            order[lo:hi] = idx[o_row]
            gid_sorted[lo:hi] = gids[c, :m].astype(np.int64) + g_off
            g_off += int(gids[c, m - 1]) + 1
    return order, gid_sorted


def _radix_rank_stats_device(codes: np.ndarray, starts: np.ndarray, k: int,
                             threads=None):
    """Device counterpart of :func:`_radix_rank_stats`: one fused jitted
    kernel per bucket row produces (order, gid, depth, first_occ) with a
    single host->device upload per bucket and a single download of the
    final group ids/stats — no host _derive_stats pass. Bit-identical to
    the host radix path: the partition is shared, the sort is stable, and
    the device segment ops mirror the bucket-local statistics exactly."""
    import jax.numpy as jnp

    from ..parallel.mesh import shard_leading_axis
    from ..utils.timing import device_dispatch, substage

    n = len(starts)
    part, offs, rows, b, cb, starts_mat, n_real, pad_codes = \
        _radix_device_layout(codes, starts, k, threads)
    C = len(offs) - 1

    with device_dispatch("k-mer grouping sort+stats (radix-sharded)"), \
            substage("sort"):
        codes_d, mat_d, nr_d = shard_leading_axis(
            jnp.asarray(pad_codes), starts_mat, n_real)
        orders, gids, depths, firsts, ngroups = \
            _radix_sharded_stats_fn(rows, b, cb, k)(codes_d, mat_d, nr_d)
        orders = np.asarray(orders)
        gids = np.asarray(gids)
        depths = np.asarray(depths)
        firsts = np.asarray(firsts)
        ngroups = np.asarray(ngroups)

    with substage("stitch"):
        order = np.empty(n, np.int64)
        gid_sorted = np.empty(n, np.int64)
        depth_parts, first_parts = [], []
        g_off = 0
        for c in range(C):
            lo, hi = int(offs[c]), int(offs[c + 1])
            m = hi - lo
            idx = part[lo:hi]
            o_row = orders[c, :m].astype(np.int64)
            order[lo:hi] = idx[o_row]
            gid_sorted[lo:hi] = gids[c, :m].astype(np.int64) + g_off
            g_c = int(ngroups[c])
            depth_parts.append(depths[c, :g_c].astype(np.int64))
            # first_local holds row-local ORIGINAL window indices (the
            # partition preserves original order within equal keys, so the
            # row-local minimum maps to the global minimum through idx)
            first_parts.append(idx[firsts[c, :g_c].astype(np.int64)])
            g_off += g_c
        depth = np.concatenate(depth_parts) if depth_parts \
            else np.zeros(0, np.int64)
        first_occ = np.concatenate(first_parts) if first_parts \
            else np.zeros(0, np.int64)
        gid = np.empty(n, np.int64)
        gid[order] = gid_sorted
    return gid, order, depth, first_occ


def group_windows_full(codes: np.ndarray, starts: np.ndarray, k: int,
                       use_jax: UseJax = None, threads=None,
                       partitions: Optional[int] = None
                       ) -> Tuple[np.ndarray, np.ndarray]:
    """Group length-k windows of ``codes`` beginning at ``starts``.

    Returns (gid, order): ``gid[i]`` is window i's dense group id (group ids
    are lexicographic ranks); ``order`` is the stable permutation grouping
    windows by gid. Owns ALL backend dispatch: jax opt-in, the
    radix-partitioned parallel host path (``threads`` > 1 on large inputs,
    or forced via ``partitions`` / AUTOCYCLER_HOST_GROUPING=radix), the
    fused native kernel, and the numpy lexsort fallback.
    """
    n = len(starts)
    if n == 0:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    if k == 0:
        # zero-length windows are all identical (k=1's (k-1)-grams)
        return np.zeros(n, np.int64), np.arange(n, dtype=np.int64)
    # XLA's variadic sort has multi-minute compile times on the current
    # TPU platform, so the device path is opt-in (AUTOCYCLER_DEVICE_GROUPING
    # or use_jax="bucketed" for the fixed-shape persistently-cached
    # variant); the native hash grouping below is the fast default.
    use_jax = _resolve_use_jax(use_jax)
    if use_jax == "direct":      # explicit per-shape variadic sort
        use_jax = True
    if isinstance(use_jax, str) and use_jax not in ("bucketed", "lsd",
                                                    "pallas", "radix"):
        # an explicit unknown mode is a programming error, not an operator
        # typo (those are handled in _resolve_use_jax): falling through to
        # the per-shape variadic sort would silently hit its multi-minute
        # compile wall
        raise ValueError(f"unknown device grouping mode {use_jax!r}")
    if use_jax:
        from ..utils.jaxcache import configure_compile_cache
        configure_compile_cache()   # AUTOCYCLER_COMPILE_CACHE opt-in: the
        # variadic sorts / Pallas networks persist across processes
        try:
            if use_jax == "pallas":
                order, gid_sorted = _pack_and_rank_jax_pallas(codes, starts, k)
            elif use_jax == "bucketed":
                order, gid_sorted = _pack_and_rank_jax_bucketed(codes, starts, k)
            elif use_jax == "lsd":
                order, gid_sorted = _pack_and_rank_jax_lsd(codes, starts, k)
            elif use_jax == "radix":
                order, gid_sorted = _pack_and_rank_jax_radix(codes, starts, k,
                                                             threads)
            else:
                order, gid_sorted = _pack_and_rank_jax(codes, starts, k)
            gid = np.empty(n, np.int64)
            gid[order] = gid_sorted
            return gid, order
        except Exception as e:  # noqa: BLE001 — any device failure must
            # still fall back to the exact host path (the guarantee), but
            # VISIBLY: a silent swallow would mask real device bugs behind a
            # correct host answer (VERDICT r2 item 7).
            import sys

            from ..utils.timing import record_device_failure
            what = (f"device k-mer grouping failed "
                    f"({type(e).__name__}: {e})")
            record_device_failure(what, exc=e)
            print(f"autocycler: {what}; falling back to host backend",
                  file=sys.stderr)
    workers = _effective_workers(_resolve_threads(threads))
    if _host_radix_enabled(n, k, workers, partitions):
        gid, order, _, _ = _radix_rank_stats(codes, starts, k, workers,
                                             partitions)
        return gid, order
    # fused native pack + hash-grouping kernel (O(n) vs the comparison sort)
    from .. import native
    host_mode = (knob_str("AUTOCYCLER_HOST_GROUPING") or "").strip().lower()
    if host_mode != "numpy" and native.available():
        result = native.group_kmers_full(codes, starts, k)
        if result is not None:
            return result
    order, gid_sorted = _pack_and_rank_numpy(codes, starts, k)
    gid = np.empty(n, np.int64)
    gid[order] = gid_sorted
    return gid, order


def group_windows(codes: np.ndarray, starts: np.ndarray, k: int,
                  use_jax: UseJax = None, threads=None,
                  partitions: Optional[int] = None
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """(order, gid_sorted) view of :func:`group_windows_full` — ``order`` is
    the stable permutation sorting windows lexicographically and
    ``gid_sorted[i]`` the group id of window ``order[i]``."""
    gid, order = group_windows_full(codes, starts, k, use_jax, threads,
                                    partitions)
    return order, gid[order]


def group_windows_stats(codes: np.ndarray, starts: np.ndarray, k: int,
                        use_jax: UseJax = None, threads=None,
                        partitions: Optional[int] = None):
    """:func:`group_windows_full` plus per-group statistics:
    (gid, order, depth, first_occ) where ``depth[g]`` is group g's
    occurrence count and ``first_occ[g]`` its smallest window index. The
    radix path produces the statistics bucket-locally (cache-resident,
    concurrent); other backends derive them with one O(N) pass — the same
    cost callers previously paid via a global bincount."""
    n = len(starts)
    if n and k > 0:
        use_jax_r = _resolve_use_jax(use_jax)
        workers = _effective_workers(_resolve_threads(threads))
        if use_jax_r == "radix":
            # the fused device kernel produces the statistics in the same
            # dispatch as the sort (no host _derive_stats pass); any device
            # failure falls back to the exact host paths, visibly
            from ..utils.jaxcache import configure_compile_cache
            configure_compile_cache()
            try:
                return _radix_rank_stats_device(codes, starts, k, threads)
            except Exception as e:  # noqa: BLE001 — host fallback guarantee
                import sys

                from ..utils.timing import record_device_failure
                what = (f"device k-mer grouping stats failed "
                        f"({type(e).__name__}: {e})")
                record_device_failure(what, exc=e)
                print(f"autocycler: {what}; falling back to host backend",
                      file=sys.stderr)
                use_jax = False
        if not use_jax_r and _host_radix_enabled(n, k, workers, partitions):
            return _radix_rank_stats(codes, starts, k, workers, partitions)
    gid, order = group_windows_full(codes, starts, k, use_jax, threads,
                                    partitions)
    depth, first_occ = _derive_stats(gid, order)
    return gid, order, depth, first_occ


@dataclass
class KmerIndex:
    """Struct-of-arrays replacement for the reference's KmerGraph
    (kmer_graph.rs:73-182), built by :func:`build_kmer_index`.

    Occurrence layout: per input sequence, first its L forward windows
    (window start p = Position.pos on the padded forward strand), then its
    L reverse windows. The partner of forward window p is reverse window
    L-1-p (and vice versa), mirroring how the reference adds each k-mer on
    both strands (kmer_graph.rs:103-133).

    Two backends fill this: the fused native kernel stores only per-FORWARD-
    window ids (``fwd_gid``) and answers occurrence queries by scanning them
    (every reverse-strand occurrence is the mirror of a forward window of the
    rc k-mer); the numpy fallback materialises the full per-occurrence arrays
    (``occ_kid``/``occ_sorted``/...). Both answer :meth:`positions_for_kmers`
    identically.
    """

    k: int
    half_k: int
    # concatenated padded byte buffer: per sequence, forward then reverse
    buf: np.ndarray
    seq_ids: np.ndarray          # (S,) external sequence ids
    seq_len: np.ndarray          # (S,) unpadded lengths
    fwd_byte_off: np.ndarray     # (S,) offset of forward padded seq in buf
    rev_byte_off: np.ndarray     # (S,)
    occ_off: np.ndarray          # (S,) occurrence-index base (2*L per seq)
    # per unique k-mer (U,):
    depth: np.ndarray            # occurrence count
    rep_byte: np.ndarray         # byte offset in buf of one occurrence's window
    rev_kid: np.ndarray          # (U,) id of the reverse-complement k-mer
    prefix_gid: np.ndarray       # (U,) (k-1)-gram id of the first k-1 bases
    suffix_gid: np.ndarray       # (U,) (k-1)-gram id of the last k-1 bases
    out_count: np.ndarray        # (U,) number of unique k-mers overlapping on the right
    in_count: np.ndarray         # (U,) ... on the left
    succ: np.ndarray             # (U,) the unique right-neighbour when out_count==1
    first_pos: np.ndarray        # (U,) bool: any occurrence at window 0
    # fused-native backend: per forward window (n_f = sum(L)), seq-major
    fwd_gid: Optional[np.ndarray] = None
    # numpy-fallback backend: per occurrence (M = 2 * sum(L))
    occ_kid: Optional[np.ndarray] = None
    first_occ: Optional[np.ndarray] = None   # smallest occurrence per group
    occ_sorted: Optional[np.ndarray] = None  # occurrences grouped by kid
    group_start: Optional[np.ndarray] = None  # (U+1,) boundaries

    # ---- occurrence coordinate helpers (vectorised) ----

    def occ_coords(self, occ: np.ndarray):
        """occurrence indices -> (seq_index, strand(bool), local window pos)."""
        seq_idx = np.searchsorted(self.occ_off, occ, side="right") - 1
        rel = occ - self.occ_off[seq_idx]
        L = self.seq_len[seq_idx]
        strand = rel < L
        pos = np.where(strand, rel, rel - L)
        return seq_idx, strand, pos

    def kmer_occurrences(self, kid: int) -> np.ndarray:
        return self.occ_sorted[self.group_start[kid]:self.group_start[kid + 1]]

    def positions_for_kmers(self, kids: np.ndarray):
        """{kid: (seq_idx, strand(bool), pos)} for every requested k-mer, in
        occurrence order (seq ascending; forward windows before reverse
        windows within a sequence; position ascending)."""
        uniq, offsets, seq_idx, strand, pos = self.positions_for_kmers_flat(kids)
        return {int(kid): (seq_idx[offsets[i]:offsets[i + 1]],
                           strand[offsets[i]:offsets[i + 1]],
                           pos[offsets[i]:offsets[i + 1]])
                for i, kid in enumerate(uniq)}

    def positions_for_kmers_flat(self, kids: np.ndarray):
        """Flat form of :meth:`positions_for_kmers` for bulk consumers:
        (uniq_kids, offsets, seq_idx, strand, pos) where kid ``uniq_kids[i]``
        owns rows ``offsets[i]:offsets[i+1]`` of the three parallel arrays
        (same per-kid occurrence order as the dict form)."""
        kids = np.unique(np.asarray(kids, dtype=np.int64))
        if self.occ_sorted is not None:
            per_kid = [self.occ_coords(self.kmer_occurrences(int(kid)))
                       for kid in kids]
            counts = np.array([len(t[0]) for t in per_kid], np.int64)
            offsets = np.zeros(len(kids) + 1, np.int64)
            np.cumsum(counts, out=offsets[1:])
            if len(per_kid):
                return (kids, offsets,
                        np.concatenate([t[0] for t in per_kid]),
                        np.concatenate([t[1] for t in per_kid]),
                        np.concatenate([t[2] for t in per_kid]))
            empty = np.zeros(0, np.int64)
            return kids, offsets, empty, empty.astype(bool), empty

        # fused backend: one scan over the forward-window ids. A forward
        # window of group g is a forward occurrence of g AND the mirror of a
        # reverse occurrence of rc(g) at pos L-1-q.
        U = self.num_kmers
        queried = np.zeros(U, bool)
        queried[kids] = True
        need = np.zeros(U, bool)
        need[kids] = True
        need[self.rev_kid[kids]] = True
        fwd_win_off = np.zeros(len(self.seq_len) + 1, np.int64)
        np.cumsum(self.seq_len, out=fwd_win_off[1:])
        from .. import native
        hits = native.collect_marked(self.fwd_gid, need.view(np.uint8)) \
            if native.available() else None
        if hits is None:
            hits = np.flatnonzero(need[self.fwd_gid])
        hg = self.fwd_gid[hits].astype(np.int64)
        seq_idx = np.searchsorted(fwd_win_off, hits, side="right") - 1
        q = hits - fwd_win_off[seq_idx]
        rk = self.rev_kid[hg]
        m_fwd = queried[hg]
        m_rev = queried[rk]
        kid_all = np.concatenate([hg[m_fwd], rk[m_rev]])
        seq_all = np.concatenate([seq_idx[m_fwd], seq_idx[m_rev]])
        strand_all = np.concatenate([np.ones(int(m_fwd.sum()), bool),
                                     np.zeros(int(m_rev.sum()), bool)])
        pos_all = np.concatenate(
            [q[m_fwd], self.seq_len[seq_idx[m_rev]] - 1 - q[m_rev]])
        order = np.lexsort((pos_all, ~strand_all, seq_all, kid_all))
        kid_sorted = kid_all[order]
        offsets = np.searchsorted(kid_sorted, np.concatenate([kids, [U]]))
        offsets[-1] = len(kid_sorted)
        return (kids, offsets, seq_all[order], strand_all[order],
                pos_all[order])

    @property
    def num_kmers(self) -> int:
        return len(self.depth)


@functools.lru_cache(maxsize=None)
def _adjacency_fn(bucket: int, gram_bucket: int):
    """One compiled (U-bucket, gram-bucket) executable for the adjacency
    segment ops: bincounts become scatter-adds, the successor table a
    scatter-max (`.at[p].max(arange)` over ascending indices equals numpy's
    last-write-wins `succ_by_gram[prefix_gid] = arange(U)` bit for bit),
    and the three gathers fuse into the same dispatch. Pad rows scatter
    into the extra slot ``gram_bucket`` so a full gram range (G ==
    gram_bucket) cannot be corrupted."""
    import jax
    import jax.numpy as jnp

    def run(prefix_d, suffix_d, n_real):
        real = jnp.arange(bucket) < n_real
        p = jnp.where(real, prefix_d, gram_bucket)
        s = jnp.where(real, suffix_d, gram_bucket)
        one = jnp.where(real, 1, 0).astype(jnp.int32)
        cnt_prefix = jnp.zeros(gram_bucket + 1, jnp.int32).at[p].add(one)
        cnt_suffix = jnp.zeros(gram_bucket + 1, jnp.int32).at[s].add(one)
        succ_by_gram = jnp.full(gram_bucket + 1, -1, jnp.int32) \
            .at[p].max(jnp.where(real, jnp.arange(bucket, dtype=jnp.int32),
                                 jnp.int32(-1)))
        out_count = cnt_prefix[s]
        in_count = cnt_suffix[p]
        succ = succ_by_gram[s]
        return out_count, in_count, succ

    return jax.jit(run)


def _adjacency_jax(prefix_gid: np.ndarray, suffix_gid: np.ndarray, G: int):
    """Device adjacency: one upload of the two gram-id vectors, one fused
    dispatch of the segment ops, one download of (out_count, in_count,
    succ). Shapes pad to buckets so the executable compiles once per bucket
    class; the pad tail is sliced off before returning."""
    import jax.numpy as jnp

    from ..utils.timing import device_dispatch

    U = len(prefix_gid)
    b = _bucket_size(max(U, 1), floor=_RADIX_DEVICE_ROW_FLOOR)
    gb = _bucket_size(max(G, 1), floor=_RADIX_DEVICE_ROW_FLOOR)
    pad_p = np.zeros(b, np.int32)
    pad_p[:U] = prefix_gid
    pad_s = np.zeros(b, np.int32)
    pad_s[:U] = suffix_gid
    with device_dispatch("adjacency segment ops",
                         bytes_moved=2.0 * b * 4 + 3.0 * b * 4):
        out_c, in_c, succ = _adjacency_fn(b, gb)(
            jnp.asarray(pad_p), jnp.asarray(pad_s), jnp.int32(U))
        out_count = np.asarray(out_c)[:U].astype(np.int64)
        in_count = np.asarray(in_c)[:U].astype(np.int64)
        succ = np.asarray(succ)[:U].astype(np.int64)
    return out_count, in_count, succ


def _adjacency(prefix_gid: np.ndarray, suffix_gid: np.ndarray, G: int,
               workers: int = 1, use_jax: bool = False):
    """Neighbour counts over UNIQUE k-mers (next_kmers/prev_kmers semantics,
    kmer_graph.rs:136-166) by (k-1)-gram id equality. With ``use_jax`` the
    segment ops run as one fused jitted device kernel
    (:func:`_adjacency_jax`), any failure falling back here visibly; on
    host the bincounts and gathers chunk over the shared pool (utils.pool)
    above one worker — bit-identical by construction (disjoint output
    ranges; integer count sums are order-independent)."""
    if use_jax and len(prefix_gid):
        try:
            return _adjacency_jax(prefix_gid, suffix_gid, G)
        except Exception as e:  # noqa: BLE001 — host fallback guarantee
            import sys

            from ..utils.timing import record_device_failure
            what = f"device adjacency failed ({type(e).__name__}: {e})"
            record_device_failure(what, exc=e)
            print(f"autocycler: {what}; falling back to host segment ops",
                  file=sys.stderr)
    from ..utils.pool import parallel_bincount, parallel_gather
    U = len(prefix_gid)
    cnt_prefix = parallel_bincount(prefix_gid, G, workers)
    cnt_suffix = parallel_bincount(suffix_gid, G, workers)
    out_count = parallel_gather(cnt_prefix, suffix_gid, workers)
    in_count = parallel_gather(cnt_suffix, prefix_gid, workers)
    succ_by_gram = np.full(G, -1, np.int64)
    # the scatter stays serial: duplicate gram ids overwrite in index order
    # (last write wins) and a chunked scatter would race on that order
    succ_by_gram[prefix_gid] = np.arange(U)
    succ = parallel_gather(succ_by_gram, suffix_gid, workers)
    return out_count, in_count, succ


def build_kmer_index(sequences, k: int, use_jax: UseJax = None,
                     use_fused: Optional[bool] = None,
                     threads=None) -> KmerIndex:
    """Build the k-mer index from Sequence objects (padded, with bytes).

    Parity notes: every k-window of every padded sequence on both strands is
    an occurrence (reference kmer_graph.rs:103-133 — exactly L windows per
    strand because the padding is half_k per side); k-mers that would start a
    sequence are flagged (Kmer::first_position, kmer_graph.rs:57-60); right
    and left neighbour counts replace next_kmers/prev_kmers probing
    (kmer_graph.rs:136-166).

    Backends: the fused native kernel (native/seqkernel.cpp
    sk_occ_index_build, k <= 55) produces every array in one pass and is the
    single-worker default; with ``threads`` > 1 on large inputs the
    radix-partitioned parallel grouping path takes over (same arrays, built
    from per-bucket statistics). The numpy/jax grouping pipeline below is
    the exact fallback and parity oracle (use_fused=False forces it).
    """
    half_k = k // 2
    S = len(sequences)
    seq_ids = np.array([s.id for s in sequences], dtype=np.int32)
    seq_len = np.array([s.length for s in sequences], dtype=np.int64)
    for s in sequences:
        # L windows of length k per strand only fit when the padding is
        # exactly half_k per side (len + 2*(k//2) bytes). With smaller
        # padding the final windows read past the sequence buffer — the
        # native kernel would return per-process heap garbage, silently.
        if len(s.forward_seq) != s.length + 2 * half_k:
            raise ValueError(
                f"sequence {s.id} is padded for half_k="
                f"{(len(s.forward_seq) - s.length) // 2}, not k={k}'s "
                f"half_k={half_k}; rebuild it with Sequence.with_seq(..., "
                f"{half_k})")

    bufs, fwd_off, rev_off = [], np.zeros(S, np.int64), np.zeros(S, np.int64)
    total = 0
    for i, s in enumerate(sequences):
        fwd_off[i] = total
        bufs.append(s.forward_seq)
        total += len(s.forward_seq)
        rev_off[i] = total
        bufs.append(s.reverse_seq)
        total += len(s.reverse_seq)
    buf = np.concatenate(bufs) if bufs else np.zeros(0, np.uint8)

    occ_off = np.zeros(S, np.int64)
    if S > 1:
        occ_off[1:] = np.cumsum(2 * seq_len)[:-1]
    M = int(2 * seq_len.sum())

    use_jax = _resolve_use_jax(use_jax)
    workers = _effective_workers(_resolve_threads(threads))
    # streamed two-pass disk-spill grouping (stream/: KMC 2-style signature
    # bins + global rank merge) takes over the whole grouping stage when
    # enabled — the fused/in-memory paths below stay the parity oracle
    from ..stream import resolve_stream_mode
    stream_on = bool(M) and k > 1 and resolve_stream_mode(M, k)
    if use_fused is None:
        # the single fused native pass wins single-threaded; with usable
        # extra workers on a large input the radix-partitioned grouping
        # pipeline below beats it (concurrent cache-resident buckets)
        use_fused = (not stream_on and not use_jax
                     and not _host_radix_enabled(M, k, workers, None))
    from .. import native
    if not stream_on and use_fused and M and native.available():
        # the kernel translates ASCII -> symbols inline; no encode pass
        res = native.build_occ_index(buf, fwd_off, rev_off, seq_len, k)
        if res is not None:
            U, G = res["U"], res["G"]
            fwd_gid, rev_kid = res["fwd_gid"], res["rev_kid"]
            # window-0 occurrences: forward window 0 per sequence, and
            # reverse window 0 (= mirror of the LAST forward window)
            fwd_win_off = np.zeros(S + 1, np.int64)
            np.cumsum(seq_len, out=fwd_win_off[1:])
            first_pos = np.zeros(U, bool)
            first_pos[fwd_gid[fwd_win_off[:-1]]] = True
            first_pos[rev_kid[fwd_gid[fwd_win_off[1:] - 1]]] = True
            from ..utils.timing import substage
            with substage("adjacency"):
                out_count, in_count, succ = _adjacency(
                    res["prefix_gid"], res["suffix_gid"], G, workers)
            return KmerIndex(
                k=k, half_k=half_k, buf=buf, seq_ids=seq_ids, seq_len=seq_len,
                fwd_byte_off=fwd_off, rev_byte_off=rev_off, occ_off=occ_off,
                depth=res["depth"], rep_byte=res["rep_byte"], rev_kid=rev_kid,
                prefix_gid=res["prefix_gid"], suffix_gid=res["suffix_gid"],
                out_count=out_count, in_count=in_count, succ=succ,
                first_pos=first_pos, fwd_gid=fwd_gid)

    # per-sequence cached both-strand encodings (models.sequence caches the
    # forward encode + arithmetic code-space revcomp, so repeated index
    # builds and other consumers never encode the same bytes twice); the
    # concatenation matches buf's (forward, reverse) per-sequence layout
    strand_codes = []
    for s in sequences:
        enc = getattr(s, "encoded_strands", None)
        if enc is not None:
            fwd_c, rev_c = enc()
        else:               # duck-typed sequence stand-ins in tests
            fwd_c = encode_bytes(s.forward_seq)
            rev_c = encode_bytes(s.reverse_seq)
        strand_codes.append(fwd_c)
        strand_codes.append(rev_c)
    codes = np.concatenate(strand_codes) if strand_codes \
        else encode_bytes(buf)

    # ---- k-mer grouping ----
    # streamed path first when enabled: disk-spill bins bound the grouping
    # working set; any spill failure — write faults, quarantined bins (torn
    # tails, count mismatches, bad RLE runs, unsupported spill record
    # formats), writer-lane errors — degrades VISIBLY to the in-memory path
    stats = None
    if stream_on:
        from ..stream import stream_group_windows_stats
        from ..utils.misc import AutocyclerError
        from ..utils.resilience import record_degrade
        try:
            from ..utils.timing import substage
            with substage("stream-kmers"):
                stats = stream_group_windows_stats(
                    codes, seq_len, fwd_off, rev_off, occ_off, k,
                    use_jax=use_jax, threads=threads)
        except (AutocyclerError, OSError) as e:
            record_degrade("stream-kmers", "stream", "in-memory",
                           f"{type(e).__name__}: {e}")

    starts = None
    if stats is None:
        # byte start of every occurrence window, built per contiguous strand
        # run (avoids materialising seq/strand/pos arrays of size M)
        start_runs = []
        for i in range(S):
            L_i = int(seq_len[i])
            start_runs.append(fwd_off[i] + np.arange(L_i, dtype=np.int64))
            start_runs.append(rev_off[i] + np.arange(L_i, dtype=np.int64))
        starts = np.concatenate(start_runs) if start_runs \
            else np.zeros(0, np.int64)
        # per-window ids come back in ORIGINAL order (no scatter needed to
        # reconstruct occ_kid); dispatch policy lives in group_windows_full
        stats = group_windows_stats(codes, starts, k, use_jax, threads)
    gid, order, depth, first_occ = stats
    occ_kid = gid.astype(np.int32)
    U = len(depth)
    depth = depth.astype(np.int64, copy=False)
    # occurrences grouped by kid; stable grouping keeps occurrence order
    # inside each group ascending
    group_start = np.zeros(U + 1, np.int64)
    np.cumsum(depth, out=group_start[1:])

    # first-position flag: only the two window-0 occurrences per sequence
    # (forward occ_off[s], reverse occ_off[s] + L) can have pos == 0
    first_pos = np.zeros(U, bool)
    if M:
        window0 = np.concatenate([occ_off, occ_off + seq_len])
        first_pos[occ_kid[window0]] = True

    # reverse-complement partner: partner occurrence of the first occurrence
    seq_idx_f = np.searchsorted(occ_off, first_occ, side="right") - 1
    rel_f = first_occ - occ_off[seq_idx_f]
    L_f = seq_len[seq_idx_f]
    strand_f = rel_f < L_f
    pos_f = np.where(strand_f, rel_f, rel_f - L_f)
    partner = occ_off[seq_idx_f] + np.where(strand_f, L_f + (L_f - 1 - pos_f),
                                            L_f - 1 - pos_f)
    rev_kid = occ_kid[partner]

    # ---- (k-1)-gram ids for adjacency ----
    # Adjacency only ever counts UNIQUE k-mers per gram (next_kmers probes
    # the k-mer set, not occurrences — kmer_graph.rs:136-166), so it
    # suffices to group the 2U gram instances at the unique k-mers'
    # representative windows: the prefix gram starts at the representative
    # byte offset, the suffix gram one byte later.
    if starts is not None:
        rep_byte = starts[first_occ]
    else:
        # streamed path never materialised the M-sized starts array; the
        # representative byte offsets follow from the occurrence layout
        from ..stream import occ_byte_starts
        rep_byte = occ_byte_starts(first_occ, seq_len, fwd_off, rev_off,
                                   occ_off)
    gram_starts = np.concatenate([rep_byte, rep_byte + 1])
    gorder, ggid_sorted = group_windows(codes, gram_starts, k - 1, use_jax,
                                        threads)
    gram_gid = np.zeros(len(gram_starts), np.int64)
    gram_gid[gorder] = ggid_sorted
    G = int(ggid_sorted[-1]) + 1 if len(gram_starts) else 0
    prefix_gid = gram_gid[:U]
    suffix_gid = gram_gid[U:]

    from ..utils.timing import substage
    with substage("adjacency"):
        out_count, in_count, succ = _adjacency(prefix_gid, suffix_gid, G,
                                               workers,
                                               use_jax=bool(use_jax))

    return KmerIndex(
        k=k, half_k=half_k, buf=buf, seq_ids=seq_ids, seq_len=seq_len,
        fwd_byte_off=fwd_off, rev_byte_off=rev_off, occ_off=occ_off,
        depth=depth, rep_byte=rep_byte, rev_kid=rev_kid,
        prefix_gid=prefix_gid, suffix_gid=suffix_gid,
        out_count=out_count, in_count=in_count, succ=succ, first_pos=first_pos,
        occ_kid=occ_kid, first_occ=first_occ, occ_sorted=order,
        group_start=group_start)
