"""MFU / peak-fraction accounting for the device kernels.

Every throughput number this framework reports (Gcells/s for the match-grid
kernels, GB/s for sorts) is convertible to hardware utilisation; this module
owns the conversion so the bench artifacts and docs can't drift (VERDICT r4
item 3: "491 Gcells/s is unanchored without it").

Peak numbers are for ONE TPU v5e (v5litepod) chip, from the public spec
(also tabulated in jax-ml.github.io/scaling-book):

- MXU: 197 TFLOP/s bf16, 394 TOP/s int8.
- VPU: 8 lanes x 128 sublanes x 4 ALUs x ~0.94 GHz clock ~= 3.85 T int32
  op/s (elementwise).
- HBM: 819 GB/s.

Work-per-cell accounting (what each kernel usefully does per grid cell):

- MXU ±1-matmul grid (ops/dotplot_pallas.py match_grid_mxu): each cell is a
  2k-deep dot product = 2 * 2k = 4k FLOPs (multiply + accumulate over 2k
  ±1 elements). The == 2k compare and count-reduce are O(1)/cell noise.
- VPU word-compare grid (match_grid): each cell is W = ceil(k/16) int32
  compares + (W - 1) ands + ~1 add in the count reduction ~= 2W ops.
- Device sorts (k-mer grouping): comparison sorts are bandwidth-bound, so
  the anchor is effective HBM traffic: each pass reads + writes the key and
  value streams (4 B each), i.e. 16 B per element per pass.
"""

from __future__ import annotations

V5E_MXU_BF16_FLOPS = 197e12
V5E_MXU_INT8_OPS = 394e12
V5E_VPU_INT_OPS = 8 * 128 * 4 * 0.94e9     # ~3.85e12
V5E_HBM_BYTES = 819e9


def mxu_grid_mfu(rate_gcells: float, k: int, int8: bool = False) -> dict:
    """±1-matmul match grid: Gcells/s -> {flops, pct_peak}. Each cell is a
    2k-deep MAC = 4k FLOPs."""
    flops = rate_gcells * 1e9 * 4.0 * k
    peak = V5E_MXU_INT8_OPS if int8 else V5E_MXU_BF16_FLOPS
    return {"tflops": round(flops / 1e12, 2),
            "pct_peak": round(100.0 * flops / peak, 1)}


def vpu_grid_mfu(rate_gcells: float, k: int) -> dict:
    """Word-compare match grid: Gcells/s -> {int32 Top/s, pct of VPU peak}.
    Each cell is ~2W elementwise int32 ops, W = ceil(k/16)."""
    W = (k + 15) // 16
    ops = rate_gcells * 1e9 * 2.0 * W
    return {"tops": round(ops / 1e12, 2),
            "pct_peak": round(100.0 * ops / V5E_VPU_INT_OPS, 1)}


def kernel_rates(kernels: dict) -> dict:
    """Anchor per-kernel dispatch telemetry (utils.timing.device_kernel_snapshot:
    ``{kernel: {phase: {count, total_s, flops?, bytes?}}}``) against hardware
    peaks. Prefers the steady phase (first-call includes XLA compile, so its
    rate says nothing about the hardware); falls back to first when a kernel
    only ever dispatched once. Returns ``{kernel: {phase, count, total_s,
    mean_s, tflops?, pct_peak_bf16?, gb_per_s?, pct_peak_hbm?}}`` — rate keys
    appear only where the dispatch site declared useful work."""
    out: dict = {}
    for kernel, phases in kernels.items():
        stats = phases.get("steady") or phases.get("first")
        phase = "steady" if "steady" in phases else "first"
        if not stats or not stats.get("count"):
            continue
        total = stats.get("total_s", 0.0)
        row = {"phase": phase, "count": stats["count"],
               "total_s": round(total, 6),
               "mean_s": round(total / stats["count"], 6)}
        if total > 0 and stats.get("flops"):
            flops_rate = stats["flops"] / total
            row["tflops"] = round(flops_rate / 1e12, 3)
            row["pct_peak_bf16"] = round(
                100.0 * flops_rate / V5E_MXU_BF16_FLOPS, 2)
        if total > 0 and stats.get("bytes"):
            byte_rate = stats["bytes"] / total
            row["gb_per_s"] = round(byte_rate / 1e9, 2)
            row["pct_peak_hbm"] = round(
                100.0 * byte_rate / V5E_HBM_BYTES, 2)
        out[kernel] = row
    return out


def sort_bandwidth(n_elements: int, n_passes: int, seconds: float,
                   n_arrays: int = 2) -> dict:
    """Multi-pass device sort: effective HBM traffic -> {GB/s, pct of HBM
    peak}. Each pass reads + writes ``n_arrays`` parallel int32 streams
    (8 B per array per element per pass) — 2 for a key+value sort, W+1 for
    the grouping network's W key words + index. ``n_elements`` should be
    the PADDED element count the kernel actually moves. A lower bound on
    real traffic (ignores scratch), so pct_peak is conservative."""
    if seconds <= 0:
        return {"gb_per_s": 0.0, "pct_peak": 0.0}
    bytes_moved = 8.0 * n_arrays * n_elements * n_passes
    rate = bytes_moved / seconds
    return {"gb_per_s": round(rate / 1e9, 1),
            "pct_peak": round(100.0 * rate / V5E_HBM_BYTES, 1)}
