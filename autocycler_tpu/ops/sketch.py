"""Minimizer-sketch contig distance: bottom-s MinHash over windowed
minimizers, compared as one batched device Jaccard/containment grid.

The exact path (ops.distance) scales with total unitig content: the
membership matrix is contigs × unitigs and the intersection contraction
is O(n² · U). Sketching (minimizers per minimap/Li 2016, signature
partitioning per KMC 2) reduces every contig to a FIXED-size vector:

1. k-mers are taken over the 5-symbol code space (ops.encode, ``.ACGT``
   → 0..4) and base-5 packed into uint64 (k ≤ 27 fits exactly), so the
   pack is a bijection and no dot-containing window ever contributes;
2. each packed k-mer is mixed with splitmix64 and folded to uint32 (JAX
   has no uint64 without x64 — the grid kernel must compare 32-bit
   values), canonicalised as min(forward hash, aligned reverse-complement
   hash) so a contig and its reverse complement sketch identically;
3. a sliding window of ``w`` consecutive k-mer positions keeps only each
   window's minimum hash (the minimizers), and the sorted-unique
   minimizer set is truncated to its ``s`` smallest values (bottom-s
   MinHash). Sorting ascending makes ``s``-truncation prefix-stable:
   a sketch at s' < s is exactly the first s' entries of the sketch at s.

Sketches are stacked into an ``(n_contigs, s)`` uint32 matrix (rows
sorted ascending, padded with ``SENTINEL``), which is exactly the shape
JAX wants: pairwise intersection counts are sorted-merge lookups via
``searchsorted``, ``vmap``ped over all pairs in one device dispatch. The
host numpy oracle runs the same integer algorithm, so device and host
intersection counts are bit-identical and the float conversion is one
shared expression (mirroring ops.distance's contract).

The distance is the same asymmetric containment shape as the exact path:
``d[a, b] = 1 - |sketch(a) ∩ sketch(b)| / |sketch(a)|`` — an estimator
of the unitig-length containment the exact path computes, converted
through the identical UPGMA/cutoff machinery in commands/cluster.py.
The exact path remains the oracle: below the auto threshold and in
parity tests, clustering decisions must match.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .encode import encode_both_strands

# uint32 max doubles as the pad value: real hashes equal to it are dropped
# during sketching, so a sentinel cell can never match a query
SENTINEL = np.uint32(0xFFFFFFFF)

MAX_K = 27      # 5**27 < 2**63: base-5 pack of a k-mer fits uint64 exactly

# device-dispatch thresholds over the stacked sketch-matrix element count
# (n_contigs * s), mirroring ops.distance's pair: above the JAX threshold
# the batched grid wins on any backend; between the two the probe future
# is consulted non-blockingly (host grid while pending, bit-identical)
_JAX_THRESHOLD = 4096 * 1024
_TPU_THRESHOLD = 1 << 16

_SPLITMIX_GAMMA = np.uint64(0x9E3779B97F4A7C15)
_SPLITMIX_M1 = np.uint64(0xBF58476D1CE4E5B9)
_SPLITMIX_M2 = np.uint64(0x94D049BB133111EB)


def sketch_params() -> Tuple[int, int, int]:
    """(k, w, s) from the AUTOCYCLER_SKETCH_* knobs, clamped to sane
    ranges (k to [4, MAX_K] so the base-5 pack stays exact, w/s to >= 1)."""
    from ..utils.knobs import knob_int
    k = min(max(int(knob_int("AUTOCYCLER_SKETCH_K")), 4), MAX_K)
    w = max(int(knob_int("AUTOCYCLER_SKETCH_W")), 1)
    s = max(int(knob_int("AUTOCYCLER_SKETCH_S")), 1)
    return k, w, s


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Vectorised splitmix64 finaliser (uint64 wrap-around arithmetic)."""
    z = x + _SPLITMIX_GAMMA
    z = (z ^ (z >> np.uint64(30))) * _SPLITMIX_M1
    z = (z ^ (z >> np.uint64(27))) * _SPLITMIX_M2
    return z ^ (z >> np.uint64(31))


def _pack_poly(codes: np.ndarray, k: int, reverse: bool = False) -> np.ndarray:
    """Base-5 pack of every k-mer of one code strand, evaluated in
    O(log k) array passes instead of k.

    ``reverse=False``: P[i] = sum_j codes[i+j] * 5**(k-1-j) — the naive
    left-to-right pack loop's polynomial, bit-identical by associativity.
    ``reverse=True``: R[i] = sum_j codes[i+j] * 5**j — the mirrored
    polynomial, which over complemented codes equals the pack of the
    reverse-complement k-mer aligned to the forward position.

    Both use square-and-multiply over the concatenation monoid
    ``concat(A_a, B_b)[i] = A[i] * 5**b + B[i+a]`` (coefficients swap
    sides when reversed); values stay < 5**MAX_K < 2**63, so uint64
    arithmetic is exact."""
    base = codes.astype(np.uint64)

    def _concat(A, a, B, b):
        m = A.shape[0] - b
        if reverse:
            return A[:m] + B[a:a + m] * np.uint64(5 ** a)
        return A[:m] * np.uint64(5 ** b) + B[a:a + m]

    acc, acc_len = None, 0
    for bit in bin(k)[2:]:
        if acc is not None:
            acc = _concat(acc, acc_len, acc, acc_len)
            acc_len *= 2
        if bit == "1":
            if acc is None:
                acc, acc_len = base, 1
            else:
                acc = _concat(acc, acc_len, base, 1)
                acc_len += 1
    return acc if acc is not base else base.copy()


def _kmer_hashes(codes: np.ndarray, k: int) -> np.ndarray:
    """uint32 mixed hash per k-mer start position of one code strand."""
    packed = _pack_poly(codes, k)
    return (_splitmix64(packed) >> np.uint64(32)).astype(np.uint32)


def _window_minima(vals: np.ndarray, w: int) -> np.ndarray:
    """Minimum over every window of ``w`` consecutive positions, in
    O(log w) array passes (same square-and-multiply shape as _pack_poly:
    ``concat(A_a, B_b)[i] = min(A[i], B[i+a])`` is associative)."""

    def _concat(A, a, B, b):
        m = A.shape[0] - b
        return np.minimum(A[:m], B[a:a + m])

    acc, acc_len = None, 0
    for bit in bin(w)[2:]:
        if acc is not None:
            acc = _concat(acc, acc_len, acc, acc_len)
            acc_len *= 2
        if bit == "1":
            if acc is None:
                acc, acc_len = vals, 1
            else:
                acc = _concat(acc, acc_len, vals, 1)
                acc_len += 1
    return acc if acc is not vals else vals.copy()


def sketch_from_codes(fwd: np.ndarray, rc: np.ndarray, k: int, w: int,
                      s: int) -> Tuple[np.ndarray, int]:
    """Bottom-s minimizer sketch of one contig from its code-space strands
    (``Sequence.encoded_strands()`` order: forward, reverse complement).

    Returns ``(sketch, m)``: a length-``s`` uint32 vector sorted ascending
    and padded with :data:`SENTINEL`, plus the count ``m`` of real values.
    Deterministic in the sequence content and (k, w, s) alone, and
    strand-symmetric: a contig and its reverse complement sketch
    identically (canonical hash + window-set symmetry).
    """
    n = fwd.shape[0]
    if n < k + w - 1:
        return np.full(s, SENTINEL, np.uint32), 0
    hf = _kmer_hashes(fwd, k)
    # the rc k-mer starting at rc-position (n - k - i) is the reverse
    # complement of the fwd k-mer at position i. Its pack equals the
    # MIRRORED polynomial over the complement strand at position i, and
    # rc[::-1] IS the complement strand (encode_both_strands builds rc as
    # complement(fwd)[::-1]) — so one reversed-coefficient pack of that
    # view replaces packing rc and re-aligning with hr[::-1]
    pr = _pack_poly(np.ascontiguousarray(rc[::-1]), k, reverse=True)
    hr = (_splitmix64(pr) >> np.uint64(32)).astype(np.uint32)
    canon = np.minimum(hf, hr)
    # windows containing a dot ('.'; pad/separator, code 0) never
    # contribute — the cumulative zero count gives dots-per-k-window
    zeros = np.zeros(n + 1, np.int64)
    np.cumsum(fwd == 0, out=zeros[1:])
    dotted = (zeros[k:] - zeros[:-k]) > 0
    canon[dotted] = SENTINEL
    minima = _window_minima(canon, w)
    # each minimizer typically wins ~w consecutive windows: collapsing
    # equal-value runs first shrinks the np.unique sort ~w-fold without
    # changing the value SET (runs only ever drop duplicates)
    if minima.size > 1:
        keep = np.empty(minima.size, bool)
        keep[0] = True
        np.not_equal(minima[1:], minima[:-1], out=keep[1:])
        minima = minima[keep]
    minimizers = np.unique(minima)
    if minimizers.size and minimizers[-1] == SENTINEL:
        minimizers = minimizers[:-1]
    minimizers = minimizers[:s]
    m = int(minimizers.size)
    sketch = np.full(s, SENTINEL, np.uint32)
    sketch[:m] = minimizers
    return sketch, m


def _contig_forward_bytes(seq, recon) -> np.ndarray:
    """A contig's forward ASCII bytes: the in-memory strand when present,
    else the bulk-reconstructed bytes (cluster loads sequences from GFA
    P-lines with ``Sequence.without_seq`` — empty strands)."""
    if seq.forward_seq.size:
        return seq.forward_seq
    return recon[seq.id]


def sketch_matrix(graph, sequences, cache=None,
                  params: Optional[Tuple[int, int, int]] = None
                  ) -> Tuple[np.ndarray, np.ndarray, List[int]]:
    """Stacked ``(n_contigs, s)`` uint32 sketch matrix, per-contig valid
    counts (int64) and sequence ids, in ``sequences`` order.

    ``cache`` (utils.cache.EncodeCache or None) stores each contig's
    sketch content-addressed by the sha256 of its forward bytes plus
    (k, w, s) — serve's warm daemon reuses sketches across jobs exactly
    like the parse/repair caches, and any byte change misses by
    construction."""
    k, w, s = params if params is not None else sketch_params()
    missing = [q.id for q in sequences if not q.forward_seq.size]
    recon = graph.get_sequences_for_ids(missing) if missing else {}
    S = np.full((len(sequences), s), SENTINEL, np.uint32)
    valid = np.zeros(len(sequences), np.int64)
    ids: List[int] = []
    for i, seq in enumerate(sequences):
        ids.append(seq.id)
        key = None
        if cache is not None:
            from ..utils.cache import content_hash
            key = content_hash(_contig_forward_bytes(seq, recon).tobytes())
            hit = cache.load_sketch(key, k, w, s)
            if hit is not None:
                S[i], valid[i] = hit
                continue
        if seq.forward_seq.size:
            fwd, rc = seq.encoded_strands()
        else:
            fwd, rc = encode_both_strands(_contig_forward_bytes(seq, recon))
        S[i], valid[i] = sketch_from_codes(fwd, rc, k, w, s)
        if cache is not None and key is not None:
            cache.store_sketch(key, k, w, s, S[i], int(valid[i]))
    return S, valid, ids


def _sketch_intersections_searchsorted(S: np.ndarray) -> np.ndarray:
    """Pairwise intersection counts over sorted sketch rows — the numpy
    oracle, bit-identical to the device grid (same sorted-merge integer
    algorithm; only the vectorisation differs)."""
    n, s = S.shape
    out = np.empty((n, n), np.int64)
    real = S != SENTINEL
    flat = S.reshape(-1)
    for b in range(n):
        idx = np.searchsorted(S[b], flat).reshape(n, s)
        np.minimum(idx, s - 1, out=idx)
        out[:, b] = ((S[b][idx] == S) & real).sum(axis=1)
    return out


def sketch_intersections_host(S: np.ndarray) -> np.ndarray:
    """Pairwise intersection counts — fast host production path. All
    sketch values are tokenised once (one global np.unique), then each
    row's token set is flipped on in a boolean lookup table and every
    other row is one O(n·s) gather — no per-pair log-s search. Counting
    set membership either way yields the same integers, pinned against
    :func:`_sketch_intersections_searchsorted` (and hence the device
    grid) in tests/test_sketch.py."""
    n, s = S.shape
    real = S != SENTINEL
    uniq, tok = np.unique(S, return_inverse=True)
    T = tok.reshape(n, s)
    out = np.empty((n, n), np.int64)
    lut = np.zeros(uniq.size, bool)
    for b in range(n):
        tb = T[b][real[b]]
        lut[tb] = True
        out[:, b] = np.count_nonzero(lut[T] & real, axis=1)
        lut[tb] = False
    return out


def _sketch_intersections_jax(S: np.ndarray) -> np.ndarray:
    """One batched device dispatch: nested-vmap ``searchsorted`` lookup of
    every sketch row against every other. Rows are padded to a multiple of
    64 with all-sentinel rows (zero intersections by construction) so the
    compiled grid is reused across runs via the persistent compile cache."""
    from ..utils.jaxcache import configure_compile_cache
    configure_compile_cache()
    import jax
    import jax.numpy as jnp

    from ..utils.timing import device_dispatch
    n, s = S.shape
    n_pad = -(-n // 64) * 64
    S_p = np.full((n_pad, s), SENTINEL, np.uint32)
    S_p[:n] = S

    def _grid(mat):
        sent = jnp.uint32(SENTINEL)

        def against(target, query):
            idx = jnp.minimum(jnp.searchsorted(target, query), s - 1)
            hit = (target[idx] == query) & (query != sent)
            return jnp.sum(hit.astype(jnp.int32))

        def row(query):
            return jax.vmap(lambda t: against(t, query))(mat)

        return jax.vmap(row)(mat)

    with device_dispatch("sketch jaccard grid",
                         flops=2.0 * n_pad * n_pad * s):
        inter = np.asarray(jax.jit(_grid)(jnp.asarray(S_p)))
    return inter[:n, :n].astype(np.int64)


def _containment_to_matrix(inter: np.ndarray, valid: np.ndarray
                           ) -> np.ndarray:
    """Integer intersection counts -> asymmetric distance matrix, one float
    expression shared by the host and device paths (ops.distance pattern).
    Rows with empty sketches (contig shorter than k + w - 1) are defined as
    distance 1 to everything and 0 to themselves."""
    a_len = valid.astype(np.float64)
    with np.errstate(divide="ignore", invalid="ignore"):
        D = 1.0 - inter / a_len[:, None]
    empty = valid == 0
    if empty.any():
        D[empty, :] = 1.0
        idx = np.flatnonzero(empty)
        D[idx, idx] = 0.0
    return D


def sketch_distance_matrix(S: np.ndarray, valid: np.ndarray,
                           use_jax=None) -> np.ndarray:
    """Asymmetric sketch distance D[a, b] = 1 - inter(a, b) / |sketch(a)|,
    with the same auto/host-fallback dispatch contract as
    ops.distance.pairwise_distance_matrix."""
    if use_jax is None:
        if S.size >= _JAX_THRESHOLD:
            use_jax = True
        elif S.size < _TPU_THRESHOLD:
            use_jax = False
        else:
            from .distance import device_attached
            use_jax = device_attached()
    if use_jax:
        try:
            inter = _sketch_intersections_jax(S)
        except Exception as e:  # noqa: BLE001 — host fallback for ANY
            # device failure, surfaced like the exact matmul's fallback
            import sys

            from ..utils.timing import record_device_failure
            what = f"device sketch grid failed ({type(e).__name__}: {e})"
            record_device_failure(what, exc=e)
            print(f"autocycler: {what}; falling back to host grid",
                  file=sys.stderr)
            inter = sketch_intersections_host(S)
    else:
        inter = sketch_intersections_host(S)
    return _containment_to_matrix(inter, valid)


def sketch_contig_distances(graph, sequences, cache=None, use_jax=None
                            ) -> Dict[Tuple[int, int], float]:
    """Sketch distances keyed by (seq_a.id, seq_b.id) — the same
    reference-shaped dict as ops.distance.pairwise_contig_distances, so
    cluster's UPGMA/cutoff path consumes either interchangeably."""
    S, valid, ids = sketch_matrix(graph, sequences, cache=cache)
    D = sketch_distance_matrix(S, valid, use_jax=use_jax)
    return {(ids[a], ids[b]): float(D[a, b])
            for a in range(len(ids)) for b in range(len(ids))}
