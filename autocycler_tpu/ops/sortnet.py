"""Multi-word bitonic sort network — the Pallas k-mer grouping kernel.

This is the device grouping engine mandated since round 3: a sorting
NETWORK, because that is the only sort shape a TPU runs well. TPUs have no
fast random scatter, so hash grouping (the native kernel's approach,
reference kmer_graph.rs:86-134) and GPU-style radix partitioning are off
the table; what the VPU does superbly is regular compare-exchange over
(8, 128) vectors. A bitonic network is nothing but compare-exchanges at
power-of-two distances with static control flow — every exchange is two
`roll`s and a `select` over VMEM-resident tiles, and every pass streams
HBM sequentially.

Why not XLA's own sort?  Three reasons, all measured in earlier rounds:
- `jnp.lexsort` over W+1 operands builds one variadic sort whose compile
  takes MINUTES per shape on this platform (docs/architecture.md);
- the LSD fallback (ops/kmers.py `_rank_windows_traced_lsd`) avoids the
  compile wall but pays W sequential 2-operand `sort_key_val`s plus a
  per-pass re-key gather — every pass re-reads and re-writes every word;
- neither fuses: this kernel sorts the full record (W key words + index)
  in ONE network. All substages with distance < the VMEM block run fused
  inside one kernel invocation, so HBM is touched once per stage plus once
  per global substage — ~(m - L) * (m - L + 3) / 2 + m sweeps for
  N = 2**m and blocks of 2**L elements, independent of W.

Layout: each of the W+1 int32 arrays (key words most significant first,
then the original index as tiebreaker — which also makes the comparator a
total order, required because a bitonic exchange of EQUAL keys is not
consistent between the two sides of the pair) is a [R, 128] matrix,
element i at (row i // 128, lane i % 128). A compare-exchange at distance
d is elementwise:

    partner = where((i & d) == 0, roll(x, -d), roll(x, +d))
    swap    = where((i & d == 0) == ascending(i), self > partner,
                    partner > self)

with the roll on the lane axis for d < 128 and on the row axis otherwise;
``ascending(i) = (i & 2**s) == 0`` for stage s.

The network is a Pallas/XLA hybrid, split where each engine is strongest:
- `_local_stages_kernel` (Pallas) — all substages with d < block elements,
  fused over a VMEM-resident block: used once for the initial per-block
  sort (stages 1..L — the majority of all compare-exchanges in one HBM
  sweep) and once per later stage for its local tail. This fusion is the
  part XLA cannot do: its own ops materialise every substage to HBM.
- `_global_exchange_jnp` (XLA) — one substage with d >= block elements as
  a reshape + elementwise compare/select: block A of each pair is the
  (i & d) == 0 side, lane-for-lane against block B. One read + one write
  per array — the same HBM traffic a hand-written pair kernel would pay,
  without per-pair DMA choreography or a kernel compile per distance;
  wide fusable elementwise work is exactly what XLA is already good at.

`sortnet_reference` runs the identical network in numpy as the tests'
oracle (the networks must match EXACTLY, not just both be valid sorts,
because the device kernel is validated block-by-block against it).

Padding: callers pad n to a power of two with INT32_MAX key words — real
key words are base-5 packed (< 5**13, ops/kmers.py) so MAX is out of band
and pads sort strictly last.
"""

from __future__ import annotations

import functools
from typing import List, Sequence, Tuple

import numpy as np

INT32_MAX = np.int32(2**31 - 1)

# default rows per VMEM block: 1024 rows x 128 lanes = 2**17 elements;
# 5 arrays x 0.5 MB in + out + partner temporaries stays well inside the
# ~16 MB VMEM budget
DEFAULT_BLOCK_ROWS = 1024


def _ceil_pow2(n: int) -> int:
    m = 1
    while m < n:
        m <<= 1
    return m


# ---------------------------------------------------------------------------
# numpy reference network (oracle for the Pallas kernels)
# ---------------------------------------------------------------------------


def _lex_gt_np(a: List[np.ndarray], b: List[np.ndarray]) -> np.ndarray:
    gt = np.zeros(a[0].shape, dtype=bool)
    eq = np.ones(a[0].shape, dtype=bool)
    for x, y in zip(a, b):
        gt |= eq & (x > y)
        eq &= x == y
    return gt


def sortnet_reference(arrays: Sequence[np.ndarray]) -> List[np.ndarray]:
    """Run the exact bitonic network on host. ``arrays`` = key words most
    significant first; the element tuples MUST be pairwise distinct (append
    an index array as the last word — ties make bitonic exchanges
    inconsistent). Returns the sorted arrays. O(n log² n) — tests only."""
    arrs = [np.asarray(a, np.int32).copy() for a in arrays]
    n = len(arrs[0])
    N = _ceil_pow2(max(n, 2))
    if N != n:
        arrs = [np.concatenate([a, np.full(N - n, INT32_MAX, np.int32)])
                for a in arrs]
        # keep tuples distinct among pads: the last array is the tiebreak
        arrs[-1][n:] = n + np.arange(N - n)
    i = np.arange(N)
    m = N.bit_length() - 1
    for s in range(1, m + 1):
        asc = (i & (1 << s)) == 0
        for t in range(s, 0, -1):
            d = 1 << (t - 1)
            partner = [a[i ^ d] for a in arrs]
            self_gt = _lex_gt_np(arrs, partner)
            lower = (i & d) == 0
            want_swap = np.where(lower == asc, self_gt, ~self_gt)
            arrs = [np.where(want_swap, p, a) for a, p in zip(arrs, partner)]
    return [a[:n] for a in arrs]


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------


def _lex_gt(a, b):
    import jax.numpy as jnp

    gt = jnp.zeros(a[0].shape, dtype=bool)
    eq = jnp.ones(a[0].shape, dtype=bool)
    for x, y in zip(a, b):
        gt = gt | (eq & (x > y))
        eq = eq & (x == y)
    return gt


def _block_exchange(arrs, d: int, asc):
    """One in-VMEM compare-exchange at distance d (< block elements) over
    [Rb, 128] tiles. ``asc`` is the ascending mask (same shape)."""
    import jax.numpy as jnp

    if d < 128:
        lane = jnp.arange(128, dtype=jnp.int32)[None, :]
        lower = (lane & d) == 0
        partner = [jnp.where(lower, jnp.roll(a, -d, axis=1),
                             jnp.roll(a, d, axis=1)) for a in arrs]
    else:
        D = d // 128
        row = jnp.arange(arrs[0].shape[0], dtype=jnp.int32)[:, None]
        lower = (row & D) == 0
        partner = [jnp.where(lower, jnp.roll(a, -D, axis=0),
                             jnp.roll(a, D, axis=0)) for a in arrs]
    self_gt = _lex_gt(arrs, partner)
    swap = jnp.where(lower == asc, self_gt, ~self_gt)
    return [jnp.where(swap, p, a) for a, p in zip(arrs, partner)]


def _local_stages_kernel(stages, block_rows: int, *refs):
    """Fused local substages over one VMEM block. ``stages`` is a static
    list of (stage_bit s, [distances d...]) with every d < block elements.
    refs = in_refs + out_refs (aliased in-place)."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    n_arr = len(refs) // 2
    in_refs, out_refs = refs[:n_arr], refs[n_arr:]
    b = pl.program_id(0)
    base = b * block_rows * 128
    row = jnp.arange(block_rows, dtype=jnp.int32)[:, None]
    lane = jnp.arange(128, dtype=jnp.int32)[None, :]
    elem = base + row * 128 + lane
    arrs = [r[:, :] for r in in_refs]
    for s, dists in stages:
        asc = ((elem >> s) & 1) == 0
        for d in dists:
            arrs = _block_exchange(arrs, d, asc)
    for r, a in zip(out_refs, arrs):
        r[:, :] = a


def _global_exchange_jnp(arrs, s: int, d: int):
    """One substage at distance d >= block elements, as plain XLA ops on
    the flat [N] arrays: viewed as [N / (2d), 2, d], axis-1 slice 0 is the
    (i & d) == 0 side, so the exchange is an elementwise compare + select
    between the two slices — one read + one write of each array, the same
    HBM traffic a hand-rolled pair kernel would pay, without per-pair DMA
    choreography or per-distance kernel compiles. The fused VMEM work —
    the vast majority of the network's compare-exchanges — stays in the
    Pallas local kernel; these wide, fusable elementwise substages are
    exactly what XLA is already good at."""
    import jax.numpy as jnp

    n_groups = arrs[0].shape[0] // (2 * d)
    split = [a.reshape(n_groups, 2, d) for a in arrs]
    a_side = [x[:, 0, :] for x in split]
    b_side = [x[:, 1, :] for x in split]
    gt = _lex_gt(a_side, b_side)
    # ascending(i): bit s of the element index, constant per group because
    # each group spans 2d <= 2**s elements aligned to a 2d boundary
    g = jnp.arange(n_groups, dtype=jnp.int32)[:, None]
    asc = (((g * 2 * d) >> s) & 1) == 0
    swap = jnp.logical_xor(gt, jnp.logical_not(asc))
    out = []
    for a, b in zip(a_side, b_side):
        new_a = jnp.where(swap, b, a)
        new_b = jnp.where(swap, a, b)
        out.append(jnp.stack([new_a, new_b], axis=1).reshape(-1))
    return out


def run_network(arrays, block_rows: int = DEFAULT_BLOCK_ROWS,
                interpret: bool = False):
    """The traced network body: sorts the parallel [N] int32 device arrays
    lexicographically. Composable inside a larger jit (the grouping path
    fuses packing + network + group-id extraction into ONE dispatch);
    :func:`sortnet` wraps it in its own jit with donated buffers."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    n_arrays = len(arrays)
    N = int(arrays[0].shape[0])
    block_elems = block_rows * 128
    L = block_elems.bit_length() - 1      # stages fully inside a block
    m = N.bit_length() - 1
    n_blocks = max(N // block_elems, 1)
    R = N // 128

    def local_call(arrs, stages):
        spec = pl.BlockSpec((block_rows, 128), lambda b: (b, 0))
        return list(pl.pallas_call(
            functools.partial(_local_stages_kernel, tuple(stages),
                              block_rows),
            grid=(n_blocks,),
            in_specs=[spec] * n_arrays,
            out_specs=[spec] * n_arrays,
            out_shape=[jax.ShapeDtypeStruct((R, 128), jnp.int32)] * n_arrays,
            input_output_aliases={j: j for j in range(n_arrays)},
            interpret=interpret,
        )(*arrs))

    arrs = [a.reshape(R, 128) for a in arrays]
    if m <= L:
        arrs = local_call(
            arrs, [(s, [1 << (t - 1) for t in range(s, 0, -1)])
                   for s in range(1, m + 1)])
        return [a.reshape(-1) for a in arrs]
    arrs = local_call(
        arrs, [(s, [1 << (t - 1) for t in range(s, 0, -1)])
               for s in range(1, L + 1)])
    for s in range(L + 1, m + 1):
        flat = [a.reshape(-1) for a in arrs]
        for t in range(s, L, -1):
            flat = _global_exchange_jnp(flat, s, 1 << (t - 1))
        arrs = [a.reshape(R, 128) for a in flat]
        arrs = local_call(
            arrs, [(s, [1 << (t - 1) for t in range(L, 0, -1)])])
    return [a.reshape(-1) for a in arrs]


def network_sweeps(N: int, block_rows: int = DEFAULT_BLOCK_ROWS) -> int:
    """Number of full HBM read+write sweeps the network makes over the
    arrays — the bandwidth anchor for MFU accounting (ops/mfu.py)."""
    block_elems = block_rows * 128
    L = block_elems.bit_length() - 1
    m = max(N.bit_length() - 1, 1)
    if m <= L:
        return 1
    sweeps = 1                             # initial local sort
    for s in range(L + 1, m + 1):
        sweeps += (s - L) + 1              # global substages + local tail
    return sweeps


@functools.lru_cache(maxsize=None)
def _sortnet_fn(n_arrays: int, N: int, block_rows: int, interpret: bool):
    """One jitted function running the whole network for (n_arrays, N)."""
    import jax

    def run(*arrays):
        return run_network(list(arrays), block_rows=block_rows,
                           interpret=interpret)

    return jax.jit(run, donate_argnums=tuple(range(n_arrays)))


def sortnet(arrays: Sequence, block_rows: int = DEFAULT_BLOCK_ROWS,
            interpret: bool = False) -> List:
    """Sort parallel int32 device arrays lexicographically (first array
    most significant, last array MUST make the tuples pairwise distinct —
    pass an index array). Length must be a power of two >= 128 *
    block_rows; use :func:`sortnet_padded` for arbitrary n."""
    n_arrays = len(arrays)
    N = int(arrays[0].shape[0])
    if N & (N - 1):
        raise ValueError(f"sortnet length {N} is not a power of two")
    if N < block_rows * 128:
        raise ValueError(f"sortnet length {N} < one block "
                         f"({block_rows * 128}); pad or shrink block_rows")
    fn = _sortnet_fn(n_arrays, N, block_rows, interpret)
    return list(fn(*arrays))


def sortnet_padded(words: Sequence, n: int,
                   block_rows: int = DEFAULT_BLOCK_ROWS,
                   interpret: bool = False) -> Tuple[List, object]:
    """Pad (words..., iota index) to the network size with INT32_MAX
    sentinels, sort on device, and return (sorted word arrays, sorted
    original indices) trimmed back to n."""
    import jax.numpy as jnp

    N = max(_ceil_pow2(max(n, 1)), block_rows * 128)
    pad = N - n
    arrs = [jnp.pad(jnp.asarray(w, jnp.int32), (0, pad),
                    constant_values=int(INT32_MAX)) for w in words]
    idx = jnp.arange(N, dtype=jnp.int32)
    out = sortnet(arrs + [idx], block_rows=block_rows, interpret=interpret)
    return [o[:n] for o in out[:-1]], out[-1][:n]
