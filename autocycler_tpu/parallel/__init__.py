from .mesh import make_mesh, mesh_axis_sizes
from .batch import (encode_batch, multi_isolate_distance_step,
                    sharded_multi_isolate_step)
