"""Batched multi-isolate pipeline step over a device mesh.

The flagship device computation: for a batch of isolates, each with several
input assemblies, compute every assembly's k-mer presence sketch and the
per-isolate all-vs-all contig distance matrix — the device core of
compress + cluster (reference kmer_graph.rs hot loop + cluster.rs:132-157)
batched over genomes, i.e. the BASELINE.json "96 genomes × 12 assemblies on
v5e-8" configuration.

Sharding layout (see parallel.mesh):
- batch dim  -> 'data'  (independent isolates; no collectives)
- length dim -> 'seq'   (sequence parallelism: k-mer windows crossing the
                         shard boundary are completed by a ring halo
                         exchange via lax.ppermute, then bucket sketches
                         are combined with one psum over 'seq')

Everything is static-shaped (padded batches) and jit-compiles once; the
matmul runs on the MXU.
"""

from __future__ import annotations

import functools
from typing import List, Optional

import numpy as np

from ..ops.encode import encode_bytes

DEFAULT_K = 51
DEFAULT_BUCKETS = 4096

# multipliers for the word-mixing hash (arbitrary odd constants)
_MIX = (0x9E3779B1, 0x85EBCA77, 0xC2B2AE3D, 0x27D4EB2F, 0x165667B1, 0x9E3779B9)


def encode_batch(seq_strings: List[List[str]], length: Optional[int] = None) -> np.ndarray:
    """[isolate][assembly] sequence strings -> [B, S, L] uint8 code batch,
    zero-padded (code 0 = '.', which never matches a real k-mer hash
    bucket-for-bucket since dot windows are masked out)."""
    from ..utils.resilience import InputError
    if not seq_strings:
        raise InputError("encode_batch: no isolates to encode "
                         "(empty isolate list)")
    empties = [b for b, iso in enumerate(seq_strings) if not iso]
    if empties:
        raise InputError(f"encode_batch: isolate(s) at index "
                         f"{', '.join(map(str, empties))} have no assemblies")
    B = len(seq_strings)
    S = max(len(iso) for iso in seq_strings)
    if length is None:
        length = max(len(s) for iso in seq_strings for s in iso)
        if length == 0:
            raise InputError("encode_batch: all assembly sequences are empty")
    out = np.zeros((B, S, length), dtype=np.uint8)
    for b, iso in enumerate(seq_strings):
        for s, seq in enumerate(iso):
            raw = np.frombuffer(seq[:length].encode(), dtype=np.uint8)
            out[b, s, :len(raw)] = encode_bytes(raw)
    return out


def _shard_map():
    """shard_map graduated from jax.experimental to the jax namespace across
    releases; probe the stable location and degrade to the experimental one
    (recorded once in the backend-degradation registry)."""
    import jax
    try:
        return jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map
        from ..utils.resilience import record_degrade
        record_degrade(
            "shard-map", "jax.shard_map", "jax.experimental.shard_map",
            f"jax {jax.__version__} predates the stable shard_map API")
        return shard_map


def _kmer_bucket_sketch(codes, k: int, buckets: int):
    """[..., L] codes -> [..., buckets] float32 presence sketch.

    Every window of k codes is hashed by mixing ceil(k/10) packed 3-bit
    words (the same packing as ops.kmers); windows containing padding
    (code 0) are masked out. Pure jnp, shard-local.
    """
    import jax.numpy as jnp

    L = codes.shape[-1]
    n = L - k + 1
    W = (k + 9) // 10
    valid = jnp.ones(codes.shape[:-1] + (n,), dtype=bool)
    h = jnp.zeros(codes.shape[:-1] + (n,), dtype=jnp.uint32)
    for w in range(W):
        word = jnp.zeros(codes.shape[:-1] + (n,), dtype=jnp.uint32)
        for t in range(10):
            idx = w * 10 + t
            if idx >= k:
                break
            sym = codes[..., idx:idx + n].astype(jnp.uint32)
            valid &= sym > 0
            word = (word << 3) | sym
        h = h ^ (word * np.uint32(_MIX[w % len(_MIX)]))
    bucket = (h % np.uint32(buckets)).astype(jnp.int32)
    lead = codes.shape[:-1]
    rows = int(np.prod(lead)) if lead else 1
    flat_bucket = bucket.reshape(rows, n)
    flat_bucket = flat_bucket + jnp.arange(rows, dtype=jnp.int32)[:, None] * buckets
    ones = jnp.where(valid, 1.0, 0.0).astype(jnp.float32).reshape(rows, n)
    presence = jnp.zeros(rows * buckets, dtype=jnp.float32)
    presence = presence.at[flat_bucket.ravel()].max(ones.ravel())
    return presence.reshape(lead + (buckets,))


def multi_isolate_distance_step(codes, k: int = DEFAULT_K,
                                buckets: int = DEFAULT_BUCKETS):
    """Single-device forward step: [B, S, L] codes -> [B, S, S] asymmetric
    contig distance sketch (1 - |A∩B| / |A| over hashed k-mer buckets —
    the device formulation of cluster.rs:132-157).

    K-mers are taken circularly (the sequence wraps around), making the
    sketch rotation-invariant — bacterial replicons are circular — and
    bit-matching the seq-sharded version, whose ring halo wraps the same
    way."""
    import jax.numpy as jnp

    codes = jnp.concatenate([jnp.asarray(codes), jnp.asarray(codes)[..., :k - 1]],
                            axis=-1)
    presence = _kmer_bucket_sketch(codes, k, buckets)          # [B, S, K]
    inter = jnp.einsum("bsk,btk->bst", presence, presence)     # MXU matmul
    own = jnp.maximum(jnp.sum(presence, axis=-1), 1.0)         # |A| per contig
    return 1.0 - inter / own[..., :, None]


def _sharded_step_body(codes, k: int, buckets: int, seq_axis: str):
    """shard_map body: halo-exchange the first k-1 codes from the next seq
    shard, sketch locally, psum sketches over the seq axis, then compute the
    distance matrix (replicated over seq shards)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    # lax.axis_size is missing on jax 0.4.x; psum of a literal 1 is its
    # documented equivalent and stays a static Python int
    n_seq = (lax.axis_size(seq_axis) if hasattr(lax, "axis_size")
             else lax.psum(1, seq_axis))
    if n_seq > 1:
        # ring halo: shard i receives the first k-1 codes of shard i+1 so
        # windows spanning the shard boundary are complete. The last shard
        # wraps around to shard 0, adding end-to-start junction windows —
        # semantically right for circular replicons and harmless for the
        # sketch otherwise.
        halo = codes[..., :k - 1]
        perm = [(i, (i - 1) % n_seq) for i in range(n_seq)]
        halo = lax.ppermute(halo, seq_axis, perm)
        codes = jnp.concatenate([codes, halo], axis=-1)
    presence = _kmer_bucket_sketch(codes, k, buckets)
    presence = lax.pmax(presence, seq_axis)
    inter = jnp.einsum("bsk,btk->bst", presence, presence)
    own = jnp.maximum(jnp.sum(presence, axis=-1), 1.0)
    return 1.0 - inter / own[..., :, None]


def sharded_multi_isolate_step(mesh, codes: np.ndarray, k: int = DEFAULT_K,
                               buckets: int = DEFAULT_BUCKETS):
    """Jit-compiled mesh-sharded step: batch over 'data', length over 'seq'.

    codes: [B, S, L] with B divisible by the data-axis size and L divisible
    by the seq-axis size. Returns [B, S, S] distances (sharded over 'data').
    """
    import jax
    shard_map = _shard_map()
    from jax.sharding import PartitionSpec as P

    body = functools.partial(_sharded_step_body, k=k, buckets=buckets,
                             seq_axis="seq")
    step = shard_map(body, mesh=mesh,
                     in_specs=P("data", None, "seq"),
                     out_specs=P("data", None, None))
    return jax.jit(step)(codes)


# ---------------------------------------------------------------------------
# Exact batched distances (the production multi-isolate step)
# ---------------------------------------------------------------------------

def _membership_body(Mw, M, seq_axis: str):
    """shard_map body: contract the (sharded) unitig axis locally on the MXU
    and psum partial intersections over 'seq' — integer arithmetic end to
    end, so the result is exactly the unsharded matmul."""
    import jax.numpy as jnp
    from jax import lax

    inter_local = jnp.einsum("bsu,btu->bst", Mw, M,
                             preferred_element_type=jnp.int32)
    return lax.psum(inter_local, seq_axis)


def batched_membership_intersections(mesh, M_list: List[np.ndarray],
                                     w_list: List[np.ndarray]) -> List[np.ndarray]:
    """Exact per-isolate contig intersection matrices, batched over the mesh.

    This is ops.distance.pairwise_distance_matrix semantics (reference
    cluster.rs:132-157: |A∩B| weighted by unitig length) for MANY isolates at
    once: isolates ride the 'data' axis (pure data parallelism), the unitig
    axis is sharded over 'seq' and contracted with an int32 einsum + psum —
    integers all the way, so each isolate's matrix is bit-identical to the
    single-isolate computation.

    M_list[i]: [S_i, U_i] uint8 membership; w_list[i]: [U_i] int64 unitig
    lengths. Returns per-isolate [S_i, S_i] int64 intersection matrices
    (divide by the diagonal on the host for the asymmetric distances).
    """
    import jax
    shard_map = _shard_map()
    from jax.sharding import PartitionSpec as P

    B = len(M_list)
    data_size, seq_size = mesh.devices.shape
    S = max((m.shape[0] for m in M_list), default=1)
    U = max((m.shape[1] for m in M_list), default=1)
    U = -(-U // seq_size) * seq_size          # pad unitig axis to seq shards
    Bp = -(-B // data_size) * data_size       # pad batch to data shards

    Mw = np.zeros((Bp, S, U), dtype=np.int32)
    M = np.zeros((Bp, S, U), dtype=np.int32)
    from ..ops.distance import exceeds_int32_accumulation
    host_only = []   # isolates whose intersections could exceed int32
    for i, (m, w) in enumerate(zip(M_list, w_list)):
        s, u = m.shape
        weighted = m.astype(np.int64) * w[None, :]
        # past int32 range the device accumulation would silently wrap, so
        # those isolates take the exact host matmul instead
        if exceeds_int32_accumulation(weighted):
            host_only.append(i)
            continue
        M[i, :s, :u] = m
        Mw[i, :s, :u] = weighted

    step = shard_map(functools.partial(_membership_body, seq_axis="seq"),
                     mesh=mesh,
                     in_specs=(P("data", None, "seq"), P("data", None, "seq")),
                     out_specs=P("data", None, None))
    from ..utils.timing import device_dispatch
    with device_dispatch("batched membership contraction"):
        inter = np.asarray(jax.jit(step)(Mw, M)).astype(np.int64)
    out = [inter[i, :m.shape[0], :m.shape[0]] for i, m in enumerate(M_list)]
    for i in host_only:
        m, w = M_list[i], w_list[i]
        out[i] = (m.astype(np.int64) * w[None, :]) @ m.astype(np.int64).T
    return out


# ---------------------------------------------------------------------------
# Sharded trim-DP screen (batch's trim stage on the mesh)
# ---------------------------------------------------------------------------

def sharded_overlap_screen(mesh, jobs, max_unitigs: int) -> np.ndarray:
    """The batched trim overlap-DP screen (ops.align.overlap_screen_scores)
    sharded over EVERY device of the mesh: DP jobs are independent, so they
    ride a flattened ('data', 'seq') axis — pure data parallelism, no
    collectives. Bit-identical to the single-device screen (integer DP).

    Returns the bool verdicts for `jobs` (padding rows dropped)."""
    import jax
    shard_map = _shard_map()
    from jax.sharding import PartitionSpec as P

    from ..ops.align import overlap_screen_scores, pack_overlap_jobs

    n_dev = mesh.devices.size
    packed = pack_overlap_jobs(jobs, max_unitigs, pad_to=n_dev)
    if packed is None:
        return np.zeros(len(jobs), bool)
    arrs, n_real = packed
    spec = {k: P(("data", "seq")) if v.ndim == 1 else P(("data", "seq"), None)
            for k, v in arrs.items()}
    step = shard_map(overlap_screen_scores, mesh=mesh,
                     in_specs=(spec,), out_specs=P(("data", "seq")))
    from ..utils.timing import device_dispatch
    with device_dispatch("sharded trim screen"):
        best = np.asarray(jax.jit(step)(arrs))
    return best[:n_real] > 0
