"""Fleet planning + bucketed device shapes for multi-isolate batch runs.

The fleet runner (commands/batch.py `--fleet`) scales `autocycler batch`
along the ROADMAP's fleet rung: isolates are packed into *shards* sized to
the device mesh, each shard's exact membership contraction runs as ONE
device dispatch sharded over the leading (isolate) axis, and the host
load/encode of upcoming isolates overlaps the current shard's device work
(the Gerbil producer/consumer shape, arXiv:1607.06618, already used by the
stream spill pipeline).

Two shape problems make the naive version slow, and both are solved here
with the KMC 2 fixed-size-bin idea (arXiv:1407.1507):

- **Bucketed packing** (:func:`plan_fleet`): isolates vary by orders of
  magnitude (a 6 Mbp chromosome next to 2 kb plasmids). Padding every
  shard to the global maximum wastes FLOPs and memory; compiling per exact
  shape retraces XLA once per isolate. The planner sorts isolates by input
  cost, splits the order into a small number of contiguous size buckets,
  and forms shards *within* a bucket — so similar-sized isolates share a
  shard and the padding stays tight.
- **Bucketed device shapes** (:func:`bucket_dim` +
  :func:`fleet_membership_intersections`): each shard's [B, S, U]
  membership tensors are padded up a power-of-two ladder, quantising the
  shape space to at most a handful of distinct shapes per run. The
  contraction is jitted ONCE at module scope, so XLA compiles once per
  ladder shape ("once per bucket") instead of once per shard.

The contraction itself is placed with ``parallel.mesh.shard_leading_axis``
— isolates ride the flattened ('data', 'seq') mesh as pure data
parallelism, no collectives — and stays integer end to end, so every
isolate's matrix is bit-identical to the serial
``batched_membership_intersections`` / single-isolate computation.
Isolates whose weighted membership could overflow int32 accumulation take
the exact int64 host matmul, exactly as the serial path does.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs import metrics_registry

# registry metric names (static by the analysis.rules.metrics contract)
FLEET_SHARDS_TOTAL = "autocycler_fleet_shards_total"
FLEET_ISOLATES_TOTAL = "autocycler_fleet_isolates_total"
FLEET_PAD_RATIO = "autocycler_fleet_pad_ratio"
FLEET_SHAPE_BUCKETS = "autocycler_fleet_shape_buckets"

# padding ladder floors: shapes below these round up to the floor, so tiny
# synthetic isolates share one compile instead of one per contig count
_PAD_FLOOR_S = 8
_PAD_FLOOR_U = 64

FLEET_MODES = ("off", "on", "auto")


def resolve_fleet_mode(cli_value: Optional[str] = None) -> str:
    """The effective fleet mode: the CLI --fleet flag when given, else the
    ``AUTOCYCLER_FLEET_MODE`` knob. Unknown values are an input error (the
    CLI argparse choices catch them first; this guards the knob path)."""
    from ..utils.knobs import knob_str
    from ..utils.resilience import InputError

    mode = (cli_value or knob_str("AUTOCYCLER_FLEET_MODE") or "off")
    mode = mode.strip().lower()
    if mode not in FLEET_MODES:
        raise InputError(f"unknown fleet mode {mode!r} "
                         f"(choose from {', '.join(FLEET_MODES)})")
    return mode


def fleet_devices() -> int:
    """How many devices the fleet planner shards for:
    ``AUTOCYCLER_FLEET_DEVICES`` when > 0, else the attached device count
    (1 on any mesh-discovery failure — the plan still runs, unsharded)."""
    from ..utils.knobs import knob_int

    forced = knob_int("AUTOCYCLER_FLEET_DEVICES")
    if forced is not None and int(forced) > 0:
        return int(forced)
    try:
        from .mesh import _devices_with_deadline
        return max(1, len(_devices_with_deadline()))
    except Exception:  # noqa: BLE001 — planning degrades to one device
        return 1


def fleet_engaged(mode: str, n_isolates: int) -> bool:
    """Whether the fleet path runs: 'on' engages for any multi-isolate
    batch, 'auto' additionally requires >1 device (a one-device fleet only
    buys the prefetch overlap). A single isolate ALWAYS degrades to the
    serial path — there is nothing to pack, and bit-for-bit equivalence
    with `autocycler batch` is then true by construction."""
    if n_isolates <= 1:
        return False
    if mode == "on":
        return True
    if mode == "auto":
        return fleet_devices() > 1
    return False


def isolate_cost(asm_dir) -> int:
    """The planner's cost proxy for one isolate: total bytes of its
    assembly files. Never raises — an unreadable dir costs 0 and fails
    later inside the per-isolate quarantine, where it is recorded."""
    from ..utils.io import _ASSEMBLY_EXTS

    total = 0
    try:
        for p in Path(asm_dir).iterdir():
            if p.is_file() and p.name.lower().endswith(_ASSEMBLY_EXTS):
                try:
                    total += p.stat().st_size
                except OSError:
                    continue
    except OSError:
        return 0
    return total


@dataclass(frozen=True)
class FleetShard:
    """One device dispatch's worth of isolates (≤ shard_size names, all
    from the same size bucket)."""
    index: int
    bucket: int
    names: Tuple[str, ...]


@dataclass
class FleetPlan:
    shards: List[FleetShard]
    shard_size: int
    n_buckets: int


def plan_fleet(costs: Dict[str, int], shard_size: int,
               n_buckets: int) -> FleetPlan:
    """Pack isolates into bucketed shards.

    Isolates are ordered by descending cost (name-tiebroken, so the plan
    is deterministic), the order is split into ``n_buckets`` contiguous
    near-equal-count groups (rank quantiles — the KMC 2 fixed-size-bin
    rule), and each group is chunked into shards of ``shard_size``. An
    adversarially skewed input (one 6 Mbp isolate among 2 kb plasmids)
    lands the giant in its own bucket, so the plasmid shards never pay its
    padding."""
    shard_size = max(1, int(shard_size))
    names = sorted(costs, key=lambda n: (-costs[n], n))
    n_buckets = max(1, min(int(n_buckets), len(names) or 1))
    bounds = np.linspace(0, len(names), n_buckets + 1).astype(int)
    shards: List[FleetShard] = []
    for b in range(n_buckets):
        group = names[bounds[b]:bounds[b + 1]]
        for i in range(0, len(group), shard_size):
            chunk = tuple(group[i:i + shard_size])
            if chunk:
                shards.append(FleetShard(len(shards), b, chunk))
    return FleetPlan(shards=shards, shard_size=shard_size,
                     n_buckets=n_buckets)


def bucket_dim(n: int, floor: int) -> int:
    """Round a dimension up the padding ladder: the smallest power-of-two
    multiple of ``floor`` that holds ``n``. Quantising shapes to the
    ladder caps the number of distinct compiled programs at the ladder
    length (~log of the size range) instead of one per isolate."""
    v = max(1, int(floor))
    n = max(1, int(n))
    while v < n:
        v <<= 1
    return v


@functools.lru_cache(maxsize=1)
def _jitted_membership_step():
    """The fleet contraction, jitted ONCE at module scope: jax caches the
    compiled executable per input shape, so every shard padded to the same
    ladder shape reuses one compile. (A fresh ``jax.jit(step)`` per call —
    the serial path's pattern — retraces every dispatch.)"""
    import jax
    import jax.numpy as jnp

    def step(Mw, M):
        return jnp.einsum("bsu,btu->bst", Mw, M,
                          preferred_element_type=jnp.int32)

    return jax.jit(step)


def fleet_membership_intersections(M_list: List[np.ndarray],
                                   w_list: List[np.ndarray],
                                   devices: Optional[int] = None
                                   ) -> List[np.ndarray]:
    """Exact per-isolate contig intersection matrices for one fleet shard.

    Same contract as ``parallel.batch.batched_membership_intersections``
    (returns [S_i, S_i] int64 matrices; isolates past int32 accumulation
    range take the exact host matmul), but laid out for the fleet: the
    [B, S, U] tensors are padded up the bucket ladder (S, U) and to a
    device multiple (B), then placed across the flattened mesh with
    ``shard_leading_axis`` — each device contracts its own isolates, no
    collectives — through the ONE module-jitted einsum. Integer arithmetic
    end to end keeps every matrix bit-identical to the serial step."""
    from ..ops.distance import exceeds_int32_accumulation
    from .mesh import shard_leading_axis

    B = len(M_list)
    if B == 0:
        return []
    n_dev = int(devices) if devices else fleet_devices()
    S = bucket_dim(max(m.shape[0] for m in M_list), _PAD_FLOOR_S)
    U = bucket_dim(max(m.shape[1] for m in M_list), _PAD_FLOOR_U)
    Bp = -(-B // n_dev) * n_dev
    Mw = np.zeros((Bp, S, U), dtype=np.int32)
    Mp = np.zeros((Bp, S, U), dtype=np.int32)
    host_only = []   # isolates whose intersections could exceed int32
    for i, (m, w) in enumerate(zip(M_list, w_list)):
        s, u = m.shape
        weighted = m.astype(np.int64) * w[None, :]
        if exceeds_int32_accumulation(weighted):
            host_only.append(i)
            continue
        Mp[i, :s, :u] = m
        Mw[i, :s, :u] = weighted
    real = sum(m.shape[0] * m.shape[1] for m in M_list)
    metrics_registry.gauge_set(
        FLEET_PAD_RATIO, round(Bp * S * U / max(1, real), 3),
        help="padded/real element ratio of the last fleet contraction")
    _, Mw_d, Mp_d = shard_leading_axis(np.int32(0), Mw, Mp)
    from ..utils.timing import device_dispatch
    with device_dispatch("fleet membership contraction"):
        inter = np.asarray(
            _jitted_membership_step()(Mw_d, Mp_d)).astype(np.int64)
    out = [inter[i, :m.shape[0], :m.shape[0]]
           for i, m in enumerate(M_list)]
    for i in host_only:
        m, w = M_list[i], w_list[i]
        out[i] = (m.astype(np.int64) * w[None, :]) @ m.astype(np.int64).T
    return out


def record_shard_metrics(n_isolates: int, bucket: int) -> None:
    """Per-shard counters the obs registry (and `autocycler top`) sees."""
    metrics_registry.counter_inc(
        FLEET_SHARDS_TOTAL, 1,
        help="fleet shards dispatched", bucket=str(bucket))
    metrics_registry.counter_inc(
        FLEET_ISOLATES_TOTAL, n_isolates,
        help="isolates processed through the fleet runner")
