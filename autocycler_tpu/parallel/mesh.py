"""Device-mesh construction for batched multi-isolate runs.

The reference has no distributed backend at all (SURVEY.md §2.4 — rayon
threads plus GNU parallel processes); scaling across TPU chips is a
greenfield design dimension. The layout here:

- axis ``data``: independent isolates (pure data parallelism over genomes —
  no cross-isolate communication is algorithmically required),
- axis ``seq``: sequence length within an isolate (sequence parallelism for
  the k-mer window kernels; k-mer windows crossing shard boundaries are
  completed by a ring halo exchange over ICI, see parallel.batch).

Collectives ride the mesh via XLA (psum over ``seq``, nothing over ``data``),
so multi-host DCN layouts work unchanged by extending the ``data`` axis.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def mesh_axis_sizes(n_devices: int, seq_parallel: Optional[int] = None) -> Tuple[int, int]:
    """Factorise a device count into (data, seq) axis sizes. Sequence
    parallelism defaults to 2 when the device count is even (halo exchange
    is cheap on ICI), otherwise 1."""
    if seq_parallel is None:
        seq_parallel = 2 if n_devices % 2 == 0 and n_devices > 1 else 1
    if n_devices % seq_parallel != 0:
        raise ValueError(f"{n_devices} devices not divisible by seq={seq_parallel}")
    return n_devices // seq_parallel, seq_parallel


def make_mesh(n_devices: Optional[int] = None, seq_parallel: Optional[int] = None):
    """Build a 2-D ('data', 'seq') jax.sharding.Mesh."""
    import jax

    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"requested a {n_devices}-device mesh but only {len(devices)} "
                "device(s) are available; refusing to silently shrink the mesh "
                "(a 1-device mesh would 'pass' without exercising any collective)")
        devices = devices[:n_devices]
    data, seq = mesh_axis_sizes(len(devices), seq_parallel)
    device_array = np.array(devices).reshape(data, seq)
    return jax.sharding.Mesh(device_array, ("data", "seq"))
