"""Device-mesh construction for batched multi-isolate runs.

The reference has no distributed backend at all (SURVEY.md §2.4 — rayon
threads plus GNU parallel processes); scaling across TPU chips is a
greenfield design dimension. The layout here:

- axis ``data``: independent isolates (pure data parallelism over genomes —
  no cross-isolate communication is algorithmically required),
- axis ``seq``: sequence length within an isolate (sequence parallelism for
  the k-mer window kernels; k-mer windows crossing shard boundaries are
  completed by a ring halo exchange over ICI, see parallel.batch).

Collectives ride the mesh via XLA (psum over ``seq``, nothing over ``data``),
so multi-host DCN layouts work unchanged by extending the ``data`` axis.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def mesh_axis_sizes(n_devices: int, seq_parallel: Optional[int] = None) -> Tuple[int, int]:
    """Factorise a device count into (data, seq) axis sizes. Sequence
    parallelism defaults to 2 when the device count is even (halo exchange
    is cheap on ICI), otherwise 1."""
    if seq_parallel is None:
        seq_parallel = 2 if n_devices % 2 == 0 and n_devices > 1 else 1
    if n_devices % seq_parallel != 0:
        raise ValueError(f"{n_devices} devices not divisible by seq={seq_parallel}")
    return n_devices // seq_parallel, seq_parallel


def _devices_with_deadline():
    """jax.devices() behind the same timed-probe pattern as
    ops.distance._tpu_attached: a wedged tunnelled TPU can block backend
    init FOREVER (observed on the axon link), and `autocycler batch` must
    fail with a clear error instead of hanging the pipeline indefinitely.
    AUTOCYCLER_MESH_INIT_TIMEOUT (default 600 s — first TPU init through a
    healthy tunnel can take minutes) bounds the wait; <= 0 skips the
    guard.

    The timeout error is TERMINAL for this process: the abandoned daemon
    thread may still be blocked inside jax backend init, so catching the
    RuntimeError and re-touching jax (e.g. a host fallback that imports
    jax.numpy) can block on the same init lock or race a half-initialised
    backend. Callers that want to survive a wedged device must run host
    fallbacks in a fresh process, or pin JAX_PLATFORMS=cpu up front."""
    import threading

    from ..utils.knobs import knob_float

    timeout = float(knob_float("AUTOCYCLER_MESH_INIT_TIMEOUT"))
    # consult the (possibly background-resolved) device probe before paying
    # for a watchdog thread: a probe that already attached (or pinned the
    # backend to host) proves jax.devices() returns promptly, and a probe
    # that TIMED OUT proves the tunnel is wedged — fail fast instead of
    # blocking this process for the full mesh-init window.
    probe_kind = None
    try:
        from ..ops.distance import device_probe_report
        report = device_probe_report()
        if report.get("attached") is not None:   # a probe has resolved
            probe_kind = report.get("kind")
    except Exception:  # noqa: BLE001 — probe state is advisory here
        probe_kind = None
    if probe_kind == "timeout":
        raise RuntimeError(
            "device probe already timed out this run (wedged tunnel?); "
            "refusing to block on mesh init — set JAX_PLATFORMS=cpu to run "
            "on host devices, or clear the probe cache to retry")
    import jax
    if timeout <= 0 or probe_kind in ("ok", "no-tpu", "pinned"):
        return jax.devices()
    result = []

    def probe() -> None:
        try:
            result.append(jax.devices())
        except Exception as e:  # noqa: BLE001 — surfaced below
            result.append(e)

    t = threading.Thread(target=probe, daemon=True, name="mesh-init")
    t.start()
    t.join(timeout)
    if not result:
        raise RuntimeError(
            f"device backend did not initialise within {timeout:.0f}s "
            "(wedged tunnel?); set AUTOCYCLER_MESH_INIT_TIMEOUT to wait "
            "longer, or JAX_PLATFORMS=cpu to run on host devices")
    if isinstance(result[0], Exception):
        raise result[0]
    return result[0]


def make_mesh(n_devices: Optional[int] = None, seq_parallel: Optional[int] = None):
    """Build a 2-D ('data', 'seq') jax.sharding.Mesh."""
    import jax

    devices = _devices_with_deadline()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"requested a {n_devices}-device mesh but only {len(devices)} "
                "device(s) are available; refusing to silently shrink the mesh "
                "(a 1-device mesh would 'pass' without exercising any collective)")
        devices = devices[:n_devices]
    data, seq = mesh_axis_sizes(len(devices), seq_parallel)
    device_array = np.array(devices).reshape(data, seq)
    return jax.sharding.Mesh(device_array, ("data", "seq"))


def shard_leading_axis(replicated, *sharded):
    """Lay bucket-stacked arrays across the full device mesh.

    ``sharded`` arrays share a leading axis (one row per radix bucket); the
    leading axis is split over the flattened ('data', 'seq') mesh axes so
    every device owns rows/devices buckets, and ``replicated`` (the packed
    codes buffer every bucket gathers from) is copied to all devices. Used
    by the radix-sharded device grouping (ops.kmers): fixed per-row shapes
    mean each shard runs the same compiled sort on its own buckets.

    Degrades to a no-op — inputs returned unchanged, jit placing them on
    the default device — with a single device, a leading axis that does not
    divide the device count, or any mesh-construction failure (the caller's
    device path still computes the right answer, just unsharded)."""
    import jax

    try:
        devices = _devices_with_deadline()
        if len(devices) <= 1:
            return (replicated, *sharded)
        rows = sharded[0].shape[0]
        if rows % len(devices):
            return (replicated, *sharded)
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = make_mesh()
        rep = jax.device_put(replicated, NamedSharding(mesh, PartitionSpec()))
        out = tuple(
            jax.device_put(a, NamedSharding(
                mesh,
                PartitionSpec(("data", "seq"),
                              *((None,) * (np.ndim(a) - 1)))))
            for a in sharded)
        return (rep, *out)
    except Exception:  # noqa: BLE001 — sharding is an optimisation only
        return (replicated, *sharded)


def make_multihost_mesh(n_devices: Optional[int] = None,
                        n_hosts: int = 2,
                        seq_parallel: Optional[int] = None):
    """A ('data', 'seq') mesh laid out for a multi-host topology
    (BASELINE.json's "DCN only if needed" projection, VERDICT r4 item 8).

    The layout rule is the scaling-book recipe applied to this workload:
    the ONLY collectives are over 'seq' (the halo ppermute + the psum in
    parallel/batch.py — ICI-class traffic), so 'seq' groups must never
    straddle a host boundary; 'data' carries no collectives at all (the
    isolates are independent), so it is the one axis allowed to span DCN.
    Devices are taken host-major (each host's devices contiguous), every
    'seq' row lives inside one host, and the 'data' axis runs across
    hosts. With real multi-host devices the host-locality of every 'seq'
    group is asserted via device.process_index; on a single-process
    virtual mesh the assertion is vacuous and the projection is the
    shape/layout math — which is exactly what the driver's CPU dry run
    validates for bit-identity."""
    import jax

    devices = _devices_with_deadline()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"requested a {n_devices}-device mesh but only {len(devices)} "
                "device(s) are available")
        devices = devices[:n_devices]
    n = len(devices)
    if n % n_hosts != 0:
        raise ValueError(f"{n} devices not divisible by {n_hosts} hosts")
    per_host = n // n_hosts
    data, seq = mesh_axis_sizes(n, seq_parallel)
    if per_host % seq != 0:
        raise ValueError(
            f"seq={seq} does not fit within one host's {per_host} devices; "
            "the seq axis (ICI collectives) must not straddle hosts")
    # jax.devices() orders devices process-major already, so the flat
    # host-major [host, local_data, seq] layout IS a straight reshape; the
    # function's layout guarantees are carried by the divisibility checks
    # above and the process-locality assertion below, not by reordering
    device_array = np.array(devices).reshape(data, seq)
    for row in device_array:
        hosts = {getattr(d, "process_index", 0) for d in row}
        if len(hosts) > 1:
            raise ValueError(
                f"seq group {list(row)} spans processes {sorted(hosts)}; "
                "ICI collectives would ride DCN")
    return jax.sharding.Mesh(device_array, ("data", "seq"))
