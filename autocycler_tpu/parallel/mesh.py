"""Device-mesh construction for batched multi-isolate runs.

The reference has no distributed backend at all (SURVEY.md §2.4 — rayon
threads plus GNU parallel processes); scaling across TPU chips is a
greenfield design dimension. The layout here:

- axis ``data``: independent isolates (pure data parallelism over genomes —
  no cross-isolate communication is algorithmically required),
- axis ``seq``: sequence length within an isolate (sequence parallelism for
  the k-mer window kernels; k-mer windows crossing shard boundaries are
  completed by a ring halo exchange over ICI, see parallel.batch).

Collectives ride the mesh via XLA (psum over ``seq``, nothing over ``data``),
so multi-host DCN layouts work unchanged by extending the ``data`` axis.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def mesh_axis_sizes(n_devices: int, seq_parallel: Optional[int] = None) -> Tuple[int, int]:
    """Factorise a device count into (data, seq) axis sizes. Sequence
    parallelism defaults to 2 when the device count is even (halo exchange
    is cheap on ICI), otherwise 1."""
    if seq_parallel is None:
        seq_parallel = 2 if n_devices % 2 == 0 and n_devices > 1 else 1
    if n_devices % seq_parallel != 0:
        raise ValueError(f"{n_devices} devices not divisible by seq={seq_parallel}")
    return n_devices // seq_parallel, seq_parallel


def _devices_with_deadline():
    """jax.devices() behind the same timed-probe pattern as
    ops.distance._tpu_attached: a wedged tunnelled TPU can block backend
    init FOREVER (observed on the axon link), and `autocycler batch` must
    fail with a clear error instead of hanging the pipeline indefinitely.
    AUTOCYCLER_MESH_INIT_TIMEOUT (default 600 s — first TPU init through a
    healthy tunnel can take minutes) bounds the wait; <= 0 skips the
    guard."""
    import os
    import sys
    import threading

    try:
        timeout = float(os.environ.get("AUTOCYCLER_MESH_INIT_TIMEOUT", "600"))
    except ValueError:
        print("autocycler: ignoring malformed AUTOCYCLER_MESH_INIT_TIMEOUT",
              file=sys.stderr)
        timeout = 600.0
    import jax
    if timeout <= 0:
        return jax.devices()
    result = []

    def probe() -> None:
        try:
            result.append(jax.devices())
        except Exception as e:  # noqa: BLE001 — surfaced below
            result.append(e)

    t = threading.Thread(target=probe, daemon=True, name="mesh-init")
    t.start()
    t.join(timeout)
    if not result:
        raise RuntimeError(
            f"device backend did not initialise within {timeout:.0f}s "
            "(wedged tunnel?); set AUTOCYCLER_MESH_INIT_TIMEOUT to wait "
            "longer, or JAX_PLATFORMS=cpu to run on host devices")
    if isinstance(result[0], Exception):
        raise result[0]
    return result[0]


def make_mesh(n_devices: Optional[int] = None, seq_parallel: Optional[int] = None):
    """Build a 2-D ('data', 'seq') jax.sharding.Mesh."""
    import jax

    devices = _devices_with_deadline()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"requested a {n_devices}-device mesh but only {len(devices)} "
                "device(s) are available; refusing to silently shrink the mesh "
                "(a 1-device mesh would 'pass' without exercising any collective)")
        devices = devices[:n_devices]
    data, seq = mesh_axis_sizes(len(devices), seq_parallel)
    device_array = np.array(devices).reshape(data, seq)
    return jax.sharding.Mesh(device_array, ("data", "seq"))
