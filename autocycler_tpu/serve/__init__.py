"""Assembly-as-a-service: the `autocycler serve` daemon and its client.

A long-lived process amortizes every one-time cost the CLI pays per
invocation — JAX init, JIT compiles, the parse/end-repair warm-start
caches, the device probe, the shared worker pool — across a stream of
isolate jobs submitted over a local HTTP endpoint (TCP loopback or a Unix
domain socket). Modules:

- :mod:`.protocol` — the job-spec / job-record wire format and validation;
- :mod:`.scheduler` — the bounded work queue with per-job fault isolation
  (``utils.resilience.RunManifest`` + quarantine) and per-job run dirs;
- :mod:`.server` — the HTTP surface (``/jobs``, ``/metrics``, ``/healthz``,
  per-job trace streaming) and the `autocycler serve` entry point;
- :mod:`.client` — the thin `autocycler submit` client.
"""

from .protocol import DEFAULT_PORT, JobSpec, parse_job_spec  # noqa: F401
