"""Thin client for the serve daemon: `autocycler submit`.

Submits one isolate job over loopback HTTP (TCP or Unix socket), prints
the job record, and can wait for completion (``--wait``) or follow the
job's span stream live (``--follow`` — reuses the `autocycler watch`
renderer on the job's run directory, which the daemon creates shortly
after admission; the follower polls until it appears).

Endpoint resolution order: ``--server`` URL > ``--socket`` path >
``--dir`` (reads the daemon's ``serve.json`` discovery file) >
``AUTOCYCLER_SERVE`` env > ``http://127.0.0.1:8642``.
"""

from __future__ import annotations

import http.client
import json
import os
import socket
import time
from pathlib import Path
from typing import Optional, Tuple
from urllib.parse import urlparse

from ..utils import AutocyclerError, log
from .protocol import (DEFAULT_PORT, SERVE_INFO_JSON, TRACE_HEADER, JobSpec,
                       mint_trace_id, sanitize_trace_id)


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, path: str, timeout: float = 10.0):
        super().__init__("localhost", timeout=timeout)
        self._unix_path = path

    def connect(self):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self.timeout)
        sock.connect(self._unix_path)
        self.sock = sock


def _read_serve_info(path) -> dict:
    """Never-raise ``serve.json`` reader, mirroring ``read_manifest``: a
    missing, torn (daemon mid-write or crashed), or non-object discovery
    file yields {} — the CALLER decides whether that's fatal, with one
    clear error at the decision point instead of a JSONDecodeError
    traceback from whichever byte the tear landed on."""
    try:
        data = json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return {}
    return data if isinstance(data, dict) else {}


def resolve_endpoint(server: Optional[str] = None,
                     socket_path: Optional[str] = None,
                     serve_dir=None) -> str:
    """The daemon endpoint as ``http://host:port`` or ``unix:<path>``."""
    if server:
        return server
    if socket_path:
        return f"unix:{socket_path}"
    if serve_dir is not None:
        info_path = Path(serve_dir) / SERVE_INFO_JSON
        endpoint = _read_serve_info(info_path).get("endpoint")
        if isinstance(endpoint, str) and endpoint:
            return endpoint
        raise AutocyclerError(
            f"cannot resolve a daemon endpoint from {info_path} "
            f"(missing, unreadable, or torn discovery file) — is "
            f"`autocycler serve` running with that root?")
    from ..utils.knobs import knob_str
    env = (knob_str("AUTOCYCLER_SERVE") or "").strip()
    if env:
        return env
    return f"http://127.0.0.1:{DEFAULT_PORT}"


def _connect(endpoint: str, timeout: float = 10.0
             ) -> http.client.HTTPConnection:
    if endpoint.startswith("unix:"):
        return _UnixHTTPConnection(endpoint[len("unix:"):], timeout=timeout)
    parsed = urlparse(endpoint if "://" in endpoint
                      else f"http://{endpoint}")
    return http.client.HTTPConnection(parsed.hostname or "127.0.0.1",
                                      parsed.port or DEFAULT_PORT,
                                      timeout=timeout)


def request_json(endpoint: str, method: str, path: str,
                 body: Optional[dict] = None,
                 timeout: float = 10.0,
                 headers: Optional[dict] = None) -> Tuple[int, dict]:
    """One JSON request/response round trip; raises AutocyclerError when
    the daemon is unreachable. ``headers`` are extra request headers
    (e.g. the X-Autocycler-Trace correlation id)."""
    conn = _connect(endpoint, timeout=timeout)
    try:
        payload = json.dumps(body).encode() if body is not None else None
        extra = dict(headers or {})
        headers = {"Content-Type": "application/json"} if payload else {}
        headers.update(extra)
        # shared-secret auth rides automatically when the client's
        # environment carries the daemon's token knob; the value is sent
        # on the wire only, never logged
        from ..utils.knobs import knob_str
        token = knob_str("AUTOCYCLER_SERVE_TOKEN") or None
        if token:
            headers["Authorization"] = f"Bearer {token}"
        try:
            conn.request(method, path, body=payload, headers=headers)
            resp = conn.getresponse()
            raw = resp.read()
        except (OSError, http.client.HTTPException) as e:
            raise AutocyclerError(
                f"cannot reach autocycler serve at {endpoint} "
                f"({type(e).__name__}: {e}) — is the daemon running?")
        try:
            data = json.loads(raw.decode() or "{}")
        except (json.JSONDecodeError, UnicodeDecodeError):
            data = {"raw": raw.decode(errors="replace")}
        return resp.status, data
    finally:
        conn.close()


def wait_for_job(endpoint: str, job_id: str, poll_s: float = 0.5,
                 timeout: Optional[float] = None) -> dict:
    """Poll ``/jobs/<id>`` until the job reaches a terminal state."""
    t0 = time.monotonic()
    while True:
        status, record = request_json(endpoint, "GET", f"/jobs/{job_id}")
        if status != 200:
            raise AutocyclerError(
                f"job {job_id} lookup failed (HTTP {status}): "
                f"{record.get('error', record)}")
        if record.get("state") in ("done", "failed"):
            return record
        if timeout is not None and time.monotonic() - t0 > timeout:
            raise AutocyclerError(
                f"timed out after {timeout}s waiting for {job_id} "
                f"(last state: {record.get('state')})")
        time.sleep(poll_s)


def submit(assemblies_dir, server: Optional[str] = None,
           socket_path: Optional[str] = None, serve_dir=None,
           fleet_dir=None,
           command: str = "compress", out_dir=None, kmer: int = 51,
           max_contigs: int = 25, threads: int = 8,
           wait: bool = False, follow: bool = False,
           poll_s: float = 0.5, timeout: Optional[float] = None,
           trace_id: Optional[str] = None) -> int:
    """CLI entry for `autocycler submit`. Returns the exit code: 0 for an
    admitted (or, with --wait/--follow, completed) job, 1 for a failed one.

    With ``fleet_dir``, the endpoint comes from the client-side router
    (least-loaded healthy replica) instead of a single daemon's
    discovery file. Every submission mints (or sanitizes the caller's)
    correlation id and sends it as the X-Autocycler-Trace header; the
    daemon threads it into the job's trace/QC/ledger so
    `autocycler report --correlate <id>` can reassemble the whole story."""
    if fleet_dir is not None:
        from .router import pick_replica
        picked = pick_replica(fleet_dir=fleet_dir)
        endpoint = picked["endpoint"]
        log.message(
            f"routed to {picked['name']} ({endpoint}; "
            f"queue {picked['queue_depth']}, "
            f"busy {picked['busy_workers']}/{picked['workers']}, "
            f"{picked['candidates']} healthy)")
    else:
        endpoint = resolve_endpoint(server, socket_path, serve_dir)
    cid = sanitize_trace_id(trace_id) or mint_trace_id()
    spec = JobSpec(assemblies_dir=str(assemblies_dir), command=command,
                   out_dir=str(out_dir) if out_dir else None, kmer=kmer,
                   max_contigs=max_contigs, threads=threads)
    status, record = request_json(endpoint, "POST", "/jobs",
                                  body=spec.to_dict(),
                                  headers={TRACE_HEADER: cid})
    if status != 202:
        raise AutocyclerError(
            f"job submission rejected (HTTP {status}): "
            f"{record.get('error', record)}")
    job_id = record["id"]
    log.message(f"submitted {job_id} [{record['state']}] to {endpoint}")
    log.message(f"  trace id: {cid} "
                f"(autocycler report --correlate {cid})")
    log.message(f"  run dir: {record['run_dir']}")
    log.message(f"  outputs: {record['out_dir']}")
    if follow:
        # the daemon creates the run dir once the job starts; the follower
        # polls until trace.jsonl appears, then renders frames until the
        # job's run finishes
        from ..obs.watch import watch as watch_run
        watch_run(record["run_dir"], follow=True)
        record = wait_for_job(endpoint, job_id, poll_s=poll_s,
                              timeout=timeout)
    elif wait:
        record = wait_for_job(endpoint, job_id, poll_s=poll_s,
                              timeout=timeout)
    else:
        return 0
    state = record.get("state")
    wall = record.get("wall_s")
    log.message(f"{job_id} {state}"
                + (f" in {wall:.2f}s" if isinstance(wall, (int, float))
                   else ""))
    if state == "failed":
        log.message(f"  error: {record.get('error')}")
        return 1
    return 0
