"""Wire format of the serve daemon: job specs, job records, endpoints.

Everything is plain JSON over HTTP so any client (curl, a workflow engine,
`autocycler submit`) can drive the daemon. A *job spec* is what the client
POSTs to ``/jobs``; a *job record* is what the daemon returns from
``/jobs`` and ``/jobs/<id>`` — the spec plus lifecycle state, timestamps,
and the paths of the run directory (trace/QC/ledger artifacts) and the
assembly output directory.

Validation mirrors the CLI flag checks (`cli.py` / `commands/compress.py`)
so a spec the daemon accepts is exactly one the CLI would have accepted —
a rejected spec costs an HTTP 400, never a quarantined job.
"""

from __future__ import annotations

import re
import uuid
from dataclasses import dataclass
from typing import Optional

from ..utils.resilience import InputError

PROTOCOL_VERSION = 1
DEFAULT_PORT = 8642

# daemon discovery file written into the serve root so local clients
# (`autocycler submit --dir <root>`) find the endpoint without flags
SERVE_INFO_JSON = "serve.json"

# cross-process correlation id header: the client mints one id per
# submission and the daemon threads it into the job's trace run header,
# QC scope, ledger and fleet-shard spans — `autocycler report --correlate
# <id>` then merges every matching trace.jsonl into one Chrome trace. It
# rides a header (not the spec body) so pre-federation daemons ignore it
# instead of rejecting the spec.
TRACE_HEADER = "X-Autocycler-Trace"
TRACE_ID_MAX = 64
_TRACE_ID_RE = re.compile(r"[^A-Za-z0-9._-]+")


def mint_trace_id() -> str:
    """A fresh correlation id: short, URL/filename/label-safe."""
    return f"t-{uuid.uuid4().hex[:12]}"


def sanitize_trace_id(raw) -> Optional[str]:
    """Normalize a client-supplied correlation id: keep only
    ``[A-Za-z0-9._-]``, cap the length, and return None for anything
    empty — a hostile or torn header value can never become a path
    component or an unbounded label."""
    if not isinstance(raw, str):
        return None
    cleaned = _TRACE_ID_RE.sub("", raw.strip())[:TRACE_ID_MAX]
    return cleaned or None

# job lifecycle: queued -> running -> done | failed. "failed" covers
# quarantined jobs — the job is recorded and the daemon keeps serving.
JOB_STATES = ("queued", "running", "done", "failed")

# what a job runs: "compress" is the single-isolate unitig-graph build;
# "pipeline" continues through cluster -> trim -> resolve -> combine,
# mirroring one isolate of `autocycler batch`.
JOB_COMMANDS = ("compress", "pipeline")

# fan-out bound for one batch submission (POST /jobs with a "batch" array)
BATCH_MAX = 64


@dataclass
class JobSpec:
    """One validated isolate job."""

    assemblies_dir: str
    command: str = "compress"
    out_dir: Optional[str] = None     # default: <run_dir>/out
    kmer: int = 51
    max_contigs: int = 25
    threads: int = 8
    cutoff: float = 0.2               # pipeline only
    min_assemblies: Optional[int] = None

    def to_dict(self) -> dict:
        return {
            "assemblies_dir": self.assemblies_dir,
            "command": self.command,
            "out_dir": self.out_dir,
            "kmer": self.kmer,
            "max_contigs": self.max_contigs,
            "threads": self.threads,
            "cutoff": self.cutoff,
            "min_assemblies": self.min_assemblies,
        }


def parse_job_spec(data) -> JobSpec:
    """Validate a decoded JSON body into a :class:`JobSpec`; raises
    :class:`InputError` with a client-renderable message on any problem
    (the server maps it to HTTP 400)."""
    if not isinstance(data, dict):
        raise InputError("job spec must be a JSON object")
    unknown = set(data) - {"assemblies_dir", "command", "out_dir", "kmer",
                           "max_contigs", "threads", "cutoff",
                           "min_assemblies"}
    if unknown:
        raise InputError(f"unknown job spec field(s): "
                         f"{', '.join(sorted(unknown))}")
    assemblies_dir = data.get("assemblies_dir")
    if not assemblies_dir or not isinstance(assemblies_dir, str):
        raise InputError("job spec requires a string 'assemblies_dir'")
    command = data.get("command", "compress")
    if command not in JOB_COMMANDS:
        raise InputError(f"unknown job command {command!r} "
                         f"(choose from {', '.join(JOB_COMMANDS)})")
    out_dir = data.get("out_dir")
    if out_dir is not None and not isinstance(out_dir, str):
        raise InputError("'out_dir' must be a string when given")

    def _int(name, default, lo, hi):
        value = data.get(name, default)
        if value is None:
            return None
        if isinstance(value, bool) or not isinstance(value, int):
            raise InputError(f"'{name}' must be an integer")
        if not lo <= value <= hi:
            raise InputError(f"'{name}' must be between {lo} and {hi} "
                             f"(inclusive)")
        return value

    kmer = _int("kmer", 51, 11, 501)
    if kmer % 2 == 0:
        raise InputError("'kmer' must be odd")
    max_contigs = _int("max_contigs", 25, 1, 10000)
    threads = _int("threads", 8, 1, 100)
    cutoff = data.get("cutoff", 0.2)
    if isinstance(cutoff, bool) or not isinstance(cutoff, (int, float)) \
            or not 0.0 < float(cutoff) < 1.0:
        raise InputError("'cutoff' must be a number between 0 and 1 "
                         "(exclusive)")
    min_assemblies = data.get("min_assemblies")
    if min_assemblies is not None:
        min_assemblies = _int("min_assemblies", None, 1, 10000)
    return JobSpec(assemblies_dir=assemblies_dir, command=command,
                   out_dir=out_dir, kmer=kmer, max_contigs=max_contigs,
                   threads=threads, cutoff=float(cutoff),
                   min_assemblies=min_assemblies)


def is_batch_spec(data) -> bool:
    """True when a POST /jobs body is a multi-isolate batch submission."""
    return isinstance(data, dict) and "batch" in data


def is_fleet_batch(data) -> bool:
    """True when a batch body opts into the fleet runner: a truthy
    top-level ``"fleet"`` next to the ``"batch"`` array. The batch then
    admits as ONE scheduler job whose execution fans the items over the
    mesh (commands.batch.run_fleet_jobs) instead of N child jobs."""
    return is_batch_spec(data) and bool(data.get("fleet"))


def validate_fleet_batch(specs) -> None:
    """Constraints the fleet runner adds on top of batch validation: the
    items share one device plan, so kmer and max_contigs must be uniform
    across the batch, and every item must run the full pipeline (a
    compress-only fleet item would silently skip its cluster/consensus
    outputs). Raises :class:`InputError` (HTTP 400)."""
    if len({s.kmer for s in specs}) > 1:
        raise InputError("fleet batch requires a uniform 'kmer' "
                         "across all items")
    if len({s.max_contigs for s in specs}) > 1:
        raise InputError("fleet batch requires a uniform 'max_contigs' "
                         "across all items")
    bad = [i for i, s in enumerate(specs) if s.command != "pipeline"]
    if bad:
        raise InputError(f"fleet batch items must use command='pipeline' "
                         f"(item {bad[0]} is "
                         f"{specs[bad[0]].command!r})")


def parse_batch_spec(data) -> list:
    """Validate a batch body into a list of :class:`JobSpec`.

    The body carries a ``"batch"`` array of per-isolate spec objects;
    every other top-level field is a shared default merged under each
    child (a child's own field wins). The whole batch validates or the
    whole batch is rejected — partial admission would leave a client
    guessing which isolates were accepted."""
    if not isinstance(data, dict):
        raise InputError("batch spec must be a JSON object")
    items = data.get("batch")
    if not isinstance(items, list) or not items:
        raise InputError("'batch' must be a non-empty JSON array of "
                         "job specs")
    if len(items) > BATCH_MAX:
        raise InputError(f"batch fan-out is capped at {BATCH_MAX} jobs "
                         f"(got {len(items)})")
    shared = {k: v for k, v in data.items() if k not in ("batch", "fleet")}
    specs = []
    for i, item in enumerate(items):
        if not isinstance(item, dict):
            raise InputError(f"batch item {i} must be a JSON object")
        merged = dict(shared)
        merged.update(item)
        try:
            specs.append(parse_job_spec(merged))
        except InputError as e:
            raise InputError(f"batch item {i}: {e}") from None
    return specs
