"""Client-side job router: pick the least-loaded healthy replica.

ROADMAP rung (b) of serve scale-out. `autocycler submit --fleet-dir`
discovers every replica's ``serve.json`` (via :mod:`obs.federate`'s
registry), probes each ``/healthz`` with the federation timeout, and
routes the job to the replica with the lowest load score::

    ((queue_depth + busy_workers) / max(1, workers), jobs_total, name)

The leading term is pressure normalised by capacity — a 1-worker replica
with one running job is MORE loaded than a 4-worker replica with two.
``jobs_total`` (lifetime admissions from /healthz job counts) breaks
ties so an idle fleet round-robins instead of hammering the
lexicographically-first replica, and the name keeps the choice
deterministic. Shedding replicas (burn-rate degraded, see serve/slo.py)
are avoided while any non-shedding healthy replica exists; probes never
raise — an unreachable replica is simply not a candidate."""

from __future__ import annotations

import time
from typing import List, Optional

from ..obs import metrics_registry
from ..obs.federate import discover_replicas, fed_timeout_s
from ..utils import AutocyclerError
from .client import request_json

PICKS_TOTAL = "autocycler_router_picks_total"


class NoHealthyReplicaError(AutocyclerError):
    """Raised when routing finds no replica that answers /healthz."""


def probe_replicas(replicas: List[dict],
                   timeout: Optional[float] = None) -> List[dict]:
    """One /healthz round trip per replica; never raises. Returns one
    block per replica with its load inputs (unreachable -> healthy=False
    plus the error string)."""
    timeout = fed_timeout_s() if timeout is None else timeout
    probes: List[dict] = []
    for rep in replicas:
        block = {"name": rep["name"], "endpoint": rep["endpoint"],
                 "healthy": False, "queue_depth": 0, "busy_workers": 0,
                 "workers": 1, "jobs_total": 0, "shedding": False}
        t0 = time.perf_counter()
        try:
            status, health = request_json(rep["endpoint"], "GET", "/healthz",
                                          timeout=timeout)
        except (AutocyclerError, OSError, ValueError) as e:
            block["error"] = str(e)
            probes.append(block)
            continue
        block["probe_s"] = round(time.perf_counter() - t0, 6)
        if status != 200 or not isinstance(health, dict):
            block["error"] = f"healthz returned HTTP {status}"
            probes.append(block)
            continue
        jobs = health.get("jobs") or {}
        block.update(
            healthy=True,
            queue_depth=int(health.get("queue_depth") or 0),
            busy_workers=int(health.get("busy_workers") or 0),
            workers=max(1, int(health.get("workers") or 1)),
            jobs_total=sum(n for n in jobs.values()
                           if isinstance(n, int)),
            shedding=bool((health.get("slo") or {}).get("shedding")),
            version=health.get("version"))
        probes.append(block)
    return probes


def load_score(probe: dict) -> tuple:
    """Sort key: lower is less loaded (see module docstring)."""
    pressure = (probe.get("queue_depth", 0) + probe.get("busy_workers", 0)) \
        / max(1, probe.get("workers", 1))
    return (pressure, probe.get("jobs_total", 0), probe.get("name", ""))


def pick_replica(fleet_dir=None, endpoints: Optional[List[str]] = None,
                 timeout: Optional[float] = None,
                 registry=None) -> dict:
    """Discover + probe + choose. Returns the winning probe block
    (``endpoint`` is what the caller submits to). Raises
    :class:`NoHealthyReplicaError` when nothing answers."""
    replicas = discover_replicas(fleet_dir=fleet_dir, endpoints=endpoints)
    if not replicas:
        where = f"fleet dir {fleet_dir}" if fleet_dir is not None \
            else "endpoint list"
        raise NoHealthyReplicaError(
            f"no replicas discovered in {where} — is any "
            f"`autocycler serve` running with a root under it?")
    probes = probe_replicas(replicas, timeout=timeout)
    healthy = [p for p in probes if p["healthy"]]
    if not healthy:
        errors = "; ".join(f"{p['name']}: {p.get('error', 'unreachable')}"
                           for p in probes)
        raise NoHealthyReplicaError(
            f"no healthy replica among {len(probes)} probed ({errors})")
    # prefer replicas that are not shedding load; if the whole fleet is
    # degraded, the least-loaded shedding replica still beats a client error
    pool = [p for p in healthy if not p.get("shedding")] or healthy
    winner = min(pool, key=load_score)
    reg = registry or metrics_registry.registry()
    reg.counter_inc(PICKS_TOTAL, 1, help="router replica picks",
                    replica=winner["name"])
    winner = dict(winner)
    winner["candidates"] = len(healthy)
    return winner
