"""Bounded work queue with per-job fault isolation for `autocycler serve`.

One scheduler owns the daemon's job table, a bounded FIFO queue and a
pool of worker threads (``AUTOCYCLER_SERVE_WORKERS``, default
``min(4, cpu//2)``; ``1`` reproduces the original single-worker daemon
bit for bit). Each job runs the same code path the CLI runs — compress
(optionally through the full cluster/trim/resolve/combine pipeline) — but
inside a quarantine: an :class:`AutocyclerError` or OSError marks the job
failed in the job table and the ``serve_manifest.json`` run manifest
(:class:`utils.resilience.RunManifest`) and the worker moves on to the
next job. One poisoned job never kills the process.

Each job owns a run directory (``<root>/jobs/<id>/``) receiving the
standard per-run artifacts — ``trace.jsonl``, ``qc_report.json``,
``ledger.json`` — exactly what ``AUTOCYCLER_TRACE_DIR`` produces for a CLI
run, so `autocycler watch` and `autocycler report` work unchanged on a
daemon job. Concurrent jobs stay disjoint because each opens its own
*scoped* trace run (:func:`obs.trace.open_run` bound to the executing
thread and propagated into pool tasks), tags QC/ledger entries with its
job id as the isolate scope, and writes scope-filtered reports at the
end. Device dispatches serialize through the process-wide device token
(:func:`utils.timing.enable_device_token`): one job on-chip at a time
while other jobs' host stages — load, parse, encode — overlap freely.

The warm wins come for free from sharing the process: the JIT caches, the
resolved device probe, the shared ``utils.pool`` executor and — because the
daemon points ``utils.cache`` at one shared directory — the parse and
end-repair caches all persist across jobs and across workers.

Batch fan-out: one ``POST /jobs`` body with a ``"batch"`` array admits N
child jobs under one parent id; ``GET /jobs/<parent>`` aggregates child
states (the admission path fleet batch rides later).
"""

from __future__ import annotations

import contextlib
import gc
import os
import queue
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

from ..obs import ledger, metrics_registry, trace
from ..obs import qc as obs_qc
from ..obs.metrics_registry import SECONDS_BUCKETS
from ..utils import AutocyclerError, log
from ..utils.resilience import InputError, RunManifest
from .protocol import JobSpec, parse_job_spec
from .slo import SloTracker

MANIFEST_NAME = "serve_manifest.json"

# registry metric names: the live /metrics endpoint and bench servesmoke
# both read these
JOBS_TOTAL = "autocycler_serve_jobs_total"
SUBMITTED_TOTAL = "autocycler_serve_submitted_total"
REJECTED_TOTAL = "autocycler_serve_rejected_total"
SHED_TOTAL = "autocycler_serve_shed_total"
QUEUE_DEPTH = "autocycler_serve_queue_depth"
JOB_SECONDS = "autocycler_serve_job_seconds"
WORKERS_GAUGE = "autocycler_serve_workers"
BUSY_GAUGE = "autocycler_serve_busy_workers"
WORKER_BUSY_GAUGE = "autocycler_serve_worker_busy"


def default_workers() -> int:
    """The scheduler pool width: ``AUTOCYCLER_SERVE_WORKERS`` when set
    (floor 1), else ``min(4, cpu//2)`` with floor 1 — conservative because
    every job already fans its own stages across the shared pool."""
    from ..utils.knobs import knob_int
    configured = knob_int("AUTOCYCLER_SERVE_WORKERS")
    if configured is not None:
        return max(1, int(configured))
    return max(1, min(4, (os.cpu_count() or 2) // 2))


def _id_num(name: str) -> int:
    try:
        return int(name.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        return 0


class QueueFullError(AutocyclerError):
    """The bounded work queue is at capacity — the server maps this to
    HTTP 503 so clients can back off and retry."""


class Job:
    """One job's record: the spec plus lifecycle state and artifact paths."""

    def __init__(self, job_id: str, spec: JobSpec, run_dir: Path,
                 out_dir: Path,
                 fleet_specs: Optional[List[JobSpec]] = None,
                 trace_id: Optional[str] = None):
        self.id = job_id
        self.spec = spec
        self.run_dir = run_dir
        self.out_dir = out_dir
        self.state = "queued"
        self.error: Optional[str] = None
        self.resumed = False              # replayed after a daemon restart
        self.parent: Optional[str] = None  # batch parent id, when fanned out
        # a fleet admission: ONE queue slot whose execution fans these
        # items over the mesh (commands.batch.run_fleet_jobs)
        self.fleet_specs: Optional[List[JobSpec]] = fleet_specs
        # cross-process correlation id (X-Autocycler-Trace): already
        # sanitized at the HTTP boundary, threaded (never assigned late —
        # the job is worker-visible once enqueued) into the trace run
        # header, QC report and ledger
        self.trace_id: Optional[str] = trace_id
        self.submitted_epoch = time.time()
        self.started_epoch: Optional[float] = None
        self.finished_epoch: Optional[float] = None
        self.wall_s: Optional[float] = None
        self.queue_wait_s: Optional[float] = None

    def to_dict(self) -> dict:
        if self.fleet_specs:
            # additive key only: existing clients keep parsing records
            # that predate fleet admissions unchanged
            return {**self._base_dict(),
                    "fleet": len(self.fleet_specs)}
        return self._base_dict()

    def _base_dict(self) -> dict:
        record = {
            "id": self.id,
            "state": self.state,
            "spec": self.spec.to_dict(),
            "run_dir": str(self.run_dir),
            "out_dir": str(self.out_dir),
            "error": self.error,
            "parent": self.parent,
            "submitted_epoch": round(self.submitted_epoch, 3),
            "started_epoch": round(self.started_epoch, 3)
            if self.started_epoch else None,
            "finished_epoch": round(self.finished_epoch, 3)
            if self.finished_epoch else None,
            "wall_s": round(self.wall_s, 3) if self.wall_s is not None
            else None,
            "queue_wait_s": round(self.queue_wait_s, 3)
            if self.queue_wait_s is not None else None,
        }
        if self.trace_id:
            # additive key only: pre-federation clients keep parsing
            record["trace_id"] = self.trace_id
        return record


class Scheduler:
    """The daemon's job table + bounded queue + worker pool."""

    # lint: locks.guarded-fields — mutations of these instance fields must
    # sit under `with self._lock:` (analysis.rules.locks enforces it)
    _GUARDED_BY = {"_lock": ("_jobs", "_parents", "_busy", "_next_id")}

    def __init__(self, root, capacity: int = 16,
                 workers: Optional[int] = None):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.capacity = max(1, int(capacity))
        self.workers = max(1, int(workers)) if workers is not None \
            else default_workers()
        self._queue: "queue.Queue[Job]" = queue.Queue(maxsize=self.capacity)
        self._jobs: Dict[str, Job] = {}
        self._parents: Dict[str, dict] = {}   # batch id -> children meta
        self._busy: Dict[str, str] = {}       # worker name -> job id
        self._lock = threading.Lock()
        # legacy whole-job serialization: held across execute() only in
        # single-worker mode, preserving the original daemon's semantics
        # bit for bit (and keeping SLO reads provably disjoint from it)
        self._run_lock = threading.Lock()
        self._next_id = 1
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # latency SLO tracking: its own lock, disjoint from _run_lock and
        # _lock by construction (the sampler and /healthz read it mid-job)
        self.slo = SloTracker()
        self.slo.set_capacity(self.workers)
        # multi-worker mode serializes on-chip work through the device
        # token; single-worker leaves it off — zero-cost, bit-for-bit
        from ..utils.timing import enable_device_token
        enable_device_token(self.workers > 1)
        metrics_registry.gauge_set(
            WORKERS_GAUGE, self.workers,
            help="serve scheduler worker pool width")
        metrics_registry.gauge_set(
            BUSY_GAUGE, 0, help="serve workers currently executing a job")
        self.manifest = RunManifest.load(self.root / MANIFEST_NAME)
        # crash-safe replay: a previous daemon's unfinished jobs come back.
        # Jobs still "pending" re-enqueue, and EVERY job caught "running"
        # (a multi-worker daemon dies with up to N of them) resumes from
        # its last checkpointed stage when a worker picks it up
        # (docs/failure-modes.md "daemon restart").
        replay: List[Job] = []
        for name, entry in list(self.manifest.items.items()):
            # resume the id sequence past every recorded job so a restarted
            # daemon never reuses (and silently overwrites) a prior job id
            self._next_id = max(self._next_id, _id_num(name) + 1)
            if entry.get("kind") == "batch":
                # parents are aggregation records, never enqueued; rebuild
                # the fan-out map so GET /jobs/<parent> keeps answering
                kids = [k for k in (entry.get("children") or [])
                        if isinstance(k, str)]
                self._parents[name] = {
                    "children": kids,
                    "submitted_epoch": entry.get("submitted_epoch")}
                continue
            status = entry.get("status")
            if status not in ("pending", "running"):
                continue
            if entry.get("kind") == "fleet":
                # a fleet admission replays as ONE job; its execution
                # resumes from the fleet manifest's per-isolate stage
                # checkpoints (commands.batch.run_fleet_jobs resume=True)
                try:
                    fleet_specs = [parse_job_spec(s)
                                   for s in (entry.get("fleet_specs") or [])]
                    if not fleet_specs:
                        raise InputError("empty fleet spec list")
                except (InputError, TypeError) as e:
                    self.manifest.fail(name, f"unreplayable fleet spec: {e}")
                    continue
                run_dir = self.root / "jobs" / name
                out_dir = Path(entry.get("out_dir") or (run_dir / "out"))
                tid = entry.get("trace_id")
                job = Job(name, fleet_specs[0], run_dir, out_dir,
                          fleet_specs=fleet_specs,
                          trace_id=tid if isinstance(tid, str) else None)
                job.resumed = status == "running"
                submitted = entry.get("submitted_epoch")
                if isinstance(submitted, (int, float)):
                    job.submitted_epoch = float(submitted)
                replay.append(job)
                continue
            spec_data = entry.get("spec")
            if not isinstance(spec_data, dict):
                # pre-replay manifests carried no spec: nothing to re-run,
                # so record the interruption the way older daemons did
                if status == "running":
                    self.manifest.fail(name, "interrupted by daemon restart")
                continue
            try:
                spec = parse_job_spec(spec_data)
            except InputError as e:
                self.manifest.fail(name, f"unreplayable job spec: {e}")
                continue
            run_dir = self.root / "jobs" / name
            out_dir = Path(entry.get("out_dir") or (run_dir / "out"))
            tid = entry.get("trace_id")
            job = Job(name, spec, run_dir, out_dir,
                      trace_id=tid if isinstance(tid, str) else None)
            job.resumed = status == "running"
            parent = entry.get("parent")
            if isinstance(parent, str):
                job.parent = parent
            submitted = entry.get("submitted_epoch")
            if isinstance(submitted, (int, float)):
                job.submitted_epoch = float(submitted)
            replay.append(job)
        # re-enqueue in true submission order: the persisted submit
        # timestamp, tie-broken by the numeric id — NOT the lexicographic
        # id sort, which misorders once ids outgrow their zero padding
        replay.sort(key=lambda j: (j.submitted_epoch, _id_num(j.id)))
        for job in replay:
            try:
                self._queue.put_nowait(job)
            except queue.Full:
                # stays pending in the manifest; the next restart (or a
                # larger capacity) picks it up
                log.message(f"WARNING: serve: {job.id} not replayed — "
                            f"queue capacity {self.capacity} exhausted")
                continue
            self._jobs[job.id] = job
            log.message(
                f"serve: {job.id} "
                + ("resuming from last checkpointed stage"
                   if job.resumed else "re-enqueued after restart"))
        if replay:
            self._gauge_depth()

    # ---- admission ----

    def submit(self, spec: JobSpec,
               trace_id: Optional[str] = None) -> Job:
        """Admit one job into the bounded queue; raises
        :class:`QueueFullError` at capacity (never blocks the caller)."""
        with self._lock:
            job = self._admit_locked(spec, trace_id=trace_id)
        # persist everything replay needs: a restarted daemon rebuilds the
        # Job from the manifest entry alone
        self.manifest.annotate(
            job.id, spec=spec.to_dict(), out_dir=str(job.out_dir),
            submitted_epoch=round(job.submitted_epoch, 3),
            **({"trace_id": job.trace_id} if job.trace_id else {}))
        metrics_registry.counter_inc(
            SUBMITTED_TOTAL, 1, help="jobs admitted into the work queue")
        self._gauge_depth()
        return job

    def _admit_locked(self, spec: JobSpec,
                      parent: Optional[str] = None,
                      fleet_specs: Optional[List[JobSpec]] = None,
                      trace_id: Optional[str] = None) -> Job:
        """Create + enqueue one job. Caller holds ``self._lock``.
        ``fleet_specs`` and ``trace_id`` must be threaded through here
        (not assigned after) — the job is visible to workers the moment it
        is enqueued, and a late assignment would race a worker into the
        single-spec path (or an untagged trace run)."""
        job_id = f"job-{self._next_id:06d}"
        self._next_id += 1
        run_dir = self.root / "jobs" / job_id
        out_dir = Path(spec.out_dir) if spec.out_dir else run_dir / "out"
        job = Job(job_id, spec, run_dir, out_dir, fleet_specs=fleet_specs,
                  trace_id=trace_id)
        job.parent = parent
        try:
            self._queue.put_nowait(job)
        except queue.Full:
            metrics_registry.counter_inc(
                REJECTED_TOTAL, 1, help="jobs rejected at admission",
                reason="queue_full")
            raise QueueFullError(
                f"work queue is full ({self.capacity} jobs); "
                "retry after a job completes") from None
        self._jobs[job_id] = job
        return job

    def submit_fleet(self, specs: List[JobSpec],
                     trace_id: Optional[str] = None) -> Job:
        """Admit a fleet batch as ONE job: a single queue slot and worker
        whose execution fans the items over the device mesh
        (commands.batch.run_fleet_jobs), instead of ``submit_batch``'s N
        independent child jobs. Raises :class:`QueueFullError` when the
        queue is at capacity."""
        specs = list(specs)
        with self._lock:
            job = self._admit_locked(specs[0], fleet_specs=specs,
                                     trace_id=trace_id)
        # persist the full item list: a restarted daemon rebuilds the
        # fleet job from the manifest entry alone and resumes it from the
        # per-isolate stage checkpoints in its fleet manifest
        self.manifest.annotate(
            job.id, kind="fleet",
            fleet_specs=[s.to_dict() for s in specs],
            out_dir=str(job.out_dir),
            submitted_epoch=round(job.submitted_epoch, 3),
            **({"trace_id": job.trace_id} if job.trace_id else {}))
        metrics_registry.counter_inc(
            SUBMITTED_TOTAL, 1, help="jobs admitted into the work queue")
        self._gauge_depth()
        return job

    def submit_batch(self, specs: List[JobSpec],
                     trace_id: Optional[str] = None) -> dict:
        """Fan a multi-isolate batch out into child jobs under one parent
        id. All-or-nothing: when fewer than ``len(specs)`` queue slots are
        free the whole batch is rejected (503), so a client never has to
        reconstruct which half of its fleet was admitted."""
        specs = list(specs)
        with self._lock:
            free = self.capacity - self._queue.qsize()
            if len(specs) > free:
                metrics_registry.counter_inc(
                    REJECTED_TOTAL, len(specs),
                    help="jobs rejected at admission", reason="queue_full")
                raise QueueFullError(
                    f"batch of {len(specs)} exceeds the {free} free queue "
                    f"slot(s) (capacity {self.capacity}); retry after jobs "
                    "complete")
            parent_id = f"batch-{self._next_id:06d}"
            self._next_id += 1
            children = [self._admit_locked(spec, parent=parent_id,
                                           trace_id=trace_id)
                        for spec in specs]
            self._parents[parent_id] = {
                "children": [j.id for j in children],
                "submitted_epoch": round(time.time(), 3)}
        for job in children:
            self.manifest.annotate(
                job.id, spec=job.spec.to_dict(), out_dir=str(job.out_dir),
                submitted_epoch=round(job.submitted_epoch, 3),
                parent=parent_id,
                **({"trace_id": job.trace_id} if job.trace_id else {}))
        self.manifest.annotate(
            parent_id, kind="batch", children=[j.id for j in children],
            submitted_epoch=self._parents[parent_id]["submitted_epoch"])
        metrics_registry.counter_inc(
            SUBMITTED_TOTAL, len(children),
            help="jobs admitted into the work queue")
        self._gauge_depth()
        record = self.batch_record(parent_id)
        assert record is not None
        return record

    def job(self, job_id: str) -> Optional[Job]:
        with self._lock:
            return self._jobs.get(job_id)

    def jobs(self) -> List[Job]:
        with self._lock:
            return list(self._jobs.values())

    def batch_record(self, parent_id: str) -> Optional[dict]:
        """The aggregated record of one batch: child job records plus the
        derived parent state (queued -> running -> done | failed) and the
        summed queue wait — what ``GET /jobs/<parent>`` serves."""
        with self._lock:
            meta = self._parents.get(parent_id)
            if meta is None:
                return None
            children = [self._jobs[c] for c in meta["children"]
                        if c in self._jobs]
            missing = len(meta["children"]) - len(children)
            records = [j.to_dict() for j in children]
        states = [r["state"] for r in records]
        if states and all(s == "queued" for s in states):
            state = "queued"
        elif any(s in ("queued", "running") for s in states):
            state = "running"
        elif any(s == "failed" for s in states):
            state = "failed"
        else:
            state = "done"
        waits = [r["queue_wait_s"] for r in records
                 if r["queue_wait_s"] is not None]
        finished = [r["finished_epoch"] for r in records]
        started = [r["started_epoch"] for r in records if r["started_epoch"]]
        wall = None
        if started and all(f is not None for f in finished):
            wall = round(max(finished) - min(started), 3)
        return {
            "id": parent_id,
            "kind": "batch",
            "state": state,
            "jobs": len(records),
            "children": records,
            "children_missing": missing,
            "states": {s: states.count(s) for s in sorted(set(states))},
            "agg_queue_wait_s": round(sum(waits), 3) if waits else None,
            "wall_s": wall,
            "submitted_epoch": meta.get("submitted_epoch"),
        }

    def batches(self) -> List[dict]:
        with self._lock:
            parent_ids = sorted(self._parents, key=_id_num)
        records = [self.batch_record(p) for p in parent_ids]
        return [r for r in records if r is not None]

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for job in self.jobs():
            out[job.state] = out.get(job.state, 0) + 1
        return out

    def _gauge_depth(self) -> None:
        metrics_registry.gauge_set(
            QUEUE_DEPTH, self._queue.qsize(),
            help="jobs waiting in the serve work queue")

    # ---- worker pool ----

    def start(self) -> None:
        if self._threads:
            return
        for i in range(self.workers):
            thread = threading.Thread(
                target=self._worker_loop, args=(f"worker-{i}",),
                name=f"autocycler-serve-worker-{i}", daemon=True)
            self._threads.append(thread)
            thread.start()

    def shutdown(self, wait: bool = True, timeout: float = 30.0) -> None:
        """Stop the workers after their current jobs; queued jobs stay
        recorded as pending in the manifest (a restarted daemon replays
        them)."""
        self._stop.set()
        threads, self._threads = self._threads, []
        if wait:
            deadline = time.monotonic() + timeout
            for thread in threads:
                thread.join(timeout=max(0.0, deadline - time.monotonic()))

    def busy_count(self) -> int:
        with self._lock:
            return len(self._busy)

    def idle(self) -> bool:
        """True when the queue is drained and no worker is executing."""
        return self._queue.empty() and self.busy_count() == 0

    def _set_busy(self, worker: str, job_id: Optional[str]) -> None:
        with self._lock:
            if job_id is None:
                self._busy.pop(worker, None)
            else:
                self._busy[worker] = job_id
            busy = len(self._busy)
        metrics_registry.gauge_set(
            BUSY_GAUGE, busy, help="serve workers currently executing a job")
        metrics_registry.gauge_set(
            WORKER_BUSY_GAUGE, 0 if job_id is None else 1,
            help="per-worker busy flag (1 = executing a job)",
            worker=worker)

    def _worker_loop(self, worker: str) -> None:
        while not self._stop.is_set():
            try:
                job = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            self._gauge_depth()
            self._set_busy(worker, job.id)
            try:
                self.execute(job)
            finally:
                self._set_busy(worker, None)
                self._queue.task_done()

    # ---- execution ----

    def execute(self, job: Job) -> None:
        """Run one job under quarantine, with its own trace/QC/ledger run.

        Every job opens a *scoped* trace run bound to the executing thread
        (and propagated into pool tasks), and tags its QC journal and
        ledger entries with the job id as the isolate scope — so N
        concurrent jobs stream N disjoint trace.jsonl files and each
        run directory's qc_report/ledger carries exactly that job's
        entries. In single-worker mode the legacy run lock is additionally
        held across the job, preserving the original daemon's execution
        semantics bit for bit."""
        spec = job.spec
        gate = self._run_lock if self.workers == 1 \
            else contextlib.nullcontext()
        with gate:
            with self._lock:
                job.state = "running"
                job.started_epoch = time.time()
                job.queue_wait_s = max(
                    0.0, job.started_epoch - job.submitted_epoch)
            self.manifest.start(job.id)
            log.message(f"serve: {job.id} started "
                        f"({spec.command} {spec.assemblies_dir})")
            t0 = time.perf_counter()
            run = None
            try:
                run = trace.open_run(job.run_dir,
                                     name=f"serve-{spec.command}",
                                     trace_id=job.trace_id)
            except OSError:
                # unwritable run dir — run the job untraced rather than
                # refuse it
                pass
            failure: Optional[BaseException] = None
            unexpected = False
            try:
                with contextlib.ExitStack() as ctx:
                    if run is not None:
                        ctx.enter_context(trace.bind_run(run))
                    span_attrs = {"job": job.id, "command": spec.command}
                    if job.trace_id:
                        span_attrs["trace"] = job.trace_id
                    ctx.enter_context(
                        trace.span(f"job/{job.id}", cat="command",
                                   **span_attrs))
                    ctx.enter_context(obs_qc.scope(job.id))
                    if job.fleet_specs:
                        self._run_fleet(job)
                    else:
                        self._run_spec(spec, job.out_dir, job_id=job.id)
            except (AutocyclerError, OSError) as e:
                failure = e
            except Exception as e:  # noqa: BLE001 — a bug in one job's
                # pipeline path must not take the worker (and every queued
                # job behind it) down with it
                failure, unexpected = e, True
            finally:
                job.wall_s = time.perf_counter() - t0
                if run is not None:
                    run_dir = trace.close_run(run)
                    if run_dir:
                        obs_qc.write_qc_report(run_dir, scope=job.id,
                                               trace_id=job.trace_id)
                        ledger.write_ledger(
                            run_dir, command=f"serve/{spec.command}",
                            scope=job.id, trace_id=job.trace_id)
                # the job's journal/ledger entries are flushed into its run
                # dir; drain them so a long-lived daemon's shared tables
                # stay bounded
                obs_qc.drain_scope(job.id)
                ledger.drain_scope(job.id)
                # job graphs are reference-cyclic; a long-lived daemon must
                # reclaim them eagerly or RSS grows by one graph per job
                gc.collect()
                # the terminal state flips only AFTER the run artifacts,
                # metrics and SLO window are flushed: a client that polls
                # /jobs/<id> to a terminal state may immediately read
                # ledger.json or scrape /metrics and must find this job
                # already accounted for
                job.finished_epoch = time.time()
                final_state = "done" if failure is None else "failed"
                metrics_registry.counter_inc(
                    JOBS_TOTAL, 1,
                    help="jobs completed by the serve worker",
                    state=final_state, command=spec.command)
                metrics_registry.observe(
                    JOB_SECONDS, job.wall_s,
                    help="per-job wall seconds",
                    buckets=SECONDS_BUCKETS, command=spec.command)
                self.slo.record(job.queue_wait_s or 0.0, job.wall_s,
                                finished_epoch=job.finished_epoch,
                                command=spec.command)
                if failure is None:
                    self.manifest.done(job.id)
                    with self._lock:
                        job.state = "done"
                else:
                    self._quarantine(job, failure, unexpected=unexpected)
                log.message(f"serve: {job.id} {job.state} "
                            f"({job.wall_s:.2f}s)")

    def _quarantine(self, job: Job, error: BaseException,
                    unexpected: bool = False) -> None:
        prefix = "unexpected error: " if unexpected else ""
        message = f"{prefix}{type(error).__name__}: {error}" if unexpected \
            else str(error)
        # counter before the state flip, for the same poll-then-scrape
        # ordering contract as execute()'s terminal accounting
        metrics_registry.counter_inc(
            "autocycler_quarantined_items_total", 1,
            help="per-item failures quarantined instead of aborting")
        self.manifest.fail(job.id, message)
        with self._lock:
            job.state = "failed"
            job.error = message
        log.message(f"WARNING: serve: {job.id} quarantined — {message}")

    def _stage_skip(self, job_id: Optional[str], stage: str,
                    outputs, cluster: Optional[str] = None) -> bool:
        """True when ``stage`` may be skipped: the manifest has a verified
        checkpoint (every recorded output re-hashes clean). The skip is
        made visible in the run's ledger and log so replay is auditable."""
        if job_id is None or not self.manifest.stage_complete(job_id, stage):
            return False
        ledger.record_stage(stage.split("/", 1)[0], outputs=outputs,
                            cluster=cluster, skipped=True)
        log.message(f"serve: {job_id} skipping {stage} "
                    "(checkpoint verified)")
        return True

    def _stage_done(self, job_id: Optional[str], stage: str,
                    outputs) -> None:
        if job_id is not None:
            self.manifest.stage_done(job_id, stage, outputs=outputs)

    def _run_fleet(self, job: Job) -> None:
        """The fleet job body: one admission fanned over the mesh through
        the CLI's fleet runner. Each item's outputs land in its spec's
        ``out_dir`` (default: ``<job run_dir>/out/isolate-NN``); the fleet
        manifest in the job's run dir gives daemon restarts per-isolate
        stage-granular resume. Partial failure (exit 2 — some isolates
        quarantined inside the fleet run) fails the job with the manifest
        path, matching `autocycler batch`'s exit contract."""
        from ..commands.batch import IsolateJob, run_fleet_jobs
        assert job.fleet_specs
        jobs = []
        for i, spec in enumerate(job.fleet_specs):
            name = f"isolate-{i:02d}"
            out_dir = Path(spec.out_dir) if spec.out_dir \
                else job.out_dir / name
            jobs.append(IsolateJob(name, Path(spec.assemblies_dir), out_dir))
        manifest_path = job.run_dir / "fleet_manifest.json"
        spec = job.fleet_specs[0]
        rc = run_fleet_jobs(jobs, k_size=spec.kmer,
                            max_contigs=spec.max_contigs,
                            threads=spec.threads,
                            manifest_path=manifest_path,
                            resume=job.resumed)
        if rc != 0:
            raise AutocyclerError(
                f"fleet run completed with failed isolate(s); "
                f"see {manifest_path}")

    def _run_spec(self, spec: JobSpec, out_dir: Path,
                  job_id: Optional[str] = None) -> None:
        """The job body: exactly the CLI code path, so outputs are
        byte-identical to `autocycler compress` / the per-isolate slice of
        `autocycler batch` by construction.

        With a ``job_id``, every stage checkpoints into the serve manifest
        after its artifacts flush, and a resumed job skips stages whose
        recorded output hashes still verify — re-entering mid-isolate
        instead of starting over. Stages re-run from disk state, so the
        resumed run's outputs match a full rerun byte for byte."""
        out_dir = Path(out_dir)
        compress_out = [out_dir / "input_assemblies.gfa",
                        out_dir / "input_assemblies.yaml"]
        if not self._stage_skip(job_id, "compress", compress_out):
            from ..commands.compress import compress
            compress(spec.assemblies_dir, out_dir, spec.kmer,
                     spec.max_contigs, threads=spec.threads)
            self._stage_done(job_id, "compress", compress_out)
        if spec.command != "pipeline":
            return
        clustering_dir = out_dir / "clustering"
        qc_pass = clustering_dir / "qc_pass"

        def cluster_out():
            return [clustering_dir / "pairwise_distances.phylip",
                    clustering_dir / "clustering.newick",
                    clustering_dir / "clustering.tsv",
                    clustering_dir / "clustering.yaml"] \
                + sorted(clustering_dir.glob("qc_*/cluster_*/1_untrimmed.gfa"))

        if not self._stage_skip(job_id, "cluster", cluster_out()):
            from ..commands.cluster import cluster
            cluster(out_dir, spec.cutoff, spec.min_assemblies,
                    spec.max_contigs)
            self._stage_done(job_id, "cluster", cluster_out())
        from ..commands.combine import combine
        from ..commands.resolve import resolve
        from ..commands.trim import trim
        cluster_dirs = sorted(d for d in qc_pass.iterdir() if d.is_dir()) \
            if qc_pass.is_dir() else []
        for cdir in cluster_dirs:
            trim_out = [cdir / "2_trimmed.gfa", cdir / "2_trimmed.yaml"]
            resolve_out = [cdir / "3_bridged.gfa", cdir / "4_merged.gfa",
                           cdir / "5_final.gfa"]
            trimmed = None
            if not self._stage_skip(job_id, f"trim/{cdir.name}", trim_out,
                                    cluster=cdir.name):
                trimmed = trim(cdir, threads=spec.threads)
                self._stage_done(job_id, f"trim/{cdir.name}", trim_out)
            if not self._stage_skip(job_id, f"resolve/{cdir.name}",
                                    resolve_out, cluster=cdir.name):
                # on resume trimmed is None and resolve re-parses
                # 2_trimmed.gfa from disk — same bytes either way
                resolve(cdir, preloaded=trimmed)
                self._stage_done(job_id, f"resolve/{cdir.name}", resolve_out)
            del trimmed
        combine_out = [out_dir / "consensus_assembly.gfa",
                       out_dir / "consensus_assembly.fasta",
                       out_dir / "consensus_assembly.yaml"]
        finals = sorted(qc_pass.glob("cluster_*/5_final.gfa"))
        if finals and not self._stage_skip(job_id, "combine", combine_out):
            combine(out_dir, finals)
            self._stage_done(job_id, "combine", combine_out)
