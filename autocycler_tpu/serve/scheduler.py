"""Bounded work queue with per-job fault isolation for `autocycler serve`.

One scheduler owns the daemon's job table, a bounded FIFO queue and a
worker thread. Each job runs the same code path the CLI runs — compress
(optionally through the full cluster/trim/resolve/combine pipeline) — but
inside a quarantine: an :class:`AutocyclerError` or OSError marks the job
failed in the job table and the ``serve_manifest.json`` run manifest
(:class:`utils.resilience.RunManifest`) and the worker moves on to the
next job. One poisoned job never kills the process.

Each job owns a run directory (``<root>/jobs/<id>/``) receiving the
standard per-run artifacts — ``trace.jsonl``, ``qc_report.json``,
``ledger.json`` — exactly what ``AUTOCYCLER_TRACE_DIR`` produces for a CLI
run, so `autocycler watch` and `autocycler report` work unchanged on a
daemon job. The span tracer, QC journal and ledger are process-wide
one-run-at-a-time machinery, so job execution holds the scheduler's run
lock: jobs are admitted concurrently (the bounded queue) but execute
serially, which is also what the device and the shared worker pool want.

The warm wins come for free from sharing the process: the JIT caches, the
resolved device probe, the shared ``utils.pool`` executor and — because the
daemon points ``utils.cache`` at one shared directory — the parse and
end-repair caches all persist across jobs.
"""

from __future__ import annotations

import gc
import queue
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

from ..obs import ledger, metrics_registry, trace
from ..obs import qc as obs_qc
from ..obs.metrics_registry import SECONDS_BUCKETS
from ..utils import AutocyclerError, log
from ..utils.resilience import RunManifest
from .protocol import JobSpec
from .slo import SloTracker

MANIFEST_NAME = "serve_manifest.json"

# registry metric names: the live /metrics endpoint and bench servesmoke
# both read these
JOBS_TOTAL = "autocycler_serve_jobs_total"
SUBMITTED_TOTAL = "autocycler_serve_submitted_total"
REJECTED_TOTAL = "autocycler_serve_rejected_total"
QUEUE_DEPTH = "autocycler_serve_queue_depth"
JOB_SECONDS = "autocycler_serve_job_seconds"


class QueueFullError(AutocyclerError):
    """The bounded work queue is at capacity — the server maps this to
    HTTP 503 so clients can back off and retry."""


class Job:
    """One job's record: the spec plus lifecycle state and artifact paths."""

    def __init__(self, job_id: str, spec: JobSpec, run_dir: Path,
                 out_dir: Path):
        self.id = job_id
        self.spec = spec
        self.run_dir = run_dir
        self.out_dir = out_dir
        self.state = "queued"
        self.error: Optional[str] = None
        self.submitted_epoch = time.time()
        self.started_epoch: Optional[float] = None
        self.finished_epoch: Optional[float] = None
        self.wall_s: Optional[float] = None
        self.queue_wait_s: Optional[float] = None

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "state": self.state,
            "spec": self.spec.to_dict(),
            "run_dir": str(self.run_dir),
            "out_dir": str(self.out_dir),
            "error": self.error,
            "submitted_epoch": round(self.submitted_epoch, 3),
            "started_epoch": round(self.started_epoch, 3)
            if self.started_epoch else None,
            "finished_epoch": round(self.finished_epoch, 3)
            if self.finished_epoch else None,
            "wall_s": round(self.wall_s, 3) if self.wall_s is not None
            else None,
            "queue_wait_s": round(self.queue_wait_s, 3)
            if self.queue_wait_s is not None else None,
        }


class Scheduler:
    """The daemon's job table + bounded queue + worker thread."""

    def __init__(self, root, capacity: int = 16):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.capacity = max(1, int(capacity))
        self._queue: "queue.Queue[Job]" = queue.Queue(maxsize=self.capacity)
        self._jobs: Dict[str, Job] = {}
        self._lock = threading.Lock()
        self._run_lock = threading.Lock()   # serializes trace/QC/ledger runs
        self._next_id = 1
        self._stop = threading.Event()
        self._worker: Optional[threading.Thread] = None
        # latency SLO tracking: its own lock, disjoint from _run_lock by
        # construction (the sampler and /healthz read it mid-job)
        self.slo = SloTracker()
        self.manifest = RunManifest.load(self.root / MANIFEST_NAME)
        # a previous daemon died mid-job: those entries can never complete
        # now — record the interruption so `/jobs` history and the manifest
        # agree (docs/failure-modes.md "daemon restart")
        for name, entry in self.manifest.items.items():
            if entry.get("status") == "running":
                self.manifest.fail(name, "interrupted by daemon restart")
            # resume the id sequence past every recorded job so a restarted
            # daemon never reuses (and silently overwrites) a prior job id
            try:
                self._next_id = max(self._next_id,
                                    int(name.rsplit("-", 1)[1]) + 1)
            except (IndexError, ValueError):
                pass

    # ---- admission ----

    def submit(self, spec: JobSpec) -> Job:
        """Admit one job into the bounded queue; raises
        :class:`QueueFullError` at capacity (never blocks the caller)."""
        with self._lock:
            job_id = f"job-{self._next_id:06d}"
            self._next_id += 1
            run_dir = self.root / "jobs" / job_id
            out_dir = Path(spec.out_dir) if spec.out_dir \
                else run_dir / "out"
            job = Job(job_id, spec, run_dir, out_dir)
            try:
                self._queue.put_nowait(job)
            except queue.Full:
                metrics_registry.counter_inc(
                    REJECTED_TOTAL, 1, help="jobs rejected at admission",
                    reason="queue_full")
                raise QueueFullError(
                    f"work queue is full ({self.capacity} jobs); "
                    "retry after a job completes") from None
            self._jobs[job_id] = job
        self.manifest.pending(job_id)
        metrics_registry.counter_inc(
            SUBMITTED_TOTAL, 1, help="jobs admitted into the work queue")
        self._gauge_depth()
        return job

    def job(self, job_id: str) -> Optional[Job]:
        with self._lock:
            return self._jobs.get(job_id)

    def jobs(self) -> List[Job]:
        with self._lock:
            return list(self._jobs.values())

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for job in self.jobs():
            out[job.state] = out.get(job.state, 0) + 1
        return out

    def _gauge_depth(self) -> None:
        metrics_registry.gauge_set(
            QUEUE_DEPTH, self._queue.qsize(),
            help="jobs waiting in the serve work queue")

    # ---- worker ----

    def start(self) -> None:
        if self._worker is not None:
            return
        self._worker = threading.Thread(
            target=self._worker_loop, name="autocycler-serve-worker",
            daemon=True)
        self._worker.start()

    def shutdown(self, wait: bool = True, timeout: float = 30.0) -> None:
        """Stop the worker after its current job; queued jobs stay recorded
        as pending in the manifest (a restarted daemon reports them)."""
        self._stop.set()
        worker, self._worker = self._worker, None
        if worker is not None and wait:
            worker.join(timeout=timeout)

    def idle(self) -> bool:
        """True when the queue is drained and no job is running."""
        return self._queue.empty() and not self._run_lock.locked()

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            try:
                job = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            self._gauge_depth()
            try:
                self.execute(job)
            finally:
                self._queue.task_done()

    # ---- execution ----

    def execute(self, job: Job) -> None:
        """Run one job under quarantine, with its own trace/QC/ledger run.

        Holding the run lock across the job keeps the process-wide run
        machinery (one active trace run, the QC journal, the ledger tables)
        exclusive to this job; the QC scope additionally labels every
        gauge/journal entry with the job id so nothing cross-contaminates
        the cumulative registry the /metrics endpoint exports."""
        spec = job.spec
        with self._run_lock:
            job.state = "running"
            job.started_epoch = time.time()
            job.queue_wait_s = max(0.0,
                                   job.started_epoch - job.submitted_epoch)
            self.manifest.start(job.id)
            log.message(f"serve: {job.id} started "
                        f"({spec.command} {spec.assemblies_dir})")
            t0 = time.perf_counter()
            owns_run = False
            try:
                trace.start_run(job.run_dir, name=f"serve-{spec.command}")
                owns_run = True
            except (RuntimeError, OSError):
                # a CLI-owned run is somehow active or the dir is
                # unwritable — run the job untraced rather than refuse it
                pass
            if owns_run:
                obs_qc.reset()
                ledger.reset()
            failure: Optional[BaseException] = None
            unexpected = False
            try:
                with trace.span(f"job/{job.id}", cat="command",
                                job=job.id, command=spec.command), \
                        obs_qc.scope(job.id):
                    self._run_spec(spec, job.out_dir)
            except (AutocyclerError, OSError) as e:
                failure = e
            except Exception as e:  # noqa: BLE001 — a bug in one job's
                # pipeline path must not take the worker (and every queued
                # job behind it) down with it
                failure, unexpected = e, True
            finally:
                job.wall_s = time.perf_counter() - t0
                if owns_run:
                    run_dir = trace.finish_run()
                    if run_dir:
                        obs_qc.write_qc_report(run_dir)
                        ledger.write_ledger(
                            run_dir, command=f"serve/{spec.command}")
                # job graphs are reference-cyclic; a long-lived daemon must
                # reclaim them eagerly or RSS grows by one graph per job
                gc.collect()
                # the terminal state flips only AFTER the run artifacts are
                # flushed: a client that polls /jobs/<id> to done may read
                # ledger.json immediately
                job.finished_epoch = time.time()
                if failure is None:
                    job.state = "done"
                    self.manifest.done(job.id)
                else:
                    self._quarantine(job, failure, unexpected=unexpected)
                metrics_registry.counter_inc(
                    JOBS_TOTAL, 1, help="jobs completed by the serve worker",
                    state=job.state, command=spec.command)
                metrics_registry.observe(
                    JOB_SECONDS, job.wall_s,
                    help="per-job wall seconds",
                    buckets=SECONDS_BUCKETS, command=spec.command)
                self.slo.record(job.queue_wait_s or 0.0, job.wall_s,
                                finished_epoch=job.finished_epoch,
                                command=spec.command)
                log.message(f"serve: {job.id} {job.state} "
                            f"({job.wall_s:.2f}s)")

    def _quarantine(self, job: Job, error: BaseException,
                    unexpected: bool = False) -> None:
        job.state = "failed"
        prefix = "unexpected error: " if unexpected else ""
        job.error = f"{prefix}{type(error).__name__}: {error}" if unexpected \
            else str(error)
        self.manifest.fail(job.id, job.error)
        log.message(f"WARNING: serve: {job.id} quarantined — {job.error}")
        metrics_registry.counter_inc(
            "autocycler_quarantined_items_total", 1,
            help="per-item failures quarantined instead of aborting")

    def _run_spec(self, spec: JobSpec, out_dir: Path) -> None:
        """The job body: exactly the CLI code path, so outputs are
        byte-identical to `autocycler compress` / the per-isolate slice of
        `autocycler batch` by construction."""
        from ..commands.compress import compress
        compress(spec.assemblies_dir, out_dir, spec.kmer, spec.max_contigs,
                 threads=spec.threads)
        if spec.command != "pipeline":
            return
        from ..commands.cluster import cluster
        cluster(out_dir, spec.cutoff, spec.min_assemblies, spec.max_contigs)
        from ..commands.combine import combine
        from ..commands.resolve import resolve
        from ..commands.trim import trim
        qc_pass = Path(out_dir) / "clustering" / "qc_pass"
        cluster_dirs = sorted(d for d in qc_pass.iterdir() if d.is_dir()) \
            if qc_pass.is_dir() else []
        for cdir in cluster_dirs:
            trimmed = trim(cdir, threads=spec.threads)
            resolve(cdir, preloaded=trimmed)
            del trimmed
        finals = sorted(qc_pass.glob("cluster_*/5_final.gfa"))
        if finals:
            combine(out_dir, finals)
