"""Bounded work queue with per-job fault isolation for `autocycler serve`.

One scheduler owns the daemon's job table, a bounded FIFO queue and a
worker thread. Each job runs the same code path the CLI runs — compress
(optionally through the full cluster/trim/resolve/combine pipeline) — but
inside a quarantine: an :class:`AutocyclerError` or OSError marks the job
failed in the job table and the ``serve_manifest.json`` run manifest
(:class:`utils.resilience.RunManifest`) and the worker moves on to the
next job. One poisoned job never kills the process.

Each job owns a run directory (``<root>/jobs/<id>/``) receiving the
standard per-run artifacts — ``trace.jsonl``, ``qc_report.json``,
``ledger.json`` — exactly what ``AUTOCYCLER_TRACE_DIR`` produces for a CLI
run, so `autocycler watch` and `autocycler report` work unchanged on a
daemon job. The span tracer, QC journal and ledger are process-wide
one-run-at-a-time machinery, so job execution holds the scheduler's run
lock: jobs are admitted concurrently (the bounded queue) but execute
serially, which is also what the device and the shared worker pool want.

The warm wins come for free from sharing the process: the JIT caches, the
resolved device probe, the shared ``utils.pool`` executor and — because the
daemon points ``utils.cache`` at one shared directory — the parse and
end-repair caches all persist across jobs.
"""

from __future__ import annotations

import gc
import queue
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional

from ..obs import ledger, metrics_registry, trace
from ..obs import qc as obs_qc
from ..obs.metrics_registry import SECONDS_BUCKETS
from ..utils import AutocyclerError, log
from ..utils.resilience import InputError, RunManifest
from .protocol import JobSpec, parse_job_spec
from .slo import SloTracker

MANIFEST_NAME = "serve_manifest.json"

# registry metric names: the live /metrics endpoint and bench servesmoke
# both read these
JOBS_TOTAL = "autocycler_serve_jobs_total"
SUBMITTED_TOTAL = "autocycler_serve_submitted_total"
REJECTED_TOTAL = "autocycler_serve_rejected_total"
SHED_TOTAL = "autocycler_serve_shed_total"
QUEUE_DEPTH = "autocycler_serve_queue_depth"
JOB_SECONDS = "autocycler_serve_job_seconds"


class QueueFullError(AutocyclerError):
    """The bounded work queue is at capacity — the server maps this to
    HTTP 503 so clients can back off and retry."""


class Job:
    """One job's record: the spec plus lifecycle state and artifact paths."""

    def __init__(self, job_id: str, spec: JobSpec, run_dir: Path,
                 out_dir: Path):
        self.id = job_id
        self.spec = spec
        self.run_dir = run_dir
        self.out_dir = out_dir
        self.state = "queued"
        self.error: Optional[str] = None
        self.resumed = False              # replayed after a daemon restart
        self.submitted_epoch = time.time()
        self.started_epoch: Optional[float] = None
        self.finished_epoch: Optional[float] = None
        self.wall_s: Optional[float] = None
        self.queue_wait_s: Optional[float] = None

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "state": self.state,
            "spec": self.spec.to_dict(),
            "run_dir": str(self.run_dir),
            "out_dir": str(self.out_dir),
            "error": self.error,
            "submitted_epoch": round(self.submitted_epoch, 3),
            "started_epoch": round(self.started_epoch, 3)
            if self.started_epoch else None,
            "finished_epoch": round(self.finished_epoch, 3)
            if self.finished_epoch else None,
            "wall_s": round(self.wall_s, 3) if self.wall_s is not None
            else None,
            "queue_wait_s": round(self.queue_wait_s, 3)
            if self.queue_wait_s is not None else None,
        }


class Scheduler:
    """The daemon's job table + bounded queue + worker thread."""

    def __init__(self, root, capacity: int = 16):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.capacity = max(1, int(capacity))
        self._queue: "queue.Queue[Job]" = queue.Queue(maxsize=self.capacity)
        self._jobs: Dict[str, Job] = {}
        self._lock = threading.Lock()
        self._run_lock = threading.Lock()   # serializes trace/QC/ledger runs
        self._next_id = 1
        self._stop = threading.Event()
        self._worker: Optional[threading.Thread] = None
        # latency SLO tracking: its own lock, disjoint from _run_lock by
        # construction (the sampler and /healthz read it mid-job)
        self.slo = SloTracker()
        self.manifest = RunManifest.load(self.root / MANIFEST_NAME)
        # crash-safe replay: a previous daemon's unfinished jobs come back.
        # Jobs still "pending" re-enqueue in submission order; jobs caught
        # "running" resume from their last checkpointed stage when the
        # worker picks them up (docs/failure-modes.md "daemon restart").
        replay: List[Job] = []
        for name in sorted(self.manifest.items):   # ids sort = submit order
            entry = self.manifest.items[name]
            # resume the id sequence past every recorded job so a restarted
            # daemon never reuses (and silently overwrites) a prior job id
            try:
                self._next_id = max(self._next_id,
                                    int(name.rsplit("-", 1)[1]) + 1)
            except (IndexError, ValueError):
                pass
            status = entry.get("status")
            if status not in ("pending", "running"):
                continue
            spec_data = entry.get("spec")
            if not isinstance(spec_data, dict):
                # pre-replay manifests carried no spec: nothing to re-run,
                # so record the interruption the way older daemons did
                if status == "running":
                    self.manifest.fail(name, "interrupted by daemon restart")
                continue
            try:
                spec = parse_job_spec(spec_data)
            except InputError as e:
                self.manifest.fail(name, f"unreplayable job spec: {e}")
                continue
            run_dir = self.root / "jobs" / name
            out_dir = Path(entry.get("out_dir") or (run_dir / "out"))
            job = Job(name, spec, run_dir, out_dir)
            job.resumed = status == "running"
            submitted = entry.get("submitted_epoch")
            if isinstance(submitted, (int, float)):
                job.submitted_epoch = float(submitted)
            replay.append(job)
        for job in replay:
            try:
                self._queue.put_nowait(job)
            except queue.Full:
                # stays pending in the manifest; the next restart (or a
                # larger capacity) picks it up
                log.message(f"WARNING: serve: {job.id} not replayed — "
                            f"queue capacity {self.capacity} exhausted")
                continue
            self._jobs[job.id] = job
            log.message(
                f"serve: {job.id} "
                + ("resuming from last checkpointed stage"
                   if job.resumed else "re-enqueued after restart"))
        if replay:
            self._gauge_depth()

    # ---- admission ----

    def submit(self, spec: JobSpec) -> Job:
        """Admit one job into the bounded queue; raises
        :class:`QueueFullError` at capacity (never blocks the caller)."""
        with self._lock:
            job_id = f"job-{self._next_id:06d}"
            self._next_id += 1
            run_dir = self.root / "jobs" / job_id
            out_dir = Path(spec.out_dir) if spec.out_dir \
                else run_dir / "out"
            job = Job(job_id, spec, run_dir, out_dir)
            try:
                self._queue.put_nowait(job)
            except queue.Full:
                metrics_registry.counter_inc(
                    REJECTED_TOTAL, 1, help="jobs rejected at admission",
                    reason="queue_full")
                raise QueueFullError(
                    f"work queue is full ({self.capacity} jobs); "
                    "retry after a job completes") from None
            self._jobs[job_id] = job
        # persist everything replay needs: a restarted daemon rebuilds the
        # Job from the manifest entry alone
        self.manifest.annotate(
            job_id, spec=spec.to_dict(), out_dir=str(out_dir),
            submitted_epoch=round(job.submitted_epoch, 3))
        metrics_registry.counter_inc(
            SUBMITTED_TOTAL, 1, help="jobs admitted into the work queue")
        self._gauge_depth()
        return job

    def job(self, job_id: str) -> Optional[Job]:
        with self._lock:
            return self._jobs.get(job_id)

    def jobs(self) -> List[Job]:
        with self._lock:
            return list(self._jobs.values())

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for job in self.jobs():
            out[job.state] = out.get(job.state, 0) + 1
        return out

    def _gauge_depth(self) -> None:
        metrics_registry.gauge_set(
            QUEUE_DEPTH, self._queue.qsize(),
            help="jobs waiting in the serve work queue")

    # ---- worker ----

    def start(self) -> None:
        if self._worker is not None:
            return
        self._worker = threading.Thread(
            target=self._worker_loop, name="autocycler-serve-worker",
            daemon=True)
        self._worker.start()

    def shutdown(self, wait: bool = True, timeout: float = 30.0) -> None:
        """Stop the worker after its current job; queued jobs stay recorded
        as pending in the manifest (a restarted daemon reports them)."""
        self._stop.set()
        worker, self._worker = self._worker, None
        if worker is not None and wait:
            worker.join(timeout=timeout)

    def idle(self) -> bool:
        """True when the queue is drained and no job is running."""
        return self._queue.empty() and not self._run_lock.locked()

    def _worker_loop(self) -> None:
        while not self._stop.is_set():
            try:
                job = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            self._gauge_depth()
            try:
                self.execute(job)
            finally:
                self._queue.task_done()

    # ---- execution ----

    def execute(self, job: Job) -> None:
        """Run one job under quarantine, with its own trace/QC/ledger run.

        Holding the run lock across the job keeps the process-wide run
        machinery (one active trace run, the QC journal, the ledger tables)
        exclusive to this job; the QC scope additionally labels every
        gauge/journal entry with the job id so nothing cross-contaminates
        the cumulative registry the /metrics endpoint exports."""
        spec = job.spec
        with self._run_lock:
            job.state = "running"
            job.started_epoch = time.time()
            job.queue_wait_s = max(0.0,
                                   job.started_epoch - job.submitted_epoch)
            self.manifest.start(job.id)
            log.message(f"serve: {job.id} started "
                        f"({spec.command} {spec.assemblies_dir})")
            t0 = time.perf_counter()
            owns_run = False
            try:
                trace.start_run(job.run_dir, name=f"serve-{spec.command}")
                owns_run = True
            except (RuntimeError, OSError):
                # a CLI-owned run is somehow active or the dir is
                # unwritable — run the job untraced rather than refuse it
                pass
            if owns_run:
                obs_qc.reset()
                ledger.reset()
            failure: Optional[BaseException] = None
            unexpected = False
            try:
                with trace.span(f"job/{job.id}", cat="command",
                                job=job.id, command=spec.command), \
                        obs_qc.scope(job.id):
                    self._run_spec(spec, job.out_dir, job_id=job.id)
            except (AutocyclerError, OSError) as e:
                failure = e
            except Exception as e:  # noqa: BLE001 — a bug in one job's
                # pipeline path must not take the worker (and every queued
                # job behind it) down with it
                failure, unexpected = e, True
            finally:
                job.wall_s = time.perf_counter() - t0
                if owns_run:
                    run_dir = trace.finish_run()
                    if run_dir:
                        obs_qc.write_qc_report(run_dir)
                        ledger.write_ledger(
                            run_dir, command=f"serve/{spec.command}")
                # job graphs are reference-cyclic; a long-lived daemon must
                # reclaim them eagerly or RSS grows by one graph per job
                gc.collect()
                # the terminal state flips only AFTER the run artifacts are
                # flushed: a client that polls /jobs/<id> to done may read
                # ledger.json immediately
                job.finished_epoch = time.time()
                if failure is None:
                    job.state = "done"
                    self.manifest.done(job.id)
                else:
                    self._quarantine(job, failure, unexpected=unexpected)
                metrics_registry.counter_inc(
                    JOBS_TOTAL, 1, help="jobs completed by the serve worker",
                    state=job.state, command=spec.command)
                metrics_registry.observe(
                    JOB_SECONDS, job.wall_s,
                    help="per-job wall seconds",
                    buckets=SECONDS_BUCKETS, command=spec.command)
                self.slo.record(job.queue_wait_s or 0.0, job.wall_s,
                                finished_epoch=job.finished_epoch,
                                command=spec.command)
                log.message(f"serve: {job.id} {job.state} "
                            f"({job.wall_s:.2f}s)")

    def _quarantine(self, job: Job, error: BaseException,
                    unexpected: bool = False) -> None:
        job.state = "failed"
        prefix = "unexpected error: " if unexpected else ""
        job.error = f"{prefix}{type(error).__name__}: {error}" if unexpected \
            else str(error)
        self.manifest.fail(job.id, job.error)
        log.message(f"WARNING: serve: {job.id} quarantined — {job.error}")
        metrics_registry.counter_inc(
            "autocycler_quarantined_items_total", 1,
            help="per-item failures quarantined instead of aborting")

    def _stage_skip(self, job_id: Optional[str], stage: str,
                    outputs, cluster: Optional[str] = None) -> bool:
        """True when ``stage`` may be skipped: the manifest has a verified
        checkpoint (every recorded output re-hashes clean). The skip is
        made visible in the run's ledger and log so replay is auditable."""
        if job_id is None or not self.manifest.stage_complete(job_id, stage):
            return False
        ledger.record_stage(stage.split("/", 1)[0], outputs=outputs,
                            cluster=cluster, skipped=True)
        log.message(f"serve: {job_id} skipping {stage} "
                    "(checkpoint verified)")
        return True

    def _stage_done(self, job_id: Optional[str], stage: str,
                    outputs) -> None:
        if job_id is not None:
            self.manifest.stage_done(job_id, stage, outputs=outputs)

    def _run_spec(self, spec: JobSpec, out_dir: Path,
                  job_id: Optional[str] = None) -> None:
        """The job body: exactly the CLI code path, so outputs are
        byte-identical to `autocycler compress` / the per-isolate slice of
        `autocycler batch` by construction.

        With a ``job_id``, every stage checkpoints into the serve manifest
        after its artifacts flush, and a resumed job skips stages whose
        recorded output hashes still verify — re-entering mid-isolate
        instead of starting over. Stages re-run from disk state, so the
        resumed run's outputs match a full rerun byte for byte."""
        out_dir = Path(out_dir)
        compress_out = [out_dir / "input_assemblies.gfa",
                        out_dir / "input_assemblies.yaml"]
        if not self._stage_skip(job_id, "compress", compress_out):
            from ..commands.compress import compress
            compress(spec.assemblies_dir, out_dir, spec.kmer,
                     spec.max_contigs, threads=spec.threads)
            self._stage_done(job_id, "compress", compress_out)
        if spec.command != "pipeline":
            return
        clustering_dir = out_dir / "clustering"
        qc_pass = clustering_dir / "qc_pass"

        def cluster_out():
            return [clustering_dir / "pairwise_distances.phylip",
                    clustering_dir / "clustering.newick",
                    clustering_dir / "clustering.tsv",
                    clustering_dir / "clustering.yaml"] \
                + sorted(clustering_dir.glob("qc_*/cluster_*/1_untrimmed.gfa"))

        if not self._stage_skip(job_id, "cluster", cluster_out()):
            from ..commands.cluster import cluster
            cluster(out_dir, spec.cutoff, spec.min_assemblies,
                    spec.max_contigs)
            self._stage_done(job_id, "cluster", cluster_out())
        from ..commands.combine import combine
        from ..commands.resolve import resolve
        from ..commands.trim import trim
        cluster_dirs = sorted(d for d in qc_pass.iterdir() if d.is_dir()) \
            if qc_pass.is_dir() else []
        for cdir in cluster_dirs:
            trim_out = [cdir / "2_trimmed.gfa", cdir / "2_trimmed.yaml"]
            resolve_out = [cdir / "3_bridged.gfa", cdir / "4_merged.gfa",
                           cdir / "5_final.gfa"]
            trimmed = None
            if not self._stage_skip(job_id, f"trim/{cdir.name}", trim_out,
                                    cluster=cdir.name):
                trimmed = trim(cdir, threads=spec.threads)
                self._stage_done(job_id, f"trim/{cdir.name}", trim_out)
            if not self._stage_skip(job_id, f"resolve/{cdir.name}",
                                    resolve_out, cluster=cdir.name):
                # on resume trimmed is None and resolve re-parses
                # 2_trimmed.gfa from disk — same bytes either way
                resolve(cdir, preloaded=trimmed)
                self._stage_done(job_id, f"resolve/{cdir.name}", resolve_out)
            del trimmed
        combine_out = [out_dir / "consensus_assembly.gfa",
                       out_dir / "consensus_assembly.fasta",
                       out_dir / "consensus_assembly.yaml"]
        finals = sorted(qc_pass.glob("cluster_*/5_final.gfa"))
        if finals and not self._stage_skip(job_id, "combine", combine_out):
            combine(out_dir, finals)
            self._stage_done(job_id, "combine", combine_out)
