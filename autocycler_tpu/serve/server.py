"""The `autocycler serve` HTTP surface: a loopback daemon over the
scheduler.

Routes (all JSON unless noted):

- ``POST /jobs``            submit a job spec -> 202 job record
                            (400 invalid spec; 503 + ``Retry-After`` when
                            the queue is full or burn-rate admission
                            control is shedding)
- ``GET  /jobs``            every job record this daemon has seen
- ``GET  /jobs/<id>``       one job record (404 unknown)
- ``GET  /jobs/<id>/trace`` raw ``trace.jsonl`` bytes from ``?offset=N``
                            (``X-Autocycler-Trace-Offset`` header carries
                            the next offset) — the span stream a remote
                            follower polls; local followers can equally
                            run `autocycler watch <run_dir>` on the path
                            in the job record
- ``GET  /metrics``         live Prometheus text exposition of the
                            process-wide metrics registry
- ``GET  /healthz``         daemon liveness + queue/job counts + probe
- ``POST /shutdown``        graceful stop (finish running jobs, exit)

``POST /jobs`` also accepts a batch body (``{"batch": [...]}``): the
daemon fans it into child jobs under one parent id and ``GET
/jobs/<parent>`` aggregates the children.

The daemon binds TCP loopback by default (``--host``/``--port``) or a Unix
domain socket (``--socket``), and writes ``serve.json`` into its root so
`autocycler submit --dir <root>` discovers the endpoint without flags.
Binding beyond loopback requires a shared secret
(``AUTOCYCLER_SERVE_TOKEN``); when a token is configured every request
must carry it (``Authorization: Bearer <token>`` or
``X-Autocycler-Token``) or it is refused with 401. The token value is
never logged and never written into ``serve.json``.
"""

from __future__ import annotations

import contextlib
import hmac
import json
import os
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Optional
from urllib.parse import parse_qs, urlparse

from .. import __version__
from ..obs import metrics_registry
from ..obs.timeseries import TimeseriesSampler, timeseries_enabled
from ..utils import log
from ..utils.knobs import knob_str
from ..utils.resilience import InputError
from .protocol import (DEFAULT_PORT, SERVE_INFO_JSON, TRACE_HEADER,
                       is_batch_spec, is_fleet_batch, parse_batch_spec,
                       parse_job_spec, sanitize_trace_id,
                       validate_fleet_batch)
from .scheduler import SHED_TOTAL, QueueFullError, Scheduler

# a sampler whose last tick is older than this many intervals is stale —
# wedged or dead, either way the continuous telemetry has stopped
SAMPLER_STALE_INTERVALS = 3.0

REQUESTS_TOTAL = "autocycler_serve_requests_total"

# Retry-After hint on 503 responses (shed or queue-full): long enough for
# a few window samples to age out, short enough to keep clients live
RETRY_AFTER_S = 15

TOKEN_ENV = "AUTOCYCLER_SERVE_TOKEN"

# hosts a daemon may bind WITHOUT a shared-secret token; anything else is
# reachable from off-box and refuses to start unauthenticated
_LOOPBACK_HOSTS = ("localhost", "::1")


def _is_loopback(host: Optional[str]) -> bool:
    if host is None:            # unix socket: filesystem permissions apply
        return True
    return host in _LOOPBACK_HOSTS or host.startswith("127.")


class _UnixHTTPServer(ThreadingHTTPServer):
    address_family = socket.AF_UNIX

    def server_bind(self):
        # a stale socket file from a dead daemon would fail the bind
        with contextlib.suppress(OSError):
            os.unlink(self.server_address)
        self.socket.bind(self.server_address)


class _Handler(BaseHTTPRequestHandler):
    server_version = f"autocycler-serve/{__version__}"

    # the ThreadingHTTPServer subclass carries the scheduler + daemon state
    @property
    def state(self) -> "ServeHandle":
        return self.server.serve_state  # type: ignore[attr-defined]

    def log_message(self, format, *args):  # noqa: A002 — stdlib signature
        pass                               # request logging goes via metrics

    def address_string(self) -> str:
        # AF_UNIX hands a str/bytes client_address; the default
        # implementation indexes it like a (host, port) tuple
        addr = self.client_address
        return addr[0] if isinstance(addr, tuple) and addr else "unix"

    # ---- plumbing ----

    def _send_json(self, code: int, payload: dict, route: str,
                   headers: Optional[dict] = None) -> None:
        body = (json.dumps(payload, indent=2, sort_keys=True) + "\n").encode()
        self._send_bytes(code, body, "application/json", route,
                         headers=headers)

    def _send_bytes(self, code: int, body: bytes, ctype: str, route: str,
                    headers: Optional[dict] = None) -> None:
        metrics_registry.counter_inc(
            REQUESTS_TOTAL, 1, help="serve HTTP requests",
            route=route, code=str(code))
        with contextlib.suppress(BrokenPipeError, ConnectionResetError,
                                 OSError):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            for key, value in (headers or {}).items():
                self.send_header(key, str(value))
            self.end_headers()
            self.wfile.write(body)

    def _read_json(self):
        length = int(self.headers.get("Content-Length") or 0)
        raw = self.rfile.read(length) if length else b""
        try:
            return json.loads(raw.decode() or "null")
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            raise InputError(f"request body is not valid JSON: {e}")

    def _authorized(self) -> bool:
        """Shared-secret check. With no token configured every request
        passes (loopback-only daemons); with one configured EVERY request
        must present it. Comparison is constant-time and the token value
        never reaches a log line or an error body."""
        token = self.state.token
        if not token:
            return True
        supplied = self.headers.get("X-Autocycler-Token") or ""
        auth = self.headers.get("Authorization") or ""
        if not supplied and auth.startswith("Bearer "):
            supplied = auth[len("Bearer "):].strip()
        if hmac.compare_digest(supplied.encode(), token.encode()):
            return True
        self._send_json(
            401, {"error": "missing or invalid serve token"}, "unauthorized",
            headers={"WWW-Authenticate": "Bearer"})
        return False

    # ---- routes ----

    def do_GET(self):  # noqa: N802 — stdlib casing
        if not self._authorized():
            return
        parsed = urlparse(self.path)
        parts = [p for p in parsed.path.split("/") if p]
        if parsed.path == "/healthz":
            return self._send_json(200, self.state.health(), "/healthz")
        if parsed.path == "/metrics":
            # ?format=json serves the registry snapshot (full histogram
            # bucket state incl. min/max) — what the fleet federation
            # scraper merges; the default stays Prometheus text
            query = parse_qs(parsed.query)
            if query.get("format", [""])[0] == "json":
                return self._send_json(200, metrics_registry.snapshot(),
                                       "/metrics")
            body = metrics_registry.to_prometheus().encode()
            return self._send_bytes(200, body,
                                    "text/plain; version=0.0.4", "/metrics")
        if parts and parts[0] == "jobs":
            if len(parts) == 1:
                jobs = [j.to_dict() for j in self.state.scheduler.jobs()]
                return self._send_json(
                    200,
                    {"jobs": jobs, "batches": self.state.scheduler.batches()},
                    "/jobs")
            job = self.state.scheduler.job(parts[1])
            if job is None:
                # batch parents live beside jobs in the same id namespace
                batch = self.state.scheduler.batch_record(parts[1])
                if batch is not None and len(parts) == 2:
                    return self._send_json(200, batch, "/jobs/<id>")
                return self._send_json(
                    404, {"error": f"unknown job {parts[1]!r}"}, "/jobs/<id>")
            if len(parts) == 2:
                return self._send_json(200, job.to_dict(), "/jobs/<id>")
            if len(parts) == 3 and parts[2] == "trace":
                return self._send_trace(job, parsed)
        return self._send_json(404, {"error": f"no route {parsed.path!r}"},
                               "unknown")

    def _send_trace(self, job, parsed) -> None:
        """Raw trace.jsonl bytes from ?offset=N — enough for a remote
        TraceFollower; the next offset rides a response header so the
        client never re-reads."""
        query = parse_qs(parsed.query)
        try:
            offset = max(0, int(query.get("offset", ["0"])[0]))
        except ValueError:
            offset = 0
        path = Path(job.run_dir) / "trace.jsonl"
        chunk = b""
        with contextlib.suppress(OSError):
            with open(path, "rb") as f:
                f.seek(offset)
                chunk = f.read(1 << 20)
        self._send_bytes(
            200, chunk, "application/x-ndjson", "/jobs/<id>/trace",
        )

    def do_POST(self):  # noqa: N802
        if not self._authorized():
            return
        parsed = urlparse(self.path)
        if parsed.path == "/jobs":
            # correlation id: optional client-minted header, sanitized so a
            # hostile value can never become a label or path fragment; it
            # threads through the scheduler into trace/QC/ledger artifacts
            trace_id = sanitize_trace_id(self.headers.get(TRACE_HEADER))
            try:
                body = self._read_json()
                batch = is_batch_spec(body)
                fleet = is_fleet_batch(body)
                specs = parse_batch_spec(body) if batch \
                    else [parse_job_spec(body)]
                if fleet:
                    validate_fleet_batch(specs)
            except InputError as e:
                metrics_registry.counter_inc(
                    "autocycler_serve_rejected_total", 1,
                    help="jobs rejected at admission", reason="bad_request")
                return self._send_json(400, {"error": str(e)}, "/jobs")
            # burn-rate admission control: when the SLO window burns error
            # budget faster than AUTOCYCLER_SLO_SHED_BURN allows, shed the
            # submission before it costs a queue slot — the window drains
            # on its own, so Retry-After is an honest hint
            slo_report = self.state.scheduler.slo.report()
            if slo_report.get("shedding"):
                metrics_registry.counter_inc(
                    SHED_TOTAL, len(specs),
                    help="submissions shed by burn-rate admission control")
                metrics_registry.counter_inc(
                    "autocycler_serve_rejected_total", len(specs),
                    help="jobs rejected at admission", reason="shed")
                return self._send_json(
                    503,
                    {"error": "shedding load: latency burn rate "
                              f"{slo_report.get('burn_rate')} exceeds "
                              f"threshold {slo_report.get('shed_burn')}",
                     "burn_rate": slo_report.get("burn_rate"),
                     "shed_burn": slo_report.get("shed_burn"),
                     "retry_after_s": RETRY_AFTER_S},
                    "/jobs", headers={"Retry-After": RETRY_AFTER_S})
            try:
                if fleet:
                    # one admission, one queue slot: the worker fans the
                    # items over the device mesh via the fleet runner
                    record = self.state.scheduler.submit_fleet(
                        specs, trace_id=trace_id).to_dict()
                elif batch:
                    record = self.state.scheduler.submit_batch(
                        specs, trace_id=trace_id)
                else:
                    record = self.state.scheduler.submit(
                        specs[0], trace_id=trace_id).to_dict()
            except QueueFullError as e:
                return self._send_json(503, {"error": str(e)}, "/jobs",
                                       headers={"Retry-After": RETRY_AFTER_S})
            return self._send_json(202, record, "/jobs")
        if parsed.path == "/shutdown":
            self._send_json(200, {"status": "shutting down"}, "/shutdown")
            self.state.request_shutdown()
            return
        return self._send_json(404, {"error": f"no route {parsed.path!r}"},
                               "unknown")


class ServeHandle:
    """A running daemon: the HTTP server thread + scheduler, stoppable.

    `serve()` builds one and blocks on it; tests and `bench.py servesmoke`
    build one in-process and drive it over real loopback HTTP."""

    def __init__(self, root, host: str = "127.0.0.1",
                 port: int = DEFAULT_PORT, socket_path=None,
                 queue_size: int = 16, workers: Optional[int] = None):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.t0 = time.time()
        # shared-secret auth: read once at startup so a daemon's policy is
        # stable for its lifetime. Held in memory only — never logged,
        # never echoed into serve.json or an error body.
        self.token = knob_str(TOKEN_ENV) or None
        if not socket_path and not _is_loopback(host) and not self.token:
            raise InputError(
                f"refusing to bind {host!r} (reachable beyond loopback) "
                f"without {TOKEN_ENV} set — configure a shared-secret "
                "token or bind loopback")
        self.scheduler = Scheduler(self.root, capacity=queue_size,
                                   workers=workers)
        self.socket_path = str(socket_path) if socket_path else None
        if self.socket_path:
            self.server = _UnixHTTPServer(self.socket_path, _Handler)
            self.endpoint = f"unix:{self.socket_path}"
            self.host, self.port = None, None
        else:
            self.server = ThreadingHTTPServer((host, port), _Handler)
            self.host, self.port = self.server.server_address[:2]
            self.endpoint = f"http://{self.host}:{self.port}"
        self.server.serve_state = self  # type: ignore[attr-defined]
        self.server.daemon_threads = True
        self._server_thread: Optional[threading.Thread] = None
        self._shutdown_requested = threading.Event()
        # continuous telemetry: one sampler per daemon, writing
        # timeseries.jsonl into the serve root. Its extra() hook reads the
        # SLO tracker and job-table lock only — never the run lock, so a
        # tick can never stall job execution.
        self.sampler: Optional[TimeseriesSampler] = None
        if timeseries_enabled():
            self.sampler = TimeseriesSampler(
                self.root, extra=self._sampler_extra)

    def _sampler_extra(self) -> dict:
        return {"serve": {"queue_depth": self.scheduler._queue.qsize(),
                          "jobs": self.scheduler.counts(),
                          "idle": self.scheduler.idle(),
                          "workers": self.scheduler.workers,
                          "busy_workers": self.scheduler.busy_count()},
                "slo": self.scheduler.slo.report()}

    # ---- lifecycle ----

    def start(self) -> "ServeHandle":
        """Start the scheduler worker and the HTTP accept loop (on a
        background thread) and write the discovery file."""
        # version info rides every /metrics export so a federated scrape
        # can flag replica version skew
        from ..obs.federate import record_build_info
        record_build_info()
        self.scheduler.start()
        if self.sampler is not None:
            self.sampler.start()
        self._server_thread = threading.Thread(
            target=self.server.serve_forever,
            name="autocycler-serve-http", daemon=True)
        self._server_thread.start()
        self._write_info()
        return self

    def request_shutdown(self) -> None:
        self._shutdown_requested.set()

    def wait(self, poll_s: float = 0.2) -> None:
        """Block until a shutdown is requested (POST /shutdown or signal)."""
        while not self._shutdown_requested.wait(poll_s):
            pass

    def stop(self) -> None:
        """Graceful stop: no new connections, finish the running job."""
        self.server.shutdown()
        self.server.server_close()
        self.scheduler.shutdown(wait=True)
        if self.sampler is not None:
            self.sampler.stop()   # takes the series' final tick
        if self.socket_path:
            with contextlib.suppress(OSError):
                os.unlink(self.socket_path)
        with contextlib.suppress(OSError):
            (self.root / SERVE_INFO_JSON).unlink()

    def _write_info(self) -> None:
        info = {"pid": os.getpid(), "endpoint": self.endpoint,
                "host": self.host, "port": self.port,
                "socket": self.socket_path,
                "started_epoch": round(self.t0, 3),
                "workers": self.scheduler.workers,
                "auth": "token" if self.token else "none",
                "version": __version__}
        path = self.root / SERVE_INFO_JSON
        tmp = path.with_suffix(".json.tmp")
        with contextlib.suppress(OSError):
            tmp.write_text(json.dumps(info, indent=2, sort_keys=True) + "\n")
            os.replace(tmp, path)

    # ---- health ----

    def health(self) -> dict:
        """Daemon health: liveness basics, queue state, the latency-SLO
        verdict and sampler liveness. ``status`` degrades (never errors —
        the daemon IS serving) when the rolling latency window violates a
        configured objective or the telemetry sampler has gone stale."""
        from ..ops.distance import probe_overlap_report
        now = time.time()
        slo_report = self.scheduler.slo.report()
        busy = self.scheduler.busy_count()
        health = {
            "status": "ok",
            "version": __version__,
            "pid": os.getpid(),
            "uptime_s": round(now - self.t0, 3),
            "queue_capacity": self.scheduler.capacity,
            "queue_depth": self.scheduler._queue.qsize(),
            "jobs": self.scheduler.counts(),
            "idle": self.scheduler.idle(),
            "workers": self.scheduler.workers,
            "busy_workers": busy,
            "utilization": round(busy / self.scheduler.workers, 4),
            "last_job_finished_epoch": slo_report.get("last_finished_epoch"),
            "slo": slo_report,
        }
        sampler = {"enabled": self.sampler is not None}
        if self.sampler is not None:
            last = self.sampler.last_tick_epoch
            age = round(now - last, 3) if last is not None else None
            stale_after = self.sampler.interval * SAMPLER_STALE_INTERVALS
            sampler.update(
                running=self.sampler.running(),
                interval_s=self.sampler.interval,
                last_tick_epoch=round(last, 3) if last else None,
                tick_age_s=age,
                stale=(not self.sampler.running()
                       or (age is not None and age > stale_after)))
        health["sampler"] = sampler
        degraded = []
        if slo_report.get("violated"):
            degraded.append("slo")
        if slo_report.get("shedding"):
            degraded.append("shedding")
        if sampler.get("stale"):
            degraded.append("sampler")
        if degraded:
            health["status"] = "degraded"
            health["degraded"] = degraded
            health["burn_rate"] = slo_report.get("burn_rate")
        with contextlib.suppress(Exception):
            health["probe"] = probe_overlap_report()
        return health


def serve(serve_dir, host: str = "127.0.0.1", port: int = DEFAULT_PORT,
          socket_path=None, queue_size: int = 16,
          workers: Optional[int] = None) -> int:
    """CLI entry for `autocycler serve`: warm the process once, then block
    serving jobs until SIGINT/SIGTERM or POST /shutdown."""
    root = Path(serve_dir)
    root.mkdir(parents=True, exist_ok=True)

    # one warm process: shared parse/repair cache dir, persistent compile
    # cache, and the device probe resolved once in the background
    from ..utils import cache as warm_cache
    if warm_cache.shared_cache_dir() is None:
        warm_cache.set_shared_cache_dir(root / ".cache")
    from ..utils.jaxcache import configure_compile_cache
    with contextlib.suppress(Exception):
        configure_compile_cache()
    from ..ops.distance import set_probe_cache_dir, start_background_probe
    set_probe_cache_dir(root / ".cache")
    start_background_probe()

    handle = ServeHandle(root, host=host, port=port,
                         socket_path=socket_path, queue_size=queue_size,
                         workers=workers)
    handle.start()

    import signal

    def _on_signal(signum, frame):
        handle.request_shutdown()

    with contextlib.suppress(ValueError):  # not the main thread
        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)

    log.section_header("autocycler serve")
    log.explanation("The daemon keeps JAX, the JIT caches, the parse/repair caches, the "
                    "device probe and the worker pool warm across jobs, so every "
                    "request after the first skips the CLI's cold-start cost.")
    log.message(f"listening on {handle.endpoint}")
    log.message(f"serve root:   {root}")
    log.message(f"work queue:   {queue_size} job(s)")
    log.message(f"workers:      {handle.scheduler.workers} "
                f"(auth: {'token' if handle.token else 'none'})")
    if handle.sampler is not None:
        log.message(f"telemetry:    {handle.sampler.path} "
                    f"(every {handle.sampler.interval:g}s; "
                    f"watch with `autocycler top {root} --follow`)")
    log.message(f"submit with:  autocycler submit -i <assemblies_dir> "
                f"--dir {root}")
    log.message()
    try:
        handle.wait()
    except KeyboardInterrupt:
        pass
    log.message("serve: shutting down (finishing running jobs)")
    handle.stop()
    return 0
