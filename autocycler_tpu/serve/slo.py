"""Latency SLOs for the serve daemon: objectives, rolling-window quantiles
and burn rate.

The ROADMAP's serve follow-on is a p50-latency objective ("under 5 s warm
vs ~20 s cold"); this module is where that target becomes measurable. The
scheduler reports every finished job's latency split — queue wait (admit
-> start) vs execution (start -> finish) — and the tracker keeps a
BOUNDED rolling window of recent totals (a deque capped in both count and
age, so a weeks-long daemon stores O(1) samples, never an unbounded
list). Long-horizon quantiles come from the registry's fixed-bucket
histograms via :meth:`MetricsRegistry.quantile`; window quantiles come
from the (small, bounded) sample window and drive the burn rate.

Objectives are environment knobs read at evaluation time —
``AUTOCYCLER_SLO_P50_S`` / ``AUTOCYCLER_SLO_P95_S`` — so an operator can
tighten or relax them against a live daemon without restarting it. When
the window's observed quantile exceeds an objective, ``/healthz`` flips
to ``"degraded"`` and reports the burn rate: the fraction of window jobs
violating the objective divided by the fraction the objective tolerates
(50% for p50, 5% for p95). Burn rate 1.0 means "burning budget exactly
as fast as allowed"; 2.0 means the error budget empties in half the
window.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..obs import metrics_registry
from ..obs.metrics_registry import SECONDS_BUCKETS
from ..utils.knobs import knob_float

P50_ENV = "AUTOCYCLER_SLO_P50_S"
P95_ENV = "AUTOCYCLER_SLO_P95_S"
WINDOW_ENV = "AUTOCYCLER_SLO_WINDOW_S"
SHED_BURN_ENV = "AUTOCYCLER_SLO_SHED_BURN"

DEFAULT_WINDOW_S = 3600.0
WINDOW_MAX_SAMPLES = 1024   # the hard size bound behind the time window

# registry metric names (the /metrics exports): histograms carry the full
# latency split; the gauge carries the live quantile estimates, labelled
# q= (the label "quantile" itself is reserved by Prometheus) and phase=
QUEUE_WAIT_SECONDS = "autocycler_serve_queue_wait_seconds"
EXEC_SECONDS = "autocycler_serve_exec_seconds"
LATENCY_QUANTILE = "autocycler_serve_latency_quantile_seconds"
LAST_FINISHED = "autocycler_serve_last_job_finished_epoch"

# tolerated violation fraction per objective: a p50 objective tolerates
# half the jobs over it, a p95 objective one in twenty
_ALLOWED_FRAC = {"p50_s": 0.50, "p95_s": 0.05}


def objectives() -> Dict[str, Optional[float]]:
    """The configured objectives, re-read from the environment on every
    call so a live daemon picks up changes without a restart. Unset or
    unparseable knobs mean "no objective"."""
    out: Dict[str, Optional[float]] = {}
    for key, env in (("p50_s", P50_ENV), ("p95_s", P95_ENV)):
        val = knob_float(env)
        out[key] = val if (val is not None and val > 0) else None
    return out


def window_seconds() -> float:
    return max(1.0, float(knob_float(WINDOW_ENV)))


def shed_burn_threshold() -> Optional[float]:
    """The burn rate above which the daemon sheds new submissions
    (admission control), or None when shedding is disabled. Re-read per
    call like the objectives, so it is operator-tunable live."""
    val = knob_float(SHED_BURN_ENV)
    return val if (val is not None and val > 0) else None


def _percentile(values: List[float], q: float) -> float:
    """Linear-interpolation percentile of a SMALL sorted sample (the
    bounded window — never the full job history)."""
    ordered = sorted(values)
    if len(ordered) == 1:
        return ordered[0]
    pos = q * (len(ordered) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(ordered) - 1)
    return ordered[lo] + (pos - lo) * (ordered[hi] - ordered[lo])


class SloTracker:
    """Rolling-window latency tracker for the serve scheduler.

    :meth:`record` is called by the scheduler once per finished job and
    takes only this tracker's own lock (never the scheduler's run lock —
    the sampler and health endpoint read through the same lock, so a
    slow reader can never stall job execution). :meth:`report` evaluates
    the objectives against the current window."""

    def __init__(self, registry=None):
        self._registry = registry or metrics_registry.registry()
        self._lock = threading.Lock()
        # (finished_epoch, queue_wait_s, exec_s, total_s)
        self._window: deque = deque(maxlen=WINDOW_MAX_SAMPLES)
        self.last_finished_epoch: Optional[float] = None
        # service capacity = scheduler worker count: a pool of N workers
        # burns error budget N× faster at the same queue pressure, so the
        # shed trigger scales with it (set_capacity; default 1 keeps the
        # single-worker thresholds bit-for-bit)
        self._capacity = 1

    def set_capacity(self, workers: int) -> None:
        self._capacity = max(1, int(workers))

    # -- write path (scheduler) --

    def record(self, queue_wait_s: float, exec_s: float,
               finished_epoch: Optional[float] = None,
               command: str = "") -> None:
        """One finished job's latency split. Updates the histograms, the
        window and the exported quantile gauges."""
        now = finished_epoch if finished_epoch is not None else time.time()
        queue_wait_s = max(0.0, float(queue_wait_s))
        exec_s = max(0.0, float(exec_s))
        total = queue_wait_s + exec_s
        reg = self._registry
        reg.observe(QUEUE_WAIT_SECONDS, queue_wait_s,
                    help="per-job wait in the work queue (admit -> start)",
                    buckets=SECONDS_BUCKETS, command=command)
        reg.observe(EXEC_SECONDS, exec_s,
                    help="per-job execution wall (start -> finish)",
                    buckets=SECONDS_BUCKETS, command=command)
        reg.gauge_set(LAST_FINISHED, now,
                      help="epoch when the last serve job finished")
        with self._lock:
            self._window.append((now, queue_wait_s, exec_s, total))
            self.last_finished_epoch = now
            self._prune(now)
        for q, label in ((0.50, "0.50"), (0.95, "0.95")):
            for name, phase in ((QUEUE_WAIT_SECONDS, "queue_wait"),
                                (EXEC_SECONDS, "exec")):
                est = reg.quantile(name, q, command=command)
                if est is not None:
                    reg.gauge_set(
                        LATENCY_QUANTILE, round(est, 6),
                        help="streaming job-latency quantile estimates "
                             "(histogram bucket interpolation)",
                        q=label, phase=phase, command=command)
            totals = self._window_totals()
            if totals:
                reg.gauge_set(
                    LATENCY_QUANTILE, round(_percentile(totals, q), 6),
                    help="streaming job-latency quantile estimates "
                         "(histogram bucket interpolation)",
                    q=label, phase="total", command=command)

    def _prune(self, now: float) -> None:
        horizon = now - window_seconds()
        while self._window and self._window[0][0] < horizon:
            self._window.popleft()

    def _window_totals(self) -> List[float]:
        with self._lock:
            return [t for (_, _, _, t) in self._window]

    # -- read path (/healthz, sampler, bench) --

    def report(self) -> dict:
        """Objectives vs the rolling window: observed quantiles, burn
        rate and the violation verdict. Cheap and lock-light — callable
        from the health endpoint and the telemetry sampler while a job
        runs."""
        now = time.time()
        with self._lock:
            self._prune(now)
            window = list(self._window)
        obj = objectives()
        out: dict = {
            "objectives": obj,
            "window_s": window_seconds(),
            "window_jobs": len(window),
            "last_finished_epoch": self.last_finished_epoch,
        }
        out["workers"] = self._capacity
        if window:
            totals = [t for (_, _, _, t) in window]
            waits = [w for (_, w, _, _) in window]
            out["p50_s"] = round(_percentile(totals, 0.50), 6)
            out["p95_s"] = round(_percentile(totals, 0.95), 6)
            out["queue_wait_p50_s"] = round(_percentile(waits, 0.50), 6)
            out["queue_wait_p95_s"] = round(_percentile(waits, 0.95), 6)
            out["exec_p50_s"] = round(
                _percentile([e for (_, _, e, _) in window], 0.50), 6)
        burn = None
        violated = False
        for key, target in obj.items():
            if target is None or not window:
                continue
            frac = sum(1 for (_, _, _, t) in window if t > target) \
                / len(window)
            rate = round(frac / _ALLOWED_FRAC[key], 4)
            burn = max(burn, rate) if burn is not None else rate
            if out.get(key) is not None and out[key] > target:
                violated = True
        out["burn_rate"] = burn
        out["violated"] = violated
        shed_burn = shed_burn_threshold()
        out["shed_burn"] = shed_burn
        # capacity-aware admission control: N workers drain the same queue
        # N× faster, so the effective shed trigger is the configured
        # threshold × capacity (capacity 1 → exactly the old behavior).
        # shed_burn stays the raw knob value; the effective value rides
        # alongside so /healthz shows both.
        effective = shed_burn * self._capacity if shed_burn is not None \
            else None
        out["shed_burn_effective"] = effective
        # shedding clears by itself as the window drains: pruned samples
        # drop the burn rate back under the threshold
        out["shedding"] = bool(effective is not None and burn is not None
                               and burn > effective)
        return out
