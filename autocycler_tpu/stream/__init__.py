"""Streaming k-mer binning: two-pass disk-spill grouping for
metagenome-scale compress (the KMC 2 / Gerbil architecture, arXiv:1407.1507
and arXiv:1607.06618, on top of the existing device kernels).

``build_kmer_index`` dispatches here behind ``AUTOCYCLER_STREAM_KMERS``
(off/on/auto); the in-memory path stays the parity oracle, and any spill
failure degrades the run back to it visibly (``record_degrade``) instead
of crashing. See :mod:`.driver` for the pipeline and
``docs/performance.md`` for the operational story.
"""

from .binner import StreamBinner
from .driver import (BINS_TOTAL, QUARANTINED_BINS_TOTAL, RLE_RATIO_GAUGE,
                     stream_group_windows_stats)
from .merge import merge_ranks
from .planner import StreamPlan, plan_stream, resolve_stream_mode
from .sorter import BinGroups, occ_byte_starts, sort_bin
from .spill import (ORPHANS_SWEPT_TOTAL, SPILL_BYTES_GAUGE,
                    SPILL_BYTES_TOTAL, decode_rle, encode_rle,
                    purge_stream_spills, read_bin_records, set_stream_root,
                    stream_root, sweep_orphan_spills)

__all__ = [
    "BINS_TOTAL",
    "BinGroups",
    "ORPHANS_SWEPT_TOTAL",
    "QUARANTINED_BINS_TOTAL",
    "RLE_RATIO_GAUGE",
    "SPILL_BYTES_GAUGE",
    "SPILL_BYTES_TOTAL",
    "StreamBinner",
    "StreamPlan",
    "decode_rle",
    "encode_rle",
    "merge_ranks",
    "occ_byte_starts",
    "plan_stream",
    "prepare_stream_root",
    "purge_stream_spills",
    "read_bin_records",
    "resolve_stream_mode",
    "set_stream_root",
    "sort_bin",
    "stream_group_windows_stats",
    "stream_root",
    "sweep_orphan_spills",
]


def prepare_stream_root(autocycler_dir) -> None:
    """Compress/batch startup wiring: install ``<dir>/.stream`` as the
    spill root and sweep any orphaned run dirs a killed run left behind."""
    from pathlib import Path
    root = Path(autocycler_dir) / ".stream"
    set_stream_root(root)
    sweep_orphan_spills(root)
